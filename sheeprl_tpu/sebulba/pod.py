"""The pod driver: Sebulba stretched across hosts (``topology=pod``).

One process (rank 0) is the **learner cell**; every other process is an
**actor cell** (:class:`~sheeprl_tpu.parallel.topology.PodTopology` — the
process boundary IS the actor/learner split).  Each cell computes only on
its own local devices through a 1-D local fabric; nothing in the
steady-state data path crosses hosts through XLA collectives.  Instead:

* **segments** — every actor cell runs the ordinary Sebulba machinery
  (per-device :class:`~sheeprl_tpu.sebulba.actor.ActorEngine` inference +
  the env-worker fleet) into a host-side :class:`~sheeprl_tpu.sebulba.
  queues.TrajQueue`; a pusher thread ships each segment to the learner
  front CRC-stamped (``sebulba/transport.py``) under the identical
  never-drop / torn-segment-reject contract the in-process queue enforces;
* **params** — the learner publishes through
  :class:`~sheeprl_tpu.sebulba.transport.DcnParamBroadcast` (same
  versioned ``max_staleness`` gate, serialized transport); actor cells
  fetch over HTTP, verify the CRC, and republish onto their local devices
  through a plain in-process ``ParamBroadcast``;
* **control** — commit-step announcements, coordinated preemption (either
  side's SIGTERM latch preempts the whole pod), liveness (transport
  heartbeats + the :class:`~sheeprl_tpu.parallel.distributed.PeerWatchdog`
  KV heartbeat hard-stop), and per-cell telemetry snapshots ride the
  ``/poll`` loop.

Checkpointing: the per-rank shard + COMMIT-last protocol
(``checkpoint/protocol.py``) is the pod's recovery substrate.  The
learner announces each save's step over the control plane BEFORE writing
its own shard; every actor cell writes its shard into the same step
directory when its next poll observes the step, and rank 0's commit waits
for all ``fabric.num_processes`` shards — so a committed snapshot always
represents the whole pod, and the pod supervisor restarts every rank from
the newest shared commit.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.checkpoint.preemption import PREEMPTION_GUARD
from sheeprl_tpu.checkpoint.protocol import probe_shared_root, step_dir_name, write_shard
from sheeprl_tpu.parallel.distributed import PeerWatchdog, distributed_cfg
from sheeprl_tpu.parallel.topology import ParamBroadcast, PodTopology, topology_cfg
from sheeprl_tpu.sebulba.actor import ActorEngine, derive_ladder
from sheeprl_tpu.sebulba.queues import ObsQueue, ServiceStopped, TrajQueue
from sheeprl_tpu.sebulba.runner import (
    StatsSink,
    arm_preemption,
    build_worker_fleet,
    clamp_queue_slots,
    drain_preemptible,
    shutdown,
)
from sheeprl_tpu.sebulba.transport import (
    DcnParamBroadcast,
    LearnerFront,
    PodClient,
    lookup_front_address,
    publish_front_address,
)
from sheeprl_tpu.telemetry import HUB, SPANS
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, flush_metrics
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import polynomial_decay, save_configs

# the marker line the learner prints its final stats behind — the pod
# drill and ``bench.py --mode dcn`` parse it out of the (rank-prefixed)
# combined fake-DCN output
POD_STATS_MARKER = "POD_STATS_JSON="


def _pod_knobs(cfg: Any) -> Tuple[Dict[str, Any], Dict[str, Any], Dict[str, Any]]:
    topo_cfg = topology_cfg(cfg)
    return topo_cfg, dict(topo_cfg.get("pod") or {}), distributed_cfg(cfg)


def _split_envs(cfg: Any, topo: PodTopology, topo_cfg: Dict[str, Any]) -> Tuple[int, int, int, int]:
    """``(num_envs, envs_per_cell, env_workers, envs_per_worker)`` — the
    global env count divided first across actor cells, then across each
    cell's worker fleet."""
    num_envs = int(cfg.env.num_envs)
    cells = topo.num_actor_cells
    if num_envs % cells:
        raise ValueError(
            f"pod topology needs env.num_envs ({num_envs}) divisible by the "
            f"{cells} actor cells"
        )
    envs_per_cell = num_envs // cells
    env_workers = max(1, int(topo_cfg.get("env_workers", 2)))
    if envs_per_cell % env_workers:
        raise ValueError(
            f"pod topology needs per-cell envs ({envs_per_cell}) divisible "
            f"by topology.env_workers ({env_workers})"
        )
    return num_envs, envs_per_cell, env_workers, envs_per_cell // env_workers


def _start_watchdog(fabric: Any, dist: Dict[str, Any]) -> Optional[PeerWatchdog]:
    """The KV heartbeat hard-stop: even if this cell's main thread is
    wedged inside a dispatch, a dead peer forces the process down within
    ``heartbeat_grace_s`` + the hard-exit delay — no rank trains past a
    dead peer, and exit code 75 tells the pod supervisor to restart."""
    if not bool(dist.get("watchdog", True)):
        return None
    try:
        return PeerWatchdog(
            fabric.global_rank,
            fabric.num_processes,
            heartbeat_s=float(dist.get("heartbeat_s", 1.0)),
            grace_s=float(dist.get("heartbeat_grace_s", 30.0)),
        ).start()
    except RuntimeError:
        return None  # KV client unavailable (tests with hand-built fabrics)


def _jsonable(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.floating, np.integer)):
        return obj.item()
    if isinstance(obj, (int, float, str, bool)) or obj is None:
        return obj
    return str(obj)


def run_pod(fabric: Any, cfg: Any) -> Dict[str, Any]:
    """Train through the cross-host pod topology.  Dispatches on this
    process's role; both roles run the identical preamble (seed, run-dir
    agreement, telemetry arm) so the fabric's host-collective sequence
    stays aligned across the pod."""
    topo = PodTopology.from_config(fabric, cfg)
    fabric.print(topo.describe())
    key = fabric.seed_everything(cfg.seed)
    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
    logger = get_logger(fabric, cfg, log_dir)

    algo = str(cfg.algo.name)
    if "ppo" in algo:
        flavor = "ppo"
    elif "sac" in algo:
        flavor = "sac"
    else:
        raise ValueError(f"topology=pod supports the decoupled ppo/sac drivers, not {algo!r}")

    _, _, dist = _pod_knobs(cfg)
    watchdog = _start_watchdog(fabric, dist)
    try:
        if topo.role == "learner":
            save_configs(cfg, log_dir)
            if flavor == "ppo":
                return _learner_ppo(fabric, cfg, topo, key=key, log_dir=log_dir, logger=logger)
            return _learner_sac(fabric, cfg, topo, key=key, log_dir=log_dir, logger=logger)
        HUB.set_namespace(f"rank{topo.process_index}")
        try:
            if flavor == "ppo":
                return _actor_ppo(fabric, cfg, topo, key=key, log_dir=log_dir)
            return _actor_sac(fabric, cfg, topo, key=key, log_dir=log_dir)
        finally:
            HUB.set_namespace(None)
    finally:
        if watchdog is not None:
            watchdog.stop()


# ---------------------------------------------------------------------------
# learner cells
# ---------------------------------------------------------------------------


def _learner_transport(
    cfg: Any,
    topo: PodTopology,
    traj_queue: TrajQueue,
    broadcast: DcnParamBroadcast,
) -> LearnerFront:
    _, pod, dist = _pod_knobs(cfg)
    front = LearnerFront(
        traj_queue,
        broadcast,
        topo.actor_cells,
        port=int(pod.get("port", 0) or 0),
        heartbeat_grace_s=float(dist.get("heartbeat_grace_s", 30.0)),
        first_contact_grace_s=float(pod.get("first_contact_grace_s", 300.0)),
    ).start()
    publish_front_address(front.address)
    return front


def _finish_learner(
    fabric: Any, ckpt_mgr: Any, front: LearnerFront, traj_queue: TrajQueue
) -> None:
    """Teardown in commit order: drain pending async saves FIRST (rank 0's
    commit waits for the actor shards, which arrive while the actors are
    still polling), then release the actors with ``done`` and collect
    their goodbyes before the front goes away."""
    try:
        ckpt_mgr.flush()
    finally:
        front.set_done()
        front.wait_goodbyes(timeout_s=30.0)
        front.stop()
        traj_queue.close()


def _pod_run_stats(
    *,
    topo: PodTopology,
    updates: int,
    wall_s: float,
    env_steps: int,
    traj_queue: TrajQueue,
    broadcast: DcnParamBroadcast,
    front: LearnerFront,
    traj_staleness_max: int,
    traj_staleness_sum: int,
    segments_consumed: int,
) -> Dict[str, Any]:
    """The ``bench.py --mode dcn`` stats contract: Sebulba's throughput
    block plus the DCN counters and the zero-drop ledger (segments the
    queue accepted vs segments the transport delivered)."""
    return {
        "phase_breakdown": SPANS.breakdown(),
        "topology": topo.describe(),
        "updates": int(updates),
        "wall_s": wall_s,
        "env_steps": int(env_steps),
        "env_steps_per_s": env_steps / max(wall_s, 1e-9),
        "updates_per_s": updates / max(wall_s, 1e-9),
        "queue_depth_frac": float(traj_queue.metrics()["Sebulba/queue_depth_frac"]),
        "param_staleness_max": int(broadcast.staleness_max),
        "traj_staleness_max": int(traj_staleness_max),
        "traj_staleness_avg": traj_staleness_sum / max(segments_consumed, 1),
        "segments_consumed": int(segments_consumed),
        "torn_rejected": int(traj_queue.torn_rejected + front.segments_rejected),
        "dcn": {k: float(v) for k, v in front.metrics().items()},
        "zero_drop": {
            "queue_total_put": int(traj_queue.total_put),
            "segments_accepted": int(front.segments_accepted),
            "segments_rejected": int(front.segments_rejected),
        },
    }


def _learner_ppo(
    fabric: Any, cfg: Any, topo: PodTopology, *, key: Any, log_dir: str, logger: Any
) -> Dict[str, Any]:
    """The decoupled-PPO learner cell: ``sebulba/ppo.py``'s learner half
    with the local actor fleet replaced by the DCN front."""
    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo_decoupled import _build_train_fns
    from sheeprl_tpu.algos.ppo.utils import normalize_obs_keys, spaces_to_dims, test
    from sheeprl_tpu.utils.optim import build_optimizer, set_learning_rate

    topo_cfg, pod, _ = _pod_knobs(cfg)
    learner_fab = topo.cell_fabric
    ckpt_mgr = fabric.get_checkpoint_manager(cfg, log_dir)
    # pod cells do not iterate in lockstep: the collective preemption poll
    # and the post-save barrier would hang against cells that never call
    # them — agreement arrives over the control plane instead
    ckpt_mgr.lockstep = False

    num_envs, _, env_workers, _ = _split_envs(cfg, topo, topo_cfg)
    rollout_steps = int(cfg.algo.rollout_steps)
    n_producers = topo.num_actor_cells * env_workers

    probe = make_env(cfg, cfg.seed, 0, run_name=log_dir, vector_env_idx=0)()
    obs_space, act_space = probe.observation_space, probe.action_space
    probe.close()
    normalize_obs_keys(cfg, obs_space)
    actions_dim, is_continuous = spaces_to_dims(act_space)
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    dist_type = cfg.get("distribution", {}).get("type", "auto")

    state: Dict[str, Any] = {}
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)
    if state and state.get("key") is not None:
        key = jnp.asarray(state["key"])
    agent, params = build_agent(
        learner_fab, actions_dim, is_continuous, cfg, obs_space, state.get("agent")
    )
    optimizer = build_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm)
    opt_state = learner_fab.replicate(state.get("opt_state") or optimizer.init(params))

    _, _, _, train_phase_raw = _build_train_fns(
        agent, optimizer, cfg, obs_keys, actions_dim, is_continuous, dist_type
    )

    T, B = rollout_steps, num_envs
    global_bs = min(int(cfg.algo.per_rank_batch_size) * learner_fab.world_size, T * B)
    num_minibatches = -(-T * B // global_bs)

    def learner_phase(p, o_state, segs, k, clip_coef, ent_coef):
        rollout = {
            kk: jnp.concatenate([s[kk] for s in segs], axis=1)
            for kk in obs_keys + ("actions", "logprobs", "rewards", "dones")
        }
        last_obs = {
            kk: jnp.concatenate([s[f"last_{kk}"] for s in segs], axis=0) for kk in obs_keys
        }
        return train_phase_raw(
            p, o_state, rollout, last_obs, k, clip_coef, ent_coef,
            batch_size=global_bs, num_minibatches=num_minibatches,
        )

    learner_phase = learner_fab.compile(
        learner_phase,
        name=f"{cfg.algo.name}.pod_learner_phase",
        donate_argnums=(0, 1),
        max_recompiles=cfg.algo.get("max_recompiles"),
    )

    broadcast = DcnParamBroadcast(
        topo.actor_cells,
        extract=lambda p: jax.device_get(p),
        max_staleness=int(topo_cfg.get("max_staleness", 2)),
        gate_timeout_s=float(topo_cfg.get("queue_timeout_s", 300.0)),
    )
    sync_every = max(1, int(topo_cfg.get("sync_every", 1)))
    traj_queue = TrajQueue(
        clamp_queue_slots(topo_cfg, n_producers),
        rollout_steps,
        learner_fab,
        stage=True,
        bootstrap_keys=tuple(f"last_{k}" for k in obs_keys),
        timeout_s=float(topo_cfg.get("queue_timeout_s", 300.0)),
    )
    front = _learner_transport(cfg, topo, traj_queue, broadcast)

    aggregator = MetricAggregator(cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {})
    timer.configure(cfg.metric)
    policy_steps_per_iter = num_envs * rollout_steps
    total_iters = max(int(cfg.algo.total_steps) // policy_steps_per_iter, 1)
    if cfg.dry_run:
        total_iters = 1
    start_iter = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))
    clip_coef_v = float(cfg.algo.clip_coef)
    ent_coef_v = float(cfg.algo.ent_coef)
    base_lr = float(cfg.algo.optimizer.lr)

    staleness_sum = 0
    staleness_max = 0
    segments_consumed = 0
    env_steps_consumed = 0
    updates_done = 0
    last_losses = None
    t_start = time.perf_counter()

    HUB.register("sebulba.traj_queue", traj_queue.metrics)
    HUB.register("dcn.front", front.metrics)
    SPANS.roll_window()
    arm_preemption(cfg)

    def save_checkpoint() -> None:
        # the step announcement goes out FIRST: actor cells write their
        # shards into step_dir(policy_step) while the learner's own shard
        # is written, and rank 0's commit waits for all of them
        front.set_commit(policy_step)
        fabric.call(
            "on_checkpoint_player",
            ckpt_path=str(Path(log_dir) / "checkpoint" / f"ckpt_{policy_step}_0.ckpt"),
            state={
                "agent": params,
                "opt_state": opt_state,
                "key": key,
                "update": update,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            },
        )

    try:
        broadcast.publish(params, version=start_iter - 1)
        front.wait_for_cells(timeout_s=float(pod.get("first_contact_grace_s", 300.0)))
        update = start_iter - 1
        for update in range(start_iter, total_iters + 1):
            with timer("Time/env_interaction_time"):
                items = drain_preemptible(
                    traj_queue, n_producers, [front], None,
                    ckpt_mgr=ckpt_mgr, fabric=fabric, policy_step=policy_step,
                    save_checkpoint=save_checkpoint,
                )
            if items is None:  # preempted mid-wait: committed save done
                break
            segs = tuple(item[0] for item in items)
            for _, meta in items:
                lag = broadcast.version - int(meta.get("version", 0))
                staleness_sum += lag
                staleness_max = max(staleness_max, lag)
                env_steps_consumed += int(meta.get("env_steps", 0))
            segments_consumed += len(items)
            policy_step += policy_steps_per_iter
            updates_done += 1

            with timer("Time/train_time"):
                key, tk = jax.random.split(key)
                params, opt_state, last_losses = learner_phase(
                    params, opt_state, segs, tk,
                    jnp.float32(clip_coef_v), jnp.float32(ent_coef_v),
                )
            if update % sync_every == 0 or update == total_iters:
                broadcast.publish(params, version=update)
                broadcast.gate()

            if cfg.algo.anneal_lr:
                opt_state = set_learning_rate(
                    opt_state,
                    polynomial_decay(update, initial=base_lr, final=0.0, max_decay_steps=total_iters),
                )
            if cfg.algo.anneal_clip_coef:
                clip_coef_v = polynomial_decay(
                    update, initial=float(cfg.algo.clip_coef), final=0.0, max_decay_steps=total_iters
                )
            if cfg.algo.anneal_ent_coef:
                ent_coef_v = polynomial_decay(
                    update, initial=float(cfg.algo.ent_coef), final=0.0, max_decay_steps=total_iters
                )

            if cfg.metric.log_level > 0 and (
                policy_step - last_log >= cfg.metric.log_every or update == total_iters or cfg.dry_run
            ):
                if last_losses is not None:
                    pg, vl, ent = last_losses
                    aggregator.update("Loss/policy_loss", pg)
                    aggregator.update("Loss/value_loss", vl)
                    aggregator.update("Loss/entropy_loss", ent)
                extra = dict(traj_queue.metrics())
                extra.update(front.metrics())
                extra["Sebulba/traj_staleness_max"] = float(staleness_max)
                extra["Sebulba/traj_staleness_avg"] = staleness_sum / max(segments_consumed, 1)
                last_log = flush_metrics(
                    aggregator, timer, logger, policy_step, last_log, extra_metrics=extra
                )

            # coordinated preemption, DCN direction actor → learner: an
            # actor cell's SIGTERM latch (surfaced by its poll) preempts
            # the whole pod through the ordinary committed-final-save path
            if front.actor_latched and not ckpt_mgr.preempted:
                fabric.print("Preemption latched on an actor cell: pod-wide final save")
                ckpt_mgr.force_preempt()
            if ckpt_mgr.should_save(policy_step, last_checkpoint, final=update == total_iters):
                last_checkpoint = policy_step
                save_checkpoint()
            if ckpt_mgr.preempted:
                fabric.print(f"Preemption: committed checkpoint at step {policy_step}, exiting")
                break
    finally:
        HUB.unregister("sebulba.traj_queue")
        HUB.unregister("dcn.front")
        _finish_learner(fabric, ckpt_mgr, front, traj_queue)

    run_stats = _pod_run_stats(
        topo=topo, updates=updates_done,
        wall_s=time.perf_counter() - t_start, env_steps=env_steps_consumed,
        traj_queue=traj_queue, broadcast=broadcast, front=front,
        traj_staleness_max=staleness_max, traj_staleness_sum=staleness_sum,
        segments_consumed=segments_consumed,
    )
    fabric.print(POD_STATS_MARKER + json.dumps(_jsonable(run_stats)))

    ckpt_mgr.finalize()
    if cfg.algo.run_test and not ckpt_mgr.preempted:
        test(agent, fabric.to_host(params), cfg, log_dir, logger)
    if logger is not None:
        logger.close()
    return run_stats


def _learner_sac(
    fabric: Any, cfg: Any, topo: PodTopology, *, key: Any, log_dir: str, logger: Any
) -> Dict[str, Any]:
    """The decoupled-SAC learner cell: ``sebulba/sac.py``'s learner half
    (host replay + the ``Ratio``-owed gradient steps) fed by the front.
    Only the actor subtree crosses the DCN, as in-process."""
    import gymnasium as gym

    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.algos.sac.sac import make_sac_train_fns
    from sheeprl_tpu.algos.sac.utils import test
    from sheeprl_tpu.data.buffers import ReplayBuffer
    from sheeprl_tpu.utils.optim import build_optimizer
    from sheeprl_tpu.utils.utils import Ratio

    topo_cfg, pod, _ = _pod_knobs(cfg)
    learner_fab = topo.cell_fabric
    ckpt_mgr = fabric.get_checkpoint_manager(cfg, log_dir)
    ckpt_mgr.lockstep = False

    num_envs, _, env_workers, envs_per_worker = _split_envs(cfg, topo, topo_cfg)
    segment_steps = max(1, int(topo_cfg.get("segment_steps", 16)))
    n_producers = topo.num_actor_cells * env_workers

    probe = make_env(cfg, cfg.seed, 0, run_name=log_dir, vector_env_idx=0)()
    obs_space, act_space = probe.observation_space, probe.action_space
    probe.close()
    if not isinstance(act_space, gym.spaces.Box):
        raise ValueError("SAC supports continuous (Box) action spaces only, like the reference")
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_dim = int(sum(np.prod(obs_space[k].shape) for k in mlp_keys))
    act_dim = int(np.prod(act_space.shape))

    state: Dict[str, Any] = {}
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)
    if state and state.get("key") is not None:
        key = jnp.asarray(state["key"])
    actor, critic, params = build_agent(learner_fab, act_dim, cfg, obs_dim, state.get("agent"))
    actor_opt = build_optimizer(cfg.algo.actor.optimizer)
    critic_opt = build_optimizer(cfg.algo.critic.optimizer)
    alpha_opt = build_optimizer(cfg.algo.alpha.optimizer)
    opt_state = learner_fab.replicate(
        state.get("opt_state")
        or {
            "actor": actor_opt.init(params["actor"]),
            "critic": critic_opt.init(params["critic"]),
            "alpha": alpha_opt.init(params["log_alpha"]),
        }
    )

    def plain_apply(critic_mod, cp, o, a, k):
        return critic_mod.apply(cp, o, a)

    _, train_phase = make_sac_train_fns(
        actor, critic, plain_apply, actor_opt, critic_opt, alpha_opt, cfg, act_dim
    )

    # host-side replay on the learner cell (the DCN pod's segments arrive
    # as host numpy; the single-host driver's DeviceReplay HBM ring is an
    # orthogonal optimization the cell can adopt later)
    capacity = int(cfg.buffer.size) // num_envs
    memmap_dir = str(Path(log_dir) / "memmap_buffer" / "rank_0") if cfg.buffer.memmap else None
    rb = ReplayBuffer(capacity, num_envs, memmap=cfg.buffer.memmap, memmap_dir=memmap_dir)
    if state and cfg.buffer.checkpoint and "rb" in state:
        rb.load_state_dict(state["rb"])
    batch_size = int(cfg.algo.per_rank_batch_size) * learner_fab.local_world_size

    broadcast = DcnParamBroadcast(
        topo.actor_cells,
        extract=lambda p: jax.device_get(p["actor"]),
        max_staleness=int(topo_cfg.get("max_staleness", 2)),
        gate_timeout_s=float(topo_cfg.get("queue_timeout_s", 300.0)),
    )
    sync_every = max(1, int(topo_cfg.get("sync_every", 1)))
    traj_queue = TrajQueue(
        clamp_queue_slots(topo_cfg, n_producers),
        segment_steps,
        learner_fab,
        stage=False,  # payloads land in the host replay ring
        timeout_s=float(topo_cfg.get("queue_timeout_s", 300.0)),
    )
    front = _learner_transport(cfg, topo, traj_queue, broadcast)

    aggregator = MetricAggregator(cfg.metric.aggregator.metrics if cfg.metric.log_level > 0 else {})
    timer.configure(cfg.metric)
    steps_per_round = num_envs * segment_steps
    total_rounds = max(int(cfg.algo.total_steps) // steps_per_round, 1)
    if cfg.dry_run:
        total_rounds = 1
    start_round = int(state.get("update", 0)) + 1 if state else 1
    policy_step = int(state.get("policy_step", 0))
    last_log = int(state.get("last_log", 0))
    last_checkpoint = int(state.get("last_checkpoint", 0))
    grad_step_counter = int(state.get("grad_steps", 0))
    windows = int(state.get("windows", 0))
    learning_starts = int(cfg.algo.learning_starts) if not cfg.dry_run else 0
    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state and "ratio" in state:
        ratio.load_state_dict(state["ratio"])

    staleness_sum = 0
    staleness_max = 0
    segments_consumed = 0
    env_steps_consumed = 0
    last_losses = None
    t_start = time.perf_counter()

    HUB.register("sebulba.traj_queue", traj_queue.metrics)
    HUB.register("dcn.front", front.metrics)
    SPANS.roll_window()
    arm_preemption(cfg)

    def save_checkpoint() -> None:
        front.set_commit(policy_step)
        fabric.call(
            "on_checkpoint_player",
            ckpt_path=str(Path(log_dir) / "checkpoint" / f"ckpt_{policy_step}_0.ckpt"),
            state={
                "agent": params,
                "opt_state": opt_state,
                "key": key,
                "update": rnd,
                "policy_step": policy_step,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "ratio": ratio.state_dict(),
                "grad_steps": grad_step_counter,
                "windows": windows,
            },
            replay_buffer=rb if cfg.buffer.checkpoint else None,
        )

    try:
        broadcast.publish(params, version=windows)
        front.wait_for_cells(timeout_s=float(pod.get("first_contact_grace_s", 300.0)))
        rnd = start_round - 1
        for rnd in range(start_round, total_rounds + 1):
            with timer("Time/env_interaction_time"):
                items = drain_preemptible(
                    traj_queue, n_producers, [front], None,
                    ckpt_mgr=ckpt_mgr, fabric=fabric, policy_step=policy_step,
                    save_checkpoint=save_checkpoint,
                )
            if items is None:
                break
            for seg, meta in items:
                base = int(meta.get("worker", 0)) * envs_per_worker
                rb.add(
                    {k: np.asarray(v) for k, v in seg.items()},
                    indices=range(base, base + envs_per_worker),
                )
                lag = broadcast.version - int(meta.get("version", 0))
                staleness_sum += lag
                staleness_max = max(staleness_max, lag)
                env_steps_consumed += int(meta.get("env_steps", 0))
            segments_consumed += len(items)
            policy_step += steps_per_round

            if policy_step >= learning_starts:
                gradient_steps = ratio(policy_step / learner_fab.world_size)
                if gradient_steps > 0:
                    windows += 1
                    with timer("Time/train_time"):
                        sample = rb.sample(batch_size, n_samples=gradient_steps)
                        batches = {
                            "obs": jnp.asarray(sample["obs"]),
                            "next_obs": jnp.asarray(sample["next_obs"]),
                            "actions": jnp.asarray(sample["actions"]),
                            "rewards": jnp.asarray(sample["rewards"][..., 0]),
                            "terminated": jnp.asarray(sample["terminated"][..., 0]),
                        }
                        batches = learner_fab.shard_batch(batches, axis=1)
                        key, tk = jax.random.split(key)
                        params, opt_state, last_losses = train_phase(
                            params, opt_state, batches, tk, jnp.int32(grad_step_counter)
                        )
                        grad_step_counter += gradient_steps
                    if windows % sync_every == 0:
                        broadcast.publish(params, version=windows)
                        broadcast.gate()

            if cfg.metric.log_level > 0 and (
                policy_step - last_log >= cfg.metric.log_every or rnd == total_rounds or cfg.dry_run
            ):
                if last_losses is not None:
                    vl, pl, al = last_losses
                    aggregator.update("Loss/value_loss", vl)
                    aggregator.update("Loss/policy_loss", pl)
                    aggregator.update("Loss/alpha_loss", al)
                extra = dict(traj_queue.metrics())
                extra.update(front.metrics())
                extra["Sebulba/traj_staleness_max"] = float(staleness_max)
                extra["Sebulba/traj_staleness_avg"] = staleness_sum / max(segments_consumed, 1)
                last_log = flush_metrics(
                    aggregator, timer, logger, policy_step, last_log, extra_metrics=extra
                )

            if front.actor_latched and not ckpt_mgr.preempted:
                fabric.print("Preemption latched on an actor cell: pod-wide final save")
                ckpt_mgr.force_preempt()
            if ckpt_mgr.should_save(policy_step, last_checkpoint, final=rnd == total_rounds):
                last_checkpoint = policy_step
                save_checkpoint()
            if ckpt_mgr.preempted:
                fabric.print(f"Preemption: committed checkpoint at step {policy_step}, exiting")
                break
    finally:
        HUB.unregister("sebulba.traj_queue")
        HUB.unregister("dcn.front")
        _finish_learner(fabric, ckpt_mgr, front, traj_queue)

    run_stats = _pod_run_stats(
        topo=topo, updates=windows,
        wall_s=time.perf_counter() - t_start, env_steps=env_steps_consumed,
        traj_queue=traj_queue, broadcast=broadcast, front=front,
        traj_staleness_max=staleness_max, traj_staleness_sum=staleness_sum,
        segments_consumed=segments_consumed,
    )
    fabric.print(POD_STATS_MARKER + json.dumps(_jsonable(run_stats)))

    ckpt_mgr.finalize()
    if cfg.algo.run_test and not ckpt_mgr.preempted:
        test(actor, fabric.to_host(params["actor"]), cfg, log_dir, logger)
    if logger is not None:
        logger.close()
    return run_stats


# ---------------------------------------------------------------------------
# actor cells
# ---------------------------------------------------------------------------


def _actor_ppo(fabric: Any, cfg: Any, topo: PodTopology, *, key: Any, log_dir: str) -> Dict[str, Any]:
    from sheeprl_tpu.algos.ppo.agent import build_agent, sample_actions
    from sheeprl_tpu.algos.ppo.utils import normalize_obs_keys, spaces_to_dims
    from sheeprl_tpu.sebulba.ppo import PPOWorkerProtocol

    probe = make_env(cfg, cfg.seed, 0, run_name=log_dir, vector_env_idx=0)()
    obs_space, act_space = probe.observation_space, probe.action_space
    probe.close()
    normalize_obs_keys(cfg, obs_space)
    actions_dim, is_continuous = spaces_to_dims(act_space)
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    dist_type = cfg.get("distribution", {}).get("type", "auto")
    gamma = float(cfg.algo.gamma)

    # the module (apply fn) only — the weights themselves arrive from the
    # learner's first broadcast before any engine starts
    agent, _ = build_agent(topo.cell_fabric, actions_dim, is_continuous, cfg, obs_space, None)

    def policy_fn(p, obs, k):
        k_sample, k_next = jax.random.split(k)
        out, value = agent.apply(p, obs)
        actions, logprob, _ = sample_actions(
            out, actions_dim, is_continuous, k_sample, dist_type=dist_type
        )
        return {"actions": actions, "logprobs": logprob, "values": value[..., 0]}, k_next

    protocol = PPOWorkerProtocol(obs_keys, cnn_keys, mlp_keys, act_space, gamma)
    probe_prep = protocol.prepare(
        {k: np.zeros((1,) + tuple(obs_space[k].shape), obs_space[k].dtype) for k in obs_keys}
    )
    obs_spec = {k: (tuple(v.shape[1:]), v.dtype) for k, v in probe_prep.items()}
    return _drive_actor_cell(
        fabric, cfg, topo,
        key=key, log_dir=log_dir,
        protocol=protocol, policy_fn=policy_fn, obs_spec=obs_spec,
        segment_steps=int(cfg.algo.rollout_steps),
        bootstrap_keys=tuple(f"last_{k}" for k in obs_keys),
    )


def _actor_sac(fabric: Any, cfg: Any, topo: PodTopology, *, key: Any, log_dir: str) -> Dict[str, Any]:
    import gymnasium as gym

    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.algos.sac.sac import make_sac_train_fns
    from sheeprl_tpu.sebulba.sac import SACWorkerProtocol
    from sheeprl_tpu.utils.optim import build_optimizer

    topo_cfg, _, _ = _pod_knobs(cfg)
    probe = make_env(cfg, cfg.seed, 0, run_name=log_dir, vector_env_idx=0)()
    obs_space, act_space = probe.observation_space, probe.action_space
    probe.close()
    if not isinstance(act_space, gym.spaces.Box):
        raise ValueError("SAC supports continuous (Box) action spaces only, like the reference")
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_dim = int(sum(np.prod(obs_space[k].shape) for k in mlp_keys))
    act_dim = int(np.prod(act_space.shape))

    actor, critic, _ = build_agent(topo.cell_fabric, act_dim, cfg, obs_dim, None)
    actor_opt = build_optimizer(cfg.algo.actor.optimizer)
    critic_opt = build_optimizer(cfg.algo.critic.optimizer)
    alpha_opt = build_optimizer(cfg.algo.alpha.optimizer)

    def plain_apply(critic_mod, cp, o, a, k):
        return critic_mod.apply(cp, o, a)

    act_fn, _ = make_sac_train_fns(
        actor, critic, plain_apply, actor_opt, critic_opt, alpha_opt, cfg, act_dim
    )

    def policy_fn(p, obs, k):
        a, k_next = act_fn.jitted(p, obs["obs"], k)
        return {"actions": a}, k_next

    _, _, env_workers, _ = _split_envs(cfg, topo, topo_cfg)
    learning_starts = int(cfg.algo.learning_starts) if not cfg.dry_run else 0
    global_workers = topo.num_actor_cells * env_workers
    protocol = SACWorkerProtocol(
        mlp_keys, act_space, prefill_steps=-(-learning_starts // global_workers)
    )
    return _drive_actor_cell(
        fabric, cfg, topo,
        key=key, log_dir=log_dir,
        protocol=protocol, policy_fn=policy_fn,
        obs_spec={"obs": ((obs_dim,), np.dtype(np.float32))},
        segment_steps=max(1, int(topo_cfg.get("segment_steps", 16))),
        bootstrap_keys=(),
    )


def _drive_actor_cell(
    fabric: Any,
    cfg: Any,
    topo: PodTopology,
    *,
    key: Any,
    log_dir: str,
    protocol: Any,
    policy_fn: Any,
    obs_spec: Dict[str, Any],
    segment_steps: int,
    bootstrap_keys: Tuple[str, ...],
) -> Dict[str, Any]:
    """The algorithm-agnostic actor cell: local inference engines + env
    workers into a host-side queue; a pusher thread ships segments over
    the DCN; the main thread runs the ``/poll`` control loop (param
    refresh, shard writes on commit announcements, coordinated exit)."""
    topo_cfg, pod, dist = _pod_knobs(cfg)
    rank = topo.process_index
    cell = topo.cell_index
    ckpt_root = Path(log_dir) / "checkpoint"
    first_contact = float(pod.get("first_contact_grace_s", 300.0))
    # fail fast on a host-local checkpoint.root (satellite of the commit
    # protocol: rank 0's probe marker must be visible from every cell)
    probe_shared_root(ckpt_root, rank, timeout_s=min(60.0, first_contact))

    _, envs_per_cell, env_workers, envs_per_worker = _split_envs(cfg, topo, topo_cfg)
    address = lookup_front_address(timeout_s=first_contact)
    client = PodClient(
        address,
        rank,
        push_deadline_s=float(pod.get("push_deadline_s", 300.0)),
        request_timeout_s=float(pod.get("request_timeout_s", 10.0)),
        heartbeat_grace_s=float(dist.get("heartbeat_grace_s", 30.0)),
    )

    # first params define the broadcast spec: block until the learner's
    # initial publish is fetchable (CRC-verified) so no engine ever runs
    # on randomly-initialized local weights
    deadline = time.monotonic() + first_contact
    fetched = None
    while fetched is None:
        fetched = client.fetch_params(-1)
        if fetched is None:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"pod actor cell {rank}: learner at {address} never "
                    f"published params within {first_contact:g}s"
                )
            time.sleep(0.2)
    host_params, applied = fetched
    param_spec = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype), host_params
    )
    # the local republish leg: the DCN staleness gate lives at the learner
    # (cursors advance on /poll acks), so the in-cell gate never binds
    broadcast = ParamBroadcast(
        topo.cell_fabric,
        topo.local_devices,
        max_staleness=2**31,
        gate_timeout_s=float(topo_cfg.get("queue_timeout_s", 300.0)),
    )
    broadcast.publish(host_params, version=applied)

    local_queue = TrajQueue(
        clamp_queue_slots(topo_cfg, env_workers),
        segment_steps,
        None,
        stage=False,  # host payloads; the DCN pusher is the consumer
        bootstrap_keys=bootstrap_keys,
        timeout_s=float(topo_cfg.get("queue_timeout_s", 300.0)),
    )
    obs_queue = ObsQueue(max_pending=2 * env_workers)
    ladder = derive_ladder(envs_per_worker, env_workers, topo_cfg.get("actor_batch_ladder"))
    engines: List[ActorEngine] = []
    for i, dev in enumerate(topo.local_devices):
        eng = ActorEngine(
            i, dev, policy_fn, obs_spec, param_spec, ladder, envs_per_worker,
            obs_queue, broadcast, jax.random.fold_in(key, 0xF0 + 16 * rank + i),
            max_wait_s=float(topo_cfg.get("max_wait_ms", 20.0)) / 1e3,
            max_recompiles=cfg.algo.get("max_recompiles"),
        )
        if cfg.algo.get("compile_warmup", True):
            eng.warmup(fabric.compile_pool, join=False)
        engines.append(eng)
    fabric.compile_pool.join()

    stats_sink = StatsSink()
    stop_event = threading.Event()
    supervisor = build_worker_fleet(
        cfg, topo_cfg,
        protocol=protocol, obs_queue=obs_queue, traj_queue=local_queue,
        segment_steps=segment_steps, num_workers=env_workers,
        envs_per_worker=envs_per_worker, log_dir=log_dir,
        stop_event=stop_event, stats_sink=stats_sink,
        env_offset=cell * envs_per_cell,
    )

    pusher_errors: List[BaseException] = []

    def _pusher() -> None:
        try:
            while True:
                try:
                    items = local_queue.get_many(1, timeout_s=1.0)
                except TimeoutError:
                    if stop_event.is_set():
                        return
                    continue
                if not items:
                    if local_queue.closed:
                        return
                    continue
                for seg, meta in items:
                    meta = dict(meta)
                    # worker ids go global so the learner's replay slot
                    # math (SAC) and staleness ledgers see one pod-wide
                    # worker namespace
                    meta["worker"] = cell * env_workers + int(meta.get("worker", 0))
                    client.push_segment({k: np.asarray(v) for k, v in seg.items()}, meta=meta)
        except ServiceStopped:
            return  # queue closed under us, or the learner finished (410)
        except BaseException as e:  # surfaced by the control loop
            pusher_errors.append(e)
            stop_event.set()

    HUB.register("dcn.client", client.metrics)
    HUB.register("sebulba.traj_queue", local_queue.metrics)
    HUB.register("sebulba.broadcast", broadcast.metrics)
    SPANS.roll_window()
    arm_preemption(cfg)
    poll_interval = float(pod.get("poll_interval_s", 0.5))
    last_shard = -1
    shards_written = 0
    reason = "done"
    t_start = time.perf_counter()
    pusher = threading.Thread(target=_pusher, name="dcn.pusher", daemon=True)
    try:
        for eng in engines:
            eng.start()
        supervisor.start()
        pusher.start()
        while True:
            resp = client.poll(
                applied, latched=PREEMPTION_GUARD.requested(), hub=HUB.collect()
            )
            if resp is not None:
                if int(resp.get("version", applied)) > applied:
                    fresh = client.fetch_params(applied)
                    if fresh is not None:
                        host_params, version = fresh
                        broadcast.publish(host_params, version=version)
                        applied = version
                # replay EVERY announced step, not just the latest: the
                # learner's async commit manager can announce two saves
                # between our polls, and each one's rank-0 commit is
                # waiting on our shard
                announced = [int(s) for s in resp.get("commit_steps", [])]
                if not announced and int(resp.get("commit_step", -1)) >= 0:
                    announced = [int(resp["commit_step"])]
                for commit_step in sorted(announced):
                    if commit_step <= last_shard:
                        continue
                    step_dir = ckpt_root / step_dir_name(commit_step)
                    step_dir.mkdir(parents=True, exist_ok=True)
                    write_shard(
                        step_dir, rank,
                        {
                            "pod_rank": rank,
                            "policy_step": commit_step,
                            "policy_version": int(applied),
                            "key": np.asarray(jax.device_get(key)),  # graftlint: disable=prng-key-reuse
                        },
                    )
                    last_shard = commit_step
                    shards_written += 1
                if resp.get("done"):
                    break
            if pusher_errors:
                raise pusher_errors[0]
            for eng in engines:
                if eng.error is not None:
                    raise eng.error
            supervisor.check()
            time.sleep(poll_interval)
    except BaseException as e:
        reason = f"{type(e).__name__}: {e}"
        raise
    finally:
        HUB.unregister("dcn.client")
        HUB.unregister("sebulba.traj_queue")
        HUB.unregister("sebulba.broadcast")
        shutdown(stop_event, local_queue, obs_queue, engines, supervisor)
        pusher.join(timeout=5.0)
        client.goodbye(reason)

    return {
        "topology": topo.describe(),
        "role": "actor",
        "cell": cell,
        "wall_s": time.perf_counter() - t_start,
        "segments_pushed": int(client.segments_pushed),
        "push_retries": int(client.push_retries),
        "param_fetches": int(client.fetches),
        "applied_version": int(applied),
        "shards_written": int(shards_written),
        "worker_restarts": supervisor.restarts,
    }
