"""Sebulba dataflow queues: the shared observation queue and the
device-resident trajectory queue.

* :class:`ObsQueue` is the actor-side admission path: env workers submit
  fixed-shape observation *blocks* (one per worker per step) and the actor
  dispatcher coalesces the head of the queue into one padded inference
  batch — exactly the :mod:`sheeprl_tpu.serve.batcher` continuous-batching
  pattern (bounded FIFO, max-batch/max-wait anchored to the oldest block),
  re-instantiated for rollout inference instead of HTTP requests.

* :class:`TrajQueue` is the learner-side trajectory ring: a bounded queue
  of rollout segments whose payloads live ON the learner sub-mesh (staged
  with ``learner_fabric.shard_batch`` along the env axis where it divides,
  replicated otherwise — the ``data/device_replay.py`` placement, one
  window at a time).  Capacity bounds the HBM the queue may pin; a full
  queue **blocks producers** (backpressure — trajectories are never
  dropped), and depth is tracked so ``bench.py --mode sebulba`` can report
  how full the pipe runs.

Both queues carry the ``sebulba.traj_queue`` / ``sebulba.env_worker``
fault sites' consequences: a ``truncate`` fault at the trajectory queue
models a torn segment — :meth:`TrajQueue.put` **rejects** it (shape
validation against the segment contract) instead of feeding the learner a
short rollout, so chaos drills can assert "no torn trajectories" as a
hard property of the dataflow.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from sheeprl_tpu.resilience.faults import fault_rows
from sheeprl_tpu.serve.batcher import AdmissionQueue, QueueFull, ServiceStopped  # noqa: F401


class TornTrajectory(ValueError):
    """A segment whose leading (time) axis does not match the queue's
    contract — e.g. a ``sebulba.traj_queue`` truncate fault."""


class ObsBlock:
    """One env worker's observation block awaiting actor inference.

    The dispatcher resolves it with the per-row policy outputs; the worker
    blocks in :meth:`wait`.  Mirrors ``serve.batcher._Request`` (enqueued
    timestamp drives the coalescer's max-wait anchor; ``cancelled`` lets a
    deposed worker's block be skipped instead of burning batch rows).
    """

    __slots__ = ("worker_id", "obs", "rows", "enqueued", "event", "result", "error", "cancelled")

    def __init__(self, worker_id: int, obs: Dict[str, np.ndarray], rows: int):
        self.worker_id = int(worker_id)
        self.obs = obs
        self.rows = int(rows)
        self.enqueued = time.perf_counter()
        self.event = threading.Event()
        self.result: Optional[Dict[str, np.ndarray]] = None
        self.error: Optional[BaseException] = None
        self.cancelled = False

    def wait(self, timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        if not self.event.wait(timeout):
            self.cancelled = True
            raise TimeoutError("actor inference request timed out")
        if self.error is not None:
            raise self.error
        return self.result

    def resolve(self, result: Dict[str, np.ndarray]) -> None:
        self.result = result
        self.event.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.event.set()


class ObsQueue(AdmissionQueue):
    """The shared observation queue (bounded FIFO + coalescing pop).

    Capacity defaults to the worker count: every worker can have at most
    one block in flight, so the queue can never grow past one round."""

    def __init__(self, max_pending: int):
        super().__init__(max_pending=max_pending)


class _DepthMeter:
    """Time-weighted queue-depth integral: ``frac()`` is the average
    fraction of capacity occupied since :meth:`start` (updated at every
    put/get transition, so idle stretches count at their true depth)."""

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._depth = 0
        self._t0 = time.perf_counter()
        self._last = self._t0
        self._area = 0.0
        self._max = 0

    def start(self) -> None:
        self._t0 = time.perf_counter()
        self._last = self._t0
        self._area = 0.0

    def move(self, delta: int) -> None:
        now = time.perf_counter()
        self._area += self._depth * (now - self._last)
        self._last = now
        self._depth += delta
        self._max = max(self._max, self._depth)

    def frac(self) -> float:
        now = time.perf_counter()
        area = self._area + self._depth * (now - self._last)
        return area / (self.capacity * max(now - self._t0, 1e-9))

    @property
    def max_depth(self) -> int:
        return self._max


class TrajQueue:
    """Bounded device-resident trajectory queue on the learner sub-mesh.

    ``put`` stages a rollout segment (dict of ``(T, B, *feat)`` arrays plus
    optional ``(B, *feat)`` bootstrap leaves) onto the learner mesh and
    appends it; while ``capacity`` segments are pending the producer
    **blocks** (backpressure).  ``get_many(n)`` pops the ``n`` oldest
    segments for one learner update.  ``stage=False`` keeps payloads on the
    host (the SAC driver appends them into its own ``DeviceReplay`` HBM
    ring — the device-resident store is the ring itself, the queue adds
    only ordering + backpressure).

    Segment metadata travels alongside the payload: the param version the
    segment was collected with (staleness accounting), its worker id, and
    its env-step count (throughput accounting).

    In the pod topology the same queue (and the same contract) sits at
    BOTH ends of the DCN: each actor cell buffers its workers' segments in
    a host-side queue (``stage=False``) drained by the transport pusher,
    and the learner front feeds its staged queue from CRC-verified HTTP
    intake (``sebulba/transport.py``) — torn segments are rejected at the
    wire with the exact :class:`TornTrajectory` semantics used in-process.
    """

    def __init__(
        self,
        capacity: int,
        rollout_steps: int,
        learner_fabric: Any = None,
        *,
        stage: bool = True,
        bootstrap_keys: Tuple[str, ...] = (),
        timeout_s: float = 300.0,
    ):
        self.capacity = max(1, int(capacity))
        self.rollout_steps = int(rollout_steps)
        self.learner_fabric = learner_fabric
        self.stage = bool(stage) and learner_fabric is not None
        self.bootstrap_keys = tuple(bootstrap_keys)
        self.timeout_s = float(timeout_s)
        self._items: List[Tuple[Dict[str, Any], Dict[str, Any]]] = []
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._closed = False
        self._meter = _DepthMeter(self.capacity)
        self._meter.start()
        self.torn_rejected = 0
        self.put_wait_s = 0.0
        self.get_wait_s = 0.0
        self.total_put = 0

    # -- staging --------------------------------------------------------------
    def _stage(self, segment: Dict[str, Any]) -> Dict[str, Any]:
        """Land the segment on the learner mesh: env axis (axis 1 of the
        ``(T, B, ...)`` rollout leaves, axis 0 of bootstrap leaves) sharded
        over the learner ``data`` axis when it divides, replicated
        otherwise — ``device_replay``'s placement rule."""
        fab = self.learner_fabric
        n = int(fab.mesh.shape[fab.data_axis])
        out = {}
        for k, v in segment.items():
            axis = 0 if k in self.bootstrap_keys else 1
            rows = int(np.shape(v)[axis]) if np.ndim(v) > axis else 0
            if rows and rows % n == 0:
                # host leaves: one explicit H2D onto the sharded layout;
                # actor-device leaves (fused jax rollout shards): a pure
                # D2D reshard — legal under the H2D transfer guard
                out[k] = fab.shard_batch(v, axis=axis)
            else:
                out[k] = fab.replicate(v if hasattr(v, "devices") else np.asarray(v))
        return out

    def _validate(self, segment: Dict[str, Any]) -> None:
        for k, v in segment.items():
            if k in self.bootstrap_keys:
                continue
            t = int(np.shape(v)[0]) if np.ndim(v) else -1
            if t != self.rollout_steps:
                raise TornTrajectory(
                    f"segment leaf '{k}' has {t} rows, expected "
                    f"rollout_steps={self.rollout_steps} — torn trajectory "
                    "rejected (never enqueued)"
                )

    # -- producer -------------------------------------------------------------
    def put(
        self,
        segment: Dict[str, Any],
        meta: Optional[Dict[str, Any]] = None,
        abort: Optional[Any] = None,
    ) -> None:
        """Stage + append one segment; blocks while the ring is full.

        ``abort`` (a callable) is evaluated under the queue lock on every
        backpressure wait slice AND immediately before the append: a
        producer whose ``abort()`` turns true (a deposed env worker) backs
        out with :class:`ServiceStopped` instead of delivering a stale
        segment — the generation fence that keeps a respawn from
        duplicating trajectories.

        The ``sebulba.traj_queue`` fault site acts here: ``latency``/
        ``hang`` delay the producer, ``raise`` fails it (the worker
        respawn path), ``truncate`` tears the segment — which the shape
        validation then rejects with :class:`TornTrajectory` so a torn
        segment can never reach the learner."""
        rollout_leaves = {k: v for k, v in segment.items() if k not in self.bootstrap_keys}
        rollout_leaves = fault_rows("sebulba.traj_queue", rollout_leaves)
        segment = {**segment, **rollout_leaves}
        try:
            self._validate(segment)
        except TornTrajectory:
            with self._lock:
                self.torn_rejected += 1
            raise
        staged = self._stage(segment) if self.stage else segment
        deadline = time.monotonic() + self.timeout_s
        t0 = time.perf_counter()
        with self._lock:
            while len(self._items) >= self.capacity and not self._closed:
                if abort is not None and abort():
                    raise ServiceStopped("producer deposed while waiting")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise QueueFull(
                        f"trajectory queue full ({self.capacity} segments) "
                        f"for {self.timeout_s}s — learner wedged?"
                    )
                self._not_full.wait(min(remaining, 0.2))
            if self._closed:
                raise ServiceStopped("trajectory queue closed")
            if abort is not None and abort():
                raise ServiceStopped("producer deposed while waiting")
            self.put_wait_s += time.perf_counter() - t0
            self._items.append((staged, dict(meta or {})))
            self.total_put += 1
            self._meter.move(+1)
            self._not_empty.notify_all()

    # -- consumer -------------------------------------------------------------
    def get_many(
        self, n: int, timeout_s: Optional[float] = None
    ) -> List[Tuple[Dict[str, Any], Dict[str, Any]]]:
        """Pop the ``n`` oldest segments (blocking).  Returns fewer only
        when the queue is closed and drained."""
        effective = self.timeout_s if timeout_s is None else float(timeout_s)
        deadline = time.monotonic() + effective
        t0 = time.perf_counter()
        with self._lock:
            while len(self._items) < n and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"trajectory queue: {len(self._items)}/{n} segments "
                        f"after {effective}s — actors wedged?"
                    )
                self._not_empty.wait(min(remaining, 0.2))
            self.get_wait_s += time.perf_counter() - t0
            take = min(n, len(self._items))
            # per-pop queue-depth sample for the flight recorder (bounded
            # ring, learner-update cadence) — postmortems show whether the
            # queue was starved or backed up when the run died
            from sheeprl_tpu.telemetry.recorder import RECORDER

            RECORDER.record(
                "queue.depth", depth=len(self._items), frac=round(self._meter.frac(), 4)
            )
            out, self._items = self._items[:take], self._items[take:]
            self._meter.move(-take)
            self._not_full.notify_all()
            return out

    def qsize(self) -> int:
        with self._lock:
            return len(self._items)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    # -- observability --------------------------------------------------------
    def metrics(self) -> Dict[str, float]:
        with self._lock:
            return {
                "Sebulba/queue_depth": float(len(self._items)),
                "Sebulba/queue_depth_frac": float(self._meter.frac()),
                "Sebulba/queue_depth_max": float(self._meter.max_depth),
                "Sebulba/queue_put_wait_s": float(self.put_wait_s),
                "Sebulba/queue_get_wait_s": float(self.get_wait_s),
                "Sebulba/queue_torn_rejected": float(self.torn_rejected),
                # accepted-segment count: the pod zero-drop gate compares
                # this against the transport's pushed/accepted counters
                "Sebulba/queue_total_put": float(self.total_put),
            }
