"""Shared math / control utilities (JAX-first).

Capability parity with the reference's grab-bag utils
(reference: sheeprl/utils/utils.py:63-313) — GAE, symlog/symexp, two-hot
encoding, normalization, polynomial decay, the replay-ratio governor — but
every array op is a pure jittable JAX function shaped for ``lax.scan`` /
XLA fusion instead of per-step Python loops.
"""

from __future__ import annotations

import copy
import os
import warnings
import tempfile
import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import yaml


# --------------------------------------------------------------------------
# returns / advantages
# --------------------------------------------------------------------------

def gae(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    next_value: jax.Array,
    gamma: float,
    lmbda: float,
) -> Tuple[jax.Array, jax.Array]:
    """Generalized advantage estimation over a ``(T, B, ...)`` rollout.

    The reference computes this with a reversed Python loop
    (reference: sheeprl/utils/utils.py:63-100); here it is a single reversed
    ``lax.scan`` so the whole advantage computation compiles into the rollout
    post-processing graph.

    ``dones[t]`` flags whether the episode ended *at* step t (so state t+1 was
    a reset).  Returns ``(returns, advantages)`` with the same shape as
    ``rewards``.
    """
    not_done = 1.0 - dones.astype(values.dtype)

    def step(carry, xs):
        lastgaelam, next_val = carry
        reward, value, nd = xs
        delta = reward + gamma * next_val * nd - value
        lastgaelam = delta + gamma * lmbda * nd * lastgaelam
        return (lastgaelam, value), lastgaelam

    init = (jnp.zeros_like(next_value), next_value)
    _, advantages = jax.lax.scan(step, init, (rewards, values, not_done), reverse=True)
    returns = advantages + values
    return returns, advantages


def lambda_returns(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    lmbda: float,
) -> jax.Array:
    """TD(λ) returns for imagined trajectories (Dreamer-style).

    ``rewards, values, continues`` are ``(T, B, ...)``; ``continues`` already
    folds in the discount factor (γ·(1-done)).  The recursion
    ``R_t = r_t + c_t · ((1-λ)·v_{t+1} + λ·R_{t+1})`` runs as a reversed
    ``lax.scan`` (reference equivalent: sheeprl/algos/dreamer_v3/utils.py:66-77).
    The last step bootstraps from ``values[-1]``.
    """

    def step(next_ret, xs):
        reward, cont, next_value = xs
        ret = reward + cont * ((1 - lmbda) * next_value + lmbda * next_ret)
        return ret, ret

    next_values = jnp.concatenate([values[1:], values[-1:]], axis=0)
    _, rets = jax.lax.scan(step, values[-1], (rewards, continues, next_values), reverse=True)
    return rets


# --------------------------------------------------------------------------
# symlog / two-hot
# --------------------------------------------------------------------------

def symlog(x: jax.Array) -> jax.Array:
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x: jax.Array) -> jax.Array:
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def two_hot_encoder(x: jax.Array, support_range: int = 300, num_buckets: Optional[int] = None) -> jax.Array:
    """Symlog two-hot encoding onto a symmetric integer support.

    A scalar ``v`` (after symlog) is split between its two neighboring bucket
    centers with linear weights (reference: sheeprl/utils/utils.py:156-205,
    default 300 range / 601 buckets; DreamerV3 uses its own 255-bin variant
    through TwoHotEncodingDistribution).  Vectorized: no loops, one scatter.
    ``x``: (..., 1) → (..., num_buckets).
    """
    if num_buckets is None:
        num_buckets = int(2 * support_range + 1)
    x = symlog(x)
    # clip INTO the support (reference: sheeprl/utils/utils.py:176 clips the
    # tensor): without it a value below -support splits weight between the
    # first two buckets instead of saturating the first
    x = jnp.clip(x, -support_range, support_range)
    buckets = jnp.linspace(-support_range, support_range, num_buckets, dtype=x.dtype)
    below = jnp.sum((buckets <= x).astype(jnp.int32), axis=-1) - 1
    below = jnp.clip(below, 0, num_buckets - 1)
    above = jnp.clip(below + 1, 0, num_buckets - 1)
    x0 = jnp.squeeze(x, -1)
    # below==above at the saturated top bucket: both distances are 0 there,
    # so force them to 1 (reference's `equal` branch) → 0.5+0.5 on one bucket
    equal = below == above
    dist_below = jnp.where(equal, 1.0, jnp.abs(buckets[below] - x0))
    dist_above = jnp.where(equal, 1.0, jnp.abs(buckets[above] - x0))
    total = dist_below + dist_above
    w_below = dist_above / total
    w_above = dist_below / total
    enc = (
        jax.nn.one_hot(below, num_buckets, dtype=x.dtype) * w_below[..., None]
        + jax.nn.one_hot(above, num_buckets, dtype=x.dtype) * w_above[..., None]
    )
    return enc


def two_hot_decoder(probs: jax.Array, support_range: int = 300) -> jax.Array:
    """Inverse of :func:`two_hot_encoder`: expectation over bucket centers,
    then symexp.  (..., num_buckets) → (..., 1)."""
    num_buckets = probs.shape[-1]
    buckets = jnp.linspace(-support_range, support_range, num_buckets, dtype=probs.dtype)
    return symexp(jnp.sum(probs * buckets, axis=-1, keepdims=True))


# --------------------------------------------------------------------------
# misc numerics
# --------------------------------------------------------------------------

def normalize_tensor(x: jax.Array, eps: float = 1e-8) -> jax.Array:
    # ddof=1: torch.std is unbiased (reference: sheeprl/utils/utils.py:126)
    return (x - x.mean()) / (x.std(ddof=1) + eps)


def polynomial_decay(
    current_step: int,
    *,
    initial: float = 1.0,
    final: float = 0.0,
    max_decay_steps: int = 100,
    power: float = 1.0,
) -> float:
    """Host-side polynomial schedule (reference: sheeprl/utils/utils.py:133-144)."""
    if current_step > max_decay_steps or initial == final:
        return final
    frac = (1 - current_step / max_decay_steps) ** power
    return (initial - final) * frac + final


def safetanh(x: jax.Array, eps: float = 1e-6) -> jax.Array:
    return jnp.clip(jnp.tanh(x), -1.0 + eps, 1.0 - eps)


def safeatanh(x: jax.Array, eps: float = 1e-6) -> jax.Array:
    return jnp.arctanh(jnp.clip(x, -1.0 + eps, 1.0 - eps))


def window_scan(body, carry, xs, unroll_limit: int = 16, unroll: bool = True):
    """``lax.scan`` over an update window, UNROLLED as a traced Python loop
    on the CPU backend for small convolution-bearing windows.

    Measured on XLA-CPU (BENCH_CPU.md round 5): a convolution-bearing
    update body runs ~5x slower inside ``lax.scan``'s outlined call (19.4 s
    vs 3.5 s for the identical DreamerV1 benchmark-sized update; the
    penalty is per iteration and ``lax.scan(..., unroll=True)`` does not
    remove it — only true trace-time inlining does).  Pure-matmul bodies
    show no such penalty, and unrolling them only inflates compile time
    (the PPO CartPole benchmark DOUBLED from the bigger program), so
    callers pass ``unroll=False`` for conv-free bodies.  On TPU the
    outlined while-loop is the right lowering (compile time stays flat),
    so scan is always kept there.

    Compile cadence is unchanged either way: the window length already
    participates in the input shape signature, so each distinct ``U``
    compiled before and still does.
    """
    leaves = jax.tree.leaves(xs)
    length = int(leaves[0].shape[0]) if leaves else 0
    if any(l.shape[0] != length for l in leaves):  # keep lax.scan's guarantee
        raise ValueError(
            f"window_scan: inconsistent leading dims {[l.shape[0] for l in leaves]}"
        )
    backend = jax.default_backend()
    if not unroll or backend != "cpu" or length == 0 or length > unroll_limit:
        return jax.lax.scan(body, carry, xs)
    ys = []
    for u in range(length):
        x_u = jax.tree.map(lambda v: v[u], xs)
        carry, y = body(carry, x_u)
        ys.append(y)
    stacked = jax.tree.map(lambda *vs: jnp.stack(vs, 0), *ys)
    return carry, stacked


def merge_framestack(x, xp=np):
    """``(..., S, H, W, C)`` framestacked pixels -> ``(..., H, W, S*C)``.

    One source of truth for the stack-to-channels layout every pixel path
    uses — train blocks, player/rollout prep, device-mirror gathers
    (``xp=jnp`` runs the permute on device).  Arbitrary leading batch dims.
    """
    s = x.shape
    x = xp.moveaxis(x, -4, -2)  # (..., H, W, S, C)
    return x.reshape(*s[:-4], s[-3], s[-2], s[-4] * s[-1])


def probe_bytes_per_update(rb, batch_size: int, **sample_kwargs) -> float:
    """Host-side byte cost of ONE update's sampled batch (for window_chunks).

    Draws a 1-update probe sample and sums leaf nbytes; snapshots/restores
    the global numpy RNG so the probe does not shift the sampling stream
    (goldens pin it).
    """
    rng_state = np.random.get_state()
    try:
        probe = rb.sample(batch_size, n_samples=1, **sample_kwargs)
    finally:
        np.random.set_state(rng_state)
    return float(sum(np.asarray(v).nbytes for v in probe.values()))


def mirror_hbm_bytes_per_update(
    obs_space: Any, cnn_keys, batch_size: int, rows: int = 1
) -> float:
    """Per-update HBM bytes of the device-GATHERED pixel block when the
    replay mirror is on (the pixels never ship H2D; the ring is uint8, so
    1 byte/px).  ``rows`` is how many gathered pixel rows each sampled
    transition contributes: the sequence length for sequential samplers
    (Dreamer), 2 for transition samplers that gather obs + next_obs
    (SAC-AE).  Feed the result to ``window_chunks(hbm_bytes_per_update=...)``
    so both loops budget the same formula."""
    return float(
        sum(int(np.prod(obs_space[k].shape)) for k in cnn_keys)
        * int(rows)
        * int(batch_size)
    )


def window_chunks(
    n_updates: int,
    bytes_per_update: float,
    budget_bytes: Optional[float] = None,
    hbm_bytes_per_update: float = 0.0,
):
    """DEPRECATED: the algo loops now chunk purely for compile reuse via
    ``data/device_replay.update_chunks`` — with the replay ring
    device-resident (``buffer.device``) there is no shipped H2D block to
    byte-budget.  Kept (with ``probe_bytes_per_update`` /
    ``mirror_hbm_bytes_per_update``) for external callers on the host path.

    Original contract: split an update window into dispatch chunk sizes
    whose shipped ``(U, ...)`` batch block stays under a device byte budget.

    The first window after ``learning_starts`` is a burst: the ratio
    governor repays every pre-training env step at once, so e.g.
    ``learning_starts=1024`` at replay_ratio 1 demands U=1024 — sampled and
    shipped as ONE uint8 block that is 12.9 GiB raw / 25.8 GiB in padded
    TPU layout for (1024, 64, 16, 64, 64, 3), over a 16 GiB HBM chip
    (the round-5 TPU learning capture died on exactly that alloc).
    Chunking caps per-dispatch block bytes; steady-state windows are far
    below the budget and stay single-dispatch.  Budget default 1 GiB
    (override ``SHEEPRL_MAX_WINDOW_BYTES``) — the padded-layout worst case
    observed is 2x raw, leaving ample HBM for params/activations.

    Chunk sizes are powers of two (largest fitting the budget, greedily
    decomposing the remainder) — every distinct chunk length compiles its
    own train-phase executable, and a remote TPU compile costs minutes, so
    a burst must reuse a handful of shapes rather than mint arbitrary ones
    (and the small tail chunks coincide with the steady-state window sizes,
    which are also tiny powers of two).

    ``bytes_per_update`` is the SHIPPED (H2D) cost of one update's batch.
    With the device mirror, pixel sequences never ship — but the on-device
    gathered ``(U, ...)`` pixel block still consumes HBM; pass its per-update
    bytes as ``hbm_bytes_per_update`` so the chunk cap honors BOTH ceilings
    (``SHEEPRL_MAX_HBM_WINDOW_BYTES``, default 2 GiB — the gathered block
    lives on-chip only, no padded-H2D-layout 2x, so it gets a looser cap
    than the shipped budget).
    """
    if budget_bytes is None:
        budget_bytes = float(os.environ.get("SHEEPRL_MAX_WINDOW_BYTES", 2**30))
    max_u = max(1, int(budget_bytes // max(bytes_per_update, 1.0)))
    if hbm_bytes_per_update > 0.0:
        hbm_budget = float(os.environ.get("SHEEPRL_MAX_HBM_WINDOW_BYTES", 2**31))
        max_u = min(max_u, max(1, int(hbm_budget // hbm_bytes_per_update)))
    cap = 1 << (max_u.bit_length() - 1)  # largest power of two <= max_u
    chunks = []
    remaining = int(n_updates)
    while remaining > 0:
        step = min(cap, 1 << (remaining.bit_length() - 1))
        chunks.append(step)
        remaining -= step
    return chunks


def should_unroll_updates(cnn_keys, n_bodies: int, limit: int = 32) -> bool:
    """One source of truth for the PPO-family two-level unroll decision:
    conv trunk present (the penalty is conv-specific), CPU backend, and a
    total body count small enough to compile unrolled."""
    return bool(cnn_keys) and jax.default_backend() == "cpu" and n_bodies <= limit


# --------------------------------------------------------------------------
# replay-ratio governor
# --------------------------------------------------------------------------

class TrainWindow:
    """Accrue ``Ratio``-owed gradient steps over K env iterations and release
    them as ONE scanned dispatch (``algo.train_window_iters``).

    Update math and count are exactly preserved — only the dispatch cadence
    changes (data staleness within a window is at most K-1 iterations, the
    reference's decoupled-trainer staleness class).  Shared by the SAC and
    SAC-AE loops so the flush rule cannot drift between them.
    """

    def __init__(self, window_iters: int, pending: int = 0):
        self.window_iters = max(int(window_iters), 1)
        self.pending = int(pending)

    def push(self, granted: int, update: int, learning_starts: int, total_iters: int) -> int:
        """Add this iteration's granted steps; return the number to run NOW
        (0 while the window is filling).  The last iteration always flushes
        so no owed step is ever dropped."""
        self.pending += int(granted)
        window_full = (update - learning_starts) % self.window_iters == self.window_iters - 1
        if self.pending > 0 and (window_full or update == total_iters):
            out, self.pending = self.pending, 0
            return out
        return 0


class Ratio:
    """Keeps gradient-steps : env-steps at a configured ratio.

    Host-side control flow by design: the number of updates per iteration is
    data-dependent, which must stay outside jit (SURVEY.md §7 hard part 2).
    Mirrors the accounting of the reference governor
    (reference: sheeprl/utils/utils.py:259-300).
    """

    def __init__(self, ratio: float, pretrain_steps: int = 0):
        if pretrain_steps < 0:
            raise ValueError(f"pretrain_steps must be non-negative, got {pretrain_steps}")
        if ratio < 0:
            raise ValueError(f"ratio must be non-negative, got {ratio}")
        self._ratio = float(ratio)
        self._pretrain_steps = int(pretrain_steps)
        self._prev: Optional[float] = None

    def __call__(self, in_steps: int) -> int:
        # Hafner's law, matching the reference exactly
        # (reference: sheeprl/utils/utils.py:273-291): the FIRST call converts
        # pretrain_steps (clamped to the current step count, in STEP units)
        # when set, else the current steps; later calls convert the delta and
        # carry the fractional remainder in step units via ``_prev``.
        if self._ratio == 0:
            return 0
        if self._prev is None:
            self._prev = in_steps
            if self._pretrain_steps > 0:
                if in_steps < self._pretrain_steps:
                    warnings.warn(
                        "pretrain_steps exceeds the current step count; clamping "
                        "to the current steps (reference behavior)", UserWarning
                    )
                    self._pretrain_steps = in_steps
                return int(self._pretrain_steps * self._ratio)
            return int(in_steps * self._ratio)
        repeats = int((in_steps - self._prev) * self._ratio)
        self._prev += repeats / self._ratio
        return repeats

    def state_dict(self) -> Dict[str, Any]:
        return {
            "ratio": self._ratio,
            "pretrain_steps": self._pretrain_steps,
            "prev": self._prev,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> "Ratio":
        self._ratio = float(state["ratio"])
        self._pretrain_steps = int(state["pretrain_steps"])
        if "prev" in state:
            self._prev = None if state["prev"] is None else float(state["prev"])
        else:
            # legacy layout (accumulator-based): translate so a resumed run
            # keeps the same future output stream
            prev_in = int(state["prev_in_steps"])
            accum = float(state["accum"])
            if prev_in == 0 and accum == 0.0:
                self._prev = None
            else:
                self._prev = prev_in - (accum / self._ratio if self._ratio else 0.0)
        return self


# --------------------------------------------------------------------------
# config persistence / misc host helpers
# --------------------------------------------------------------------------

def save_configs(cfg: Any, log_dir: str) -> None:
    os.makedirs(log_dir, exist_ok=True)
    as_dict = cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg)
    with open(os.path.join(log_dir, "config.yaml"), "w") as f:
        yaml.safe_dump(as_dict, f, sort_keys=False)


def print_config(cfg: Any) -> None:
    try:
        from rich.pretty import pprint

        pprint(cfg.as_dict() if hasattr(cfg, "as_dict") else cfg, expand_all=False)
    except Exception:
        print(yaml.safe_dump(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg)))


def unwrap_fabric(module: Any) -> Any:  # parity shim; no wrapping in JAX
    return module


def dict_to_numpy(tree: Dict[str, Any]) -> Dict[str, np.ndarray]:
    return {k: np.asarray(v) for k, v in tree.items()}


def copy_cfg(cfg: Any) -> Any:
    return copy.deepcopy(cfg)


def _untrusted_block_until_ready() -> bool:
    """True when the active backend's ``block_until_ready`` resolves at
    dispatch instead of completion (the axon tunnel PJRT plugin — detected
    by its platform_version stamp), so timing fences must materialize a
    value instead."""
    try:
        version = getattr(jax.devices()[0].client, "platform_version", "")
    except Exception:
        return False
    return "axon" in version


def device_sync(tree: Any = None) -> None:
    """True device fence: block the host until device work has FINISHED.

    ``jax.Array.block_until_ready`` resolves at *dispatch*, not completion,
    on the axon tunnel PJRT plugin (BENCH_TPU.md timing-validity note), so
    every wall-clock measurement must instead materialize a value that
    depends on the work.  This fence slices one element from each leaf of
    ``tree`` (or from every live array when ``tree`` is None), reduces them
    to a single scalar in one device program per platform, and fetches that
    scalar to host — per-device program ordering guarantees the fetch
    returns only after every producer has executed.  Cost: one tiny D2H
    transfer (~65 ms over the tunnel, ~µs on local backends).

    On backends whose ``block_until_ready`` IS trustworthy (cpu / gpu /
    directly-attached tpu), BOTH forms use it directly: the token program
    is O(leaves) to build and compile, and a fresh state-tree signature
    (every checkpoint save in every test process) would pay a multi-second
    XLA compile for a guarantee block_until_ready already provides there.
    """
    if tree is None:
        leaves = list(jax.live_arrays())
    else:
        leaves = jax.tree_util.tree_leaves(tree)
    if not _untrusted_block_until_ready():
        for a in leaves:
            # donated inputs may linger as deleted buffers — skip, and
            # keep draining the rest if any single array refuses
            try:
                if isinstance(a, jax.Array) and not a.is_deleted():
                    a.block_until_ready()
            except Exception:
                continue
        return
    groups: Dict[Any, list] = {}
    for leaf in leaves:
        if not isinstance(leaf, jax.Array):
            continue
        try:
            if leaf.is_deleted():
                continue
            # group by exact device set: concatenating tokens committed to
            # different devices (or shardings) would raise and silently void
            # the fence on the one backend that needs it
            key = tuple(sorted((d.platform, d.id) for d in leaf.devices()))
            if jnp.issubdtype(leaf.dtype, jax.dtypes.extended):
                # typed PRNG key arrays (and other extended dtypes) have no
                # float32 cast — fence their uint32 key-data view instead of
                # skipping the leaf: RNG state threaded through the timed
                # program must hold the fence like any other output
                leaf = jax.random.key_data(leaf)
            groups.setdefault(key, []).append(jnp.ravel(leaf)[:1].astype(jnp.float32))
        except Exception:
            continue
    for toks in groups.values():
        try:
            tok = jnp.concatenate(toks) if len(toks) > 1 else toks[0]
            np.asarray(tok.sum())
        except Exception:
            # the fence must never take down the run.  On the untrusted
            # backend fall back to per-token materialization (slow but
            # correct); elsewhere block_until_ready is fine.
            untrusted = _untrusted_block_until_ready()
            for t in toks:
                try:
                    if untrusted:
                        np.asarray(t)
                    else:
                        t.block_until_ready()
                except Exception:
                    continue


_ACCELERATOR_ALIVE: Optional[bool] = None

# Cross-process probe cache: a wedged tunnel costs the 90 s subprocess probe
# once per TTL window, not once per bench target / graft entry (VERDICT r3).
# Per-UID path + ownership check: on a multi-user host another user must not
# be able to pre-create the file and poison the alive/wedged verdict.
_PROBE_CACHE_PATH = os.path.join(
    os.environ.get("XDG_RUNTIME_DIR") or tempfile.gettempdir(),
    f"sheeprl_tpu_probe_cache.{os.getuid() if hasattr(os, 'getuid') else 'u'}",
)
_PROBE_CACHE_TTL_S = 600.0


def _read_probe_cache() -> Optional[bool]:
    try:
        if hasattr(os, "getuid") and os.stat(_PROBE_CACHE_PATH).st_uid != os.getuid():
            return None
        with open(_PROBE_CACHE_PATH) as f:
            stamp, verdict = f.read().split()
        if time.time() - float(stamp) <= _PROBE_CACHE_TTL_S:
            return verdict == "alive"
    except (OSError, ValueError):
        pass
    return None


def _write_probe_cache(alive: bool) -> None:
    try:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(_PROBE_CACHE_PATH))
        with os.fdopen(fd, "w") as f:
            f.write(f"{time.time()} {'alive' if alive else 'wedged'}")
        os.replace(tmp, _PROBE_CACHE_PATH)
    except OSError:
        pass  # cache is an optimization; the probe result still stands


def accelerator_alive(timeout_s: int = 90) -> bool:
    """Probe the default JAX backend in a SUBPROCESS (memoized per process,
    plus a short-TTL cross-process cache file).

    A wedged TPU tunnel hangs ``jax.devices()`` forever; probing in a child
    process bounds the damage so callers (bench.py, __graft_entry__.py) can
    fall back to CPU instead of hanging.
    """
    global _ACCELERATOR_ALIVE
    if _ACCELERATOR_ALIVE is not None:
        return _ACCELERATOR_ALIVE
    cached = _read_probe_cache()
    if cached is not None:
        _ACCELERATOR_ALIVE = cached
        return _ACCELERATOR_ALIVE
    import subprocess
    import sys

    try:
        _ACCELERATOR_ALIVE = (
            subprocess.run(
                [
                    sys.executable,
                    "-c",
                    # an actual dispatch MATERIALIZED to host, not just device
                    # enumeration or block_until_ready: a half-wedged tunnel
                    # can still LIST devices while computation hangs, and
                    # block_until_ready resolves at dispatch on the tunnel
                    "import jax, jax.numpy as jnp, numpy as np; jax.devices();"
                    " assert float(np.asarray((jnp.ones((8, 8)) * 2).sum())) == 128.0",
                ],
                timeout=timeout_s,
                capture_output=True,
            ).returncode
            == 0
        )
    except subprocess.TimeoutExpired:
        _ACCELERATOR_ALIVE = False
    _write_probe_cache(_ACCELERATOR_ALIVE)
    return _ACCELERATOR_ALIVE


def force_cpu_backend() -> bool:
    """Pin this process's default JAX backend to CPU.  Returns False (with a
    visible warning) if backends were already initialized — in that case the
    caller's subsequent device use may still target the accelerator."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        return True
    except Exception as e:  # pragma: no cover - depends on init order
        print(f"[sheeprl_tpu] WARNING: could not force CPU backend: {e}", flush=True)
        return False
