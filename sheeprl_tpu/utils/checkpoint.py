"""Checkpoint serialization — compatibility shim.

The implementation moved to the fault-tolerant checkpointing subsystem
(:mod:`sheeprl_tpu.checkpoint`, see docs/checkpointing.md): durable
fsync'd atomic writes, typed-PRNG-key-safe host trees, the multi-rank
commit protocol, async snapshots, preemption handling and retention all
live there.  This module keeps the original import surface:

* :func:`save_checkpoint` — single-file durable pickle (``fabric.save``).
* :func:`load_checkpoint` — loads a legacy ``.ckpt`` file or a committed
  snapshot directory.
* :func:`prune_checkpoints` — legacy flat ``ckpt_*.ckpt`` retention; new
  snapshot directories are retained by
  :func:`sheeprl_tpu.checkpoint.gc_checkpoints`.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Union

from sheeprl_tpu.checkpoint.serialize import (  # noqa: F401  (re-exports)
    load_checkpoint,
    save_checkpoint,
)
from sheeprl_tpu.checkpoint.protocol import latest_checkpoint  # noqa: F401


def prune_checkpoints(ckpt_dir: Union[str, os.PathLike], keep_last: int) -> None:
    """Delete all but the newest ``keep_last`` legacy flat-file checkpoints
    in a directory (reference: sheeprl/utils/callback.py:144-148)."""
    if keep_last is None or keep_last <= 0:
        return
    ckpts = sorted(Path(ckpt_dir).glob("ckpt_*.ckpt"), key=lambda p: p.stat().st_mtime)
    for old in ckpts[:-keep_last]:
        try:
            old.unlink()
        except OSError:
            pass
