"""Checkpoint serialization.

A checkpoint is a nested dict whose leaves may be jax arrays (fetched to
host), numpy arrays, ``MemmapArray``s (pickled as references to their backing
files — the reference persists buffers the same way,
sheeprl/utils/memmap.py:251-258), and plain Python scalars/objects.

Format: a single pickle stream with jax arrays converted to numpy.  The save
is atomic (tmp file + rename) so a preempted TPU job never leaves a torn
checkpoint behind.
"""

from __future__ import annotations

import os
import pickle
import tempfile
from pathlib import Path
from typing import Any, Dict, Union

import jax
import numpy as np


def _to_host(tree: Any) -> Any:
    def leaf(x: Any) -> Any:
        if isinstance(x, jax.Array):
            return np.asarray(jax.device_get(x))
        return x

    return jax.tree.map(leaf, tree, is_leaf=lambda x: isinstance(x, jax.Array))


def save_checkpoint(path: Union[str, os.PathLike], state: Dict[str, Any]) -> None:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    host_state = _to_host(state)
    fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_checkpoint(path: Union[str, os.PathLike]) -> Dict[str, Any]:
    with open(path, "rb") as f:
        return pickle.load(f)


def prune_checkpoints(ckpt_dir: Union[str, os.PathLike], keep_last: int) -> None:
    """Delete all but the newest ``keep_last`` checkpoints in a directory
    (reference: sheeprl/utils/callback.py:144-148)."""
    if keep_last is None or keep_last <= 0:
        return
    ckpts = sorted(Path(ckpt_dir).glob("ckpt_*.ckpt"), key=lambda p: p.stat().st_mtime)
    for old in ckpts[:-keep_last]:
        try:
            old.unlink()
        except OSError:
            pass
