"""Profiler gating for train loops + monitor shims.

The process-global monitors that historically lived here — the compile
accounting of the compile-once layer (PR 1), the checkpoint writer
accounting (PR 2) and the resilience accounting (PR 8) — are owned by the
**telemetry subsystem** since PR 13 (``sheeprl_tpu/telemetry/monitors.py``,
registered with the :data:`~sheeprl_tpu.telemetry.hub.HUB` behind one
flush contract; see docs/telemetry.md).  The names below are thin shims
over the SAME objects, kept so every existing call site
(``from sheeprl_tpu.utils.profiler import COMPILE_MONITOR``) and test
keeps working unchanged.

This module keeps :class:`ProfilerGate` — the config-armed
``jax.profiler`` window around a fixed update range
(``metric.profiler.start_update``/``stop_update``).  For *on-demand*
windows on a live run (update numbers, ``SHEEPRL_TRACE_AT``, SIGUSR1),
use ``telemetry.trace_at`` — ``sheeprl_tpu/telemetry/tracer.py``.
"""

from __future__ import annotations

import os
from typing import Any

from sheeprl_tpu.telemetry.monitors import (  # noqa: F401  (thin shims)
    CHECKPOINT_MONITOR,
    COMPILE_MONITOR,
    RESILIENCE_MONITOR,
    CheckpointMonitor,
    CompileMonitor,
    RecompileLimitExceeded,
    ResilienceMonitor,
)


class ProfilerGate:
    """Start/stop ``jax.profiler`` around a window of training updates."""

    def __init__(self, cfg: Any, log_dir: str):
        pcfg = (cfg.metric.get("profiler", {}) or {}) if "metric" in cfg else {}
        self.enabled = bool(pcfg.get("enabled", False))
        self.start_update = int(pcfg.get("start_update", 10))
        self.stop_update = int(pcfg.get("stop_update", 12))
        self.trace_dir = os.path.join(log_dir, "profiler")
        self._on = False

    def step(self, update: int) -> None:
        """Call once per training update with the loop counter."""
        if not self.enabled:
            return
        import jax

        if not self._on and self.start_update <= update < self.stop_update:
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
            self._on = True
        elif self._on and update >= self.stop_update:
            jax.profiler.stop_trace()
            self._on = False

    def close(self) -> None:
        if self._on:
            import jax

            jax.profiler.stop_trace()
            self._on = False
