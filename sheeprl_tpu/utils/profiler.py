"""Profiler gating for train loops.

The reference has no profiler integration (SURVEY.md §5.1 — named timers
only); on TPU a ``jax.profiler`` trace is the difference between guessing
and knowing where the step time goes (MXU utilization, HBM stalls, host
H2D gaps), so the TPU framework makes it a config switch:

    metric.profiler.enabled=True metric.profiler.start_update=10 \
    metric.profiler.stop_update=12

captures updates [start, stop) into ``<log_dir>/profiler`` (viewable with
TensorBoard's profile plugin / xprof).  Updates before ``start_update``
are skipped so compilation and warm-up never pollute the trace.
"""

from __future__ import annotations

import os
from typing import Any


class ProfilerGate:
    """Start/stop ``jax.profiler`` around a window of training updates."""

    def __init__(self, cfg: Any, log_dir: str):
        pcfg = (cfg.metric.get("profiler", {}) or {}) if "metric" in cfg else {}
        self.enabled = bool(pcfg.get("enabled", False))
        self.start_update = int(pcfg.get("start_update", 10))
        self.stop_update = int(pcfg.get("stop_update", 12))
        self.trace_dir = os.path.join(log_dir, "profiler")
        self._on = False

    def step(self, update: int) -> None:
        """Call once per training update with the loop counter."""
        if not self.enabled:
            return
        import jax

        if not self._on and self.start_update <= update < self.stop_update:
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
            self._on = True
        elif self._on and update >= self.stop_update:
            jax.profiler.stop_trace()
            self._on = False

    def close(self) -> None:
        if self._on:
            import jax

            jax.profiler.stop_trace()
            self._on = False
