"""Optional-dependency availability flags (reference: sheeprl/utils/imports.py:1-17)."""

from __future__ import annotations

import importlib.util


def _available(mod: str) -> bool:
    try:
        return importlib.util.find_spec(mod) is not None
    except Exception:
        return False


_IS_ALE_AVAILABLE = _available("ale_py")
_IS_DMC_AVAILABLE = _available("dm_control")
_IS_CRAFTER_AVAILABLE = _available("crafter")
_IS_MINERL_AVAILABLE = _available("minerl")
_IS_MINEDOJO_AVAILABLE = _available("minedojo")
_IS_DIAMBRA_AVAILABLE = _available("diambra")
_IS_SMB_AVAILABLE = _available("gym_super_mario_bros")
_IS_MLFLOW_AVAILABLE = _available("mlflow")
