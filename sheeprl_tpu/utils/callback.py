"""Checkpoint callback.

Role parity with the reference's ``CheckpointCallback``
(reference: sheeprl/utils/callback.py:14-148): algorithms fire
``fabric.call("on_checkpoint_coupled", ...)`` (or ``_player``/``_trainer`` in
the decoupled topology) and the callback attaches replay-buffer state, applies
the buffer-consistency trick, saves, and prunes old checkpoints.

Buffer-consistency trick: the environment state is not checkpointed, so on
resume the step at the write head must not be treated as a continuation — the
last stored step is temporarily marked truncated/done for the save and
restored afterwards (reference: sheeprl/utils/callback.py:87-142).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Dict, Optional

import numpy as np

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, EpisodeBuffer, ReplayBuffer
from sheeprl_tpu.utils.checkpoint import prune_checkpoints


class CheckpointCallback:
    def __init__(self, keep_last: Optional[int] = 5):
        self.keep_last = keep_last

    # -- hooks -------------------------------------------------------------
    def on_checkpoint_coupled(
        self,
        fabric: Any,
        ckpt_path: str,
        state: Dict[str, Any],
        replay_buffer: Any = None,
    ) -> None:
        if replay_buffer is not None:
            with _consistent_tail(replay_buffer):
                state = dict(state)
                state["rb"] = _buffer_state(replay_buffer)
                # with an async manager the state SNAPSHOT (host memcpys)
                # happens inside save() on this thread, i.e. still under the
                # tail patch — only serialization/IO runs in the background
                self._save(fabric, ckpt_path, state)
        else:
            self._save(fabric, ckpt_path, state)

    def on_checkpoint_player(self, fabric: Any, ckpt_path: str, state: Dict[str, Any], replay_buffer: Any = None) -> None:
        self.on_checkpoint_coupled(fabric, ckpt_path, state, replay_buffer)

    def on_checkpoint_trainer(self, fabric: Any, ckpt_path: str, state: Dict[str, Any]) -> None:
        self._save(fabric, ckpt_path, state)

    # -- save routing --------------------------------------------------------
    def _save(self, fabric: Any, ckpt_path: str, state: Dict[str, Any]) -> None:
        """Route through the run's CheckpointManager (async snapshots, commit
        protocol, retention — sheeprl_tpu/checkpoint) when the loop has bound
        one; otherwise the legacy single-file path + flat-file pruning."""
        manager = getattr(fabric, "checkpoint_manager", None)
        if manager is not None:
            manager.save(int(state.get("policy_step", 0)), state)
            return
        fabric.save(ckpt_path, state)
        if fabric.is_global_zero:
            prune_checkpoints(Path(ckpt_path).parent, self.keep_last)


def _buffer_state(rb: Any) -> Any:
    if isinstance(rb, (list, tuple)):
        return [b.state_dict() for b in rb]
    return rb.state_dict()


class _consistent_tail:
    """Temporarily force the last written step to look like an episode end."""

    def __init__(self, rb: Any):
        self.rbs = []
        for buf in rb if isinstance(rb, (list, tuple)) else [rb]:
            if isinstance(buf, EnvIndependentReplayBuffer):
                self.rbs.extend(buf.buffer)
            elif isinstance(buf, ReplayBuffer):
                self.rbs.append(buf)
            # EpisodeBuffer drops open episodes in state_dict already
        self._saved = []

    def __enter__(self) -> "_consistent_tail":
        for rb in self.rbs:
            patch = {}
            # Buffers that store an explicit next_obs per row (SAC/DroQ style)
            # need no tail patch: every row is self-contained, and forcing a
            # fake terminated=1 would permanently drop that row's bootstrap
            # after a buffer-checkpointed resume.
            if len(rb) == 0 or any(k.startswith("next_") for k in rb.keys()):
                self._saved.append(patch)
                continue
            tail = (rb._pos - 1) % rb.buffer_size
            # Only episode-boundary keys that mean "do not continue across the
            # checkpoint" are patched: truncated/dones. Never force
            # terminated=1 — that is a value-semantics (bootstrap-killing)
            # flag, not a storage-boundary one.
            for key in ("truncated", "dones"):
                if key in rb:
                    patch[key] = (tail, np.array(rb._buf[key][tail]))
                    rb._buf[key][tail] = np.ones_like(np.asarray(rb._buf[key][tail]))
            self._saved.append(patch)
        return self

    def __exit__(self, *exc: Any) -> bool:
        for rb, patch in zip(self.rbs, self._saved):
            for key, (tail, val) in patch.items():
                rb._buf[key][tail] = val
        return False
