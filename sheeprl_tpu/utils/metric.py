"""Metric aggregation.

Host-side running aggregators with the same role as the reference's
torchmetrics-based ``MetricAggregator`` (reference: sheeprl/utils/metric.py:17-195):
a dict of named metrics that train loops ``update``, a ``compute`` that drops
NaNs/non-scalars, global disabling by log level, and a rank-independent
variant that gathers per-process values across hosts.

Device values are accepted lazily: ``update`` stores whatever it is given
(including not-yet-materialized ``jax.Array``s from inside the train step —
asynchronous dispatch means no sync happens until ``compute``), and
``compute`` coerces to float.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional

import numpy as np


class _RunningMetric:
    """One named accumulator: mode 'mean' | 'sum' | 'last' | 'max' | 'min'."""

    def __init__(self, mode: str = "mean"):
        if mode not in ("mean", "sum", "last", "max", "min"):
            raise ValueError(f"Unknown metric mode: {mode}")
        self.mode = mode
        self.reset()

    def reset(self) -> None:
        self._values: List[Any] = []

    def update(self, value: Any) -> None:
        self._values.append(value)

    @property
    def empty(self) -> bool:
        return not self._values

    def compute(self) -> Optional[float]:
        if not self._values:
            return None
        vals = []
        for v in self._values:
            arr = np.asarray(v, dtype=np.float64)
            if arr.size != 1:
                return None
            vals.append(float(arr.reshape(())))
        arr = np.asarray(vals)
        arr = arr[~np.isnan(arr)]
        if arr.size == 0:
            return None
        if self.mode == "mean":
            return float(arr.mean())
        if self.mode == "sum":
            return float(arr.sum())
        if self.mode == "last":
            return float(arr[-1])
        if self.mode == "max":
            return float(arr.max())
        return float(arr.min())


class MetricAggregator:
    disabled: bool = False

    def __init__(self, metrics: Optional[Dict[str, str]] = None, raise_on_missing: bool = False):
        self.metrics: Dict[str, _RunningMetric] = {}
        self.raise_on_missing = raise_on_missing
        for name, mode in (metrics or {}).items():
            self.add(name, mode)

    def add(self, name: str, mode: str = "mean") -> None:
        if name not in self.metrics:
            self.metrics[name] = _RunningMetric(mode if isinstance(mode, str) else "mean")

    def update(self, name: str, value: Any) -> None:
        if self.disabled:
            return
        if name not in self.metrics:
            if self.raise_on_missing:
                raise KeyError(f"Unregistered metric: {name}")
            return
        self.metrics[name].update(value)

    def pop(self, name: str) -> None:
        self.metrics.pop(name, None)

    def reset(self) -> None:
        for m in self.metrics.values():
            m.reset()

    def keys(self) -> Iterable[str]:
        return self.metrics.keys()

    def compute(self) -> Dict[str, float]:
        """Return finite scalar values only (NaNs and non-scalars dropped,
        like the reference compute, sheeprl/utils/metric.py:109-143)."""
        if self.disabled:
            return {}
        out: Dict[str, float] = {}
        for name, metric in self.metrics.items():
            if metric.empty:
                continue
            val = metric.compute()
            if val is not None and np.isfinite(val):
                out[name] = val
        return out


class RankIndependentMetricAggregator(MetricAggregator):
    """Aggregator whose ``compute`` first all-gathers values across processes
    (reference: sheeprl/utils/metric.py:146-195).  In the single-controller
    JAX runtime there is one process per host; cross-host gathering uses
    ``jax.experimental.multihost_utils`` when world_size > 1.
    """

    def __init__(self, *args: Any, fabric: Any = None, **kwargs: Any):
        super().__init__(*args, **kwargs)
        self._fabric = fabric

    def compute(self) -> Dict[str, float]:
        local = super().compute()
        if self._fabric is None or getattr(self._fabric, "world_size", 1) == 1:
            return local
        gathered = self._fabric.all_gather_object(local)
        merged: Dict[str, List[float]] = defaultdict(list)
        for d in gathered:
            for k, v in d.items():
                merged[k].append(v)
        return {k: float(np.mean(v)) for k, v in merged.items()}


def flush_metrics(
    aggregator: "MetricAggregator",
    timer_obj: Any,
    logger: Any,
    policy_step: int,
    last_log: int,
    extra_times: Optional[Dict[str, float]] = None,
    extra_metrics: Optional[Dict[str, float]] = None,
) -> int:
    """THE end-of-interval metric flush every train loop shares: compute+reset
    the aggregator, drain the named timers, derive the two SPS throughputs
    (reference: the identical block at e.g. sheeprl/algos/ppo/ppo.py:376-413 /
    dreamer_v3.py:715-730), merge ``extra_times`` (e.g. trainer-side times
    shipped over DCN in the dedicated decoupled topology) and
    ``extra_metrics`` (e.g. ``Params/replay_ratio``), log, and return the new
    ``last_log``."""
    metrics = aggregator.compute()
    aggregator.reset()
    times = timer_obj.to_dict(reset=True)
    if extra_times:
        times = {**times, **{k: times.get(k, 0.0) + v for k, v in extra_times.items()}}
    steps_since = max(policy_step - last_log, 1)
    if "Time/env_interaction_time" in times:
        metrics["Time/sps_env_interaction"] = steps_since / max(times["Time/env_interaction_time"], 1e-9)
    if "Time/train_time" in times:
        metrics["Time/sps_train"] = steps_since / max(times["Time/train_time"], 1e-9)
    if extra_metrics:
        metrics.update(extra_metrics)
    metrics.update(times)
    # telemetry hub flush: every registered source in one call — the
    # compile-once accounting (Compile/*: a count that keeps growing after
    # warm-up IS the recompile pathology), checkpoint writer accounting
    # (Checkpoint/*: async-save cost), resilience accounting (Resilience/*:
    # empty unless something actually happened), the span tracker's
    # per-window phase-breakdown fractions (Phase/*), and anything a run
    # registered (Sebulba queues, the policy service).  roll=True closes
    # the span window — the metric interval IS the phase window.
    from sheeprl_tpu.telemetry.hub import HUB

    metrics.update(HUB.flush(roll=True))
    HUB.note_step(policy_step)
    if logger is not None and metrics:
        logger.log_metrics(metrics, policy_step)
    return policy_step
