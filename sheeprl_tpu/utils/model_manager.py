"""Model registry (filesystem-backed).

Capability parity with the reference's MLflow model manager
(reference: sheeprl/utils/mlflow.py:36-427 — AbstractModelManager,
register_model, register_model_from_checkpoint, transition/delete/download):
a versioned store of named model artifacts with metadata.  MLflow is not
available in this environment; the store is a directory tree

    <registry_root>/<model_name>/v<k>/{params.pkl, meta.yaml}

which covers the same lifecycle (register, list, get latest/specific
version, transition stage, delete, download≡path).
"""

from __future__ import annotations

import os
import pickle
import time
from abc import ABC, abstractmethod
from pathlib import Path
from typing import Any, Dict, List, Optional

import yaml


class AbstractModelManager(ABC):
    """Lifecycle surface (reference: sheeprl/utils/mlflow.py:36-73)."""

    @abstractmethod
    def register_model(self, name: str, params: Any, description: str = "", metadata: Optional[Dict] = None) -> int: ...

    @abstractmethod
    def get_latest_version(self, name: str) -> Optional[int]: ...

    @abstractmethod
    def load_model(self, name: str, version: Optional[int] = None) -> Any: ...

    @abstractmethod
    def transition_model(self, name: str, version: int, stage: str) -> None: ...

    @abstractmethod
    def delete_model(self, name: str, version: Optional[int] = None) -> None: ...


class FileSystemModelManager(AbstractModelManager):
    def __init__(self, registry_root: str = "models_registry"):
        self.root = Path(registry_root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _model_dir(self, name: str) -> Path:
        return self.root / name

    def _versions(self, name: str) -> List[int]:
        d = self._model_dir(name)
        if not d.is_dir():
            return []
        return sorted(int(p.name[1:]) for p in d.iterdir() if p.name.startswith("v"))

    def register_model(self, name: str, params: Any, description: str = "", metadata: Optional[Dict] = None) -> int:
        import jax

        version = (self.get_latest_version(name) or 0) + 1
        vdir = self._model_dir(name) / f"v{version}"
        vdir.mkdir(parents=True, exist_ok=True)
        host_params = jax.device_get(params)
        with open(vdir / "params.pkl", "wb") as f:
            pickle.dump(host_params, f, protocol=pickle.HIGHEST_PROTOCOL)
        with open(vdir / "meta.yaml", "w") as f:
            yaml.safe_dump(
                {
                    "name": name,
                    "version": version,
                    "description": description,
                    "stage": "None",
                    "registered_at": time.strftime("%Y-%m-%d %H:%M:%S"),
                    "metadata": metadata or {},
                },
                f,
            )
        return version

    def get_latest_version(self, name: str) -> Optional[int]:
        versions = self._versions(name)
        return versions[-1] if versions else None

    def get_model_info(self, name: str, version: Optional[int] = None) -> Dict[str, Any]:
        version = version or self.get_latest_version(name)
        with open(self._model_dir(name) / f"v{version}" / "meta.yaml") as f:
            return yaml.safe_load(f)

    def load_model(self, name: str, version: Optional[int] = None) -> Any:
        version = version or self.get_latest_version(name)
        if version is None:
            raise FileNotFoundError(f"No registered versions of model '{name}'")
        with open(self._model_dir(name) / f"v{version}" / "params.pkl", "rb") as f:
            return pickle.load(f)

    def transition_model(self, name: str, version: int, stage: str) -> None:
        meta_path = self._model_dir(name) / f"v{version}" / "meta.yaml"
        with open(meta_path) as f:
            meta = yaml.safe_load(f)
        meta["stage"] = stage
        with open(meta_path, "w") as f:
            yaml.safe_dump(meta, f)

    def delete_model(self, name: str, version: Optional[int] = None) -> None:
        import shutil

        if version is None:
            shutil.rmtree(self._model_dir(name), ignore_errors=True)
        else:
            shutil.rmtree(self._model_dir(name) / f"v{version}", ignore_errors=True)

    def download_model(self, name: str, version: Optional[int] = None) -> str:
        version = version or self.get_latest_version(name)
        return str(self._model_dir(name) / f"v{version}" / "params.pkl")

    def models(self) -> List[str]:
        return sorted(p.name for p in self.root.iterdir() if p.is_dir())


def _run_best_metric(run_dir: Path, metric: str) -> Optional[float]:
    """Best (max) value of ``metric`` logged by a run, from metrics.csv
    (CSV backend) or TensorBoard event files when the reader is available."""
    best: Optional[float] = None
    csv_path = run_dir / "metrics.csv"
    if csv_path.exists():
        import csv as _csv

        with open(csv_path) as f:
            for row in _csv.DictReader(f):
                if row.get("name") == metric:
                    v = float(row["value"])
                    best = v if best is None else max(best, v)
        return best
    try:
        from tensorboard.backend.event_processing.event_accumulator import EventAccumulator
    except Exception:
        return None
    for events in run_dir.glob("events.out.tfevents.*"):
        acc = EventAccumulator(str(events))
        acc.Reload()
        if metric in acc.Tags().get("scalars", ()):
            vals = [s.value for s in acc.Scalars(metric)]
            if vals:
                m = max(vals)
                best = m if best is None else max(best, m)
    return best


def register_best_models(
    log_dir: str,
    cfg: Any,
    metric: str = "Rewards/rew_avg",
    models_keys: Optional[set] = None,
) -> Dict[str, int]:
    """Scan every run under ``log_dir`` (``**/version_*``), pick the one
    whose logged ``metric`` peaked highest, and register that run's last
    checkpointed sub-models (reference: sheeprl/utils/mlflow.py
    register_best_models — same behavior against the MLflow backend)."""
    from sheeprl_tpu.utils.checkpoint import load_checkpoint

    best_run, best_val = None, None
    for vdir in sorted(Path(log_dir).glob("**/version_*")):
        ckpts = sorted((vdir / "checkpoint").glob("ckpt_*.ckpt"))
        if not ckpts:
            continue
        val = _run_best_metric(vdir, metric)
        if val is None:
            continue
        if best_val is None or val > best_val:
            best_run, best_val = ckpts[-1], val
    if best_run is None:
        return {}
    state = load_checkpoint(best_run)
    versions = register_model_from_checkpoint(None, cfg, state, models_keys=models_keys)
    return versions


def register_model_from_checkpoint(
    fabric: Any, cfg: Any, state: Dict[str, Any], models_keys: Optional[set] = None
) -> Dict[str, int]:
    """Export checkpointed sub-models to the registry
    (reference: sheeprl/utils/mlflow.py register_model_from_checkpoint).
    Backend chosen by ``model_manager.backend`` (filesystem default; mlflow
    when the optional dep is installed — sheeprl_tpu/utils/mlflow_manager.py)."""
    from sheeprl_tpu.utils.mlflow_manager import get_model_manager

    manager = get_model_manager(cfg)
    agent_state = state.get("agent", {})
    models_cfg = cfg.get("model_manager", {}).get("models", {}) or {}
    versions = {}
    keys = models_keys or set(models_cfg) or set(agent_state if isinstance(agent_state, dict) else [])
    for key in keys:
        sub = agent_state.get(key) if isinstance(agent_state, dict) else None
        if sub is None and key == "agent":
            sub = agent_state
        if sub is None:
            continue
        info = models_cfg.get(key, {}) if isinstance(models_cfg.get(key), dict) else {}
        name = info.get("model_name", f"{cfg.algo.name}_{key}")
        versions[key] = manager.register_model(
            name, sub, description=info.get("description", f"{cfg.algo.name} {key}"),
            metadata={"algo": cfg.algo.name, "env": cfg.env.id, "seed": cfg.seed},
        )
    return versions
