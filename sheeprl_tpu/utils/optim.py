"""Optimizer factory (optax).

The reference hydra-instantiates torch optimizers from ``configs/optim/*``
(reference: sheeprl/configs/optim/adam.yaml and sheeprl/optim/rmsprop_tf.py).
Here the same config surface builds an optax chain: global-norm clipping →
the base optimizer, with the learning rate exposed as an injectable
hyperparameter so host-side schedules (polynomial anneal) can update it
without recompilation.

``rmsprop_tf`` reproduces TF-style RMSprop (epsilon inside the sqrt,
square-average state initialized to ones) used by Dreamer V1/V2
(reference: sheeprl/optim/rmsprop_tf.py:14+).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax


def rmsprop_tf(
    learning_rate: Any,
    decay: float = 0.9,
    eps: float = 1e-10,
    momentum: float = 0.0,
    centered: bool = False,
) -> optax.GradientTransformation:
    """TF-flavored RMSprop: ``eps`` added inside the sqrt and ``square_avg``
    initialized to ones (so early steps are not over-scaled)."""

    def init_fn(params):
        nu = jax.tree.map(jnp.ones_like, params)  # square avg, ones-init
        mom = jax.tree.map(jnp.zeros_like, params) if momentum > 0 else None
        mg = jax.tree.map(jnp.zeros_like, params) if centered else None
        return {"nu": nu, "mom": mom, "mg": mg}

    def update_fn(updates, state, params=None):
        nu = jax.tree.map(lambda n, g: decay * n + (1 - decay) * g * g, state["nu"], updates)
        if centered:
            mg = jax.tree.map(lambda m, g: decay * m + (1 - decay) * g, state["mg"], updates)
            denom = jax.tree.map(lambda n, m: jnp.sqrt(n - m * m + eps), nu, mg)
        else:
            mg = None
            denom = jax.tree.map(lambda n: jnp.sqrt(n + eps), nu)
        scaled = jax.tree.map(lambda g, d: g / d, updates, denom)
        if momentum > 0:
            mom = jax.tree.map(lambda b, s: momentum * b + s, state["mom"], scaled)
            out = mom
        else:
            mom = None
            out = scaled
        out = jax.tree.map(lambda u: -u, out)
        return out, {"nu": nu, "mom": mom, "mg": mg}

    base = optax.GradientTransformation(init_fn, update_fn)
    return optax.chain(base, optax.scale_by_learning_rate(learning_rate, flip_sign=False))


#: optax >= 0.2.4 exposes eps placement on optax.rmsprop; 0.2.3 does not.
import inspect as _inspect

_OPTAX_RMSPROP_HAS_EPS_IN_SQRT = (
    "eps_in_sqrt" in _inspect.signature(optax.rmsprop).parameters
)


def rmsprop_torch(
    learning_rate: Any,
    decay: float = 0.99,
    eps: float = 1e-8,
    momentum: float = 0.0,
    centered: bool = False,
) -> optax.GradientTransformation:
    """Torch-flavored RMSprop for optax builds without ``eps_in_sqrt``:
    square-average initialized to ZEROS and ``eps`` added OUTSIDE the sqrt —
    ``g / (sqrt(nu) + eps)`` — matching ``torch.optim.RMSprop`` (and
    ``optax.rmsprop(eps_in_sqrt=False)`` on newer optax)."""

    def init_fn(params):
        nu = jax.tree.map(jnp.zeros_like, params)
        mom = jax.tree.map(jnp.zeros_like, params) if momentum > 0 else None
        mg = jax.tree.map(jnp.zeros_like, params) if centered else None
        return {"nu": nu, "mom": mom, "mg": mg}

    def update_fn(updates, state, params=None):
        nu = jax.tree.map(lambda n, g: decay * n + (1 - decay) * g * g, state["nu"], updates)
        if centered:
            mg = jax.tree.map(lambda m, g: decay * m + (1 - decay) * g, state["mg"], updates)
            denom = jax.tree.map(lambda n, m: jnp.sqrt(n - m * m) + eps, nu, mg)
        else:
            mg = None
            denom = jax.tree.map(lambda n: jnp.sqrt(n) + eps, nu)
        scaled = jax.tree.map(lambda g, d: g / d, updates, denom)
        if momentum > 0:
            mom = jax.tree.map(lambda b, s: momentum * b + s, state["mom"], scaled)
            out = mom
        else:
            mom = None
            out = scaled
        out = jax.tree.map(lambda u: -u, out)
        return out, {"nu": nu, "mom": mom, "mg": mg}

    base = optax.GradientTransformation(init_fn, update_fn)
    return optax.chain(base, optax.scale_by_learning_rate(learning_rate, flip_sign=False))


def build_optimizer(
    optim_cfg: Any,
    max_grad_norm: Optional[float] = None,
) -> optax.GradientTransformation:
    """Build from an ``optim`` config group entry: {name, lr, eps, ...}."""
    name = optim_cfg.get("name", "adam")
    lr = float(optim_cfg.get("lr", 1e-3))
    if name == "adam":
        base = optax.inject_hyperparams(optax.adam)(
            learning_rate=lr,
            b1=float(optim_cfg.get("betas", [0.9, 0.999])[0]),
            b2=float(optim_cfg.get("betas", [0.9, 0.999])[1]),
            eps=float(optim_cfg.get("eps", 1e-8)),
        )
    elif name == "adamw":
        base = optax.inject_hyperparams(optax.adamw)(
            learning_rate=lr,
            eps=float(optim_cfg.get("eps", 1e-8)),
            weight_decay=float(optim_cfg.get("weight_decay", 1e-2)),
        )
    elif name == "sgd":
        base = optax.inject_hyperparams(optax.sgd)(
            learning_rate=lr, momentum=float(optim_cfg.get("momentum", 0.0))
        )
    elif name == "rmsprop":
        momentum = float(optim_cfg.get("momentum", 0.0))
        if _OPTAX_RMSPROP_HAS_EPS_IN_SQRT:
            base = optax.inject_hyperparams(optax.rmsprop)(
                learning_rate=lr,
                decay=float(optim_cfg.get("alpha", 0.99)),
                eps=float(optim_cfg.get("eps", 1e-8)),
                # torch semantics: eps OUTSIDE the sqrt (the TF-style variant
                # is the separate rmsprop_tf above)
                eps_in_sqrt=False,
                momentum=momentum if momentum > 0 else None,
                centered=bool(optim_cfg.get("centered", False)),
            )
        else:
            # optax <= 0.2.3: optax.rmsprop has no eps_in_sqrt knob and its
            # scale_by_rms puts eps INSIDE the sqrt — use the local
            # torch-semantics implementation instead of TypeError-ing
            # momentum is static: it selects the transform's STRUCTURE
            # (whether a momentum buffer exists), so it cannot be traced
            base = optax.inject_hyperparams(rmsprop_torch, static_args=("momentum",))(
                learning_rate=lr,
                decay=float(optim_cfg.get("alpha", 0.99)),
                eps=float(optim_cfg.get("eps", 1e-8)),
                momentum=momentum,
                centered=bool(optim_cfg.get("centered", False)),
            )
    elif name == "rmsprop_tf":
        # momentum static for the same structural reason as rmsprop_torch
        base = optax.inject_hyperparams(rmsprop_tf, static_args=("momentum",))(
            learning_rate=lr,
            decay=float(optim_cfg.get("alpha", 0.9)),
            eps=float(optim_cfg.get("eps", 1e-10)),
            momentum=float(optim_cfg.get("momentum", 0.0)),
            centered=bool(optim_cfg.get("centered", False)),
        )
    else:
        raise ValueError(f"Unknown optimizer '{name}'")
    if max_grad_norm is not None and max_grad_norm > 0:
        return optax.chain(optax.clip_by_global_norm(float(max_grad_norm)), base)
    return base


def set_learning_rate(opt_state: Any, lr: float) -> Any:
    """Update the injected learning rate in-place (returns the same state).

    Handles both a bare ``InjectStatefulHyperparamsState`` (itself a
    NamedTuple, i.e. a tuple — check it FIRST) and arbitrarily nested chains.
    """
    if hasattr(opt_state, "hyperparams") and isinstance(getattr(opt_state, "hyperparams"), dict):
        if "learning_rate" in opt_state.hyperparams:
            opt_state.hyperparams["learning_rate"] = jnp.asarray(lr, dtype=jnp.float32)
            return opt_state
    if isinstance(opt_state, tuple):
        for s in opt_state:
            set_learning_rate(s, lr)
    return opt_state


def get_learning_rate(opt_state: Any) -> Optional[float]:
    """Read back the injected learning rate (for tests / logging)."""
    if hasattr(opt_state, "hyperparams") and isinstance(getattr(opt_state, "hyperparams"), dict):
        if "learning_rate" in opt_state.hyperparams:
            return float(opt_state.hyperparams["learning_rate"])
    if isinstance(opt_state, tuple):
        for s in opt_state:
            lr = get_learning_rate(s)
            if lr is not None:
                return lr
    return None
