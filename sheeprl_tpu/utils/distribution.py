"""Probability distributions (pure JAX, jit-safe, bf16-aware).

Capability parity with the reference distribution suite
(reference: sheeprl/utils/distribution.py:25-416): TruncatedNormal,
SymlogDistribution, MSEDistribution, TwoHotEncodingDistribution,
OneHotCategorical (+ straight-through), BernoulliSafeMode — plus the policy
distributions the algorithms build (Categorical, MultiCategorical, Normal,
tanh-squashed Normal) and a ``kl_divergence`` dispatcher.

Everything here is a thin immutable object over ``jax.Array`` leaves: safe to
construct inside jit, differentiable, no host sync.  Reductions over event
dims follow the torch.distributions ``Independent`` convention via an
``event_dims`` argument instead of a wrapper class.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.utils.utils import symexp, symlog

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


def _sum_event(x: jax.Array, event_dims: int) -> jax.Array:
    if event_dims <= 0:
        return x
    return x.sum(axis=tuple(range(-event_dims, 0)))


# --------------------------------------------------------------------------
# categorical family
# --------------------------------------------------------------------------

class Categorical:
    """Categorical over the last axis of ``logits``."""

    def __init__(self, logits: jax.Array):
        self.logits = logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)

    @property
    def probs(self) -> jax.Array:
        return jnp.exp(self.logits)

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        return jax.random.categorical(key, self.logits, shape=sample_shape + self.logits.shape[:-1])

    def log_prob(self, value: jax.Array) -> jax.Array:
        value = value.astype(jnp.int32)
        return jnp.take_along_axis(self.logits, value[..., None], axis=-1)[..., 0]

    def entropy(self) -> jax.Array:
        return -jnp.sum(self.probs * self.logits, axis=-1)

    def mode(self) -> jax.Array:
        return jnp.argmax(self.logits, axis=-1)


class MultiCategorical:
    """Factorized categorical over multiple discrete action dims
    (reference handles multi-discrete actions per-branch in each agent)."""

    def __init__(self, logits: Sequence[jax.Array]):
        self.dists = [Categorical(l) for l in logits]

    def sample(self, key: jax.Array) -> jax.Array:
        keys = jax.random.split(key, len(self.dists))
        return jnp.stack([d.sample(k) for d, k in zip(self.dists, keys)], axis=-1)

    def log_prob(self, value: jax.Array) -> jax.Array:
        return sum(d.log_prob(value[..., i]) for i, d in enumerate(self.dists))

    def entropy(self) -> jax.Array:
        return sum(d.entropy() for d in self.dists)

    def mode(self) -> jax.Array:
        return jnp.stack([d.mode() for d in self.dists], axis=-1)


class OneHotCategorical:
    """One-hot-valued categorical (reference: distribution.py:281-345)."""

    def __init__(self, logits: jax.Array, unimix: float = 0.0):
        if unimix > 0.0:
            # 1% uniform mixing (DreamerV3 trick,
            # reference: sheeprl/algos/dreamer_v3/agent.py:437-449)
            probs = jax.nn.softmax(logits, axis=-1)
            probs = (1.0 - unimix) * probs + unimix / logits.shape[-1]
            logits = jnp.log(probs)
        self.logits = logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)

    @property
    def probs(self) -> jax.Array:
        return jnp.exp(self.logits)

    @property
    def num_classes(self) -> int:
        return self.logits.shape[-1]

    def sample(self, key: jax.Array) -> jax.Array:
        idx = jax.random.categorical(key, self.logits)
        return jax.nn.one_hot(idx, self.num_classes, dtype=self.logits.dtype)

    def rsample(self, key: jax.Array) -> jax.Array:
        """Straight-through gradient sample
        (reference: OneHotCategoricalStraightThroughValidateArgs,
        distribution.py:348-401)."""
        sample = self.sample(key)
        probs = self.probs
        return sample + probs - jax.lax.stop_gradient(probs)

    # -- noise-hoisted sampling (pipeline sample-invariance law) ----------
    #
    # ``jax.random.categorical(key, logits)`` IS ``argmax(logits + gumbel)``
    # with the gumbel drawn at ``logits.shape``/``logits.dtype`` — the split
    # below is bit-identical to ``sample(key)`` when the noise comes from
    # ``sample_noise(key, logits.shape, logits.dtype)`` (pinned by
    # tests/test_parallel/test_pipeline.py).  Because argmax is rowwise, the
    # noise can be drawn ONCE at full batch shape and row-sliced per
    # microbatch: pipelined stages sample the exact bits the full-batch
    # baseline would, so schedule choices never become numerics changes
    # (sheeprl_tpu/parallel/pipeline.py module docs).

    @staticmethod
    def sample_noise(key: jax.Array, shape, dtype=jnp.float32) -> jax.Array:
        """The sampling noise ``sample(key)`` would consume for logits of
        this shape/dtype — hoistable because it is logits-independent."""
        return jax.random.gumbel(key, shape, dtype)

    def sample_from_noise(self, noise: jax.Array) -> jax.Array:
        """``sample`` with pre-drawn noise (any row-slice thereof)."""
        idx = jnp.argmax(self.logits + noise, axis=-1)
        return jax.nn.one_hot(idx, self.num_classes, dtype=self.logits.dtype)

    def rsample_from_noise(self, noise: jax.Array) -> jax.Array:
        """``rsample`` with pre-drawn noise (any row-slice thereof)."""
        sample = self.sample_from_noise(noise)
        probs = self.probs
        return sample + probs - jax.lax.stop_gradient(probs)

    def log_prob(self, value: jax.Array) -> jax.Array:
        return jnp.sum(value * self.logits, axis=-1)

    def entropy(self) -> jax.Array:
        return -jnp.sum(self.probs * self.logits, axis=-1)

    def mode(self) -> jax.Array:
        return jax.nn.one_hot(jnp.argmax(self.logits, axis=-1), self.num_classes, dtype=self.logits.dtype)


def kl_categorical(p: OneHotCategorical, q: OneHotCategorical) -> jax.Array:
    """KL(p‖q) summed over the categorical axis (registered-KL parity,
    reference: distribution.py:404-406)."""
    return jnp.sum(p.probs * (p.logits - q.logits), axis=-1)


# --------------------------------------------------------------------------
# normal family
# --------------------------------------------------------------------------

class Normal:
    def __init__(self, loc: jax.Array, scale: jax.Array, event_dims: int = 0):
        self.loc = loc
        self.scale = scale
        self.event_dims = event_dims

    def sample(self, key: jax.Array) -> jax.Array:
        return self.loc + self.scale * jax.random.normal(key, self.loc.shape, self.loc.dtype)

    rsample = sample  # reparameterized by construction

    def log_prob(self, value: jax.Array) -> jax.Array:
        z = (value - self.loc) / self.scale
        lp = -0.5 * z**2 - jnp.log(self.scale) - _HALF_LOG_2PI
        return _sum_event(lp, self.event_dims)

    def entropy(self) -> jax.Array:
        ent = 0.5 + _HALF_LOG_2PI + jnp.log(self.scale)
        return _sum_event(ent, self.event_dims)

    def mode(self) -> jax.Array:
        return self.loc

    @property
    def mean(self) -> jax.Array:
        return self.loc


def kl_normal(p: Normal, q: Normal) -> jax.Array:
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    kl = 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))
    return _sum_event(kl, max(p.event_dims, q.event_dims))


class TanhNormal:
    """Tanh-squashed Gaussian with exact log-det correction — the SAC policy
    distribution (reference squashes via torch TanhTransform in
    sheeprl/algos/sac/agent.py)."""

    def __init__(self, loc: jax.Array, scale: jax.Array, event_dims: int = 1):
        self.base = Normal(loc, scale, event_dims=0)
        self.event_dims = event_dims

    def sample_and_log_prob(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        pre = self.base.rsample(key)
        action = jnp.tanh(pre)
        # log|d tanh/dx| = 2*(log2 - x - softplus(-2x)) — numerically stable
        log_det = 2.0 * (math.log(2.0) - pre - jax.nn.softplus(-2.0 * pre))
        lp = self.base.log_prob(pre) - log_det
        return action, _sum_event(lp, self.event_dims)

    def sample(self, key: jax.Array) -> jax.Array:
        return jnp.tanh(self.base.rsample(key))

    def mode(self) -> jax.Array:
        return jnp.tanh(self.base.loc)


class TruncatedNormal:
    """Normal truncated to ``[low, high]`` (reference: distribution.py:25-147,
    used by Dreamer V1/V2 continuous actions with [-1, 1]).

    Sampling uses inverse-CDF over the truncated interval; ``log_prob`` is
    the base log-density minus the log of the interval mass.
    """

    def __init__(
        self,
        loc: jax.Array,
        scale: jax.Array,
        low: float = -1.0,
        high: float = 1.0,
        event_dims: int = 0,
    ):
        self.loc = loc
        self.scale = scale
        self.low = low
        self.high = high
        self.event_dims = event_dims
        self._a = (low - loc) / scale
        self._b = (high - loc) / scale
        cdf = jax.scipy.stats.norm.cdf
        self._cdf_a = cdf(self._a)
        self._z = jnp.clip(cdf(self._b) - self._cdf_a, 1e-8, None)

    def sample(self, key: jax.Array) -> jax.Array:
        u = jax.random.uniform(key, self.loc.shape, self.loc.dtype, 1e-6, 1.0 - 1e-6)
        p = self._cdf_a + u * self._z
        x = self.loc + self.scale * jax.scipy.special.ndtri(jnp.clip(p, 1e-7, 1 - 1e-7))
        return jnp.clip(x, self.low, self.high)

    rsample = sample

    def log_prob(self, value: jax.Array) -> jax.Array:
        z = (value - self.loc) / self.scale
        lp = -0.5 * z**2 - jnp.log(self.scale) - _HALF_LOG_2PI - jnp.log(self._z)
        return _sum_event(lp, self.event_dims)

    def entropy(self) -> jax.Array:
        # differential entropy of the truncated normal (standard identity)
        pdf = jax.scipy.stats.norm.pdf
        a_pdf, b_pdf = pdf(self._a), pdf(self._b)
        frac = (self._a * a_pdf - self._b * b_pdf) / self._z
        ent = 0.5 + _HALF_LOG_2PI + jnp.log(self.scale * self._z) + 0.5 * frac
        return _sum_event(ent, self.event_dims)

    def mode(self) -> jax.Array:
        return jnp.clip(self.loc, self.low, self.high)

    @property
    def mean(self) -> jax.Array:
        pdf = jax.scipy.stats.norm.pdf
        return self.loc + self.scale * (pdf(self._a) - pdf(self._b)) / self._z


# --------------------------------------------------------------------------
# regression-as-distribution heads (Dreamer)
# --------------------------------------------------------------------------

class MSEDistribution:
    """Deterministic prediction scored with -MSE
    (reference: distribution.py:196-221)."""

    def __init__(self, mode: jax.Array, event_dims: int = 0):
        self._mode = mode
        self.event_dims = event_dims

    def log_prob(self, value: jax.Array) -> jax.Array:
        return _sum_event(-((self._mode - value) ** 2), self.event_dims)

    def mode(self) -> jax.Array:
        return self._mode

    @property
    def mean(self) -> jax.Array:
        return self._mode


class SymlogDistribution:
    """MSE in symlog space; mode/mean decode via symexp
    (reference: distribution.py:152-193)."""

    def __init__(self, mode: jax.Array, event_dims: int = 1):
        self._mode = mode
        self.event_dims = event_dims

    def log_prob(self, value: jax.Array) -> jax.Array:
        return _sum_event(-((self._mode - symlog(value)) ** 2), self.event_dims)

    def mode(self) -> jax.Array:
        return symexp(self._mode)

    @property
    def mean(self) -> jax.Array:
        return symexp(self._mode)


class TwoHotEncodingDistribution:
    """Symlog two-hot categorical over exponentially-spaced-free integer bins
    (reference: distribution.py:224-276; DreamerV3 reward/critic heads with
    255 bins over [-20, 20]).

    ``log_prob(x)`` = two-hot(symlog x) · log-softmax(logits); ``mean`` =
    symexp of the expected bin value.
    """

    def __init__(self, logits: jax.Array, dims: int = 1, low: float = -20.0, high: float = 20.0):
        self.logits = logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
        self.event_dims = dims
        self.bins = jnp.linspace(low, high, logits.shape[-1], dtype=jnp.float32)

    @property
    def probs(self) -> jax.Array:
        return jnp.exp(self.logits)

    @property
    def mean(self) -> jax.Array:
        return symexp(jnp.sum(self.probs * self.bins, axis=-1, keepdims=True))

    def mode(self) -> jax.Array:
        return self.mean

    def _two_hot(self, x: jax.Array) -> jax.Array:
        n = self.bins.shape[0]
        x = symlog(x)
        # saturate outside the support: the reference derives `above` from the
        # UNCLAMPED below so out-of-range values degenerate to one bucket
        # (reference: distribution.py:256-266); clipping x is equivalent
        x = jnp.clip(x, self.bins[0], self.bins[-1])
        below = jnp.sum((self.bins <= x).astype(jnp.int32), axis=-1) - 1
        below = jnp.clip(below, 0, n - 1)
        above = jnp.clip(below + 1, 0, n - 1)
        x0 = jnp.squeeze(x, -1)
        # reference's `equal` branch (distribution.py:264-266): at the
        # saturated top bucket below==above and both distances are 0 — force
        # them to 1 so the weights sum to 1 on that bucket, not 0
        equal = below == above
        d_below = jnp.where(equal, 1.0, jnp.abs(self.bins[below] - x0))
        d_above = jnp.where(equal, 1.0, jnp.abs(self.bins[above] - x0))
        total = d_below + d_above
        w_below = d_above / total
        w_above = d_below / total
        return (
            jax.nn.one_hot(below, n, dtype=jnp.float32) * w_below[..., None]
            + jax.nn.one_hot(above, n, dtype=jnp.float32) * w_above[..., None]
        )

    def log_prob(self, value: jax.Array) -> jax.Array:
        # value: (..., 1) → (..., ) after event reduction over the encoded axis
        target = self._two_hot(value)
        lp = jnp.sum(target * self.logits, axis=-1, keepdims=True)
        return _sum_event(lp, self.event_dims)


class Bernoulli:
    """Bernoulli over logits with a non-NaN mode — ``BernoulliSafeMode``
    parity (reference: distribution.py:409-416)."""

    def __init__(self, logits: jax.Array, event_dims: int = 0):
        self.logits = logits
        self.event_dims = event_dims

    @property
    def probs(self) -> jax.Array:
        return jax.nn.sigmoid(self.logits)

    def log_prob(self, value: jax.Array) -> jax.Array:
        lp = -jax.nn.softplus(-self.logits) * value - jax.nn.softplus(self.logits) * (1.0 - value)
        return _sum_event(lp, self.event_dims)

    def sample(self, key: jax.Array) -> jax.Array:
        return (jax.random.uniform(key, self.logits.shape) < self.probs).astype(jnp.float32)

    def mode(self) -> jax.Array:
        return (self.probs > 0.5).astype(jnp.float32)

    @property
    def mean(self) -> jax.Array:
        return self.probs
