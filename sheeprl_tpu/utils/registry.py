"""Algorithm / evaluation registries.

Decorator-driven name -> (module, entrypoint, decoupled) maps, mirroring the
capability of the reference registry (reference: sheeprl/utils/registry.py:11-108):
algorithms self-register at import time, the CLI dispatches by ``cfg.algo.name``,
and the evaluation registry is validated against the algorithm registry.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

# name -> list of entries (a name may expose both coupled and decoupled forms
# under different registered entrypoints, like the reference's ppo/ppo_decoupled)
algorithm_registry: Dict[str, List["AlgorithmEntry"]] = {}
evaluation_registry: Dict[str, List["EvaluationEntry"]] = {}


@dataclass
class AlgorithmEntry:
    name: str
    module: str
    entrypoint: str
    decoupled: bool = False


@dataclass
class EvaluationEntry:
    name: str
    module: str
    entrypoint: str
    algorithms: List[str] = field(default_factory=list)


def register_algorithm(decoupled: bool = False, name: Optional[str] = None) -> Callable:
    """Class-free registration: decorate the algorithm's ``main`` function.

    The registered name defaults to the leaf module name (``...algos.ppo.ppo``
    registers ``ppo``), matching how users select algorithms via
    ``algo=<name>`` / ``cfg.algo.name``.
    """

    def decorator(fn: Callable) -> Callable:
        module = fn.__module__
        algo_name = name or module.rsplit(".", 1)[-1]
        entry = AlgorithmEntry(algo_name, module, fn.__name__, decoupled)
        entries = algorithm_registry.setdefault(algo_name, [])
        if not any(e.module == module and e.entrypoint == entry.entrypoint for e in entries):
            entries.append(entry)
        return fn

    return decorator


def register_evaluation(algorithms, name: Optional[str] = None) -> Callable:
    if isinstance(algorithms, str):
        algorithms = [algorithms]

    def decorator(fn: Callable) -> Callable:
        module = fn.__module__
        eval_name = name or module.rsplit(".", 2)[-2]
        entry = EvaluationEntry(eval_name, module, fn.__name__, list(algorithms))
        for algo in algorithms:
            entries = evaluation_registry.setdefault(algo, [])
            if not any(e.module == module for e in entries):
                entries.append(entry)
        return fn

    return decorator


def resolve_algorithm(name: str, decoupled: Optional[bool] = None) -> AlgorithmEntry:
    entries = algorithm_registry.get(name)
    if not entries:
        available = ", ".join(sorted(algorithm_registry))
        raise ValueError(f"Unknown algorithm '{name}'. Registered: {available}")
    if decoupled is None:
        return entries[0]
    for e in entries:
        if e.decoupled == decoupled:
            return e
    return entries[0]


def resolve_entrypoint(entry: AlgorithmEntry) -> Callable:
    module = sys.modules.get(entry.module)
    if module is None:
        import importlib

        module = importlib.import_module(entry.module)
    return getattr(module, entry.entrypoint)
