"""Named wall-clock timers.

Same role as the reference's ``timer`` ContextDecorator
(reference: sheeprl/utils/timer.py:16-83): train loops wrap the env-interaction
and train phases, and at log time derived steps-per-second throughputs are
computed then timers reset.

JAX note on attribution: dispatch is asynchronous, so by default a phase's
measured time is its HOST time — device compute dispatched in the train
phase that the host never waits for lands in whichever later phase first
blocks (on a single-stream host that is usually the env phase's next
device call).  ``metric.sync_timers=True`` (``timer.sync``) makes every
timed phase drain the device at entry and exit, so phase times are
attributable at the cost of losing host/device overlap — totals stay the
same on a single-stream host, only the split moves.  bench captures turn
it on; leave it off for throughput runs.
"""

from __future__ import annotations

import time
from contextlib import ContextDecorator
from typing import Any, ClassVar, Dict

from sheeprl_tpu.telemetry.spans import SPANS, TIMER_PHASES


class timer(ContextDecorator):
    disabled: ClassVar[bool] = False
    sync: ClassVar[bool] = False
    timers: ClassVar[Dict[str, float]] = {}
    _counts: ClassVar[Dict[str, int]] = {}

    def __init__(self, name: str, mode: str = "sum"):
        self.name = name
        self.mode = mode

    @classmethod
    def configure(cls, metric_cfg: Any) -> None:
        """Apply the ``metric.*`` timing knobs (every train loop calls this)."""
        cls.disabled = bool(
            metric_cfg.disable_timer or metric_cfg.log_level == 0
        )
        cls.sync = bool(metric_cfg.get("sync_timers", False))

    @staticmethod
    def _drain_device() -> None:
        """Block until every in-flight device computation has finished.

        Uses ``utils.device_sync`` (D2H scalar materialization) rather than
        ``block_until_ready``: the latter resolves at dispatch on the axon
        tunnel platform, which would silently void sync-mode attribution
        (BENCH_TPU.md timing-validity note)."""
        try:
            from sheeprl_tpu.utils.utils import device_sync

            device_sync()
        except Exception:
            return  # timing must never take down the run

    def __enter__(self) -> "timer":
        if timer.sync and not timer.disabled:
            timer._drain_device()
        # phase-span bridge (telemetry/spans.py): the two timers every train
        # loop already wraps ARE the rollout / update.dispatch phases — one
        # mapping here wires all 12 loops.  Independent of `disabled`: spans
        # (and the tracer tick stream they drive) stay live at
        # metric.log_level=0, which is how bench runs get phase breakdowns.
        phase = TIMER_PHASES.get(self.name)
        self._span = SPANS.push(phase) if phase is not None else None
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> bool:
        if self._span is not None:
            SPANS.pop(self._span)
        if not timer.disabled:
            if timer.sync:
                timer._drain_device()
            elapsed = time.perf_counter() - self._start
            if self.mode == "sum":
                timer.timers[self.name] = timer.timers.get(self.name, 0.0) + elapsed
            else:  # mean
                timer.timers[self.name] = timer.timers.get(self.name, 0.0) + elapsed
                timer._counts[self.name] = timer._counts.get(self.name, 0) + 1
        return False

    @classmethod
    def to_dict(cls, reset: bool = True) -> Dict[str, float]:
        out = {}
        for k, v in cls.timers.items():
            n = cls._counts.get(k)
            out[k] = v / n if n else v
        if reset:
            cls.timers = {}
            cls._counts = {}
        return out
