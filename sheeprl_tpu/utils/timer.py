"""Named wall-clock timers.

Same role as the reference's ``timer`` ContextDecorator
(reference: sheeprl/utils/timer.py:16-83): train loops wrap the env-interaction
and train phases, and at log time derived steps-per-second throughputs are
computed then timers reset.  JAX note: because dispatch is asynchronous, the
train-phase wrapper calls ``block_until_ready`` on an optional sentinel array
so measured time includes device execution.
"""

from __future__ import annotations

import time
from contextlib import ContextDecorator
from typing import Any, ClassVar, Dict


class timer(ContextDecorator):
    disabled: ClassVar[bool] = False
    timers: ClassVar[Dict[str, float]] = {}
    _counts: ClassVar[Dict[str, int]] = {}

    def __init__(self, name: str, mode: str = "sum"):
        self.name = name
        self.mode = mode

    def __enter__(self) -> "timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> bool:
        if not timer.disabled:
            elapsed = time.perf_counter() - self._start
            if self.mode == "sum":
                timer.timers[self.name] = timer.timers.get(self.name, 0.0) + elapsed
            else:  # mean
                timer.timers[self.name] = timer.timers.get(self.name, 0.0) + elapsed
                timer._counts[self.name] = timer._counts.get(self.name, 0) + 1
        return False

    @classmethod
    def to_dict(cls, reset: bool = True) -> Dict[str, float]:
        out = {}
        for k, v in cls.timers.items():
            n = cls._counts.get(k)
            out[k] = v / n if n else v
        if reset:
            cls.timers = {}
            cls._counts = {}
        return out
