"""Attribute-access config containers.

The reference resolves every Hydra config to a plain ``dotdict`` before any
algorithm code runs (reference: sheeprl/utils/utils.py:34-60), so that train
loops are config-framework-free.  We keep the same boundary: the compose
engine (sheeprl_tpu/config/compose.py) produces a ``dotdict`` tree and nothing
below the CLI ever sees YAML machinery.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping


class dotdict(dict):
    """A dict with attribute access, recursively converting nested mappings.

    Lists of mappings are converted element-wise.  Unknown attribute reads
    raise ``AttributeError`` (not ``KeyError``) so ``getattr(cfg, "x", None)``
    works as expected.
    """

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__()
        src: Dict[str, Any] = dict(*args, **kwargs)
        for k, v in src.items():
            self[k] = v

    @staticmethod
    def _wrap(value: Any) -> Any:
        if isinstance(value, dotdict):
            return value
        if isinstance(value, Mapping):
            return dotdict(value)
        if isinstance(value, (list, tuple)):
            wrapped = [dotdict._wrap(v) for v in value]
            return type(value)(wrapped) if isinstance(value, tuple) else wrapped
        return value

    def __setitem__(self, key: str, value: Any) -> None:
        super().__setitem__(key, dotdict._wrap(value))

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = value

    def __delattr__(self, name: str) -> None:
        try:
            del self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def setdefault(self, key: str, default: Any = None) -> Any:
        if key not in self:
            self[key] = default
        return self[key]

    def as_dict(self) -> Dict[str, Any]:
        """Deep-convert back to plain builtins (for YAML/pickle dumps)."""

        def unwrap(v: Any) -> Any:
            if isinstance(v, dict):
                return {k: unwrap(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [unwrap(x) for x in v]
            return v

        return unwrap(self)

    def copy(self) -> "dotdict":
        return dotdict(self.as_dict())


def get_by_path(tree: Mapping[str, Any], path: str) -> Any:
    """Fetch ``tree[a][b][c]`` for ``path == "a.b.c"``."""
    node: Any = tree
    for part in path.split("."):
        if not isinstance(node, Mapping) or part not in node:
            raise KeyError(path)
        node = node[part]
    return node


def set_by_path(tree: Dict[str, Any], path: str, value: Any) -> None:
    """Set ``tree[a][b][c] = value`` for ``path == "a.b.c"``, creating nodes."""
    parts = path.split(".")
    node: Dict[str, Any] = tree
    for part in parts[:-1]:
        nxt = node.get(part)
        if not isinstance(nxt, dict):
            nxt = dotdict() if isinstance(node, dotdict) else {}
            node[part] = nxt
        node = node[part]
    node[parts[-1]] = value


def deep_merge(base: Dict[str, Any], overlay: Mapping[str, Any]) -> Dict[str, Any]:
    """Recursively merge ``overlay`` into ``base`` (mutates and returns base).

    Dicts merge key-wise; everything else (including lists) is replaced.
    """
    for k, v in overlay.items():
        if isinstance(v, Mapping) and isinstance(base.get(k), dict):
            deep_merge(base[k], v)
        else:
            base[k] = v
    return base
