"""MLflow-backed model registry (reference: sheeprl/utils/mlflow.py:76-427).

Same ``AbstractModelManager`` lifecycle as the filesystem backend, executed
against an MLflow tracking server (or local ``file:`` store): model params
are logged as a pickled-pytree artifact under a run, registered as model
versions, and every lifecycle event (register / transition / delete) appends
to a markdown MODEL CHANGELOG on both the registered model and the version —
the same audit-trail behavior the reference maintains.

TPU-side difference from the reference: artifacts are JAX pytrees (pickled
host arrays), not torch ``state_dict``s — ``load_model`` returns the pytree
ready for ``jax.device_put``.
"""

from __future__ import annotations

import getpass
import os
import pickle
import tempfile
import time
import warnings
from typing import Any, Dict, Optional

from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE
from sheeprl_tpu.utils.model_manager import AbstractModelManager

VERSION_MD_TEMPLATE = "## **Version {}**\n"
DESCRIPTION_MD_TEMPLATE = "### Description: \n{}\n"

# Tested optional-dependency range (ADVICE r3): the stage-transition API this
# backend drives was written against the mlflow 2.x client; mlflow >= 2.9
# deprecates `transition_model_version_stage` in favor of registered-model
# aliases (removed in 3.x), for which `transition_model` carries a fallback.
MLFLOW_TESTED_RANGE = ">=2.0,<2.9"


def _mlflow_major_minor() -> tuple:
    import mlflow

    try:
        return tuple(int(p) for p in mlflow.__version__.split(".")[:2])
    except (ValueError, AttributeError):  # dev builds etc.
        return (0, 0)

_PARAMS_ARTIFACT = "params.pkl"


def _require_mlflow():
    if not _IS_MLFLOW_AVAILABLE:
        raise ModuleNotFoundError(
            "mlflow is not installed; use FileSystemModelManager or install mlflow "
            "(model_manager.backend=mlflow requires the optional dependency)"
        )
    import mlflow  # noqa: F401  (deferred so the module imports without the dep)

    return mlflow


class MlflowModelManager(AbstractModelManager):
    """Registry backend against an MLflow tracking server
    (reference: sheeprl/utils/mlflow.py:76-427 — MlflowModelManager)."""

    def __init__(self, tracking_uri: Optional[str] = None, experiment_name: str = "sheeprl_tpu"):
        mlflow = _require_mlflow()
        from mlflow.tracking import MlflowClient

        if _mlflow_major_minor() >= (2, 9):
            warnings.warn(
                f"mlflow {mlflow.__version__} is outside the tested range "
                f"{MLFLOW_TESTED_RANGE}: stage transitions fall back to "
                "registered-model aliases (stages were deprecated in 2.9)"
            )
        self.tracking_uri = tracking_uri or os.environ.get("MLFLOW_TRACKING_URI", "file:./mlruns")
        mlflow.set_tracking_uri(self.tracking_uri)
        self.experiment_name = experiment_name
        self.client = MlflowClient()

    # -- helpers ---------------------------------------------------------

    @staticmethod
    def _get_author_and_date() -> str:
        try:
            user = getpass.getuser()
        except Exception:
            user = "unknown"
        return f"### Author: {user}, Date: {time.strftime('%d/%m/%Y %H:%M:%S')}\n"

    @staticmethod
    def _generate_description(description: Optional[str] = None) -> str:
        return "" if description is None else DESCRIPTION_MD_TEMPLATE.format(description)

    def _safe_get_stage(self, name: str, version: int) -> Optional[str]:
        try:
            mv = self.client.get_model_version(name, str(version))
        except Exception:
            warnings.warn(f"Model {name} version {version} not found")
            return None
        stage = getattr(mv, "current_stage", None)
        if stage in (None, "None"):
            # alias-mode fallback (mlflow >= 2.9): transition_model records
            # the stage in a version tag instead — read it back so the
            # idempotency guard and changelog see the real previous stage
            tag = (getattr(mv, "tags", None) or {}).get("stage")
            if tag:
                return tag
            # the version EXISTS but has no stage anywhere (mlflow 3.x
            # removed the stage API): return the stage-less sentinel, not
            # None — None means version-not-found and would make the
            # caller's guard silently skip the first-ever transition
            return "None"
        return stage

    def _append_changelog(self, name: str, version: str, entry: str, version_entry: Optional[str] = None) -> None:
        """Append ``entry`` to the registered model's changelog and
        ``version_entry`` (default: same) to the version's own changelog."""
        model_desc = self.client.get_registered_model(name).description or ""
        header = "# MODEL CHANGELOG\n" if not model_desc else ""
        self.client.update_registered_model(name, header + model_desc + entry)
        if version is not None:
            ver_desc = self.client.get_model_version(name, version).description or ""
            ver_header = "# MODEL CHANGELOG\n" if not ver_desc else ""
            self.client.update_model_version(name, version, ver_header + ver_desc + (version_entry or entry))

    # -- lifecycle -------------------------------------------------------

    def register_model(
        self, name: str, params: Any, description: str = "", metadata: Optional[Dict] = None
    ) -> int:
        """Pickle the params pytree, log it under a run, register the run
        artifact as a new model version, and append a changelog entry
        (reference: mlflow.py:88-123)."""
        mlflow = _require_mlflow()
        import jax

        mlflow.set_experiment(self.experiment_name)
        with mlflow.start_run(run_name=f"register-{name}") as run:
            with tempfile.TemporaryDirectory() as tmp:
                path = os.path.join(tmp, _PARAMS_ARTIFACT)
                with open(path, "wb") as f:
                    pickle.dump(jax.device_get(params), f, protocol=pickle.HIGHEST_PROTOCOL)
                mlflow.log_artifact(path)
            model_uri = f"runs:/{run.info.run_id}/{_PARAMS_ARTIFACT}"
        model_version = mlflow.register_model(model_uri=model_uri, name=name, tags=metadata)
        entry = (
            VERSION_MD_TEMPLATE.format(model_version.version)
            + self._get_author_and_date()
            + self._generate_description(description or None)
        )
        self._append_changelog(name, model_version.version, entry)
        return int(model_version.version)

    def register_model_from_uri(
        self, model_location: str, name: str, description: str = "", metadata: Optional[Dict] = None
    ) -> int:
        """Register an artifact that already lives in the tracking store
        (reference signature: register_model(model_location, ...))."""
        mlflow = _require_mlflow()
        model_version = mlflow.register_model(model_uri=model_location, name=name, tags=metadata)
        entry = (
            VERSION_MD_TEMPLATE.format(model_version.version)
            + self._get_author_and_date()
            + self._generate_description(description or None)
        )
        self._append_changelog(name, model_version.version, entry)
        return int(model_version.version)

    def get_latest_version(self, name: str) -> Optional[int]:
        try:
            versions = self.client.search_model_versions(f"name='{name}'")
        except Exception:
            return None
        if not versions:
            return None
        return max(int(v.version) for v in versions)

    def load_model(self, name: str, version: Optional[int] = None) -> Any:
        path = self.download_model(name, version)
        with open(path, "rb") as f:
            return pickle.load(f)

    def transition_model(self, name: str, version: int, stage: str, description: Optional[str] = None) -> None:
        """Stage transition + changelog (reference: mlflow.py:139-177)."""
        previous_stage = self._safe_get_stage(name, version)
        if previous_stage is None:
            return
        if previous_stage.lower() == stage.lower():
            warnings.warn(f"Model {name} version {version} is already in stage {stage}")
            return
        if hasattr(self.client, "transition_model_version_stage") and _mlflow_major_minor() < (2, 9):
            model_version = self.client.transition_model_version_stage(
                name=name, version=str(version), stage=stage
            )
            new_stage = model_version.current_stage
        else:
            # mlflow >= 2.9: stages are deprecated (removed in 3.x) in favor
            # of registered-model aliases — the alias IS the stage label.
            # A version LEAVES its previous stage on transition (stage-API
            # semantics): drop the old alias if it still points at us.
            if previous_stage and previous_stage.lower() != "none":
                try:
                    held = self.client.get_model_version_by_alias(name, previous_stage.lower())
                    if str(held.version) == str(version):
                        self.client.delete_registered_model_alias(name, previous_stage.lower())
                except Exception:
                    pass  # no such alias
            self.client.set_registered_model_alias(name, stage.lower(), str(version))
            self.client.set_model_version_tag(name, str(version), "stage", stage)
            new_stage = stage
        entry = (
            "## **Transition:**\n"
            + f"### Version {version} from {previous_stage} to {new_stage}\n"
            + self._get_author_and_date()
            + self._generate_description(description)
        )
        self._append_changelog(name, str(version), entry)

    def delete_model(self, name: str, version: Optional[int] = None, description: Optional[str] = None) -> None:
        """Delete one version (changelog on the registered model survives) or,
        with ``version=None``, the whole registered model
        (reference: mlflow.py:179-214; the interactive confirm prompt is
        dropped — this framework's deletion is driven by config/CLI, not a
        TTY)."""
        if version is None:
            try:
                self.client.delete_registered_model(name)
            except Exception:
                warnings.warn(f"Model {name} not found")
            return
        stage = self._safe_get_stage(name, version)
        if stage is None:
            return
        self.client.delete_model_version(name, str(version))
        entry = (
            "## **Deletion:**\n"
            + f"### Version {version} from stage: {stage}\n"
            + self._get_author_and_date()
            + self._generate_description(description)
        )
        # version is gone — changelog only on the registered model
        model_desc = self.client.get_registered_model(name).description or ""
        self.client.update_registered_model(name, model_desc + entry)

    def download_model(self, name: str, version: Optional[int] = None, output_path: Optional[str] = None) -> str:
        """Fetch a version's artifact; returns the local file path
        (reference: mlflow.py:282-297)."""
        mlflow = _require_mlflow()
        version = version or self.get_latest_version(name)
        if version is None:
            raise FileNotFoundError(f"No registered versions of model '{name}'")
        artifact_uri = self.client.get_model_version_download_uri(name, str(version))
        output_path = output_path or os.path.join(tempfile.gettempdir(), f"sheeprl_tpu_{name}_v{version}")
        os.makedirs(output_path, exist_ok=True)
        local = mlflow.artifacts.download_artifacts(artifact_uri=artifact_uri, dst_path=output_path)
        if os.path.isdir(local):
            local = os.path.join(local, _PARAMS_ARTIFACT)
        return local

    def register_best_models(
        self,
        experiment_name: str,
        models_info: Dict[str, Dict[str, Any]],
        metric: str = "Test/cumulative_reward",
        mode: str = "max",
    ) -> Dict[str, int]:
        """Pick the experiment run with the best ``metric`` and register its
        model artifacts (reference: mlflow.py:216-280)."""
        if mode not in ("max", "min"):
            raise ValueError(f"Mode must be either 'max' or 'min', got {mode}")
        experiment = self.client.get_experiment_by_name(experiment_name)
        if experiment is None:
            return {}
        runs = self.client.search_runs(experiment_ids=[experiment.experiment_id])
        models_path = [v["path"] for v in models_info.values()]
        best_run, best_artifacts = None, None
        for run in runs:
            artifacts = [a.path for a in self.client.list_artifacts(run.info.run_id) if a.path in models_path]
            if not artifacts or run.data.metrics.get(metric) is None:
                continue
            if best_run is None or (
                run.data.metrics[metric] > best_run.data.metrics[metric]
                if mode == "max"
                else run.data.metrics[metric] < best_run.data.metrics[metric]
            ):
                best_run, best_artifacts = run, set(artifacts)
        if best_run is None:
            return {}
        versions = {}
        for key, info in models_info.items():
            if info["path"] in best_artifacts:
                versions[key] = self.register_model_from_uri(
                    f"runs:/{best_run.info.run_id}/{info['path']}",
                    info["name"],
                    description=info.get("description", ""),
                    metadata=info.get("tags"),
                )
        return versions


def get_model_manager(cfg: Any) -> AbstractModelManager:
    """Backend dispatch from config: ``model_manager.backend={filesystem,mlflow}``."""
    from sheeprl_tpu.utils.model_manager import FileSystemModelManager

    mm_cfg = cfg.get("model_manager", {}) or {}
    backend = mm_cfg.get("backend", "filesystem")
    if backend == "mlflow":
        return MlflowModelManager(
            tracking_uri=mm_cfg.get("tracking_uri"),
            experiment_name=mm_cfg.get("experiment_name", cfg.get("exp_name", "sheeprl_tpu")),
        )
    return FileSystemModelManager(mm_cfg.get("registry_root", "models_registry"))
