"""Run loggers and versioned log directories.

Parity with the reference logger factory (reference: sheeprl/utils/logger.py:12-89):
rank-0 (process 0) creates ``<log_dir>/<root_dir>/<run_name>/version_k`` and, in
multi-host runs, broadcasts the chosen directory to other hosts so every
process logs/checkpoints consistently.  Backends: TensorBoard (tensorboardX)
or CSV (always-available fallback).
"""

from __future__ import annotations

import csv
import os
from typing import Any, Dict, Optional


class CSVLogger:
    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._path = os.path.join(log_dir, "metrics.csv")
        self._fieldnames = ["step", "name", "value"]
        if not os.path.exists(self._path):
            with open(self._path, "w", newline="") as f:
                csv.writer(f).writerow(self._fieldnames)

    def log_metrics(self, metrics: Dict[str, float], step: int) -> None:
        with open(self._path, "a", newline="") as f:
            w = csv.writer(f)
            for k, v in metrics.items():
                w.writerow([step, k, v])

    def log_hyperparams(self, params: Dict[str, Any]) -> None:
        import yaml

        with open(os.path.join(self.log_dir, "hparams.yaml"), "w") as f:
            yaml.safe_dump(params, f)

    def close(self) -> None:
        pass


class TensorBoardLogger:
    def __init__(self, log_dir: str):
        from tensorboardX import SummaryWriter

        self.log_dir = log_dir
        self.writer = SummaryWriter(log_dir)

    def log_metrics(self, metrics: Dict[str, float], step: int) -> None:
        for k, v in metrics.items():
            self.writer.add_scalar(k, v, step)

    def log_hyperparams(self, params: Dict[str, Any]) -> None:
        import yaml

        self.writer.add_text("hparams", "```\n" + yaml.safe_dump(params) + "\n```", 0)

    def log_video(self, tag: str, frames: Any, step: int, fps: int = 30) -> None:
        # frames: (T, H, W, C) uint8 → tensorboardX wants (N, T, C, H, W)
        import numpy as np

        vid = np.transpose(np.asarray(frames), (0, 3, 1, 2))[None]
        self.writer.add_video(tag, vid, step, fps=fps)

    def close(self) -> None:
        self.writer.close()


class MLflowLogger:
    """Thin adapter over the optional ``mlflow`` package (reference:
    sheeprl/configs/logger/mlflow.yaml + lightning MLFlowLogger). Requires
    ``mlflow`` to be installed and ``MLFLOW_TRACKING_URI`` (or the
    ``tracking_uri`` config key) to point at a tracking server."""

    def __init__(self, log_dir: str, experiment_name: str = "default",
                 tracking_uri: Optional[str] = None, run_name: Optional[str] = None):
        try:
            import mlflow
        except ImportError as e:  # pragma: no cover - mlflow absent from image
            raise ImportError(
                "metric.logger=mlflow requires the optional `mlflow` package; "
                "install it or use the tensorboard/csv loggers"
            ) from e
        self.log_dir = log_dir
        self._mlflow = mlflow
        if tracking_uri:
            mlflow.set_tracking_uri(tracking_uri)
        mlflow.set_experiment(experiment_name)
        self._run = mlflow.start_run(run_name=run_name)

    def log_metrics(self, metrics: Dict[str, float], step: int) -> None:
        self._mlflow.log_metrics({k.replace("/", "_"): float(v) for k, v in metrics.items()}, step=step)

    def log_hyperparams(self, params: Dict[str, Any]) -> None:
        flat = {}

        def walk(node, prefix=""):
            if isinstance(node, dict):
                for k, v in node.items():
                    walk(v, f"{prefix}{k}.")
            else:
                flat[prefix[:-1]] = node

        walk(params)
        # mlflow caps param batches; log defensively
        for k, v in flat.items():
            try:
                self._mlflow.log_param(k, v)
            except Exception:
                pass

    def close(self) -> None:
        self._mlflow.end_run()


def get_log_dir(fabric: Any, root_dir: str, run_name: str, base: str = "logs/runs") -> str:
    """Create (on process 0) and agree on a versioned run directory."""
    root = os.path.join(base, root_dir, run_name)
    if fabric is None or fabric.global_rank == 0:
        version = 0
        while os.path.isdir(os.path.join(root, f"version_{version}")):
            version += 1
        log_dir = os.path.join(root, f"version_{version}")
        os.makedirs(log_dir, exist_ok=True)
    else:
        log_dir = None
    if fabric is not None and (fabric.world_size > 1 or fabric.num_processes > 1):
        # num_processes matters independently of world_size: a pod of
        # single-device cells still needs every process to agree on rank
        # 0's version_N pick
        log_dir = fabric.broadcast_object(log_dir, src=0)
    return log_dir


def get_logger(fabric: Any, cfg: Any, log_dir: str) -> Optional[Any]:
    """Instantiate the configured logger on process 0 only.

    Also the central telemetry arm-point: every training loop (all 12
    algos, the Sebulba drivers, evaluation) constructs its logger here, so
    ``telemetry.setup_run`` — spans, trace windows, the flight recorder's
    run directory, the introspection endpoint — needs no per-loop wiring.
    The created logger is attached to the hub so the ``finally`` path of
    ``cli.run`` can land the last metric window after a crash."""
    from sheeprl_tpu import telemetry

    telemetry.setup_run(
        cfg, log_dir, rank=fabric.global_rank if fabric is not None else 0
    )
    if fabric is not None and fabric.global_rank != 0:
        return None
    if getattr(cfg.metric, "log_level", 1) <= 0:
        return None
    kind = cfg.metric.logger.kind if "logger" in cfg.metric else "tensorboard"
    if kind == "tensorboard":
        try:
            logger = TensorBoardLogger(log_dir)
        except Exception:
            logger = CSVLogger(log_dir)
    elif kind == "csv":
        logger = CSVLogger(log_dir)
    elif kind == "mlflow":
        lcfg = cfg.metric.logger
        logger = MLflowLogger(
            log_dir,
            experiment_name=lcfg.get("experiment_name") or cfg.get("exp_name", "default"),
            tracking_uri=lcfg.get("tracking_uri"),
            run_name=lcfg.get("run_name"),
        )
    else:
        raise ValueError(f"Unknown logger kind: {kind}")
    telemetry.HUB.attach_logger(logger)
    return logger
