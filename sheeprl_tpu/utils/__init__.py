"""sheeprl_tpu.utils."""
