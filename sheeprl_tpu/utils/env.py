"""Environment factory.

Parity with the reference factory (reference: sheeprl/utils/env.py:26-231):
``make_env(cfg, seed, rank, ...)`` returns a thunk producing a fully-wrapped
``gym.Env`` whose observation space is ALWAYS a ``gym.spaces.Dict``, with the
wrapper pipeline: suite wrapper → ActionRepeat → velocity masking →
image normalization (resize / grayscale) → FrameStack → actions-as-obs →
reward-as-obs → reward clipping → TimeLimit → RecordEpisodeStatistics →
RecordVideo (rank 0, env 0 only).

TPU-first convention: images are channel-last ``(H, W, C)`` uint8 (XLA TPU
convolutions are natively NHWC); the reference uses torch's ``(C, H, W)``.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import gymnasium as gym
import numpy as np
from gymnasium import spaces

from sheeprl_tpu.envs.dummy import (
    ContinuousDummyEnv,
    DiscreteDummyEnv,
    MultiDiscreteDummyEnv,
    PixelGridDummyEnv,
)
from sheeprl_tpu.envs.wrappers import (
    ActionRepeat,
    ActionsAsObservationWrapper,
    FrameStack,
    MaskVelocityWrapper,
    RewardAsObservationWrapper,
)

DUMMY_ENVS = {
    "discrete_dummy": DiscreteDummyEnv,
    "multidiscrete_dummy": MultiDiscreteDummyEnv,
    "continuous_dummy": ContinuousDummyEnv,
    "pixel_grid_dummy": PixelGridDummyEnv,
}


def get_dummy_env(env_id: str, **kwargs: Any) -> gym.Env:
    if env_id not in DUMMY_ENVS:
        raise ValueError(f"Unknown dummy env '{env_id}'; options: {list(DUMMY_ENVS)}")
    return DUMMY_ENVS[env_id](**kwargs)


def _wrapper_config(cfg: Any) -> Dict[str, Any]:
    """Normalize ``cfg.env.wrapper`` (dict, bare suite name, or the "???"
    placeholder) into a dict with a ``kind`` entry."""
    wrapper_cfg = cfg.env.get("wrapper") or {}
    if not isinstance(wrapper_cfg, dict):  # "???" placeholder or suite name
        wrapper_cfg = {"kind": str(wrapper_cfg)} if wrapper_cfg != "???" else {}
    return {"kind": "gym", **wrapper_cfg}


def _make_base_env(
    cfg: Any, seed: Optional[int], render_mode: str, rank: int = 0, vector_env_idx: int = 0
) -> gym.Env:
    env_id = cfg.env.id
    if env_id in DUMMY_ENVS:
        # wrapper kwargs pass through to the dummy constructors like every
        # other suite (episode_len, random_start, grid, ...)
        dummy_cfg = _wrapper_config(cfg)
        return get_dummy_env(
            env_id, **{k: v for k, v in dummy_cfg.items() if k not in ("kind", "id")}
        )
    wrapper_cfg = _wrapper_config(cfg)
    kind = wrapper_cfg["kind"]
    if kind == "gym":
        kwargs = {k: v for k, v in wrapper_cfg.items() if k not in ("kind", "id")}
        return gym.make(env_id, render_mode=render_mode, **kwargs)
    if kind == "atari":
        from sheeprl_tpu.envs.atari import make_atari_env

        return make_atari_env(env_id, cfg, render_mode=render_mode)
    if kind == "dmc":
        from sheeprl_tpu.envs.dmc import DMCWrapper

        kwargs = {k: v for k, v in wrapper_cfg.items() if k not in ("kind", "id")}
        return DMCWrapper(env_id, seed=seed, **kwargs)
    if kind == "crafter":
        from sheeprl_tpu.envs.crafter import CrafterWrapper

        kwargs = {k: v for k, v in wrapper_cfg.items() if k not in ("kind", "id")}
        return CrafterWrapper(env_id, **kwargs)
    if kind == "minedojo":
        from sheeprl_tpu.envs.minedojo import MineDojoWrapper

        kwargs = {k: v for k, v in wrapper_cfg.items() if k not in ("kind", "id")}
        return MineDojoWrapper(env_id, seed=seed, **kwargs)
    if kind == "minerl":
        from sheeprl_tpu.envs.minerl import MineRLWrapper

        kwargs = {k: v for k, v in wrapper_cfg.items() if k not in ("kind", "id")}
        return MineRLWrapper(env_id, seed=seed, **kwargs)
    if kind == "diambra":
        from sheeprl_tpu.envs.diambra import DiambraWrapper

        kwargs = {k: v for k, v in wrapper_cfg.items() if k not in ("kind", "id")}
        # each parallel env needs its own engine slot (reference:
        # sheeprl/utils/env.py:72 uses rank * num_envs + vector_env_idx)
        kwargs.setdefault("rank", rank * int(cfg.env.num_envs) + vector_env_idx)
        return DiambraWrapper(env_id, render_mode=render_mode, **kwargs)
    if kind == "super_mario_bros":
        from sheeprl_tpu.envs.super_mario_bros import SuperMarioBrosWrapper

        kwargs = {k: v for k, v in wrapper_cfg.items() if k not in ("kind", "id")}
        return SuperMarioBrosWrapper(env_id, render_mode=render_mode, **kwargs)
    if kind == "jax":
        # pure-JAX env behind the gymnasium API: every existing loop runs
        # it unmodified; on-policy loops may bypass this path entirely and
        # fuse the rollout on device (envs/jax/anakin.py)
        from sheeprl_tpu.envs.jax.adapter import JaxToGymAdapter
        from sheeprl_tpu.envs.jax.registry import make_jax_env

        kwargs = {k: v for k, v in wrapper_cfg.items() if k not in ("kind", "id")}
        # difficulty axis: the top-level env.level override reaches the
        # adapter path too (same contract as registry.jax_env_from_cfg)
        if cfg.env.get("level") is not None:
            kwargs.setdefault("level", float(cfg.env.level))
        return JaxToGymAdapter(make_jax_env(wrapper_cfg.get("id") or env_id, **kwargs))
    raise ValueError(f"Unknown env wrapper kind '{kind}'")


class _DictObs(gym.ObservationWrapper):
    """Normalize any observation space into a Dict: vectors → 'state',
    images → 'rgb' (reference behavior: sheeprl/utils/env.py:117-159)."""

    def __init__(self, env: gym.Env):
        super().__init__(env)
        obs_space = env.observation_space
        if isinstance(obs_space, spaces.Dict):
            self._key_map = None
            self.observation_space = obs_space
        else:
            key = "rgb" if len(obs_space.shape or ()) == 3 else "state"
            self._key_map = key
            self.observation_space = spaces.Dict({key: obs_space})

    def observation(self, observation: Any) -> Dict[str, Any]:
        if self._key_map is None:
            return observation
        return {self._key_map: observation}


class _ImageTransform(gym.ObservationWrapper):
    """Resize / grayscale every cnn key to ``(screen, screen, C)`` uint8
    (reference: sheeprl/utils/env.py:161-196, rewritten channel-last)."""

    def __init__(self, env: gym.Env, cnn_keys: list, screen_size: int, grayscale: bool):
        super().__init__(env)
        import cv2  # local import: heavy

        self._cv2 = cv2
        self._cnn_keys = cnn_keys
        self._screen = screen_size
        self._gray = grayscale
        new_spaces = dict(env.observation_space.spaces)
        channels = 1 if grayscale else 3
        for k in cnn_keys:
            new_spaces[k] = spaces.Box(0, 255, (screen_size, screen_size, channels), np.uint8)
        self.observation_space = spaces.Dict(new_spaces)

    def _transform(self, img: np.ndarray) -> np.ndarray:
        cv2 = self._cv2
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[..., None]
        if img.shape[0] in (1, 3) and img.shape[-1] not in (1, 3):
            img = np.transpose(img, (1, 2, 0))  # CHW → HWC
        if img.shape[:2] != (self._screen, self._screen):
            img = cv2.resize(img, (self._screen, self._screen), interpolation=cv2.INTER_AREA)
            if img.ndim == 2:
                img = img[..., None]
        if self._gray and img.shape[-1] == 3:
            img = cv2.cvtColor(img, cv2.COLOR_RGB2GRAY)[..., None]
        elif not self._gray and img.shape[-1] == 1:
            img = np.repeat(img, 3, axis=-1)
        return img.astype(np.uint8)

    def observation(self, observation: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(observation)
        for k in self._cnn_keys:
            out[k] = self._transform(observation[k])
        return out


def make_env(
    cfg: Any,
    seed: Optional[int],
    rank: int = 0,
    run_name: Optional[str] = None,
    prefix: str = "",
    vector_env_idx: int = 0,
) -> Callable[[], gym.Env]:
    """Build a thunk creating one fully-wrapped environment instance."""

    def thunk() -> gym.Env:
        if cfg.env.get("restart_on_exception", False):
            # auto-recreate the WHOLE wrapped pipeline on env crashes
            # (reference wraps every DreamerV3 thunk, dreamer_v3.py:385-400)
            from sheeprl_tpu.envs.wrappers import RestartOnException

            return RestartOnException(_build)
        return _build()

    def _build() -> gym.Env:
        capture = bool(cfg.env.capture_video) and rank == 0 and vector_env_idx == 0 and run_name is not None
        render_mode = "rgb_array" if capture else cfg.env.get("render_mode", "rgb_array")
        env = _make_base_env(cfg, seed, render_mode, rank=rank, vector_env_idx=vector_env_idx)

        # Suites that repeat actions inside their own engine (atari via
        # frame_skip, DIAMBRA via WrappersSettings.repeat_action) must not be
        # wrapped again or frames/rewards would be consumed twice
        # (reference: sheeprl/utils/env.py:76-81 excludes both).
        if cfg.env.action_repeat > 1 and _wrapper_config(cfg)["kind"] not in ("atari", "diambra"):
            env = ActionRepeat(env, cfg.env.action_repeat)
        if cfg.env.get("mask_velocities", False):
            env = MaskVelocityWrapper(env)

        env = _DictObs(env)

        cnn_keys = [
            k
            for k in env.observation_space.spaces
            if len(env.observation_space[k].shape) in (2, 3)
        ]
        if cnn_keys:
            env = _ImageTransform(env, cnn_keys, cfg.env.screen_size, cfg.env.grayscale)
        if cfg.env.frame_stack > 1 and cnn_keys:
            env = FrameStack(env, cfg.env.frame_stack, cnn_keys, cfg.env.frame_stack_dilation)

        aao = cfg.env.get("actions_as_observation", {})
        if aao and aao.get("num_stack", -1) > 0:
            env = ActionsAsObservationWrapper(env, aao["num_stack"], aao["noop"], aao.get("dilation", 1))
        if cfg.env.reward_as_observation:
            env = RewardAsObservationWrapper(env)
        if cfg.env.clip_rewards:
            env = gym.wrappers.TransformReward(env, lambda r: float(np.tanh(r)))
        if cfg.env.max_episode_steps is not None and cfg.env.max_episode_steps > 0:
            env = gym.wrappers.TimeLimit(env, cfg.env.max_episode_steps)
        env = gym.wrappers.RecordEpisodeStatistics(env)
        if capture:
            import os

            video_dir = os.path.join(run_name, prefix + "_videos" if prefix else "videos")
            env = gym.wrappers.RecordVideo(env, video_dir, disable_logger=True)

        if seed is not None:
            env.reset(seed=seed + rank * cfg.env.num_envs + vector_env_idx)
            env.action_space.seed(seed + rank * cfg.env.num_envs + vector_env_idx)

        # chaos drills: fire the env.step/env.reset injection sites — only
        # wrapped when an active fault plan targets them, so the disabled
        # path adds no wrapper (and no per-step overhead) at all.  Applied
        # after seeding (construction resets are not injection targets) and
        # INSIDE RestartOnException, so injected crashes exercise the real
        # restart path and injected hangs wedge the vector worker the
        # step-deadline watchdog guards against.
        from sheeprl_tpu.resilience.faults import active_plan

        plan = active_plan()
        if plan is not None and plan.targets("env."):
            from sheeprl_tpu.envs.wrappers import FaultInjectionEnv

            env = FaultInjectionEnv(env)
        return env

    return thunk


def episode_stats(info: Dict[str, Any]):
    """Extract finished-episode (return, length) pairs from vector-env info
    (gymnasium 1.x layout: masked dict-of-arrays under ``final_info``)."""
    out = []
    src = None
    if isinstance(info.get("final_info"), dict) and "episode" in info["final_info"]:
        src = info["final_info"]
    elif "episode" in info:
        src = info
    if src is not None:
        ep = src["episode"]
        mask = np.asarray(src.get("_episode", ep.get("_r", np.ones_like(ep["r"], bool))))
        for i in np.nonzero(mask)[0]:
            out.append((float(ep["r"][i]), int(ep["l"][i])))
    return out


def final_obs_rows(info: Dict[str, Any], env_indices: np.ndarray, obs_keys) -> Optional[Dict[str, np.ndarray]]:
    """Stack the real final observations of the given env rows from vector
    info (``final_obs`` is an object array with None for running envs)."""
    fo = info.get("final_obs")
    if fo is None:
        return None
    rows = []
    for i in env_indices:
        entry = fo[i]
        if entry is None:
            return None
        if not isinstance(entry, dict):
            return None
        rows.append(entry)
    return {k: np.stack([np.asarray(r[k]) for r in rows]) for k in obs_keys}


class StepDeadlineVectorEnv:
    """Liveness watchdog around ``AsyncVectorEnv``: a wedged env worker
    (deadlocked engine, NFS stall, injected hang) no longer deadlocks the
    run forever.

    ``RestartOnException`` (inside each worker) only catches *exceptions*; a
    worker that simply stops answering leaves ``AsyncVectorEnv.step``
    blocked with no timeout.  This wrapper drives the async pair itself —
    ``step_async`` + ``step_wait(timeout=deadline_s)`` (and the same for
    ``reset``) — and on a deadline miss tears the whole vector env down
    (``close(terminate=True)`` SIGTERMs the stuck workers), recreates it
    from the original thunks, resets, and reports the break to the train
    loop through the same ``info["restart_on_exception"]`` contract the
    per-env restart wrapper uses, so sequence replay patches its tail
    (``ReplayBuffer.repair_tail``) instead of bootstrapping across the gap.

    At most ``max_restarts`` teardowns within ``window_s`` seconds; beyond
    that the timeout propagates as ``RuntimeError`` — a persistently wedged
    fleet should fail the run, not loop silently.
    """

    def __init__(
        self,
        make_vec: Callable[[], gym.vector.VectorEnv],
        deadline_s: float,
        max_restarts: int = 3,
        window_s: float = 600.0,
    ):
        from collections import deque

        self._make_vec = make_vec
        self._deadline = float(deadline_s)
        self._max_restarts = int(max_restarts)
        self._window = float(window_s)
        self._restart_times: Any = deque()
        self._env = make_vec()

    def __getattr__(self, name: str) -> Any:
        # spaces, num_envs, call(), metadata… all delegate to the live env.
        # Private names never delegate: looking up self._env before __init__
        # finished (failed construction) must raise, not recurse.
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._env, name)

    @property
    def unwrapped(self) -> gym.vector.VectorEnv:
        return self._env

    def _spend_restart_budget(self, reason: str) -> None:
        now = time.monotonic()
        while self._restart_times and now - self._restart_times[0] > self._window:
            self._restart_times.popleft()
        if len(self._restart_times) >= self._max_restarts:
            # watchdog teardown exhausted its budget: this kills the run, so
            # leave the evidence NOW — the stall/restart event trail plus
            # this giveup — even if something swallows the raise upstream
            from sheeprl_tpu.telemetry.recorder import RECORDER

            RECORDER.record(
                "watchdog.giveup", reason=reason, restarts=len(self._restart_times)
            )
            RECORDER.dump("watchdog")
            raise RuntimeError(
                f"vector env wedged {len(self._restart_times) + 1} times within "
                f"{self._window}s ({reason}); giving up"
            )
        self._restart_times.append(now)

    def _teardown_and_recreate(self, reason: str) -> Dict[str, Any]:
        import multiprocessing as mp
        import warnings

        from sheeprl_tpu.utils.profiler import RESILIENCE_MONITOR

        # the recovery reset gets the SAME deadline as a step — a worker
        # that wedges during reset too must spend restart budget per
        # attempt and eventually propagate, not hang the watchdog itself
        while True:
            self._spend_restart_budget(reason)
            warnings.warn(
                f"vector env watchdog: {reason}; terminating workers and recreating",
                RuntimeWarning,
            )
            RESILIENCE_MONITOR.record_stall("vecenv.step")
            RESILIENCE_MONITOR.record_env_restart(getattr(self._env, "num_envs", 1))
            try:
                self._env.close(timeout=5.0, terminate=True)
            except (mp.TimeoutError, OSError, RuntimeError, EOFError):
                pass
            self._env = self._make_vec()
            try:
                self._env.reset_async()
                obs, info = self._env.reset_wait(timeout=self._deadline)
                break
            except mp.TimeoutError:
                reason = f"recovery reset exceeded the {self._deadline}s deadline"
        info = dict(info)
        # every env restarted: the whole batch of streams broke
        info["restart_on_exception"] = np.ones(self._env.num_envs, dtype=bool)
        return {"obs": obs, "info": info}

    def step(self, actions: Any):
        import multiprocessing as mp

        try:
            self._env.step_async(actions)
            return self._env.step_wait(timeout=self._deadline)
        except mp.TimeoutError:
            out = self._teardown_and_recreate(
                f"step exceeded the {self._deadline}s deadline"
            )
            n = self._env.num_envs
            return (
                out["obs"],
                np.zeros(n, dtype=np.float64),
                np.zeros(n, dtype=bool),
                np.zeros(n, dtype=bool),
                out["info"],
            )

    def reset(self, **kwargs: Any):
        import multiprocessing as mp

        try:
            self._env.reset_async(**kwargs)
            return self._env.reset_wait(timeout=self._deadline)
        except mp.TimeoutError:
            out = self._teardown_and_recreate(
                f"reset exceeded the {self._deadline}s deadline"
            )
            return out["obs"], out["info"]

    def close(self, **kwargs: Any) -> None:
        self._env.close(**kwargs)


def vectorize(cfg: Any, thunks: list) -> gym.vector.VectorEnv:
    """Vectorize with SAME_STEP autoreset so rollout loops observe the
    pre-1.0 gymnasium semantics the algorithms are written against
    (final observations surfaced via ``info["final_obs"]``).

    The async path is wrapped in :class:`StepDeadlineVectorEnv` when
    ``env.step_deadline_s`` > 0 (the default), so a wedged worker is
    detected and restarted instead of deadlocking the run; the sync path
    runs envs on the caller thread where a hang IS the caller hanging —
    nothing to watchdog from inside the process."""
    from gymnasium.vector import AutoresetMode

    if cfg.env.sync_env:
        return gym.vector.SyncVectorEnv(thunks, autoreset_mode=AutoresetMode.SAME_STEP)

    def make() -> gym.vector.VectorEnv:
        return gym.vector.AsyncVectorEnv(thunks, autoreset_mode=AutoresetMode.SAME_STEP)

    deadline = float(cfg.env.get("step_deadline_s", 0) or 0)
    if deadline > 0:
        return StepDeadlineVectorEnv(
            make,
            deadline,
            max_restarts=int(cfg.env.get("max_vecenv_restarts", 3) or 3),
            window_s=float(cfg.env.get("vecenv_restart_window_s", 600.0) or 600.0),
        )
    return make()
