"""GSPMD partition-rule sharding: regex-on-param-path → ``PartitionSpec``.

The structural substrate for scaling world models past pure data parallelism
(ROADMAP item 2): a 2-D ``(data, model)`` mesh where batches shard over
``data`` and the large matmul weights — RSSM dense stacks, decoder deconv
kernels, actor/critic MLPs — shard over ``model``.  Round-5 chip captures
put DV3-XL (210M params) at 8.8% MFU under data parallelism alone; the
matmuls were simply too narrow per chip.

Mechanism (the LM-stack recipe — SNIPPETS [3] ``match_partition_rules``,
named-sharding mesh of SNIPPETS [2]; arXiv:2412.14374, arXiv:2512.06392):
an ORDERED rule table of ``(regex, PartitionSpec)`` pairs is matched against
each leaf's tree path (``world_model/params/recurrent_model/gru/fused/
kernel``).  First match wins; scalars and unmatched leaves replicate.  The
same table therefore shards a param tree and its optimizer state
consistently — Adam moments live under paths like
``world_model/0/mu/params/.../kernel`` and ``re.search`` finds the same
suffix — which is what lets ``fabric.compile`` pin opt-state shardings to
the param rules and donate both for in-place updates.

A rule's spec may also be a callable ``fn(path, leaf, mesh) ->
Optional[PartitionSpec]`` (``None`` falls through to the next rule).  The
retired ad-hoc size-threshold TP heuristic of ``parallel/fabric.py`` lives
on as exactly such a table (:func:`size_threshold_rules`) — the fallback
for algorithms without a curated table, keeping ``fabric.tp_min_param_size``
as a compat knob.

Validation happens HERE, not in XLA: a spec naming an axis the mesh does
not have, or tiling a dimension the mesh axis does not divide, historically
surfaced as an opaque XLA error deep inside the first compile.
:func:`partition_specs` raises a ``ValueError`` naming the leaf, its shape,
the offending spec and the mesh — or demotes the leaf to replicated when
``undivisible="replicate"`` (the default: small presets simply replicate
kernels their mesh cannot tile).  :func:`explain` prints the resolved
spec per leaf for debugging (``sharding.explain=true``).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

RuleSpec = Union[P, Callable[[str, Any, Optional[Mesh]], Optional[P]]]
Rule = Tuple[str, RuleSpec]

__all__ = [
    "match_partition_rules",
    "partition_specs",
    "named_sharding_tree",
    "shardings_of",
    "explain",
    "resolve_rules",
    "rules_for_algo",
    "size_threshold_rules",
    "spec_from_config",
    "replay_partition_spec",
    "replay_sharding",
    "env_state_partition_spec",
    "env_state_sharding",
    "DREAMER_V3_RULES",
    "RULE_TABLES",
]


# --------------------------------------------------------------------------
# replay shardings (data/device_replay.py)
# --------------------------------------------------------------------------

def replay_partition_spec(n_envs: int, mesh: Optional[Mesh], data_axis: str = "data") -> P:
    """``PartitionSpec`` for a device-resident replay ring ``(T, n_envs, *)``.

    The ENV axis (axis 1) shards over the mesh ``data`` axis — each device
    owns the ring slots of its own env streams, the same ``data``-axis
    layout ``fabric.shard_batch`` gives a shipped batch, so on-device
    sampled gathers stay mostly shard-local and the consuming train step
    sees the canonical data-parallel placement.  The time axis never shards
    (ring writes hit every shard's head in lockstep).  When the env count
    does not divide the ``data`` axis the ring replicates — a correct (if
    un-sharded) placement, mirroring ``shard_batch``'s divisibility rule.
    Trailing feature dims are left unspecified (replicated) by the short
    spec, whatever the leaf rank."""
    if mesh is None or data_axis not in mesh.shape:
        return P()
    n_data = int(mesh.shape[data_axis])
    if n_data <= 1 or int(n_envs) % n_data != 0:
        return P()
    return P(None, data_axis)


def replay_sharding(mesh: Mesh, n_envs: int, data_axis: str = "data") -> NamedSharding:
    """``NamedSharding`` form of :func:`replay_partition_spec` on ``mesh``."""
    return NamedSharding(mesh, replay_partition_spec(n_envs, mesh, data_axis))


# --------------------------------------------------------------------------
# Anakin env-state shardings (envs/jax/anakin.py)
# --------------------------------------------------------------------------

def env_state_partition_spec(n_envs: int, mesh: Optional[Mesh], data_axis: str = "data") -> P:
    """``PartitionSpec`` for a batched ``EnvState`` pytree ``(n_envs, *)``.

    The LEADING axis of every env-state leaf is the env instance axis; it
    shards over the mesh ``data`` axis so each device steps its own env
    rows inside the fused Anakin rollout — the same placement the fused
    train phase's minibatch gathers expect (and the replay ring uses, one
    axis earlier).  Indivisible env counts replicate, mirroring
    :func:`replay_partition_spec`'s rule."""
    if mesh is None or data_axis not in mesh.shape:
        return P()
    n_data = int(mesh.shape[data_axis])
    if n_data <= 1 or int(n_envs) % n_data != 0:
        return P()
    return P(data_axis)


def env_state_sharding(mesh: Mesh, n_envs: int, data_axis: str = "data") -> NamedSharding:
    """``NamedSharding`` form of :func:`env_state_partition_spec`."""
    return NamedSharding(mesh, env_state_partition_spec(n_envs, mesh, data_axis))


# --------------------------------------------------------------------------
# tree paths
# --------------------------------------------------------------------------

def _key_name(entry: Any) -> str:
    """One path segment from a ``tree_flatten_with_path`` key entry."""
    for attr in ("key", "idx", "name"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def tree_paths_and_leaves(tree: Any, sep: str = "/"):
    """``[(path, leaf), ...], treedef`` with ``/``-joined string paths.

    Works uniformly over dicts, (named)tuples and dataclass-ish optax states:
    ``params['world_model']['params']['actor']...`` and
    ``opt_state['world_model'][1].inner_state[0].mu[...]`` both flatten to
    slash paths a single regex can address.
    """
    from jax.tree_util import tree_flatten_with_path

    flat, treedef = tree_flatten_with_path(tree)
    return [(sep.join(_key_name(k) for k in kp), leaf) for kp, leaf in flat], treedef


# --------------------------------------------------------------------------
# rule matching
# --------------------------------------------------------------------------

def _is_scalar(leaf: Any) -> bool:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return True
    return len(shape) == 0 or int(np.prod(shape)) == 1


def _match_one(
    rules: Sequence[Rule], path: str, leaf: Any, mesh: Optional[Mesh]
) -> Tuple[P, str]:
    """(spec, rule label) for one leaf.  Scalars never partition; unmatched
    leaves replicate — ``P()`` on a 2-D mesh means fully replicated over BOTH
    the data and the model axis, which is the correct placement for biases,
    LayerNorm params and other small leaves no rule claims."""
    if _is_scalar(leaf):
        return P(), "<scalar>"
    for pattern, spec in rules:
        if re.search(pattern, path) is None:
            continue
        if callable(spec):
            out = spec(path, leaf, mesh)
            if out is None:
                continue  # predicate declined: keep scanning the table
            return out, pattern
        return spec, pattern
    return P(), "<unmatched>"


def match_partition_rules(
    rules: Sequence[Rule], tree: Any, mesh: Optional[Mesh] = None, sep: str = "/"
) -> Any:
    """Pytree of ``PartitionSpec`` for ``tree`` under ordered first-match-wins
    ``rules`` (the SNIPPETS [3] surface).  Handles param trees and optax
    optimizer states alike; no validation — see :func:`partition_specs`."""
    flat, treedef = tree_paths_and_leaves(tree, sep=sep)
    return treedef.unflatten([_match_one(rules, p, l, mesh)[0] for p, l in flat])


def _spec_axes(entry: Any) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def _check_spec(mesh: Mesh, path: str, leaf: Any, spec: P) -> Optional[str]:
    """None when ``spec`` is placeable on ``mesh``; else a human-readable
    reason (unknown axis → always an error upstream, undivisible dim →
    subject to the ``undivisible`` policy)."""
    shape = tuple(getattr(leaf, "shape", ()) or ())
    if len(spec) > len(shape):
        return f"spec {spec} has more dimensions than leaf shape {shape}"
    for d, entry in enumerate(spec):
        axes = _spec_axes(entry)
        tile = 1
        for ax in axes:
            if ax not in mesh.shape:
                return f"axis {ax!r} not in mesh axes {tuple(mesh.axis_names)}"
            tile *= int(mesh.shape[ax])
        if tile > 1 and shape[d] % tile != 0:
            return (
                f"dim {d} of shape {shape} ({shape[d]}) does not divide by "
                f"mesh axes {axes} (tile {tile})"
            )
    return None


def partition_specs(
    rules: Sequence[Rule],
    tree: Any,
    mesh: Mesh,
    undivisible: str = "replicate",
    sep: str = "/",
) -> Any:
    """Matched + VALIDATED ``PartitionSpec`` pytree for ``tree`` on ``mesh``.

    Every leaf's spec is checked against the mesh before XLA ever sees it:

    * a spec naming an axis the mesh doesn't have always raises (that is a
      wrong rule table, not a small model);
    * a sharded dimension the mesh axis doesn't divide follows the
      ``undivisible`` policy — ``"replicate"`` demotes the leaf to ``P()``
      (small presets on big meshes), ``"error"`` raises with the leaf path,
      shape, spec and mesh spelled out (the production assertion — an
      undivided 500M kernel silently replicating would waste the mesh).
    """
    if undivisible not in ("replicate", "error"):
        raise ValueError(f"undivisible policy must be 'replicate' or 'error', got {undivisible!r}")
    flat, treedef = tree_paths_and_leaves(tree, sep=sep)
    out: List[P] = []
    for path, leaf in flat:
        spec, label = _match_one(rules, path, leaf, mesh)
        problem = _check_spec(mesh, path, leaf, spec) if len(spec) else None
        if problem is not None:
            if "not in mesh axes" in problem or "more dimensions" in problem:
                raise ValueError(
                    f"partition rule {label!r} produced an unplaceable spec for "
                    f"'{path}': {problem} (mesh {dict(mesh.shape)})"
                )
            if undivisible == "error":
                raise ValueError(
                    f"partition rule {label!r} cannot tile '{path}': {problem} "
                    f"(mesh {dict(mesh.shape)}); pick divisible model dims, "
                    "adjust the rule, or set sharding.undivisible=replicate"
                )
            spec = P()
        out.append(spec)
    return treedef.unflatten(out)


def named_sharding_tree(mesh: Mesh, spec_tree: Any) -> Any:
    """``PartitionSpec`` pytree → ``NamedSharding`` pytree on ``mesh``."""
    import jax

    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), spec_tree, is_leaf=lambda x: isinstance(x, P)
    )


def shardings_of(tree: Any) -> Any:
    """Per-leaf shardings of an already-placed pytree — the bridge from
    ``fabric.shard_params`` output to ``fabric.compile`` in/out shardings.
    Non-``jax.Array`` leaves map to ``None`` (jit: 'unspecified')."""
    import jax

    return jax.tree.map(
        lambda x: x.sharding if isinstance(x, jax.Array) else None, tree
    )


# --------------------------------------------------------------------------
# explain
# --------------------------------------------------------------------------

def explain(
    rules: Sequence[Rule],
    tree: Any,
    mesh: Optional[Mesh] = None,
    undivisible: str = "replicate",
    title: str = "partition rules",
    sep: str = "/",
) -> str:
    """Render the resolved spec per leaf as a table — the debugging surface
    for "why is this kernel replicated?".  With a mesh, validation notes
    (demotions, per-device byte counts) are included."""
    flat, _ = tree_paths_and_leaves(tree, sep=sep)
    rows: List[Tuple[str, str, str, str, str]] = []
    sharded = demoted = 0
    for path, leaf in flat:
        spec, label = _match_one(rules, path, leaf, mesh)
        note = ""
        if mesh is not None and len(spec):
            problem = _check_spec(mesh, path, leaf, spec)
            if problem is not None:
                note = f"-> replicated ({problem})" if undivisible == "replicate" else f"ERROR: {problem}"
                spec = P() if undivisible == "replicate" else spec
                demoted += 1
        if len([e for e in spec if e is not None]):
            sharded += 1
        shape = tuple(getattr(leaf, "shape", ()) or ())
        rows.append((path, str(shape), label, str(spec), note))
    widths = [max(len(r[i]) for r in rows) if rows else 0 for i in range(4)]
    header = f"{title}" + (f" on mesh {dict(mesh.shape)}" if mesh is not None else "")
    lines = [header, f"  {len(rows)} leaves, {sharded} sharded, {demoted} demoted to replicated"]
    for path, shape, label, spec, note in rows:
        lines.append(
            f"  {path:<{widths[0]}}  {shape:<{widths[1]}}  "
            f"{label:<{widths[2]}}  {spec:<{widths[3]}}  {note}".rstrip()
        )
    return "\n".join(lines)


# --------------------------------------------------------------------------
# rule tables
# --------------------------------------------------------------------------

def size_threshold_rules(min_size: int, axis: str = "model") -> Tuple[Rule, ...]:
    """The retired fabric.py ad-hoc TP rule as a rules table: 2-D kernels of
    ``size >= min_size`` whose output dim divides the ``model`` axis are
    column-sharded; everything else replicates.  Kept as the fallback table
    for algorithms without a curated one (``fabric.tp_min_param_size`` is
    its compat knob) — identical placement to the pre-rules-engine code."""

    def rule(path: str, leaf: Any, mesh: Optional[Mesh]) -> Optional[P]:
        k = int(mesh.shape.get(axis, 1)) if mesh is not None else 1
        if (
            getattr(leaf, "ndim", 0) == 2
            and int(np.prod(leaf.shape)) >= int(min_size)
            and k > 1
            and leaf.shape[-1] % k == 0
        ):
            return P(None, axis)
        return None

    return ((r".*", rule),)


#: DreamerV3 family (dreamer_v3, p2e_dv3): column/row-shard the RSSM dense
#: stacks, decoder deconv kernels and actor/critic MLPs over ``model``.
#: Ordering matters — first match wins:
#:  * the RGB output head (3 channels) is pinned replicated explicitly.
#:    Today this is defensive, not ordering-critical — the generic deconv
#:    regex requires a numeric suffix (``deconv_3``) and cannot match
#:    ``deconv_out`` — but the pin keeps a future broadening of that regex
#:    from column-sharding 3 channels;
#:  * conv/deconv kernels shard their output-channel dim (flax layout
#:    ``(kh, kw, in, out)``);
#:  * the fused GRU gate kernel, the RSSM input projection and the decoder
#:    latent expansion (``cnn_in`` — the single largest kernel in DV3-XL+)
#:    column-shard: their output features split across chips and GSPMD
#:    inserts the all-gathers where a consumer needs full rows;
#:  * MLP output heads row-shard (input-dim split → psum of partials):
#:    their output widths — action dims, 255 two-hot bins, per-key obs
#:    dims — rarely divide a mesh axis, but their input (dense_units) always
#:    does;
#:  * every remaining dense-stack kernel column-shards.
DREAMER_V3_RULES: Tuple[Rule, ...] = (
    (r"observation_model/deconv_out/", P()),
    (r"(?:de)?conv_[0-9]+/kernel", P(None, None, None, "model")),
    (r"recurrent_model/(?:gru/fused|in)/kernel", P(None, "model")),
    (r"observation_model/cnn_in/kernel", P(None, "model")),
    (r"head(?:_[a-z0-9_]+)?/kernel", P("model", None)),
    (r"(?:dense|mlp)_[0-9]+/kernel", P(None, "model")),
)


RULE_TABLES: Dict[str, Any] = {
    "dreamer_v3": DREAMER_V3_RULES,
    "p2e_dv3": DREAMER_V3_RULES,
    "replicate": (),
    # callable tables are parameterized by the compat knob at resolve time
    "size_threshold": size_threshold_rules,
}


def rules_for_algo(algo: Optional[str], tp_min_param_size: int = 2**18) -> Tuple[Rule, ...]:
    """Default table for an algorithm name: curated where one exists
    (DreamerV3 family), the legacy size-threshold fallback otherwise."""
    for name, table in RULE_TABLES.items():
        if algo and algo.startswith(name):
            return table if not callable(table) else table(tp_min_param_size)
    return size_threshold_rules(tp_min_param_size)


def spec_from_config(entry: Any) -> RuleSpec:
    """YAML spec → ``PartitionSpec``: ``[null, model]`` → ``P(None,
    'model')``; nested lists mean multi-axis dims (``[[data, model]]``)."""
    if isinstance(entry, P):
        return entry
    if entry is None:
        return P()
    if isinstance(entry, str):
        return P(entry)
    return P(*(tuple(e) if isinstance(e, (list, tuple)) else e for e in entry))


def resolve_rules(
    sharding_cfg: Optional[Dict[str, Any]] = None,
    tp_min_param_size: int = 2**18,
) -> Tuple[Rule, ...]:
    """Concrete rule table from the ``sharding`` config group.

    ``rules`` entries (user overrides) are PREPENDED — first-match-wins
    means a user rule always beats the built-in table.  Accepted entry
    forms: ``[pattern, spec]`` pairs or ``{pattern: ..., spec: ...}``
    mappings.  ``table`` selects the base: ``auto`` (per-``algo`` curated
    table or the size-threshold fallback), a named table from
    :data:`RULE_TABLES`, or ``replicate``/``null`` for none.
    """
    cfg = dict(sharding_cfg or {})
    user: List[Rule] = []
    for entry in cfg.get("rules") or ():
        if isinstance(entry, dict):
            pattern, spec = entry["pattern"], entry.get("spec")
        else:
            pattern, spec = entry
        user.append((str(pattern), spec_from_config(spec)))
    table = cfg.get("table", "auto")
    if table in (None, "none"):
        base: Tuple[Rule, ...] = ()
    elif table == "auto":
        base = rules_for_algo(cfg.get("algo"), tp_min_param_size)
    elif table in RULE_TABLES:
        found = RULE_TABLES[table]
        base = found(tp_min_param_size) if callable(found) else found
    else:
        raise ValueError(
            f"Unknown sharding table {table!r}; choose from "
            f"{['auto', *RULE_TABLES]} or provide explicit rules"
        )
    return tuple(user) + tuple(base)
