"""Sebulba device-group topology: actor/learner mesh split + param broadcast.

Podracer's Sebulba architecture (arXiv:2104.06272; TorchBeast's actor/learner
split, arXiv:1910.03552) divides the devices of one pod between two roles:

* **actor devices** run batched policy inference (or, for pure-JAX envs,
  whole fused rollout shards) and produce trajectories;
* **learner devices** own the training mesh: they consume a device-resident
  trajectory queue and run the optimization program, with gradients
  all-reduced over the learner sub-mesh only.

Parameters flow learner → actors as a device-to-device broadcast (the
:class:`ParamBroadcast` below), replacing the point-to-point
:class:`~sheeprl_tpu.parallel.fabric.PlayerSync` host pulls of the pipelined
decoupled loops.  Staleness — how many learner updates behind the actors'
weights are — is *bounded* (``topology.max_staleness`` gates the learner)
and *reported* (``Sebulba/*`` metrics), instead of being an accident of
dispatch timing.

This module owns the device bookkeeping only; the queues, actor loops and
per-algorithm drivers live in :mod:`sheeprl_tpu.sebulba`.

Config surface (the ``topology`` Hydra group)::

    topology:
      name: auto            # auto | pipelined | sebulba
      actor_devices: 1      # devices in the actor group (int)
      learner_devices: -1   # devices in the learner group (-1 = the rest)
      ...                   # queue/worker knobs read by sheeprl_tpu.sebulba

``name: auto`` selects sebulba only when the split is explicitly sized
(``actor_devices`` non-null) — existing decoupled configs keep the
single-controller pipelined path untouched.  ``name: sebulba`` demands the
split (defaulting to one actor device) and raises where it cannot exist.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from sheeprl_tpu.parallel.fabric import Fabric


def topology_cfg(cfg: Any) -> Dict[str, Any]:
    """The ``topology`` config group as a plain dict (tolerates configs
    composed before the group existed — external callers, old tests)."""
    raw = cfg.get("topology") if hasattr(cfg, "get") else None
    return dict(raw) if raw else {}


def resolve_topology(cfg: Any, fabric: Fabric) -> str:
    """Which decoupled topology this run should use: ``"sebulba"``,
    ``"pod"`` (cross-host sebulba) or ``"pipelined"``.

    ``auto`` (the default) upgrades to sebulba only when the user sized the
    device split (``topology.actor_devices`` set): the pipelined
    single-controller loop *is* the degenerate sebulba (both roles
    time-share every device), and silently re-topologizing existing runs
    would change their compile set and overlap semantics.  ``sebulba``
    forces the split and raises where it cannot exist (a tensor-parallel
    ``model`` mesh axis — the learner sub-mesh is 1-D).

    Multi-process runs dispatch to the **pod** flavor: the process
    boundary IS the device split (one learner cell, N-1 actor cells on
    different hosts; see :class:`PodTopology` and ``sheeprl_tpu.sebulba.
    pod``), so a wanted split no longer refuses ``fabric.num_processes >
    1`` — it crosses the DCN instead.
    """
    topo = topology_cfg(cfg)
    name = str(topo.get("name", "auto")).lower()
    if name == "pipelined":
        return "pipelined"
    if name not in ("auto", "sebulba", "pod"):
        raise ValueError(
            f"topology.name must be auto|pipelined|sebulba|pod, got {name!r}"
        )
    if name == "pod" and fabric.num_processes <= 1:
        raise ValueError(
            "topology=pod needs a multi-process fabric (fabric.distributed.*, "
            "or SHEEPRL_FAKE_DCN=N for the CI pod)"
        )
    wanted = name in ("sebulba", "pod") or topo.get("actor_devices") is not None
    if not wanted:
        if fabric.num_processes > 1:
            from sheeprl_tpu.parallel.distributed import rank_zero_warn

            rank_zero_warn(
                "multi-process fabric without a topology split: the "
                "pipelined loop will run in lockstep collectives only "
                "(set topology=pod for the cross-host actor/learner split)",
                key="topology.pod_hint",
            )
        return "pipelined"
    if fabric.num_processes > 1:
        return "pod"
    reasons = []
    if fabric.model_axis is not None:
        reasons.append("a tensor-parallel 'model' mesh axis")
    if reasons:
        if name == "sebulba":
            raise ValueError(
                "topology=sebulba does not support " + " or ".join(reasons)
            )
        import warnings

        warnings.warn(
            "topology.actor_devices set but the run cannot split devices "
            f"({'; '.join(reasons)}); falling back to the pipelined topology",
            RuntimeWarning,
        )
        return "pipelined"
    return "sebulba"


def _submesh_fabric(fabric: Fabric, devices: List[Any]) -> Fabric:
    """A fabric whose 1-D ``data`` mesh spans only ``devices`` — the shared
    :func:`~sheeprl_tpu.parallel.fabric.clone_with_devices` surgery over an
    arbitrary single-process device subset (the learner group)."""
    from sheeprl_tpu.parallel.fabric import clone_with_devices

    return clone_with_devices(fabric, devices)


@dataclass
class DeviceTopology:
    """The resolved actor/learner device split of one mesh.

    ``actor_devices`` / ``learner_devices`` are disjoint (except in the
    degenerate single-device case, where both roles share the one device —
    functional, documented, and warned about).  ``learner_fabric`` is a
    1-D-data-mesh fabric over the learner group: the training program, its
    batch sharding, and the device-resident trajectory queue all live
    there.  Actors are per-device inference engines, so they get plain
    device handles, not a mesh.
    """

    fabric: Fabric
    actor_devices: List[Any]
    learner_devices: List[Any]
    learner_fabric: Fabric = field(init=False)
    shared: bool = False  # one device playing both roles

    def __post_init__(self) -> None:
        self.learner_fabric = _submesh_fabric(self.fabric, self.learner_devices)

    @property
    def num_actors(self) -> int:
        return len(self.actor_devices)

    @property
    def num_learners(self) -> int:
        return len(self.learner_devices)

    def describe(self) -> str:
        a = ", ".join(str(d) for d in self.actor_devices)
        l = ", ".join(str(d) for d in self.learner_devices)
        tag = " (shared device: degenerate split)" if self.shared else ""
        return f"sebulba topology{tag}: actors=[{a}] learners=[{l}]"

    @classmethod
    def from_config(cls, fabric: Fabric, cfg: Any) -> "DeviceTopology":
        """Split ``fabric``'s mesh devices per ``topology.actor_devices`` /
        ``topology.learner_devices``, validated against the mesh size.

        ``learner_devices: -1`` (default) takes every device the actor
        group left.  A 1-device mesh degenerates to both groups sharing the
        device (warned): every code path still runs, which is what CI
        single-device smoke cells need.
        """
        topo = topology_cfg(cfg)
        devices = list(fabric.mesh.devices.flat)
        n = len(devices)
        a = topo.get("actor_devices")
        a = 1 if a is None else int(a)
        l_raw = topo.get("learner_devices", -1)
        l = -1 if l_raw is None else int(l_raw)
        if n == 1:
            import warnings

            warnings.warn(
                "topology=sebulba on a 1-device mesh: actor and learner "
                "groups share the device (no real split; use >= 2 devices "
                "for the actor/learner overlap)",
                RuntimeWarning,
            )
            return cls(fabric, [devices[0]], [devices[0]], shared=True)
        if a < 1:
            raise ValueError(f"topology.actor_devices must be >= 1, got {a}")
        if a >= n:
            raise ValueError(
                f"topology.actor_devices={a} leaves no learner devices on a "
                f"{n}-device mesh (mesh {dict(fabric.mesh.shape)})"
            )
        if l == -1:
            l = n - a
        if l < 1:
            raise ValueError(f"topology.learner_devices must be >= 1 or -1, got {l}")
        if a + l > n:
            raise ValueError(
                f"topology.actor_devices={a} + learner_devices={l} exceeds "
                f"the {n}-device mesh (mesh {dict(fabric.mesh.shape)})"
            )
        if a + l < n:
            import warnings

            warnings.warn(
                f"topology: {n - a - l} of {n} mesh devices are assigned to "
                "neither group and will idle",
                RuntimeWarning,
            )
        return cls(fabric, devices[:a], devices[a : a + l])


@dataclass
class PodTopology:
    """The cross-host actor/learner split: the process boundary IS the
    device split.

    One process (``topology.pod.learner_process``, fixed at rank 0 — the
    checkpoint commit protocol's manifest writer) is the **learner cell**;
    every other process is an **actor cell**.  Each cell computes only on
    its OWN local devices through a 1-D local fabric — there are no
    cross-host XLA collectives in the steady-state data path.  Everything
    that crosses hosts goes over the DCN transport
    (:mod:`sheeprl_tpu.sebulba.transport`): CRC-stamped trajectory
    segments in, versioned parameter fetches out, and the control plane
    (commit steps, preemption, liveness) alongside.
    """

    fabric: Fabric
    role: str  # "learner" | "actor"
    process_index: int
    learner_process: int
    actor_cells: List[int]
    local_devices: List[Any]
    cell_fabric: Fabric = field(init=False)

    def __post_init__(self) -> None:
        self.cell_fabric = _submesh_fabric(self.fabric, self.local_devices)

    @property
    def num_actor_cells(self) -> int:
        return len(self.actor_cells)

    @property
    def cell_index(self) -> int:
        """This actor cell's dense index among the actor cells (learner: -1)."""
        return self.actor_cells.index(self.process_index) if self.role == "actor" else -1

    def describe(self) -> str:
        devs = ", ".join(str(d) for d in self.local_devices)
        return (
            f"pod topology: {self.fabric.num_processes} cells "
            f"(learner=process {self.learner_process}, actors={self.actor_cells}); "
            f"this cell: rank {self.process_index} role={self.role} devices=[{devs}]"
        )

    @classmethod
    def from_config(cls, fabric: Fabric, cfg: Any) -> "PodTopology":
        import jax

        topo = topology_cfg(cfg)
        pod = dict(topo.get("pod") or {})
        world = fabric.num_processes
        if world < 2:
            raise ValueError("PodTopology needs >= 2 processes (one learner cell + actors)")
        learner_process = int(pod.get("learner_process", 0) or 0)
        if learner_process != 0:
            # rank 0 writes the checkpoint manifest + COMMIT (protocol.py);
            # splitting the learner from the committer would leave the
            # commit racing a cell that has no authoritative step counter
            raise ValueError(
                "topology.pod.learner_process must be 0 (the checkpoint "
                f"commit rank), got {learner_process}"
            )
        rank = fabric.global_rank
        local = [d for d in jax.local_devices() if d.platform == fabric.accelerator]
        if not local:
            raise RuntimeError(
                f"pod cell {rank} owns no local {fabric.accelerator} devices — "
                "fabric.devices must be 'auto' so the global mesh spans every cell"
            )
        actor_cells = [r for r in range(world) if r != learner_process]
        return cls(
            fabric,
            role="learner" if rank == learner_process else "actor",
            process_index=rank,
            learner_process=learner_process,
            actor_cells=actor_cells,
            local_devices=local,
        )


class StalenessExceeded(RuntimeError):
    """The learner waited past its deadline for actors to pick up fresh
    params (``topology.max_staleness`` gate)."""


class ParamBroadcast:
    """Learner → actor device-to-device parameter broadcast with a bounded,
    *observable* staleness contract.

    The learner calls :meth:`publish` after each optimization step: the
    (actor-relevant subtree of the) fresh params are copied onto every
    actor device — ``fabric.copy_to`` per target, i.e. a real device-to-
    device transfer (packed per dtype cross-platform), never a host
    round-trip through pickled numpy like the retired ``PlayerSync`` pull
    path.  Actors call :meth:`fetch` before each inference dispatch and
    always receive the newest published version.

    The staleness bound: before optimization step ``v+1`` the learner calls
    :meth:`gate`, which blocks until every actor has fetched a version
    ``>= v - max_staleness``.  Since actors fetch-before-dispatch, an actor
    batch is therefore computed with weights at most ``max_staleness``
    learner updates behind the weights being trained — the knob trades
    actor/learner decoupling against off-policyness, and the observed gap
    is reported as ``Sebulba/param_staleness``.
    """

    def __init__(
        self,
        fabric: Fabric,
        actor_devices: List[Any],
        extract: Callable[[Any], Any] = lambda p: p,
        max_staleness: int = 2,
        gate_timeout_s: float = 300.0,
    ):
        self.fabric = fabric
        self.actor_devices = list(actor_devices)
        self.extract = extract
        self.max_staleness = int(max_staleness)
        self.gate_timeout_s = float(gate_timeout_s)
        self._lock = threading.Lock()
        self._fetched = threading.Condition(self._lock)
        self._version = 0
        self._params: List[Any] = [None] * len(self.actor_devices)
        self._fetched_version = [0] * len(self.actor_devices)
        # observability (read under the lock, flushed as Sebulba/* metrics)
        self.publishes = 0
        self.gate_wait_s = 0.0
        self.staleness_sum = 0
        self.staleness_max = 0
        self.fetches = 0

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def publish(self, params: Any, version: Optional[int] = None) -> int:
        """Copy the actor subtree of ``params`` onto every actor device and
        stamp it with ``version`` (defaults to the next integer).  Called by
        the learner right after its (async-dispatched) update — the D2D
        copies are enqueued behind the update, so by the time an actor
        dispatch reads them the device has finished both."""
        from sheeprl_tpu.telemetry.spans import span

        with span("param.broadcast"):
            sub = self.extract(params)
            copies = [self.fabric.copy_to(sub, d) for d in self.actor_devices]
        with self._lock:
            first = self.publishes == 0
            self._version = int(version) if version is not None else self._version + 1
            if first:
                # the FIRST publish defines the baseline (a resumed run
                # publishes its checkpointed version): seeding the fetch
                # cursors here keeps staleness metrics measuring lag, not
                # the absolute resume offset
                self._fetched_version = [self._version] * len(self.actor_devices)
            self._params = copies
            self.publishes += 1
            self._fetched.notify_all()
            return self._version

    def fetch(self, actor_index: int) -> tuple:
        """Newest published ``(params, version)`` for one actor engine;
        records the fetch for the staleness gate and metrics.  Returns
        ``(None, 0)`` before the first publish."""
        with self._lock:
            params = self._params[actor_index]
            version = self._version
            lag = version - self._fetched_version[actor_index]
            self._fetched_version[actor_index] = version
            self.fetches += 1
            self.staleness_sum += lag
            self.staleness_max = max(self.staleness_max, lag)
            self._fetched.notify_all()
            return params, version

    def staleness(self, actor_index: int) -> int:
        """How many published versions behind this actor's last fetch is."""
        with self._lock:
            return self._version - self._fetched_version[actor_index]

    def gate(self, timeout_s: Optional[float] = None) -> float:
        """Block the learner until every actor's last-fetched version is
        within ``max_staleness`` of the current one.  Returns seconds
        waited; raises :class:`StalenessExceeded` past the deadline (a
        wedged actor must fail the run loudly, not silently train on a
        frozen data distribution)."""
        deadline = time.monotonic() + (
            self.gate_timeout_s if timeout_s is None else float(timeout_s)
        )
        t0 = time.monotonic()
        with self._lock:
            while self._version - min(self._fetched_version) > self.max_staleness:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    lags = [self._version - f for f in self._fetched_version]
                    raise StalenessExceeded(
                        f"actors still {lags} versions behind after "
                        f"{self.gate_timeout_s}s (max_staleness="
                        f"{self.max_staleness})"
                    )
                self._fetched.wait(remaining)
        waited = time.monotonic() - t0
        with self._lock:
            self.gate_wait_s += waited
        return waited

    def metrics(self) -> Dict[str, float]:
        with self._lock:
            return {
                "Sebulba/param_version": float(self._version),
                "Sebulba/param_staleness_max": float(self.staleness_max),
                "Sebulba/param_staleness_avg": (
                    self.staleness_sum / self.fetches if self.fetches else 0.0
                ),
                "Sebulba/param_gate_wait_s": float(self.gate_wait_s),
            }
