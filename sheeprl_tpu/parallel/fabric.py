"""Single-controller SPMD runtime over a ``jax.sharding.Mesh``.

This is the TPU-native replacement for the reference's Lightning Fabric layer
(reference: sheeprl/configs/fabric/default.yaml and the ``fabric.*`` calls all
over sheeprl/algos/*): device selection, the device mesh, precision policy,
checkpointing callbacks, and host collectives.

Design differences from the reference, on purpose (SURVEY.md §2.2/§7):

* The reference spawns one Python process per device and synchronizes with
  NCCL/Gloo DDP all-reduce.  Here ONE controller process drives all local
  devices: parameters are *replicated* over the mesh, batches are *sharded*
  over the ``data`` axis, and a jitted train step whose loss is a mean over
  the batch makes XLA insert the gradient all-reduce over ICI automatically
  (GSPMD).  There is no process-group bookkeeping to port.
* Multi-host (DCN) uses ``jax.distributed.initialize`` + the same mesh
  spanning all hosts; host-side object exchange (log dirs, configs) rides
  :meth:`broadcast_object` built on ``multihost_utils``.
* "world_size" therefore means the total number of devices in the mesh (the
  data-parallel degree), and "global_rank" the process index — which is what
  the reference uses each for (batch splitting vs. rank-0-only logging).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class Precision:
    """Maps the reference's Lightning precision strings to JAX dtype policy.

    ``param_dtype`` is the dtype parameters are stored in, ``compute_dtype``
    the dtype activations are computed in (models cast inputs / params at
    call sites).  On TPU, bf16 compute hits the MXU fast path while fp32
    params keep optimizer numerics stable.
    """

    name: str
    param_dtype: Any
    compute_dtype: Any

    @staticmethod
    def from_string(precision: str) -> "Precision":
        table = {
            "32-true": (jnp.float32, jnp.float32),
            "bf16-mixed": (jnp.float32, jnp.bfloat16),
            "bf16-true": (jnp.bfloat16, jnp.bfloat16),
        }
        if precision not in table:
            raise ValueError(f"Unknown precision '{precision}'; choose from {list(table)}")
        param, compute = table[precision]
        return Precision(precision, param, compute)


def _resolve_accelerator(accelerator: str) -> str:
    if accelerator in ("auto", None):
        platforms = {d.platform for d in jax.devices()}
        for pref in ("tpu", "gpu", "axon"):
            if pref in platforms:
                return pref
        return "cpu"
    return {"tpu": "tpu", "cuda": "gpu", "gpu": "gpu", "cpu": "cpu", "axon": "axon"}.get(
        accelerator, accelerator
    )


_FORCED_CPU_PLATFORM = False


def ensure_compilation_cache() -> Optional[str]:
    """Default-on persistent XLA compilation cache (compile-once hygiene).

    Every Fabric construction — including CPU dryruns and tests, which
    historically ran cache-less and re-paid every compile per process —
    points JAX at a persistent cache directory unless one is already
    configured.  Resolution order:

    * an explicit ``fabric.compilation_cache_dir`` config (``build_fabric``)
      or a prior ``jax.config`` update wins;
    * ``SHEEPRL_COMPILE_CACHE`` overrides the location; setting it to ``""``
      or ``0`` disables the default entirely;
    * otherwise ``/tmp/sheeprl_tpu_compile_cache.<uid>`` (per-user so a
      shared host can't poison another user's cache).

    JAX's own min-compile-time threshold (default ~1s, override via
    ``JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS``) keeps tiny test
    programs out of the cache; only the expensive train-phase programs are
    persisted and re-used across processes/rounds.
    Returns the active cache dir, or None when disabled.
    """
    current = jax.config.jax_compilation_cache_dir
    if current:
        return current
    env = os.environ.get("SHEEPRL_COMPILE_CACHE")
    if env is not None and env.strip() in ("", "0", "off", "none"):
        return None
    if env:
        cache_dir = env
    else:
        uid = os.getuid() if hasattr(os, "getuid") else "u"
        import tempfile

        cache_dir = os.path.join(
            tempfile.gettempdir(), f"sheeprl_tpu_compile_cache.{uid}"
        )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:
        return None
    return cache_dir


class Fabric:
    """Runtime facade handed to every algorithm ``main(fabric, cfg)``."""

    def __init__(
        self,
        devices: Union[int, str] = 1,
        num_nodes: int = 1,
        strategy: str = "auto",
        accelerator: str = "auto",
        precision: str = "32-true",
        callbacks: Optional[Dict[str, Any]] = None,
        mesh_shape: Optional[Dict[str, int]] = None,
        tp_min_param_size: int = 2**18,
        sharding: Optional[Dict[str, Any]] = None,
    ):
        self.strategy = strategy
        self.tp_min_param_size = int(tp_min_param_size)
        #: the ``sharding`` config group (rules table selection, user rules,
        #: undivisible policy, explain flag); resolved lazily into a concrete
        #: rule table by :attr:`sharding_rules`.  A bare ``Fabric(...)`` with
        #: no config keeps the legacy size-threshold behavior.
        self.sharding_cfg: Dict[str, Any] = dict(sharding or {})
        self._sharding_rules: Optional[Tuple[Any, ...]] = None
        self.precision = Precision.from_string(precision)
        self.callbacks: List[Any] = []
        self._callback_cfg = callbacks or {}
        #: set by get_checkpoint_manager once a train loop binds its log_dir
        self.checkpoint_manager: Optional[Any] = None
        ensure_compilation_cache()

        global _FORCED_CPU_PLATFORM
        if accelerator == "cpu":
            # make CPU the default backend too (not just the device list), so
            # jitted computations execute where the user asked; needed because
            # TPU plugins may force their platform over JAX_PLATFORMS
            jax.config.update("jax_platforms", "cpu")
            _FORCED_CPU_PLATFORM = True
        platform = _resolve_accelerator(accelerator)
        try:
            all_devices = jax.devices(platform)
        except RuntimeError:
            if _FORCED_CPU_PLATFORM and platform != "cpu":
                raise RuntimeError(
                    f"accelerator='{accelerator}' requested, but an earlier "
                    "Fabric(accelerator='cpu') pinned this process to the CPU "
                    "backend; use a fresh process for accelerator runs"
                ) from None
            all_devices = jax.devices()
        if devices in ("auto", -1, "-1", None):
            n = len(all_devices)
        else:
            n = int(devices)
        if n > len(all_devices):
            raise ValueError(
                f"Requested {n} devices but only {len(all_devices)} {platform} devices exist"
            )
        self.devices: List[Any] = all_devices[:n]
        self.accelerator = platform

        # Mesh: default a single "data" axis (DDP semantics).  mesh_shape may
        # request extra axes, e.g. {"data": -1, "model": 2} for TP sharding.
        if mesh_shape:
            names = tuple(mesh_shape.keys())
            sizes = list(mesh_shape.values())
            minus = [i for i, s in enumerate(sizes) if s in (-1, None)]
            fixed = int(np.prod([s for s in sizes if s not in (-1, None)])) or 1
            if minus:
                sizes[minus[0]] = n // fixed
            dev_array = np.asarray(self.devices).reshape(tuple(int(s) for s in sizes))
            self.mesh = Mesh(dev_array, names)
        else:
            self.mesh = Mesh(np.asarray(self.devices), ("data",))
        self.data_axis = self.mesh.axis_names[0]

    # -- topology ---------------------------------------------------------
    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    @property
    def local_world_size(self) -> int:
        """Mesh devices owned by THIS process.  Data sizing must use this,
        not ``world_size``: under multi-host, each process contributes its
        own local shard and ``shard_batch`` assembles the global batch from
        the per-process locals — sampling ``per_rank * world_size`` rows per
        process would multiply the global batch by ``num_processes``.
        Single-process, this equals ``world_size``."""
        me = jax.process_index()
        return int(sum(1 for d in self.mesh.devices.flat if d.process_index == me))

    @property
    def global_rank(self) -> int:
        return jax.process_index()

    @property
    def num_processes(self) -> int:
        return jax.process_count()

    @property
    def is_global_zero(self) -> bool:
        return self.global_rank == 0

    @property
    def device(self) -> Any:
        return self.devices[0]

    @property
    def host_device(self) -> Any:
        """The host (CPU) device used for the env-interaction "player" copy
        of the policy.  Accelerator dispatch latency (100ms+ on tunneled
        TPUs, nontrivial even on-pod) makes per-env-step device round-trips
        the dominant cost of RL rollouts; inference for action selection runs
        on host and the train step refreshes the host params once per
        iteration — the single-process analogue of the reference's decoupled
        player/trainer split (reference: sheeprl/algos/ppo/ppo_decoupled.py)."""
        return jax.local_devices(backend="cpu")[0]

    def to_host(self, tree: Any) -> Any:
        """Copy a pytree to the host CPU device (one bulk transfer)."""
        return self.copy_to(tree, self.host_device)

    def copy_to(self, tree: Any, device: Any) -> Any:
        """Copy a pytree onto ``device``.

        ALWAYS a real copy: when the source already lives on the target
        device, ``device_put`` would be a no-op alias — and the training
        step donates its params input, which would invalidate the player's
        copy mid-rollout.  ``.copy()`` breaks the alias.

        Cross-platform trees (the host-player param pull) take the PACKED
        path: per-leaf transfers cost one link round-trip each (~65 ms over
        the axon tunnel — a ~40-leaf player tree paid ~2.6 s per refresh),
        so same-dtype leaves are flattened into one device-side buffer per
        dtype, moved in one transfer, and split on the target.
        """
        # chaos-drill injection site: raise simulates a dropped tunnel link
        # mid-param-pull, latency a congested one (no-op unless a fault plan
        # targets fabric.copy_to)
        from sheeprl_tpu.resilience.faults import fault_point

        fault_point("fabric.copy_to")
        leaves, treedef = jax.tree.flatten(tree)
        if all(isinstance(x, jax.Array) and x.is_fully_addressable for x in leaves):
            # replicated multi-device params (any real mesh) carry the full
            # value in every shard — pack from the process-local one
            single = [
                x if len(x.devices()) == 1
                else (x.addressable_shards[0].data if x.sharding.is_fully_replicated else None)
                for x in leaves
            ]
            src = {next(iter(x.devices())) for x in single if x is not None}
            if (
                len(leaves) > 1
                and all(x is not None for x in single)
                and len(src) == 1
                and next(iter(src)).platform != device.platform
            ):
                return treedef.unflatten(_packed_copy(single, device))

        def put(x: Any) -> Any:
            if isinstance(x, jax.Array) and not x.is_fully_addressable:
                # multi-host global array: device_put rejects it.  Replicated
                # arrays (params) carry the FULL value in every local shard —
                # copy from the process-local one.
                if not x.sharding.is_fully_replicated:
                    raise ValueError(
                        "copy_to got a non-replicated multi-host array; only "
                        "replicated (player/param) trees can be copied to a "
                        "single device"
                    )
                x = x.addressable_shards[0].data
            if isinstance(x, jax.Array) and x.committed and set(x.devices()) == {device}:
                return x.copy()
            out = jax.device_put(x, device)
            if (
                isinstance(x, jax.Array)
                and len(x.devices()) > 1
                and next(iter(x.devices())).platform == device.platform
            ):
                # same-platform mesh → single device: device_put may be a
                # ZERO-COPY alias of the shard already living on `device`
                # (measured on jax 0.4.37 CPU).  The train step donates the
                # source params, which would invalidate the player's "copy"
                # mid-rollout — break the alias.  Cross-platform transfers
                # (the production TPU→host pull) always materialize and skip
                # this extra dispatch.
                out = out.copy()
            return out

        return jax.tree.map(put, tree)

    def player_device(self, cfg: Any) -> Any:
        """The device the env-interaction player runs on.

        ``algo.player.device=host`` (default) pins rollout inference to the
        host CPU — the right call when device dispatch latency dominates
        (tunneled chips, small models).  ``accelerator`` runs the player on
        the first mesh device instead — the right call for big pixel
        encoders on-pod, where the host would become the bottleneck."""
        choice = (cfg.algo.get("player", {}) or {}).get("device", "host")
        if choice == "accelerator":
            # PROCESS-LOCAL first device: self.device is globally enumerated
            # and non-addressable from worker hosts in multi-host runs (the
            # on-pod scenario this option exists for)
            local = [d for d in jax.local_devices() if d.platform == self.accelerator]
            return local[0] if local else self.device
        if choice != "host":
            raise ValueError(f"algo.player.device must be 'host' or 'accelerator', got {choice!r}")
        return self.host_device

    # -- sharding helpers --------------------------------------------------
    def sharding(self, *spec: Any) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    @property
    def batch_sharded(self) -> NamedSharding:
        """Shard the leading axis over the data axis of the mesh."""
        return NamedSharding(self.mesh, P(self.data_axis))

    def shard_batch(self, tree: Any, axis: int = 0) -> Any:
        """Place a host batch on device, split along ``axis`` over the mesh.

        Single-process: a plain ``device_put`` onto the mesh-wide sharding.
        Multi-host (DCN): each process holds its *own* locally-sampled shard,
        and ``device_put`` onto a non-fully-addressable sharding is not the
        sanctioned path — assemble the global array from per-process locals
        via ``multihost_utils.host_local_array_to_global_array`` instead.
        """
        multi_host = self.num_processes > 1
        if multi_host:
            from jax.experimental import multihost_utils

        def put(x: Any) -> Any:
            spec = [None] * np.ndim(x)
            if np.ndim(x) > axis:
                # validate HERE, not in XLA: an indivisible batch used to
                # surface as an opaque "sharding ... is not divisible" deep
                # inside device_put/compile
                if not multi_host:
                    n = int(self.mesh.shape[self.data_axis])
                    dim = int(np.shape(x)[axis])
                    if dim % n != 0:
                        raise ValueError(
                            f"shard_batch: leaf of shape {np.shape(x)} cannot "
                            f"shard axis {axis} ({dim} rows) over the "
                            f"'{self.data_axis}' mesh axis ({n} devices); batch/"
                            f"env counts must be multiples of the data-parallel "
                            f"degree (mesh {dict(self.mesh.shape)})"
                        )
                spec[axis] = self.data_axis
            pspec = P(*spec)
            if multi_host:
                return multihost_utils.host_local_array_to_global_array(
                    np.asarray(x), self.mesh, pspec
                )
            return jax.device_put(x, NamedSharding(self.mesh, pspec))

        return jax.tree.map(put, tree)

    def replicate(self, tree: Any) -> Any:
        """Replicate a pytree (params/opt state) across the mesh."""
        return jax.device_put(tree, self.replicated)

    # -- tensor parallelism ------------------------------------------------
    @property
    def model_axis(self) -> Optional[str]:
        """Name of the tensor-parallel mesh axis, or None when the mesh has
        no ``model`` axis of size > 1 (``fabric.mesh_shape={data: -1, model: k}``)."""
        if "model" in self.mesh.axis_names and self.mesh.shape["model"] > 1:
            return "model"
        return None

    @property
    def pipeline_axis(self) -> Optional[str]:
        """Name of the pipeline mesh axis, or None when the mesh has no
        ``pipeline`` axis of size > 1
        (``fabric.mesh_shape={data: d, pipeline: s, model: k}`` — the stage
        sub-groups of parallel/pipeline.py, docs/pipeline.md)."""
        if "pipeline" in self.mesh.axis_names and self.mesh.shape["pipeline"] > 1:
            return "pipeline"
        return None

    @property
    def sharding_rules(self) -> Tuple[Any, ...]:
        """The resolved partition-rule table (``parallel/sharding.py``):
        user ``sharding.rules`` overrides prepended to the selected base
        table — the per-algo curated table under ``table: auto`` (DreamerV3
        family: RSSM dense stacks, decoder deconvs, actor/critic MLPs), or
        the legacy size-threshold fallback parameterized by the
        ``tp_min_param_size`` compat knob.  With a ``pipeline`` mesh axis
        the table is composed through
        :func:`sheeprl_tpu.parallel.pipeline.compose_pipeline_rules`: every
        model-sharded dim tiles over the ``(pipeline, model)`` product so
        each stage sub-group owns its weight slice."""
        if self._sharding_rules is None:
            from sheeprl_tpu.parallel.sharding import resolve_rules

            rules = resolve_rules(
                self.sharding_cfg, tp_min_param_size=self.tp_min_param_size
            )
            if self.pipeline_axis is not None:
                from sheeprl_tpu.parallel.pipeline import compose_pipeline_rules

                rules = compose_pipeline_rules(
                    rules,
                    pipeline_axis=self.pipeline_axis,
                    has_model=self.model_axis is not None,
                )
            self._sharding_rules = rules
        return self._sharding_rules

    def param_sharding(
        self, tree: Any, min_size: Optional[int] = None, rules: Optional[Any] = None
    ) -> Any:
        """Per-leaf ``NamedSharding``s for a param-shaped pytree, resolved
        through :func:`sheeprl_tpu.parallel.sharding.match_partition_rules`
        over :attr:`sharding_rules` (regex on tree path → ``PartitionSpec``,
        first match wins, unmatched/scalar leaves replicate over the whole
        mesh).  GSPMD propagates the annotations through the train step and
        inserts the matching collectives (scaling-book recipe: annotate
        weights, let XLA place the all-gathers/psums).

        With no ``model`` axis every leaf is replicated, so this is a strict
        generalization of ``replicate``.  Every produced spec is validated
        against the mesh up front (axis exists, dims divide) — the
        ``sharding.undivisible`` policy decides between a clear error and a
        demotion to replicated; XLA never sees an unplaceable spec.

        ``min_size`` is the ``tp_min_param_size`` compat hook: passing it
        explicitly selects the legacy size-threshold table at that
        threshold, bypassing the configured rules."""
        if self.model_axis is None and self.pipeline_axis is None:
            return jax.tree.map(lambda _: self.replicated, tree)
        if self.num_processes > 1:
            # the player-sync path (copy_to/to_host) materializes params on
            # one device from the process-local replica — a column-sharded
            # array has no such replica across hosts.  Multi-host TP/PP needs
            # a gather-to-host protocol; fail with the fix spelled out
            # instead of crashing at the first player refresh.
            raise NotImplementedError(
                "model sharding (fabric.mesh_shape with a 'model' or 'pipeline' "
                "axis) is currently single-controller only; multi-host runs "
                "must use a pure data mesh (drop mesh_shape or set model: 1 "
                "and pipeline: 1)"
            )
        from sheeprl_tpu.parallel import sharding as shd

        if rules is None:
            rules = (
                shd.size_threshold_rules(int(min_size))
                if min_size is not None
                else self.sharding_rules
            )
        undivisible = str(self.sharding_cfg.get("undivisible", "replicate"))
        specs = shd.partition_specs(rules, tree, self.mesh, undivisible=undivisible)
        if self.sharding_cfg.get("explain"):
            self.print(shd.explain(rules, tree, self.mesh, undivisible=undivisible))
        return shd.named_sharding_tree(self.mesh, specs)

    def shard_params(
        self, tree: Any, min_size: Optional[int] = None, rules: Optional[Any] = None
    ) -> Any:
        """Place a param-shaped pytree per ``param_sharding``.  Also correct
        for optimizer states: Adam/RMSProp moments live under tree paths
        containing the same module/kernel suffix their params do, so the
        same regex rules place them consistently with their params."""
        return jax.device_put(tree, self.param_sharding(tree, min_size, rules))

    def explain_sharding(self, tree: Any, title: str = "partition rules") -> str:
        """Human-readable resolved spec per leaf (``sharding.explain`` and
        interactive debugging): which rule matched, what got demoted, what
        stays replicated."""
        from sheeprl_tpu.parallel import sharding as shd

        return shd.explain(
            self.sharding_rules,
            tree,
            self.mesh,
            undivisible=str(self.sharding_cfg.get("undivisible", "replicate")),
            title=title,
        )

    def setup_module(self, tree: Any) -> Any:  # reference-API parity alias
        return self.replicate(tree)

    def jit(
        self,
        fn: Callable,
        in_shardings: Any = None,
        out_shardings: Any = None,
        donate_argnums: Tuple[int, ...] = (),
        static_argnums: Tuple[int, ...] = (),
    ) -> Callable:
        """``jax.jit`` bound to this fabric's mesh."""
        return jax.jit(
            fn,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            donate_argnums=donate_argnums,
            static_argnums=static_argnums,
        )

    def compile(
        self,
        fn: Callable,
        *,
        name: Optional[str] = None,
        static_argnums: Tuple[int, ...] = (),
        static_argnames: Tuple[str, ...] = (),
        donate_argnums: Tuple[int, ...] = (),
        in_shardings: Any = None,
        out_shardings: Any = None,
        max_recompiles: Optional[int] = None,
    ) -> Any:
        """The compile-once entry point (see ``parallel/compile.py``):
        returns an :class:`~sheeprl_tpu.parallel.compile.AOTFunction` whose
        executables are AOT-lowered/compiled per abstract signature, counted
        in the recompile detector, and warmable from :attr:`compile_pool`.
        Drop-in replacement for decorating ``fn`` with ``jax.jit``.

        ``in_shardings``/``out_shardings`` take ``NamedSharding`` pytrees
        (``None`` entries = unspecified).  Train phases pass their param and
        opt-state sharding trees on both sides plus ``donate_argnums`` so the
        partition-rules placement is pinned across updates and the state is
        updated in place — build the tuples with
        :func:`sheeprl_tpu.parallel.compile.state_io_shardings`."""
        from sheeprl_tpu.parallel.compile import compile_once

        return compile_once(
            fn,
            name=name,
            static_argnums=static_argnums,
            static_argnames=static_argnames,
            donate_argnums=donate_argnums,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
            max_recompiles=max_recompiles,
        )

    @property
    def compile_pool(self) -> Any:
        """Process-wide parallel compile warm-up pool (lazily created)."""
        from sheeprl_tpu.parallel.compile import get_compile_pool

        return get_compile_pool()

    # -- host collectives --------------------------------------------------
    #
    # Two transports:
    # * TPU pods: XLA collectives over ICI/DCN via ``multihost_utils`` —
    #   native, fast, and the path real deployments exercise.
    # * CPU multiprocess (the test rig): the ``jax.distributed``
    #   coordination-service KV store.  XLA-CPU gloo collectives silently
    #   zero-fill payloads when the host is CPU-oversubscribed (observed on
    #   a 2-core container: the int64 length psum lands, the back-to-back
    #   uint8 payload psum arrives all-zero on non-source ranks, no error
    #   raised) — host OBJECT exchange is control-plane traffic, which the
    #   coordination service transports reliably over gRPC.
    _kv_seq: int = 0

    def _coordination_client(self) -> Any:
        """The jax.distributed KV client when host objects should ride it
        (CPU backend + real multiprocess), else None."""
        if self.num_processes == 1 or self.accelerator != "cpu":
            return None
        from jax._src import distributed

        if distributed.global_state.client is None:
            return None
        # the thread-safe wrapper: raw client calls from two threads (the
        # PeerWatchdog beating during a host collective) segfault
        from sheeprl_tpu.parallel.distributed import _SafeKV

        return _SafeKV(distributed.global_state.client)

    @staticmethod
    def _kv_timeout_ms() -> int:
        # generous: a trainer blocks here for a full player rollout in the
        # dedicated decoupled topology
        return int(float(os.environ.get("SHEEPRL_KV_TIMEOUT_S", 600)) * 1000)

    def _next_kv_seq(self) -> int:
        # collective calls execute in the same order on every rank, so a
        # per-rank counter stays in lockstep and namespaces each exchange
        seq, self._kv_seq = self._kv_seq, self._kv_seq + 1
        return seq

    def _kv_all_gather(self, client: Any, obj: Any) -> List[Any]:
        seq, timeout = self._next_kv_seq(), self._kv_timeout_ms()
        prefix = f"sheeprl_tpu/ag/{seq}"
        mine = f"{prefix}/{self.global_rank:08d}"
        client.key_value_set_bytes(mine, bytes(_pickle_to_u8(obj).tobytes()))
        out = [
            _u8_to_obj(
                np.frombuffer(
                    client.blocking_key_value_get_bytes(f"{prefix}/{r:08d}", timeout),
                    dtype=np.uint8,
                )
            )
            for r in range(self.num_processes)
        ]
        # every rank has read every entry once the barrier clears; each rank
        # deletes its own key so the KV store stays bounded on long runs
        client.wait_at_barrier(f"{prefix}/done", timeout)
        client.key_value_delete(mine)
        return out

    def _kv_broadcast(self, client: Any, obj: Any, src: int) -> Any:
        seq, timeout = self._next_kv_seq(), self._kv_timeout_ms()
        key = f"sheeprl_tpu/bc/{seq}"
        if self.global_rank == src:
            client.key_value_set_bytes(key, bytes(_pickle_to_u8(obj).tobytes()))
            out = obj
        else:
            out = _u8_to_obj(
                np.frombuffer(
                    client.blocking_key_value_get_bytes(key, timeout), dtype=np.uint8
                )
            )
        client.wait_at_barrier(f"{key}/done", timeout)
        if self.global_rank == src:
            client.key_value_delete(key)
        return out

    def all_gather_object(self, obj: Any) -> List[Any]:
        if self.num_processes == 1:
            return [obj]
        client = self._coordination_client()
        if client is not None:
            return self._kv_all_gather(client, obj)
        from jax.experimental import multihost_utils

        payload = _pickle_to_u8(obj)
        # process_allgather needs equal shapes: agree on max length, pad.
        lengths = multihost_utils.process_allgather(
            np.asarray([payload.size], dtype=np.int64)
        ).reshape(-1)
        max_len = int(lengths.max())
        padded = np.zeros(max_len, dtype=np.uint8)
        padded[: payload.size] = payload
        gathered = multihost_utils.process_allgather(padded)
        return [
            _u8_to_obj(np.asarray(row[: int(n)]))
            for row, n in zip(np.atleast_2d(gathered), lengths)
        ]

    def broadcast_object(self, obj: Any, src: int = 0) -> Any:
        if self.num_processes == 1:
            return obj
        client = self._coordination_client()
        if client is not None:
            return self._kv_broadcast(client, obj, src)
        from jax.experimental import multihost_utils

        is_source = self.global_rank == src
        payload = _pickle_to_u8(obj) if is_source else None
        # broadcast_one_to_all sources from process 0 unless told otherwise —
        # src != 0 (e.g. the trainer→player weight refresh of the dedicated
        # decoupled topology) must pass is_source explicitly
        length = multihost_utils.broadcast_one_to_all(
            np.asarray([0 if payload is None else payload.size], dtype=np.int64),
            is_source=is_source,
        )[0]
        buf = payload if payload is not None else np.zeros(int(length), dtype=np.uint8)
        out = multihost_utils.broadcast_one_to_all(buf, is_source=is_source)
        if is_source:
            # skip re-deserializing our own payload (sync-A rollouts are
            # ~100MB/iteration in the dedicated decoupled topology)
            return obj
        return _u8_to_obj(np.asarray(out))

    def barrier(self) -> None:
        if self.num_processes > 1:
            client = self._coordination_client()
            if client is not None:
                client.wait_at_barrier(
                    f"sheeprl_tpu/barrier/{self._next_kv_seq()}", self._kv_timeout_ms()
                )
                return
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("sheeprl_tpu_barrier")

    # -- checkpoint callbacks ---------------------------------------------
    def register_callback(self, callback: Any) -> None:
        self.callbacks.append(callback)

    def call(self, hook: str, **kwargs: Any) -> None:
        for cb in self.callbacks:
            fn = getattr(cb, hook, None)
            if fn is not None:
                fn(fabric=self, **kwargs)

    # -- persistence -------------------------------------------------------
    def get_checkpoint_manager(self, cfg: Any, log_dir: Union[str, os.PathLike]) -> Any:
        """The run's :class:`~sheeprl_tpu.checkpoint.CheckpointManager`,
        created on first call (train loops bind it right after resolving
        their ``log_dir``) and cached on the fabric so the checkpoint
        callback can reach it through ``fabric.checkpoint_manager``."""
        if self.checkpoint_manager is None:
            from sheeprl_tpu.checkpoint import CheckpointManager

            self.checkpoint_manager = CheckpointManager(self, cfg, log_dir)
        return self.checkpoint_manager

    def save(self, path: Union[str, os.PathLike], state: Dict[str, Any]) -> None:
        """Legacy single-file save (rank 0 only + barrier).  Train loops now
        checkpoint through the manager/commit protocol instead; this remains
        for tests, tools, and external callers."""
        from sheeprl_tpu.utils.checkpoint import save_checkpoint

        if self.is_global_zero:
            save_checkpoint(path, state)
        self.barrier()

    def load(self, path: Union[str, os.PathLike]) -> Dict[str, Any]:
        """Load a legacy ``.ckpt`` file or a committed snapshot directory
        (this rank's shard, falling back to shard 0)."""
        from sheeprl_tpu.utils.checkpoint import load_checkpoint

        return load_checkpoint(path, rank=self.global_rank)

    # -- misc ---------------------------------------------------------------
    def print(self, *args: Any, **kwargs: Any) -> None:
        if self.is_global_zero:
            print(*args, **kwargs)

    def seed_everything(self, seed: int) -> jax.Array:
        """Seed host RNGs PER-RANK and return the SHARED jax key.

        The returned key seeds agent init and the train-dispatch stream,
        which must be identical on every process: replicated inputs of the
        global program (params, train keys) have to agree across ranks.
        Host-side RNG (replay sampling, random prefill actions) must DIFFER
        per rank or multi-host data parallelism collects/samples the same
        data ``num_processes`` times.  Per-rank player sampling keys are
        derived in the loops via ``fold_in(key, global_rank)``."""
        np.random.seed(seed + self.global_rank)
        import random

        random.seed(seed + self.global_rank)
        return jax.random.PRNGKey(seed)

    def env_sharding_plan(self, num_envs: int, algo: str = "") -> Tuple[bool, int]:
        """Whether per-rank env rollouts can shard over the data axis, and
        the GLOBAL env count the train program then sees.  Multi-host
        requires shardability — validated here ONCE, before any rollout is
        collected."""
        sharded = num_envs % self.local_world_size == 0
        if not sharded and self.num_processes > 1:
            raise ValueError(
                f"multi-host {algo or 'training'} requires env.num_envs "
                f"({num_envs}) divisible by the local device count "
                f"({self.local_world_size})"
            )
        return sharded, num_envs * (self.num_processes if sharded else 1)


class PlayerSync:
    """Overlap env interaction with (async-dispatched) device training.

    JAX dispatches the train phase asynchronously; what serializes the loop
    is pulling the fresh params to the player right after the dispatch — the
    next ``player_step`` then blocks on the whole train phase.  In deferred
    mode the pull happens at the START of the next optimization window
    instead: the env steps of window N+1 run on window N-1's weights while
    the device trains window N — the single-controller analogue of the
    reference's decoupled trainer→player broadcast
    (reference: sheeprl/algos/ppo/ppo_decoupled.py:32-365,
    sac_decoupled.py:250-305).  With ``sync_every=1`` that is one training
    window of weight staleness — the decoupled topology's semantics; set
    ``algo.player.deferred_sync=False`` for the strict coupled behavior.

    ``sync_every`` additionally rate-limits refreshes to every k-th
    TRAINING window (``algo.player.sync_every``, sac_decoupled sets 10);
    the player then acts on weights up to k (+1 when deferred) training
    windows old — the reference's player↔trainer refresh cadence.
    """

    def __init__(self, fabric: "Fabric", cfg: Any, extract: Callable[[Any], Any]):
        player_cfg = cfg.algo.get("player", {}) or {}
        self.fabric = fabric
        self.extract = extract
        self.device = fabric.player_device(cfg)
        self.deferred = bool(player_cfg.get("deferred_sync", True))
        self.sync_every = max(1, int(player_cfg.get("sync_every", 1)))
        self._pending: Any = None
        self._windows = 0  # completed training windows (dispatches)
        # staleness accounting (ISSUE 12 satellite): which window produced
        # the weights the player is CURRENTLY acting with (0 = init params)
        self._player_version = 0
        self._pending_version = 0
        self.staleness_max = 0

    def init(self, params: Any) -> Any:
        self._player_version = self._windows
        self._pending = None
        return self.fabric.copy_to(self.extract(params), self.device)

    @property
    def staleness(self) -> int:
        """Completed training windows the player's weights are behind —
        the deferred-sync/cadence staleness, previously invisible.  Bound:
        ``sync_every - 1`` with immediate sync (the off-cadence windows
        before each refresh), ``sync_every`` deferred (the pending params
        land one ``before_dispatch`` later)."""
        return self._windows - self._player_version

    def _observe_staleness(self) -> None:
        self.staleness_max = max(self.staleness_max, self.staleness)

    def metrics(self) -> Dict[str, float]:
        """``Player/*`` staleness gauges for ``flush_metrics`` callers."""
        return {
            "Player/param_staleness_windows": float(self.staleness),
            "Player/param_staleness_max": float(self.staleness_max),
        }

    def before_dispatch(self, player_params: Any) -> Any:
        """Pull the previous window's (long since finished) train output."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            self._player_version = self._pending_version
            self._observe_staleness()
            return self.fabric.copy_to(self.extract(pending), self.device)
        self._observe_staleness()
        return player_params

    def after_dispatch(self, params: Any, player_params: Any) -> Any:
        # Gate on COMPLETED TRAINING WINDOWS, not the env-loop update counter:
        # with a fractional replay_ratio the Ratio governor fires training on
        # a fixed update parity, and an `update % sync_every` gate can then
        # systematically never coincide with a training update (player runs
        # on init weights forever).
        self._windows += 1
        if self._windows % self.sync_every != 0:
            self._observe_staleness()
            return player_params
        if self.deferred:
            self._pending = params
            self._pending_version = self._windows
            self._observe_staleness()
            return player_params
        self._player_version = self._windows
        self._observe_staleness()
        return self.fabric.copy_to(self.extract(params), self.device)

    # -- checkpointing ------------------------------------------------------
    def state_dict(self) -> Dict[str, int]:
        """Cadence position, so a resumed run keeps FUTURE refreshes on the
        same training-window parity as an uninterrupted one.  ``_pending``
        is deliberately NOT saved: ``init`` on resume starts the player from
        the checkpointed (latest) params — so at the resume point itself the
        player is one refresh AHEAD of an uninterrupted run (which would
        still act on the last on-cadence weights); exact mid-interval
        staleness is not reproduced, only the refresh schedule."""
        return {"windows": self._windows}

    def load_state_dict(self, state: Dict[str, int]) -> None:
        self._windows = int(state.get("windows", 0))
        # resume starts the player from the checkpointed (latest) params —
        # see state_dict: staleness restarts at zero, only cadence persists
        self._player_version = self._windows
        self._pending = None


def _packed_copy(leaves: Any, device: Any) -> Any:
    """Move a flat list of same-device arrays to ``device`` in ONE transfer
    per dtype: flatten+concatenate on the SOURCE device (one fused program),
    ship the packed buffer, split+reshape on the target.  Values are
    bit-identical to per-leaf ``device_put`` (no casts — leaves group by
    exact dtype, and weak-typed leaves go per-leaf: concatenate would
    strip weak_type and change downstream promotion).
    See ``Fabric.copy_to`` for why this exists."""
    by_dtype: Dict[Any, list] = {}
    for i, x in enumerate(leaves):
        by_dtype.setdefault((x.dtype, bool(getattr(x, "weak_type", False))), []).append(i)
    out: list = [None] * len(leaves)
    for (dtype, weak), idxs in by_dtype.items():
        if len(idxs) == 1 or weak:
            for i in idxs:
                out[i] = jax.device_put(leaves[i], device)
            continue
        packed = jnp.concatenate([jnp.ravel(leaves[i]) for i in idxs])
        packed = jax.device_put(packed, device)
        offset = 0
        for i in idxs:
            n = leaves[i].size
            out[i] = packed[offset : offset + n].reshape(leaves[i].shape)
            offset += n
    return out


def _pickle_to_u8(obj: Any) -> np.ndarray:
    import pickle

    return np.frombuffer(pickle.dumps(obj), dtype=np.uint8).copy()


def _u8_to_obj(arr: np.ndarray) -> Any:
    import pickle

    return pickle.loads(arr.tobytes())


# process-wide latch for the tp_min_param_size deprecation notice
_TP_MIN_PARAM_SIZE_WARNED = False


def build_fabric(cfg: Any) -> Fabric:
    """Instantiate the runtime from ``cfg.fabric`` (+ register callbacks)."""
    global _TP_MIN_PARAM_SIZE_WARNED
    fab_cfg = cfg.fabric
    # distributed init FIRST: jax.distributed.initialize must run before
    # the first backend touch (Fabric.__init__ calls jax.devices()), or the
    # process binds a single-host backend and can never join the pod
    from sheeprl_tpu.parallel.distributed import ensure_distributed

    ensure_distributed(cfg)
    cache_dir = fab_cfg.get("compilation_cache_dir")
    if cache_dir:
        # persistent XLA compilation cache: the 20-40s first compile of a
        # Dreamer train window is paid once per (program, jaxlib, topology),
        # not once per process — essential for short driver/bench runs.
        # (The min-compile-time threshold is left at JAX's default so the
        # JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS env override is honored.)
        if jax.config.jax_compilation_cache_dir != str(cache_dir):
            jax.config.update("jax_compilation_cache_dir", str(cache_dir))
            # JAX memoizes the cache backend on first use; with the
            # default-on cache (ensure_compilation_cache) an earlier Fabric
            # may have initialized it at another path — drop it so the
            # explicitly configured directory actually receives entries
            try:
                from jax._src.compilation_cache import reset_cache

                reset_cache()
            except Exception:
                pass
    if "tp_min_param_size" in fab_cfg and not _TP_MIN_PARAM_SIZE_WARNED:
        # fire ONCE per process, not per build_fabric call: long runs build
        # fabrics repeatedly (supervisor relaunch probes, bench A/B arms,
        # player clones) and a per-call DeprecationWarning floods the log —
        # and "default"-filtered warnings dedupe per call SITE, which this
        # single callsite defeats.  Pinned by
        # tests/test_sharding/test_deprecation.py.  In a pod, only rank 0
        # speaks: the knob is global config, so N hosts repeating the same
        # deprecation is noise (rank_zero_warn also latches per-process).
        from sheeprl_tpu.parallel.distributed import rank_zero_warn

        _TP_MIN_PARAM_SIZE_WARNED = True
        rank_zero_warn(
            "fabric.tp_min_param_size is deprecated: parameter placement is "
            "now decided by the sharding rules engine (sharding.rules / "
            "sharding.table, see docs/sharding.md). The knob still "
            "parameterizes the legacy 'size_threshold' fallback table only.",
            DeprecationWarning,
            key="fabric.tp_min_param_size",
        )
    # the sharding config group travels with the algo name so `table: auto`
    # can resolve the curated per-algo rule table at first use
    sharding_cfg = dict(cfg.get("sharding") or {})
    sharding_cfg.setdefault("algo", (cfg.get("algo") or {}).get("name"))
    fabric = Fabric(
        devices=fab_cfg.get("devices", 1),
        num_nodes=fab_cfg.get("num_nodes", 1),
        strategy=fab_cfg.get("strategy", "auto"),
        accelerator=fab_cfg.get("accelerator", "auto"),
        precision=fab_cfg.get("precision", "32-true"),
        callbacks=fab_cfg.get("callbacks", {}),
        mesh_shape=fab_cfg.get("mesh_shape", None),
        tp_min_param_size=fab_cfg.get("tp_min_param_size", 2**18),
        sharding=sharding_cfg,
    )
    if fabric.num_processes > 1:
        _validate_pod_device_view(fabric)
    cb_cfg = fab_cfg.get("callbacks", {}) or {}
    if "checkpoint" in cb_cfg:
        from sheeprl_tpu.utils.callback import CheckpointCallback

        fabric.register_callback(CheckpointCallback(keep_last=cb_cfg["checkpoint"].get("keep_last", 5)))
    # graceful preemption (SIGTERM/SIGINT latch) is armed by the FIRST
    # CheckpointManager.should_save poll, not here: surfaces that never poll
    # the latch (dedicated lockstep topologies, the evaluation CLI) must keep
    # the default signal disposition — latching a signal nobody reads would
    # swallow the preemption grace window entirely
    return fabric


def _validate_pod_device_view(fabric: Fabric) -> None:
    """Multi-process sanity of the per-process device view.

    Hard requirements: this process must SEE the whole pod (a process
    whose ``jax.devices()`` is local-only never initialized the
    distributed backend) and must own at least one local device.  Soft
    requirement (warned, rank 0 only): the mesh should cover every
    process — a mesh that excludes a rank's devices is legal for
    host-collective-only fabrics but no pod topology can train on it.
    """
    from sheeprl_tpu.parallel.distributed import rank_zero_warn

    procs_seen = {d.process_index for d in jax.devices(fabric.accelerator)}
    if len(procs_seen) < fabric.num_processes:
        raise RuntimeError(
            f"fabric.distributed: jax reports {fabric.num_processes} processes but this "
            f"rank's device view covers only processes {sorted(procs_seen)} — "
            "distributed init ran after a backend touch, or the pod is partitioned"
        )
    mesh_procs = {d.process_index for d in fabric.mesh.devices.flat}
    if len(mesh_procs) < fabric.num_processes:
        rank_zero_warn(
            f"fabric.devices={len(fabric.devices)} leaves some processes with no mesh "
            "devices; pod topologies need fabric.devices=auto (the global mesh)",
            key="fabric.pod_device_view",
        )


def trainer_device_count(fabric: Fabric, player_process: int = 0) -> int:
    """Number of mesh devices in the trainer group of the dedicated
    decoupled topology — THE sizing rule both sides of the protocol share
    (the player can't build the trainer fabric itself but must agree on
    ``batch_size = per_rank_batch_size * trainer_world``)."""
    return sum(1 for d in fabric.mesh.devices.flat if d.process_index != player_process)


def clone_with_devices(fabric: Fabric, devices: List[Any]) -> Fabric:
    """A fabric sharing ``fabric``'s policy state (precision, callbacks,
    sharding config, checkpoint manager) whose 1-D ``data`` mesh spans only
    ``devices`` — THE device-subset surgery shared by the dedicated-player
    trainer group and the Sebulba learner sub-mesh.  New ``Fabric.__init__``
    state must be mirrored here, in ONE place."""
    sub = Fabric.__new__(Fabric)
    sub.strategy = fabric.strategy
    sub.precision = fabric.precision
    sub.callbacks = fabric.callbacks
    sub._callback_cfg = fabric._callback_cfg
    sub.devices = list(devices)
    sub.accelerator = fabric.accelerator
    sub.mesh = Mesh(np.asarray(list(devices)), ("data",))
    sub.data_axis = "data"
    sub.tp_min_param_size = fabric.tp_min_param_size
    sub.sharding_cfg = dict(fabric.sharding_cfg)
    sub._sharding_rules = None
    sub.checkpoint_manager = fabric.checkpoint_manager
    return sub


def get_trainer_fabric(fabric: Fabric, player_process: int = 0) -> Fabric:
    """A fabric whose mesh spans only the devices NOT owned by the dedicated
    player process — the trainer group of the cross-process decoupled
    topology (reference: the trainer-only ``optimization_pg`` DDP subgroup,
    sheeprl/algos/ppo/ppo_decoupled.py:645-666).  Programs jitted on this
    mesh must be launched by every trainer process and by no other."""
    trainer_devices = [
        d for d in fabric.mesh.devices.flat if d.process_index != player_process
    ]
    if not trainer_devices:
        raise ValueError(
            "dedicated-player topology needs at least one device owned by a "
            "non-player process (got none; run with >= 2 processes)"
        )
    return clone_with_devices(fabric, trainer_devices)


def get_single_device_fabric(fabric: Fabric, device: Optional[Any] = None) -> Fabric:
    """A fabric pinned to one device, for inference-only "player" models
    (reference: sheeprl/utils/fabric.py:8-35).  Pass ``device`` to pin to a
    specific one — e.g. ``fabric.host_device`` for the dedicated player of
    the cross-process decoupled topology."""
    device = fabric.device if device is None else device
    single = Fabric.__new__(Fabric)
    single.strategy = fabric.strategy
    single.precision = fabric.precision
    single.callbacks = []
    single._callback_cfg = {}
    single.devices = [device]
    single.accelerator = fabric.accelerator
    single.mesh = Mesh(np.asarray([device]), ("data",))
    single.data_axis = "data"
    single.tp_min_param_size = fabric.tp_min_param_size
    single.sharding_cfg = dict(fabric.sharding_cfg)
    single._sharding_rules = None
    single.checkpoint_manager = None
    return single


def host_tree_to_mesh(tree: Any, mesh: Mesh, axis: int = 0, shard: bool = True) -> Any:
    """Assemble global device arrays ON a (possibly multi-process) mesh from
    host numpy values every participating process holds in full — the
    trainer-side batch landing of the dedicated decoupled topology.  Uses
    ``jax.make_array_from_callback``: no communication, each process serves
    its addressable shards.  ``shard=False`` replicates instead (the
    fallback when the batch axis does not divide the mesh)."""

    def put(x: Any) -> Any:
        x = np.asarray(x)
        spec: List[Any] = [None] * x.ndim
        if shard and x.ndim > axis:
            spec[axis] = mesh.axis_names[0]
        sh = NamedSharding(mesh, P(*spec))
        return jax.make_array_from_callback(x.shape, sh, lambda idx, _x=x: _x[idx])

    return jax.tree.map(put, tree)


def fetch_local(tree: Any) -> Any:
    """Pull a (replicated) device pytree to host numpy via the process-local
    shard — works on non-fully-addressable multi-process arrays where
    ``np.asarray`` alone would fail."""
    return jax.tree.map(
        lambda x: np.asarray(x.addressable_shards[0].data)
        if isinstance(x, jax.Array)
        else np.asarray(x),
        tree,
    )
