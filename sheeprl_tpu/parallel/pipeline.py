"""MPMD-style pipeline parallelism for ≥5B world models (ROADMAP item 3).

The PR 7 rules engine shards the big matmuls over a ``model`` mesh axis, but
the RSSM's sequential scan leaves that axis idle between layers — DV3-XL
measured 8.8% MFU data-parallel-only (BENCH_TPU round 5), far from the ≥25%
target.  "Scaling Deep Learning Training with MPMD Pipeline Parallelism"
(arXiv:2412.14374) recovers exactly this idle time by splitting the model
into stages and streaming microbatches through them; the Podracer line
(arXiv:2104.06272) is the same keep-the-chips-busy discipline this repo
already applies to rollouts.  This module applies it to the update step.

Three cooperating pieces:

**Stage partitioning** — the dreamer world model splits into a linear chain
of stages (encoder → RSSM → heads/decoder).  On the mesh, a new ``pipeline``
axis composes with the existing ``data``/``model`` axes
(``fabric.mesh_shape={data: D, pipeline: S, model: K}``):
:func:`compose_pipeline_rules` rewrites the curated partition-rule table so
every ``model``-sharded weight dimension tiles over the ``(pipeline, model)``
product — the single-controller GSPMD realization of "stages mapped to mesh
sub-groups" (each sub-group owns a ``1/(S·K)`` weight slice, which is what
unlocks ≥5B world models no 2-D mesh can hold).  With a ``pipeline`` axis
and no ``model`` axis, weights tile over ``pipeline`` alone.

**1F1B microbatch schedule** — :func:`pipeline_value_and_grad` runs the
stage chain over ``pipeline.microbatches`` slices of the sequence batch in
one-forward-one-backward order (:func:`one_f_one_b`), inside the SAME traced
program as the rest of the train phase (a ``lax``-level schedule: the tick
order is unrolled at trace time, so the compile-once law is untouched —
``cache_size()==1`` across windows under the armed transfer guard).  Each
microbatch's backward runs as early as its cotangents exist, so at most
``S - s`` forward activations per stage are ever live (the 1F1B memory
bound), and the per-unit gradient accumulation chain pins XLA's liveness to
the schedule order.  Inter-stage activation buffers stay on device and are
donated in place by XLA's buffer reuse; the HOST-level analogue
(:func:`compile_stage_pair`, the per-stage measurement harness) donates them
explicitly — donating a stage output and reading it again for the backward
is the ``use-after-donate`` hazard graftlint's curated table now covers.

**Sample invariance law** — stage functions must be DETERMINISTIC and
microbatch-invariant: a PRNG draw at microbatch shape would give different
samples than the full-batch baseline (bit-streams depend on shape), turning
a scheduling choice into a numerics change.  Callers hoist all sampling
noise out of the stages (draw at full batch shape with the baseline's exact
keys, slice per microbatch — ``OneHotCategorical.rsample_from_noise``),
which is what makes DP-vs-pipelined parity hold at reassociation level
(tests/test_parallel/test_pipeline.py; tolerance tiers in
tests/test_regression/DRIFT.md).

Telemetry: the schedule's bubble fraction ``(S-1)/(M+S-1)`` is a
first-class metric (``Pipeline/bubble_frac`` through the hub;
``Phase/pipeline.stage.*`` spans from the bench harness — taxonomy in
docs/telemetry.md).  Tuning guide and schedule diagram: docs/pipeline.md.
"""

from __future__ import annotations

import operator
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = [
    "PipelineSpec",
    "resolve_pipeline",
    "one_f_one_b",
    "bubble_fraction",
    "split_microbatches",
    "merge_microbatches",
    "pipeline_value_and_grad",
    "chunked_rows",
    "compose_pipeline_rules",
    "compile_stage_pair",
    "register_pipeline_metrics",
    "PIPELINE_ALGOS",
]

#: algorithms whose train-phase builders implement the stage split.  The
#: dreamer-family loop validates against this so an enabled pipeline on an
#: unsupported algo fails at build time, not silently.
PIPELINE_ALGOS: Tuple[str, ...] = ("dreamer_v3",)

#: the canonical pipeline mesh-axis name (composes with "data"/"model")
PIPELINE_AXIS = "pipeline"


# --------------------------------------------------------------------------
# config resolution
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class PipelineSpec:
    """Resolved ``pipeline`` config group (configs/pipeline/default.yaml)."""

    stages: int = 1
    microbatches: int = 1
    axis: str = PIPELINE_AXIS
    schedule: str = "1f1b"
    #: row-chunking factor for the imagination batch's wide head
    #: evaluations (:func:`chunked_rows`); 1 = full-batch
    imagination_microbatches: int = 1

    @property
    def enabled(self) -> bool:
        return self.stages > 1 or self.microbatches > 1

    @property
    def bubble_frac(self) -> float:
        return bubble_fraction(self.stages, self.microbatches)

    def check_algo(self, algo_name: str) -> None:
        if self.enabled and algo_name not in PIPELINE_ALGOS:
            raise ValueError(
                f"pipeline parallelism (pipeline.stages={self.stages}, "
                f"pipeline.microbatches={self.microbatches}) is implemented for "
                f"{PIPELINE_ALGOS}, not '{algo_name}'; set pipeline.stages=1 "
                "and pipeline.microbatches=1 (configs/pipeline/default.yaml)"
            )

    def metrics(self) -> Dict[str, float]:
        """``Pipeline/*`` metrics for the telemetry hub."""
        if not self.enabled:
            return {}
        return {
            "Pipeline/stages": float(self.stages),
            "Pipeline/microbatches": float(self.microbatches),
            "Pipeline/bubble_frac": self.bubble_frac,
        }


def resolve_pipeline(cfg: Any) -> PipelineSpec:
    """``cfg.pipeline`` → validated :class:`PipelineSpec`.

    Accepts the full composed config or the group dict itself; a missing
    group resolves to the disabled spec (bare ``Fabric`` users, old exps)."""
    group = cfg.get("pipeline") if hasattr(cfg, "get") else None
    if group is None:
        group = {}
    stages = int(group.get("stages", 1))
    microbatches = int(group.get("microbatches", 1))
    schedule = str(group.get("schedule", "1f1b"))
    imag = int(group.get("imagination_microbatches", 1))
    if stages < 1 or microbatches < 1 or imag < 1:
        raise ValueError(
            f"pipeline.stages ({stages}), pipeline.microbatches ({microbatches}) "
            f"and pipeline.imagination_microbatches ({imag}) must all be >= 1"
        )
    if schedule != "1f1b":
        raise ValueError(
            f"pipeline.schedule='{schedule}' is not supported; the only "
            "implemented schedule is '1f1b' (docs/pipeline.md)"
        )
    if stages > 1 and microbatches < stages:
        raise ValueError(
            f"pipeline.microbatches ({microbatches}) must be >= pipeline.stages "
            f"({stages}): with fewer microbatches than stages the 1F1B schedule "
            f"is all bubble (bubble_frac="
            f"{bubble_fraction(stages, max(microbatches, 1)):.2f}); raise "
            "microbatches or lower stages"
        )
    return PipelineSpec(
        stages=stages, microbatches=microbatches,
        schedule=schedule, imagination_microbatches=imag,
    )


# --------------------------------------------------------------------------
# the 1F1B schedule
# --------------------------------------------------------------------------

def bubble_fraction(stages: int, microbatches: int) -> float:
    """Idle fraction of the 1F1B schedule: ``(S-1)/(M+S-1)``.

    ``M + S - 1`` ticks drain ``M`` microbatches through ``S`` stages; the
    ``S - 1`` ramp-up/ramp-down ticks are bubble.  Per-stage-balanced
    approximation — bench.py --mode pipeline also reports the measured
    estimate from per-stage wall times."""
    s, m = int(stages), int(microbatches)
    if s <= 1:
        return 0.0
    return (s - 1) / (m + s - 1)


def one_f_one_b(stages: int, microbatches: int) -> List[Tuple[str, int, int]]:
    """The one-forward-one-backward unit order: ``[(op, stage, microbatch)]``
    with ``op`` in ``{"F", "B"}``.

    Tick simulation of the classic non-interleaved 1F1B schedule: each stage
    runs at most one unit per tick; stage ``s`` ramps up until ``S - s``
    forwards are in flight, then alternates backward/forward (backwards
    drain towards stage 0).  Dependencies are enforced against the PREVIOUS
    tick's completions — the returned flat list (ticks concatenated in
    order) is therefore a valid execution order for
    :func:`pipeline_value_and_grad`'s trace-time unrolling, and its liveness
    profile (≤ ``S - s`` live activations at stage ``s``) is the 1F1B
    memory bound."""
    S, M = int(stages), int(microbatches)
    if S < 1 or M < 1:
        raise ValueError(f"one_f_one_b: need stages >= 1 and microbatches >= 1, got ({S}, {M})")
    order: List[Tuple[str, int, int]] = []
    f_cnt = [0] * S  # forwards completed per stage (microbatches 0..f_cnt-1)
    b_cnt = [0] * S  # backwards completed per stage
    max_ticks = 4 * S * (M + S)  # generous; the schedule needs M + S - 1
    for _ in range(max_ticks):
        if all(f == M for f in f_cnt) and all(b == M for b in b_cnt):
            return order
        f_snap, b_snap = list(f_cnt), list(b_cnt)
        progressed = False
        for s in range(S):
            in_flight = f_cnt[s] - b_cnt[s]
            cap = S - s  # 1F1B in-flight bound at stage s
            can_f = f_cnt[s] < M and (s == 0 or f_cnt[s] < f_snap[s - 1])
            can_b = (
                b_cnt[s] < M
                and b_cnt[s] < f_snap[s]
                and (s == S - 1 or b_cnt[s] < b_snap[s + 1])
            )
            if can_b and (in_flight >= cap or f_cnt[s] == M):
                order.append(("B", s, b_cnt[s]))
                b_cnt[s] += 1
                progressed = True
            elif can_f and in_flight < cap:
                order.append(("F", s, f_cnt[s]))
                f_cnt[s] += 1
                progressed = True
            elif can_b:
                order.append(("B", s, b_cnt[s]))
                b_cnt[s] += 1
                progressed = True
        if not progressed:
            raise RuntimeError(
                f"one_f_one_b: schedule wedged at f={f_cnt} b={b_cnt} "
                f"(stages={S}, microbatches={M}) — internal scheduling bug"
            )
    raise RuntimeError(
        f"one_f_one_b: schedule did not drain within {max_ticks} ticks "
        f"(stages={S}, microbatches={M}) — internal scheduling bug"
    )


# --------------------------------------------------------------------------
# microbatch plumbing
# --------------------------------------------------------------------------

def split_microbatches(tree: Any, microbatches: int, axis: int = 1) -> Any:
    """Split every leaf's ``axis`` into a LEADING microbatch axis:
    ``(..., M*b, ...) → (M, ..., b, ...)`` with contiguous row chunks
    (microbatch ``m`` holds rows ``[m*b, (m+1)*b)`` — the exact inverse of
    :func:`merge_microbatches`, so reassembled outputs keep row order).

    An indivisible batch errors HERE with the offending leaf spelled out,
    mirroring ``fabric.shard_batch``'s divisibility law — historically this
    class of mismatch surfaced as an opaque reshape error deep in XLA."""
    m = int(microbatches)

    def split(x: Any) -> Any:
        shape = jnp.shape(x)
        if len(shape) <= axis:
            raise ValueError(
                f"split_microbatches: leaf of shape {shape} has no axis {axis} to microbatch"
            )
        dim = shape[axis]
        if dim % m != 0:
            raise ValueError(
                f"pipeline: leaf of shape {shape} cannot split axis {axis} "
                f"({dim} rows) into {m} microbatches; batch sizes must be "
                f"multiples of pipeline.microbatches (the same divisibility "
                f"law as fabric.shard_batch's data axis)"
            )
        x = jnp.reshape(x, shape[:axis] + (m, dim // m) + shape[axis + 1:])
        return jnp.moveaxis(x, axis, 0)

    return jax.tree.map(split, tree)


def merge_microbatches(x: jax.Array, axis: int = 1) -> jax.Array:
    """Inverse of :func:`split_microbatches` for one stacked output:
    ``(M, ..., b, ...) → (..., M*b, ...)``."""
    x = jnp.moveaxis(x, 0, axis)
    shape = x.shape
    return jnp.reshape(x, shape[:axis] + (shape[axis] * shape[axis + 1],) + shape[axis + 2:])


def chunked_rows(fn: Callable[[jax.Array], jax.Array], x: jax.Array, chunks: int) -> jax.Array:
    """Apply a per-row ``fn`` over ``chunks`` row-chunks of ``x`` via
    ``lax.map`` — the microbatched form of the imagination batch's wide head
    evaluations (reward/value/continue over ``(H+1)·L·B`` rows).  Sequential
    chunks bound the live activation footprint to ``rows/chunks`` without
    changing any per-row value (parity is pure reassociation).  Indivisible
    row counts error with the same law as :func:`split_microbatches`."""
    c = int(chunks)
    if c <= 1:
        return fn(x)
    n = x.shape[0]
    if n % c != 0:
        raise ValueError(
            f"pipeline: imagination batch of {n} rows cannot split into "
            f"{c} chunks; pipeline.imagination_microbatches must divide the "
            f"(horizon+1)·L·B row count (the same divisibility law as "
            f"fabric.shard_batch's data axis)"
        )
    xs = jnp.reshape(x, (c, n // c) + x.shape[1:])
    ys = jax.lax.map(fn, xs)
    return jnp.reshape(ys, (n,) + ys.shape[2:])


# --------------------------------------------------------------------------
# the pipelined value-and-grad
# --------------------------------------------------------------------------

def pipeline_value_and_grad(
    stage_fns: Sequence[Callable[..., Any]],
    params: Any,
    consts: Any,
    *,
    microbatches: int,
    stage_names: Optional[Sequence[str]] = None,
    constrain: Optional[Callable[[int, Any], Any]] = None,
) -> Tuple[jax.Array, Any, Any]:
    """Run a linear stage chain over microbatches in 1F1B order and return
    ``(loss, aux_stacked, grads)``.

    ``stage_fns`` is the chain: ``stage_fns[0](params, None, const_m)`` →
    carry, middle stages ``(params, carry, const_m)`` → carry, and the LAST
    stage returns ``(loss_m, aux_m)`` (means over the microbatch — the
    returned ``loss``/``grads`` are microbatch means, equal to the
    full-batch values up to float reassociation because every dreamer loss
    is a batch mean).  ``consts`` is a pytree with leading microbatch axis
    ``M`` (data slices, pre-drawn noise — never differentiated).
    ``aux_stacked`` keeps the leading ``M`` axis; reassemble batch-shaped
    fields with :func:`merge_microbatches`.

    The schedule is unrolled at trace time inside the CALLER's jitted
    program — one executable per window signature (compile-once holds), the
    1F1B order realized as data dependencies: each backward unit folds its
    parameter cotangent into the running accumulator immediately, so the
    accumulation chain serializes backwards in schedule order and at most
    ``S - s`` forward residuals per stage are live (activation buffers are
    reused in place by XLA's donation-aware liveness).  ``constrain`` (e.g.
    a ``with_sharding_constraint`` over the ``data`` axis) is applied to
    every stage output so GSPMD keeps microbatch activations on their
    sub-groups."""
    S = len(stage_fns)
    M = int(microbatches)
    if S < 1:
        raise ValueError("pipeline_value_and_grad: need at least one stage")
    names = list(stage_names) if stage_names is not None else [f"stage{i}" for i in range(S)]
    if len(names) != S:
        raise ValueError(f"pipeline_value_and_grad: {len(names)} names for {S} stages")
    order = one_f_one_b(S, M)

    def const_of(m: int) -> Any:
        return jax.tree.map(operator.itemgetter(m), consts)

    carries: Dict[Tuple[int, int], Any] = {}
    vjps: Dict[Tuple[int, int], Callable[..., Any]] = {}
    dcarry: Dict[Tuple[int, int], Any] = {}  # cotangent INTO stage s's carry input
    losses: List[Any] = [None] * M
    auxes: List[Any] = [None] * M
    grads = jax.tree.map(jnp.zeros_like, params)

    for op, s, m in order:
        tag = f"pipeline.{names[s]}.{'fwd' if op == 'F' else 'bwd'}"
        const_m = const_of(m)
        if op == "F":
            cin = None if s == 0 else carries.pop((s - 1, m))
            with jax.named_scope(tag):
                if s == S - 1:
                    if s == 0:
                        out, vjp, aux = jax.vjp(
                            lambda p: stage_fns[s](p, None, const_m), params, has_aux=True
                        )
                    else:
                        out, vjp, aux = jax.vjp(
                            lambda p, c: stage_fns[s](p, c, const_m), params, cin, has_aux=True
                        )
                    losses[m], auxes[m] = out, aux
                elif s == 0:
                    out, vjp = jax.vjp(lambda p: stage_fns[s](p, None, const_m), params)
                else:
                    out, vjp = jax.vjp(lambda p, c: stage_fns[s](p, c, const_m), params, cin)
            if s < S - 1:
                if constrain is not None:
                    out = constrain(s, out)
                carries[(s, m)] = out
            vjps[(s, m)] = vjp
        else:
            with jax.named_scope(tag):
                if s == S - 1:
                    cots = vjps.pop((s, m))(jnp.ones((), jnp.result_type(losses[m])))
                else:
                    cots = vjps.pop((s, m))(dcarry.pop((s + 1, m)))
            dp = cots[0]
            if s > 0:
                dcarry[(s, m)] = cots[1]
            # immediate fold-in: the accumulation chain pins the 1F1B order
            grads = jax.tree.map(jnp.add, grads, dp)

    inv_m = 1.0 / float(M)
    grads = jax.tree.map(lambda g: g * jnp.asarray(inv_m, g.dtype), grads)
    loss = jnp.mean(jnp.stack(losses))
    aux_stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *auxes)
    return loss, aux_stacked, grads


# --------------------------------------------------------------------------
# sharding integration (parallel/sharding.py rule tables)
# --------------------------------------------------------------------------

def compose_pipeline_rules(
    rules: Sequence[Tuple[str, Any]],
    *,
    pipeline_axis: str = PIPELINE_AXIS,
    model_axis: str = "model",
    has_model: bool = True,
) -> Tuple[Tuple[str, Any], ...]:
    """Rewrite a partition-rule table for a mesh with a ``pipeline`` axis.

    Every ``model``-sharded weight dimension tiles over the
    ``(pipeline, model)`` axis product (or over ``pipeline`` alone when the
    mesh has no ``model`` axis): on a ``{data: D, pipeline: S, model: K}``
    mesh each sub-group owns a ``1/(S·K)`` slice of every stage's kernels —
    the GSPMD weight-placement half of the stage partition (the schedule
    half lives in :func:`pipeline_value_and_grad`).  Callable rule specs are
    wrapped so their RESULT is rewritten the same way; validation
    (axis-exists / dims-divide, ``sharding.undivisible`` policy) stays in
    ``partition_specs`` downstream."""

    def rewrite(spec: Optional[P]) -> Optional[P]:
        if spec is None:
            return None
        out: List[Any] = []
        for entry in spec:
            if entry == model_axis:
                out.append((pipeline_axis, model_axis) if has_model else pipeline_axis)
            elif isinstance(entry, (tuple, list)) and model_axis in entry:
                out.append((pipeline_axis, *entry))
            else:
                out.append(entry)
        return P(*out)

    composed: List[Tuple[str, Any]] = []
    for regex, spec in rules:
        if isinstance(spec, P) or spec is None:
            composed.append((regex, rewrite(spec)))
        elif callable(spec):
            def wrapped(path, leaf, mesh, _fn=spec):
                return rewrite(_fn(path, leaf, mesh))

            composed.append((regex, wrapped))
        else:
            composed.append((regex, spec))
    return tuple(composed)


def stage_batch_constraint(mesh: Any, data_axis: str, batch_axis: int = 1):
    """A ``constrain`` hook for :func:`pipeline_value_and_grad`: pin every
    stage output's microbatch batch axis to the ``data`` mesh axis so GSPMD
    keeps in-flight activations data-sharded on their sub-groups instead of
    round-tripping through a replicated layout between stages.  Leaves whose
    batch dim does not divide the axis pass through unconstrained (the
    ``shard_batch`` demotion rule)."""
    if mesh is None or data_axis not in getattr(mesh, "shape", {}):
        return None
    n = int(mesh.shape[data_axis])
    if n <= 1:
        return None

    def constrain(stage: int, carry: Any) -> Any:
        del stage

        def pin(x: Any) -> Any:
            if not hasattr(x, "ndim") or x.ndim <= batch_axis or x.shape[batch_axis] % n:
                return x
            spec = [None] * x.ndim
            spec[batch_axis] = data_axis
            return jax.lax.with_sharding_constraint(
                x, jax.sharding.NamedSharding(mesh, P(*spec))
            )

        return jax.tree.map(pin, carry)

    return constrain


# --------------------------------------------------------------------------
# per-stage measurement harness (bench.py --mode pipeline)
# --------------------------------------------------------------------------

def compile_stage_pair(fabric: Any, stage_fn: Callable[[Any, Any], Any], *, name: str,
                       max_recompiles: Optional[int] = None) -> Tuple[Any, Any]:
    """Standalone compiled ``(forward, backward)`` programs for ONE stage —
    the per-stage timing harness behind ``bench.py --mode pipeline``'s phase
    breakdown (``Phase/pipeline.stage.*`` spans).

    The backward rematerializes the stage forward (the 1F1B activation-
    recompute discipline, same lever as ``algo.remat``) and DONATES both the
    inter-stage activation buffer and the incoming cotangent — after a
    stage's backward the activation is dead by construction.  Reading a
    donated activation again afterwards is exactly the hazard graftlint's
    ``use-after-donate`` rule flags (donation.py's curated table carries
    this factory), so keep the canonical rebinding shape at call sites:
    ``act = fwd(p, x); dx = bwd(p, act, dy)`` and rebind ``act`` before the
    next use."""

    def fwd(p, x):
        return stage_fn(p, x)

    def bwd(p, x, dy):
        _, vjp = jax.vjp(lambda xx: stage_fn(p, xx), x)
        (dx,) = vjp(dy)
        return dx

    fwd_c = fabric.compile(fwd, name=f"{name}.fwd", max_recompiles=max_recompiles)
    bwd_c = fabric.compile(
        bwd, name=f"{name}.bwd", donate_argnums=(1, 2), max_recompiles=max_recompiles
    )
    return fwd_c, bwd_c


# --------------------------------------------------------------------------
# telemetry
# --------------------------------------------------------------------------

def register_pipeline_metrics(spec: PipelineSpec) -> None:
    """Publish the schedule's shape as hub metrics (``Pipeline/stages``,
    ``Pipeline/microbatches``, ``Pipeline/bubble_frac``) — bubble fraction
    as a first-class metric next to the ``Phase/*`` fractions.  Re-register
    is the hub's documented supersede semantics (a new run's spec replaces
    the finished run's)."""
    from sheeprl_tpu.telemetry.hub import HUB

    HUB.register("pipeline", spec.metrics)
