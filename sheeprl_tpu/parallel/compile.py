"""Compile-once execution layer: explicit AOT lowering + parallel warm-up.

Motivation (Podracer / RLAX TPU recipe): an RL framework's device programs
should be **compiled once, then only fed data**.  Implicit ``jax.jit``
first-call tracing hides when that contract breaks — a last-batch
remainder, a framestack variant or a drifted scalar dtype silently
re-traces a multi-minute TPU program mid-run.  This module makes the
contract explicit:

* :class:`AOTFunction` wraps ``jax.jit(fn).lower(*args).compile()`` behind
  a per-abstract-signature executable cache.  Every compile is recorded in
  ``utils.profiler.COMPILE_MONITOR`` (per-function counter + signature
  log) and can be capped with ``max_recompiles``.
* :class:`CompilePool` lowers/compiles *distinct* executables concurrently
  in a thread pool (XLA compilation releases the GIL), so warm-up overlaps
  with host-side setup — env construction, replay-buffer allocation, the
  prefill rollout — instead of serializing in front of the first update.

All algorithm train loops route their update/player programs through
``fabric.compile`` (a thin veneer over :func:`compile_once` here), so the
executed program is byte-identical to the plain-``jax.jit`` one; only the
compile *cadence* becomes observable and enforceable.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from sheeprl_tpu.utils.profiler import COMPILE_MONITOR, RecompileLimitExceeded  # noqa: F401

_FALLBACK = object()  # cache sentinel: route this signature through plain jit


def _canon_placement(sharding: Any) -> Any:
    """Canonical placement key: every fully-on-ONE-device placement —
    committed ``SingleDeviceSharding``, an uncommitted array on the default
    device, a replicated ``NamedSharding`` over a 1-device mesh — collapses
    to the same ``("dev", platform, id)`` key.  A compiled executable
    accepts all of them interchangeably (verified on jax 0.4.37), and NOT
    collapsing them burns a duplicate multi-minute compile the first time a
    program's inputs ping-pong between e.g. the host-committed initial key
    and the executable-returned one.  Multi-device shardings stay distinct
    (they genuinely select different programs).  A canonicalization miss at
    worst triggers the safe plain-jit fallback, never a wrong answer."""
    if sharding is None:
        d = jax.devices()[0]
        return ("dev", d.platform, d.id)
    try:
        dset = sharding.device_set
        if len(dset) == 1:
            d = next(iter(dset))
            return ("dev", d.platform, d.id)
    except Exception:
        pass
    return sharding


def _leaf_sig(x: Any) -> Tuple[Any, ...]:
    """Abstract signature of one argument leaf: shape / dtype / placement.

    Placement is the canonicalized sharding (see :func:`_canon_placement`;
    hashable jax sharding objects compare structurally).
    ``jax.ShapeDtypeStruct`` leaves get the same treatment so spec-based
    warm-up hits the same cache slot as the real call.
    """
    if isinstance(x, jax.ShapeDtypeStruct):
        return ("arr", x.shape, str(x.dtype), _canon_placement(x.sharding), False)
    if isinstance(x, jax.Array):
        placement = _canon_placement(x.sharding)
        return ("arr", x.shape, str(x.dtype), placement, bool(getattr(x, "weak_type", False)))
    if isinstance(x, np.ndarray):
        return ("np", x.shape, str(x.dtype))
    if isinstance(x, np.generic):
        return ("np", (), str(x.dtype))
    # dynamic python scalars: jit keys on the type, not the value
    return ("py", type(x).__name__)


def _has_tracer(leaves) -> bool:
    return any(isinstance(leaf, jax.core.Tracer) for leaf in leaves)


class AOTFunction:
    """``jax.jit`` wrapper with explicit AOT compilation and recompile audit.

    Call it like the jitted function.  The first call with a new abstract
    signature lowers + compiles ahead-of-time (recorded in
    ``COMPILE_MONITOR``); later same-signature calls dispatch straight into
    the cached executable.  ``warmup``/``compile_for`` build the executable
    without running it — from a :class:`CompilePool` thread they overlap
    compilation with host-side setup.

    Guaranteed-equivalent escape hatches: tracer arguments (the function is
    being traced inside another program) and any executable/argument
    mismatch fall through to the underlying ``jax.jit`` function, which by
    construction runs the identical program.
    """

    def __init__(
        self,
        fn: Callable,
        *,
        name: Optional[str] = None,
        static_argnums: Tuple[int, ...] = (),
        static_argnames: Tuple[str, ...] = (),
        donate_argnums: Tuple[int, ...] = (),
        in_shardings: Any = None,
        out_shardings: Any = None,
        max_recompiles: Optional[int] = None,
        monitor=None,
    ):
        self._fn = fn
        self.name = name or getattr(fn, "__name__", "<anonymous>")
        self.__name__ = self.name
        self._static_argnums = tuple(static_argnums)
        self._static_argnames = tuple(static_argnames)
        # a static argument is static to jax.jit however it is passed —
        # positionally, by keyword, or omitted with a default (names resolve
        # to positions and vice versa); mirror that here so the executable
        # cache keys every spelling of the same VALUE to the same slot
        try:
            import inspect

            sig = inspect.signature(fn)
            self._param_names = tuple(sig.parameters)
            self._param_defaults = {
                p: v.default
                for p, v in sig.parameters.items()
                if v.default is not inspect.Parameter.empty
            }
        except (TypeError, ValueError):
            self._param_names = ()
            self._param_defaults = {}
        positions = {p: i for i, p in enumerate(self._param_names)}
        self._static_name_pos = frozenset(
            positions[n] for n in self._static_argnames if n in positions
        )
        self._static_names = frozenset(self._static_argnames) | frozenset(
            self._param_names[i]
            for i in self._static_argnums
            if i < len(self._param_names)
        )
        self.max_recompiles = max_recompiles
        self._monitor = monitor if monitor is not None else COMPILE_MONITOR
        jit_kwargs: Dict[str, Any] = dict(
            static_argnums=self._static_argnums or None,
            static_argnames=self._static_argnames or None,
            donate_argnums=tuple(donate_argnums),
        )
        if in_shardings is not None:
            jit_kwargs["in_shardings"] = in_shardings
        if out_shardings is not None:
            jit_kwargs["out_shardings"] = out_shardings
        self._jitted = jax.jit(fn, **jit_kwargs)
        self._lock = threading.Lock()
        self._cache: Dict[Any, Any] = {}
        self._inflight: Dict[Any, Future] = {}
        # instance-local compile audit: THIS wrapper is one compile-once
        # program, so the max_recompiles budget counts only its own
        # executables (the process-global monitor aggregates per name
        # across instances — e.g. one per run in a test process — and
        # would charge this program for compiles it never performed)
        self._compile_count = 0
        self._sig_history: list = []

    # -- plain-jit passthroughs ---------------------------------------------
    @property
    def jitted(self) -> Callable:
        """The underlying ``jax.jit`` function (implicit-compile semantics)."""
        return self._jitted

    @property
    def fn(self) -> Callable:
        """The raw (unjitted, undonated) function.  Wrappers that trace this
        program inside ANOTHER program and still use the original arguments
        afterwards (the health guard's old-vs-new select) MUST trace this,
        not the jitted callable: an inner jit's ``donate_argnums`` survives
        inlining as an aliasing hint, so XLA may clobber a donated input's
        buffer while the outer computation still reads it."""
        return self._fn

    def lower(self, *args: Any, **kwargs: Any):
        return self._jitted.lower(*args, **kwargs)

    # -- signature / static-arg handling ------------------------------------
    def _split(self, args, kwargs):
        static_idx = set(self._static_argnums) | self._static_name_pos
        dyn_args = tuple(a for i, a in enumerate(args) if i not in static_idx)
        dyn_kwargs = {
            k: v for k, v in kwargs.items() if k not in self._static_names
        }
        # canonical static key: every spelling of the same value — positional,
        # keyword, or an omitted default — resolves to the same (name, value)
        # pairs, so it selects the same executable
        static: Dict[Any, Any] = {}
        for i in sorted(static_idx):
            if i < len(args):
                key = self._param_names[i] if i < len(self._param_names) else i
                static[key] = args[i]
        for k, v in kwargs.items():
            if k in self._static_names:
                static[k] = v
        for n in self._static_names:
            if n not in static and n in self._param_defaults:
                static[n] = self._param_defaults[n]
        static_key = tuple(sorted(static.items(), key=lambda kv: str(kv[0])))
        return dyn_args, dyn_kwargs, static_key

    def _signature_and_split(self, args, kwargs):
        """(signature, dyn_args, dyn_kwargs) in ONE pass — dispatch is the
        per-env-step hot path, so the split must not run twice per call."""
        dyn_args, dyn_kwargs, static_key = self._split(args, kwargs)
        leaves, treedef = jax.tree.flatten((dyn_args, dyn_kwargs))
        if _has_tracer(leaves):
            return None, dyn_args, dyn_kwargs
        sig = (treedef, tuple(_leaf_sig(leaf) for leaf in leaves), static_key)
        return sig, dyn_args, dyn_kwargs

    def signature(self, *args: Any, **kwargs: Any):
        return self._signature_and_split(args, kwargs)[0]

    # -- compilation ---------------------------------------------------------
    def compile_for(self, *args: Any, **kwargs: Any):
        """Return the compiled executable for this signature, building it
        (and recording the compile) on first sight.  Raises
        :class:`RecompileLimitExceeded` past the budget."""
        sig = self.signature(*args, **kwargs)
        if sig is None:
            raise ValueError(f"{self.name}: cannot AOT-compile under a tracer")
        exe = self._lookup(sig, args, kwargs)
        if exe is _FALLBACK:
            raise ValueError(f"{self.name}: signature is in plain-jit fallback mode")
        return exe

    def warmup(self, *args: Any, **kwargs: Any):
        """Alias of :meth:`compile_for` — reads as intent at call sites."""
        return self.compile_for(*args, **kwargs)

    def _check_budget(self, signature) -> None:
        """Count one compile of THIS instance; raise past the budget."""
        with self._lock:
            self._compile_count += 1
            self._sig_history.append(str(signature))
            limit = self.max_recompiles
            if limit is None:
                limit = self._monitor.default_limit()
            if limit is not None and self._compile_count - 1 > int(limit):
                history = "\n  ".join(self._sig_history)
                raise RecompileLimitExceeded(
                    f"'{self.name}' compiled {self._compile_count} times, "
                    f"exceeding max_recompiles={int(limit)} (first compile is "
                    f"free). A new abstract signature reached a compile-once "
                    f"program — signature history:\n  {history}"
                )

    def _rollback_budget(self, signature) -> None:
        """Undo one ``_check_budget`` whose compile never completed.  Removes
        the MATCHING signature (searched from the end), not blindly the last
        one — two signatures of this function can compile concurrently (the
        warm-up pool overlapping the main thread) and interleave their
        begin/rollback pairs."""
        sig_str = str(signature)
        with self._lock:
            self._compile_count -= 1
            for i in range(len(self._sig_history) - 1, -1, -1):
                if self._sig_history[i] == sig_str:
                    del self._sig_history[i]
                    break

    def _lookup(self, sig, args, kwargs):
        """Executable for ``sig``: cached, inflight-awaited, or compiled now."""
        while True:
            with self._lock:
                exe = self._cache.get(sig)
                if exe is not None:
                    return exe
                fut = self._inflight.get(sig)
                if fut is None:
                    fut = Future()
                    self._inflight[sig] = fut
                    owner = True
                else:
                    owner = False
            if not owner:
                return fut.result()
            try:
                # the guard runs BEFORE the (expensive) compile: tripping the
                # budget must not first pay for the offending executable
                self._check_budget(sig[1:])
                self._monitor.begin(self.name, sig[1:])
                t0 = time.perf_counter()
                exe = self._jitted.lower(*args, **kwargs).compile()
                self._monitor.end(self.name, time.perf_counter() - t0)
            except BaseException as e:
                if not isinstance(e, RecompileLimitExceeded):
                    # the compile itself failed: roll the audit back so the
                    # executable counters (metrics, budget) reflect programs
                    # actually BUILT, and a later retry isn't double-counted
                    self._monitor.abort(self.name, sig[1:])
                    self._rollback_budget(sig[1:])
                with self._lock:
                    self._inflight.pop(sig, None)
                fut.set_exception(e)
                raise
            with self._lock:
                self._cache[sig] = exe
                self._inflight.pop(sig, None)
            fut.set_result(exe)
            return exe

    # -- dispatch -------------------------------------------------------------
    def __call__(self, *args: Any, **kwargs: Any):
        sig, dyn_args, dyn_kwargs = self._signature_and_split(args, kwargs)
        if sig is None:  # traced inside another program: inline like plain jit
            return self._jitted(*args, **kwargs)
        exe = self._lookup(sig, args, kwargs)
        if exe is _FALLBACK:
            return self._jitted(*args, **kwargs)
        try:
            return exe(*dyn_args, **dyn_kwargs)
        except (TypeError, ValueError):
            # argument/executable mismatch our coarse signature missed
            # (argument-validation errors fire before execution, so donated
            # buffers are still intact) — plain jit is always correct; pin
            # this signature to the fallback so the cost is paid once.
            # The implicit-jit call re-traces for the TRUE signature: count
            # that compile (and hold it to the budget) so retraces stay
            # visible exactly where the coarse scheme failed — but only
            # once the call SUCCEEDS: genuinely bad arguments raise the
            # same error from plain jit without compiling anything, and
            # must not leave a phantom executable in the audit.  Only LATER
            # drift inside this pinned bucket escapes the audit.
            fb_sig = ("jit-fallback",) + sig[1:]
            self._check_budget(fb_sig)
            try:
                out = self._jitted(*args, **kwargs)
            except BaseException:
                self._rollback_budget(fb_sig)
                raise
            self._monitor.begin(self.name, fb_sig)
            with self._lock:
                self._cache[sig] = _FALLBACK
            return out

    def cache_size(self) -> int:
        with self._lock:
            return len(self._cache)


def state_io_shardings(
    param_shardings: Any,
    opt_shardings: Any,
    n_extra_in: int,
    n_extra_out: int = 1,
) -> Tuple[Tuple[Any, ...], Tuple[Any, ...]]:
    """``(in_shardings, out_shardings)`` for the canonical train-phase
    calling convention ``f(params, opt_state, *data) -> (params, opt_state,
    *aux)`` shared by every algo's scanned update program.

    ``param_shardings``/``opt_shardings`` are ``NamedSharding`` pytrees —
    normally ``sharding.shardings_of(fabric.shard_params(...))``, i.e. the
    partition-rules placement.  Pinning them on BOTH sides of the program
    (and donating argnums 0/1 at the call site) is what makes a sharded
    train step update params and optimizer state IN PLACE: the optimizer
    moments keep exactly their params' column/row sharding across every
    update, and XLA reuses the donated buffers instead of materializing a
    gathered copy.  The ``None`` entries for data/key/counter arguments and
    aux outputs mean 'unspecified' — jit infers those from the arguments
    (the batch keeps its ``data``-axis sharding) and the computation.
    """
    return (
        (param_shardings, opt_shardings) + (None,) * int(n_extra_in),
        (param_shardings, opt_shardings) + (None,) * int(n_extra_out),
    )


def compile_once(
    fn: Callable,
    *,
    name: Optional[str] = None,
    static_argnums: Tuple[int, ...] = (),
    static_argnames: Tuple[str, ...] = (),
    donate_argnums: Tuple[int, ...] = (),
    in_shardings: Any = None,
    out_shardings: Any = None,
    max_recompiles: Optional[int] = None,
) -> AOTFunction:
    """Module-level constructor for factories that have no fabric in scope
    (``make_sac_train_fns``, the decoupled PPO train-fn builder...);
    ``Fabric.compile`` delegates here."""
    return AOTFunction(
        fn,
        name=name,
        static_argnums=static_argnums,
        static_argnames=static_argnames,
        donate_argnums=donate_argnums,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
        max_recompiles=max_recompiles,
    )


class CompilePool:
    """Parallel compile warm-up over a shared thread pool.

    XLA compilation is C++ work that releases the GIL, so the *distinct*
    executables of a run (update step, player step, eval step, per-preset
    variants) lower and compile concurrently while the host builds envs and
    buffers.  Submissions are best-effort by design: a warm-up failure is
    swallowed at ``join`` (the executable would simply compile inline at
    first call), EXCEPT the recompile guard, which must stay a hard error.
    """

    def __init__(self, max_workers: Optional[int] = None):
        if max_workers is None:
            max_workers = max(2, min(4, (os.cpu_count() or 2)))
        self._executor = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="sheeprl-compile"
        )
        self._futures: list[Future] = []
        self._hard_errors: list[BaseException] = []
        self._lock = threading.Lock()

    def _track(self, fut: Future) -> Future:
        """Self-draining bookkeeping: completed futures remove themselves, so
        a long-lived process whose loops submit warm-ups but never ``join``
        (the fire-and-forget player warm-up) doesn't grow ``_futures`` — and
        their captured args — without bound.  Recompile-budget trips are
        stashed so a later ``join`` still surfaces them; they are never truly
        lost even without a join, because the real call re-enters the same
        budget check and raises at the call site."""
        with self._lock:
            self._futures.append(fut)

        def _drain(f: Future) -> None:
            exc = f.exception()
            with self._lock:
                try:
                    self._futures.remove(f)
                except ValueError:
                    # a join() snapshot owns this future and will observe
                    # its exception itself — stashing here too would make a
                    # LATER join spuriously re-raise an already-surfaced trip
                    return
                if isinstance(exc, RecompileLimitExceeded):
                    self._hard_errors.append(exc)

        fut.add_done_callback(_drain)
        return fut

    def submit(self, aot_fn: AOTFunction, *args: Any, **kwargs: Any) -> Future:
        return self._track(self._executor.submit(aot_fn.compile_for, *args, **kwargs))

    def submit_fn(self, fn: Callable, *args: Any, **kwargs: Any) -> Future:
        """Run an arbitrary warm-up thunk (e.g. a stage builder) in the pool."""
        return self._track(self._executor.submit(fn, *args, **kwargs))

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for all outstanding warm-ups.  Re-raises only
        :class:`RecompileLimitExceeded`; other warm-up failures degrade to
        inline compilation at first call."""
        with self._lock:
            futures, self._futures = self._futures, []
        for fut in futures:
            try:
                fut.result(timeout=timeout)
            except RecompileLimitExceeded:
                raise  # snapshot futures are reported here, never stashed
            except Exception:
                pass
        with self._lock:
            errs, self._hard_errors = list(self._hard_errors), []
        if errs:
            # a fire-and-forget warm-up (self-drained before this join)
            # tripped the budget: surface it now
            raise errs[0]

    def shutdown(self) -> None:
        self._executor.shutdown(wait=False, cancel_futures=True)


def warmup_batch_ladder(
    aot_fn: AOTFunction,
    spec_fn: Callable[[int], Tuple[Any, ...]],
    batch_sizes: Tuple[int, ...],
    pool: Optional["CompilePool"] = None,
    join: bool = True,
    timeout: Optional[float] = None,
) -> list:
    """AOT-compile ``aot_fn`` at every batch size of a serving ladder.

    ``spec_fn(batch)`` returns the positional argument tuple for one ladder
    rung — concrete arrays and/or ``jax.ShapeDtypeStruct`` leaves, exactly
    as the steady-state dispatch will pass them (the abstract signature
    keys the executable cache, so warm-up specs must match dispatch leaves
    kind-for-kind).  Distinct rungs compile concurrently on the shared
    :class:`CompilePool`; with ``join=True`` this blocks until the whole
    ladder is warm, so a server can guarantee ZERO steady-state compiles
    before admitting traffic.
    """
    pool = pool if pool is not None else get_compile_pool()
    futures = [pool.submit(aot_fn, *spec_fn(int(b))) for b in batch_sizes]
    if join:
        pool.join(timeout)
    return futures


_POOL: Optional[CompilePool] = None
_POOL_LOCK = threading.Lock()


def get_compile_pool() -> CompilePool:
    """The process-wide warm-up pool (lazily created)."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = CompilePool()
        return _POOL
