"""First-class ``jax.distributed`` init: the pod fabric's front door.

Three ways a process learns it is one rank of a pod, resolved in order by
:func:`ensure_distributed` (called by ``build_fabric`` BEFORE anything
touches the JAX backend — ``jax.distributed.initialize`` must run before
the first ``jax.devices()`` call or the process binds a single-host
backend and can never join the pod):

1. **Fake-DCN cell** — ``SHEEPRL_DCN_PROCESS_ID`` is set (by the
   launcher below, the pod supervisor, or a test harness).  The process
   forces the CPU platform + gloo collectives and joins the coordinator
   at ``SHEEPRL_DCN_COORD``.  This is the CI substrate: N real OS
   processes, one CPU device each, a real coordination service — every
   cross-host code path exercised on one machine.
2. **Fake-DCN launcher** — ``SHEEPRL_FAKE_DCN=N`` with no process id:
   this process re-executes itself N times as cells (fresh coordinator
   port, rank-prefixed output) and exits with the worst child return
   code, so ``SHEEPRL_FAKE_DCN=2 python -m sheeprl_tpu ...`` "just
   works".
3. **Real pods** — explicit ``fabric.distributed.coordinator_address``
   (+ ``num_processes``/``process_id``), or env-var autodetect
   (``fabric.distributed.enabled=auto``, the default): on Cloud TPU pod
   slices ``jax.distributed.initialize()`` discovers everything from the
   metadata server, so a recognised TPU-pod environment initializes with
   no arguments.

The module also owns the pod's *liveness* primitive: a
:class:`PeerWatchdog` heart-beating through the jax.distributed KV store
(the same client ``Fabric._coordination_client`` exposes) so a rank whose
peer dies stops within ``heartbeat_grace_s`` instead of sitting out a
collective timeout — "no rank trains past a dead peer".
"""

from __future__ import annotations

import base64
import os
import socket
import subprocess
import sys
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "PeerLost",
    "PeerWatchdog",
    "distributed_cfg",
    "ensure_distributed",
    "free_port",
    "is_fake_dcn",
    "launch_fake_dcn",
    "process_index",
    "process_count",
    "rank_zero_warn",
]

# env-var protocol between the fake-DCN launcher and its cells (also what
# the pod supervisor and the subprocess tests set by hand)
ENV_FAKE = "SHEEPRL_FAKE_DCN"
ENV_PROCESS_ID = "SHEEPRL_DCN_PROCESS_ID"
ENV_NUM_PROCESSES = "SHEEPRL_DCN_NUM_PROCESSES"
ENV_COORD = "SHEEPRL_DCN_COORD"

# env vars whose presence marks a real multi-host TPU pod environment
# (worth an argument-less jax.distributed.initialize())
_TPU_POD_ENV_VARS = (
    "MEGASCALE_COORDINATOR_ADDRESS",
    "TPU_WORKER_HOSTNAMES",
    "CLOUD_TPU_TASK_ID",
)


class PeerLost(RuntimeError):
    """A pod peer stopped heart-beating (crashed host / SIGKILLed rank)."""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def process_index() -> int:
    """This process's pod rank WITHOUT touching the JAX backend (safe to
    call before/without ``jax.distributed.initialize``)."""
    try:
        from jax._src import distributed

        # global_state.process_id DEFAULTS to 0 before initialize — only
        # trust it once the coordination client actually exists, else a
        # rank-3 cell warning before init would claim to be rank 0
        if distributed.global_state.client is not None:
            return int(distributed.global_state.process_id or 0)
    except Exception:
        pass
    return int(os.environ.get(ENV_PROCESS_ID, 0) or 0)


def process_count() -> int:
    """Pod size without touching the backend (1 when not distributed)."""
    try:
        from jax._src import distributed

        if distributed.global_state.client is not None:
            return int(distributed.global_state.num_processes or 1)
    except Exception:
        pass
    return int(os.environ.get(ENV_NUM_PROCESSES, 1) or 1)


def is_fake_dcn() -> bool:
    return bool(os.environ.get(ENV_FAKE))


_WARNED_KEYS: set = set()


def rank_zero_warn(message: str, category: type = RuntimeWarning, *, key: Optional[str] = None) -> None:
    """``warnings.warn`` for *global* facts: emitted by rank 0 only (an
    N-host pod should log one copy of a pod-wide warning, not N), and at
    most once per ``key`` per process (defaults to the message text)."""
    if process_index() != 0:
        return
    k = key or message
    if k in _WARNED_KEYS:
        return
    _WARNED_KEYS.add(k)
    warnings.warn(message, category, stacklevel=3)


def distributed_cfg(cfg: Any) -> Dict[str, Any]:
    """The ``fabric.distributed`` group as a plain dict ({} when absent)."""
    try:
        fab = cfg.get("fabric") if hasattr(cfg, "get") else None
        group = fab.get("distributed") if fab is not None else None
        return dict(group) if group else {}
    except Exception:
        return {}


def _force_cpu_gloo() -> None:
    """Fake-DCN cells collectivize over gloo on the host platform — set
    BEFORE the first backend touch."""
    import jax

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")


def ensure_distributed(cfg: Any) -> str:
    """Resolve and perform distributed init for this process.

    Returns ``"cell"`` (joined a fake-DCN pod), ``"pod"`` (joined a real
    pod), or ``"single"``.  Raises :class:`SystemExit` from launcher mode
    after the fake-DCN children finish.  Idempotent: a second call after a
    successful init is a no-op.
    """
    import jax

    try:
        from jax._src import distributed as _dist

        if _dist.global_state.client is not None:  # already initialized
            return "cell" if is_fake_dcn() else "pod"
    except Exception:
        pass

    dcfg = distributed_cfg(cfg)

    # 1) fake-DCN cell: the launcher/supervisor/test set the full protocol
    if os.environ.get(ENV_PROCESS_ID) is not None:
        coord = os.environ.get(ENV_COORD)
        num = int(os.environ.get(ENV_NUM_PROCESSES, 0) or 0)
        pid = int(os.environ[ENV_PROCESS_ID])
        if not coord or num <= 0:
            raise RuntimeError(
                f"{ENV_PROCESS_ID} is set but {ENV_COORD}/{ENV_NUM_PROCESSES} are not — "
                "fake-DCN cells need the full coordinator protocol"
            )
        _force_cpu_gloo()
        init_timeout = int(dcfg.get("init_timeout_s", 120) or 120)
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=num,
            process_id=pid,
            initialization_timeout=init_timeout,
        )
        return "cell"

    # 2) fake-DCN launcher: re-exec this command as N cells
    fake = int(os.environ.get(ENV_FAKE, 0) or 0)
    if fake > 1:
        raise SystemExit(launch_fake_dcn(fake))

    # 3) real pods: explicit coordinator, or TPU-pod env autodetect
    coord = dcfg.get("coordinator_address")
    if coord:
        kwargs: Dict[str, Any] = {"coordinator_address": str(coord)}
        if dcfg.get("num_processes") is not None:
            kwargs["num_processes"] = int(dcfg["num_processes"])
        if dcfg.get("process_id") is not None:
            kwargs["process_id"] = int(dcfg["process_id"])
        if dcfg.get("init_timeout_s"):
            kwargs["initialization_timeout"] = int(dcfg["init_timeout_s"])
        jax.distributed.initialize(**kwargs)
        return "pod"

    enabled = dcfg.get("enabled", "auto")
    if enabled is True or (
        str(enabled) == "auto" and any(v in os.environ for v in _TPU_POD_ENV_VARS)
    ):
        try:
            jax.distributed.initialize()
            return "pod"
        except Exception as e:  # autodetect is best-effort; explicit is not
            if enabled is True:
                raise
            rank_zero_warn(
                f"fabric.distributed autodetect found pod env vars but "
                f"jax.distributed.initialize() failed ({e}); continuing single-process",
                key="distributed.autodetect",
            )
    return "single"


def launch_fake_dcn(
    num: int,
    argv: Optional[List[str]] = None,
    *,
    env: Optional[Dict[str, str]] = None,
    prefix_output: bool = True,
) -> int:
    """Spawn ``num`` copies of this command as fake-DCN cells and wait.

    Each child gets the full cell protocol (coordinator on a fresh local
    port, its process id, one forced CPU device) and a rank-prefixed
    stdout relay.  Returns the worst child return code.
    """
    argv = list(sys.argv if argv is None else argv)
    if argv and argv[0].endswith("__main__.py"):
        # a `python -m pkg` launch shows up as .../pkg/__main__.py in argv —
        # re-exec'ing that path directly would put pkg/ (not its parent) on
        # sys.path and the cells would fail to import the package
        spec = getattr(sys.modules.get("__main__"), "__spec__", None)
        name = getattr(spec, "name", None)
        if name:
            mod = name[: -len(".__main__")] if name.endswith(".__main__") else name
            argv = ["-m", mod] + argv[1:]
    coord = f"127.0.0.1:{free_port()}"
    base_env = dict(os.environ if env is None else env)
    base_env.pop(ENV_PROCESS_ID, None)
    xla_flags = base_env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla_flags:
        base_env["XLA_FLAGS"] = (xla_flags + " --xla_force_host_platform_device_count=1").strip()
    children: List[subprocess.Popen] = []
    relays: List[threading.Thread] = []
    for rank in range(num):
        child_env = dict(base_env)
        child_env.update(
            {
                ENV_FAKE: str(num),
                ENV_PROCESS_ID: str(rank),
                ENV_NUM_PROCESSES: str(num),
                ENV_COORD: coord,
                "JAX_PLATFORMS": "cpu",
            }
        )
        child = subprocess.Popen(
            [sys.executable] + argv[:],
            env=child_env,
            stdout=subprocess.PIPE if prefix_output else None,
            stderr=subprocess.STDOUT if prefix_output else None,
            text=prefix_output,
        )
        children.append(child)
        if prefix_output:

            def _relay(c=child, r=rank):
                for line in c.stdout:  # type: ignore[union-attr]
                    sys.stdout.write(f"[dcn:{r}] {line}")
                    sys.stdout.flush()

            t = threading.Thread(target=_relay, name=f"dcn-relay[{rank}]", daemon=True)
            t.start()
            relays.append(t)
    rcs = [c.wait() for c in children]
    for t in relays:
        t.join(timeout=5)
    return max(abs(rc) for rc in rcs)


#: one lock for EVERY coordination-service call in this process: jax's KV
#: client is not thread-safe — concurrent calls from two threads (a
#: watchdog beating while the main thread publishes the front address)
#: segfault the process under the gloo CPU backend.
_KV_LOCK = threading.RLock()


class _SafeKV:
    """Thread-safe face of jax's coordination-service client.

    Two hazards observed under the gloo CPU backend (jaxlib 0.4.x):
    concurrent client calls from two threads can segfault the process,
    and ``blocking_key_value_get_bytes`` segfaults whenever it SUCCEEDS
    off the main thread (the bytes-return binding) — exactly the
    PeerWatchdog's watcher-thread usage.  So every call serializes under
    :data:`_KV_LOCK`, byte payloads ride the STRING key-value API
    base64-armored (the string bindings are thread-clean), and the long
    blocking get is re-implemented as short lock-slices (~200 ms per
    slice, lock released between): an actor cell waiting minutes for the
    learner front's address must not starve the watchdog's heartbeats —
    silence past ``grace_s`` reads as a dead host.
    """

    _SLICE_MS = 200

    def __init__(self, client: Any) -> None:
        self._client = client

    def key_value_set_bytes(self, key: str, value: bytes) -> None:
        armored = base64.b64encode(bytes(value)).decode("ascii")
        with _KV_LOCK:
            self._client.key_value_set(key, armored)

    def blocking_key_value_get_bytes(self, key: str, timeout_ms: int) -> bytes:
        deadline = time.monotonic() + max(int(timeout_ms), 1) / 1000.0
        while True:
            remaining_ms = int((deadline - time.monotonic()) * 1000)
            slice_ms = max(1, min(self._SLICE_MS, remaining_ms))
            with _KV_LOCK:
                try:
                    raw = self._client.blocking_key_value_get(  # graftlint: disable=prng-key-reuse
                        key, slice_ms
                    )
                except Exception:
                    if remaining_ms <= self._SLICE_MS:
                        raise
                else:
                    return base64.b64decode(raw)
            time.sleep(0.01)

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._client, name)
        if not callable(attr):
            return attr

        def locked(*args: Any, **kwargs: Any) -> Any:
            with _KV_LOCK:
                return attr(*args, **kwargs)

        return locked


def _kv_client() -> Any:
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError("PeerWatchdog needs jax.distributed to be initialized")
    return _SafeKV(client)


class PeerWatchdog:
    """KV-store heartbeats between pod ranks.

    Every rank writes ``sheeprl_tpu/hb/<rank>/<seq>`` each
    ``heartbeat_s``; a watcher thread blocks on each peer's next sequence
    key with a ``grace_s`` timeout.  A peer that stops writing (crashed
    process, SIGKILLed host) times the watcher out → ``on_peer_lost(rank)``
    fires exactly once and — unless the callback raised SystemExit itself —
    a delayed hard-exit timer guarantees the process cannot keep training
    past the dead peer even if the main thread is wedged inside a
    collective.

    ``stop()`` before teardown: a clean shutdown writes a goodbye marker
    so surviving watchers treat the silence as departure, not death.
    """

    _PREFIX = "sheeprl_tpu/hb"
    _GOODBYE = b"__goodbye__"

    def __init__(
        self,
        rank: int,
        world: int,
        *,
        heartbeat_s: float = 1.0,
        grace_s: float = 15.0,
        on_peer_lost: Optional[Callable[[int], None]] = None,
        hard_exit_after_s: float = 10.0,
        exit_code: int = 75,  # EX_TEMPFAIL: the supervisor restarts the pod
        client: Any = None,
    ) -> None:
        self.rank = int(rank)
        self.world = int(world)
        self.heartbeat_s = float(heartbeat_s)
        self.grace_s = float(grace_s)
        self.on_peer_lost = on_peer_lost
        self.hard_exit_after_s = float(hard_exit_after_s)
        self.exit_code = int(exit_code)
        self._client = client or _kv_client()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lost_lock = threading.Lock()
        self.lost_peer: Optional[int] = None

    # -- key schema -----------------------------------------------------------
    def _key(self, rank: int, seq: int) -> str:
        return f"{self._PREFIX}/{rank}/{seq}"

    # -- beat side ------------------------------------------------------------
    def _beat_loop(self) -> None:
        seq = 0
        while not self._stop.wait(self.heartbeat_s if seq else 0.0):
            try:
                self._client.key_value_set_bytes(self._key(self.rank, seq), b"%d" % seq)
                if seq >= 20:  # bound KV growth; watchers resync within the window
                    self._client.key_value_delete(self._key(self.rank, seq - 20))
            except Exception:
                return  # coordinator gone: the watcher side decides
            seq += 1
        try:  # clean departure: silence after a goodbye is not a death
            self._client.key_value_set_bytes(self._key(self.rank, seq), self._GOODBYE)
        except Exception:
            pass

    # -- watch side -----------------------------------------------------------
    def _get(self, key: str, timeout_ms: int) -> Optional[bytes]:
        try:
            return self._client.blocking_key_value_get_bytes(key, timeout_ms)
        except Exception:
            return None

    def _watch_peer(self, peer: int) -> None:
        seq = 0
        grace_ms = max(int(self.grace_s * 1000), 1000)
        while not self._stop.is_set():
            val = self._get(self._key(peer, seq), grace_ms)
            if self._stop.is_set():
                return
            if val is not None:
                if val == self._GOODBYE:
                    return
                seq += 1
                continue
            # missed seq: resync forward inside the retention window before
            # declaring death (a slow watcher must not kill a healthy pod)
            for ahead in range(1, 21):
                val = self._get(self._key(peer, seq + ahead), 50)
                if val is not None:
                    seq += ahead + (0 if val == self._GOODBYE else 1)
                    if val == self._GOODBYE:
                        return
                    break
            else:
                self._declare_lost(peer)
                return

    def _declare_lost(self, peer: int) -> None:
        with self._lost_lock:
            if self.lost_peer is not None or self._stop.is_set():
                return
            self.lost_peer = peer
        if self.hard_exit_after_s > 0:
            t = threading.Timer(self.hard_exit_after_s, os._exit, args=(self.exit_code,))
            t.daemon = True
            t.start()
        if self.on_peer_lost is not None:
            try:
                self.on_peer_lost(peer)
            except Exception:
                pass

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "PeerWatchdog":
        beat = threading.Thread(target=self._beat_loop, name="dcn.heartbeat", daemon=True)
        beat.start()
        self._threads.append(beat)
        for peer in range(self.world):
            if peer == self.rank:
                continue
            w = threading.Thread(
                target=self._watch_peer, args=(peer,), name=f"dcn.watch[{peer}]", daemon=True
            )
            w.start()
            self._threads.append(w)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
