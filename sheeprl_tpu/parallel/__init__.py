"""sheeprl_tpu.parallel."""
