"""Rule family 2: trace purity / recompile hazards.

Functions handed to ``fabric.compile`` / ``compile_once`` / ``jax.jit`` /
``lax.scan`` / ``window_scan`` / ``jax.grad`` / the fused builders are
traced once and replayed as a fixed program.  Host-side Python evaluated
during tracing therefore either freezes (clocks, host RNG), raises
(``ConcretizationTypeError`` on ``float()``/``if`` over traced values), or
— worst — silently keys a recompile per concrete value.  Three rules:

* ``trace-impure-time`` — ``time.time()`` / ``datetime.now()`` /
  ``np.random.*`` / stdlib ``random.*`` calls anywhere in a traced
  function: the value is baked in at trace time, every later dispatch
  replays it.
* ``trace-host-concretize`` — ``float()`` / ``int()`` / ``bool()`` /
  ``np.<fn>()`` / ``.item()`` applied to an expression that mentions a
  traced parameter: forces a device sync at best, a tracer leak at worst.
* ``trace-python-branch`` — ``if`` / ``while`` / ternary whose test
  mentions a traced parameter (static arguments, declared via
  ``static_argnums``/``static_argnames`` at the wrapping call, are
  exempt): data-dependent Python control flow is exactly what
  ``jnp.where`` / ``lax.cond`` exist for, and the recompile detector only
  catches it after the signature churns at runtime.

A "traced function" is any local ``def`` whose *name* is passed in the
function position of a known tracing consumer, or that is decorated with
``jax.jit`` / ``partial(jax.jit, ...)``.  Static-structure tests —
``isinstance``/``hasattr``/``len``/``is None``/``.shape``/``.ndim``/
``.dtype`` comparisons — are recognized as trace-time-legal and skipped.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from sheeprl_tpu.analysis.core import (
    Finding,
    SourceFile,
    attr_chain,
    call_name,
    literal_int_tuple,
    literal_str_tuple,
)

#: consumer callable name -> positional index of the traced function
TRACING_CONSUMERS: Dict[str, int] = {
    "compile": 0,       # fabric.compile(fn, ...)
    "compile_once": 0,
    "jit": 0,           # jax.jit / fabric.jit
    "scan": 0,          # lax.scan(fn, ...)
    "window_scan": 0,
    "vmap": 0,
    "pmap": 0,
    "grad": 0,
    "value_and_grad": 0,
    "checkpoint": 0,    # jax.checkpoint / remat
    "remat": 0,
    "wrap": 0,          # HealthSentinel.wrap(phase)
    "fused_uniform_train": 1,   # fused_*_train(fabric, phase, ...)
    "fused_sequence_train": 1,
}

_IMPURE_TIME_CALLS: Tuple[Tuple[str, ...], ...] = (
    ("time", "time"),
    ("time", "perf_counter"),
    ("time", "monotonic"),
    ("time", "process_time"),
    ("datetime", "now"),
    ("datetime", "utcnow"),
    ("datetime", "datetime", "now"),
)

_CONCRETIZERS = ("float", "int", "bool", "complex")


def check(src: SourceFile, ctx) -> List[Finding]:
    traced = _find_traced_functions(src.tree)
    findings: List[Finding] = []
    for fn, static_names in traced.items():
        params = _param_names(fn) - static_names
        _check_traced_fn(src, fn, params, findings)
    return findings


def _param_names(fn: ast.AST) -> Set[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    return set(names)


def _find_traced_functions(tree: ast.Module) -> Dict[ast.AST, Set[str]]:
    """Map of FunctionDef -> static argument names (exempt from the traced
    set), for every def whose name reaches a tracing consumer."""
    # name -> defs with that name (any scope; collisions are conservative)
    defs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)

    traced: Dict[ast.AST, Set[str]] = {}

    def mark(name: str, static_names: Set[str], static_nums: Tuple[int, ...]) -> None:
        for fn in defs.get(name, ()):
            statics = set(static_names)
            params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
            for i in static_nums:
                if i < len(params):
                    statics.add(params[i])
            traced.setdefault(fn, set()).update(statics)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            cname = call_name(node)
            if cname not in TRACING_CONSUMERS:
                continue
            idx = TRACING_CONSUMERS[cname]
            if idx >= len(node.args):
                continue
            fn_arg = node.args[idx]
            if not isinstance(fn_arg, ast.Name):
                continue
            static_names: Set[str] = set()
            static_nums: Tuple[int, ...] = ()
            for kw in node.keywords:
                if kw.arg == "static_argnames":
                    static_names = set(literal_str_tuple(kw.value))
                elif kw.arg == "static_argnums":
                    static_nums = literal_int_tuple(kw.value) or ()
            mark(fn_arg.id, static_names, static_nums)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                chain = attr_chain(dec if not isinstance(dec, ast.Call) else dec.func)
                if chain and chain[-1] in ("jit",):
                    traced.setdefault(node, set())
                elif (
                    isinstance(dec, ast.Call)
                    and call_name(dec) == "partial"
                    and dec.args
                    and (attr_chain(dec.args[0]) or [""])[-1] == "jit"
                ):
                    statics = set()
                    params = [a.arg for a in node.args.posonlyargs + node.args.args]
                    for kw in dec.keywords:
                        if kw.arg == "static_argnames":
                            statics |= set(literal_str_tuple(kw.value))
                        elif kw.arg == "static_argnums":
                            for i in literal_int_tuple(kw.value) or ():
                                if i < len(params):
                                    statics.add(params[i])
                    traced.setdefault(node, set()).update(statics)
    return traced


def _check_traced_fn(
    src: SourceFile, fn: ast.AST, params: Set[str], findings: List[Finding]
) -> None:
    ctx_name = getattr(fn, "name", "<traced>")
    for node in ast.walk(fn):
        # impure host clocks / host RNG — flagged regardless of arguments
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain:
                tchain = tuple(chain)
                if tchain in _IMPURE_TIME_CALLS or (
                    len(chain) >= 2 and chain[0] in ("np", "numpy") and chain[1] == "random"
                ) or (len(chain) == 2 and chain[0] == "random" and chain[1] in (
                    "random", "randint", "uniform", "normalvariate", "choice", "shuffle", "gauss"
                )):
                    findings.append(
                        Finding(
                            "trace-impure-time",
                            src.rel,
                            node.lineno,
                            f"'{'.'.join(chain)}()' inside traced function "
                            f"'{ctx_name}' — evaluated once at trace time, "
                            "frozen into every later dispatch",
                            context=ctx_name,
                        )
                    )
                    continue
            # host concretization of traced values
            cname = call_name(node)
            if cname in _CONCRETIZERS and node.args and _mentions(node.args[0], params):
                findings.append(
                    Finding(
                        "trace-host-concretize",
                        src.rel,
                        node.lineno,
                        f"'{cname}()' over a traced value inside '{ctx_name}' — "
                        "raises ConcretizationTypeError under jit (or silently "
                        "freezes the value); keep the computation in jnp",
                        context=ctx_name,
                    )
                )
                continue
            if (
                chain
                and chain[0] in ("np", "numpy")
                and len(chain) >= 2
                and chain[1] != "random"
                and any(_mentions(a, params) for a in node.args)
            ):
                findings.append(
                    Finding(
                        "trace-host-concretize",
                        src.rel,
                        node.lineno,
                        f"'{'.'.join(chain)}()' applied to a traced value inside "
                        f"'{ctx_name}' — numpy pulls the value to host at trace "
                        "time; use the jnp equivalent",
                        context=ctx_name,
                    )
                )
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and _mentions(node.func.value, params)
            ):
                findings.append(
                    Finding(
                        "trace-host-concretize",
                        src.rel,
                        node.lineno,
                        f"'.item()' on a traced value inside '{ctx_name}'",
                        context=ctx_name,
                    )
                )
        # Python control flow on traced values
        elif isinstance(node, (ast.If, ast.While, ast.IfExp, ast.Assert)):
            test = node.test
            if _mentions_dynamic(test, params):
                kind = {
                    ast.If: "if",
                    ast.While: "while",
                    ast.IfExp: "ternary",
                    ast.Assert: "assert",
                }[type(node)]
                findings.append(
                    Finding(
                        "trace-python-branch",
                        src.rel,
                        node.lineno,
                        f"Python '{kind}' on a traced value inside '{ctx_name}' — "
                        "the branch is resolved ONCE at trace time (or raises); "
                        "use jnp.where / lax.cond / lax.while_loop, or declare "
                        "the argument static",
                        context=ctx_name,
                    )
                )


def _mentions(node: ast.AST, params: Set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) and sub.id in params:
            return True
    return False


#: attributes whose access yields STATIC (trace-time) information
_STATIC_ATTRS = ("shape", "ndim", "dtype", "size", "keys")


def _mentions_dynamic(test: ast.AST, params: Set[str]) -> bool:
    """Does ``test`` read a traced param in a way that needs its VALUE —
    i.e. not through a static-structure probe (isinstance/hasattr/len,
    ``is None`` comparisons, .shape/.ndim/.dtype/.size access)?"""
    dynamic = False

    def scan(node: ast.AST) -> None:
        nonlocal dynamic
        if dynamic:
            return
        if isinstance(node, ast.Call) and call_name(node) in (
            "isinstance", "hasattr", "len", "getattr", "callable",
        ):
            return  # static probes — ignore whole subtree
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return
        if isinstance(node, ast.Compare) and any(
            isinstance(c, ast.Constant) and c.value is None for c in node.comparators
        ):
            # `x is None` / `x == None` — structural, legal at trace time
            ops_ok = all(isinstance(o, (ast.Is, ast.IsNot, ast.Eq, ast.NotEq)) for o in node.ops)
            if ops_ok:
                return
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) and node.id in params:
            dynamic = True
            return
        for child in ast.iter_child_nodes(node):
            scan(child)

    scan(test)
    return dynamic
