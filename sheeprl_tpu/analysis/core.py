"""graftlint core: findings, suppressions, the statement-flow engine, and
the analyzer driver.

The analyzer is a pure-AST pass (no jax import, no code execution): every
rule receives a parsed :class:`SourceFile` plus the shared
:class:`RepoContext` (the composed-config key tree, the fault-site registry
extracted from ``resilience/faults.py``, the documented metric families) and
returns :class:`Finding` objects.  The driver applies suppression comments
and the checked-in baseline, then renders text/JSON reports.

Design constraints, in order:

1. **Zero unsuppressed findings on this repo** — rules prefer precision
   over recall; anything heuristic must either be fixable cheaply or
   baselinable with a reason.
2. **The two shipped bugs must be caught** — the PR 7 ``copy_to``
   zero-copy alias and the PR 14 donation-aliasing /
   ``device_put``-borrowed-buffer classes are regression fixtures in
   ``tests/test_analysis/``; any refactor of the donation rule must keep
   them red.
3. **Fast** — the whole-repo run is a CI stage with a <60 s wall budget
   and a tier-1 test; parsing ~350 files plus one YAML sweep fits in a few
   seconds.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from pathlib import Path
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

REPO_PACKAGE = "sheeprl_tpu"

#: every rule id the engine knows, with a one-line meaning (the catalogue in
#: docs/static_analysis.md expands each with the historical bug it targets).
RULE_IDS: Dict[str, str] = {
    "use-after-donate": (
        "a variable passed in a donated argnum position of a compiled "
        "program (or an un-copied alias of one) is read after the dispatch"
    ),
    "donation-borrowed-buffer": (
        "a jax.device_put of a numpy value is passed in a donated argnum "
        "position — donation hands XLA a buffer it may not own"
    ),
    "trace-impure-time": (
        "host clock / host RNG call inside a traced function — the value "
        "freezes at trace time"
    ),
    "trace-host-concretize": (
        "float()/int()/bool()/np.* applied to a traced value inside a "
        "traced function — concretization error or silent host constant"
    ),
    "trace-python-branch": (
        "Python if/while/ternary on a traced value inside a traced "
        "function — per-value recompile or ConcretizationTypeError"
    ),
    "prng-key-reuse": (
        "a PRNG key is consumed by two sinks without an intervening "
        "jax.random.split / rebind"
    ),
    "prng-split-discarded": "the result of jax.random.split is discarded",
    "cfg-unknown-key": (
        "a cfg.<path> attribute access has no backing key anywhere in the "
        "composed sheeprl_tpu/configs/ tree"
    ),
    "cfg-dead-key": (
        "a YAML leaf under sheeprl_tpu/configs/ is read by no code path "
        "(dead config)"
    ),
    "fault-site-unknown": (
        "a fault-site string literal does not exist in "
        "resilience/faults.py's KNOWN_SITES registry"
    ),
    "metric-family-unknown": (
        "an emitted metric name uses a Family/ prefix that is not a "
        "documented metric family"
    ),
    "parse-error": "the file does not parse — nothing in it can be analyzed",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, "/" separated
    line: int
    message: str
    context: str = ""  # enclosing function, yaml key path, ...

    def render(self) -> str:
        ctx = f" [{self.context}]" if self.context else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{ctx}"

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class SourceFile:
    """One parsed Python file plus its suppression table."""

    def __init__(self, path: Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.tree = ast.parse(text, filename=str(path))
        self.lines = text.splitlines()
        self.suppressed_lines, self.suppressed_file, self.suppression_warnings = (
            _parse_suppressions(text)
        )

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.suppressed_file or "all" in self.suppressed_file:
            return True
        rules = self.suppressed_lines.get(line)
        return bool(rules) and (rule in rules or "all" in rules)


_SUPPRESS_RE = re.compile(r"graftlint:\s*(disable(?:-file)?)\s*=\s*([\w,\- ]+)")


def _parse_suppressions(
    text: str,
) -> Tuple[Dict[int, Set[str]], Set[str], List[Tuple[int, Set[str]]]]:
    """``# graftlint: disable=<rule>[,<rule>...]`` suppresses the named
    rules on its own line; on a comment-only line it also covers the next
    code line.  ``# graftlint: disable-file=<rule>`` covers the whole file.
    Comments are read with tokenize so string literals can't fake one.
    Returns (per-line rules, file-wide rules, unknown-rule warnings) — a
    typo'd rule name suppresses nothing and is surfaced as a report note.
    """
    by_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    warnings: List[Tuple[int, Set[str]]] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        comments = [(t.start[0], t.string, t.line) for t in tokens if t.type == tokenize.COMMENT]
    except tokenize.TokenError:
        comments = []
    for lineno, comment, full_line in comments:
        m = _SUPPRESS_RE.search(comment)
        if not m:
            continue
        rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
        unknown = rules - set(RULE_IDS) - {"all"}
        if unknown:
            rules -= unknown
            warnings.append((lineno, unknown))
        if "disable-file" in m.group(1):
            file_wide |= rules
        else:
            by_line.setdefault(lineno, set()).update(rules)
            if full_line.strip().startswith("#"):
                # comment-only line: also cover the next line
                by_line.setdefault(lineno + 1, set()).update(rules)
    return by_line, file_wide, warnings


# ---------------------------------------------------------------------------
# small AST helpers shared by the rules
# ---------------------------------------------------------------------------

def attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` -> ["a", "b", "c"]; None for anything non-dotted."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def call_name(call: ast.Call) -> Optional[str]:
    """Last dotted segment of the callee (``fabric.compile`` -> "compile")."""
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def literal_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """A literal int or tuple/list of ints; None when not statically known."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int) and not isinstance(elt.value, bool):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def literal_str_tuple(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return tuple(
            elt.value for elt in node.elts
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str)
        )
    return ()


def assigned_names(stmt: ast.stmt) -> Set[str]:
    """Plain names (re)bound by this statement's assignment targets."""
    out: Set[str] = set()

    def collect(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect(e)
        elif isinstance(t, ast.Starred):
            collect(t.value)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            collect(t)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        collect(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        collect(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                collect(item.optional_vars)
    return out


# ---------------------------------------------------------------------------
# statement-flow engine
# ---------------------------------------------------------------------------

class FlowState:
    """Interface for the branch/loop-aware statement scan.

    Rules subclass this with their abstract state; :func:`flow_scan` drives
    it through a body in approximate execution order: If/Try branches are
    analyzed independently from a forked copy and merged; For/While bodies
    get TWO passes (so state created in iteration N is visible at the top of
    iteration N+1 — the shape of every "donated in the loop, read next
    iteration" bug); nested function/class definitions are handed to
    :meth:`on_nested_def` instead of being walked inline (their execution
    order is unknowable statically).
    """

    def fork(self) -> "FlowState":
        raise NotImplementedError

    def merge(self, *branches: "FlowState") -> None:
        raise NotImplementedError

    def visit(self, stmt: ast.stmt) -> None:
        raise NotImplementedError

    def on_nested_def(self, stmt: ast.stmt) -> None:  # noqa: B027 - optional hook
        pass


def _header_stmt(stmt: ast.stmt) -> List[ast.stmt]:
    """Synthetic statements covering ONLY a compound statement's header —
    the body is scanned separately, so visit() must never see it (it would
    process body reads/writes out of order)."""
    out: List[ast.stmt] = []

    def expr(e: ast.expr) -> ast.stmt:
        s = ast.Expr(value=e)
        ast.copy_location(s, e)
        return ast.fix_missing_locations(s)

    def assign(target: ast.expr, value: ast.expr) -> ast.stmt:
        s = ast.Assign(targets=[target], value=value)
        ast.copy_location(s, value)
        return ast.fix_missing_locations(s)

    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        out.append(assign(stmt.target, stmt.iter))
    elif isinstance(stmt, ast.While):
        out.append(expr(stmt.test))
    elif isinstance(stmt, ast.If):
        out.append(expr(stmt.test))
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                out.append(assign(item.optional_vars, item.context_expr))
            else:
                out.append(expr(item.context_expr))
    return out


def flow_scan(body: Sequence[ast.stmt], state: FlowState) -> bool:
    """Scan ``body`` through ``state``.  Returns True when the body
    definitely TERMINATES the enclosing flow (return/raise/break/continue
    on every path) — a terminated branch's state is never merged back, so
    mutually-exclusive early-return paths can't cross-contaminate (the
    ``if continuous: return d.sample(key)`` / ``split(key)`` shape)."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            state.on_nested_def(stmt)
        elif isinstance(stmt, (ast.Return, ast.Raise, ast.Break, ast.Continue)):
            state.visit(stmt)  # reads in the return/raise expression count
            return True
        elif isinstance(stmt, ast.If):
            for h in _header_stmt(stmt):
                state.visit(h)
            s_body = state.fork()
            t_body = flow_scan(stmt.body, s_body)
            s_else = state.fork()
            t_else = flow_scan(stmt.orelse, s_else)
            live = [s for s, t in ((s_body, t_body), (s_else, t_else)) if not t]
            if live:
                state.merge(*live)
            if t_body and t_else:
                return True
        elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            for h in _header_stmt(stmt):
                state.visit(h)
            for _ in range(2):
                s_loop = state.fork()
                flow_scan(stmt.body, s_loop)
                state.merge(s_loop)
            s_else = state.fork()
            flow_scan(stmt.orelse, s_else)
            state.merge(s_else)
        elif isinstance(stmt, ast.Try):
            s_body = state.fork()
            t_all = flow_scan(stmt.body, s_body)
            branches = [(s_body, t_all)]
            for handler in stmt.handlers:
                s_h = state.fork()
                branches.append((s_h, flow_scan(handler.body, s_h)))
            live = [s for s, t in branches if not t]
            if live:
                state.merge(*live)
            flow_scan(stmt.orelse, state)
            flow_scan(stmt.finalbody, state)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for h in _header_stmt(stmt):
                state.visit(h)
            if flow_scan(stmt.body, state):
                return True
        else:
            state.visit(stmt)
    return False


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

class Report:
    def __init__(self) -> None:
        self.findings: List[Finding] = []
        self.suppressed: List[Finding] = []
        self.baselined: List[Finding] = []
        self.stale_baseline: List[Dict[str, Any]] = []
        self.notes: List[str] = []
        self.files_analyzed: int = 0
        self.wall_s: float = 0.0

    @property
    def unsuppressed(self) -> List[Finding]:
        return self.findings

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {
            "files_analyzed": self.files_analyzed,
            "wall_s": round(self.wall_s, 3),
            "unsuppressed": [f.to_dict() for f in self.findings],
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "stale_baseline": self.stale_baseline,
            "counts": self.counts(),
            "notes": self.notes,
        }

    def render_text(self, verbose: bool = False) -> str:
        out: List[str] = []
        for f in self.findings:
            out.append(f.render())
        if verbose:
            for f in self.baselined:
                out.append(f"baselined: {f.render()}")
        for entry in self.stale_baseline:
            out.append(
                "stale baseline entry (matched nothing): "
                f"{entry.get('rule')} {entry.get('file', '*')} "
                f"match={entry.get('match', '')!r}"
            )
        for note in self.notes:
            out.append(f"note: {note}")
        out.append(
            f"graftlint: {len(self.findings)} unsuppressed finding(s), "
            f"{len(self.baselined)} baselined, {len(self.suppressed)} "
            f"comment-suppressed across {self.files_analyzed} file(s) "
            f"in {self.wall_s:.2f}s"
        )
        return "\n".join(out)


def iter_py_files(paths: Sequence[Path]) -> Iterable[Path]:
    seen: Set[Path] = set()
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            if p not in seen:
                seen.add(p)
                yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" in f.parts or f in seen:
                    continue
                seen.add(f)
                yield f


def repo_root() -> Path:
    """The repo checkout containing the installed package (parent of
    ``sheeprl_tpu/``)."""
    return Path(__file__).resolve().parents[2]


def relpath(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


RuleFn = Callable[[SourceFile, Any], List[Finding]]


def run_analysis(
    paths: Optional[Sequence[os.PathLike]] = None,
    *,
    select: Optional[Sequence[str]] = None,
    baseline: Any = None,  # Baseline | None; resolved by caller/CLI
    context: Any = None,  # RepoContext; built lazily when None
    root: Optional[Path] = None,
) -> Report:
    """Analyze ``paths`` (default: the ``sheeprl_tpu`` package) and return a
    :class:`Report`.  This is the in-process entry the tier-1 test and
    ``bench.py --mode lint`` call; the CLI wraps it."""
    import time as _time

    from sheeprl_tpu.analysis import donation, prng, purity, registry
    from sheeprl_tpu.analysis.context import RepoContext

    t0 = _time.perf_counter()
    root = root or repo_root()
    targets = [Path(p) for p in (paths or [root / REPO_PACKAGE])]
    ctx = context if context is not None else RepoContext.build(root)
    report = Report()
    report.notes.extend(ctx.notes)

    selected = set(select) if select else set(RULE_IDS)
    unknown = selected - set(RULE_IDS)
    if unknown:
        raise ValueError(f"unknown rule id(s): {sorted(unknown)}")

    per_file_rules: List[RuleFn] = [
        donation.check,
        purity.check,
        prng.check,
        registry.check_file,
    ]

    sources: List[SourceFile] = []
    raw: List[Finding] = []
    for path in iter_py_files(targets):
        rel = relpath(path, root)
        try:
            src = SourceFile(path, rel, path.read_text())
        except (SyntaxError, UnicodeDecodeError) as e:
            raw.append(Finding("parse-error", rel, getattr(e, "lineno", 1) or 1,
                               f"file does not parse: {e}"))
            continue
        sources.append(src)
        for rule in per_file_rules:
            raw.extend(rule(src, ctx))
    report.files_analyzed = len(sources)

    # repo-level rules (dead config; yaml-side fault sites) need the whole
    # read-set, so they run after the per-file sweep.  Dead config is only
    # meaningful when the WHOLE package was analyzed — on a file subset
    # every key the subset doesn't read would misreport as dead.
    pkg = (root / REPO_PACKAGE).resolve()
    full_package = any(Path(t).resolve() == pkg for t in targets)
    raw.extend(registry.check_repo(sources, ctx, dead_config=full_package))

    # dedupe (the loop two-pass produces repeats), stable order
    uniq: Dict[Tuple[str, str, int, str], Finding] = {}
    for f in raw:
        uniq.setdefault((f.rule, f.path, f.line, f.message), f)
    findings = sorted(uniq.values(), key=lambda f: (f.path, f.line, f.rule))

    by_rel = {s.rel: s for s in sources}
    for f in findings:
        src = by_rel.get(f.path)
        suppressed_inline = src is not None and src.is_suppressed(f.rule, f.line)
        # baseline matching runs even for DESELECTED rules so their ledger
        # entries register hits — otherwise `--select x --strict` would
        # falsely report every other rule's entries as stale
        baselined = (
            not suppressed_inline and baseline is not None and baseline.matches(f)
        )
        if f.rule not in selected:
            continue
        if suppressed_inline:
            report.suppressed.append(f)
        elif baselined:
            report.baselined.append(f)
        else:
            report.findings.append(f)
    for src in sources:
        for line, names in sorted(src.suppression_warnings):
            report.notes.append(
                f"{src.rel}:{line}: suppression comment names unknown rule(s) "
                f"{sorted(names)} — it suppresses nothing (see --list-rules)"
            )
    if baseline is not None:
        report.stale_baseline = baseline.stale_entries()
    report.wall_s = _time.perf_counter() - t0
    return report
