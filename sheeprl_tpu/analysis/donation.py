"""Rule family 1: buffer donation.

``use-after-donate`` — the bug class behind the two worst shipped bugs:

* **PR 7**: same-platform ``copy_to`` returned a zero-copy *alias* of the
  params; the next donated train dispatch deleted the player's copy
  ("buffer has been deleted or donated").
* **PR 14**: ``HealthSentinel.wrap`` traced the *jitted* (donating)
  callable inside another program and then re-read the original arguments
  for its old-vs-new select — the inner ``donate_argnums`` survives
  inlining as an aliasing hint, so XLA may clobber the donated input while
  the outer computation still reads it.  Sibling facet: the zero
  ``HealthState`` was built by ``jax.device_put`` of numpy scalars; on CPU
  ``device_put`` can zero-copy *borrow* the numpy buffer, so donating it
  hands XLA memory it does not own (intermittent heap corruption).

Static model, per scope (module / function), donated-callable tables
inherited by nested scopes:

* A name bound from ``fabric.compile(f, donate_argnums=...)`` /
  ``compile_once(...)`` / ``jax.jit(...)`` / ``fabric.jit(...)`` with a
  literal ``donate_argnums`` is a *donating callable*.  Factories that
  return donating callables are propagated intra-module (``make_*`` that
  ``return``s donating names), plus a curated table for the framework's
  cross-module fused builders.
* Calling a donating callable donates every plain-``Name`` argument in a
  donated position (``x.copy()`` at the call site opts out), together with
  that name's un-copied aliases (``y = x``, ``y = copy_to(x, ...)``,
  ``y = jax.device_put(x, ...)``).
* Reading a donated name afterwards — without rebinding it first — is the
  finding.  Rebinding in the same statement (``p, o = step(p, o)``) is the
  canonical safe shape.  Loops are scanned twice so a donation in one
  iteration reaches reads at the top of the next.

``donation-borrowed-buffer`` — a value built by ``jax.device_put`` of a
numpy expression passed in a donated position (the PR 14 sibling facet).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from sheeprl_tpu.analysis.core import (
    Finding,
    FlowState,
    SourceFile,
    assigned_names,
    attr_chain,
    call_name,
    flow_scan,
    literal_int_tuple,
)

#: wrappers that produce a donating callable from ``fn`` at arg 0 when
#: called with donate_argnums
_COMPILE_WRAPPERS = ("compile", "compile_once", "jit")

#: cross-module factories known to return donating callables and which of
#: the RETURNED callable's positional args are donated.  Kept conservative:
#: the fused replay builders donate (params, opt_state) in every variant
#: (the health variant also donates the sentinel state at position 2, but
#: flagging 0/1 is enough to catch the bug class without risking noise).
KNOWN_FACTORY_DONATIONS: Dict[str, Tuple[int, ...]] = {
    "fused_uniform_train": (0, 1),
    "fused_sequence_train": (0, 1),
    # parallel/pipeline.py per-stage harness: (fwd, bwd) tuple whose bwd
    # (position 1) donates the inter-stage activation buffer and incoming
    # cotangent — reading a stage output again after its backward consumed
    # it is the 1F1B use-after-donate hazard (ISSUE 16)
    "compile_stage_pair@1": (1, 2),
}

#: callables whose result may ALIAS their first argument (the PR 7 class:
#: same-platform copy_to / device_put can be zero-copy)
_ALIAS_HAZARDS = ("copy_to", "device_put", "to_host")


def check(src: SourceFile, ctx) -> List[Finding]:
    findings: List[Finding] = []
    # donating callables returned by local factories, discovered first so
    # call sites anywhere in the module see them
    factory_table = dict(KNOWN_FACTORY_DONATIONS)
    factory_table.update(_local_factory_donations(src.tree))
    _scan_scope(
        src, src.tree.body, {}, factory_table, findings, context="module",
    )
    return findings


# ---------------------------------------------------------------------------
# factory propagation
# ---------------------------------------------------------------------------

def _donating_callable_argnums(value: ast.expr) -> Optional[Tuple[int, ...]]:
    """``compile_once(f, donate_argnums=(0, 1))``-shaped expression ->
    (0, 1); None otherwise."""
    if not isinstance(value, ast.Call):
        return None
    name = call_name(value)
    if name not in _COMPILE_WRAPPERS:
        return None
    for kw in value.keywords:
        if kw.arg == "donate_argnums":
            nums = literal_int_tuple(kw.value)
            if nums:
                return nums
    return None


def _local_factory_donations(tree: ast.Module) -> Dict[str, Tuple[int, ...]]:
    """Functions in this module that RETURN donating callables.

    Only the simple single-return shape is propagated: the factory binds
    ``f = compile_once(..., donate_argnums=...)`` and ends with
    ``return f`` or ``return a, f`` — the caller's tuple unpacking then
    maps positionally (``act_fn, train_phase = make_sac_train_fns(...)``).
    Multi-position returns map each donating element.
    """
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        donating: Dict[str, Tuple[int, ...]] = {}
        for stmt in ast.walk(node):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                t = stmt.targets[0]
                nums = _donating_callable_argnums(stmt.value)
                if nums and isinstance(t, ast.Name):
                    donating[t.id] = nums
        if not donating:
            continue
        for stmt in ast.walk(node):
            if not isinstance(stmt, ast.Return) or stmt.value is None:
                continue
            ret = stmt.value
            if isinstance(ret, ast.Tuple):
                for pos, elt in enumerate(ret.elts):
                    if isinstance(elt, ast.Name) and elt.id in donating:
                        # "factory returning a donating callable at tuple
                        # position pos" — callers unpack positionally
                        out[f"{node.name}@{pos}"] = donating[elt.id]
            elif isinstance(ret, ast.Name) and ret.id in donating:
                # bare single return: `x = make_step(...)` binds the
                # donating callable directly
                out[node.name] = donating[ret.id]
    return out


# ---------------------------------------------------------------------------
# per-scope flow analysis
# ---------------------------------------------------------------------------

class _DonationState(FlowState):
    def __init__(
        self,
        src: SourceFile,
        donators: Dict[str, Tuple[int, ...]],
        factories: Dict[str, Tuple[int, ...]],
        findings: List[Finding],
        context: str,
    ):
        self.src = src
        self.donators = donators          # name -> donated argnums
        self.factories = factories        # factory name (+@pos) -> argnums
        self.findings = findings
        self.context = context
        self.dead: Dict[str, str] = {}    # name -> description of the donation
        self.aliases: Dict[str, Set[str]] = {}  # origin -> alias names
        self.np_buffers: Set[str] = set()  # names holding device_put-of-numpy

    # -- FlowState plumbing --------------------------------------------------
    def fork(self) -> "_DonationState":
        s = _DonationState(self.src, dict(self.donators), self.factories, self.findings, self.context)
        s.dead = dict(self.dead)
        s.aliases = {k: set(v) for k, v in self.aliases.items()}
        s.np_buffers = set(self.np_buffers)
        return s

    def merge(self, *branches: "_DonationState") -> None:
        for b in branches:
            self.dead.update(b.dead)
            for k, v in b.aliases.items():
                self.aliases.setdefault(k, set()).update(v)
            self.np_buffers |= b.np_buffers
            self.donators.update(b.donators)
        # union semantics on purpose: dead in ANY path stays dead — a read
        # that is only safe on one branch is still a bug on the other

    def on_nested_def(self, stmt: ast.stmt) -> None:
        # nested scope: fresh liveness (its params are new buffers), but the
        # donating-callable table flows in — the PR 14 wrap() shape is a
        # nested fn calling an ENCLOSING scope's donating callable
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_scope(
                self.src, stmt.body, dict(self.donators), self.factories,
                self.findings, context=stmt.name,
            )

    # -- statement semantics -------------------------------------------------
    def visit(self, stmt: ast.stmt) -> None:
        rebound = assigned_names(stmt)

        # 1. reads of dead names anywhere in this statement (skip nested
        #    defs/lambdas — execution order unknowable)
        for node in _walk_no_nested(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in self.dead:
                    self.findings.append(
                        Finding(
                            "use-after-donate",
                            self.src.rel,
                            node.lineno,
                            f"'{node.id}' is read after {self.dead[node.id]}",
                            context=self.context,
                        )
                    )
                    # one report per donation event; stop cascading
                    self.dead.pop(node.id, None)

        # 2. donation events + borrowed-buffer checks in calls
        for node in _walk_no_nested(stmt):
            if isinstance(node, ast.Call):
                self._visit_call(node, rebound)

        # 3. rebinding resurrects (BEFORE tracking this statement's own new
        #    binding, or `y = copy_to(x)` would discard the alias it creates)
        for name in rebound:
            self.dead.pop(name, None)
            # a rebound name no longer aliases anything
            for origin in self.aliases:
                self.aliases[origin].discard(name)

        # 4. alias / np-buffer tracking on simple assignments
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and isinstance(stmt.targets[0], ast.Name):
            self._track_assign(stmt.targets[0].id, stmt.value)

    def _track_assign(self, target: str, value: ast.expr) -> None:
        # donating-callable binding: f = compile(g, donate_argnums=...)
        nums = _donating_callable_argnums(value)
        if nums:
            self.donators[target] = nums
            return
        # factory binding: f = make_fns(...) for a known factory (single
        # return position) — tuple unpacking handled in visit via Assign
        if isinstance(value, ast.Call):
            fname = call_name(value)
            # single-return factory (bare `return donating_fn`); tuple
            # returns only exist under `{fname}@{pos}` keys and are mapped
            # by the tuple-unpack pre-pass in _scan_scope
            if fname in self.factories and self.factories[fname]:
                self.donators[target] = self.factories[fname]
                return
            if fname in _ALIAS_HAZARDS and value.args:
                # note: `y = copy_to(x, d).copy()` never reaches here as a
                # hazard — the outer .copy() call is what _track_assign
                # sees, so the alias is naturally broken
                arg0 = value.args[0]
                if isinstance(arg0, ast.Name):
                    self.aliases.setdefault(arg0.id, set()).add(target)
                    return
                # device_put of a numpy expression: borrowed host buffer
                if fname == "device_put" and _is_numpy_expr(arg0, self.np_buffers):
                    self.np_buffers.add(target)
                    return
        # plain alias: y = x
        if isinstance(value, ast.Name):
            self.aliases.setdefault(value.id, set()).add(target)
            return
        # numpy value: y = np.zeros(...) — becomes interesting if later
        # device_put and donated
        if _is_numpy_expr(value, self.np_buffers):
            self.np_buffers.add(target)

    def _visit_call(self, call: ast.Call, rebound: Set[str]) -> None:
        # tuple-unpacked factory: a, b = make_fns(...) — map positions
        # handled here because visit() sees the Assign before rebinding
        fname = call_name(call)
        argnums: Optional[Tuple[int, ...]] = None
        if isinstance(call.func, ast.Name) and call.func.id in self.donators:
            argnums = self.donators[call.func.id]
        elif isinstance(call.func, ast.Attribute) and fname in self.donators:
            # method-style dispatch of a tracked callable (rare) — skip:
            # attribute identity is not trackable
            argnums = None
        if argnums is None:
            return
        has_star = any(isinstance(a, ast.Starred) for a in call.args)
        for pos in argnums:
            if has_star and pos >= next(
                (i for i, a in enumerate(call.args) if isinstance(a, ast.Starred)),
                len(call.args),
            ):
                break  # positions past *args are not statically mappable
            if pos >= len(call.args):
                continue
            arg = call.args[pos]
            if isinstance(arg, ast.Name):
                name = arg.id
                if name in self.np_buffers:
                    self.findings.append(
                        Finding(
                            "donation-borrowed-buffer",
                            self.src.rel,
                            arg.lineno,
                            f"'{name}' holds a jax.device_put of a numpy value and is "
                            f"donated at argnum {pos} of '{call.func.id}' — on CPU "
                            "device_put can borrow the numpy buffer, so donation "
                            "frees memory XLA does not own; build it from jnp "
                            "values instead",
                            context=self.context,
                        )
                    )
                donated_desc = (
                    f"being donated at argnum {pos} of '{call.func.id}' "
                    f"(line {call.lineno})"
                )
                if name not in rebound:
                    self.dead[name] = donated_desc
                # aliases die with the buffer regardless of rebinding
                for alias in self.aliases.get(name, ()):  # un-copied aliases
                    if alias not in rebound:
                        self.dead[alias] = (
                            f"'{name}' (which it may alias zero-copy) was "
                            f"donated at argnum {pos} of '{call.func.id}' "
                            f"(line {call.lineno}) — break the alias with "
                            ".copy() before donating"
                        )
            elif isinstance(arg, ast.Call) and call_name(arg) == "device_put":
                if arg.args and _is_numpy_expr(arg.args[0], self.np_buffers):
                    self.findings.append(
                        Finding(
                            "donation-borrowed-buffer",
                            self.src.rel,
                            arg.lineno,
                            f"jax.device_put of a numpy value donated inline at "
                            f"argnum {pos} of '{call.func.id}' — the donated "
                            "buffer may be borrowed from numpy",
                            context=self.context,
                        )
                    )


def _walk_no_nested(stmt: ast.stmt):
    """ast.walk skipping nested function/class/lambda bodies."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


def _is_numpy_expr(node: ast.expr, np_names: Set[str]) -> bool:
    """An expression that produces a host numpy buffer: an ``np.*`` /
    ``numpy.*`` call, or a name already known to hold one."""
    if isinstance(node, ast.Name):
        return node.id in np_names
    if isinstance(node, ast.Call):
        chain = attr_chain(node.func)
        return bool(chain) and chain[0] in ("np", "numpy")
    return False


def _scan_scope(
    src: SourceFile,
    body: Sequence[ast.stmt],
    donators: Dict[str, Tuple[int, ...]],
    factories: Dict[str, Tuple[int, ...]],
    findings: List[Finding],
    context: str,
) -> None:
    state = _DonationState(src, donators, factories, findings, context)
    # pre-pass: tuple-unpacked factory results (act_fn, phase = make_fns(...))
    # must be visible from the first statement of the scope they land in
    for stmt in body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Tuple)
            and isinstance(stmt.value, ast.Call)
        ):
            fname = call_name(stmt.value)
            for pos, elt in enumerate(stmt.targets[0].elts):
                key = f"{fname}@{pos}"
                if isinstance(elt, ast.Name) and key in factories:
                    state.donators[elt.id] = factories[key]
    flow_scan(body, state)
