"""CLI for graftlint: ``sheeprl-tpu-lint`` / ``python -m sheeprl_tpu.analysis``.

Exit codes: 0 = clean (no unsuppressed findings; under ``--strict`` also no
stale baseline entries), 1 = findings, 2 = usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="sheeprl-tpu-lint",
        description=(
            "graftlint: static analysis of JAX-law invariants (donation, "
            "trace purity, PRNG discipline, config/fault-site/metric "
            "registries). docs/static_analysis.md has the rule catalogue."
        ),
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to analyze (default: the sheeprl_tpu package)",
    )
    parser.add_argument(
        "--strict", action="store_true",
        help="also fail on stale baseline entries (the CI spelling)",
    )
    parser.add_argument(
        "--baseline", default=None,
        help="baseline file (default: sheeprl_tpu/analysis/baseline.json)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline (show every finding)",
    )
    parser.add_argument(
        "--select", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text", dest="fmt",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue and exit",
    )
    parser.add_argument(
        "--write-baseline", metavar="REASON", default=None,
        help=(
            "regenerate the baseline from current unsuppressed findings, "
            "stamping REASON on new entries (bootstrap helper — edit the "
            "reasons before committing)"
        ),
    )
    parser.add_argument(
        "-v", "--verbose", action="store_true", help="also list baselined findings",
    )
    args = parser.parse_args(argv)

    from sheeprl_tpu.analysis.baseline import DEFAULT_BASELINE, Baseline, BaselineError
    from sheeprl_tpu.analysis.core import RULE_IDS, run_analysis

    if args.list_rules:
        for rule, desc in RULE_IDS.items():
            print(f"{rule:26s} {desc}")
        return 0

    baseline = None
    if not args.no_baseline and args.write_baseline is None:
        path = Path(args.baseline) if args.baseline else DEFAULT_BASELINE
        if path.is_file():
            try:
                baseline = Baseline.load(path)
            except (BaselineError, ValueError) as e:
                print(f"graftlint: invalid baseline {path}: {e}", file=sys.stderr)
                return 2

    select = [r.strip() for r in args.select.split(",")] if args.select else None
    try:
        report = run_analysis(
            args.paths or None, select=select, baseline=baseline,
        )
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline is not None:
        path = Path(args.baseline) if args.baseline else DEFAULT_BASELINE
        Baseline.write(report.findings, path, args.write_baseline)
        print(
            f"graftlint: wrote {len(report.findings)} finding(s) to {path} — "
            "edit the reasons before committing"
        )
        return 0

    if args.fmt == "json":
        print(json.dumps(report.to_dict(), indent=2))
    else:
        print(report.render_text(verbose=args.verbose))

    if report.findings:
        return 1
    if args.strict and report.stale_baseline:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
