"""graftlint — the JAX-law static analyzer (``sheeprl-tpu-lint``).

An AST-based pass enforcing the framework's performance and correctness
contracts at review time instead of runtime: buffer donation discipline
(the PR 7 / PR 14 use-after-donate bug class), trace purity / recompile
hazards, PRNG stream hygiene, and the config / fault-site / metric-family
registries.  See docs/static_analysis.md for the rule catalogue and
suppression etiquette.

Entry points:

* ``sheeprl-tpu-lint`` / ``python -m sheeprl_tpu.analysis`` — the CLI
* :func:`run_analysis` — in-process (the tier-1 test and ``bench.py
  --mode lint`` call this)
* ``# graftlint: disable=<rule>`` — inline suppression;
  ``analysis/baseline.json`` — the accepted-findings ledger
"""

from sheeprl_tpu.analysis.baseline import DEFAULT_BASELINE, Baseline, BaselineError
from sheeprl_tpu.analysis.context import METRIC_FAMILIES, RepoContext
from sheeprl_tpu.analysis.core import RULE_IDS, Finding, Report, run_analysis

__all__ = [
    "Baseline",
    "BaselineError",
    "DEFAULT_BASELINE",
    "Finding",
    "METRIC_FAMILIES",
    "Report",
    "RepoContext",
    "RULE_IDS",
    "run_analysis",
]
