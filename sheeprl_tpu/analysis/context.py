"""Shared repo context for the registry rules: the composed-config key
tree (with per-leaf provenance), the fault-site registry extracted from
``resilience/faults.py``, and the documented metric families.

Everything here is derived **statically** — YAML files are parsed with the
same loader the compose engine uses (so ``1e-3`` floats and friends agree),
and the fault-site registry is read out of ``faults.py``'s AST rather than
imported, keeping the analyzer runnable without initializing anything.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Any, Dict, List, Mapping, Optional, Sequence, Set, Tuple

from sheeprl_tpu.analysis.core import REPO_PACKAGE

#: The documented metric families (docs/static_analysis.md keeps the
#: human-facing table; tests assert the two stay in sync).  A metric name
#: ``Family/rest`` emitted anywhere — aggregator updates, hub sources,
#: ``log_metrics`` payloads, ``extra_metrics`` dicts — must use one of
#: these prefixes or carry a suppression/baseline entry.
METRIC_FAMILIES: Tuple[str, ...] = (
    "Loss",        # per-update optimization losses
    "Rewards",     # episode returns
    "Game",        # episode length / env accounting
    "State",       # world-model latent diagnostics (kl, entropies)
    "Test",        # evaluation rollouts
    "Time",        # utils.timer phase walls
    "Params",      # run parameters surfaced as metrics (replay ratio, lr)
    "Grads",       # gradient norms
    "Info",        # miscellaneous run info (ratios, counters)
    "Compile",     # compile-once recompile detector
    "Checkpoint",  # async snapshot writer
    "Resilience",  # fault injections, retries, watchdogs, breakers
    "Phase",       # telemetry span phase-breakdown fractions
    "Health",      # training-health sentinels
    "Serve",       # policy-as-a-service stats
    "Fleet",       # serving-fleet router (replicas, failovers, migrations)
    "Sebulba",     # actor-learner topology queues/broadcast
    "Dcn",         # cross-host pod transport (segments, broadcast, control)
    "Player",      # PlayerSync staleness
    "Telemetry",   # introspection endpoint self-metrics
    "Population",  # in-trace PBT: fitness spread, exploits, hp quantiles
)

#: config subtrees whose LEAVES are data, not knobs — metric names as keys,
#: user-authored fault plans, partition-rule tables: reading them key-by-key
#: is not how they are consumed, so the dead-key rule skips them.
DEAD_KEY_EXEMPT_PREFIXES: Tuple[str, ...] = (
    "metric.aggregator.metrics",
    "fault_injection.plan",
    "sharding.rules",
)


def _flatten(tree: Mapping[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in tree.items():
        path = f"{prefix}{k}"
        if isinstance(v, Mapping) and v:
            out.update(_flatten(v, path + "."))
        else:
            out[path] = v
    return out


@dataclasses.dataclass
class ConfigLeaf:
    path: str          # dotted, e.g. "buffer.device_mirror"
    file: str          # repo-relative yaml file that (first) defines it
    line: int


class RepoContext:
    """Everything the rules need beyond a single file's AST."""

    def __init__(self) -> None:
        self.config_paths: Set[str] = set()       # every dotted path incl. interior nodes
        self.config_leaves: Dict[str, ConfigLeaf] = {}
        self.yaml_reads: Set[str] = set()          # ${a.b.c} interpolation targets
        self.yaml_fault_sites: List[Tuple[str, str, int]] = []  # (site, file, line)
        self.fault_sites: Tuple[str, ...] = ()
        self.metric_families: Tuple[str, ...] = METRIC_FAMILIES
        self.notes: List[str] = []
        self.root: Path = Path(".")

    # -- construction --------------------------------------------------------
    @classmethod
    def build(cls, root: Path) -> "RepoContext":
        ctx = cls()
        ctx.root = root
        ctx._load_fault_registry(root / REPO_PACKAGE / "resilience" / "faults.py")
        ctx._load_config_tree(root / REPO_PACKAGE / "configs")
        return ctx

    def _load_fault_registry(self, faults_py: Path) -> None:
        """KNOWN_SITES (the site registry; ROW/BYTE/TRACE sites are subsets
        of it) out of faults.py's AST."""
        sites: List[str] = []
        try:
            tree = ast.parse(faults_py.read_text())
        except (OSError, SyntaxError) as e:
            self.notes.append(f"fault registry unavailable ({e}); fault-site rule disabled")
            self.fault_sites = ()
            return
        for node in tree.body:
            if isinstance(node, ast.Assign):
                names = {t.id for t in node.targets if isinstance(t, ast.Name)}
                if "KNOWN_SITES" in names and isinstance(node.value, (ast.Tuple, ast.List)):
                    for elt in node.value.elts:
                        if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                            sites.append(elt.value)
        if not sites:
            self.notes.append("KNOWN_SITES not found in faults.py; fault-site rule disabled")
        self.fault_sites = tuple(sites)

    # -- config tree ---------------------------------------------------------
    def _load_config_tree(self, config_dir: Path) -> None:
        """Union of every YAML file's keys, mounted where compose would put
        them: root ``config.yaml`` and ``exp/*`` at the root, each group dir
        under its group name, and ``@``-placed groups (``/optim@optimizer``)
        at their placement paths inside the placing group.  Groups only ever
        referenced through ``@`` placements (optim, logger) do NOT mount at
        root — recording them there would manufacture dead keys."""
        try:
            from sheeprl_tpu.config.compose import _ConfigLoader  # same float grammar
            import yaml

            def load(path: Path) -> Dict[str, Any]:
                with open(path) as f:
                    data = yaml.load(f, Loader=_ConfigLoader)
                return data if isinstance(data, dict) else {}
        except Exception as e:  # pragma: no cover - yaml always present in repo
            self.notes.append(f"config tree unavailable ({e}); cfg rules disabled")
            return

        if not config_dir.is_dir():
            self.notes.append(f"config dir {config_dir} missing; cfg rules disabled")
            return

        def record(tree: Mapping[str, Any], prefix: str, file: Path) -> None:
            rel = _rel(file, self.root)
            for path, _value in _flatten(tree).items():
                full = f"{prefix}{path}" if prefix else path
                if full not in self.config_leaves:
                    self.config_leaves[full] = ConfigLeaf(
                        full, rel, _yaml_key_line(file, path.rsplit(".", 1)[-1])
                    )
                parts = full.split(".")
                for i in range(1, len(parts) + 1):
                    self.config_paths.add(".".join(parts[:i]))
            _collect_interpolations(tree, self.yaml_reads)
            _collect_fault_sites(tree, rel, file, self.yaml_fault_sites)

        # pass 1: parse every file, strip defaults, collect '@' placements
        # and the root defaults group list
        parsed: List[Tuple[str, Path, Dict[str, Any]]] = []  # (group, file, data)
        at_mounts: Set[Tuple[str, str]] = set()  # (mount prefix, group)
        root_groups: Set[str] = set()

        root_cfg = config_dir / "config.yaml"
        root_data: Dict[str, Any] = {}
        if root_cfg.is_file():
            root_data = load(root_cfg)
            for entry in root_data.pop("defaults", []) or []:
                if isinstance(entry, Mapping):
                    for g in entry:
                        g = str(g)
                        for pfx in ("optional ", "override "):
                            if g.startswith(pfx):
                                g = g[len(pfx):]
                        root_groups.add(g)

        for sub in sorted(config_dir.iterdir()):
            if not sub.is_dir():
                continue
            group = sub.name
            for f in sorted(p for p in sub.iterdir() if p.suffix in (".yaml", ".yml")):
                data = load(f)
                for entry in data.pop("defaults", None) or []:
                    if not isinstance(entry, Mapping):
                        continue
                    for k in entry:
                        k = str(k)
                        if k.startswith("override "):
                            k = k[len("override "):]
                        if "@" in k:
                            src, _, at = k.partition("@")
                            mount = at if group == "exp" else f"{group}.{at}"
                            at_mounts.add((mount, src.lstrip("/")))
                        # '/group: name' entries re-select root groups —
                        # covered by that group's own root mount
                parsed.append((group, f, data))

        # pass 2: record at the right mounts
        at_only = {g for _, g in at_mounts} - root_groups
        if root_data:
            record(root_data, "", root_cfg)
        for group, f, data in parsed:
            if group == "exp":
                record(data, "", f)  # exp overlays mount at root
            elif group not in at_only:
                record(data, f"{group}.", f)
        for mount, group in sorted(at_mounts):
            for g, f, data in parsed:
                if g == group:
                    record(data, f"{mount}.", f)

    # -- queries -------------------------------------------------------------
    def has_config_path(self, path: str) -> bool:
        return path in self.config_paths

    def config_prefix_exists(self, path: str) -> bool:
        """True when ``path`` is a known interior node or leaf."""
        return path in self.config_paths


def _rel(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def _yaml_key_line(file: Path, key: str) -> int:
    """Best-effort line of a YAML key: first ``key:`` occurrence.  Good
    enough for pointing a finding at (duplicate nested key names are rare
    in this tree and the file is always exact)."""
    try:
        lines = file.read_text().splitlines()
    except OSError:
        return 1
    pat = re.compile(rf"^\s*['\"]?{re.escape(key)}['\"]?\s*:")
    for i, raw in enumerate(lines, 1):
        if pat.match(raw):
            return i
    return 1


_INTERP = re.compile(r"\$\{([a-zA-Z0-9_.]+)\}")


def _collect_interpolations(tree: Any, out: Set[str]) -> None:
    if isinstance(tree, Mapping):
        for v in tree.values():
            _collect_interpolations(v, out)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            _collect_interpolations(v, out)
    elif isinstance(tree, str):
        for m in _INTERP.finditer(tree):
            ref = m.group(1)
            if not ref.split(".", 1)[0] in ("env", "eval", "now", "oc"):
                out.add(ref)


#: a mapping is a fault-plan spec only when its "site" key has schedule/kind
#: siblings — the ONE definition shared by the Python-side dict check
#: (registry.py) and the YAML-side plan scan below, so the two can't drift
SPEC_SIBLING_KEYS = ("kind", "at", "every", "p", "seconds", "max_fires", "exception")


def _collect_fault_sites(
    tree: Any, rel: str, file: Path, out: List[Tuple[str, str, int]]
) -> None:
    """``site:`` entries of fault-plan-shaped mappings (a ``site`` key with
    schedule/kind siblings — the fault_injection plan schema)."""
    if isinstance(tree, Mapping):
        site = tree.get("site")
        if isinstance(site, str) and any(k in tree for k in SPEC_SIBLING_KEYS):
            out.append((site, rel, _yaml_key_line(file, "site")))
        for v in tree.values():
            _collect_fault_sites(v, rel, file, out)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            _collect_fault_sites(v, rel, file, out)
