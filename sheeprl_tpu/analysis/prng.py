"""Rule family 3: PRNG discipline.

JAX keys are not stateful generators: passing the same key to two sampling
sites yields *identical* randomness, and the framework's bit-exact
checkpoint/restore contract makes silent stream reuse especially costly
(two "independent" noise sources move in lockstep forever, and the drift
only shows up as training pathology).  Two rules:

* ``prng-key-reuse`` — a key-typed name is consumed by a second sink
  without an intervening ``jax.random.split`` / rebind.  A *sink* is any
  call the key is passed to, except the known non-consuming plumbing
  (``fold_in`` derives without consuming; ``key_data`` / ``device_put`` /
  ``asarray`` / ``replicate`` move or reinterpret).  ``split`` itself
  consumes its operand — using a key after splitting it IS reuse.  The
  scan is branch-aware (an if/else where both arms consume the key once is
  one consumption) and loops are scanned twice, so a key created outside a
  loop and consumed inside it without rebinding is caught.
* ``prng-split-discarded`` — the result of ``jax.random.split`` is thrown
  away (a bare expression statement or an all-``_`` target): the caller
  paid for a new stream and kept none of it, which almost always means the
  OLD key keeps getting used.

Key-typed names: bound from ``jax.random.PRNGKey/key/split/fold_in`` or
``fabric.seed_everything``, or parameters spelled like keys (``key``,
``k``, ``rng``, ``*_key``).  Only plain names are tracked — attributes and
containers are out of scope by design (precision over recall).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from sheeprl_tpu.analysis.core import (
    Finding,
    FlowState,
    SourceFile,
    assigned_names,
    call_name,
    flow_scan,
)

#: callables that CREATE key values (assignment RHS)
_KEY_MAKERS = ("PRNGKey", "key", "split", "fold_in", "seed_everything", "wrap_key_data", "clone")

#: callables a key can pass through without being consumed
_NON_CONSUMING = (
    "fold_in",          # derives a new stream, original stays usable
    "key_data", "wrap_key_data", "clone",
    "device_put", "asarray", "array", "replicate", "copy", "copy_to",
    "block_until_ready", "to_host", "shard_batch",
    "print", "repr", "str", "format", "append", "isinstance", "len",
    "ShapeDtypeStruct", "tree_map", "debug_print",
    # plain-value builtins: params that merely LOOK key-named (copies_per_key)
    # flow through these without touching any PRNG stream
    "int", "float", "bool", "max", "min", "abs", "round", "sum", "type",
)

_KEY_PARAM_NAMES = ("key", "k", "rng", "prng_key", "player_key")


def _is_key_param(name: str) -> bool:
    return name in _KEY_PARAM_NAMES or name.endswith("_key") or name.endswith("_rng")


def check(src: SourceFile, ctx) -> List[Finding]:
    findings: List[Finding] = []
    _scan(src, src.tree.body, set(), findings, "module")
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = {
                a.arg
                for a in node.args.posonlyargs + node.args.args + node.args.kwonlyargs
                if _is_key_param(a.arg)
            }
            _scan(src, node.body, params, findings, node.name)
    return findings


def _scan(
    src: SourceFile,
    body: Sequence[ast.stmt],
    initial_keys: Set[str],
    findings: List[Finding],
    context: str,
) -> None:
    state = _PrngState(src, findings, context)
    for k in initial_keys:
        state.keys[k] = None
    flow_scan(body, state)


class _PrngState(FlowState):
    def __init__(self, src: SourceFile, findings: List[Finding], context: str):
        self.src = src
        self.findings = findings
        self.context = context
        #: key name -> consumption site description (None = fresh)
        self.keys: Dict[str, Optional[str]] = {}

    def fork(self) -> "_PrngState":
        s = _PrngState(self.src, self.findings, self.context)
        s.keys = dict(self.keys)
        return s

    def merge(self, *branches: "_PrngState") -> None:
        for b in branches:
            for name, consumed in b.keys.items():
                if name not in self.keys or (consumed is not None and self.keys[name] is None):
                    self.keys[name] = consumed

    def visit(self, stmt: ast.stmt) -> None:
        # split-result-discarded: a bare `jax.random.split(...)` statement
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            if call_name(stmt.value) == "split" and _is_jax_random(stmt.value):
                self.findings.append(
                    Finding(
                        "prng-split-discarded",
                        self.src.rel,
                        stmt.lineno,
                        "result of jax.random.split is discarded — the old key "
                        "is still live and will be reused",
                        context=self.context,
                    )
                )
        if isinstance(stmt, ast.Assign):
            targets = _flat_names(stmt.targets)
            if (
                targets
                and all(t == "_" for t in targets)
                and isinstance(stmt.value, ast.Call)
                and call_name(stmt.value) == "split"
                and _is_jax_random(stmt.value)
            ):
                self.findings.append(
                    Finding(
                        "prng-split-discarded",
                        self.src.rel,
                        stmt.lineno,
                        "every result of jax.random.split is assigned to '_'",
                        context=self.context,
                    )
                )

        # consumption events, in source order inside the statement
        rebound = assigned_names(stmt)
        for call in _calls_no_nested(stmt):
            cname = call_name(call)
            if cname in _NON_CONSUMING:
                continue
            seen_in_call: Set[str] = set()
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                if not (isinstance(arg, ast.Name) and isinstance(arg.ctx, ast.Load)):
                    continue
                name = arg.id
                if name not in self.keys:
                    continue
                if name in seen_in_call:
                    self.findings.append(
                        Finding(
                            "prng-key-reuse",
                            self.src.rel,
                            call.lineno,
                            f"key '{name}' passed twice to '{cname}' in one call",
                            context=self.context,
                        )
                    )
                    continue
                seen_in_call.add(name)
                prior = self.keys[name]
                if prior is not None:
                    self.findings.append(
                        Finding(
                            "prng-key-reuse",
                            self.src.rel,
                            call.lineno,
                            f"key '{name}' consumed again by '{cname}' after {prior} "
                            "— split it (or thread the returned key) first",
                            context=self.context,
                        )
                    )
                else:
                    self.keys[name] = f"being consumed by '{cname}' (line {call.lineno})"

        # creations / rebinding LAST: `key, tk = split(key)` consumes the
        # old key above, then the new binding resets it here
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            if call_name(stmt.value) in _KEY_MAKERS and (
                _is_jax_random(stmt.value) or call_name(stmt.value) == "seed_everything"
            ):
                for t in _flat_names(stmt.targets):
                    if t != "_":
                        self.keys[t] = None
        for name in rebound:
            if name in self.keys:
                self.keys[name] = None


def _calls_no_nested(stmt: ast.stmt):
    """Call nodes in this statement, in source order, skipping nested
    function/lambda bodies (their execution time is unknowable here)."""
    calls: List[ast.Call] = []
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            calls.append(node)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    return calls


def _flat_names(targets: Sequence[ast.expr]) -> List[str]:
    out: List[str] = []

    def collect(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect(e)
        elif isinstance(t, ast.Starred):
            collect(t.value)

    for t in targets:
        collect(t)
    return out


def _is_jax_random(call: ast.Call) -> bool:
    """``jax.random.X(...)`` / ``random.X(...)`` / ``jrandom.X(...)`` —
    or a bare name imported from jax.random (``from jax.random import
    split``).  Bare-name calls are accepted: the cost of a false 'is
    jax.random' here is only a slightly eager finding on stdlib-random
    code, which this codebase never mixes with key plumbing."""
    func = call.func
    if isinstance(func, ast.Attribute):
        chain_root = func.value
        while isinstance(chain_root, ast.Attribute):
            chain_root = chain_root.value
        if isinstance(chain_root, ast.Name) and chain_root.id in ("jax", "random", "jrandom", "jr"):
            return True
        return False
    return isinstance(func, ast.Name)
