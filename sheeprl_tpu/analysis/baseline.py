"""Baseline mechanics: the checked-in ledger of ACCEPTED findings.

``analysis/baseline.json`` lets the repo land at zero *unsuppressed*
findings without papering over the analyzer's precision limits inline.
Every entry names a rule, an optional file, a ``match`` substring against
the finding message, and a mandatory human **reason** — an entry without a
reason is a validation error, because a baseline whose entries nobody can
explain is just a mute button.

Matching is content-based (rule + file + message substring), NOT
line-based: line numbers churn with every edit above a finding, and a
baseline that goes stale on unrelated refactors trains people to
regenerate it blindly.  ``--strict`` additionally fails on entries that
matched nothing — a fixed finding must take its baseline entry with it.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, List, Optional

from sheeprl_tpu.analysis.core import RULE_IDS, Finding

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


class BaselineError(ValueError):
    pass


class Baseline:
    def __init__(self, entries: List[Dict[str, Any]], path: Optional[Path] = None):
        self.path = path
        self.entries = entries
        self._hits = [0] * len(entries)
        for i, e in enumerate(entries):
            if not isinstance(e, dict):
                raise BaselineError(f"baseline entry {i} is not an object: {e!r}")
            rule = e.get("rule")
            if rule not in RULE_IDS:
                raise BaselineError(f"baseline entry {i} names unknown rule {rule!r}")
            if not str(e.get("reason", "")).strip():
                raise BaselineError(
                    f"baseline entry {i} ({rule} {e.get('file', '*')}) has no "
                    "reason — every accepted finding must say why"
                )

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        data = json.loads(Path(path).read_text())
        if isinstance(data, dict):
            entries = data.get("entries", [])
        else:
            entries = data
        return cls(list(entries), path=Path(path))

    @classmethod
    def empty(cls) -> "Baseline":
        return cls([])

    def matches(self, f: Finding) -> bool:
        for i, e in enumerate(self.entries):
            if e["rule"] != f.rule:
                continue
            file = e.get("file")
            if file and file != f.path:
                continue
            match = e.get("match")
            if match and match not in f.message and match != f.context:
                continue
            self._hits[i] += 1
            return True
        return False

    def stale_entries(self) -> List[Dict[str, Any]]:
        return [e for e, h in zip(self.entries, self._hits) if h == 0]

    @staticmethod
    def write(findings: List[Finding], path: Path, reason: str) -> None:
        """Regenerate a baseline from current findings (one entry per
        finding, keyed by rule+file+context-or-message).  The caller-supplied
        reason is stamped on every entry as a placeholder to be edited —
        ``--write-baseline`` is a bootstrap, not a workflow."""
        entries = []
        seen = set()
        for f in findings:
            match = f.context or f.message[:80]
            key = (f.rule, f.path, match)
            if key in seen:
                continue
            seen.add(key)
            entries.append(
                {"rule": f.rule, "file": f.path, "match": match, "reason": reason}
            )
        payload = {
            "version": 1,
            "_comment": (
                "Accepted graftlint findings. Entries match by rule + file + "
                "message/context substring (never by line). Every entry MUST "
                "carry a real reason. --strict fails on entries matching "
                "nothing — delete them when the finding is fixed. See "
                "docs/static_analysis.md."
            ),
            "entries": entries,
        }
        Path(path).write_text(json.dumps(payload, indent=2) + "\n")
