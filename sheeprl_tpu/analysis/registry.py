"""Rule family 4: registry cross-checks.

The framework has three string-keyed registries whose consumers and
producers live far apart, so a typo validates nowhere until runtime (or
never — a dead YAML knob silently reassures whoever flips it):

* ``cfg-unknown-key`` — every ``cfg.<a>.<b>`` attribute chain in the
  package must resolve against the union of the Hydra-style YAML tree
  under ``sheeprl_tpu/configs/`` (root config, group files under their
  group, exp overlays at root, ``@``-placed groups at their mounts).
  ``.get("k", default)`` steps are the sanctioned optional-access
  spelling and are never errors (they still count as reads).
* ``cfg-dead-key`` — a YAML leaf no code path reads.  The read-set is
  collected from the package PLUS the read-only roots (tests/, bench.py,
  benchmarks/, examples/, the graft entry): prefix reads cover subtrees
  (``build_optimizer(cfg.algo.actor.optimizer)`` reads everything under
  it), ``${a.b.c}`` YAML interpolations count, and a final conservative
  fallback treats a leaf as read when its last segment appears anywhere
  in code as an attribute name or an exact string literal (that is how
  ``topo_cfg.get("env_workers")``-style subtree reads look).  What
  survives all of that is genuinely dead.
* ``fault-site-unknown`` — every fault-site string literal (hook calls,
  ``site=`` kwargs, ``"site":`` dict entries, YAML fault plans) must
  exist in ``resilience/faults.py``'s ``KNOWN_SITES`` registry.
* ``metric-family-unknown`` — every emitted metric name ``Family/rest``
  (aggregator updates, ``log_metrics`` payload keys, ``Family/``-keyed
  subscript stores, ``AGGREGATOR_KEYS`` tables, ``extra_metrics`` dicts)
  must use a documented family (``context.METRIC_FAMILIES``; the
  human-readable catalogue lives in docs/static_analysis.md).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from sheeprl_tpu.analysis.context import (
    DEAD_KEY_EXEMPT_PREFIXES,
    SPEC_SIBLING_KEYS,
    RepoContext,
)
from sheeprl_tpu.analysis.core import (
    REPO_PACKAGE,
    Finding,
    SourceFile,
    attr_chain,
    call_name,
    iter_py_files,
    relpath,
)

#: extra roots scanned for READS only (they never produce findings, but a
#: key only they read is not dead)
READ_ONLY_ROOTS = ("tests", "benchmarks", "examples", "bench.py", "__graft_entry__.py")

#: dict/dotdict methods that terminate a cfg chain without extending it
_DICT_METHODS = (
    "keys", "values", "items", "pop", "update", "setdefault", "copy",
    "clear", "to_dict", "as_dict", "get",
)

_METRIC_RE = re.compile(r"^[A-Z][A-Za-z0-9_]*/[\w./\- %]+$")

_FAULT_HOOKS = ("fault_point", "fault_bytes", "fault_rows")


# ---------------------------------------------------------------------------
# cfg access collection
# ---------------------------------------------------------------------------

class CfgAccess:
    __slots__ = ("path", "line", "optional", "context")

    def __init__(self, path: str, line: int, optional: bool, context: str):
        self.path = path
        self.line = line
        self.optional = optional
        self.context = context


def cfg_accesses(src: SourceFile) -> List[CfgAccess]:
    """Per-file cfg-access list, computed ONCE per SourceFile — both the
    unknown-key check and the dead-config harvest need it, and the walk
    (binding resolution + per-node chain analysis) is the most expensive
    part of this rule family."""
    cached = getattr(src, "_cfg_accesses", None)
    if cached is None:
        cached = _collect_cfg_accesses(src.tree)
        src._cfg_accesses = cached
    return cached


def _collect_cfg_accesses(tree: ast.Module) -> List[CfgAccess]:
    """Attribute/get chains rooted at a name ``cfg`` — plus one level of
    subtree variables (``v = cfg.algo.world_model`` makes later ``v.x``
    accesses resolve as ``algo.world_model.x``)."""
    accesses: List[CfgAccess] = []

    # scope-less variable->(path, optional) bindings; name collisions across
    # scopes make this slightly over-eager, which only ever ADDS reads
    # (helping the dead-key rule) and resolves unknown-key paths that
    # plainly exist.  A binding through .get() keeps its optionality: later
    # chains on the variable are still the sanctioned optional spelling.
    bindings: Dict[str, Tuple[str, bool]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            res = _chain_of(node.value, bindings)
            if res is not None and res[0]:
                bindings[node.targets[0].id] = res

    func_of: Dict[int, str] = {}
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                func_of.setdefault(id(sub), fn.name)

    seen: Set[int] = set()
    for node in ast.walk(tree):
        if id(node) in seen:
            continue
        if isinstance(node, (ast.Attribute, ast.Call)):
            res = _chain_of(node, bindings)
            if res is None:
                continue
            # mark every sub-node consumed (even for empty paths, e.g. a
            # bare `cfg.get(dynamic)`) so inner attributes of the same chain
            # don't re-report shorter prefixes
            for sub in ast.walk(node):
                seen.add(id(sub))
            path, optional = res
            if not path:
                continue
            accesses.append(
                CfgAccess(path, node.lineno, optional, func_of.get(id(node), ""))
            )
    return accesses


def _chain_of(node: ast.AST, bindings: Dict[str, Tuple[str, bool]]) -> Optional[Tuple[str, bool]]:
    """Resolve a ``cfg.a.b`` / ``cfg.a.get("b")`` / ``v.c`` expression to
    ``(dotted path, passed-through-optional-get)``.  None = not a cfg
    expression."""
    parts: List[str] = []
    optional = False
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Call):
            # only .get("literal"[, default]) extends the chain
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr == "get":
                if node.args and isinstance(node.args[0], ast.Constant) and isinstance(node.args[0].value, str):
                    parts.append(node.args[0].value)
                    optional = True
                    node = func.value
                    continue
                # .get(<dynamic>) — chain ends at the receiver
                node = func.value
                optional = True
                continue
            if isinstance(func, ast.Attribute) and func.attr in _DICT_METHODS:
                node = func.value
                continue
            return None
        elif isinstance(node, ast.Name):
            root = node.id
            if root == "cfg":
                prefix: List[str] = []
            elif root in bindings:
                bound_path, bound_optional = bindings[root]
                prefix = bound_path.split(".")
                optional = optional or bound_optional
            else:
                return None
            # drop trailing dict-method segments that slipped into parts
            chain = prefix + parts[::-1]
            chain = [c for c in chain if c not in _DICT_METHODS]
            return ".".join(chain), optional
        elif isinstance(node, ast.Subscript):
            # dynamic subscript: chain ends; keep what we have as a read of
            # the receiver subtree
            node = node.value
            optional = True
        else:
            return None


# ---------------------------------------------------------------------------
# per-file checks
# ---------------------------------------------------------------------------

def check_file(src: SourceFile, ctx: RepoContext) -> List[Finding]:
    findings: List[Finding] = []
    findings.extend(_check_cfg_unknown(src, ctx))
    findings.extend(_check_fault_sites(src, ctx))
    findings.extend(_check_metric_families(src, ctx))
    return findings


def _check_cfg_unknown(src: SourceFile, ctx: RepoContext) -> List[Finding]:
    if not ctx.config_paths:
        return []
    findings: List[Finding] = []
    for access in cfg_accesses(src):
        if access.optional:
            continue
        if ctx.has_config_path(access.path):
            continue
        # report at the deepest resolving prefix for a useful message
        parts = access.path.split(".")
        known = ""
        for i in range(len(parts) - 1, 0, -1):
            p = ".".join(parts[:i])
            if ctx.has_config_path(p):
                known = p
                break
        if known and known in ctx.config_leaves:
            # the chain resolves to a LEAF and keeps going: the tail is
            # attribute access on the value (`cfg.buffer.device.lower()`),
            # not a config path
            continue
        findings.append(
            Finding(
                "cfg-unknown-key",
                src.rel,
                access.line,
                f"cfg.{access.path} has no backing key in sheeprl_tpu/configs/"
                + (f" (deepest resolving prefix: '{known}')" if known else ""),
                context=access.context,
            )
        )
    return findings


def _check_fault_sites(src: SourceFile, ctx: RepoContext) -> List[Finding]:
    if not ctx.fault_sites:
        return []
    # the registry definition file itself is the source of truth
    if src.rel.endswith("resilience/faults.py"):
        return []
    sites = set(ctx.fault_sites)
    findings: List[Finding] = []

    def bad(lit: str, line: int, how: str) -> None:
        findings.append(
            Finding(
                "fault-site-unknown",
                src.rel,
                line,
                f"fault site '{lit}' ({how}) is not in resilience/faults.py "
                f"KNOWN_SITES — a typo here silently disarms the drill",
            )
        )

    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call):
            cname = call_name(node)
            if cname in _FAULT_HOOKS and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                    if a0.value not in sites:
                        bad(a0.value, node.lineno, f"first arg of {cname}")
            if cname == "FaultSpec":
                # NOTE: only FaultSpec's site= names an injection site; the
                # retry/Watchdog primitives also take site= but that labels
                # Resilience/* metric accounting, a different namespace
                for kw in node.keywords:
                    if (
                        kw.arg == "site"
                        and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)
                        and kw.value.value not in sites
                    ):
                        bad(kw.value.value, node.lineno, "FaultSpec site= kwarg")
        elif isinstance(node, ast.Dict):
            entry = _fault_spec_dict(node)
            if entry is not None:
                site, line = entry
                if site not in sites:
                    bad(site, line, "fault-plan spec dict")
    return findings


#: a dict is a fault-plan spec only when "site" has schedule/kind siblings —
#: bare {"site": ...} dicts exist in other schemas.  The sibling-key set is
#: context.SPEC_SIBLING_KEYS, shared with the YAML-side plan scan so the
#: Python and YAML halves of this rule can't drift.
_SPEC_SIBLINGS = SPEC_SIBLING_KEYS


def _fault_spec_dict(node: ast.Dict) -> Optional[Tuple[str, int]]:
    keys = {
        k.value for k in node.keys
        if isinstance(k, ast.Constant) and isinstance(k.value, str)
    }
    if "site" not in keys or not keys.intersection(_SPEC_SIBLINGS):
        return None
    for k, v in zip(node.keys, node.values):
        if (
            isinstance(k, ast.Constant) and k.value == "site"
            and isinstance(v, ast.Constant) and isinstance(v.value, str)
        ):
            return v.value, v.lineno
    return None


#: metric-emission shapes: .update("Family/...", ...) on an aggregator-ish
#: receiver; dict keys in log_metrics(...) / extra_metrics=; subscript
#: stores with a Family/ literal key; AGGREGATOR_KEYS tables
def _check_metric_families(src: SourceFile, ctx: RepoContext) -> List[Finding]:
    families = set(ctx.metric_families)
    findings: List[Finding] = []

    def verify(lit: str, line: int, how: str) -> None:
        if not _METRIC_RE.match(lit):
            return
        family = lit.split("/", 1)[0]
        if family not in families:
            findings.append(
                Finding(
                    "metric-family-unknown",
                    src.rel,
                    line,
                    f"metric '{lit}' ({how}) uses undocumented family "
                    f"'{family}/' — add it to the documented families "
                    "(docs/static_analysis.md + analysis/context.py) or fold "
                    "it into an existing one",
                )
            )

    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call):
            cname = call_name(node)
            if cname == "update" and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                    verify(a0.value, node.lineno, "aggregator update")
            if cname == "log_metrics" and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Dict):
                    for k in a0.keys:
                        if isinstance(k, ast.Constant) and isinstance(k.value, str):
                            verify(k.value, k.lineno, "log_metrics key")
            for kw in node.keywords:
                if kw.arg == "extra_metrics" and isinstance(kw.value, ast.Dict):
                    for k in kw.value.keys:
                        if isinstance(k, ast.Constant) and isinstance(k.value, str):
                            verify(k.value, k.lineno, "extra_metrics key")
        elif isinstance(node, ast.Assign):
            # metrics["Family/x"] = ... subscript stores
            for t in node.targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)
                ):
                    verify(t.slice.value, t.lineno, "metric-dict store")
            # AGGREGATOR_KEYS = ["Family/x", ...] tables
            names = {x.id for x in node.targets if isinstance(x, ast.Name)}
            if any("AGGREGATOR" in n or "METRICS" in n for n in names) and isinstance(
                node.value, (ast.List, ast.Tuple, ast.Set)
            ):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        verify(elt.value, elt.lineno, "aggregator-keys table")
    return findings


# ---------------------------------------------------------------------------
# repo-level checks (need the whole read-set)
# ---------------------------------------------------------------------------

def check_repo(
    sources: Sequence[SourceFile], ctx: RepoContext, dead_config: bool = True
) -> List[Finding]:
    findings: List[Finding] = []
    if ctx.config_paths and dead_config:
        findings.extend(_check_dead_config(sources, ctx))
    if ctx.fault_sites:
        sites = set(ctx.fault_sites)
        for site, rel, line in ctx.yaml_fault_sites:
            if site not in sites:
                findings.append(
                    Finding(
                        "fault-site-unknown",
                        rel,
                        line,
                        f"fault site '{site}' in a YAML fault plan is not in "
                        "resilience/faults.py KNOWN_SITES",
                    )
                )
    return findings


def _check_dead_config(sources: Sequence[SourceFile], ctx: RepoContext) -> List[Finding]:
    reads: Set[str] = set(ctx.yaml_reads)
    attr_names: Set[str] = set()
    str_consts: Set[str] = set()

    def harvest(tree: ast.Module, accesses: Optional[List[CfgAccess]] = None) -> None:
        for access in accesses if accesses is not None else _collect_cfg_accesses(tree):
            reads.add(access.path)
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute):
                attr_names.add(node.attr)
            elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                s = node.value
                if 0 < len(s) < 80:
                    str_consts.add(s)
                    # `a.b.c=value` CLI-override literals read a.b.c
                    if "=" in s:
                        reads.add(s.split("=", 1)[0].lstrip("+"))

    for src in sources:
        harvest(src.tree, cfg_accesses(src))  # reuses the check_file walk
    for extra in READ_ONLY_ROOTS:
        p = ctx.root / extra
        if not p.exists():
            continue
        for f in iter_py_files([p]):
            try:
                harvest(ast.parse(f.read_text()))
            except (SyntaxError, UnicodeDecodeError):
                continue

    read_prefixes = reads  # every read covers its whole subtree

    def is_read(path: str) -> bool:
        parts = path.split(".")
        for i in range(1, len(parts) + 1):
            if ".".join(parts[:i]) in read_prefixes:
                return True
        last = parts[-1]
        return last in attr_names or last in str_consts

    findings: List[Finding] = []
    for path, leaf in sorted(ctx.config_leaves.items()):
        if any(path == p or path.startswith(p + ".") for p in DEAD_KEY_EXEMPT_PREFIXES):
            continue
        if is_read(path):
            continue
        findings.append(
            Finding(
                "cfg-dead-key",
                leaf.file,
                leaf.line,
                f"config key '{path}' is read by no code path (dead config) — "
                "remove it or route it through a deprecation shim",
                context=path,
            )
        )
    return findings
