"""The self-healing run supervisor (``sheeprl-tpu-supervise``).

Launches any training CLI invocation as a child process and keeps it
alive the way an external operator would — but with the judgment the
PR 13 telemetry gives it:

* **heartbeat**: the child is forced to arm its introspection endpoint
  (``telemetry.introspect.port=0``; the URL is parsed off its stdout) and
  ``/healthz`` is polled — an unreachable endpoint past a grace window,
  or a ``stalled: true`` (HTTP 503, update-free past
  ``telemetry.stall_after_s``) answer that persists, gets the child
  killed (SIGTERM first: the preemption latch turns that into a final
  committed save) and restarted;
* **classification** (``classify.py``): every exit is triaged on the exit
  status + the run's ``postmortem.json``.  Transient failures (signals,
  hangs, first-occurrence crashes, preemptions, missing postmortems)
  restart under a budget with jittered exponential backoff and
  ``checkpoint.resume_from=auto`` — the run continues from its last
  committed snapshot.  The SAME fatal signature ``(error, last_step)``
  twice in a row opens the **crash-loop circuit breaker**: the supervisor
  stops, exits nonzero, and surfaces the postmortem reason instead of
  looping;
* **audit**: every episode appends one JSON line to
  ``<log_dir>/<root_dir>/supervisor_log.jsonl`` — when the run finally
  needs a human, the whole restart history is one file.

Exit codes: ``0`` the run completed; ``2`` the circuit breaker opened
(deterministic failure — the postmortem reason is printed); ``3`` the
restart budget is exhausted; the child's own code when the supervisor
itself was told to stop (SIGTERM/SIGINT are forwarded to the child).

Configured by the ``supervisor.*`` Hydra group; see docs/supervisor.md.
"""

from __future__ import annotations

import glob
import json
import os
import random
import re
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from sheeprl_tpu.supervisor.classify import (
    DETERMINISTIC,
    SUCCESS,
    Verdict,
    classify,
    load_postmortem,
)

_URL_RE = re.compile(r"telemetry introspection on (http://\S+)")

#: supervisor exit codes (documented in docs/supervisor.md)
EXIT_OK = 0
EXIT_BREAKER = 2
EXIT_BUDGET = 3


def _now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S%z")


class Supervisor:
    """One supervised run: episodes of the same child invocation."""

    def __init__(
        self,
        cfg: Any,
        argv: List[str],
        *,
        child_cmd: Optional[Callable[[List[str]], List[str]]] = None,
        child_env: Optional[Dict[str, str]] = None,
        handle_signals: bool = True,
    ):
        scfg = (cfg.get("supervisor") or {}) if hasattr(cfg, "get") else {}
        self.cfg = cfg
        self.argv = list(argv)
        self.max_restarts = int(scfg.get("max_restarts", 10))
        self.breaker_threshold = max(2, int(scfg.get("breaker_threshold", 2) or 2))
        self.backoff_base_s = float(scfg.get("backoff_base_s", 2.0))
        self.backoff_max_s = float(scfg.get("backoff_max_s", 60.0))
        self.poll_interval_s = float(scfg.get("poll_interval_s", 2.0))
        self.heartbeat_grace_s = float(scfg.get("heartbeat_grace_s", 60.0))
        self.stall_grace_s = float(scfg.get("stall_grace_s", 30.0))
        self.first_heartbeat_timeout_s = float(scfg.get("first_heartbeat_timeout_s", 0.0) or 0.0)
        self.progress_timeout_s = float(scfg.get("progress_timeout_s", 0.0) or 0.0)
        self.kill_grace_s = float(scfg.get("kill_grace_s", 30.0))
        self.introspect = bool(scfg.get("introspect", True))
        log_dir = str(cfg.get("log_dir", "logs/runs")) if hasattr(cfg, "get") else "logs/runs"
        root_dir = str(cfg.get("root_dir", "run")) if hasattr(cfg, "get") else "run"
        self.exp_root = os.path.join(log_dir, root_dir)
        self.audit_path = os.path.join(
            self.exp_root, str(scfg.get("log_name", "supervisor_log.jsonl"))
        )
        self._child_cmd = child_cmd or (
            lambda child_argv: [sys.executable, "-m", "sheeprl_tpu", *child_argv]
        )
        self._child_env = dict(child_env) if child_env else None
        self._handle_signals = bool(handle_signals)
        self._rng = random.Random(int(scfg.get("seed", 0) or 0) or None)
        self._stop = threading.Event()
        self._child: Optional[subprocess.Popen] = None
        self._url: Optional[str] = None
        self._url_event = threading.Event()
        self.restarts_used = 0
        self._last_signature: Optional[tuple] = None
        self._signature_run = 0
        self.episodes: List[Dict[str, Any]] = []

    # -- signal forwarding ----------------------------------------------------
    def install_signals(self) -> None:
        """SIGTERM/SIGINT stop the SUPERVISOR: the signal is forwarded to
        the child (whose preemption latch performs a final committed save)
        and no restart follows — a preempted pod must drain, not respawn."""
        if not self._handle_signals:
            return
        if threading.current_thread() is not threading.main_thread():
            return
        def handler(signum: int, frame: Any) -> None:
            self._stop.set()
            child = self._child
            if child is not None and child.poll() is None:
                try:
                    child.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        try:
            signal.signal(signal.SIGTERM, handler)
            signal.signal(signal.SIGINT, handler)
        except (ValueError, OSError):
            pass

    # -- episode mechanics ----------------------------------------------------
    def _episode_argv(self, episode: int) -> List[str]:
        child_argv = list(self.argv)
        if self.introspect and not any(
            a.startswith("telemetry.introspect.port=") for a in child_argv
        ):
            child_argv.append("telemetry.introspect.port=0")
        if episode > 0:
            # appended LAST so it wins over any user-given resume_from: a
            # relaunch must resume from the newest committed snapshot, which
            # by now is the previous episode's, not the user's original
            child_argv.append("checkpoint.resume_from=auto")
        return child_argv

    def _spawn(self, episode: int) -> subprocess.Popen:
        cmd = self._child_cmd(self._episode_argv(episode))
        env = None
        if self._child_env is not None:
            env = {**os.environ, **self._child_env}
        self._url = None
        self._url_event.clear()
        child = subprocess.Popen(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        self._child = child

        def drain() -> None:
            try:
                for line in child.stdout:  # type: ignore[union-attr]
                    sys.stdout.write(line)
                    sys.stdout.flush()
                    if self._url is None:
                        m = _URL_RE.search(line)
                        if m:
                            self._url = m.group(1)
                            self._url_event.set()
            except (ValueError, OSError):
                pass  # pipe closed under us during kill

        threading.Thread(target=drain, name="supervisor-stdout", daemon=True).start()
        return child

    def _healthz(self) -> Optional[Dict[str, Any]]:
        """One ``/healthz`` probe: the parsed body (including 503 stalled
        answers), or None when unreachable."""
        if not self._url:
            return None
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(
                self._url + "/healthz", timeout=min(5.0, max(1.0, self.poll_interval_s))
            ) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            if e.code == 503:
                try:
                    return json.loads(e.read().decode())
                except Exception:
                    return {"ok": False, "stalled": True}
            return None
        except Exception:
            return None

    def _kill_child(self, child: subprocess.Popen) -> None:
        """SIGTERM (graceful: the preemption latch commits a final save),
        escalate to SIGKILL past the grace window."""
        if child.poll() is not None:
            return
        try:
            child.send_signal(signal.SIGTERM)
        except OSError:
            return
        try:
            child.wait(timeout=self.kill_grace_s)
        except subprocess.TimeoutExpired:
            try:
                child.kill()
            except OSError:
                pass
            try:
                child.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                pass

    def _watch(self, child: subprocess.Popen, started: float) -> bool:
        """Block until the child exits or the watchdog kills it.  Returns
        True when the supervisor decided the child was HUNG."""
        last_ok: Optional[float] = None
        stalled_since: Optional[float] = None
        last_updates: Optional[int] = None
        last_progress = started
        while True:
            if child.poll() is not None:
                return False
            if self._stop.is_set():
                self._kill_child(child)
                return False
            now = time.monotonic()
            hung = False
            body = self._healthz()
            if body is not None:
                if body.get("stalled"):
                    stalled_since = stalled_since or now
                    if now - stalled_since > self.stall_grace_s:
                        self._log_line(
                            f"child stalled (last_update_age_s="
                            f"{body.get('last_update_age_s')}) past the grace window"
                        )
                        hung = True
                else:
                    stalled_since = None
                    last_ok = now
                updates = body.get("updates_done")
                if isinstance(updates, int):
                    if updates != last_updates:
                        last_updates = updates
                        last_progress = now
                    elif (
                        self.progress_timeout_s > 0
                        and updates > 0
                        and now - last_progress > self.progress_timeout_s
                    ):
                        self._log_line("child made no update progress past the timeout")
                        hung = True
            else:
                if self._url is not None:
                    if last_ok is None:
                        # the URL just appeared: start the unreachable clock
                        # NOW — a child that prints its URL but whose server
                        # never answers a single probe must still be killable
                        last_ok = now
                    elif now - last_ok > self.heartbeat_grace_s:
                        self._log_line("child heartbeat unreachable past the grace window")
                        hung = True
                elif (
                    self.first_heartbeat_timeout_s > 0
                    and now - started > self.first_heartbeat_timeout_s
                ):
                    self._log_line("child never armed its introspection endpoint")
                    hung = True
            if hung:
                self._kill_child(child)
                return True
            # wait on the URL event the first time around so short-lived
            # children don't sleep a full interval before being noticed
            if not self._url_event.is_set():
                self._url_event.wait(self.poll_interval_s)
            else:
                time.sleep(self.poll_interval_s)

    def _find_postmortem(self, not_before: float) -> Optional[str]:
        """Newest postmortem.json under the experiment root written since
        ``not_before`` (each episode gets a fresh timestamped run dir, so
        mtime-filtering keeps old episodes' evidence out).  The tolerance
        is a bare float-jitter epsilon: anything generous (e.g. 1 s) would
        let a fast relaunch re-read the PREVIOUS episode's preemption
        postmortem and misclassify a clean completion as preempted."""
        newest, newest_mtime = None, not_before - 1e-3
        for path in glob.glob(
            os.path.join(glob.escape(self.exp_root), "**", "postmortem.json"), recursive=True
        ):
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                continue
            if mtime > newest_mtime:
                newest, newest_mtime = path, mtime
        return newest

    # -- audit ----------------------------------------------------------------
    def _log_line(self, msg: str) -> None:
        print(f"[supervisor] {msg}", flush=True)

    def _append_audit(self, record: Dict[str, Any]) -> None:
        self.episodes.append(record)
        try:
            os.makedirs(os.path.dirname(self.audit_path), exist_ok=True)
            with open(self.audit_path, "a") as f:
                f.write(json.dumps(record, default=str) + "\n")
        except OSError as e:
            self._log_line(f"audit log write failed: {e}")

    def _backoff_s(self) -> float:
        base = min(
            self.backoff_max_s,
            self.backoff_base_s * (2.0 ** max(0, self.restarts_used - 1)),
        )
        return base * self._rng.uniform(0.5, 1.5)

    # -- the supervision loop --------------------------------------------------
    def run(self) -> int:
        self.install_signals()
        self._log_line(
            f"supervising: {' '.join(self.argv)} "
            f"(max_restarts={self.max_restarts}, breaker={self.breaker_threshold})"
        )
        episode = 0
        while True:
            started_mono = time.monotonic()
            started_wall = time.time()
            started_iso = _now_iso()
            child = self._spawn(episode)
            hung = self._watch(child, started_mono)
            returncode = child.wait()
            pm_path = self._find_postmortem(started_wall)
            postmortem = load_postmortem(pm_path)
            verdict = classify(returncode, postmortem, hung=hung)

            # crash-loop circuit breaker: the SAME fatal signature twice in
            # a row is a deterministic failure — stop and surface it
            if verdict.signature is not None and verdict.signature == self._last_signature:
                self._signature_run += 1
            else:
                self._signature_run = 1
            self._last_signature = verdict.signature
            if (
                verdict.signature is not None
                and self._signature_run >= self.breaker_threshold
            ):
                verdict = Verdict(
                    DETERMINISTIC,
                    f"circuit breaker open: identical fatal signature "
                    f"{self._signature_run}x in a row — {verdict.reason}",
                    signature=verdict.signature,
                    detail=verdict.detail,
                )

            stopping = self._stop.is_set()
            budget_left = self.max_restarts - self.restarts_used
            if verdict.kind == SUCCESS or stopping:
                action, delay = "done", 0.0
            elif verdict.kind == DETERMINISTIC:
                action, delay = "stop", 0.0
            elif budget_left <= 0:
                action, delay = "budget-exhausted", 0.0
            else:
                action = "restart"
                self.restarts_used += 1
                delay = self._backoff_s()

            record = {
                "episode": episode,
                "started_at": started_iso,
                "ended_at": _now_iso(),
                "wall_s": round(time.monotonic() - started_mono, 3),
                "returncode": returncode,
                "hung": hung,
                "classification": verdict.kind,
                "reason": verdict.reason,
                "signature": list(verdict.signature) if verdict.signature else None,
                "signature_run": self._signature_run,
                "postmortem": pm_path,
                "action": action,
                "next_delay_s": round(delay, 3),
                "restarts_used": self.restarts_used,
                **({"detail": verdict.detail} if verdict.detail else {}),
            }
            self._append_audit(record)
            self._log_line(
                f"episode {episode}: rc={returncode} hung={hung} -> "
                f"{verdict.kind} ({verdict.reason}); action={action}"
            )

            if verdict.kind == SUCCESS:
                return EXIT_OK
            if stopping:
                self._log_line("stop requested — not restarting")
                # only a sane positive child code passes through: a
                # signal-killed child reports a NEGATIVE returncode, and
                # sys.exit(-15) would surface as shell status 241 —
                # indistinguishable from a crash to scripts keying on the
                # documented 0/2/3 codes
                return returncode if returncode and returncode > 0 else EXIT_OK
            if verdict.kind == DETERMINISTIC:
                reason = (postmortem or {}).get("reason") if postmortem else None
                err = verdict.signature[0] if verdict.signature else verdict.reason
                self._log_line(
                    f"giving up: deterministic failure (postmortem reason="
                    f"{reason!r}): {err}"
                )
                return EXIT_BREAKER
            if action == "budget-exhausted":
                self._log_line(
                    f"giving up: restart budget exhausted "
                    f"(supervisor.max_restarts={self.max_restarts})"
                )
                return EXIT_BUDGET

            self._log_line(
                f"restarting (attempt {self.restarts_used}/{self.max_restarts}) "
                f"in {delay:.1f}s with checkpoint.resume_from=auto"
            )
            if self._stop.wait(delay):
                self._log_line("stop requested during backoff — not restarting")
                return EXIT_OK
            episode += 1


def main(argv: Optional[List[str]] = None) -> None:
    """``sheeprl-tpu-supervise <the same overrides you would pass to
    sheeprl-tpu>``: composes the config once (for the ``supervisor.*`` and
    path knobs), then supervises the child invocation."""
    argv = list(sys.argv[1:] if argv is None else argv)
    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.supervisor.pod import resolve_supervisor

    cfg = compose(argv)
    sys.exit(resolve_supervisor(cfg, argv).run())
