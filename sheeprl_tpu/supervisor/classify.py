"""Failure classification: transient infra vs deterministic crash.

The supervisor's one hard rule: **never restart-loop a deterministic
failure**.  A child that dies the same way at the same step twice will die
a third time — restarting it burns the restart budget, the TPU
reservation, and the on-call's patience while hiding the actual bug.
Everything else (SIGKILL'd by the scheduler, a wedged host, a transient
storage error, a preemption, a first-occurrence exception) is worth one
resume-from-checkpoint attempt under the budget.

Classification evidence, in order of trust:

1. the **hang verdict** the supervisor itself reached (its heartbeat
   watchdog killed the child) — the exit status is then meaningless (a
   SIGTERM'd child often exits 0 through its preemption save);
2. the **exit status**: 0 = success; killed by a signal = infra;
3. the **postmortem** (``postmortem.json``, PR 13): its ``reason`` and,
   for exceptions, a *fatal signature* ``(error, last_step)`` — the same
   signature twice in a row opens the circuit breaker.

A missing or malformed postmortem is itself a signal the child died hard
(OOM-killer, segfault before the dump) — treated as transient, bounded by
the restart budget.
"""

from __future__ import annotations

import json
import signal as _signal
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

#: classification kinds
SUCCESS = "success"
TRANSIENT = "transient"
DETERMINISTIC = "deterministic"
PREEMPTED = "preempted"
DIVERGED = "diverged"


@dataclass
class Verdict:
    """One episode's classification."""

    kind: str  # SUCCESS | TRANSIENT | DETERMINISTIC | PREEMPTED | DIVERGED
    reason: str  # human-readable one-liner for the audit log
    #: fatal signature for breaker matching — (error, last_step) for
    #: exceptions, ("hang", last_step) for watchdog kills, None when the
    #: failure mode cannot be deterministic (signals, missing postmortem)
    signature: Optional[Tuple[str, Any]] = None
    #: free-form evidence forwarded into supervisor_log.jsonl
    detail: Dict[str, Any] = field(default_factory=dict)

    @property
    def restartable(self) -> bool:
        return self.kind in (TRANSIENT, PREEMPTED, DIVERGED)


def _signal_name(returncode: int) -> str:
    try:
        return _signal.Signals(-returncode).name
    except (ValueError, OverflowError):
        return f"signal {-returncode}"


def load_postmortem(path: Optional[str]) -> Optional[Dict[str, Any]]:
    """Parse a postmortem.json; None when absent/undecodable/not ours."""
    if not path:
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or not str(doc.get("schema", "")).startswith("sheeprl.postmortem/"):
        return None
    return doc


def crash_error(postmortem: Dict[str, Any]) -> Optional[str]:
    """The newest ``crash`` event's error string (the exception that ended
    the run, recorded by ``cli.run``'s crash path)."""
    events = postmortem.get("events")
    if not isinstance(events, list):
        return None
    for evt in reversed(events):
        if isinstance(evt, dict) and evt.get("kind") == "crash":
            err = evt.get("error")
            return str(err) if err is not None else None
    return None


def classify(
    returncode: Optional[int],
    postmortem: Optional[Dict[str, Any]],
    *,
    hung: bool = False,
) -> Verdict:
    """Classify one finished episode (see module docstring for the rules).

    ``postmortem`` is the already-parsed document (or None); ``hung`` means
    the supervisor's own watchdog killed the child, which overrides the
    exit status.  Breaker accounting — "same signature twice" — is the
    caller's job: this function only derives the signature.
    """
    last_step = postmortem.get("last_step") if isinstance(postmortem, dict) else None

    if hung:
        return Verdict(
            TRANSIENT,
            "hang: heartbeat/progress watchdog killed the child",
            signature=("hang", last_step),
            detail={"last_step": last_step},
        )

    # BEFORE the rc==0 success branch: a preempted child exits 0 — the
    # latch breaks the loop and cli.run returns normally after the final
    # committed save — but it did NOT finish its configured steps.  The
    # preemption postmortem (only written when the latch fired) is the
    # tell; a genuinely completed run leaves no such document.
    if isinstance(postmortem, dict) and str(postmortem.get("reason", "")) == "preemption":
        return Verdict(
            PREEMPTED,
            "preemption latch honored (final committed save)",
            signature=None,
            detail={"last_step": last_step},
        )

    if returncode == 0:
        return Verdict(SUCCESS, "clean exit (rc=0)")

    if returncode is not None and returncode < 0:
        # killed by a signal the child never handled (kill -9, OOM, segv):
        # infrastructure, by definition not reproducible from the program's
        # own state — restart under the budget, never the breaker
        return Verdict(
            TRANSIENT,
            f"killed by {_signal_name(returncode)}",
            signature=None,
            detail={"last_step": last_step},
        )

    if postmortem is None:
        return Verdict(
            TRANSIENT,
            f"nonzero exit (rc={returncode}) with missing/malformed postmortem",
            signature=None,
        )

    reason = str(postmortem.get("reason", ""))
    error = crash_error(postmortem) or f"rc={returncode}, reason={reason or 'unknown'}"
    if "DivergenceError" in error:
        # the health sentinels surfaced divergence: restarting with
        # resume_from=auto IS the rollback-to-last-committed-checkpoint —
        # but repeated divergence at the same step is deterministic, so it
        # carries a signature for the breaker like any other crash
        return Verdict(
            DIVERGED,
            f"training diverged: {error}",
            signature=(error, last_step),
            detail={"last_step": last_step},
        )

    return Verdict(
        TRANSIENT,
        f"crash: {error}",
        signature=(error, last_step),
        detail={"last_step": last_step, "reason": reason},
    )
