"""The pod-aware supervisor: collective restart for multi-process runs.

One :class:`PodSupervisor` drives one pod: every episode it spawns ALL
``num_processes`` cells of the fake-DCN protocol (fresh coordinator port,
``SHEEPRL_DCN_*`` env per cell, rank-prefixed output) and applies the pod's
collective failure semantics on top of the single-child machinery it
inherits from :class:`~sheeprl_tpu.supervisor.supervise.Supervisor`:

* **any-cell crash is pod death** — a cell exiting NONZERO while its
  peers live (SIGKILLed host, crash, watchdog hard-exit 75) triggers
  coordinated teardown: SIGTERM to every survivor (their preemption
  latches run final committed saves where possible), SIGKILL past
  ``kill_grace_s``.  No rank is left training past a dead peer — the
  in-run :class:`~sheeprl_tpu.parallel.distributed.PeerWatchdog` enforces
  this from the inside; the supervisor enforces it from the outside.  A
  cell exiting ZERO is the done→goodbye protocol completing, not a death
  (actors routinely finish a beat before the learner's finalize).
* **collective restart** — classification (breaker/budget/backoff) is the
  inherited single-run logic, fed by the learner's exit status and the
  episode's most *telling* postmortem: the newest NON-preemption document
  (the culprit's crash evidence) when one exists, else the newest overall
  (everyone honoring the latch = a preemption verdict).  A restart
  relaunches ALL ranks with ``checkpoint.resume_from=auto`` appended —
  every cell resumes from the newest COMMIT under the shared checkpoint
  root, so the pod restarts from one agreed snapshot.
* **audit** — the same ``supervisor_log.jsonl`` line per episode, with a
  ``cells`` block recording each rank's return code.

The heartbeat/stall watchdog it inherits keys on the learner cell (rank 0
owns the introspection endpoint the URL regex finds first) — a wedged
learner is killed and the teardown above fans out to the actors.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from sheeprl_tpu.parallel.distributed import (
    ENV_COORD,
    ENV_FAKE,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
    free_port,
)
from sheeprl_tpu.supervisor.classify import load_postmortem
from sheeprl_tpu.supervisor.supervise import _URL_RE, Supervisor


class PodSupervisor(Supervisor):
    """Episodes of an entire pod instead of a single child."""

    def __init__(
        self,
        cfg: Any,
        argv: List[str],
        num_processes: int,
        *,
        child_cmd: Optional[Callable[[List[str]], List[str]]] = None,
        child_env: Optional[Dict[str, str]] = None,
        handle_signals: bool = True,
    ):
        super().__init__(
            cfg, argv, child_cmd=child_cmd, child_env=child_env, handle_signals=handle_signals
        )
        if num_processes < 2:
            raise ValueError("PodSupervisor needs num_processes >= 2 (use Supervisor)")
        self.num_processes = int(num_processes)
        self._cells: List[subprocess.Popen] = []

    # -- spawning: the whole pod ----------------------------------------------
    def _spawn(self, episode: int) -> subprocess.Popen:
        cmd = self._child_cmd(self._episode_argv(episode))
        base_env = dict(os.environ)
        if self._child_env is not None:
            base_env.update(self._child_env)
        base_env.pop(ENV_PROCESS_ID, None)
        xla_flags = base_env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in xla_flags:
            base_env["XLA_FLAGS"] = (
                xla_flags + " --xla_force_host_platform_device_count=1"
            ).strip()
        coord = f"127.0.0.1:{free_port()}"  # fresh coordinator per episode
        self._url = None
        self._url_event.clear()
        self._cells = []
        for rank in range(self.num_processes):
            env = dict(base_env)
            env.update(
                {
                    ENV_FAKE: str(self.num_processes),
                    ENV_PROCESS_ID: str(rank),
                    ENV_NUM_PROCESSES: str(self.num_processes),
                    ENV_COORD: coord,
                    "JAX_PLATFORMS": "cpu",
                }
            )
            cell = subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True
            )
            self._cells.append(cell)
            threading.Thread(
                target=self._relay, args=(cell, rank), name=f"pod-relay[{rank}]", daemon=True
            ).start()
        # rank 0 (the learner cell — it writes COMMIT and owns the
        # introspection endpoint) is "the child" the inherited watch,
        # returncode and classification key on
        self._child = self._cells[0]
        return self._cells[0]

    def _relay(self, cell: subprocess.Popen, rank: int) -> None:
        try:
            for line in cell.stdout:  # type: ignore[union-attr]
                sys.stdout.write(f"[dcn:{rank}] {line}")
                sys.stdout.flush()
                if rank == 0 and self._url is None:
                    m = _URL_RE.search(line)
                    if m:
                        self._url = m.group(1)
                        self._url_event.set()
        except (ValueError, OSError):
            pass  # pipe closed under us during teardown

    # -- collective teardown ---------------------------------------------------
    def _terminate_pod(self, exclude: Optional[subprocess.Popen] = None) -> None:
        """SIGTERM every live cell (preemption latch → final save where the
        checkpoint path still works), SIGKILL past ``kill_grace_s``."""
        live = [c for c in self._cells if c is not exclude and c.poll() is None]
        for c in live:
            try:
                c.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + self.kill_grace_s
        for c in live:
            try:
                c.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    c.kill()
                    c.wait(timeout=10.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass

    def _kill_child(self, child: subprocess.Popen) -> None:
        # the inherited watchdog decided the learner is hung: the whole pod
        # goes down with it — survivors would only block on a dead front
        super()._kill_child(child)
        self._terminate_pod(exclude=child)

    def _watch(self, child: subprocess.Popen, started: float) -> bool:
        """The inherited learner heartbeat watch, plus the pod rule: ANY
        cell exiting ends the episode for every rank."""
        sidecar_stop = threading.Event()

        def sidecar() -> None:
            while not sidecar_stop.wait(0.5):
                dead = {i: c.poll() for i, c in enumerate(self._cells) if c.poll() is not None}
                if 0 in dead:
                    return  # the inherited watch sees the learner exit itself
                # only a CRASHED peer is pod death.  An actor exiting 0 is
                # the done→goodbye protocol completing (it routinely beats
                # the learner's own finalize by a few seconds) — tearing
                # the learner down for it turns every successful episode
                # into a SIGTERM "failure".  An actor that exits 0 when it
                # should NOT have is the learner front's heartbeat-grace /
                # PeerLost problem, handled inside the run.
                crashed = [i for i, rc in dead.items() if rc != 0]
                if not crashed:
                    continue
                rc = dead[crashed[0]]
                self._log_line(
                    f"pod cell {crashed[0]} exited (rc={rc}) — coordinated teardown"
                )
                # give the survivors one grace window to notice on their own
                # (PeerWatchdog/PeerLost) and commit final saves, then the
                # teardown escalates for real
                self._terminate_pod()
                return

        t = threading.Thread(target=sidecar, name="pod-sidecar", daemon=True)
        t.start()
        try:
            hung = super()._watch(child, started)
        finally:
            sidecar_stop.set()
            t.join(timeout=2.0)
        # the learner is down (exit or kill): reap the rest before
        # classification so the next episode never races leftover cells
        # over the coordinator port or the checkpoint root
        self._terminate_pod(exclude=child)
        return hung

    # -- evidence --------------------------------------------------------------
    def _find_postmortem(self, not_before: float) -> Optional[str]:
        """Prefer the episode's newest NON-preemption postmortem: in a
        coordinated teardown every surviving rank honors the latch and
        writes a ``reason: preemption`` document — the one cell that
        actually crashed wrote the document worth classifying."""
        import glob as _glob

        candidates: List[tuple] = []
        for path in _glob.glob(
            os.path.join(_glob.escape(self.exp_root), "**", "postmortem.json"), recursive=True
        ):
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                continue
            if mtime > not_before - 1e-3:
                candidates.append((mtime, path))
        if not candidates:
            return None
        candidates.sort()
        for _, path in reversed(candidates):
            doc = load_postmortem(path)
            if doc is not None and str(doc.get("reason", "")) != "preemption":
                return path
        return candidates[-1][1]

    # -- audit ----------------------------------------------------------------
    def _append_audit(self, record: Dict[str, Any]) -> None:
        record = dict(record)
        record["cells"] = [
            {"rank": r, "returncode": c.poll()} for r, c in enumerate(self._cells)
        ]
        record["num_processes"] = self.num_processes
        super()._append_audit(record)


def resolve_supervisor(cfg: Any, argv: List[str], **kwargs: Any) -> Supervisor:
    """The launch-time routing: a pod-shaped invocation (``SHEEPRL_FAKE_DCN``
    set, or ``fabric.distributed.num_processes`` configured > 1) gets the
    :class:`PodSupervisor`; everything else the plain :class:`Supervisor`."""
    from sheeprl_tpu.parallel.distributed import distributed_cfg

    num = int(os.environ.get(ENV_FAKE, 0) or 0)
    if num <= 1:
        num = int(distributed_cfg(cfg).get("num_processes") or 0)
    if num > 1:
        env = dict(kwargs.pop("child_env", None) or {})
        # the launcher-mode env var must NOT leak into the cells as a
        # re-launch trigger; _spawn sets the full per-cell protocol itself
        return PodSupervisor(cfg, argv, num, child_env=env or None, **kwargs)
    return Supervisor(cfg, argv, **kwargs)
