"""``python -m sheeprl_tpu.supervisor`` — the ``sheeprl-tpu-supervise``
entry point without an installed console script."""

from sheeprl_tpu.supervisor.supervise import main

if __name__ == "__main__":
    main()
