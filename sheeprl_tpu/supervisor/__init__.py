"""Self-healing run supervision (docs/supervisor.md).

``supervise`` owns the process-level loop — launch the training CLI as a
child, heartbeat it through the PR 13 introspection endpoint, restart
transient failures from the last committed checkpoint under a budget and
backoff; ``classify`` owns the transient-vs-deterministic triage of each
exit (the crash-loop circuit breaker's evidence).  The in-loop half of
the robustness story — the training-health sentinels that skip poisoned
updates and detect divergence *inside* the run — lives in
``sheeprl_tpu.resilience.health``.
"""

from sheeprl_tpu.supervisor.classify import (
    DETERMINISTIC,
    DIVERGED,
    PREEMPTED,
    SUCCESS,
    TRANSIENT,
    Verdict,
    classify,
    crash_error,
    load_postmortem,
)
from sheeprl_tpu.supervisor.pod import PodSupervisor, resolve_supervisor
from sheeprl_tpu.supervisor.supervise import (
    EXIT_BREAKER,
    EXIT_BUDGET,
    EXIT_OK,
    Supervisor,
    main,
)

__all__ = [
    "DETERMINISTIC",
    "DIVERGED",
    "EXIT_BREAKER",
    "EXIT_BUDGET",
    "EXIT_OK",
    "PREEMPTED",
    "PodSupervisor",
    "SUCCESS",
    "TRANSIENT",
    "Supervisor",
    "Verdict",
    "classify",
    "crash_error",
    "load_postmortem",
    "main",
    "resolve_supervisor",
]
