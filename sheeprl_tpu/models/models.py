"""Model building blocks (flax.linen, channel-last, MXU-friendly).

Capability parity with the reference block library
(reference: sheeprl/models/models.py:16-525): MLP, CNN, DeCNN, NatureCNN,
LayerNormGRUCell, MultiEncoder/MultiDecoder, dtype-preserving LayerNorm —
redesigned for TPU:

* images are NHWC (XLA TPU conv layout), not NCHW;
* every module takes a ``dtype`` (compute) / ``param_dtype`` pair so bf16
  activations hit the MXU while params stay fp32;
* LayerNorm computes in fp32 and casts back (the reference forces fp32 LN
  output for numerics, models.py:507-525 — here we keep the policy but
  return the compute dtype, which is what XLA fuses best);
* the recurrent cell is shaped for ``flax.linen.scan`` / ``lax.scan`` over
  time — no per-step Python loops anywhere.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

ModuleDef = Any
Activation = Callable[[jax.Array], jax.Array]


def get_activation(name: Union[str, Activation, None]) -> Activation:
    if name is None:
        return lambda x: x
    if callable(name):
        return name
    table = {
        "relu": nn.relu,
        "tanh": jnp.tanh,
        "silu": nn.silu,
        "swish": nn.silu,
        "gelu": nn.gelu,
        "elu": nn.elu,
        "leaky_relu": nn.leaky_relu,
        "sigmoid": nn.sigmoid,
        "identity": lambda x: x,
    }
    if name not in table:
        raise ValueError(f"Unknown activation '{name}'")
    return table[name]


class LayerNorm(nn.Module):
    """LayerNorm computed in fp32 for stability, output cast to ``dtype``."""

    dtype: Any = jnp.float32
    eps: float = 1e-5
    use_scale: bool = True
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        y = nn.LayerNorm(
            epsilon=self.eps,
            use_scale=self.use_scale,
            use_bias=self.use_bias,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
        )(x.astype(jnp.float32))
        return y.astype(self.dtype)


class MLP(nn.Module):
    """Configurable dense stack (reference: models/models.py:16-119).

    ``hidden_sizes`` plus optional ``output_dim`` head; per-layer LayerNorm /
    dropout / activation.  ``flatten_dim`` flattens trailing dims before the
    first layer.
    """

    hidden_sizes: Sequence[int] = ()
    output_dim: Optional[int] = None
    activation: Union[str, Activation] = "tanh"
    layer_norm: bool = False
    dropout_rate: float = 0.0
    flatten_input: bool = False
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        act = get_activation(self.activation)
        if self.flatten_input and x.ndim > 1:
            x = x.reshape(*x.shape[:1], -1) if x.ndim == 2 else x.reshape(*x.shape[:-3], -1)
        x = x.astype(self.dtype)
        for i, size in enumerate(self.hidden_sizes):
            x = nn.Dense(size, dtype=self.dtype, param_dtype=self.param_dtype, name=f"dense_{i}")(x)
            if self.layer_norm:
                x = LayerNorm(dtype=self.dtype, name=f"ln_{i}")(x)
            x = act(x)
            if self.dropout_rate > 0.0:
                x = nn.Dropout(self.dropout_rate, deterministic=deterministic)(x)
        if self.output_dim is not None:
            x = nn.Dense(
                self.output_dim, dtype=self.dtype, param_dtype=self.param_dtype, name="head"
            )(x)
        return x


class CNN(nn.Module):
    """Conv stack over NHWC images (reference: models/models.py:122-202)."""

    channels: Sequence[int]
    kernel_sizes: Union[int, Sequence[int]] = 3
    strides: Union[int, Sequence[int]] = 2
    activation: Union[str, Activation] = "relu"
    layer_norm: bool = False
    flatten_output: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = get_activation(self.activation)
        n = len(self.channels)
        ks = [self.kernel_sizes] * n if isinstance(self.kernel_sizes, int) else list(self.kernel_sizes)
        st = [self.strides] * n if isinstance(self.strides, int) else list(self.strides)
        x = x.astype(self.dtype)
        for i, (c, k, s) in enumerate(zip(self.channels, ks, st)):
            x = nn.Conv(
                c, (k, k), strides=(s, s), padding="SAME",
                dtype=self.dtype, param_dtype=self.param_dtype, name=f"conv_{i}",
            )(x)
            if self.layer_norm:
                x = LayerNorm(dtype=self.dtype, name=f"ln_{i}")(x)
            x = act(x)
        if self.flatten_output:
            x = x.reshape(*x.shape[:-3], -1)
        return x


class DeCNN(nn.Module):
    """Transposed-conv stack, NHWC (reference: models/models.py:205-285)."""

    channels: Sequence[int]
    kernel_sizes: Union[int, Sequence[int]] = 4
    strides: Union[int, Sequence[int]] = 2
    paddings: Union[str, int, Sequence[Any]] = "SAME"
    activation: Union[str, Activation] = "relu"
    layer_norm: bool = False
    final_activation: Union[str, Activation, None] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = get_activation(self.activation)
        final_act = get_activation(self.final_activation)
        n = len(self.channels)
        ks = [self.kernel_sizes] * n if isinstance(self.kernel_sizes, int) else list(self.kernel_sizes)
        st = [self.strides] * n if isinstance(self.strides, int) else list(self.strides)
        x = x.astype(self.dtype)
        for i, (c, k, s) in enumerate(zip(self.channels, ks, st)):
            last = i == n - 1
            x = nn.ConvTranspose(
                c, (k, k), strides=(s, s), padding=self.paddings if isinstance(self.paddings, str) else "SAME",
                dtype=self.dtype, param_dtype=self.param_dtype, name=f"deconv_{i}",
            )(x)
            if not last:
                if self.layer_norm:
                    x = LayerNorm(dtype=self.dtype, name=f"ln_{i}")(x)
                x = act(x)
            else:
                x = final_act(x)
        return x


class NatureCNN(nn.Module):
    """DQN-Nature conv encoder + dense head
    (reference: models/models.py:288-328).  Input NHWC uint8/float."""

    features_dim: int = 512
    activation: Union[str, Activation] = "relu"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = get_activation(self.activation)
        x = x.astype(self.dtype)
        for i, (c, k, s) in enumerate(((32, 8, 4), (64, 4, 2), (64, 3, 1))):
            x = nn.Conv(
                c, (k, k), strides=(s, s), padding="VALID",
                dtype=self.dtype, param_dtype=self.param_dtype, name=f"conv_{i}",
            )(x)
            x = act(x)
        x = x.reshape(*x.shape[:-3], -1)
        x = nn.Dense(self.features_dim, dtype=self.dtype, param_dtype=self.param_dtype)(x)
        return act(x)


class LayerNormGRUCell(nn.Module):
    """Hafner-variant GRU cell: LayerNorm on the fused input/recurrent
    projection and a ``-1`` bias on the update gate
    (reference: models/models.py:331-410) — the hot recurrent cell of all
    Dreamers.

    One fused ``Dense(3*units)`` matmul per step keeps the MXU busy; wrap
    with ``flax.linen.scan`` (see :func:`scan_rnn`) for the time loop.
    """

    units: int
    layer_norm: bool = True
    use_pallas: bool = False  # fused VMEM-resident Pallas kernel (TPU);
    # NOTE: pallas and flax paths have different param layouts — pick the
    # flag at model-creation time (checkpoints are flag-specific)
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h: jax.Array, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        if self.use_pallas and self.layer_norm:
            from sheeprl_tpu.ops.gru_pallas import fused_layernorm_gru

            d_in = x.shape[-1] + self.units
            w = self.param(
                "fused_kernel",
                nn.initializers.lecun_normal(),
                (d_in, 3 * self.units),
                self.param_dtype,
            )
            scale = self.param("ln_scale", nn.initializers.ones_init(), (3 * self.units,), self.param_dtype)
            bias = self.param("ln_bias", nn.initializers.zeros_init(), (3 * self.units,), self.param_dtype)
            new_h = fused_layernorm_gru(x, h, w, scale, bias).astype(self.dtype)
            return new_h, new_h
        inp = jnp.concatenate([x.astype(self.dtype), h.astype(self.dtype)], axis=-1)
        parts = nn.Dense(
            3 * self.units,
            use_bias=not self.layer_norm,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="fused",
        )(inp)
        if self.layer_norm:
            parts = LayerNorm(dtype=self.dtype, name="ln")(parts)
        reset, cand, update = jnp.split(parts, 3, axis=-1)
        reset = nn.sigmoid(reset)
        cand = jnp.tanh(reset * cand)
        update = nn.sigmoid(update - 1.0)
        new_h = update * cand + (1.0 - update) * h.astype(self.dtype)
        return new_h, new_h

    @staticmethod
    def initial_state(batch: int, units: int, dtype: Any = jnp.float32) -> jax.Array:
        return jnp.zeros((batch, units), dtype)


class MultiEncoder(nn.Module):
    """Fuse per-key CNN and MLP encoders by concatenating feature vectors
    (reference: models/models.py:413-475).

    ``cnn_keys`` observations are concatenated on channels and encoded once;
    ``mlp_keys`` are concatenated on features and encoded once — same fusion
    strategy as the reference.
    """

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_channels: Sequence[int] = (32, 64, 128, 256)
    cnn_layer_norm: bool = False
    cnn_features_dim: Optional[int] = None
    mlp_sizes: Sequence[int] = (256, 256)
    mlp_layer_norm: bool = False
    mlp_features_dim: Optional[int] = None
    activation: Union[str, Activation] = "silu"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        act = get_activation(self.activation)
        feats = []
        if self.cnn_keys:
            img = jnp.concatenate([obs[k] for k in self.cnn_keys], axis=-1)
            y = CNN(
                channels=self.cnn_channels,
                kernel_sizes=4,
                strides=2,
                activation=self.activation,
                layer_norm=self.cnn_layer_norm,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="cnn_encoder",
            )(img)
            if self.cnn_features_dim:
                y = act(
                    nn.Dense(
                        self.cnn_features_dim, dtype=self.dtype,
                        param_dtype=self.param_dtype, name="cnn_proj",
                    )(y)
                )
            feats.append(y)
        if self.mlp_keys:
            vec = jnp.concatenate([obs[k] for k in self.mlp_keys], axis=-1)
            y = MLP(
                hidden_sizes=self.mlp_sizes,
                activation=self.activation,
                layer_norm=self.mlp_layer_norm,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="mlp_encoder",
            )(vec)
            if self.mlp_features_dim:
                y = act(
                    nn.Dense(
                        self.mlp_features_dim, dtype=self.dtype,
                        param_dtype=self.param_dtype, name="mlp_proj",
                    )(y)
                )
            feats.append(y)
        if not feats:
            raise ValueError("MultiEncoder needs at least one cnn or mlp key")
        return jnp.concatenate(feats, axis=-1)


class MultiDecoder(nn.Module):
    """Latent features → per-key observation reconstructions
    (reference: sheeprl/models/models.py:480-504).

    The inverse of :class:`MultiEncoder`: one shared DeCNN branch emits all
    ``cnn_keys`` concatenated on channels (then split per key), and one
    shared MLP trunk feeds a per-key Dense head for each of ``mlp_keys``.
    The CNN branch stems from a Dense projection to a
    ``(h0, w0, cnn_stem_channels)`` seed where ``h0 = H / 2**n_deconvs`` —
    so ``cnn_channels`` must agree with the target resolution
    (``len(cnn_channels) + 1`` stride-2 deconvs).

    MLP heads emit fp32 regardless of the compute dtype — reconstruction
    targets feed losses, and keeping the head output fp32 is this repo's
    LayerNorm-style numerics policy.
    """

    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    cnn_shapes: Dict[str, Tuple[int, int, int]] = None  # key -> (H, W, C)
    mlp_shapes: Dict[str, int] = None  # key -> flat dim
    cnn_channels: Sequence[int] = (64, 32)
    cnn_stem_channels: int = 128
    mlp_sizes: Sequence[int] = (256, 256)
    kernel_size: int = 4
    stride: int = 2
    activation: Union[str, Activation] = "relu"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, features: jax.Array) -> Dict[str, jax.Array]:
        if not self.cnn_keys and not self.mlp_keys:
            raise ValueError("MultiDecoder needs at least one cnn or mlp key")
        act = get_activation(self.activation)
        out: Dict[str, jax.Array] = {}
        if self.cnn_keys:
            n_deconvs = len(self.cnn_channels) + 1
            h, w, _ = next(iter(self.cnn_shapes.values()))
            h0, w0 = h // 2**n_deconvs, w // 2**n_deconvs
            total_c = sum(self.cnn_shapes[k][-1] for k in self.cnn_keys)
            x = nn.Dense(
                h0 * w0 * self.cnn_stem_channels,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="cnn_in",
            )(features)
            x = act(x)
            x = x.reshape(*x.shape[:-1], h0, w0, self.cnn_stem_channels)
            x = DeCNN(
                channels=tuple(self.cnn_channels) + (total_c,),
                kernel_sizes=self.kernel_size,
                strides=self.stride,
                activation=self.activation,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="decnn",
            )(x)
            start = 0
            for k in self.cnn_keys:
                c = self.cnn_shapes[k][-1]
                out[k] = x[..., start:start + c]
                start += c
        if self.mlp_keys:
            trunk = MLP(
                hidden_sizes=self.mlp_sizes,
                activation=self.activation,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="mlp",
            )(features)
            for k in self.mlp_keys:
                out[k] = nn.Dense(
                    self.mlp_shapes[k],
                    dtype=jnp.float32,
                    param_dtype=self.param_dtype,
                    name=f"head_{k}",
                )(trunk)
        return out


def cnn_forward(fn: Callable, x: jax.Array, image_ndim: int = 3) -> jax.Array:
    """Flatten leading ``(T, B)`` dims around an image op, restore after —
    the ``(T, B, *)`` convention adapter (reference: sheeprl/utils/model.py:165+)."""
    lead = x.shape[:-image_ndim]
    flat = x.reshape((-1,) + x.shape[-image_ndim:])
    y = fn(flat)
    return y.reshape(lead + y.shape[1:])
