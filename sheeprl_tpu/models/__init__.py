"""sheeprl_tpu.models."""
