"""Seeded, deterministic fault-injection engine.

Faults are described by a :class:`FaultPlan`: a list of :class:`FaultSpec`
entries, each naming an **injection site** (a string like ``env.step`` —
the full registry is :data:`KNOWN_SITES`), a **schedule** (fire at the
site's N-th invocation, every K-th, or with seeded probability ``p``) and a
**fault kind**:

* ``raise``   — raise :class:`InjectedFault` (or an importable exception),
* ``hang``    — sleep ``seconds`` (simulates a wedged worker / dead disk),
* ``latency`` — sleep ``seconds`` then continue (slow link, GC pause),
* ``corrupt`` — flip bytes of the payload passing through the site,
* ``truncate``— drop the tail of the payload passing through the site.

The plan comes from the ``fault_injection`` config group
(``fault_injection.enabled=true fault_injection.plan='[...]'``) or from the
``SHEEPRL_FAULT_PLAN`` environment variable (a JSON list of spec dicts —
the spelling that crosses process boundaries: spawned env workers, the
decoupled trainer, subprocess drills).

**Zero overhead when disabled is a hard guarantee** (gated in ``bench.py``):
:func:`install_plan` stores ``None`` when the plan has no specs, and every
hot-path hook (:func:`fault_point`, :func:`fault_bytes`) starts with a
single module-global ``is None`` test.  Nothing else — no dict lookups, no
monitor calls — happens on the disabled path.

Determinism: ``at``/``every`` schedules count the site's invocations in the
current process (each env worker counts its own steps); ``p`` schedules
draw from a per-spec ``random.Random`` seeded with
``seed ^ crc32(site)``, so a run with the same plan and seed injects the
same fault sequence.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence

#: The named injection sites wired through the runtime.  A spec naming an
#: unknown site is rejected at plan-build time (typos must not silently
#: disarm a chaos drill).
KNOWN_SITES = (
    "env.step",
    "env.reset",
    "checkpoint.write_shard",
    "checkpoint.commit",
    "serve.http",
    "serve.router",
    "serve.replica",
    "fabric.copy_to",
    "replay.spill",
    "sebulba.env_worker",
    "sebulba.traj_queue",
    "update.grads",
    "dcn.broadcast",
    "dcn.traj",
)

KINDS = ("raise", "hang", "latency", "corrupt", "truncate", "nonfinite", "divergence")

#: Sites whose hook passes a byte payload (``fault_bytes``) — the only
#: legal targets for ``corrupt`` specs.  The two ``dcn.*`` sites sit on
#: the cross-host wire AFTER the CRC stamp: ``corrupt``/``truncate``
#: there model a damaged DCN payload, which the receiving cell's CRC
#: check must reject (torn-segment / torn-broadcast contract).
BYTE_SITES = ("checkpoint.write_shard", "dcn.broadcast", "dcn.traj")

#: Sites whose hook passes replay rows (``fault_rows``): ``truncate`` there
#: tail-halves the queued rows (a torn spill write / a torn trajectory
#: segment), not a byte payload.
ROW_SITES = ("replay.spill", "sebulba.traj_queue")

#: Sites whose faults are compiled INTO the train trace by the health
#: sentinels (``resilience/health.py``) rather than polled host-side.
#: ``nonfinite`` poisons the update's params/loss with NaN (what a NaN
#: gradient does), ``divergence`` multiplies the loss the spike detector
#: sees — both deterministically, at the spec's ``at``/``every`` guarded
#: dispatch number, with ZERO per-step host involvement (the schedule is
#: resolved at trace-build time, so the guarded executable stays one
#: program and the transfer guard sees no extra H2D).  ``p`` schedules are
#: rejected here: a host RNG draw per dispatch would need a per-step
#: transfer.
TRACE_SITES = ("update.grads",)
TRACE_KINDS = ("nonfinite", "divergence")

ENV_VAR = "SHEEPRL_FAULT_PLAN"


class InjectedFault(RuntimeError):
    """The default exception raised by a ``kind: raise`` fault."""


@dataclass
class FaultSpec:
    """One fault: where, when, and what."""

    site: str
    kind: str = "raise"
    #: fire exactly at the site's N-th invocation (1-based)
    at: Optional[int] = None
    #: fire at every K-th invocation
    every: Optional[int] = None
    #: fire with this seeded probability per invocation
    p: Optional[float] = None
    #: hang/latency duration
    seconds: float = 5.0
    #: stop firing after this many injections (None = unlimited)
    max_fires: Optional[int] = None
    #: per-spec RNG seed override (defaults to the plan seed)
    seed: Optional[int] = None
    #: exception message for ``raise`` kinds
    message: str = ""
    #: builtin exception class name for ``raise`` kinds (default
    #: :class:`InjectedFault`) — e.g. ``OSError`` to look transient to the
    #: retry layer, ``ConnectionError`` for the serve client
    exception: str = ""

    # runtime state (not part of the spec identity)
    _calls: int = field(default=0, repr=False, compare=False)
    _fires: int = field(default=0, repr=False, compare=False)
    _rng: Any = field(default=None, repr=False, compare=False)

    def validate(self) -> "FaultSpec":
        if self.site not in KNOWN_SITES:
            raise ValueError(
                f"unknown fault site '{self.site}' (known: {', '.join(KNOWN_SITES)})"
            )
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind '{self.kind}' (known: {', '.join(KINDS)})")
        if (self.kind in TRACE_KINDS) != (self.site in TRACE_SITES):
            # same build-time philosophy as the corrupt/truncate checks: a
            # trace-kind at a host site (or a host kind at the trace site)
            # validates and then silently never acts — reject it loudly
            raise ValueError(
                f"fault kind '{self.kind}' and site '{self.site}' do not match: "
                f"kinds {TRACE_KINDS} act only at the in-trace sites "
                f"{TRACE_SITES} (and those sites accept only them)"
            )
        if self.site in TRACE_SITES:
            if self.p is not None:
                raise ValueError(
                    f"fault site '{self.site}' is compiled into the train trace "
                    "and only supports deterministic at=/every= schedules, not p="
                )
        payload_sites = BYTE_SITES + ROW_SITES
        if self.kind == "corrupt" and self.site not in BYTE_SITES:
            # a byte fault at a value site would validate and then silently
            # never act — exactly the "drill runs green while injecting
            # nothing" failure the build-time checks exist to prevent
            raise ValueError(
                f"fault kind '{self.kind}' only acts at byte-payload sites "
                f"({', '.join(BYTE_SITES)}), not '{self.site}'"
            )
        if self.kind == "truncate" and self.site not in payload_sites:
            raise ValueError(
                f"fault kind 'truncate' only acts at payload sites "
                f"({', '.join(payload_sites)}), not '{self.site}'"
            )
        if self.at is None and self.every is None and self.p is None:
            raise ValueError(
                f"fault spec for '{self.site}' has no schedule: set at=, every= or p="
            )
        if self.p is not None and not (0.0 <= float(self.p) <= 1.0):
            raise ValueError(f"fault p={self.p} is not a probability")
        self.make_exception()  # typo'd exception names fail at build time
        return self

    def make_exception(self) -> BaseException:
        if not self.exception:
            return InjectedFault(self.message or f"injected fault at {self.site}")
        import builtins

        exc_type = getattr(builtins, self.exception, None)
        if not (isinstance(exc_type, type) and issubclass(exc_type, BaseException)):
            raise ValueError(f"fault exception '{self.exception}' is not a builtin exception")
        return exc_type(self.message or f"injected {self.exception} at {self.site}")

    def bind(self, plan_seed: int) -> "FaultSpec":
        import random

        seed = self.seed if self.seed is not None else plan_seed
        self._rng = random.Random((int(seed) ^ (zlib.crc32(self.site.encode()) & 0x7FFFFFFF)))
        return self

    def should_fire(self) -> bool:
        """Advance this spec's invocation counter and decide (thread-safe
        under the plan lock, see :meth:`FaultPlan.poll`)."""
        self._calls += 1
        if self.max_fires is not None and self._fires >= self.max_fires:
            return False
        fire = False
        if self.at is not None and self._calls == int(self.at):
            fire = True
        if not fire and self.every is not None and int(self.every) > 0:
            fire = self._calls % int(self.every) == 0
        if not fire and self.p is not None:
            fire = self._rng.random() < float(self.p)
        if fire:
            self._fires += 1
        return fire


def _spec_from_mapping(raw: Mapping[str, Any]) -> FaultSpec:
    known = {f for f in FaultSpec.__dataclass_fields__ if not f.startswith("_")}
    unknown = set(raw) - known
    if unknown:
        raise ValueError(f"unknown fault spec fields {sorted(unknown)} in {dict(raw)}")
    return FaultSpec(**{k: raw[k] for k in raw}).validate()


class FaultPlan:
    """A validated, seeded set of fault specs, indexed by site."""

    def __init__(self, specs: Iterable[FaultSpec], seed: int = 0):
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._by_site: Dict[str, List[FaultSpec]] = {}
        for spec in specs:
            spec.validate().bind(self.seed)
            self._by_site.setdefault(spec.site, []).append(spec)

    def __bool__(self) -> bool:
        return bool(self._by_site)

    @property
    def sites(self) -> List[str]:
        return sorted(self._by_site)

    def targets(self, prefix: str) -> bool:
        """Does any spec target a site under ``prefix`` (e.g. ``"env."``)?"""
        return any(s.startswith(prefix) for s in self._by_site)

    @classmethod
    def from_specs(
        cls, raw: Sequence[Mapping[str, Any]], seed: int = 0
    ) -> "FaultPlan":
        return cls([_spec_from_mapping(r) for r in raw], seed=seed)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        """Parse the ``SHEEPRL_FAULT_PLAN`` spelling: either a bare JSON list
        of spec dicts, or ``{"seed": n, "plan": [...]}``."""
        data = json.loads(text)
        if isinstance(data, Mapping):
            return cls.from_specs(data.get("plan", []), seed=int(data.get("seed", 0) or 0))
        return cls.from_specs(data)

    def to_json(self) -> str:
        """Serialize for handing to a subprocess via ``SHEEPRL_FAULT_PLAN``."""
        out = []
        for specs in self._by_site.values():
            for s in specs:
                entry = {
                    k: getattr(s, k)
                    for k in (
                        "site", "kind", "at", "every", "p", "seconds", "max_fires",
                        "seed", "message", "exception",
                    )
                    if getattr(s, k) not in (None, "")
                }
                out.append(entry)
        return json.dumps({"seed": self.seed, "plan": out})

    # -- firing --------------------------------------------------------------
    def poll(self, site: str) -> List[FaultSpec]:
        """All specs of ``site`` that fire at this invocation."""
        specs = self._by_site.get(site)
        if not specs:
            return []
        with self._lock:
            return [s for s in specs if s.should_fire()]

    def specs_for(self, site: str) -> List[FaultSpec]:
        """Read-only view of the specs targeting ``site`` — NO counter
        advance.  The health sentinels use this at trace-build time to
        compile ``update.grads`` schedules into the guarded executable."""
        return list(self._by_site.get(site, ()))


# -- the process-global active plan ------------------------------------------
#
# ``_PLAN is None`` IS the disabled fast path: install_plan() of an empty
# plan stores None, so every instrumented call site pays exactly one global
# load + identity test when fault injection is off.
_PLAN: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install (or clear, with ``None``/empty) the process-global plan."""
    global _PLAN
    _PLAN = plan if plan else None
    return _PLAN


def clear_plan() -> None:
    install_plan(None)


def install_from_env() -> Optional[FaultPlan]:
    """(Re)install from ``SHEEPRL_FAULT_PLAN`` if set; returns the plan."""
    raw = os.environ.get(ENV_VAR, "").strip()
    if not raw:
        return _PLAN
    return install_plan(FaultPlan.from_json(raw))


def install_from_config(cfg: Any) -> Optional[FaultPlan]:
    """Install from the ``fault_injection`` config group (CLI entrypoints
    call this after compose).  The ``SHEEPRL_FAULT_PLAN`` env var wins when
    both are set — it is how drills reach into subprocesses."""
    if os.environ.get(ENV_VAR, "").strip():
        return install_from_env()
    fi = cfg.get("fault_injection") if hasattr(cfg, "get") else None
    if not fi or not fi.get("enabled", False):
        return install_plan(None)
    seed = fi.get("seed")
    if seed is None:
        seed = cfg.get("seed", 0) if hasattr(cfg, "get") else 0
    return install_plan(FaultPlan.from_specs(fi.get("plan") or [], seed=int(seed or 0)))


# -- hot-path hooks -----------------------------------------------------------
def fault_point(site: str) -> None:
    """Raise / hang / delay if the active plan fires at ``site``.

    The disabled path is ONE global load + ``is None`` test — safe to call
    per env step / per HTTP request / per device transfer.
    """
    if _PLAN is None:
        return
    for spec in _PLAN.poll(site):
        # corrupt/truncate specs are byte transforms: they only act through
        # fault_bytes — at a non-payload site they are inert (not recorded)
        if spec.kind in ("hang", "latency"):
            _record_injection(site, spec.kind)
            time.sleep(float(spec.seconds))
        elif spec.kind == "raise":
            _record_injection(site, spec.kind)
            raise spec.make_exception()


def fault_bytes(site: str, payload: bytes) -> bytes:
    """Pass ``payload`` through the plan's corrupt/truncate specs for
    ``site`` (also honors raise/hang/latency specs, so one call
    instruments a write site completely)."""
    if _PLAN is None:
        return payload
    for spec in _PLAN.poll(site):
        _record_injection(site, spec.kind)
        if spec.kind in ("hang", "latency"):
            time.sleep(float(spec.seconds))
        elif spec.kind == "raise":
            raise spec.make_exception()
        elif spec.kind == "truncate":
            payload = payload[: max(0, len(payload) // 2)]
        elif spec.kind == "corrupt":
            flip = max(1, len(payload) // 2)
            payload = payload[:flip] + bytes(b ^ 0xFF for b in payload[flip : flip + 8]) + payload[flip + 8 :]
    return payload


def fault_rows(site: str, rows: "dict") -> "dict":
    """Pass a dict of ``(T, B, *)`` replay rows through the plan's specs for
    ``site`` (the ``replay.spill`` hook): latency/hang sleep, raise raises,
    truncate drops the tail half of the time axis (a torn spill write —
    the spill worker persists fewer rows than the device ring took)."""
    if _PLAN is None:
        return rows
    for spec in _PLAN.poll(site):
        _record_injection(site, spec.kind)
        if spec.kind in ("hang", "latency"):
            time.sleep(float(spec.seconds))
        elif spec.kind == "raise":
            raise spec.make_exception()
        elif spec.kind == "truncate":
            rows = {k: v[: max(1, v.shape[0] // 2)] for k, v in rows.items()}
    return rows


def _record_injection(site: str, kind: str) -> None:
    from sheeprl_tpu.utils.profiler import RESILIENCE_MONITOR

    RESILIENCE_MONITOR.record_injection(site, kind)


# install from the environment at import: fault plans must reach processes
# that never compose a config (spawned env workers, the serve CLI, drills)
install_from_env()
