"""Chaos-hardened runtime: fault injection + recovery primitives.

``faults`` is the seeded, deterministic injection engine (driven by the
``fault_injection`` config group / ``SHEEPRL_FAULT_PLAN``, compiled to a
no-op when empty); ``retry`` holds the liveness half — jittered-backoff
:func:`retry`, the heartbeat :class:`Watchdog`, and the
:class:`CircuitBreaker` — all reporting ``Resilience/*`` metrics through
``utils.profiler.RESILIENCE_MONITOR``.  See docs/resilience.md.
"""

from sheeprl_tpu.resilience.faults import (
    ENV_VAR,
    KNOWN_SITES,
    TRACE_SITES,
    FaultPlan,
    FaultSpec,
    InjectedFault,
    active_plan,
    clear_plan,
    fault_bytes,
    fault_point,
    install_from_config,
    install_from_env,
    install_plan,
)
from sheeprl_tpu.resilience.health import (
    DivergenceError,
    HealthSentinel,
    HealthState,
)
from sheeprl_tpu.resilience.retry import CircuitBreaker, Watchdog, retry

__all__ = [
    "ENV_VAR",
    "KNOWN_SITES",
    "TRACE_SITES",
    "CircuitBreaker",
    "DivergenceError",
    "FaultPlan",
    "FaultSpec",
    "HealthSentinel",
    "HealthState",
    "InjectedFault",
    "Watchdog",
    "active_plan",
    "clear_plan",
    "fault_bytes",
    "fault_point",
    "install_from_config",
    "install_from_env",
    "install_plan",
    "retry",
]
