"""In-loop training-health sentinels: NaN-skip + divergence rollback.

A production run that hits a non-finite loss or gradient does not crash —
it silently destroys its own parameters and keeps training on garbage.
The supervisor (``sheeprl_tpu/supervisor/``) can restart a *dead* run;
only the loop itself can refuse a *poisoned* update.  This module guards
the update dispatch with two sentinels (docs/supervisor.md):

* a **non-finite guard**, compiled INTO the train trace: after the algo's
  own update math, the guarded program checks the window's loss (and, by
  default, the freshly-updated params) for NaN/Inf and SELECTS the old
  params/opt-state when the check fails — the poisoned window is skipped,
  bit-identically, with zero extra host↔device traffic per step.  The
  decision, counters and loss statistics live in a tiny device-resident
  :class:`HealthState` threaded through the executable like the grad-step
  counter, so ``cache_size() == 1`` and the transfer guard are preserved.
* a **loss-spike / divergence detector**: an EMA of the (finite) window
  loss with a consecutive-spike counter.  When ``patience`` consecutive
  windows spike past ``spike_factor``, the run is declared diverged; the
  host-side :meth:`HealthSentinel.poll` (called once per poll interval,
  NOT per step) then triggers a rollback to the last committed checkpoint
  (``health.divergence.action=rollback``) instead of continuing on
  garbage params, or just reports (``action=none``, the default).

Chaos drills exercise both paths deterministically through the
``update.grads`` fault site (``resilience/faults.py``): ``nonfinite`` and
``divergence`` specs are resolved at trace-BUILD time into the guarded
executable (``at=``/``every=`` count guarded dispatches), so a planted
fault needs no host hook in the hot path and survives the transfer guard.

Granularity: the loops dispatch updates in windows (``update_chunks``);
the guard skips the whole poisoned *window* — the dispatch is one fused
executable and the device cannot report which inner step went bad without
breaking the single-program contract.  Windows are short (the chunk law),
and a skipped window costs exactly one window of progress.

Everything is reported as ``Health/*`` through the telemetry hub and as
``health.*`` flight-recorder events, so a postmortem shows what the
sentinels saw.
"""

from __future__ import annotations

import warnings
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

from sheeprl_tpu.resilience.faults import active_plan


class DivergenceError(RuntimeError):
    """Training diverged and in-loop rollback is unavailable or exhausted.

    Raised by the sentinel when the divergence detector fires but there is
    no committed checkpoint to roll back to, the rollback budget
    (``health.divergence.max_rollbacks``) is spent, or the loop does not
    implement in-loop rollback.  The exception reaches ``cli.run``'s crash
    path (postmortem + final flush); the supervisor classifies it and
    restarts with ``checkpoint.resume_from=auto`` — rollback through the
    process boundary."""


class HealthState(NamedTuple):
    """Device-resident sentinel state, threaded through the guarded
    executable as data (one tiny replicated pytree — never rebuilt
    host-side per window, so the steady state performs no extra H2D)."""

    dispatches: Any  # int32: guarded update dispatches (windows) so far
    applied: Any  # int32: windows whose update was applied
    skipped: Any  # int32: windows skipped by the non-finite guard
    nonfinite_loss: Any  # int32: windows whose loss itself was non-finite
    last_loss: Any  # float32: newest FINITE window loss
    ema: Any  # float32: EMA of the finite window loss
    spike_run: Any  # int32: consecutive spiking windows
    spike_total: Any  # int32: total spiking windows
    diverged: Any  # int32: sticky divergence flag


def _zero_state(dispatches: int = 0) -> HealthState:
    # jnp (XLA-owned) scalars, NOT numpy: the state is DONATED into the
    # guarded executable on its first dispatch, and a CPU `device_put` of a
    # numpy scalar may zero-copy-borrow the numpy buffer — donating a
    # borrowed buffer hands XLA memory it does not own (heap corruption
    # that surfaces as a later unrelated segfault)
    import jax.numpy as jnp

    return HealthState(
        dispatches=jnp.full((), int(dispatches), jnp.int32),
        applied=jnp.zeros((), jnp.int32),
        skipped=jnp.zeros((), jnp.int32),
        nonfinite_loss=jnp.zeros((), jnp.int32),
        last_loss=jnp.zeros((), jnp.float32),
        ema=jnp.zeros((), jnp.float32),
        spike_run=jnp.zeros((), jnp.int32),
        spike_total=jnp.zeros((), jnp.int32),
        diverged=jnp.zeros((), jnp.int32),
    )


def _is_float_leaf(x: Any) -> bool:
    import jax.numpy as jnp

    dtype = getattr(x, "dtype", None)
    return dtype is not None and jnp.issubdtype(dtype, jnp.floating)


def tree_finite(tree: Any) -> Any:
    """In-trace AND-reduce of ``isfinite`` over every floating leaf."""
    import jax
    import jax.numpy as jnp

    ok = jnp.bool_(True)
    for leaf in jax.tree.leaves(tree):
        if _is_float_leaf(leaf):
            ok = ok & jnp.all(jnp.isfinite(leaf))
    return ok


def tree_select(pred: Any, new: Any, old: Any) -> Any:
    """Elementwise select: ``new`` where ``pred`` else ``old`` (exact —
    ``where(True, a, b)`` is ``a`` bit-for-bit, so an applied window is
    byte-identical to the unguarded update)."""
    import jax
    import jax.numpy as jnp

    return jax.tree.map(lambda n, o: jnp.where(pred, n, o), new, old)


def loss_scalar(metrics: Any) -> Any:
    """One f32 scalar summarizing a train dispatch's loss pytree: the sum
    of the means of every floating leaf.  Algorithms return different loss
    shapes (SAC a 3-tuple, Dreamer a 10-tuple) — the sentinel only needs a
    consistent scalar whose finiteness and trend track the update's."""
    import jax
    import jax.numpy as jnp

    leaves = [l for l in jax.tree.leaves(metrics) if _is_float_leaf(l)]
    if not leaves:
        return jnp.float32(0.0)
    total = jnp.float32(0.0)
    for leaf in leaves:
        total = total + jnp.mean(leaf).astype(jnp.float32)
    return total


def _spec_fire_count(spec: Any, lo: int, hi: int) -> int:
    """How many guarded dispatches in ``(lo, hi]`` the spec fires at —
    pure host arithmetic, mirroring :meth:`HealthSentinel._fire_pred`."""
    fires = 0
    if spec.at is not None and lo < int(spec.at) <= hi:
        fires += 1
    if spec.every is not None and int(spec.every) > 0:
        e = int(spec.every)
        top = hi // e
        if spec.max_fires is not None:
            top = min(top, int(spec.max_fires))
        fires += max(0, top - lo // e)
    return fires


class HealthSentinel:
    """Host-side controller for the in-trace sentinels of ONE train loop.

    Lifecycle (see ``algos/sac/sac.py`` for the reference wiring):

    1. ``HealthSentinel.from_config(cfg, fabric)`` — ``None`` when
       ``health.enabled=false`` (the guard is compiled OUT; call sites keep
       the exact unguarded program — the bench A/B arm).
    2. ``train_phase = fabric.compile(sentinel.wrap(train_phase), ...)`` —
       the guarded program: ``(h, p, o, *rest) -> (h, p, o, metrics)``.
    3. ``h = sentinel.init_state()`` — the replicated device state.
    4. per poll interval: ``action = sentinel.poll(h, policy_step)`` —
       fetches the tiny state (the only D2H, outside the guarded window),
       publishes ``Health/*`` through the hub, records recorder events,
       and returns ``"rollback"`` when the divergence detector fired.
    """

    HUB_SOURCE = "health"

    def __init__(self, hcfg: Any, fabric: Any = None):
        hcfg = hcfg or {}
        self.fabric = fabric
        self.check_params = bool(hcfg.get("check_params", True))
        self.poll_every = max(1, int(hcfg.get("poll_every_updates", 25) or 1))
        self.ema_decay = float(hcfg.get("ema_decay", 0.99))
        self.spike_factor = float(hcfg.get("spike_factor", 10.0))
        self.spike_min = float(hcfg.get("spike_min", 1.0))
        self.min_windows = int(hcfg.get("min_windows", 20))
        self.patience = max(1, int(hcfg.get("patience", 3) or 1))
        dcfg = hcfg.get("divergence") or {}
        self.action = str(dcfg.get("action", "none"))
        if self.action not in ("none", "rollback"):
            raise ValueError(f"health.divergence.action must be none|rollback, got {self.action!r}")
        self.max_rollbacks = int(dcfg.get("max_rollbacks", 3))
        self.divergence_scale = float(dcfg.get("fault_scale", 1e6))
        self.rollbacks = 0
        # planted update.grads faults, resolved ONCE (the plan is installed
        # before the loops build their programs — cli.run guarantees it)
        plan = active_plan()
        self._trace_specs: List[Any] = (
            plan.specs_for("update.grads") if plan is not None else []
        )
        self._metrics: Dict[str, float] = {}
        self._registered = False
        self._reset_baseline()

    # -- construction --------------------------------------------------------
    @classmethod
    def from_config(cls, cfg: Any, fabric: Any = None) -> Optional["HealthSentinel"]:
        hcfg = (cfg.get("health") or {}) if hasattr(cfg, "get") else {}
        if not hcfg.get("enabled", True):
            return None
        return cls(hcfg, fabric)

    def _reset_baseline(self) -> None:
        self._prev = {"dispatches": 0, "skipped": 0, "nonfinite_loss": 0, "spike_total": 0}
        self._diverged_reported = False

    def init_state(self, dispatches: int = 0) -> HealthState:
        """A fresh replicated device :class:`HealthState` (also resets the
        host-side poll baselines).  ``dispatches`` seeds the guarded-
        dispatch counter — see :meth:`reseed_state`."""
        self._reset_baseline()
        self._prev["dispatches"] = int(dispatches)
        zero = _zero_state(dispatches)
        if self.fabric is not None:
            return self.fabric.replicate(zero)
        return zero

    def reseed_state(self) -> HealthState:
        """Fresh state after a rollback: counters and the sticky diverged
        flag cleared, but the guarded-dispatch counter PRESERVED — planted
        ``update.grads`` schedules and the ``min_windows`` warmup key on
        it, and a rollback must not replay them."""
        return self.init_state(dispatches=self._prev["dispatches"])

    # -- the in-trace guard --------------------------------------------------
    def _fire_pred(self, d: Any, kind: str) -> Optional[Any]:
        """OR of the planted ``update.grads`` schedules of ``kind`` at
        guarded-dispatch number ``d`` (in-trace; None = nothing planted, so
        nothing is compiled in)."""
        import jax.numpy as jnp

        preds = []
        for spec in self._trace_specs:
            if spec.kind != kind:
                continue
            if spec.at is not None:
                preds.append(d == jnp.int32(int(spec.at)))
            if spec.every is not None and int(spec.every) > 0:
                e = jnp.int32(int(spec.every))
                cond = (d % e) == 0
                if spec.max_fires is not None:
                    cond = cond & ((d // e) <= jnp.int32(int(spec.max_fires)))
                preds.append(cond)
        if not preds:
            return None
        fire = preds[0]
        for p in preds[1:]:
            fire = fire | p
        return fire

    def wrap(self, phase: Any) -> Any:
        """Wrap a train phase obeying the canonical convention
        ``phase(p, o_state, *data) -> (p, o_state, metrics)`` into the
        guarded program ``guarded(h, p, o_state, *data) -> (h, p, o_state,
        metrics)``.  Call sites compile the result with
        ``donate_argnums=(0, 1, 2)``.  ``phase`` may be raw or an already
        compile-once'd :class:`~sheeprl_tpu.parallel.compile.AOTFunction` —
        the guard traces the RAW function (``AOTFunction.fn``), never the
        jitted one: the inner jit's ``donate_argnums=(0, 1)`` would survive
        inlining as an aliasing hint, and the guard re-reads ``p``/``o``
        AFTER the inner call (the old-vs-new select), so an honored inner
        donation can clobber the very buffers the select reads."""
        import jax
        import jax.numpy as jnp

        from sheeprl_tpu.parallel.compile import AOTFunction

        if isinstance(phase, AOTFunction):
            phase = phase.fn

        check_params = self.check_params
        decay = jnp.float32(self.ema_decay)
        factor = jnp.float32(self.spike_factor)
        smin = jnp.float32(self.spike_min)
        min_windows = jnp.int32(self.min_windows)
        patience = jnp.int32(self.patience)
        div_scale = jnp.float32(self.divergence_scale)

        def guarded(h: HealthState, p: Any, o_state: Any, *rest: Any, **kw: Any):
            new_p, new_o, metrics = phase(p, o_state, *rest, **kw)
            d = h.dispatches + jnp.int32(1)

            # planted chaos, compiled from the fault plan (drills only —
            # with no update.grads specs these branches emit NO ops)
            nan_fire = self._fire_pred(d, "nonfinite")
            div_fire = self._fire_pred(d, "divergence")
            loss = loss_scalar(metrics)
            if nan_fire is not None:
                poison = jnp.where(nan_fire, jnp.float32(jnp.nan), jnp.float32(0.0))
                new_p = jax.tree.map(
                    lambda x: x + poison.astype(x.dtype) if _is_float_leaf(x) else x,
                    new_p,
                )
                loss = loss + poison
            if div_fire is not None:
                loss = loss * jnp.where(div_fire, div_scale, jnp.float32(1.0))

            # -- non-finite guard: skip the poisoned window ------------------
            loss_ok = jnp.isfinite(loss)
            ok = loss_ok & tree_finite(new_p) if check_params else loss_ok
            p_out = tree_select(ok, new_p, p)
            o_out = tree_select(ok, new_o, o_state)

            # -- spike / divergence detector over the FINITE loss stream -----
            loss_f = jnp.where(loss_ok, loss, h.last_loss)
            seeded = (h.applied + h.skipped) > 0
            ema_prev = jnp.where(seeded, h.ema, loss_f)
            warm = d >= min_windows
            is_spike = loss_ok & warm & (
                (loss_f - ema_prev) > factor * (jnp.abs(ema_prev) + smin)
            )
            # a spiking window is NOT absorbed into the EMA: repeated spikes
            # must stay spikes, not drag the baseline up under them
            ema_new = jnp.where(is_spike, ema_prev, decay * ema_prev + (1.0 - decay) * loss_f)
            spike_run = jnp.where(is_spike, h.spike_run + 1, jnp.int32(0))
            diverged = jnp.maximum(h.diverged, (spike_run >= patience).astype(jnp.int32))

            oki = ok.astype(jnp.int32)
            h2 = HealthState(
                dispatches=d,
                applied=h.applied + oki,
                skipped=h.skipped + (jnp.int32(1) - oki),
                nonfinite_loss=h.nonfinite_loss + (jnp.int32(1) - loss_ok.astype(jnp.int32)),
                last_loss=loss_f,
                ema=ema_new,
                spike_run=spike_run,
                spike_total=h.spike_total + is_spike.astype(jnp.int32),
                diverged=diverged,
            )
            return h2, p_out, o_out, metrics

        guarded.__name__ = f"health_guarded[{getattr(phase, '__name__', 'train_phase')}]"
        return guarded

    # -- hub / recorder plumbing ---------------------------------------------
    def register(self) -> "HealthSentinel":
        from sheeprl_tpu.telemetry.hub import HUB

        HUB.register(self.HUB_SOURCE, self.metrics)
        self._registered = True
        return self

    def close(self) -> None:
        if self._registered:
            from sheeprl_tpu.telemetry.hub import HUB

            HUB.unregister(self.HUB_SOURCE)
            self._registered = False

    def metrics(self) -> Dict[str, float]:
        """The newest polled ``Health/*`` snapshot (a hub source; empty
        until the first poll, so an idle sentinel emits nothing)."""
        return dict(self._metrics)

    # -- per-interval host poll ----------------------------------------------
    def should_poll(self, update: int, total_iters: int) -> bool:
        return update % self.poll_every == 0 or update >= total_iters

    def poll(self, h: HealthState, policy_step: int) -> str:
        """Fetch the device state (tiny, once per poll interval), publish
        metrics/events, and return the pending action: ``"none"`` or
        ``"rollback"``."""
        import jax

        vals = jax.device_get(h)
        d = int(vals.dispatches)
        skipped = int(vals.skipped)
        nonfinite = int(vals.nonfinite_loss)
        spike_total = int(vals.spike_total)
        diverged = bool(int(vals.diverged))

        # planted-fault accounting: the schedule is deterministic, so the
        # host can mirror exactly which guarded dispatches in the polled
        # range fired — landing fault.injected recorder events + the
        # Resilience/* injection counters without any in-trace callback
        lo = self._prev["dispatches"]
        if d > lo and self._trace_specs:
            from sheeprl_tpu.utils.profiler import RESILIENCE_MONITOR

            for spec in self._trace_specs:
                for _ in range(_spec_fire_count(spec, lo, d)):
                    RESILIENCE_MONITOR.record_injection("update.grads", spec.kind)

        from sheeprl_tpu.telemetry.recorder import RECORDER

        new_skips = skipped - self._prev["skipped"]
        if new_skips > 0:
            RECORDER.record(
                "health.skip",
                count=new_skips,
                nonfinite_loss=nonfinite - self._prev["nonfinite_loss"],
                step=int(policy_step),
            )
        new_spikes = spike_total - self._prev["spike_total"]
        if new_spikes > 0:
            RECORDER.record(
                "health.spike",
                count=new_spikes,
                loss=float(vals.last_loss),
                ema=float(vals.ema),
                step=int(policy_step),
            )
        if diverged and not self._diverged_reported:
            self._diverged_reported = True
            RECORDER.record("health.diverged", step=int(policy_step), ema=float(vals.ema))
            if self.action != "rollback":
                warnings.warn(
                    f"training-health sentinel: loss diverged at step {policy_step} "
                    "(health.divergence.action=none — continuing; set "
                    "health.divergence.action=rollback to auto-restore the last "
                    "committed checkpoint)",
                    RuntimeWarning,
                )

        self._prev = {
            "dispatches": d,
            "skipped": skipped,
            "nonfinite_loss": nonfinite,
            "spike_total": spike_total,
        }
        self._metrics = {
            "Health/windows": float(d),
            "Health/applied": float(vals.applied),
            "Health/skipped": float(skipped),
            "Health/nonfinite_loss": float(nonfinite),
            "Health/loss_last": float(vals.last_loss),
            "Health/loss_ema": float(vals.ema),
            "Health/spike_windows": float(spike_total),
            "Health/diverged": float(int(diverged)),
            "Health/rollbacks": float(self.rollbacks),
        }
        if diverged and self.action == "rollback":
            return "rollback"
        return "none"

    # -- rollback budget ------------------------------------------------------
    def begin_rollback(self, policy_step: int) -> None:
        """Count one rollback attempt; raise :class:`DivergenceError` past
        the budget (a run that keeps diverging after ``max_rollbacks``
        restores is deterministically sick — surface it, don't loop)."""
        self.rollbacks += 1
        if self.rollbacks > self.max_rollbacks:
            raise DivergenceError(
                f"training diverged at step {policy_step} and the in-loop "
                f"rollback budget (health.divergence.max_rollbacks="
                f"{self.max_rollbacks}) is exhausted"
            )

    def rolled_back(self, policy_step: int, resume_step: Any) -> None:
        from sheeprl_tpu.telemetry.recorder import RECORDER

        RECORDER.record(
            "health.rollback", step=int(policy_step), resume_step=str(resume_step)
        )
        self._metrics["Health/rollbacks"] = float(self.rollbacks)
