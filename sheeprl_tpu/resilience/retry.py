"""Recovery primitives: jittered-backoff retry, hang watchdog, circuit breaker.

Every primitive reports into :data:`sheeprl_tpu.utils.profiler.RESILIENCE_MONITOR`
(the ``COMPILE_MONITOR``/``CHECKPOINT_MONITOR`` pattern), so retries, stalls
and breaker transitions surface as ``Resilience/*`` metrics through
``utils.metric.flush_metrics`` without threading handles through the loops.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Optional, Tuple, Type

from sheeprl_tpu.utils.profiler import RESILIENCE_MONITOR


def retry(
    fn: Callable[[], Any],
    *,
    attempts: int = 3,
    base_s: float = 0.2,
    max_s: float = 10.0,
    multiplier: float = 2.0,
    jitter: float = 0.5,
    deadline_s: Optional[float] = None,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    should_retry: Optional[Callable[[BaseException], bool]] = None,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    site: str = "",
) -> Any:
    """Call ``fn()`` with jittered exponential backoff.

    * ``attempts`` — total tries (1 = no retry).
    * ``base_s * multiplier**k`` capped at ``max_s`` is the k-th sleep; the
      actual sleep is uniformly drawn from ``[sleep*(1-jitter), sleep]`` so
      a fleet of workers retrying the same dead disk doesn't stampede.
    * ``deadline_s`` — total wall budget including sleeps: when the next
      sleep would cross it, the last error re-raises immediately.
    * ``retry_on`` / ``should_retry`` — which exceptions are transient;
      anything else propagates on first occurrence.
    * ``site`` labels the ``Resilience/*`` accounting.
    """
    attempts = max(1, int(attempts))
    deadline = None if deadline_s is None else time.monotonic() + float(deadline_s)
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            out = fn()
            if attempt:
                RESILIENCE_MONITOR.record_retry_success(site)
            return out
        except retry_on as e:
            if should_retry is not None and not should_retry(e):
                raise
            last = e
            if attempt == attempts - 1:
                break
            sleep = min(float(max_s), float(base_s) * float(multiplier) ** attempt)
            sleep -= sleep * float(jitter) * random.random()
            if deadline is not None and time.monotonic() + sleep > deadline:
                break
            RESILIENCE_MONITOR.record_retry(site)
            if on_retry is not None:
                on_retry(attempt + 1, e, sleep)
            time.sleep(sleep)
    RESILIENCE_MONITOR.record_giveup(site)
    assert last is not None
    raise last


class Watchdog:
    """Heartbeat-based hang detector.

    The owner calls :meth:`beat` whenever it makes progress; a daemon thread
    checks every ``interval_s`` whether the last beat is older than
    ``timeout_s`` while the watchdog is :meth:`armed <arm>`, and fires
    ``on_stall(stalled_for_s)`` ONCE per stall (re-arming after the next
    beat).  Use it to watch work that has no timeout-taking wait of its own
    (a background writer job, a dispatch loop); prefer a native timeout
    (e.g. ``AsyncVectorEnv.step_wait(timeout=...)``) where one exists.
    """

    def __init__(
        self,
        timeout_s: float,
        on_stall: Optional[Callable[[float], None]] = None,
        interval_s: Optional[float] = None,
        name: str = "watchdog",
    ):
        self.timeout_s = float(timeout_s)
        self._interval = float(interval_s) if interval_s else max(0.05, self.timeout_s / 4)
        self._on_stall = on_stall
        self._name = name
        self._lock = threading.Lock()
        self._last_beat = time.monotonic()
        self._armed = False
        self._fired = False
        self.stalls = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    # -- owner API -----------------------------------------------------------
    def beat(self) -> None:
        with self._lock:
            self._last_beat = time.monotonic()
            self._fired = False

    def arm(self) -> None:
        with self._lock:
            self._armed = True
            self._last_beat = time.monotonic()
            self._fired = False

    def disarm(self) -> None:
        with self._lock:
            self._armed = False

    def watching(self) -> "_WatchdogContext":
        """``with wd.watching():`` — arm for the block, disarm on exit."""
        return _WatchdogContext(self)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(self._interval * 2 + 1.0)

    # -- checker -------------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            with self._lock:
                if not self._armed or self._fired:
                    continue
                stalled = time.monotonic() - self._last_beat
                if stalled < self.timeout_s:
                    continue
                self._fired = True  # once per stall
                self.stalls += 1
            RESILIENCE_MONITOR.record_stall(self._name)
            if self._on_stall is not None:
                try:
                    self._on_stall(stalled)
                except Exception:
                    pass  # a broken stall handler must not kill the checker


class _WatchdogContext:
    def __init__(self, wd: Watchdog):
        self._wd = wd

    def __enter__(self) -> Watchdog:
        self._wd.arm()
        return self._wd

    def __exit__(self, *exc: Any) -> None:
        self._wd.disarm()


class CircuitBreaker:
    """Classic closed → open → half-open breaker.

    ``record_failure()`` after ``failure_threshold`` consecutive failures
    opens the circuit; :meth:`allow` then answers False for
    ``reset_timeout_s``, after which ONE probe is allowed through
    (half-open) — its ``record_success`` closes the circuit, its
    ``record_failure`` re-opens it for another cool-down.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(
        self, failure_threshold: int = 3, reset_timeout_s: float = 30.0, name: str = "breaker"
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout_s = float(reset_timeout_s)
        self.name = name
        self._lock = threading.Lock()
        self._failures = 0
        self._state = self.CLOSED
        self._opened_at = 0.0
        self.opens = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._probe_state()

    @property
    def failures(self) -> int:
        with self._lock:
            return self._failures

    def _probe_state(self) -> str:
        # lock held: open → half_open once the cool-down elapsed
        if self._state == self.OPEN and (
            time.monotonic() - self._opened_at >= self.reset_timeout_s
        ):
            self._state = self.HALF_OPEN
        return self._state

    def allow(self) -> bool:
        """May the protected call proceed right now?"""
        with self._lock:
            return self._probe_state() != self.OPEN

    def record_success(self) -> None:
        with self._lock:
            if self._state != self.CLOSED:
                RESILIENCE_MONITOR.record_breaker(self.name, self.CLOSED)
            self._failures = 0
            self._state = self.CLOSED

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            state = self._probe_state()
            if state == self.HALF_OPEN or (
                state == self.CLOSED and self._failures >= self.failure_threshold
            ):
                self._state = self.OPEN
                self._opened_at = time.monotonic()
                self.opens += 1
                RESILIENCE_MONITOR.record_breaker(self.name, self.OPEN)

    def snapshot(self) -> dict:
        """State dict for ``/healthz`` / ``/v1/stats`` surfaces."""
        with self._lock:
            return {
                "state": self._probe_state(),
                "failures": self._failures,
                "threshold": self.failure_threshold,
                "opens": self.opens,
            }
