"""sheeprl_tpu.ops."""
