"""Pallas TPU kernel: fused LayerNorm-GRU cell.

The LayerNorm-GRU cell is the hot recurrent op of every Dreamer
(SURVEY.md §7: "Pallas fused LayerNorm-GRU cell is the stretch goal").  The
cell is one fused matmul followed by LayerNorm and three gate nonlinearities
(see sheeprl_tpu/models/models.py:LayerNormGRUCell); XLA already fuses the
elementwise tail, but routes the (B, 3H) projection through HBM between the
matmul and the normalization.  This kernel keeps the projection resident in
VMEM: concat → MXU matmul → fp32 LayerNorm → gates → new state, one pass.

Layout: grid over batch tiles; the full (D+H, 3H) weight block stays in VMEM
for every grid step (fits for Dreamer S/M sizes: e.g. S → (1536, 1536) fp32
= 9.4 MB < 16 MB VMEM).  For XL-scale recurrent states shard H over the
mesh instead (LN is per-3H-row; the gate split is H-blocked, so a model-axis
sharding composes).

Use via ``fused_layernorm_gru(...)`` — numerically identical (fp32) to the
flax cell; validated against it in tests/test_models/test_gru_pallas.py with
``interpret=True`` (no TPU needed).  Enable inside models with
``LayerNormGRUCell(use_pallas=True)``.

HARDWARE STATUS (2026-07-31, v5e, honest scan-based timing — BENCH_TPU.md):
Mosaic-compiles and matches the flax cell to <3e-6, but LOSES to XLA's
fused scan body at every shape (speedup 0.38-0.56x; H=512/B=16: 11.3 µs vs
XLA 4.5 µs per step) — XLA already keeps the scan working set VMEM-resident.
RULING: XLA path stays the default; the kernel remains as a
correctness-validated reference implementation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LN_EPS = 1e-5  # matches models.LayerNorm default


def _gru_kernel(x_ref, h_ref, w_ref, scale_ref, bias_ref, out_ref):
    """One batch-tile of the fused cell.

    x: (Bt, D) input features;  h: (Bt, H) carried state;
    w: (D+H, 3H) fused projection;  scale/bias: (1, 3H) LayerNorm params.
    """
    x = x_ref[:]
    h = h_ref[:]
    w = w_ref[:]
    inp = jnp.concatenate([x, h], axis=-1)
    # MXU: (Bt, D+H) @ (D+H, 3H), fp32 accumulation
    parts = jnp.dot(inp, w, preferred_element_type=jnp.float32)
    # fp32 LayerNorm over the 3H axis (matches models.LayerNorm eps)
    mean = jnp.mean(parts, axis=-1, keepdims=True)
    var = jnp.mean((parts - mean) ** 2, axis=-1, keepdims=True)
    parts = (parts - mean) * jax.lax.rsqrt(var + LN_EPS)
    parts = parts * scale_ref[:] + bias_ref[:]
    # gate split / nonlinearities (Hafner variant: update bias -1)
    H = h.shape[-1]
    reset = jax.nn.sigmoid(parts[:, :H])
    cand = jnp.tanh(reset * parts[:, H:2 * H])
    update = jax.nn.sigmoid(parts[:, 2 * H:] - 1.0)
    out_ref[:] = update * cand + (1.0 - update) * h


def fused_layernorm_gru(
    x: jax.Array,
    h: jax.Array,
    w: jax.Array,
    ln_scale: jax.Array,
    ln_bias: jax.Array,
    block_b: int = 128,
    interpret: bool = None,
) -> jax.Array:
    if interpret is None:
        # only TPU has the Mosaic backend: fall back to the interpreter
        # everywhere else (CPU tests, GPU dev boxes)
        interpret = jax.default_backend() != "tpu"
    # accept arbitrary leading batch dims like the flax cell
    lead = x.shape[:-1]
    if len(lead) > 1:
        x = x.reshape(-1, x.shape[-1])
        h = h.reshape(-1, h.shape[-1])
        out = _fused_layernorm_gru(x, h, w, ln_scale, ln_bias, block_b, interpret)
        return out.reshape(*lead, out.shape[-1])
    return _fused_layernorm_gru(x, h, w, ln_scale, ln_bias, block_b, interpret)


def _reference_math(x, h, w, ln_scale, ln_bias):
    """Pure-JAX same-math path (fp32): autodiff source for the backward."""
    f32 = jnp.float32
    h = h.astype(f32)
    inp = jnp.concatenate([x.astype(f32), h], axis=-1)
    parts = jnp.dot(inp, w.astype(f32))
    mean = jnp.mean(parts, axis=-1, keepdims=True)
    var = jnp.mean((parts - mean) ** 2, axis=-1, keepdims=True)
    parts = (parts - mean) * jax.lax.rsqrt(var + LN_EPS)
    parts = parts * ln_scale.astype(f32).reshape(1, -1) + ln_bias.astype(f32).reshape(1, -1)
    H = h.shape[-1]
    reset = jax.nn.sigmoid(parts[:, :H])
    cand = jnp.tanh(reset * parts[:, H:2 * H])
    update = jax.nn.sigmoid(parts[:, 2 * H:] - 1.0)
    return update * cand + (1.0 - update) * h


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _gru_core(x, h, w, ln_scale, ln_bias, block_b, interpret):
    return _pallas_forward(x, h, w, ln_scale, ln_bias, block_b, interpret)


def _gru_core_fwd(x, h, w, ln_scale, ln_bias, block_b, interpret):
    out = _pallas_forward(x, h, w, ln_scale, ln_bias, block_b, interpret)
    return out, (x, h, w, ln_scale, ln_bias)


def _gru_core_bwd(block_b, interpret, residuals, g):
    # pallas_call has no reverse-mode rule; differentiate the same math via
    # XLA (what the flax path's backward is anyway)
    _, vjp = jax.vjp(_reference_math, *residuals)
    return vjp(g)


_gru_core.defvjp(_gru_core_fwd, _gru_core_bwd)


@functools.partial(jax.jit, static_argnames=("block_b", "interpret"))
def _fused_layernorm_gru(
    x: jax.Array,
    h: jax.Array,
    w: jax.Array,
    ln_scale: jax.Array,
    ln_bias: jax.Array,
    block_b: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Fused LayerNorm-GRU step.

    Args:
        x: (B, D) inputs. h: (B, H) previous state. w: (D+H, 3H) fused
        kernel (the flax cell's ``fused`` Dense, bias-free). ln_scale/ln_bias:
        (3H,) LayerNorm parameters.
    Returns:
        (B, H) new recurrent state (fp32).
    """
    return _gru_core(x, h, w, ln_scale, ln_bias, block_b, interpret)


# conservative VMEM budget for the resident weight block (see rssm_pallas)
_VMEM_WEIGHT_BUDGET_BYTES = 12 * 1024 * 1024


def _pallas_forward(
    x: jax.Array,
    h: jax.Array,
    w: jax.Array,
    ln_scale: jax.Array,
    ln_bias: jax.Array,
    block_b: int = 128,
    interpret: bool = False,
) -> jax.Array:
    if 4 * w.size > _VMEM_WEIGHT_BUDGET_BYTES:
        raise ValueError(
            f"fused GRU kernel keeps the (D+H, 3H) weight VMEM-resident; "
            f"{4 * w.size / 2**20:.1f} MB fp32 exceeds the "
            f"{_VMEM_WEIGHT_BUDGET_BYTES / 2**20:.0f} MB budget — use the "
            "flax cell (use_pallas=False) or shard H over the mesh."
        )
    B, D = x.shape
    H = h.shape[-1]
    x = x.astype(jnp.float32)
    h = h.astype(jnp.float32)
    w = w.astype(jnp.float32)
    scale = ln_scale.reshape(1, 3 * H).astype(jnp.float32)
    bias = ln_bias.reshape(1, 3 * H).astype(jnp.float32)

    bt = min(block_b, B)
    # pad B to a multiple of the tile
    pad = (-B) % bt
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        h = jnp.pad(h, ((0, pad), (0, 0)))
    grid = ((B + pad) // bt,)

    out = pl.pallas_call(
        _gru_kernel,
        out_shape=jax.ShapeDtypeStruct((B + pad, H), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bt, D), lambda i: (i, 0)),
            pl.BlockSpec((bt, H), lambda i: (i, 0)),
            pl.BlockSpec((D + H, 3 * H), lambda i: (0, 0)),
            pl.BlockSpec((1, 3 * H), lambda i: (0, 0)),
            pl.BlockSpec((1, 3 * H), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bt, H), lambda i: (i, 0)),
        interpret=interpret,
    )(x, h, w, scale, bias)
    return out[:B]
