"""Pallas TPU kernel: fully-fused RSSM recurrent path.

The RSSM recurrent step (reference: sheeprl/algos/dreamer_v3/agent.py:281-341
``RecurrentModel``) is ``dense+LN+SiLU`` over ``z ⊕ a`` followed by the
LayerNorm-GRU cell — two matmuls with elementwise tails, executed once per
sequence step inside a ``lax.scan``.  XLA fuses each tail into its matmul but
still stages the intermediate ``(B, D)`` activation and the ``(B, 3H)`` gate
projection through HBM every step.  This kernel runs the WHOLE path in one
``pallas_call``: both weight blocks stay resident in VMEM for every batch
tile, the intermediates never leave VMEM, and the new recurrent state is the
only output.

Sizes (DreamerV3-S, fp32): W_in (1056, 512) ≈ 2.2 MB, W_gru (1024, 1536)
≈ 6.3 MB → comfortably inside the ~16 MB VMEM budget, so the S/XS kernel
keeps both weight blocks fully VMEM-resident.  M and larger presets exceed
VMEM with fp32 weights (L: W_gru (2816, 6144) ≈ 69 MB) — those dispatch to
the H-TILED kernel below (``_pallas_forward_tiled``): the gate projection
``w_gru`` streams through VMEM in column tiles over a second grid axis
while the raw gate pre-activations accumulate into a VMEM scratch; at the
last column step the full-row (3H) LayerNorm — which couples ALL gate
columns and is why a naive column tiling is wrong — plus the gate
nonlinearities and the state update run from scratch, and only the (B, H)
new state is written to HBM.  The intermediate (B, 3H) block never touches
HBM at ANY preset size.

Autodiff: ``pallas_call`` has no reverse-mode rule, so the op carries a
``custom_vjp`` whose backward differentiates the SAME math via XLA.  The
backward re-runs the forward (rematerialization semantics) — in gradient
paths the fused kernel therefore trades a little recompute for the VMEM
residency; the clear wins are the grad-free player/rollout and posterior
paths, and any training setup already under ``jax.checkpoint``.  Decide
per-preset with benchmarks/bench_gru_pallas.py on hardware.

Numerics match the flax path exactly (fp32 throughout): input LN eps 1e-3,
GRU LN eps 1e-5 (models.LayerNorm defaults), Hafner ``-1`` update-gate bias.
Validated against the flax modules in tests/test_models/test_rssm_pallas.py
with ``interpret=True`` (no TPU needed).  Enable inside the world model with
``algo.world_model.recurrent_model.fused_pallas=True`` once on TPU hardware.

HARDWARE STATUS (2026-07-31, v5e, honest scan-based timing — BENCH_TPU.md):
Mosaic-compiles and matches the XLA path to <1e-4 at every preset shape,
but LOSES to XLA's fused scan body on all of them (speedup 0.18-0.47x;
e.g. D=512/H=512/B=16: 13.2 µs vs XLA 4.4 µs per step).  XLA already keeps
this working set in VMEM across scan iterations; the kernel's VMEM-residency
premise buys nothing and its fp32 MXU path gives up bf16.  RULING:
the XLA path stays the default; these kernels remain as correctness-validated
reference implementations (`fused_pallas=True` still dispatches them).  The VMEM planner (`_plan_tiled`) sizes the tiled variant's
working set against `_VMEM_WEIGHT_BUDGET_BYTES` and raises when no legal
tiling fits, instead of letting Mosaic fail opaquely.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LN_IN_EPS = 1e-3   # RecurrentModel input LayerNorm (agent.py RecurrentModel)
LN_GRU_EPS = 1e-5  # models.LayerNorm default (GRU projection LN)


def _ln(x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float) -> jax.Array:
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * scale + bias


def _rssm_kernel(
    x_ref, h_ref,
    w_in_ref, b_in_ref, ln_in_scale_ref, ln_in_bias_ref,
    w_gru_ref, gru_scale_ref, gru_bias_ref,
    out_ref,
):
    """One batch tile of the fused recurrent path.

    x: (Bt, Z+A) concatenated stochastic state + action;  h: (Bt, H);
    w_in/b_in: (Z+A, D)/(1, D) input projection;  ln_in_*: (1, D);
    w_gru: (D+H, 3H) fused GRU projection;  gru_*: (1, 3H) GRU LayerNorm.
    """
    x = x_ref[:]
    h = h_ref[:]
    # input projection + LN(1e-3) + SiLU — all VMEM-resident
    y = jnp.dot(x, w_in_ref[:], preferred_element_type=jnp.float32) + b_in_ref[:]
    y = _ln(y, ln_in_scale_ref[:], ln_in_bias_ref[:], LN_IN_EPS)
    y = jax.nn.silu(y)
    # LayerNorm-GRU (same math as ops/gru_pallas._gru_kernel)
    inp = jnp.concatenate([y, h], axis=-1)
    parts = jnp.dot(inp, w_gru_ref[:], preferred_element_type=jnp.float32)
    parts = _ln(parts, gru_scale_ref[:], gru_bias_ref[:], LN_GRU_EPS)
    H = h.shape[-1]
    reset = jax.nn.sigmoid(parts[:, :H])
    cand = jnp.tanh(reset * parts[:, H:2 * H])
    update = jax.nn.sigmoid(parts[:, 2 * H:] - 1.0)
    out_ref[:] = update * cand + (1.0 - update) * h


def fused_rssm_recurrent(
    x: jax.Array,
    h: jax.Array,
    w_in: jax.Array,
    b_in: jax.Array,
    ln_in_scale: jax.Array,
    ln_in_bias: jax.Array,
    w_gru: jax.Array,
    gru_scale: jax.Array,
    gru_bias: jax.Array,
    block_b: int = 128,
    interpret: bool = None,
) -> jax.Array:
    """Fused ``RecurrentModel`` forward: ``GRU(h, SiLU(LN(x @ W_in + b)))``.

    Args:
        x: (..., Z+A) inputs (z ⊕ action).  h: (..., H) recurrent state.
        w_in/b_in: input Dense params.  ln_in_*: input LayerNorm params (D,).
        w_gru: (D+H, 3H) fused GRU kernel.  gru_*: GRU LayerNorm params (3H,).
    Returns:
        (..., H) new recurrent state, fp32.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = x.shape[:-1]
    if len(lead) > 1:
        x = x.reshape(-1, x.shape[-1])
        h = h.reshape(-1, h.shape[-1])
        out = _fused_rssm_recurrent(
            x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias,
            block_b, interpret,
        )
        return out.reshape(*lead, out.shape[-1])
    return _fused_rssm_recurrent(
        x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias,
        block_b, interpret,
    )


def _reference_math(x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias):
    """Pure-JAX implementation of the same math (fp32) — the autodiff source
    for the kernel's backward pass and the numerical reference in tests."""
    f32 = jnp.float32
    y = jnp.dot(x.astype(f32), w_in.astype(f32)) + b_in.astype(f32).reshape(1, -1)
    y = _ln(y, ln_in_scale.astype(f32).reshape(1, -1), ln_in_bias.astype(f32).reshape(1, -1), LN_IN_EPS)
    y = jax.nn.silu(y)
    h = h.astype(f32)
    inp = jnp.concatenate([y, h], axis=-1)
    parts = jnp.dot(inp, w_gru.astype(f32))
    parts = _ln(parts, gru_scale.astype(f32).reshape(1, -1), gru_bias.astype(f32).reshape(1, -1), LN_GRU_EPS)
    H = h.shape[-1]
    reset = jax.nn.sigmoid(parts[:, :H])
    cand = jnp.tanh(reset * parts[:, H:2 * H])
    update = jax.nn.sigmoid(parts[:, 2 * H:] - 1.0)
    return update * cand + (1.0 - update) * h


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10))
def _rssm_core(x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias,
               block_b, interpret):
    return _pallas_forward(
        x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias,
        block_b, interpret,
    )


def _rssm_core_fwd(x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias,
                   block_b, interpret):
    out = _pallas_forward(
        x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias,
        block_b, interpret,
    )
    return out, (x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias)


def _rssm_core_bwd(block_b, interpret, residuals, g):
    # backward through the SAME math via XLA autodiff — pallas_call has no
    # reverse-mode rule; XLA's fused backward is what the flax path uses too
    _, vjp = jax.vjp(_reference_math, *residuals)
    return vjp(g)


_rssm_core.defvjp(_rssm_core_fwd, _rssm_core_bwd)


@functools.partial(jax.jit, static_argnames=("block_b", "interpret"))
def _fused_rssm_recurrent(
    x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias,
    block_b: int = 128,
    interpret: bool = False,
):
    return _rssm_core(
        x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias,
        block_b, interpret,
    )


# conservative VMEM budget for the weight blocks (v5e has 16 MB/core; leave
# headroom for activations and double-buffering)
_VMEM_WEIGHT_BUDGET_BYTES = 12 * 1024 * 1024


def _pallas_forward(
    x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias,
    block_b: int = 128,
    interpret: bool = False,
):
    weight_bytes = 4 * (w_in.size + w_gru.size)
    if weight_bytes > _VMEM_WEIGHT_BUDGET_BYTES:
        # M/L/XL presets: stream w_gru in column tiles instead (same math,
        # same single-HBM-write-per-row-block contract)
        return _pallas_forward_tiled(
            x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias,
            block_b=min(block_b, 64), interpret=interpret,
        )
    B, ZA = x.shape
    H = h.shape[-1]
    D = w_in.shape[-1]
    f32 = jnp.float32
    x = x.astype(f32)
    h = h.astype(f32)
    w_in = w_in.astype(f32)
    b_in = b_in.reshape(1, D).astype(f32)
    ln_in_scale = ln_in_scale.reshape(1, D).astype(f32)
    ln_in_bias = ln_in_bias.reshape(1, D).astype(f32)
    w_gru = w_gru.astype(f32)
    gru_scale = gru_scale.reshape(1, 3 * H).astype(f32)
    gru_bias = gru_bias.reshape(1, 3 * H).astype(f32)

    bt = min(block_b, B)
    pad = (-B) % bt
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        h = jnp.pad(h, ((0, pad), (0, 0)))
    grid = ((B + pad) // bt,)

    out = pl.pallas_call(
        _rssm_kernel,
        out_shape=jax.ShapeDtypeStruct((B + pad, H), f32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bt, ZA), lambda i: (i, 0)),
            pl.BlockSpec((bt, H), lambda i: (i, 0)),
            pl.BlockSpec((ZA, D), lambda i: (0, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
            pl.BlockSpec((D + H, 3 * H), lambda i: (0, 0)),
            pl.BlockSpec((1, 3 * H), lambda i: (0, 0)),
            pl.BlockSpec((1, 3 * H), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bt, H), lambda i: (i, 0)),
        interpret=interpret,
    )(x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias)
    return out[:B]


# ---------------------------------------------------------------------------
# H-tiled variant for M/L/XL presets (w_gru too large for VMEM residency)
# ---------------------------------------------------------------------------

def _rssm_kernel_tiled(
    x_ref, h_ref,
    w_in_ref, b_in_ref, ln_in_scale_ref, ln_in_bias_ref,
    w_gru_ref, gru_scale_ref, gru_bias_ref,
    out_ref,
    y_scratch, parts_scratch,
):
    """One (batch tile, gate-column tile) step of the streamed recurrent path.

    Grid is (num_batch_tiles, num_col_tiles); for a fixed batch tile the
    column axis runs sequentially, streaming ``w_gru`` (D+H, tj) tiles from
    HBM.  ``y`` (the input projection) is computed once at j==0 into VMEM
    scratch; every j accumulates its raw gate pre-activation columns into
    ``parts_scratch``; the last j applies the full-3H LayerNorm (it couples
    every gate column — the reason this kernel is two-phase) + gates + state
    update and performs the kernel's only HBM write.
    """
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    D = y_scratch.shape[-1]
    H = h_ref.shape[-1]
    tj = w_gru_ref.shape[-1]

    @pl.when(j == 0)
    def _input_projection():
        y = jnp.dot(x_ref[:], w_in_ref[:], preferred_element_type=jnp.float32) + b_in_ref[:]
        y = _ln(y, ln_in_scale_ref[:], ln_in_bias_ref[:], LN_IN_EPS)
        y_scratch[:] = jax.nn.silu(y)

    # this column tile's raw pre-activations: [y, h] @ w_gru[:, jt]
    parts_tile = (
        jnp.dot(y_scratch[:], w_gru_ref[:D, :], preferred_element_type=jnp.float32)
        + jnp.dot(h_ref[:], w_gru_ref[D:, :], preferred_element_type=jnp.float32)
    )
    parts_scratch[:, pl.ds(j * tj, tj)] = parts_tile

    @pl.when(j == nj - 1)
    def _finalize():
        parts = _ln(parts_scratch[:], gru_scale_ref[:], gru_bias_ref[:], LN_GRU_EPS)
        h = h_ref[:]
        reset = jax.nn.sigmoid(parts[:, :H])
        cand = jnp.tanh(reset * parts[:, H:2 * H])
        update = jax.nn.sigmoid(parts[:, 2 * H:] - 1.0)
        out_ref[:] = update * cand + (1.0 - update) * h


def _col_tile(total: int, target: int = 512) -> int:
    """Largest divisor of ``total`` that is ≤ target and a multiple of 128
    (TPU lane width); falls back to ``total`` for small models."""
    if total <= target:
        return total
    for t in range(target, 127, -128):
        if total % t == 0:
            return t
    return total


def _tiled_vmem_bytes(bt: int, tj: int, ZA: int, D: int, H: int) -> int:
    """Estimated VMEM residency of one `_rssm_kernel_tiled` step (fp32):
    resident w_in block, the streamed w_gru column tile (×2 for pallas
    double-buffering), both scratches, and the batch-tile operands/output."""
    return 4 * (
        ZA * D                # w_in (resident across the column axis)
        + 2 * (D + H) * tj    # streamed w_gru tile, double-buffered
        + bt * D              # y scratch
        + bt * 3 * H          # parts scratch
        + bt * (ZA + 2 * H)   # x, h, out tiles
        + 3 * D + 2 * 3 * H   # LN/bias vectors
    )


def _legal_col_tiles(total: int, target: int = 512) -> list:
    """Legal column tiles for a ``total``-wide axis, descending: every
    divisor of ``total`` that is a multiple of 128 and ≤ target, seeded with
    the :func:`_col_tile` choice.  When no 128-multiple divides ``total``
    (3H < 128 or an odd width) the only legal tile is ``total`` itself
    (ADVICE r4: stepping down from tj in raw -128 increments could miss
    every divisor and give up while a smaller legal tile existed)."""
    tiles = {t for t in range(128, min(total, target) + 1, 128) if total % t == 0}
    tiles.add(_col_tile(total, target))
    return sorted(tiles, reverse=True)


def _plan_tiled(B: int, ZA: int, D: int, H: int, block_b: int):
    """Pick (bt, tj) so the tiled kernel's working set fits the VMEM budget
    (ADVICE r3: the tiled path previously had no accounting at all and XL
    could exceed ~16MB/core).  Prefers shrinking the column tile first (it
    only adds grid steps), then the batch tile; raises when even the
    smallest legal tiling cannot fit."""
    bt = min(block_b, B)
    col_tiles = _legal_col_tiles(3 * H)
    while True:
        tj = next(
            (
                t
                for t in col_tiles
                if _tiled_vmem_bytes(bt, t, ZA, D, H) <= _VMEM_WEIGHT_BUDGET_BYTES
            ),
            col_tiles[-1],
        )
        if _tiled_vmem_bytes(bt, tj, ZA, D, H) <= _VMEM_WEIGHT_BUDGET_BYTES:
            return bt, tj
        if bt > 8:
            bt = max(8, bt // 2)
            continue
        raise ValueError(
            f"fused RSSM tiled kernel cannot fit VMEM: D={D} H={H} ZA={ZA} "
            f"needs {_tiled_vmem_bytes(bt, tj, ZA, D, H) / 2**20:.1f} MiB at the "
            f"smallest tiling (budget {_VMEM_WEIGHT_BUDGET_BYTES / 2**20:.0f} MiB) "
            "— disable algo.world_model.recurrent_model.fused_pallas for this preset"
        )


def _pallas_forward_tiled(
    x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias,
    block_b: int = 64,
    interpret: bool = False,
):
    from jax.experimental.pallas import tpu as pltpu

    B, ZA = x.shape
    H = h.shape[-1]
    D = w_in.shape[-1]
    f32 = jnp.float32
    x = x.astype(f32)
    h = h.astype(f32)
    w_in = w_in.astype(f32)
    b_in = b_in.reshape(1, D).astype(f32)
    ln_in_scale = ln_in_scale.reshape(1, D).astype(f32)
    ln_in_bias = ln_in_bias.reshape(1, D).astype(f32)
    w_gru = w_gru.astype(f32)
    gru_scale = gru_scale.reshape(1, 3 * H).astype(f32)
    gru_bias = gru_bias.reshape(1, 3 * H).astype(f32)

    bt, tj = _plan_tiled(B, ZA, D, H, block_b)
    pad = (-B) % bt
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        h = jnp.pad(h, ((0, pad), (0, 0)))
    grid = ((B + pad) // bt, (3 * H) // tj)

    out = pl.pallas_call(
        _rssm_kernel_tiled,
        out_shape=jax.ShapeDtypeStruct((B + pad, H), f32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bt, ZA), lambda i, j: (i, 0)),
            pl.BlockSpec((bt, H), lambda i, j: (i, 0)),
            pl.BlockSpec((ZA, D), lambda i, j: (0, 0)),
            pl.BlockSpec((1, D), lambda i, j: (0, 0)),
            pl.BlockSpec((1, D), lambda i, j: (0, 0)),
            pl.BlockSpec((1, D), lambda i, j: (0, 0)),
            pl.BlockSpec((D + H, tj), lambda i, j: (0, j)),  # streamed
            pl.BlockSpec((1, 3 * H), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 3 * H), lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bt, H), lambda i, j: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((bt, D), f32),       # y (input projection)
            pltpu.VMEM((bt, 3 * H), f32),   # raw gate pre-activations
        ],
        interpret=interpret,
    )(x, h, w_in, b_in, ln_in_scale, ln_in_bias, w_gru, gru_scale, gru_bias)
    return out[:B]
