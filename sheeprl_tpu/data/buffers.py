"""Host-side replay buffers feeding jit-compiled device train steps.

Capability parity with the reference buffer suite
(reference: sheeprl/data/buffers.py:20-1180): ``ReplayBuffer`` (uniform FIFO
ring), ``SequentialReplayBuffer`` (contiguous sequences with wrap-around),
``EnvIndependentReplayBuffer`` (one sub-buffer per env), ``EpisodeBuffer``
(whole episodes with end-prioritized sampling) — all NumPy ``(T, B, *)``.

TPU-first design decisions:
* Buffers live in host RAM (optionally memmapped to disk) — device HBM only
  ever sees *sampled batches*, shipped once per ratio window as a single
  stacked block (the reference discovered the same bulk-sample pattern,
  sheeprl/algos/dreamer_v3/dreamer_v3.py:664-671).
* ``sample(..., n_samples=k)`` returns ``(k, ...)``-stacked numpy arrays so
  the caller can ``jax.device_put`` one contiguous block and ``lax.scan`` or
  index over the leading axis on device, keeping every train-step shape
  static.
* No per-step torch/jax conversion: conversion happens at the device
  boundary via :func:`to_device`.
"""

from __future__ import annotations

import os
import typing
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from sheeprl_tpu.data.memmap import MemmapArray

Arrays = Dict[str, np.ndarray]


def _steps_and_envs(data: Arrays) -> Tuple[int, int]:
    key = next(iter(data))
    shape = data[key].shape
    if len(shape) < 2:
        raise ValueError(f"Buffer data must be (T, B, *): key '{key}' has shape {shape}")
    return shape[0], shape[1]


def to_device(batch: Arrays, dtype: Optional[Any] = None, device: Optional[Any] = None) -> Dict[str, Any]:
    """Stage a sampled numpy batch onto the accelerator in one transfer per key."""
    import jax
    import jax.numpy as jnp

    out: Dict[str, Any] = {}
    for k, v in batch.items():
        arr = jnp.asarray(v, dtype=dtype if (dtype is not None and np.issubdtype(v.dtype, np.floating)) else None)
        if device is not None:
            arr = jax.device_put(arr, device)
        out[k] = arr
    return out


class DeviceMirror:
    """DEPRECATED shim over :class:`sheeprl_tpu.data.device_replay.DeviceReplay`.

    The per-device, probe-gated pixel mirror has been superseded by the
    mesh-sharded device-resident replay (``data/device_replay.py``), which
    keeps EVERY key in HBM and samples inside the compiled update step —
    no host ring, no host-drawn coordinates, no per-key mirror budget.  The
    algo loops no longer construct mirrors; this class remains so external
    callers of ``attach_mirror`` keep working (identical scatter/gather
    semantics, now riding ``DeviceReplay``'s ring primitives) while they
    migrate — see docs/device_replay.md for the migration notes.
    """

    def __init__(self, capacity: int, n_envs: int):
        import warnings

        warnings.warn(
            "DeviceMirror/attach_mirror is deprecated: use buffer.device=True "
            "(data/device_replay.DeviceReplay) — the mirror shim keeps the old "
            "write/gather contract over the new ring (docs/device_replay.md)",
            DeprecationWarning,
            stacklevel=3,
        )
        from sheeprl_tpu.data.device_replay import DeviceReplay

        self._replay = DeviceReplay(capacity, n_envs)

    def write(self, key: str, rows: np.ndarray, time_pos: np.ndarray, env_cols: Sequence[int]) -> None:
        """Scatter ``rows (T, K, *)`` at ring slots ``time_pos (T, K)`` for
        env columns ``env_cols (K,)`` — the exact slots the host ring wrote."""
        self._replay.write_at(key, np.asarray(rows), np.asarray(time_pos), env_cols)

    def gather(self, key: str, time_idx: np.ndarray, env_idx: np.ndarray):
        """Device gather of ``(U, L, B, *)`` sequences at host-sampled ring
        indices; the result never crosses the host<->device link."""
        return self._replay.gather_at(key, np.asarray(time_idx), np.asarray(env_idx))

    def nbytes(self) -> int:
        return self._replay.hbm_bytes


def maybe_attach_mirror(
    rb: Any,
    cfg: Any,
    fabric_accelerator: str,
    obs_space: Any,
    cnn_keys: Sequence[str],
    mirror_keys: Optional[Sequence[str]] = None,
    copies_per_key: int = 1,
) -> bool:
    """DEPRECATED (kept for external callers): the algo loops now route
    through ``data/device_replay.DeviceReplay`` (``buffer.device``), which
    holds the WHOLE ring in HBM and samples on device — the mirror's
    probe-gated pixel-only subset is subsumed.  Original contract: resolve
    ``auto`` (on iff training on an accelerator), estimate the ring bytes
    from the observation space (× ``copies_per_key`` for layouts that also
    store ``next_<k>`` rows), enforce ``SHEEPRL_MIRROR_BUDGET_BYTES``
    (default 6 GiB) with a printed graceful fallback, and attach.
    Returns whether the mirror is active."""
    mirror_cfg = cfg.buffer.get("device_mirror", "auto")
    if isinstance(mirror_cfg, str) and mirror_cfg.lower() == "auto":
        # on CPU the "mirror" is a pure host-RAM duplicate: only worth it
        # when the train device is a real accelerator
        mirror_cfg = fabric_accelerator != "cpu"
    if not (bool(mirror_cfg) and cnn_keys and hasattr(rb, "attach_mirror")):
        return False
    capacity = rb._buffer_size
    ring_bytes = sum(
        capacity
        * rb.n_envs
        * int(np.prod(obs_space[k].shape))
        * np.dtype(obs_space[k].dtype).itemsize
        * copies_per_key
        for k in cnn_keys
    )
    budget = float(os.environ.get("SHEEPRL_MIRROR_BUDGET_BYTES", 6 * 2**30))
    if ring_bytes > budget:
        print(
            f"[sheeprl_tpu] buffer.device_mirror disabled: pixel ring needs "
            f"{ring_bytes / 2**30:.1f} GiB > budget {budget / 2**30:.1f} GiB "
            "(set SHEEPRL_MIRROR_BUDGET_BYTES to raise)",
            flush=True,
        )
        return False
    rb.attach_mirror(tuple(mirror_keys) if mirror_keys is not None else tuple(cnn_keys))
    return True


class ReplayBuffer:
    """Uniform-sampling FIFO ring buffer over ``Dict[str, (size, n_envs, *)]``.

    Storage is lazily allocated on the first ``add`` (so observation keys and
    shapes need not be declared up front), optionally as ``MemmapArray``s
    under ``memmap_dir`` (reference behavior: sheeprl/data/buffers.py:20-360).
    """

    batch_axis: int = 1

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        memmap: bool = False,
        memmap_dir: Optional[Union[str, os.PathLike]] = None,
        obs_keys: Sequence[str] = (),
        **kwargs: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"buffer_size must be positive, got {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"n_envs must be positive, got {n_envs}")
        self._buffer_size = int(buffer_size)
        self._n_envs = int(n_envs)
        self._memmap = bool(memmap)
        self._memmap_dir = Path(memmap_dir) if memmap_dir is not None else None
        if self._memmap and self._memmap_dir is not None:
            self._memmap_dir.mkdir(parents=True, exist_ok=True)
        self._buf: Dict[str, Union[np.ndarray, MemmapArray]] = {}
        self._obs_keys = tuple(obs_keys)
        self._pos = 0
        self._full = False
        self._mirror: Optional[DeviceMirror] = None
        self._mirror_keys: Tuple[str, ...] = ()
        # set by sample() when a mirror is attached: (U, B) ring slots +
        # (U, B) env columns of the drawn transitions
        self.last_sample_indices: Optional[Tuple[np.ndarray, np.ndarray]] = None

    # -- device mirror -----------------------------------------------------
    @property
    def mirror(self) -> Optional[DeviceMirror]:
        return self._mirror

    def attach_mirror(self, keys: Sequence[str]) -> DeviceMirror:
        """Mirror ``keys`` on the default device (see :class:`DeviceMirror`).

        For next-observation training use buffers that STORE ``next_<k>``
        rows (the SAC-AE layout) and mirror those keys too —
        ``sample_next_obs`` derivation is not index-tracked.
        """
        self._mirror = DeviceMirror(self._buffer_size, self._n_envs)
        self._mirror_keys = tuple(keys)
        self._sync_mirror()
        return self._mirror

    def _sync_mirror(self) -> None:
        filled = self._buffer_size if self._full else self._pos
        if filled == 0:
            return
        idx = np.arange(filled)
        for k in self._mirror_keys:
            if k in self._buf:
                self._mirror.write(
                    k, np.asarray(self._buf[k])[idx], idx[:, None], list(range(self._n_envs))
                )

    # -- properties -------------------------------------------------------
    @property
    def buffer(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self._buf.items()}

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def full(self) -> bool:
        return self._full

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    @property
    def empty(self) -> bool:
        return not self._buf

    def __len__(self) -> int:
        return self._buffer_size if self._full else self._pos

    def __contains__(self, key: str) -> bool:
        return key in self._buf

    def __getitem__(self, key: str) -> np.ndarray:
        return np.asarray(self._buf[key])

    def keys(self) -> Tuple[str, ...]:
        return tuple(self._buf.keys())

    # -- write path -------------------------------------------------------
    def _allocate(self, key: str, shape: Tuple[int, ...], dtype: Any) -> None:
        full_shape = (self._buffer_size, self._n_envs) + tuple(shape)
        if self._memmap:
            filename = None
            if self._memmap_dir is not None:
                filename = self._memmap_dir / f"{key}.memmap"
            self._buf[key] = MemmapArray(full_shape, dtype=dtype, filename=filename)
        else:
            self._buf[key] = np.zeros(full_shape, dtype=dtype)

    def add(self, data: Arrays, indices: Optional[Sequence[int]] = None) -> None:
        """Append ``T`` steps of ``(T, B, *)`` data for all (or ``indices``) envs."""
        if not isinstance(data, dict) or not data:
            raise ValueError("add() expects a non-empty dict of (T, B, *) arrays")
        steps, envs = _steps_and_envs(data)
        if steps > self._buffer_size:
            # keep only the last buffer_size steps
            data = {k: v[-self._buffer_size:] for k, v in data.items()}
            steps = self._buffer_size
        env_sel = np.arange(self._n_envs) if indices is None else np.asarray(indices)
        if envs != len(env_sel):
            raise ValueError(f"data has {envs} envs, expected {len(env_sel)}")
        for k, v in data.items():
            if k not in self._buf:
                self._allocate(k, v.shape[2:], v.dtype)
        idx = (self._pos + np.arange(steps)) % self._buffer_size
        for k, v in data.items():
            self._buf[k][idx[:, None], env_sel[None, :]] = v
        if self._mirror is not None:
            for k in self._mirror_keys:
                if k in data:
                    self._mirror.write(k, np.asarray(data[k]), idx[:, None], list(env_sel))
        if self._pos + steps >= self._buffer_size:
            self._full = True
        self._pos = int((self._pos + steps) % self._buffer_size)

    # -- read path --------------------------------------------------------
    def _valid_steps(self, sample_next_obs: bool) -> np.ndarray:
        """Step indices that can be sampled.  When ``sample_next_obs`` we must
        not sample the slot right before the write head (its successor is the
        oldest, unrelated step — reference: sheeprl/data/buffers.py:244-264)."""
        if self._full:
            if sample_next_obs:
                valid = (self._pos + np.arange(self._buffer_size - 1)) % self._buffer_size
            else:
                valid = np.arange(self._buffer_size)
        else:
            n = self._pos - 1 if sample_next_obs else self._pos
            valid = np.arange(max(n, 0))
        return valid

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        keys: Optional[Sequence[str]] = None,
        **kwargs: Any,
    ) -> Arrays:
        """Uniformly sample ``n_samples`` × ``batch_size`` transitions.

        Returns ``(n_samples, batch_size, *)`` arrays.  When
        ``sample_next_obs`` is set, adds ``next_<key>`` entries for every
        observation key by reading the successor step.  ``keys`` restricts
        the gathered output (the drawn indices are unchanged — a
        DeviceMirror gathers the excluded keys on device from
        ``last_sample_indices``).
        """
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError("batch_size and n_samples must be positive")
        if self.empty or len(self) == 0:
            raise RuntimeError("Cannot sample from an empty buffer")
        valid = self._valid_steps(sample_next_obs)
        if valid.size == 0:
            raise RuntimeError("No valid steps to sample (buffer too small)")
        total = batch_size * n_samples
        step_idx = valid[np.random.randint(0, valid.size, size=total)]
        env_idx = np.random.randint(0, self._n_envs, size=total)
        self.last_sample_indices = (
            step_idx.reshape(n_samples, batch_size),
            env_idx.reshape(n_samples, batch_size),
        )
        batch = self._gather(step_idx, env_idx, sample_next_obs, keys=keys)
        return {k: v.reshape(n_samples, batch_size, *v.shape[1:]) for k, v in batch.items()}

    def _gather(
        self,
        step_idx: np.ndarray,
        env_idx: np.ndarray,
        sample_next_obs: bool,
        keys: Optional[Sequence[str]] = None,
    ) -> Arrays:
        out: Arrays = {}
        for k, v in self._buf.items():
            if keys is not None and k not in keys:
                continue
            arr = np.asarray(v)
            out[k] = arr[step_idx, env_idx]
        if sample_next_obs:
            next_idx = (step_idx + 1) % self._buffer_size
            obs_keys = self._obs_keys or tuple(k for k in self._buf if k.startswith("obs") or k == "observations")
            for k in obs_keys:
                if k in self._buf and (keys is None or k in keys):
                    out[f"next_{k}"] = np.asarray(self._buf[k])[next_idx, env_idx]
        return out

    def repair_tail(self, env: int = 0) -> None:
        """Mark the last written step as a truncation: called when the data
        stream breaks mid-episode (e.g. a crashed-and-restarted env) so the
        stored partial episode never bootstraps across the break.  The
        patched row must not also start an episode (reference behavior:
        sheeprl/algos/dreamer_v3/dreamer_v3.py:595-608)."""
        if len(self) == 0:
            return
        tail = (self._pos - 1) % self._buffer_size
        for key, value in (("truncated", 1.0), ("terminated", 0.0), ("is_first", 0.0)):
            if key in self._buf:
                self._buf[key][tail, env] = value

    def sample_tensors(self, batch_size: int, dtype: Optional[Any] = None, device: Optional[Any] = None, **kwargs: Any) -> Dict[str, Any]:
        return to_device(self.sample(batch_size, **kwargs), dtype=dtype, device=device)

    def to_tensor(self, dtype: Optional[Any] = None, device: Optional[Any] = None) -> Dict[str, Any]:
        return to_device(self.buffer, dtype=dtype, device=device)

    # -- persistence ------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        return {
            "buffer": {k: v if isinstance(v, MemmapArray) else np.asarray(v) for k, v in self._buf.items()},
            "pos": self._pos,
            "full": self._full,
            "buffer_size": self._buffer_size,
            "n_envs": self._n_envs,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> "ReplayBuffer":
        if state["buffer_size"] != self._buffer_size or state["n_envs"] != self._n_envs:
            raise ValueError(
                "Checkpointed buffer has incompatible geometry: "
                f"size {state['buffer_size']} x envs {state['n_envs']} vs "
                f"{self._buffer_size} x {self._n_envs} (resume requires the same world size, "
                "as in the reference, sheeprl/algos/dreamer_v3/dreamer_v3.py:486-492)"
            )
        self._buf = dict(state["buffer"])
        self._pos = int(state["pos"])
        self._full = bool(state["full"])
        if self._mirror is not None:
            self._sync_mirror()  # mirror is derived state: rebuild on resume
        return self


class SequentialReplayBuffer(ReplayBuffer):
    """Samples contiguous length-L sequences, ignoring episode boundaries,
    with modulo wrap-around when full (reference: sheeprl/data/buffers.py:363-526).

    Output layout: ``(n_samples, sequence_length, batch_size, *)`` — the
    natural shape for a ``lax.scan`` over time with a static batch.
    """

    batch_axis: int = 2

    def sample(
        self,
        batch_size: int,
        sequence_length: int = 1,
        n_samples: int = 1,
        sample_next_obs: bool = False,
        keys: Optional[Sequence[str]] = None,
        **kwargs: Any,
    ) -> Arrays:
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError("batch_size and n_samples must be positive")
        if sequence_length <= 0:
            raise ValueError(f"sequence_length must be positive, got {sequence_length}")
        filled = len(self)
        if filled == 0:
            raise RuntimeError("Cannot sample from an empty buffer")
        if filled < sequence_length:
            raise RuntimeError(
                f"Buffer has {filled} steps, fewer than sequence_length={sequence_length}"
            )
        # valid sequence start offsets (relative to the oldest step); one
        # extra trailing step is reserved when next-observations are needed
        span = sequence_length + (1 if sample_next_obs else 0)
        if self._full:
            # a sequence may not cross the write head
            max_start = self._buffer_size - span
            base = self._pos
        else:
            max_start = self._pos - span
            base = 0
        if max_start < 0:
            raise RuntimeError("Not enough contiguous data for the requested sequence length")
        total = batch_size * n_samples
        starts = np.random.randint(0, max_start + 1, size=total)
        env_idx = np.random.randint(0, self._n_envs, size=total)
        # absolute step indices (total, L)
        step_idx = (base + starts[:, None] + np.arange(sequence_length)[None, :]) % self._buffer_size
        # record the drawn ring coordinates in the output layout so a
        # DeviceMirror can gather the same sequences on device:
        # (n_samples, L, batch) time slots + (n_samples, batch) env columns
        self.last_sequence_indices = step_idx.reshape(
            n_samples, batch_size, sequence_length
        ).swapaxes(1, 2)

        def gather(arr: np.ndarray, idx: np.ndarray) -> np.ndarray:
            g = arr[idx, env_idx[:, None]]  # (total, L, *)
            return g.reshape(n_samples, batch_size, sequence_length, *arr.shape[2:]).swapaxes(1, 2)

        out: Arrays = {}
        for k, v in self._buf.items():
            if keys is not None and k not in keys:
                continue
            out[k] = gather(np.asarray(v), step_idx)
        if sample_next_obs:
            next_idx = (step_idx + 1) % self._buffer_size
            obs_keys = self._obs_keys or tuple(
                k for k in self._buf if k.startswith("obs") or k == "observations"
            )
            for k in obs_keys:
                if k in self._buf and (keys is None or k in keys):
                    out[f"next_{k}"] = gather(np.asarray(self._buf[k]), next_idx)
        return out


class EnvIndependentReplayBuffer:
    """One sub-buffer per environment stream
    (reference: sheeprl/data/buffers.py:529-743).

    Needed because per-env streams advance at different rates after resets;
    sampling draws a multinomial split across sub-buffers then concatenates
    on the sub-buffer class's batch axis.
    """

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        memmap: bool = False,
        memmap_dir: Optional[Union[str, os.PathLike]] = None,
        buffer_cls: type = SequentialReplayBuffer,
        **kwargs: Any,
    ):
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._buffer_cls = buffer_cls
        self._buffers: List[ReplayBuffer] = []
        for i in range(n_envs):
            sub_dir = None
            if memmap and memmap_dir is not None:
                sub_dir = Path(memmap_dir) / f"env_{i}"
            self._buffers.append(
                buffer_cls(buffer_size, n_envs=1, memmap=memmap, memmap_dir=sub_dir, **kwargs)
            )
        self._concat_along = getattr(buffer_cls, "batch_axis", 1)
        self._mirror: Optional[DeviceMirror] = None
        self._mirror_keys: Tuple[str, ...] = ()
        # set by sample() when a mirror is attached: (U, L, B) ring slots +
        # (U, L, B) env columns in the concatenated output's batch order
        self.last_sample_indices: Optional[Tuple[np.ndarray, np.ndarray]] = None

    # -- device mirror -----------------------------------------------------
    @property
    def mirror(self) -> Optional[DeviceMirror]:
        return self._mirror

    def attach_mirror(self, keys: Sequence[str]) -> DeviceMirror:
        """Mirror ``keys`` on the default device (see :class:`DeviceMirror`);
        uploads any content already in the host ring."""
        if self._buffer_cls is not SequentialReplayBuffer:
            raise ValueError("DeviceMirror requires SequentialReplayBuffer sub-buffers")
        self._mirror = DeviceMirror(self._buffer_size, self._n_envs)
        self._mirror_keys = tuple(keys)
        self._sync_mirror()
        return self._mirror

    def _sync_mirror(self) -> None:
        for env, b in enumerate(self._buffers):
            filled = len(b)
            if filled == 0:
                continue
            idx = np.arange(self._buffer_size if b.full else filled)
            for k in self._mirror_keys:
                if k in b:
                    rows = np.asarray(b[k])[idx]  # (T, 1, *) sub-buffer col
                    self._mirror.write(k, rows, idx[:, None], [env])

    @property
    def buffer(self) -> List[ReplayBuffer]:
        return self._buffers

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def full(self) -> bool:
        return all(b.full for b in self._buffers)

    def __len__(self) -> int:
        return sum(len(b) for b in self._buffers)

    def add(self, data: Arrays, indices: Optional[Sequence[int]] = None) -> None:
        env_sel = list(range(self._n_envs)) if indices is None else list(indices)
        write_pos = None
        if self._mirror is not None:
            # the ring slots each sub-buffer is ABOUT to write (its add()
            # advances _pos); same truncation law as ReplayBuffer.add
            steps, _ = _steps_and_envs(data)
            steps = min(steps, self._buffer_size)
            write_pos = np.stack(
                [
                    (self._buffers[env]._pos + np.arange(steps)) % self._buffer_size
                    for env in env_sel
                ],
                axis=1,
            )  # (T, K)
        for col, env in enumerate(env_sel):
            self._buffers[env].add({k: v[:, col:col + 1] for k, v in data.items()})
        if self._mirror is not None:
            for k in self._mirror_keys:
                if k in data:
                    self._mirror.write(k, data[k][-write_pos.shape[0]:], write_pos, env_sel)

    def sample(self, batch_size: int, n_samples: int = 1, track_indices: bool = False, **kwargs: Any) -> Arrays:
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError("batch_size and n_samples must be positive")
        # only sub-buffers able to serve the request get sampling mass
        min_len = kwargs.get("sequence_length", 1) + (1 if kwargs.get("sample_next_obs") else 0)
        occupied = np.array(
            [len(b) if len(b) >= min_len else 0 for b in self._buffers], dtype=np.float64
        )
        if occupied.sum() == 0:
            raise RuntimeError("Cannot sample from an empty buffer")
        probs = occupied / occupied.sum()
        counts = np.random.multinomial(batch_size, probs)
        # index tracking feeds device-side gathers at the SAME draw
        # (DeviceReplay.gather_at); explicit `track_indices=True` replaces
        # the old implicit mirror-attached gate
        track = track_indices or self._mirror is not None
        if track and self._buffer_cls is not SequentialReplayBuffer:
            # only sequential sub-buffers record their drawn ring slots
            # (last_sequence_indices) — same constraint attach_mirror enforced
            raise ValueError(
                "track_indices requires SequentialReplayBuffer sub-buffers "
                "(uniform sub-buffers do not record sampled ring slots)"
            )
        parts: List[Arrays] = []
        idx_parts: List[np.ndarray] = []
        env_parts: List[np.ndarray] = []
        for env, (b, c) in enumerate(zip(self._buffers, counts)):
            if c > 0:
                parts.append(b.sample(int(c), n_samples=n_samples, **kwargs))
                if track:
                    t_idx = b.last_sequence_indices  # (U, L, c)
                    idx_parts.append(t_idx)
                    env_parts.append(np.full_like(t_idx, env))
        if track and idx_parts:
            self.last_sample_indices = (
                np.concatenate(idx_parts, axis=2),
                np.concatenate(env_parts, axis=2),
            )
        keys = parts[0].keys()
        return {k: np.concatenate([p[k] for p in parts], axis=self._concat_along) for k in keys}

    def repair_tail(self, env: int) -> None:
        """See :meth:`ReplayBuffer.repair_tail` — applied to one env stream."""
        self._buffers[env].repair_tail(env=0)

    def sample_tensors(self, batch_size: int, dtype: Optional[Any] = None, device: Optional[Any] = None, **kwargs: Any) -> Dict[str, Any]:
        return to_device(self.sample(batch_size, **kwargs), dtype=dtype, device=device)

    def state_dict(self) -> Dict[str, Any]:
        return {"buffers": [b.state_dict() for b in self._buffers]}

    def load_state_dict(self, state: Dict[str, Any]) -> "EnvIndependentReplayBuffer":
        saved = state["buffers"]
        if len(saved) != self._n_envs:
            raise ValueError(
                f"Checkpoint has {len(saved)} env buffers, expected {self._n_envs}"
            )
        for b, s in zip(self._buffers, saved):
            b.load_state_dict(s)
        if self._mirror is not None:
            self._sync_mirror()  # mirror is derived state: rebuild on resume
        return self


class EpisodeBuffer:
    """Whole-episode storage with end-prioritized sequence sampling
    (reference: sheeprl/data/buffers.py:746-1155).

    Open episodes accumulate per-env; an episode is committed on terminal /
    truncation if it is at least ``minimum_episode_length`` long, evicting the
    oldest committed episodes when total stored steps would exceed
    ``buffer_size``.
    """

    def __init__(
        self,
        buffer_size: int,
        sequence_length: int,
        n_envs: int = 1,
        prioritize_ends: bool = False,
        minimum_episode_length: Optional[int] = None,
        memmap: bool = False,
        memmap_dir: Optional[Union[str, os.PathLike]] = None,
        **kwargs: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"buffer_size must be positive, got {buffer_size}")
        if sequence_length <= 0:
            raise ValueError(f"sequence_length must be positive, got {sequence_length}")
        self._buffer_size = buffer_size
        self._sequence_length = sequence_length
        self._minimum_episode_length = minimum_episode_length or sequence_length
        if self._minimum_episode_length < sequence_length:
            raise ValueError("minimum_episode_length must be >= sequence_length")
        self._n_envs = n_envs
        self._prioritize_ends = prioritize_ends
        self._memmap = memmap
        self._memmap_dir = Path(memmap_dir) if memmap_dir is not None else None
        self._episodes: List[Arrays] = []
        self._open: List[Optional[Arrays]] = [None] * n_envs
        self._stored_steps = 0

    @property
    def buffer(self) -> List[Arrays]:
        return self._episodes

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def full(self) -> bool:
        return self._stored_steps >= self._buffer_size

    def __len__(self) -> int:
        return self._stored_steps

    def add(self, data: Arrays, indices: Optional[Sequence[int]] = None) -> None:
        """``data`` is ``(T, B, *)`` and must contain a ``terminated`` or
        ``truncated``/``dones`` signal to commit episodes."""
        done = None
        for key in ("dones", "terminated"):
            if key in data:
                done = data[key].astype(bool)
                break
        if done is None:
            raise ValueError("EpisodeBuffer.add requires a 'dones' or 'terminated' key")
        if "truncated" in data:
            done = done | data["truncated"].astype(bool)
        steps, envs = _steps_and_envs(data)
        env_sel = list(range(self._n_envs)) if indices is None else list(indices)
        for col, env in enumerate(env_sel):
            for t in range(steps):
                step = {k: v[t, col] for k, v in data.items()}
                if self._open[env] is None:
                    self._open[env] = {k: [] for k in data}
                for k, v in step.items():
                    self._open[env][k].append(v)
                if bool(done[t, col].reshape(-1)[0] if hasattr(done[t, col], "reshape") else done[t, col]):
                    self._commit(env)

    def repair_tail(self, env: int) -> None:
        """The stream for ``env`` broke mid-episode: the open (uncommitted)
        episode can never be finished — discard it."""
        self._open[env] = None

    def _commit(self, env: int) -> None:
        open_ep = self._open[env]
        self._open[env] = None
        if open_ep is None:
            return
        length = len(next(iter(open_ep.values())))
        if length < self._minimum_episode_length:
            return
        episode = {k: np.stack(v) for k, v in open_ep.items()}
        if self._memmap:
            ep_id = self._episode_counter = getattr(self, "_episode_counter", 0) + 1
            episode = {
                k: MemmapArray.from_array(
                    v,
                    filename=(self._memmap_dir / f"ep_{ep_id}_{k}.memmap")
                    if self._memmap_dir is not None
                    else None,
                )
                for k, v in episode.items()
            }
        self._episodes.append(episode)
        self._stored_steps += length
        while self._stored_steps > self._buffer_size and self._episodes:
            evicted = self._episodes.pop(0)
            self._stored_steps -= len(next(iter(evicted.values())))
            for v in evicted.values():
                if isinstance(v, MemmapArray):
                    v.close(delete_file=True)

    def sample(
        self,
        batch_size: int,
        n_samples: int = 1,
        sequence_length: Optional[int] = None,
        **kwargs: Any,
    ) -> Arrays:
        """Returns ``(n_samples, L, batch_size, *)`` sequences: episodes are
        chosen UNIFORMLY among those long enough (reference semantics —
        data/buffers.py:1077-1080 uses a uniform randint over valid episodes,
        NOT length weighting), then a start index uniform over the valid
        range; with ``prioritize_ends`` the start draw runs over the FULL
        episode and clamps to the last valid start, so the final window
        carries (L+1)/(ep_len+1) of the mass (reference: buffers.py:1092-1099)."""
        L = sequence_length or self._sequence_length
        if not self._episodes:
            raise RuntimeError("Cannot sample from an empty EpisodeBuffer")
        lengths = np.array([len(next(iter(ep.values()))) for ep in self._episodes])
        eligible = np.where(lengths >= L)[0]
        if eligible.size == 0:
            raise RuntimeError(f"No episode is >= sequence_length={L}")
        total = batch_size * n_samples
        chosen = np.random.choice(eligible, size=total)
        keys = self._episodes[0].keys()
        gathered: Dict[str, List[np.ndarray]] = {k: [] for k in keys}
        for ep_idx in chosen:
            ep = self._episodes[ep_idx]
            ep_len = lengths[ep_idx]
            max_start = ep_len - L
            if self._prioritize_ends:
                start = min(np.random.randint(0, ep_len + 1), max_start)
            else:
                start = np.random.randint(0, max_start + 1)
            for k in keys:
                gathered[k].append(ep[k][start:start + L])
        out: Arrays = {}
        for k, chunks in gathered.items():
            arr = np.stack(chunks)  # (total, L, *)
            out[k] = arr.reshape(n_samples, batch_size, L, *arr.shape[2:]).swapaxes(1, 2)
        return out

    def sample_tensors(self, batch_size: int, dtype: Optional[Any] = None, device: Optional[Any] = None, **kwargs: Any) -> Dict[str, Any]:
        return to_device(self.sample(batch_size, **kwargs), dtype=dtype, device=device)

    def state_dict(self) -> Dict[str, Any]:
        # open episodes are dropped, like the reference checkpoint trick
        # (sheeprl/utils/callback.py:122-142)
        return {"episodes": self._episodes, "stored_steps": self._stored_steps}

    def load_state_dict(self, state: Dict[str, Any]) -> "EpisodeBuffer":
        self._episodes = list(state["episodes"])
        self._stored_steps = int(state["stored_steps"])
        return self
