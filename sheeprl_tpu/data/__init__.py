"""sheeprl_tpu.data: replay buffers.

``buffers`` holds the host-numpy suite (``ReplayBuffer``,
``SequentialReplayBuffer``, ``EnvIndependentReplayBuffer``,
``EpisodeBuffer``) plus the deprecated ``DeviceMirror`` shim;
``device_replay`` is the zero-copy device-resident path — the
mesh-sharded HBM ring with on-device sampling compiled into the update
dispatch (docs/device_replay.md) that the algo loops use on accelerators.
"""

from sheeprl_tpu.data.buffers import (  # noqa: F401
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
)
from sheeprl_tpu.data.device_replay import (  # noqa: F401
    DeviceReplay,
    HostSpill,
    resolve_device_replay,
    steady_guard,
    update_chunks,
)
