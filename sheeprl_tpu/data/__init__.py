"""sheeprl_tpu.data."""
