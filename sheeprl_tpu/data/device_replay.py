"""Zero-copy device-resident replay: sharded HBM dataflow with on-device sampling.

The steady-state dataflow gap this closes (ROADMAP item 4; MindSpeed RL,
arXiv:2507.19017; Podracer/Anakin, arXiv:2104.06272): every algorithm used
to sample replay on the host with numpy and ship a fresh ``(U, ..., B, *)``
batch H2D on every update window, and the ``DeviceMirror`` pixel path was
per-device and probe-gated rather than mesh-sharded.  :class:`DeviceReplay`
makes HBM the home of replay:

* **Storage** is one pytree of device arrays ``(capacity, n_envs, *feat)``,
  sharded over the mesh ``data`` axis along the env dimension
  (:func:`sheeprl_tpu.parallel.sharding.replay_sharding`) so the ring's
  layout matches what ``fabric.shard_batch`` would give a shipped batch.
* **Writes are donated in-place**: the actor path appends host rows with one
  explicit ``device_put`` per key plus a jitted ``buffer.at[slots].set(rows)``
  whose ring argument is donated — no HBM reallocation, no 2x spike.
* **Sampling is compiled into the update step**: :meth:`sample_uniform` /
  :meth:`sample_sequences` are pure jit-traceable functions over
  ``(buffers, cursor, key)``; :func:`fused_uniform_train` /
  :func:`fused_sequence_train` fold index generation + gather + the algo's
  existing train phase into ONE ``fabric.compile`` AOT executable.  In steady
  state the update dispatch performs **zero host-to-device transfers** — a
  contract ``steady_guard`` can enforce with ``jax.transfer_guard``.
* **Capacity beyond the HBM window spills to the host asynchronously**
  (:class:`HostSpill`, the ``checkpoint/writer.py`` background-thread
  pattern): appends enqueue host rows to a full-capacity shadow ring
  (optionally memmapped) without ever blocking the compiled step; a stalled
  spill tier (chaos site ``replay.spill``) slows eviction bookkeeping only.

Cursors (``pos``/``filled`` per env) live on device as ``int32`` data, so 50
windows of sample+update reuse ONE executable — cursor motion is values, not
signatures (asserted by ``tests/test_data/test_device_replay.py``).
"""

from __future__ import annotations

import contextlib
import os
import queue
import threading
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

Arrays = Dict[str, np.ndarray]


# --------------------------------------------------------------------------
# config resolution
# --------------------------------------------------------------------------

def resolve_device_replay(cfg: Any, fabric_accelerator: str) -> bool:
    """One policy for every algo's ``buffer.device`` handling: ``auto`` means
    on iff training on a real accelerator (on CPU the "device ring" would be
    a host-RAM duplicate of the host ring — same RAM, none of the H2D win);
    True/False force it (tests force True on CPU to exercise the path)."""
    mode = cfg.buffer.get("device", "auto")
    if isinstance(mode, str) and mode.lower() == "auto":
        return fabric_accelerator != "cpu"
    return bool(mode)


def estimate_step_bytes(
    obs_space: Any, obs_keys: Sequence[str], extra_bytes: int = 64, copies_per_key: int = 1
) -> int:
    """Per-(env, step) ring bytes estimated from the observation space —
    sized BEFORE allocation so :func:`fit_hbm_window` can shrink the HBM
    window (and arm the spill tier) instead of dying in an HBM alloc.
    ``extra_bytes`` covers actions/rewards/flags; ``copies_per_key`` is 2 for
    layouts that also store ``next_<k>`` rows (SAC-AE)."""
    total = int(extra_bytes)
    for k in obs_keys:
        space = obs_space[k]
        total += int(np.prod(space.shape)) * np.dtype(space.dtype).itemsize * int(copies_per_key)
    return total


def fit_hbm_window(
    capacity: int, n_envs: int, step_bytes: int, requested: Optional[int] = None
) -> Tuple[int, bool]:
    """``(hbm_window_steps, spill_needed)`` under the device byte budget
    (``SHEEPRL_REPLAY_BUDGET_BYTES``, default 8 GiB).  The window is the
    per-env ring length kept in HBM; anything beyond pages to the host spill
    tier.  An explicit ``buffer.hbm_window`` is honored (still budget-capped)."""
    budget = float(os.environ.get("SHEEPRL_REPLAY_BUDGET_BYTES", 8 * 2**30))
    window = int(capacity) if requested is None else min(int(requested), int(capacity))
    fits = max(1, int(budget // max(step_bytes * n_envs, 1)))
    if window > fits:
        print(
            f"[sheeprl_tpu] buffer.device: HBM window shrunk {window} -> {fits} "
            f"steps/env (~{step_bytes * n_envs * fits / 2**30:.2f} GiB ring; raise "
            "SHEEPRL_REPLAY_BUDGET_BYTES to widen) — older data pages to the host "
            "spill tier",
            flush=True,
        )
        window = fits
    return window, window < int(capacity)


def update_chunks(
    n_updates: int, cap: Optional[int] = None, bytes_per_update: float = 0.0
) -> List[int]:
    """Split an update window into power-of-two dispatch chunk sizes.

    Replaces the byte-probed ``utils.window_chunks``: with device-resident
    replay nothing ships H2D, but two budgets remain —

    * COMPILE reuse: every distinct chunk length U is its own abstract
      signature, and a remote-TPU compile costs minutes.  Powers of two
      (largest first, greedy remainder) keep a burst window (the
      post-``learning_starts`` ratio repayment) to a handful of executables
      whose small tail sizes coincide with the steady-state window sizes.
      ``cap`` (default ``SHEEPRL_MAX_WINDOW_UPDATES``, 1024) bounds any
      single scanned dispatch.
    * HBM: the fused program still MATERIALIZES the gathered ``(U, ...)``
      block on device before scanning it — a U=1024 DV3-S pixel burst is
      ~12.9 GiB raw / ~2x padded, the exact alloc that killed the round-5
      TPU capture.  Pass the per-update gathered bytes (see
      ``DeviceReplay.sampled_bytes_per_update``) and the cap also honors
      ``SHEEPRL_MAX_HBM_WINDOW_BYTES`` (default 2 GiB, the same knob the
      retired ``window_chunks`` used for on-device gathered blocks).
    """
    if cap is None:
        cap = int(os.environ.get("SHEEPRL_MAX_WINDOW_UPDATES", 1024))
    if bytes_per_update > 0.0:
        hbm_budget = float(os.environ.get("SHEEPRL_MAX_HBM_WINDOW_BYTES", 2**31))
        cap = min(int(cap), max(1, int(hbm_budget // bytes_per_update)))
    cap = 1 << (max(1, int(cap)).bit_length() - 1)
    chunks: List[int] = []
    remaining = int(n_updates)
    while remaining > 0:
        step = min(cap, 1 << (remaining.bit_length() - 1))
        chunks.append(step)
        remaining -= step
    return chunks


@contextlib.contextmanager
def steady_guard(enabled: bool):
    """Arm ``jax.transfer_guard_host_to_device("disallow")`` around a
    steady-state train window: any IMPLICIT host→device transfer inside
    raises (explicit ``device_put`` staging stays legal).  This is the
    red/green spelling of the zero-copy claim — the same guard ``bench.py``
    arms around its timed loop and the ``run_ci.sh`` replay stage arms
    around whole training runs.

    Scoped to the H2D direction on purpose: device-to-device movement (the
    per-window PRNG key broadcasting onto a multi-device mesh, GSPMD
    resharding) rides ICI and is not host traffic, and device-to-host pulls
    are the metrics/logging path — neither is the copy this guard exists to
    outlaw."""
    if not enabled:
        yield
        return
    import jax

    with jax.transfer_guard_host_to_device("disallow"):
        yield


# --------------------------------------------------------------------------
# async host spill tier
# --------------------------------------------------------------------------

class HostSpill:
    """Asynchronous full-capacity host shadow of a :class:`DeviceReplay`.

    Reuses the ``checkpoint/writer.py`` split of work: the CALLER (env/actor
    path) copies the incoming host rows and enqueues; ONE daemon worker
    drains the queue into a host ring (``ReplayBuffer`` /
    ``SequentialReplayBuffer``, optionally memmapped) so capacity beyond the
    HBM window survives without ever blocking the compiled train step — the
    train step never touches this tier at all.  The ``replay.spill`` fault
    site (latency / raise / truncate) instruments the worker's write:

    * latency/hang → eviction bookkeeping falls behind (queue grows), the
      device ring and sampling are unaffected;
    * raise → the error is parked, :attr:`degraded` flips, later writes
      continue (a dead spill disk degrades capacity, not training);
    * truncate → the queued rows are tail-halved before the write (the
      chaos drill for torn spill writes).
    """

    def __init__(
        self,
        capacity: int,
        n_envs: int,
        sequential: bool = False,
        memmap: bool = False,
        memmap_dir: Optional[Union[str, os.PathLike]] = None,
        queue_size: int = 256,
    ):
        from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, ReplayBuffer

        if sequential:
            # per-env sub-buffers, NOT a shared-cursor ring: the dreamer add
            # path appends reset rows to done envs only (``indices=``), and a
            # shared cursor would advance every env's stream for a subset
            # write, misaligning the shadow history
            self._rb: Any = EnvIndependentReplayBuffer(
                int(capacity), n_envs=int(n_envs), memmap=memmap, memmap_dir=memmap_dir
            )
        else:
            self._rb = ReplayBuffer(int(capacity), int(n_envs), memmap=memmap, memmap_dir=memmap_dir)
        self._queue: "queue.Queue[Optional[Tuple[Arrays, Optional[List[int]]]]]" = queue.Queue(
            maxsize=max(1, int(queue_size))
        )
        self._error: Optional[BaseException] = None
        self._idle = threading.Event()
        self._idle.set()
        self._pending = 0
        self._lock = threading.Lock()
        self._closed = False
        self._thread = threading.Thread(target=self._loop, name="replay-spill", daemon=True)
        self._thread.start()

    # -- worker --------------------------------------------------------------
    def _loop(self) -> None:
        from sheeprl_tpu.resilience.faults import fault_rows

        while True:
            job = self._queue.get()
            if job is None:
                self._queue.task_done()
                return
            data, indices = job
            try:
                data = fault_rows("replay.spill", data)
                self._rb.add(data, indices=indices)
            except BaseException as e:  # parked: spill degrades, never kills
                if self._error is None:
                    self._error = e
                    warnings.warn(
                        f"replay spill tier degraded ({type(e).__name__}: {e}); the "
                        "device ring keeps training, capacity beyond the HBM window "
                        "is no longer persisted",
                        RuntimeWarning,
                    )
            finally:
                self._queue.task_done()
                with self._lock:
                    self._pending -= 1
                    if self._pending == 0:
                        self._idle.set()

    # -- API -----------------------------------------------------------------
    @property
    def degraded(self) -> bool:
        return self._error is not None

    @property
    def backlog(self) -> int:
        return self._queue.unfinished_tasks

    @property
    def buffer(self) -> Any:
        """The host ring (drain with :meth:`flush` before reading)."""
        return self._rb

    def submit(self, data: Arrays, indices: Optional[Sequence[int]] = None) -> None:
        """Enqueue one append.  Rows are COPIED here (the caller reuses its
        step arrays).  Blocks only when the bounded queue is full — back
        pressure on the (host) actor path, never on the device step."""
        if self._closed:
            return
        copied = {k: np.array(v, copy=True) for k, v in data.items()}
        with self._lock:
            self._pending += 1
            self._idle.clear()
        self._queue.put((copied, list(indices) if indices is not None else None))

    def flush(self, timeout_s: Optional[float] = 60.0) -> bool:
        return self._idle.wait(timeout_s)

    def state_dict(self) -> Dict[str, Any]:
        self.flush()
        return self._rb.state_dict()

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.flush()
        self._rb.load_state_dict(state)

    def close(self, timeout_s: float = 30.0) -> None:
        if self._closed:
            return
        self._closed = True
        self._idle.wait(timeout_s)
        try:
            self._queue.put(None, timeout=5.0)
        except queue.Full:
            pass
        self._thread.join(5.0)


# --------------------------------------------------------------------------
# the device-resident ring
# --------------------------------------------------------------------------

class DeviceReplay:
    """Mesh-sharded device-resident replay ring over ``Dict[str, (W, E, *)]``.

    ``W`` is the HBM window (steps per env), ``E`` the env count.  Arrays are
    placed with ``PartitionSpec(None, 'data', ...)`` when the env axis
    divides the mesh ``data`` axis (else replicated) — the same layout
    ``fabric.shard_batch`` gives shipped batches, so gathers stay mostly
    shard-local and GSPMD inserts the cross-shard collectives where a
    sampled batch needs them.

    Write path: host ``(T, B, *)`` rows → one explicit ``device_put`` per
    key → a donated jitted scatter at ring slots derived from per-env
    cursors.  Cursors live twice: as ``int32`` device arrays (``cursor``
    — sampling consumes them INSIDE the compiled update, so their motion is
    data, not signature) and as host numpy shadows (``len()``/eligibility
    checks without device syncs).
    """

    def __init__(
        self,
        capacity: int,
        n_envs: int,
        mesh: Optional[Any] = None,
        data_axis: str = "data",
        spill: Optional[HostSpill] = None,
    ):
        import jax
        import jax.numpy as jnp

        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        if n_envs <= 0:
            raise ValueError(f"n_envs must be positive, got {n_envs}")
        self._capacity = int(capacity)
        self._n_envs = int(n_envs)
        self._mesh = mesh
        self._data_axis = data_axis
        self.spill = spill
        self._buf: Dict[str, Any] = {}
        self._sharding = None
        if mesh is not None:
            from sheeprl_tpu.parallel.sharding import replay_sharding

            self._sharding = replay_sharding(mesh, n_envs, data_axis)
        self._pos_h = np.zeros(self._n_envs, np.int64)
        self._filled_h = np.zeros(self._n_envs, np.int64)
        zeros = jnp.zeros(self._n_envs, jnp.int32)
        if self._sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            replicated = NamedSharding(mesh, P())
            zeros = jax.device_put(zeros, replicated)
        self.cursor: Dict[str, Any] = {"pos": zeros, "filled": zeros}
        self._scatter = None
        self._gather = None
        self._advance = None

    # -- geometry / introspection -------------------------------------------
    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def buffer_size(self) -> int:  # host-buffer API parity
        return self._capacity

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def buffers(self) -> Dict[str, Any]:
        """The device pytree — pass it (with :attr:`cursor`) into a fused
        train program; never copied, never donated."""
        return self._buf

    @property
    def full(self) -> bool:
        return bool((self._filled_h >= self._capacity).all())

    @property
    def empty(self) -> bool:
        return not self._buf

    def __len__(self) -> int:
        return int(self._filled_h.sum())

    def __contains__(self, key: str) -> bool:
        return key in self._buf

    def keys(self) -> Tuple[str, ...]:
        return tuple(self._buf.keys())

    @property
    def hbm_bytes(self) -> int:
        """Resident ring bytes (the ``replay_hbm_bytes`` bench column)."""
        return sum(int(np.prod(a.shape)) * a.dtype.itemsize for a in self._buf.values())

    def sampled_bytes_per_update(
        self,
        batch_size: int,
        sequence_length: int = 1,
        derive_next: Sequence[str] = (),
    ) -> float:
        """HBM bytes one update's gathered batch materializes on device —
        the ``bytes_per_update`` input to :func:`update_chunks`, computed
        exactly from the allocated ring (call after the first ``add``)."""
        total = 0.0
        for k, buf in self._buf.items():
            row = int(np.prod(buf.shape[2:])) * buf.dtype.itemsize
            copies = 2 if k in derive_next else 1
            total += row * int(batch_size) * int(sequence_length) * copies
        return total

    def can_sample(self, min_steps: int = 1) -> bool:
        return bool((self._filled_h >= max(1, int(min_steps))).any())

    def can_sample_sequences(self, sequence_length: int) -> bool:
        # host-law parity: EnvIndependent requires len(b) > seq_len somewhere
        return bool((self._filled_h > int(sequence_length)).any())

    # -- jitted primitives ---------------------------------------------------
    def _ops(self):
        if self._scatter is None:
            import jax

            # donate the ring: updates are in-place, no 2x HBM spike; pin the
            # output back onto the replay sharding so a multi-device scatter
            # cannot drift the layout update-over-update
            self._scatter = jax.jit(
                lambda arr, rows, t, e: arr.at[t, e[None, :]].set(rows),
                donate_argnums=0,
                out_shardings=self._sharding,
            )
            self._gather = jax.jit(lambda arr, t, e: arr[t, e])

            def advance(pos, filled, steps, mask):
                new_pos = (pos + steps) % self._capacity
                new_filled = jax.numpy.minimum(filled + steps, self._capacity)
                return (
                    jax.numpy.where(mask, new_pos, pos),
                    jax.numpy.where(mask, new_filled, filled),
                )

            # no donation: the cursor vectors are a few bytes, and pos/filled
            # start life aliased to one zeros buffer (double-donation trap)
            self._advance = jax.jit(advance)
        return self._scatter, self._gather, self._advance

    def _ensure(self, key: str, feat_shape: Tuple[int, ...], dtype: Any) -> None:
        if key in self._buf:
            return
        import jax
        import jax.numpy as jnp

        shape = (self._capacity, self._n_envs) + tuple(feat_shape)
        arr = jnp.zeros(shape, dtype)
        if self._sharding is not None:
            arr = jax.device_put(arr, self._sharding)
        self._buf[key] = arr

    def _put(self, x: np.ndarray) -> Any:
        """Explicit H2D staging (transfer-guard-legal) of host rows/indices."""
        import jax

        if self._sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.device_put(np.asarray(x), NamedSharding(self._mesh, P()))
        return jax.device_put(np.asarray(x))

    # -- write path ----------------------------------------------------------
    def add(self, data: Arrays, indices: Optional[Sequence[int]] = None) -> None:
        """Append ``T`` steps of ``(T, B, *)`` host data for all (or
        ``indices``) envs — the host-buffer ``add`` contract, device-resident."""
        if not isinstance(data, dict) or not data:
            raise ValueError("add() expects a non-empty dict of (T, B, *) arrays")
        first = next(iter(data.values()))
        if np.ndim(first) < 2:
            raise ValueError("Buffer data must be (T, B, *)")
        steps = int(np.shape(first)[0])
        if self.spill is not None:
            # the spill tier shadows FULL capacity: hand it the whole block
            # BEFORE the HBM-window truncation below (its own ring applies
            # its own, larger, truncation law)
            self.spill.submit(data, indices=indices)
        if steps > self._capacity:
            data = {k: np.asarray(v)[-self._capacity:] for k, v in data.items()}
            steps = self._capacity
        env_sel = np.arange(self._n_envs) if indices is None else np.asarray(list(indices))
        if np.shape(first)[1] != len(env_sel):
            raise ValueError(
                f"data has {np.shape(first)[1]} envs, expected {len(env_sel)}"
            )
        for k, v in data.items():
            self._ensure(k, np.shape(v)[2:], np.asarray(v).dtype)
        # ring slots each env is about to write (host math, no device sync)
        t_idx = np.stack(
            [(self._pos_h[e] + np.arange(steps)) % self._capacity for e in env_sel],
            axis=1,
        ).astype(np.int32)  # (T, K)
        # host→ring staging is its own telemetry phase (replay.write): the
        # H2D stage + donated scatter dispatch the rollout pays per append
        from sheeprl_tpu.telemetry.spans import span

        with span("replay.write"):
            scatter, _, advance = self._ops()
            t_dev = self._put(t_idx)
            e_dev = self._put(env_sel.astype(np.int32))
            for k, v in data.items():
                rows = self._put(np.asarray(v)[-steps:])
                self._buf[k] = scatter(self._buf[k], rows, t_dev, e_dev)
            mask = np.zeros(self._n_envs, bool)
            mask[env_sel] = True
            self.cursor["pos"], self.cursor["filled"] = advance(
                self.cursor["pos"],
                self.cursor["filled"],
                self._put(np.int32(steps)),
                self._put(mask),
            )
        self._pos_h[env_sel] = (self._pos_h[env_sel] + steps) % self._capacity
        self._filled_h[env_sel] = np.minimum(self._filled_h[env_sel] + steps, self._capacity)

    def repair_tail(self, env: int = 0) -> None:
        """Mark the last written step of ``env`` as a truncation (stream
        broke: crashed-and-restarted env) — host-buffer contract."""
        if self._filled_h[env] == 0:
            return
        tail = int((self._pos_h[env] - 1) % self._capacity)
        for key, value in (("truncated", 1.0), ("terminated", 0.0), ("is_first", 0.0)):
            if key in self._buf:
                feat = self._buf[key].shape[2:]
                row = np.full((1, 1) + tuple(feat), value, dtype=np.dtype(self._buf[key].dtype))
                self.write_at(key, row, np.asarray([[tail]], np.int32), [env])

    # -- mirror-compatible primitives (the attach_mirror shim rides these) ---
    def write_at(self, key: str, rows: np.ndarray, time_pos: np.ndarray, env_cols: Sequence[int]) -> None:
        """Scatter ``rows (T, K, *)`` at explicit ring slots ``time_pos
        (T, K)`` for env columns ``env_cols (K,)`` — cursors untouched."""
        rows = np.asarray(rows)
        self._ensure(key, rows.shape[2:], rows.dtype)
        scatter, _, _ = self._ops()
        self._buf[key] = scatter(
            self._buf[key],
            self._put(rows),
            self._put(np.asarray(time_pos, np.int32)),
            self._put(np.asarray(env_cols, np.int32)),
        )

    def gather_at(self, key: str, time_idx: np.ndarray, env_idx: np.ndarray) -> Any:
        """Device gather at explicit ring coordinates (mirror contract)."""
        _, gather, _ = self._ops()
        return gather(
            self._buf[key],
            self._put(np.asarray(time_idx, np.int32)),
            self._put(np.asarray(env_idx, np.int32)),
        )

    # -- on-device sampling (jit-traceable over buffers/cursor/key) ----------
    def uniform_indices(self, cursor: Dict[str, Any], key: Any, total: int, sample_next_obs: bool = False):
        """``(step, env)`` index vectors for ``total`` uniform draws — the
        host ``ReplayBuffer._valid_steps`` law, traced: all envs share the
        ring head (they advance in lockstep on the uniform layouts), so env
        0's cursor is THE cursor; when full and successor rows are needed the
        slot before the write head is excluded by basing draws at ``pos``."""
        import jax
        import jax.numpy as jnp

        pos = cursor["pos"][0]
        filled = cursor["filled"][0]
        full = filled >= self._capacity
        trim = 1 if sample_next_obs else 0
        valid = jnp.where(full, self._capacity - trim, jnp.maximum(filled - trim, 0))
        k_step, k_env = jax.random.split(key)
        r = jax.random.randint(k_step, (total,), 0, jnp.maximum(valid, 1))
        step = jnp.where(
            jnp.logical_and(full, sample_next_obs), (pos + r) % self._capacity, r
        )
        env = jax.random.randint(k_env, (total,), 0, self._n_envs)
        return step, env

    def sample_uniform(
        self,
        buffers: Dict[str, Any],
        cursor: Dict[str, Any],
        key: Any,
        batch_size: int,
        n_samples: int = 1,
        keys: Optional[Sequence[str]] = None,
        derive_next: Sequence[str] = (),
        constrain: bool = True,
    ) -> Dict[str, Any]:
        """Uniform ``(n_samples, batch_size, *)`` batches gathered on device.

        ``derive_next`` lists observation keys whose successor row should be
        emitted as ``next_<k>`` (layouts that do not store next rows); when
        empty, draws never exclude the write-head predecessor — exactly the
        host law.  Call INSIDE a jitted train program: the index generation
        and gather compile into the update step."""
        total = int(batch_size) * int(n_samples)
        step, env = self.uniform_indices(cursor, key, total, sample_next_obs=bool(derive_next))
        out: Dict[str, Any] = {}
        for k, buf in buffers.items():
            if keys is not None and k not in keys:
                continue
            out[k] = buf[step, env].reshape(n_samples, batch_size, *buf.shape[2:])
        for k in derive_next:
            if k in buffers:
                nxt = (step + 1) % self._capacity
                out[f"next_{k}"] = buffers[k][nxt, env].reshape(
                    n_samples, batch_size, *buffers[k].shape[2:]
                )
        return self._constrain(out, batch_axis=1) if constrain else out

    def sequence_indices(self, cursor: Dict[str, Any], key: Any, total: int, sequence_length: int):
        """``(t_idx (total, L), env (total,))`` for contiguous sequence draws
        — the ``EnvIndependentReplayBuffer`` law, traced: envs weighted by
        occupancy among those holding >= L steps, start uniform over the
        env's valid range, sequences never crossing that env's write head."""
        import jax
        import jax.numpy as jnp

        L = int(sequence_length)
        pos = cursor["pos"]
        filled = cursor["filled"]
        full = filled >= self._capacity
        max_start = jnp.where(full, self._capacity - L, filled - L)  # per env
        weights = jnp.where(filled >= L, filled, 0).astype(jnp.float32)
        logits = jnp.where(weights > 0, jnp.log(jnp.maximum(weights, 1e-9)), -jnp.inf)
        k_env, k_start = jax.random.split(key)
        env = jax.random.categorical(k_env, logits, shape=(total,))
        valid = jnp.maximum(jnp.take(max_start, env) + 1, 1)
        start = jax.random.randint(k_start, (total,), 0, valid)
        base = jnp.where(jnp.take(full, env), jnp.take(pos, env), 0)
        t_idx = (base[:, None] + start[:, None] + jnp.arange(L)[None, :]) % self._capacity
        return t_idx.astype(jnp.int32), env.astype(jnp.int32)

    def sample_sequences(
        self,
        buffers: Dict[str, Any],
        cursor: Dict[str, Any],
        key: Any,
        batch_size: int,
        sequence_length: int,
        n_samples: int = 1,
        keys: Optional[Sequence[str]] = None,
        constrain: bool = True,
    ) -> Dict[str, Any]:
        """Contiguous ``(n_samples, L, batch_size, *)`` sequence batches
        gathered on device — the Dreamer-family sampling layout."""
        total = int(batch_size) * int(n_samples)
        L = int(sequence_length)
        t_idx, env = self.sequence_indices(cursor, key, total, L)
        out: Dict[str, Any] = {}
        for k, buf in buffers.items():
            if keys is not None and k not in keys:
                continue
            g = buf[t_idx, env[:, None]]  # (total, L, *feat)
            g = g.reshape(n_samples, batch_size, L, *buf.shape[2:])
            out[k] = g.swapaxes(1, 2)  # (n_samples, L, batch, *feat)
        return self._constrain(out, batch_axis=2) if constrain else out

    def _constrain(self, tree: Dict[str, Any], batch_axis: int) -> Dict[str, Any]:
        """Re-lay sampled batches over the mesh ``data`` axis (the
        ``fabric.shard_batch`` layout) so the consuming update step starts
        from the canonical data-parallel placement."""
        if self._mesh is None or int(np.prod(list(self._mesh.shape.values()))) == 1:
            return tree
        n_data = int(self._mesh.shape.get(self._data_axis, 1))
        if n_data <= 1:
            return tree
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def put(x):
            if x.shape[batch_axis] % n_data != 0:
                return x
            spec = [None] * x.ndim
            spec[batch_axis] = self._data_axis
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(self._mesh, P(*spec))
            )

        return {k: put(v) for k, v in tree.items()}

    # -- persistence ---------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        """Host snapshot.  Prefers the spill tier's full-capacity ring when
        armed AND healthy (it holds MORE history than the HBM window — a
        degraded or backlogged-past-timeout spill falls back to the device
        ring, never snapshotting a half-drained shadow); otherwise one D2H
        fetch of the ring with the checkpoint tail-consistency patch applied
        to the host COPY (the callback's ``_consistent_tail`` contract: the
        step at each env's write head must not look continuable on resume —
        only ``truncated``/``dones`` are forced, NEVER ``terminated``, which
        is a value-semantics bootstrap-killing flag)."""
        if self.spill is not None and not self.spill.degraded:
            if self.spill.flush(self._spill_flush_timeout_s):
                state = self.spill.state_dict()
                _patch_spill_tail(state)
                state["device_replay"] = {
                    "pos": np.array(self._pos_h),
                    "filled": np.array(self._filled_h),
                    "from_spill": True,
                }
                return state
            warnings.warn(
                "replay spill tier did not drain in time; checkpointing the "
                "device ring (HBM window) instead of the full spill history",
                RuntimeWarning,
            )
        buf = {k: np.asarray(v) for k, v in self._buf.items()}
        if buf and not any(k.startswith("next_") for k in buf):
            # writable copies for just the patched flag keys (np.asarray of a
            # device array is a read-only view)
            for key in ("truncated", "dones"):
                if key in buf:
                    buf[key] = np.array(buf[key], copy=True)
            for env in range(self._n_envs):
                if self._filled_h[env] == 0:
                    continue
                tail = int((self._pos_h[env] - 1) % self._capacity)
                for key in ("truncated", "dones"):
                    if key in buf:
                        buf[key][tail, env] = 1.0
        return {
            "buffer": buf,
            "pos": np.array(self._pos_h),
            "filled": np.array(self._filled_h),
            "buffer_size": self._capacity,
            "n_envs": self._n_envs,
            "device_replay": {"from_spill": False},
        }

    def load_state_dict(self, state: Dict[str, Any]) -> "DeviceReplay":
        meta = state.get("device_replay") or {}
        if meta.get("from_spill"):
            return self._load_from_spill(state, meta)
        if int(state.get("n_envs", self._n_envs)) != self._n_envs:
            raise ValueError(
                f"Checkpointed replay has {state.get('n_envs')} envs, expected "
                f"{self._n_envs} (resume requires the same world size)"
            )
        if "buffers" in state:
            raise ValueError(
                "this checkpoint was written by the host EnvIndependent buffer "
                "backend; restore it with buffer.device=False or re-collect — "
                "host->device restore is only supported through the spill tier"
            )
        saved_cap = int(state.get("buffer_size", self._capacity))
        buf = state["buffer"]
        pos = np.asarray(state["pos"]).reshape(-1)
        filled = np.asarray(state["filled"]).reshape(-1)
        if pos.size == 1:  # host ReplayBuffer scalar-cursor checkpoints
            pos = np.full(self._n_envs, int(pos[0]))
            filled = np.full(
                self._n_envs, saved_cap if state.get("full") else int(pos[0])
            )
        if saved_cap != self._capacity:
            raise ValueError(
                f"Checkpointed replay window {saved_cap} != {self._capacity}"
            )
        for k, v in buf.items():
            v = np.asarray(v)
            self._ensure(k, v.shape[2:], v.dtype)
            self.write_at(k, v, np.tile(np.arange(saved_cap)[:, None], (1, self._n_envs)), list(range(self._n_envs)))
        self._pos_h = pos.astype(np.int64).copy()
        self._filled_h = np.minimum(filled.astype(np.int64), self._capacity).copy()
        # rebuild the device cursors from the host shadows (explicit puts)
        self.cursor = {
            "pos": self._put(self._pos_h.astype(np.int32)),
            "filled": self._put(self._filled_h.astype(np.int32)),
        }
        return self

    #: how long ``state_dict`` waits for the spill worker before falling back
    #: to a device-ring snapshot
    _spill_flush_timeout_s: float = 60.0

    def _load_from_spill(self, state: Dict[str, Any], meta: Dict[str, Any]) -> "DeviceReplay":
        """Restore a spill-tier checkpoint: reload the full-capacity host
        shadow ring, then rebuild the HBM window from each env's newest rows
        at exactly the saved device cursors — save and resume round-trip
        regardless of which tier wrote the snapshot."""
        if self.spill is None:
            raise ValueError(
                "checkpoint was written from the replay spill tier but this "
                "run has no spill armed — keep the same buffer.size / "
                "buffer.hbm_window / SHEEPRL_REPLAY_BUDGET_BYTES as the saved run"
            )
        spill_state = {k: v for k, v in state.items() if k != "device_replay"}
        self.spill.load_state_dict(spill_state)
        pos = np.asarray(meta["pos"]).reshape(-1).astype(np.int64)
        filled = np.minimum(
            np.asarray(meta["filled"]).reshape(-1).astype(np.int64), self._capacity
        )
        if pos.size != self._n_envs:
            raise ValueError(
                f"spill checkpoint has {pos.size} env cursors, expected {self._n_envs}"
            )
        for env in range(self._n_envs):
            history = self._spill_env_history(env)  # key -> (L_e, *) oldest->newest
            if not history:
                continue
            length = next(iter(history.values())).shape[0]
            n = int(min(filled[env], length))
            if n == 0:
                continue
            slots = ((pos[env] - n + np.arange(n)) % self._capacity).astype(np.int32)
            for k, rows in history.items():
                self.write_at(k, rows[-n:][:, None], slots[:, None], [env])
            filled[env] = n
        self._pos_h = pos.copy()
        self._filled_h = filled.copy()
        self.cursor = {
            "pos": self._put(self._pos_h.astype(np.int32)),
            "filled": self._put(self._filled_h.astype(np.int32)),
        }
        return self

    def _spill_env_history(self, env: int) -> Dict[str, np.ndarray]:
        """One env's stored rows from the spill host ring, oldest -> newest."""
        from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer

        host = self.spill.buffer
        if isinstance(host, EnvIndependentReplayBuffer):
            sub = host.buffer[env]
            length = len(sub)
            if length == 0:
                return {}
            if sub.full:
                idx = (sub._pos + np.arange(sub.buffer_size)) % sub.buffer_size
            else:
                idx = np.arange(length)
            return {k: np.asarray(sub[k])[idx, 0] for k in sub.keys()}
        length = len(host)
        if length == 0:
            return {}
        if host.full:
            idx = (host._pos + np.arange(host.buffer_size)) % host.buffer_size
        else:
            idx = np.arange(length)
        return {k: np.asarray(host[k])[idx, env] for k in host.keys()}


def _patch_spill_tail(state: Dict[str, Any]) -> None:
    """Checkpoint tail-consistency patch for a spill-tier snapshot — the
    ``utils.callback._consistent_tail`` contract applied to the state COPY
    (the callback's isinstance dispatch never matches a ``DeviceReplay``, so
    this module owns the invariant for both snapshot branches): each ring's
    write-head row is forced ``truncated``/``dones`` = 1 so the stored tail
    never looks continuable on resume.  ``terminated`` is untouched (a
    value-semantics flag) and layouts storing ``next_<k>`` rows need no
    patch (every row is self-contained)."""

    def patch_one(sub: Dict[str, Any]) -> None:
        buf = sub.get("buffer") or {}
        if not buf or any(k.startswith("next_") for k in buf):
            return
        filled = int(sub["buffer_size"]) if sub.get("full") else int(sub.get("pos", 0))
        if filled == 0:
            return
        tail = (int(sub["pos"]) - 1) % int(sub["buffer_size"])
        for key in ("truncated", "dones"):
            if key in buf:
                # copy before writing: state_dict arrays can be live views
                # of (or memmap references into) the spill's host ring
                arr = np.array(np.asarray(buf[key]), copy=True)
                arr[tail] = 1.0
                buf[key] = arr

    if "buffers" in state:  # EnvIndependent spill: one sub-state per env
        for sub in state["buffers"]:
            patch_one(sub)
    else:
        patch_one(state)


# --------------------------------------------------------------------------
# fused sample+update programs
# --------------------------------------------------------------------------

def fused_uniform_train(
    fabric: Any,
    train_phase: Callable,
    replay: DeviceReplay,
    batch_size: int,
    prep: Callable[[Dict[str, Any]], Dict[str, Any]],
    name: str,
    derive_next: Sequence[str] = (),
    max_recompiles: Optional[int] = None,
    health: bool = False,
) -> Any:
    """Fold uniform index generation + device gather + ``prep`` + the algo's
    existing ``train_phase(p, o, batches, key, counter)`` into ONE
    ``fabric.compile`` AOT executable: ``fused(p, o, buffers, cursor, key,
    counter, n_samples=U)`` → ``(p, o, counter + U, metrics)``.

    The counter is threaded through the program as device data (not rebuilt
    host-side per window) so a transfer-guarded steady state performs zero
    implicit H2D; ``n_samples`` is static — distinct window lengths compile
    distinct executables exactly as the shipped-batch path did (chunked by
    :func:`update_chunks` for reuse).

    ``health=True``: ``train_phase`` is a health-guarded program
    (``resilience/health.py``) with the sentinel state threaded first —
    the fused signature becomes ``fused(p, o, h, buffers, cursor, key,
    counter, n_samples=U)`` → ``(p, o, h, counter + U, metrics)``, with
    ``h`` donated alongside params/opt-state (device data like the
    counter, so the guarded steady state stays one executable)."""
    import jax

    if health:
        def fused_h(p, o_state, h, buffers, cursor, k, counter, n_samples):
            k_sample, k_train = jax.random.split(k)
            batch = replay.sample_uniform(
                buffers, cursor, k_sample, batch_size, int(n_samples), derive_next=derive_next
            )
            h, p, o_state, metrics = train_phase(h, p, o_state, prep(batch), k_train, counter)
            return p, o_state, h, counter + int(n_samples), metrics

        return fabric.compile(
            fused_h,
            name=name,
            static_argnames=("n_samples",),
            donate_argnums=(0, 1, 2),
            max_recompiles=max_recompiles,
        )

    def fused(p, o_state, buffers, cursor, k, counter, n_samples):
        k_sample, k_train = jax.random.split(k)
        batch = replay.sample_uniform(
            buffers, cursor, k_sample, batch_size, int(n_samples), derive_next=derive_next
        )
        p, o_state, metrics = train_phase(p, o_state, prep(batch), k_train, counter)
        return p, o_state, counter + int(n_samples), metrics

    return fabric.compile(
        fused,
        name=name,
        static_argnames=("n_samples",),
        donate_argnums=(0, 1),
        max_recompiles=max_recompiles,
    )


def fused_sequence_train(
    fabric: Any,
    train_phase: Callable,
    replay: DeviceReplay,
    batch_size: int,
    sequence_length: int,
    prep: Callable[[Dict[str, Any]], Dict[str, Any]],
    name: str,
    max_recompiles: Optional[int] = None,
    health: bool = False,
) -> Any:
    """Sequence-sampling twin of :func:`fused_uniform_train` (the Dreamer
    family): ``fused(p, o, buffers, cursor, key, counter, n_samples=U)``
    samples ``(U, L, B, *)`` blocks on device and runs the scanned update.
    ``health=True`` threads the sentinel state exactly like the uniform
    variant."""
    import jax

    if health:
        def fused_h(p, o_state, h, buffers, cursor, k, counter, n_samples):
            k_sample, k_train = jax.random.split(k)
            blocks = replay.sample_sequences(
                buffers, cursor, k_sample, batch_size, sequence_length, int(n_samples)
            )
            h, p, o_state, metrics = train_phase(h, p, o_state, prep(blocks), k_train, counter)
            return p, o_state, h, counter + int(n_samples), metrics

        return fabric.compile(
            fused_h,
            name=name,
            static_argnames=("n_samples",),
            donate_argnums=(0, 1, 2),
            max_recompiles=max_recompiles,
        )

    def fused(p, o_state, buffers, cursor, k, counter, n_samples):
        k_sample, k_train = jax.random.split(k)
        blocks = replay.sample_sequences(
            buffers, cursor, k_sample, batch_size, sequence_length, int(n_samples)
        )
        p, o_state, metrics = train_phase(p, o_state, prep(blocks), k_train, counter)
        return p, o_state, counter + int(n_samples), metrics

    return fabric.compile(
        fused,
        name=name,
        static_argnames=("n_samples",),
        donate_argnums=(0, 1),
        max_recompiles=max_recompiles,
    )


# --------------------------------------------------------------------------
# on-policy donated staging
# --------------------------------------------------------------------------

def stage_rollout(fabric: Any, tree: Arrays, axis: int, sharded: bool) -> Any:
    """Explicit device staging for on-policy rollout blocks (PPO/A2C family).

    One ``device_put`` per leaf onto the mesh layout — EXPLICIT transfers,
    so a ``steady_guard``-armed train window accepts them — replacing the
    former per-leaf ``jnp.asarray`` (an implicit transfer the guard rejects).
    The staged block is meant to be DONATED into the train phase (its HBM is
    reused for activations) — on-policy loops consume each rollout exactly
    once per dispatch, which is what makes the donation legal."""
    host = {k: np.asarray(v) for k, v in tree.items()}
    if sharded:
        return fabric.shard_batch(host, axis=axis)
    return fabric.replicate(host)


def stage_scalar(value: Any, dtype: Any = np.float32) -> Any:
    """Explicitly staged device scalar (annealed coefficients, counters) —
    ``jnp.float32(x)`` is an implicit transfer under the steady guard."""
    import jax

    return jax.device_put(np.asarray(value, dtype))
