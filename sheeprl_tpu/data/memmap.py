"""Disk-backed arrays for replay persistence.

Same capability as the reference's ``MemmapArray``
(reference: sheeprl/utils/memmap.py:22-270): an ``np.memmap`` container with
explicit file ownership, transparent ndarray behavior, and pickle support
that reopens the map on load — which is what lets replay buffers survive
checkpoint/restart by living under ``log_dir/memmap_buffer/``.

On TPU this stays host-side: buffers are memmapped host RAM/disk; sampled
batches are staged to device HBM explicitly by the buffer's ``sample``
consumers.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Optional, Tuple

import numpy as np


class MemmapArray(np.lib.mixins.NDArrayOperatorsMixin):
    def __init__(
        self,
        shape: Tuple[int, ...],
        dtype: Any = np.float32,
        filename: Optional[os.PathLike] = None,
        mode: str = "r+",
    ):
        self._shape = tuple(int(s) for s in shape)
        self._dtype = np.dtype(dtype)
        self._anonymous = filename is None
        if filename is None:
            import tempfile

            fd, filename = tempfile.mkstemp(suffix=".memmap")
            os.close(fd)
            self._owner = True
        else:
            filename = os.fspath(filename)
            self._owner = not os.path.exists(filename)
            Path(filename).parent.mkdir(parents=True, exist_ok=True)
        self._filename = str(filename)
        exists = os.path.exists(self._filename) and os.path.getsize(self._filename) > 0
        create_mode = "r+" if exists and mode != "w+" else "w+"
        self._array: Optional[np.memmap] = np.memmap(
            self._filename, dtype=self._dtype, mode=create_mode, shape=self._shape
        )

    # -- construction -----------------------------------------------------
    @classmethod
    def from_array(
        cls, array: np.ndarray, filename: Optional[os.PathLike] = None
    ) -> "MemmapArray":
        out = cls(array.shape, array.dtype, filename=filename, mode="w+")
        out._array[:] = array
        out.flush()
        return out

    # -- ndarray protocol -------------------------------------------------
    @property
    def array(self) -> np.memmap:
        if self._array is None:
            raise RuntimeError("MemmapArray is closed")
        return self._array

    @property
    def filename(self) -> str:
        return self._filename

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    @property
    def dtype(self) -> np.dtype:
        return self._dtype

    @property
    def ndim(self) -> int:
        return len(self._shape)

    def __len__(self) -> int:
        return self._shape[0]

    def __getitem__(self, idx: Any) -> np.ndarray:
        return self.array[idx]

    def __setitem__(self, idx: Any, value: Any) -> None:
        self.array[idx] = value

    def __array__(self, dtype: Any = None, copy: Optional[bool] = None) -> np.ndarray:
        arr = np.asarray(self.array)
        return arr.astype(dtype) if dtype is not None else arr

    def __array_ufunc__(self, ufunc: Any, method: str, *inputs: Any, **kwargs: Any) -> Any:
        unwrapped = [np.asarray(i.array) if isinstance(i, MemmapArray) else i for i in inputs]
        return getattr(ufunc, method)(*unwrapped, **kwargs)

    def __repr__(self) -> str:
        return f"MemmapArray(shape={self._shape}, dtype={self._dtype}, file={self._filename})"

    # -- lifecycle --------------------------------------------------------
    def flush(self) -> None:
        if self._array is not None:
            self._array.flush()

    def close(self, delete_file: Optional[bool] = None) -> None:
        if self._array is not None:
            self._array.flush()
            del self._array
            self._array = None
        if delete_file is None:
            delete_file = self._owner
        if delete_file and os.path.exists(self._filename):
            try:
                os.unlink(self._filename)
            except OSError:
                pass

    def __del__(self) -> None:
        # anonymous temp files are cleaned up on GC; named files persist so
        # buffers can be reopened after a restart (the point of memmapping)
        try:
            self.close(delete_file=self._owner and self._anonymous)
        except Exception:
            pass

    # -- pickling (reopen map on load; reference memmap.py:251-258) -------
    def __getstate__(self) -> dict:
        self.flush()
        return {
            "_shape": self._shape,
            "_dtype": self._dtype,
            "_filename": self._filename,
            "_owner": False,
            "_anonymous": False,
        }

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)
        if not os.path.exists(self._filename):
            # The pickle stream only carries a REFERENCE to the backing file;
            # when a checkpoint moves hosts without its memmap_buffer dir the
            # data is genuinely gone.  Rehydrate an owned, anonymous,
            # zero-filled backing of the right geometry and say so clearly,
            # instead of letting np.memmap raise a bare FileNotFoundError
            # from deep inside unpickling (the caller would have no idea
            # which buffer, file, or checkpoint key was at fault).
            import tempfile
            import warnings

            missing = self._filename
            fd, fresh = tempfile.mkstemp(suffix=".memmap")
            os.close(fd)
            warnings.warn(
                f"MemmapArray backing file '{missing}' is missing (checkpoint "
                "restored on a different host without its memmap_buffer "
                "directory?): rehydrating shape "
                f"{self._shape} {self._dtype} ZERO-FILLED in '{fresh}' — "
                "buffer contents from before the move are lost",
                RuntimeWarning,
                stacklevel=2,
            )
            self._filename = fresh
            self._owner = True
            self._anonymous = True
            self._array = np.memmap(fresh, dtype=self._dtype, mode="w+", shape=self._shape)
            return
        self._array = np.memmap(self._filename, dtype=self._dtype, mode="r+", shape=self._shape)
