"""sheeprl_tpu.config."""
