"""A small, dependency-free YAML config composition engine.

The reference uses Hydra 1.3 (reference: sheeprl/cli.py:358-366 and
sheeprl/configs/config.yaml:4-16) to compose a root config from defaults
groups (``algo/``, ``env/``, ``fabric/``, ...), apply ``exp=`` global
overlays, CLI dot-overrides, and ``${...}`` interpolations.  Hydra is not a
dependency of this framework; this module reimplements the subset of that
behavior the framework needs, with the same user-facing syntax:

    sheeprl-tpu exp=dreamer_v3 env.id=CartPole-v1 algo.learning_starts=128

Supported semantics
-------------------
* Root ``configs/config.yaml`` has a ``defaults:`` list of ``{group: name}``
  entries (plus ``_self_``); each loads ``configs/<group>/<name>.yaml`` under
  the ``group`` key.
* A group file may itself have a ``defaults:`` list whose first entry is the
  group-local base (e.g. ``dreamer_v3_S.yaml`` starts from ``dreamer_v3``).
* ``exp=<name>`` files are global overlays (Hydra's ``# @package _global_``):
  merged at the root, and their ``defaults:`` entries of the form
  ``{override /group: name}`` or ``{/group: name}`` re-select root groups.
* CLI ``a.b.c=value`` dot-overrides are applied last; values parse as YAML.
  ``group=name`` (for a known top-level group) re-selects the group file.
* ``${a.b.c}`` interpolations resolve against the final tree (recursively,
  with cycle detection).  Extra resolvers: ``${eval:<python-expr>}`` over
  pure arithmetic, and ``${env:VAR,default}``.
* Extension point: the ``SHEEPRL_SEARCH_PATH`` environment variable is a
  ``;``-separated list of extra config directories searched *before* the
  built-in ones (reference: hydra_plugins/sheeprl_search_path.py:11-33).
"""

from __future__ import annotations

import ast
import os
import re
from pathlib import Path
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import yaml

from sheeprl_tpu.utils.structured import deep_merge, dotdict, get_by_path, set_by_path

BUILTIN_CONFIG_DIR = Path(__file__).resolve().parent.parent / "configs"

_INTERP_RE = re.compile(r"\$\{([^${}]+)\}")


class ConfigError(Exception):
    pass


def _search_dirs(extra_dirs: Optional[Sequence[os.PathLike]] = None) -> List[Path]:
    dirs: List[Path] = []
    env_path = os.environ.get("SHEEPRL_SEARCH_PATH", "")
    for entry in env_path.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if entry.startswith("file://"):
            entry = entry[len("file://"):]
        dirs.append(Path(entry))
    for d in extra_dirs or []:
        dirs.append(Path(d))
    dirs.append(BUILTIN_CONFIG_DIR)
    return dirs


def _find_config_file(rel: str, dirs: Sequence[Path]) -> Optional[Path]:
    for d in dirs:
        p = d / f"{rel}.yaml"
        if p.is_file():
            return p
        p = d / f"{rel}.yml"
        if p.is_file():
            return p
    return None


class _ConfigLoader(yaml.SafeLoader):
    """SafeLoader + YAML-1.2-style float resolution: PyYAML's 1.1 grammar
    parses ``1e-3`` (no dot before the exponent) as a STRING, while Hydra/
    OmegaConf — whose config surface this engine mirrors — parse it as a
    float.  Config files full of ``lr: 1e-3`` must load as numbers."""


_ConfigLoader.add_implicit_resolver(
    "tag:yaml.org,2002:float",
    re.compile(
        r"""^(?:[-+]?(?:[0-9][0-9_]*)\.[0-9_]*(?:[eE][-+]?[0-9]+)?
        |[-+]?(?:[0-9][0-9_]*)(?:[eE][-+]?[0-9]+)
        |[-+]?\.[0-9_]+(?:[eE][-+]?[0-9]+)?
        |[-+]?\.(?:inf|Inf|INF)
        |\.(?:nan|NaN|NAN))$""",
        re.X,
    ),
    list("-+0123456789."),
)


def _load_yaml(path: Path) -> Dict[str, Any]:
    with open(path, "r") as f:
        data = yaml.load(f, Loader=_ConfigLoader)
    if data is None:
        return {}
    if not isinstance(data, dict):
        raise ConfigError(f"Config file {path} must contain a mapping, got {type(data)}")
    return data


def known_groups(dirs: Sequence[Path]) -> List[str]:
    groups: List[str] = []
    for d in dirs:
        if not d.is_dir():
            continue
        for sub in d.iterdir():
            if sub.is_dir() and sub.name not in groups:
                groups.append(sub.name)
    return groups


def _parse_value(raw: str) -> Any:
    try:
        return yaml.load(raw, Loader=_ConfigLoader)
    except yaml.YAMLError:
        return raw


def _load_group(group: str, name: Any, dirs: Sequence[Path], _depth: int = 0) -> Dict[str, Any]:
    """Load ``<group>/<name>.yaml`` honoring a group-local defaults chain."""
    if _depth > 16:
        raise ConfigError(f"defaults chain too deep for {group}/{name}")
    if name is None:
        return {}
    path = _find_config_file(f"{group}/{name}", dirs)
    if path is None:
        raise ConfigError(
            f"Cannot find config '{group}/{name}' in: {[str(d) for d in dirs]}"
        )
    data = _load_yaml(path)
    defaults = data.pop("defaults", None)
    base: Dict[str, Any] = {}
    if defaults:
        for entry in defaults:
            if entry == "_self_":
                continue
            if isinstance(entry, str):
                base = deep_merge(base, _load_group(group, entry, dirs, _depth + 1))
            elif isinstance(entry, Mapping):
                for k, v in entry.items():
                    k = str(k)
                    if k.startswith("override "):
                        k = k[len("override "):]
                    if "@" in k:
                        # "/logger@logger: tensorboard": load group "logger"
                        # and place it at the given key inside this package.
                        src, _, at = k.partition("@")
                        loaded = _load_group(src.lstrip("/"), v, dirs, _depth + 1)
                        loaded.pop("__root__", None)
                        sub_tree: Dict[str, Any] = {}
                        set_by_path(sub_tree, at, loaded)
                        base = deep_merge(base, sub_tree)
                    elif k.startswith("/"):
                        # cross-group default inside a group file: return it
                        # namespaced so the composer can merge it at root.
                        base.setdefault("__root__", {})
                        base["__root__"][k[1:]] = v
                    else:
                        base = deep_merge(base, _load_group(k, v, dirs, _depth + 1))
    return deep_merge(base, data)


def compose(
    overrides: Sequence[str] = (),
    config_name: str = "config",
    extra_dirs: Optional[Sequence[os.PathLike]] = None,
    resolve: bool = True,
) -> dotdict:
    """Compose the full config tree from the root config + CLI overrides."""
    dirs = _search_dirs(extra_dirs)
    root_path = _find_config_file(config_name, dirs)
    if root_path is None:
        raise ConfigError(f"Root config '{config_name}' not found in {[str(d) for d in dirs]}")
    root = _load_yaml(root_path)
    defaults = root.pop("defaults", [])

    groups = set(known_groups(dirs))
    for entry in defaults:
        if isinstance(entry, Mapping):
            for g in entry:
                g = str(g)
                for prefix in ("optional ", "override "):
                    if g.startswith(prefix):
                        g = g[len(prefix):]
                groups.add(g)
    group_selection, placed_groups, dot_overrides = _classify_overrides(overrides, groups)

    cfg: Dict[str, Any] = {}
    exp_names: List[Any] = []
    seen_groups: List[str] = []
    cli_groups = frozenset(group_selection)
    for entry in defaults:
        if entry == "_self_":
            cfg = deep_merge(cfg, root)
            continue
        if not isinstance(entry, Mapping):
            raise ConfigError(f"Unsupported defaults entry: {entry!r}")
        for group, name in entry.items():
            group = str(group)
            optional = False
            if group.startswith("optional "):
                optional = True
                group = group[len("optional "):]
            if group in group_selection:
                name = group_selection.pop(group)
            if group == "exp":
                if name is not None:
                    exp_names.append(name)
                seen_groups.append("exp")
                continue
            seen_groups.append(group)
            if name is None:
                continue
            try:
                _merge_group_into(cfg, group, name, dirs)
            except ConfigError:
                if optional:
                    continue
                raise

    # group selections not present in root defaults (e.g. exp=..., logger=...)
    for group, name in list(group_selection.items()):
        if group == "exp":
            exp_names.append(name)
        else:
            _merge_group_into(cfg, group, name, dirs)
        group_selection.pop(group)

    # exp overlays merge at the root (Hydra "@package _global_" semantics)
    for name in exp_names:
        overlay = _load_yaml_exp(name, dirs, cfg, cli_groups)
        cfg = deep_merge(cfg, overlay)

    _apply_placed_groups(cfg, placed_groups, dirs)

    for key, value in dot_overrides:
        set_by_path(cfg, key, value)

    out = dotdict(cfg)
    if resolve:
        resolve_interpolations(out)
    return out


def _apply_placed_groups(
    tree: Dict[str, Any], placed_groups: List[Tuple[str, str, Any]], dirs: Sequence[Path]
) -> None:
    """Place group files at their dotted destinations (shared by compose and
    apply_cli_overrides so eval-time replay cannot diverge from training)."""
    for path, grp, name in placed_groups:
        loaded = _load_group(grp, name, dirs)
        loaded.pop("__root__", None)
        set_by_path(tree, path, loaded)


def _classify_overrides(
    overrides: Sequence[str], groups: set
) -> Tuple[Dict[str, Any], List[Tuple[str, str, Any]], List[Tuple[str, Any]]]:
    """Split CLI overrides into (group selections, nested placed groups, dot
    overrides) — the single source of truth for override syntax, shared by
    :func:`compose` and :func:`apply_cli_overrides`.

    ``parent/group=name`` (e.g. ``metric/logger=mlflow``) swaps the group
    instance PLACED at a nested path (the defaults-list "@" packaging, e.g.
    metric/default.yaml's ``/logger@logger: tensorboard``) — hydra's
    ``logger@metric.logger=mlflow`` equivalent."""
    group_selection: Dict[str, Any] = {}
    placed_groups: List[Tuple[str, str, Any]] = []  # (target path, group, name)
    dot_overrides: List[Tuple[str, Any]] = []
    for ov in overrides:
        if "=" not in ov:
            raise ConfigError(f"Override '{ov}' must look like key=value")
        key, _, raw = ov.partition("=")
        key = key.strip().lstrip("+")
        value = _parse_value(raw.strip())
        if "." not in key and key in groups:
            group_selection[key] = value
        elif "@" in key and key.partition("@")[0] in groups and key.partition("@")[2]:
            # hydra's full placement grammar, "optim@algo.world_model.optimizer=sgd":
            # place group file optim/sgd.yaml AT the dotted destination path
            grp, _, dest = key.partition("@")
            placed_groups.append((dest, grp, value))
        elif "/" in key and key.rpartition("/")[2] in groups:
            parent, _, grp = key.rpartition("/")
            placed_groups.append((f"{parent.replace('/', '.')}.{grp}", grp, value))
        else:
            dot_overrides.append((key, value))
    return group_selection, placed_groups, dot_overrides


def apply_cli_overrides(cfg: dotdict, overrides: Sequence[str]) -> None:
    """Apply CLI-style overrides to an ALREADY-composed config tree with
    compose's classification AND ordering: group re-selections first (each
    REPLACES the old group instance, like a defaults-list re-select), then
    nested placed groups, then ``a.b.c=value`` dot overrides last, then an
    interpolation-resolution pass over the tree (freshly loaded group files
    may carry ``${...}`` references; the rest of the tree is already
    resolved, so the pass is a no-op elsewhere).

    Used by the eval/registration dispatchers, which start from a saved run
    config instead of the defaults tree (reference: sheeprl/cli.py:369-405
    re-runs Hydra; here the saved config IS the tree, so only the override
    step is replayed).  ``exp=`` overlays are rejected: an experiment picks
    algorithms/environments, which cannot be swapped under a checkpoint."""
    import copy

    dirs = _search_dirs()
    groups = set(known_groups(dirs))
    group_selection, placed_groups, dot_overrides = _classify_overrides(overrides, groups)
    if "exp" in group_selection:
        raise ConfigError(
            "exp=... cannot be applied on top of a saved run config; "
            "override individual keys or groups instead"
        )
    for key, value in dot_overrides:
        if "." not in key and isinstance(cfg.get(key), Mapping) and not isinstance(value, Mapping):
            # compose() would have resolved this as a group selection (the
            # group dir existed at train time, e.g. via SHEEPRL_SEARCH_PATH);
            # silently replacing a whole section with a scalar corrupts the
            # tree far from the error site — fail loudly instead.
            raise ConfigError(
                f"'{key}={value}' would replace the whole '{key}' config section "
                f"with a scalar; '{key}' is not a known config group in "
                f"{[str(d) for d in dirs]}"
            )
    # stage on a copy so a failing group load / interpolation leaves the
    # caller's tree untouched — a caller catching ConfigError must not be
    # left with a half-modified config
    staged = copy.deepcopy(dict(cfg))
    for group, name in group_selection.items():
        staged.pop(group, None)
        _merge_group_into(staged, group, name, dirs)
    _apply_placed_groups(staged, placed_groups, dirs)
    for key, value in dot_overrides:
        set_by_path(staged, key, value)
    staged = resolve_interpolations(dotdict(staged))
    cfg.clear()
    cfg.update(staged)


def _load_yaml_exp(
    name: Any,
    dirs: Sequence[Path],
    cfg: Dict[str, Any],
    cli_groups: frozenset = frozenset(),
) -> Dict[str, Any]:
    path = _find_config_file(f"exp/{name}", dirs)
    if path is None:
        raise ConfigError(f"Cannot find experiment config 'exp/{name}'")
    data = _load_yaml(path)
    defaults = data.pop("defaults", None)
    if defaults:
        for entry in defaults:
            if entry == "_self_":
                continue
            if isinstance(entry, str):
                # inherited base exp: the child's own values win
                data = deep_merge(_load_yaml_exp(entry, dirs, cfg, cli_groups), data)
                continue
            for k, v in entry.items():
                k = str(k)
                if k.startswith("override "):
                    k = k[len("override "):]
                k = k.lstrip("/")
                if k == "exp":
                    base = _load_yaml_exp(v, dirs, cfg, cli_groups)
                    data = deep_merge(base, data)
                elif k in cli_groups:
                    # a CLI group selection always beats the exp's override
                    continue
                else:
                    # Hydra semantics: re-SELECT the group (replace, not merge
                    # over the previously loaded default group file)
                    cfg.pop(k, None)
                    _merge_group_into(cfg, k, v, dirs)
    return data


def _merge_group_into(cfg: Dict[str, Any], group: str, name: Any, dirs: Sequence[Path]) -> None:
    """Load ``group/name`` and merge it (plus any cross-group defaults it
    declares via ``/other_group: name`` entries) into ``cfg``."""
    if name is None:
        return
    sub = _load_group(group, name, dirs)
    root_extra = sub.pop("__root__", None)
    deep_merge(cfg, {group: sub})
    if root_extra:
        for g2, n2 in root_extra.items():
            _merge_group_into(cfg, g2, n2, dirs)


# --------------------------------------------------------------------------
# interpolation
# --------------------------------------------------------------------------

def _safe_eval(expr: str) -> Any:
    """Evaluate a pure-arithmetic expression (for ``${eval:...}``)."""
    node = ast.parse(expr, mode="eval")
    allowed = (
        ast.Expression, ast.BinOp, ast.UnaryOp, ast.Constant, ast.Add, ast.Sub,
        ast.Mult, ast.Div, ast.FloorDiv, ast.Mod, ast.Pow, ast.USub, ast.UAdd,
        ast.Compare, ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
        ast.IfExp, ast.BoolOp, ast.And, ast.Or, ast.Not, ast.Tuple, ast.List,
        ast.Load,
    )
    for sub in ast.walk(node):
        if not isinstance(sub, allowed):
            raise ConfigError(f"Disallowed expression in eval interpolation: {expr!r}")
    return eval(compile(node, "<eval-interp>", "eval"), {"__builtins__": {}}, {})


def _resolve_value(value: Any, tree: Mapping[str, Any], stack: Tuple[str, ...]) -> Any:
    if isinstance(value, str):
        full = _INTERP_RE.fullmatch(value)
        if full:
            return _resolve_ref(full.group(1), tree, stack)

        def sub(m: "re.Match[str]") -> str:
            return str(_resolve_ref(m.group(1), tree, stack))

        prev = None
        while prev != value and _INTERP_RE.search(value):
            prev = value
            value = _INTERP_RE.sub(sub, value)
        return value
    return value


def _resolve_ref(ref: str, tree: Mapping[str, Any], stack: Tuple[str, ...]) -> Any:
    ref = ref.strip()
    if ref.startswith("now:"):
        import datetime

        return datetime.datetime.now().strftime(ref[len("now:"):])
    if ref.startswith("eval:"):
        inner = _resolve_value(ref[len("eval:"):], tree, stack)
        return _safe_eval(str(inner))
    if ref.startswith("oc.env:"):
        # hydra/omegaconf-compatible alias — and omegaconf-compatible
        # STRICTNESS: a missing variable with no default raises instead of
        # silently resolving to None (``${env:...}`` stays lenient)
        body = ref[len("oc.env:"):]
        if "," not in body and body.strip() not in os.environ:
            raise ConfigError(
                f"Environment variable '{body.strip()}' (from ${{oc.env:...}}) is not set"
            )
        ref = "env:" + body
    if ref.startswith("env:"):
        body = ref[len("env:"):]
        var, _, default = body.partition(",")
        return os.environ.get(var.strip(), _parse_value(default.strip()) if default else None)
    if ref in stack:
        raise ConfigError(f"Interpolation cycle at ${{{ref}}} (stack: {stack})")
    try:
        target = get_by_path(tree, ref)
    except KeyError:
        raise ConfigError(f"Interpolation ${{{ref}}} not found") from None
    return _resolve_value(target, tree, stack + (ref,))


def resolve_interpolations(tree: dotdict) -> dotdict:
    """Resolve ``${...}`` references in-place over the whole tree."""

    def walk(node: Any, prefix: str) -> Any:
        if isinstance(node, dict):
            for k in list(node.keys()):
                node[k] = walk(node[k], f"{prefix}{k}.")
            return node
        if isinstance(node, list):
            return [walk(v, prefix) for v in node]
        return _resolve_value(node, tree, ())

    walk(tree, "")
    return tree
