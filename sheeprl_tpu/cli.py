"""CLI dispatchers.

Parity with the reference CLI layer (reference: sheeprl/cli.py:23-450):
``run`` (training), ``evaluation`` (from checkpoint), ``registration``
(model export) and ``available_agents`` — minus Hydra: composition is done
by :mod:`sheeprl_tpu.config.compose` with the same user-facing syntax.

Usage:
    python -m sheeprl_tpu exp=ppo env.id=CartPole-v1 fabric.devices=8
    python -m sheeprl_tpu --eval checkpoint_path=... [overrides...]
"""

from __future__ import annotations

import os
import pathlib
import sys
import warnings
from typing import List, Optional

from sheeprl_tpu.config.compose import ConfigError, compose
from sheeprl_tpu.utils.registry import (
    algorithm_registry,
    evaluation_registry,
    resolve_algorithm,
    resolve_entrypoint,
)
from sheeprl_tpu.utils.structured import deep_merge, dotdict


def import_extra_modules(cfg: dotdict) -> None:
    """Import user packages listed in ``algo.extra_modules`` so their
    ``@register_algorithm`` / ``@register_evaluation`` decorators run —
    the external-algorithm extension point (reference behavior:
    sheeprl/cli.py registration-at-import + howto/register_external_algorithm.md)."""
    import importlib

    for mod in cfg.get("algo", {}).get("extra_modules", []) or []:
        importlib.import_module(mod)


def check_configs(cfg: dotdict) -> None:
    """Config sanity validation (reference: sheeprl/cli.py:271-345)."""
    if "algo" not in cfg or cfg.algo.get("name") in (None, "???"):
        raise ConfigError(
            "No algorithm specified: pass exp=<experiment> or algo=<name> "
            f"(registered: {', '.join(sorted(algorithm_registry))})"
        )
    if algorithm_registry and cfg.algo.name not in algorithm_registry:
        raise ConfigError(
            f"Unknown algorithm '{cfg.algo.name}'. "
            f"Registered: {', '.join(sorted(algorithm_registry))}"
        )
    if "env" not in cfg or cfg.env.get("id") in (None, "???"):
        raise ConfigError("No environment specified: set env=<group> / env.id=<id>")
    for field in ("total_steps", "per_rank_batch_size"):
        if cfg.algo.get(field) in (None, "???"):
            raise ConfigError(f"algo.{field} must be set")
    strategy = cfg.fabric.get("strategy", "auto")
    if strategy not in ("auto", "dp"):
        warnings.warn(
            f"fabric.strategy='{strategy}' is not recognized; the runtime is a "
            "single-controller SPMD mesh ('auto'/'dp' are equivalent)",
            UserWarning,
        )


def resume_from_checkpoint(cfg: dotdict) -> dotdict:
    """Merge the previous run's saved config under the new one, keeping the
    user's total_steps / learning_starts overrides
    (reference: sheeprl/cli.py:23-57)."""
    import yaml

    ckpt_path = pathlib.Path(cfg.checkpoint.resume_from)
    old_cfg_path = ckpt_path.parent.parent / "config.yaml"
    if not old_cfg_path.is_file():
        return cfg
    with open(old_cfg_path) as f:
        old = yaml.safe_load(f)
    keep = {
        "total_steps": cfg.algo.get("total_steps"),
        "learning_starts": cfg.algo.get("learning_starts"),
    }
    merged = deep_merge(old, cfg.as_dict())
    out = dotdict(merged)
    for k, v in keep.items():
        if v is not None:
            out.algo[k] = v
    out.checkpoint.resume_from = str(ckpt_path)
    return out


def run_algorithm(cfg: dotdict) -> None:
    """Resolve, build the runtime, dispatch (reference: sheeprl/cli.py:60-199)."""
    import jax

    import sheeprl_tpu
    from sheeprl_tpu.parallel.fabric import build_fabric

    sheeprl_tpu.register_all_algorithms()
    import_extra_modules(cfg)
    entry = resolve_algorithm(cfg.algo.name, decoupled=cfg.fabric.get("decoupled"))
    entrypoint = resolve_entrypoint(entry)

    if cfg.get("matmul_precision"):
        jax.config.update("jax_default_matmul_precision", cfg.matmul_precision)
    fabric = build_fabric(cfg)
    entrypoint(fabric, cfg)
    _maybe_register_models(fabric, cfg)


def _maybe_register_models(fabric, cfg: dotdict) -> None:
    """End-of-training model export (reference: sheeprl/algos/*/…
    `register_model` hook at the end of every `main`, e.g. ppo.py:448-453):
    when ``model_manager.disabled`` is False, the final checkpoint's
    sub-models are registered with the configured names/descriptions."""
    mm = cfg.get("model_manager") or {}
    if mm.get("disabled", True) or (fabric is not None and not fabric.is_global_zero):
        return
    import glob

    from sheeprl_tpu.utils.checkpoint import load_checkpoint
    from sheeprl_tpu.utils.model_manager import register_model_from_checkpoint

    root = os.path.join(cfg.get("log_dir", "logs/runs"), str(cfg.get("root_dir")), str(cfg.get("run_name")))
    versions = sorted(
        glob.glob(os.path.join(root, "version_*")),
        key=lambda p: int(p.rsplit("_", 1)[-1]),
    )
    if not versions:
        return
    # ONLY the newest version dir — the one this run just wrote.  Falling
    # back to older runs would silently register stale weights when this
    # run saved no checkpoint (checkpoint.every=0, save_last=False).
    from sheeprl_tpu.checkpoint import latest_checkpoint

    newest = latest_checkpoint(os.path.join(versions[-1], "checkpoint"))
    if newest is None:
        # legacy flat-file layout (fabric.save / old runs)
        ckpts = sorted(
            glob.glob(os.path.join(versions[-1], "checkpoint", "*.ckpt")), key=os.path.getmtime
        )
        newest = ckpts[-1] if ckpts else None
    if newest is None:
        warnings.warn(
            "model_manager.disabled=False but the run saved no checkpoint; "
            "nothing registered", UserWarning
        )
        return
    state = load_checkpoint(newest)
    out = register_model_from_checkpoint(fabric, cfg, state)
    if out:
        print(f"Registered models from {newest}: {out}")


def resolve_resume_target(cfg: dotdict) -> dotdict:
    """Resolve ``checkpoint.resume_from=auto`` to the newest COMMITTED
    snapshot across every run/version under this experiment's root
    (``<log_dir>/<root_dir>``).  Torn snapshots (no COMMIT marker) are never
    eligible.  No committed snapshot → start fresh, with a warning."""
    if cfg.checkpoint.get("resume_from") != "auto":
        return cfg
    from sheeprl_tpu.checkpoint import resolve_auto_resume
    from sheeprl_tpu.checkpoint.protocol import verify_or_quarantine

    # a committed snapshot can still be damaged (bit rot, a torn write that
    # raced the manifest): verify the CRCs before trusting it, quarantine
    # (step_* → step_*.corrupt) on mismatch, and fall back to the next
    # newest committed snapshot instead of crashing the resume
    verify = bool(cfg.checkpoint.get("verify_on_resume", True))
    # quarantine can fail (read-only mount): a damaged snapshot that cannot
    # be renamed is EXCLUDED from re-resolution instead of re-tried forever,
    # so older intact commits are still found
    damaged: set = set()
    target = resolve_auto_resume(cfg.get("log_dir", "logs/runs"), cfg.root_dir)
    while target is not None and verify:
        problems = verify_or_quarantine(target)
        if not problems:
            break
        warnings.warn(
            f"checkpoint.resume_from=auto: {target} is damaged "
            f"({'; '.join(problems)}); trying the next committed snapshot",
            RuntimeWarning,
        )
        damaged.add(target)
        target = resolve_auto_resume(
            cfg.get("log_dir", "logs/runs"), cfg.root_dir, exclude=damaged
        )
    if target is None:
        warnings.warn(
            f"checkpoint.resume_from=auto: no committed checkpoint found under "
            f"{os.path.join(str(cfg.get('log_dir', 'logs/runs')), str(cfg.root_dir))}; "
            "starting fresh",
            UserWarning,
        )
        cfg.checkpoint.resume_from = None
    else:
        print(f"checkpoint.resume_from=auto -> {target}")
        cfg.checkpoint.resume_from = str(target)
    return cfg


def run(argv: Optional[List[str]] = None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    # a preemption latched during a PREVIOUS run in this interpreter was
    # honored by that run's final save; this run starts un-preempted
    from sheeprl_tpu.checkpoint import PREEMPTION_GUARD

    PREEMPTION_GUARD.clear_latch()
    # same for the telemetry hub and flight recorder: a logger/step left
    # over from a previous run in this interpreter must not receive THIS
    # run's final flush, and a postmortem written by this run must hold
    # this run's events — not a previous drill's fault trail
    from sheeprl_tpu import telemetry

    telemetry.HUB.reset()
    # a crashed loop never reached its sentinel teardown: drop the stale
    # run-scoped Health/* and Population/* sources so they cannot leak
    # into this run's flushes
    telemetry.HUB.unregister("health")
    telemetry.HUB.unregister("population")
    telemetry.RECORDER.clear()
    cfg = compose(argv)
    # arm (or explicitly clear) the fault-injection plan before anything
    # else touches envs/checkpoints — SHEEPRL_FAULT_PLAN wins over the group
    from sheeprl_tpu.resilience import install_from_config

    install_from_config(cfg)
    cfg = resolve_resume_target(cfg)
    if cfg.checkpoint.get("resume_from"):
        cfg = resume_from_checkpoint(cfg)
    import sheeprl_tpu

    sheeprl_tpu.register_all_algorithms()
    import_extra_modules(cfg)
    check_configs(cfg)
    from sheeprl_tpu.utils.utils import print_config

    if cfg.get("print_config", True):
        print_config(cfg)
    try:
        run_algorithm(cfg)
    except BaseException as e:
        # every abnormal exit leaves evidence: the flight recorder dumps
        # its ring (injected faults, stalls, restarts, span edges, the
        # crash itself) as postmortem.json under the run dir
        telemetry.RECORDER.record("crash", error=f"{type(e).__name__}: {e}")
        telemetry.RECORDER.dump("exception")
        raise
    finally:
        if PREEMPTION_GUARD.requested():
            telemetry.RECORDER.record(
                "preemption", signal=PREEMPTION_GUARD.signal_name
            )
            telemetry.RECORDER.dump("preemption")
        # metrics buffered in the monitors since the last log interval
        # would otherwise be lost on any non-interval exit (exception,
        # preemption latch, dry-run) — land the final window through the
        # attached logger, then stop trace windows / the introspection
        # server.  Best-effort: telemetry never masks the real exception.
        telemetry.HUB.final_flush()
        telemetry.shutdown_run()


def evaluation(argv: Optional[List[str]] = None) -> None:
    """Evaluate a checkpoint (reference: sheeprl/cli.py:202-268, 369-405).

    Checkpoint discovery and snapshot→policy reconstruction go through
    ``sheeprl_tpu.serve.loader`` — the SAME path the policy server uses, so
    evaluation and serving can never disagree on how a snapshot is rebuilt.
    Algorithms with a registered serving player (ppo/sac/dreamer_v3
    families) evaluate through the serving player itself; the rest fall
    back to their ``@register_evaluation`` entrypoint, still fed by the
    loader's discovery + config resolution.
    """
    argv = list(sys.argv[1:] if argv is None else argv)
    ckpt_override = [a for a in argv if a.startswith("checkpoint_path=")]
    if not ckpt_override:
        raise ConfigError("evaluation requires checkpoint_path=<path-to-ckpt>")
    rest = [a for a in argv if not a.startswith("checkpoint_path=")]

    from sheeprl_tpu.serve.loader import load_policy, load_run_config, resolve_checkpoint
    from sheeprl_tpu.serve.players import PLAYER_BUILDERS

    ckpt_path = resolve_checkpoint(ckpt_override[0].split("=", 1)[1])
    cfg = load_run_config(ckpt_path, rest)
    if cfg.algo.name in PLAYER_BUILDERS:
        from sheeprl_tpu.serve.loader import evaluate_player
        from sheeprl_tpu.utils.logger import get_log_dir, get_logger

        fabric, cfg, _, player = load_policy(ckpt_path, rest, cfg=cfg)
        import_extra_modules(cfg)
        log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, base=cfg.get("log_dir", "logs/runs"))
        logger = get_logger(fabric, cfg, log_dir)
        evaluate_player(fabric, cfg, player, log_dir, logger)
        return

    # legacy registry path (algorithms without a serving player) — discovery
    # and config resolution above already came from the loader
    import sheeprl_tpu
    from sheeprl_tpu.parallel.fabric import build_fabric

    cfg.fabric.devices = 1
    cfg.env.num_envs = 1
    cfg.env.capture_video = cfg.env.get("capture_video", False)
    sheeprl_tpu.register_all_algorithms()
    import_extra_modules(cfg)
    entries = evaluation_registry.get(cfg.algo.name)
    if not entries:
        raise ConfigError(
            f"No evaluation registered for '{cfg.algo.name}' "
            f"(available: {', '.join(sorted(evaluation_registry))})"
        )
    entry = entries[0]
    import importlib

    module = importlib.import_module(entry.module)
    fn = getattr(module, entry.entrypoint)
    fabric = build_fabric(cfg)
    state = fabric.load(ckpt_path)
    fn(fabric, cfg, state)


def serve(argv: Optional[List[str]] = None) -> None:
    """Serve a committed checkpoint as a continuous-batching policy server.

    Usage:
        python -m sheeprl_tpu.serve checkpoint_path=<ckpt-or-run-dir> \\
            [serve.port=7455] [serve.batch_ladder=[1,8,32,128]] [overrides...]

    ``checkpoint_path`` accepts a committed ``step_*`` snapshot directory, a
    run/version directory (→ newest committed snapshot), or a legacy
    ``.ckpt`` file.  The server AOT-warms the policy executable at every
    batch-ladder rung before binding the socket, then hot-swaps params
    whenever training commits a newer snapshot into the same run directory.
    """
    argv = list(sys.argv[1:] if argv is None else argv)
    ckpt_override = [a for a in argv if a.startswith("checkpoint_path=")]
    if not ckpt_override:
        raise ConfigError("serve requires checkpoint_path=<ckpt-or-run-dir>")
    rest = [a for a in argv if not a.startswith("checkpoint_path=")]

    from sheeprl_tpu.serve import PolicyService
    from sheeprl_tpu.serve.server import PolicyServer

    # SHEEPRL_FAULT_PLAN plans arm BEFORE the checkpoint resolve/load so
    # startup-path sites (fabric.copy_to, the loader) are covered; a
    # config-group plan can only arm after the run config is loaded from
    # next to the checkpoint, i.e. it covers the serving phase only
    from sheeprl_tpu.resilience import install_from_config, install_from_env

    install_from_env()
    service = PolicyService.from_checkpoint(ckpt_override[0].split("=", 1)[1], rest)
    install_from_config(service.cfg)
    serve_cfg = service.cfg.get("serve") or {}
    server = PolicyServer(
        service,
        host=str(serve_cfg.get("host", "127.0.0.1")),
        port=int(serve_cfg.get("port", 7455)),
    )
    # flush: the smoke/CI parent parses this line off a block-buffered pipe
    # while serve_forever() never returns to flush it naturally
    print(
        f"serving {service.player.algo} (checkpoint step {service.store.step}) "
        f"on {server.url} — batch ladder {list(service.ladder)}, "
        f"commit watch {'on' if service.watcher else 'off'}",
        flush=True,
    )
    server.serve_forever()


def serve_fleet(argv: Optional[List[str]] = None) -> None:
    """Serve a committed checkpoint through the fault-tolerant fleet:
    N replica processes behind one health-checked router.

    Usage:
        python -m sheeprl_tpu.serve.fleet checkpoint_path=<run-dir> \\
            [serve.fleet.replicas=2] [serve.fleet.port=7456] [overrides...]

    Prefer a run/version directory over a pinned ``step_*`` snapshot: a
    respawned replica re-resolves ``checkpoint_path`` on its own, and a
    pinned step would come back serving stale params after a rolling
    reload.  See docs/serving.md "Fleet".
    """
    argv = list(sys.argv[1:] if argv is None else argv)
    ckpt_override = [a for a in argv if a.startswith("checkpoint_path=")]
    if not ckpt_override:
        raise ConfigError("serve_fleet requires checkpoint_path=<ckpt-or-run-dir>")
    ckpt_path = ckpt_override[0].split("=", 1)[1]
    rest = [a for a in argv if not a.startswith("checkpoint_path=")]

    from sheeprl_tpu.resilience import install_from_config, install_from_env
    from sheeprl_tpu.serve.fleet import FleetRouter, FleetServer, LocalFleet
    from sheeprl_tpu.serve.loader import (
        checkpoint_root,
        ensure_serve_config,
        load_run_config,
        resolve_checkpoint,
    )

    install_from_env()
    ckpt = resolve_checkpoint(ckpt_path)
    cfg = ensure_serve_config(load_run_config(ckpt, rest))
    install_from_config(cfg)
    serve_cfg = cfg.get("serve") or {}
    fleet_cfg = serve_cfg.get("fleet") or {}

    fleet = LocalFleet(
        ckpt_path,
        overrides=rest,
        replicas=int(fleet_cfg.get("replicas", 2)),
        respawn_max=int(fleet_cfg.get("respawn_max", 10)),
        backoff_base_s=float(fleet_cfg.get("respawn_backoff_base_s", 0.5)),
        backoff_max_s=float(fleet_cfg.get("respawn_backoff_max_s", 30.0)),
        seed=int(cfg.get("seed", 0) or 0),
    )

    # the replicas are OUR children: SIGTERM's default handler would kill
    # this process before the ``finally`` below reaps them, leaving N
    # orphaned servers bound to their ports — route it through SystemExit
    # so ``fleet.stop()`` runs and the exit is clean
    import signal

    signal.signal(signal.SIGTERM, lambda signum, frame: sys.exit(0))

    fleet.start()
    try:
        root = checkpoint_root(ckpt) if ckpt.is_dir() else None
        rolling = bool(fleet_cfg.get("rolling_reload", True))
        router = FleetRouter(fleet.addresses(), cfg, ckpt_root=root if rolling else None)
        fleet.attach(router)
        server = FleetServer(
            router,
            host=str(fleet_cfg.get("host", "127.0.0.1")),
            port=int(fleet_cfg.get("port", 7456)),
        )
        # flush: drills/CI parse this line off a block-buffered pipe while
        # serve_forever() never returns to flush it naturally
        print(
            f"fleet router over {fleet.n} replicas on {server.url} — "
            f"rolling reload {'on' if router.ckpt_root is not None else 'off'}, "
            f"replicas: {', '.join(f'{rid}={url}' for rid, url in sorted(fleet.addresses().items()))}",
            flush=True,
        )
        server.serve_forever()
    finally:
        fleet.stop()


def registration(argv: Optional[List[str]] = None) -> None:
    """Export checkpointed models to the model store
    (reference: sheeprl/cli.py:408-450)."""
    argv = list(sys.argv[1:] if argv is None else argv)
    ckpt_override = [a for a in argv if a.startswith("checkpoint_path=")]
    if not ckpt_override:
        raise ConfigError("registration requires checkpoint_path=<path-to-ckpt>")
    ckpt_path = pathlib.Path(ckpt_override[0].split("=", 1)[1])
    import yaml

    with open(ckpt_path.parent.parent / "config.yaml") as f:
        cfg = dotdict(yaml.safe_load(f))
    from sheeprl_tpu.config.compose import apply_cli_overrides

    apply_cli_overrides(cfg, [a for a in argv if not a.startswith("checkpoint_path=")])
    import importlib

    import sheeprl_tpu

    sheeprl_tpu.register_all_algorithms()
    import_extra_modules(cfg)
    entry = resolve_algorithm(cfg.algo.name)
    try:
        utils_mod = importlib.import_module(entry.module.rsplit(".", 1)[0] + ".utils")
    except ModuleNotFoundError:
        utils_mod = None
    from sheeprl_tpu.parallel.fabric import build_fabric
    from sheeprl_tpu.utils.model_manager import register_model_from_checkpoint

    fabric = build_fabric(cfg)
    state = fabric.load(ckpt_path)
    log_models = getattr(utils_mod, "log_models_from_checkpoint", None)
    if log_models is not None:
        log_models(fabric, cfg, state)
    else:
        keys = getattr(utils_mod, "MODELS_TO_REGISTER", None)
        versions = register_model_from_checkpoint(fabric, cfg, state, keys)
        print(f"Registered models: {versions}")


def available_agents() -> None:
    """Print the registered algorithms (reference: sheeprl/available_agents.py:7-34)."""
    import sheeprl_tpu

    sheeprl_tpu.register_all_algorithms()
    try:
        from rich.console import Console
        from rich.table import Table

        table = Table(title="sheeprl-tpu agents")
        table.add_column("Algorithm")
        table.add_column("Module")
        table.add_column("Entrypoint")
        table.add_column("Decoupled")
        for name, entries in sorted(algorithm_registry.items()):
            for e in entries:
                table.add_row(name, e.module, e.entrypoint, str(e.decoupled))
        Console().print(table)
    except Exception:
        for name, entries in sorted(algorithm_registry.items()):
            for e in entries:
                print(f"{name}\t{e.module}\t{e.entrypoint}\tdecoupled={e.decoupled}")
