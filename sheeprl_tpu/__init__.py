"""sheeprl-tpu: a TPU-native reinforcement-learning framework.

Built from scratch for JAX/XLA/Pallas/pjit with the capability surface of
Eclectic-Sheep/sheeprl (the reference implementation analyzed in SURVEY.md):
A2C, PPO (+recurrent, +decoupled), SAC (+AE, +decoupled), DroQ,
Dreamer V1/V2/V3 and Plan2Explore, over Gymnasium environments, with
host-side replay buffers feeding jit-compiled SPMD train steps on a
``jax.sharding.Mesh``.

Importing the package registers every available algorithm (the reference does
the same import-side-effect registration, sheeprl/__init__.py:18-47).
"""

from __future__ import annotations

__version__ = "0.1.0"


def register_all_algorithms() -> None:
    """Import every algorithm module for its registration side effect."""
    import importlib

    for mod in (
        "sheeprl_tpu.algos.ppo.ppo",
        "sheeprl_tpu.algos.ppo.ppo_decoupled",
        "sheeprl_tpu.algos.ppo.evaluate",
        "sheeprl_tpu.algos.ppo_recurrent.ppo_recurrent",
        "sheeprl_tpu.algos.ppo_recurrent.evaluate",
        "sheeprl_tpu.algos.a2c.a2c",
        "sheeprl_tpu.algos.a2c.evaluate",
        "sheeprl_tpu.algos.sac.sac",
        "sheeprl_tpu.algos.sac.sac_decoupled",
        "sheeprl_tpu.algos.sac.evaluate",
        "sheeprl_tpu.algos.sac_ae.sac_ae",
        "sheeprl_tpu.algos.sac_ae.evaluate",
        "sheeprl_tpu.algos.droq.droq",
        "sheeprl_tpu.algos.droq.evaluate",
        "sheeprl_tpu.algos.dreamer_v1.dreamer_v1",
        "sheeprl_tpu.algos.dreamer_v1.evaluate",
        "sheeprl_tpu.algos.dreamer_v2.dreamer_v2",
        "sheeprl_tpu.algos.dreamer_v2.evaluate",
        "sheeprl_tpu.algos.dreamer_v3.dreamer_v3",
        "sheeprl_tpu.algos.dreamer_v3.evaluate",
        "sheeprl_tpu.algos.p2e_dv1.p2e_dv1_exploration",
        "sheeprl_tpu.algos.p2e_dv1.p2e_dv1_finetuning",
        "sheeprl_tpu.algos.p2e_dv1.evaluate",
        "sheeprl_tpu.algos.p2e_dv2.p2e_dv2_exploration",
        "sheeprl_tpu.algos.p2e_dv2.p2e_dv2_finetuning",
        "sheeprl_tpu.algos.p2e_dv2.evaluate",
        "sheeprl_tpu.algos.p2e_dv3.p2e_dv3_exploration",
        "sheeprl_tpu.algos.p2e_dv3.p2e_dv3_finetuning",
        "sheeprl_tpu.algos.p2e_dv3.evaluate",
    ):
        try:
            importlib.import_module(mod)
        except ModuleNotFoundError as e:
            # Only tolerate modules not built yet; surface real import errors.
            if "sheeprl_tpu" not in str(e):
                raise
