"""Multi-device Dreamer coverage: the FULL loop (sharded replay sampling,
``batch_size = per_rank_batch_size * world_size``, checkpoint + resume under
a mesh) on 2 virtual devices — not just a jitted step
(reference test strategy: tests/test_algos/test_algos.py runs every algo on
1 and 2 devices)."""

import glob

import numpy as np

from sheeprl_tpu.cli import run
from tests.ckpt_utils import find_checkpoints
from tests.test_algos.test_algos import TINY_DV3_ARGS, standard_args


def test_dreamer_v3_two_devices_with_resume(tmp_path):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo.run_test=False",
            *TINY_DV3_ARGS,
        ],
        devices=2,
    )
    run(args)
    ckpts = find_checkpoints(f"{tmp_path}/logs")
    assert ckpts
    # resume the 2-device run from its own mesh-saved checkpoint
    run(args + [f"checkpoint.resume_from={sorted(ckpts)[-1]}"])


def test_dreamer_v2_two_devices_episode_buffer(tmp_path):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=dreamer_v2",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo.per_rank_batch_size=2",
            "algo.per_rank_sequence_length=8",
            "algo.learning_starts=0",
            "algo.horizon=4",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[state]",
            "algo.world_model.encoder.cnn_channels_multiplier=4",
            "algo.dense_units=16",
            "algo.mlp_layers=1",
            "algo.world_model.recurrent_model.recurrent_state_size=16",
            "algo.world_model.transition_model.hidden_size=16",
            "algo.world_model.representation_model.hidden_size=16",
            "algo.world_model.discrete_size=4",
            "algo.world_model.stochastic_size=4",
            "buffer.type=episode",
            "env.max_episode_steps=12",
            "buffer.size=400",
        ],
        devices=2,
    )
    run(args)


def test_p2e_dv2_two_devices(tmp_path):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=p2e_dv2_exploration",
            "env=dummy",
            "env.id=continuous_dummy",
            "algo.per_rank_batch_size=2",
            "algo.per_rank_sequence_length=8",
            "algo.learning_starts=0",
            "algo.per_rank_pretrain_steps=0",
            "algo.horizon=4",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[state]",
            "algo.world_model.encoder.cnn_channels_multiplier=4",
            "algo.dense_units=16",
            "algo.mlp_layers=1",
            "algo.world_model.recurrent_model.recurrent_state_size=16",
            "algo.world_model.transition_model.hidden_size=16",
            "algo.world_model.representation_model.hidden_size=16",
            "algo.world_model.discrete_size=4",
            "algo.world_model.stochastic_size=4",
            "algo.ensembles.n=2",
            "env.max_episode_steps=12",
            "buffer.size=400",
        ],
        devices=2,
    )
    run(args)


def test_dreamer_v3_restart_on_exception(tmp_path, monkeypatch):
    """An env that crashes mid-episode is recreated (RestartOnException) and
    the replay stream is repaired via the buffer API — training completes
    and the stored stream never bootstraps across the break
    (reference behavior: sheeprl/algos/dreamer_v3/dreamer_v3.py:595-608)."""
    import sheeprl_tpu.utils.env as env_mod
    from sheeprl_tpu.envs.dummy import DiscreteDummyEnv

    crashes = {"n": 0}

    class FaultingDummyEnv(DiscreteDummyEnv):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self._steps = 0

        def step(self, action):
            self._steps += 1
            if self._steps == 5 and crashes["n"] < 2:
                crashes["n"] += 1
                raise RuntimeError("injected env crash")
            return super().step(action)

    monkeypatch.setitem(env_mod.DUMMY_ENVS, "faulting_dummy", FaultingDummyEnv)
    args = standard_args(
        tmp_path,
        extra=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=faulting_dummy",
            "env.restart_on_exception=True",
            "env.num_envs=1",
            *TINY_DV3_ARGS,
        ],
    )
    run(args)
    assert crashes["n"] > 0  # the fault actually fired and was survived
