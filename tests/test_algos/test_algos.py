"""E2E smoke tests over the real CLI — the backbone of the test strategy
(reference: tests/test_algos/test_algos.py:22-183): every registered
algorithm runs end-to-end through ``sheeprl_tpu.cli.run`` with tiny,
CPU-only, deterministic settings, on 1 and 2 virtual devices.
"""

import os
import sys
from unittest import mock

import pytest

from sheeprl_tpu.cli import run
from tests.ckpt_utils import find_checkpoints


def standard_args(tmp_path, extra=(), devices=1):
    return [
        "dry_run=True",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        f"fabric.devices={devices}",
        "fabric.accelerator=cpu",
        "metric.log_level=1",
        "metric.log_every=1",
        "checkpoint.every=1",
        "buffer.memmap=False",
        f"log_dir={tmp_path}/logs",
        "print_config=False",
        "algo.run_test=True",
        *extra,
    ]


@pytest.fixture(params=[1, 2], ids=["1device", "2devices"])
def devices(request):
    return request.param


# Shared tiny world-model sizing for every Dreamer-family smoke test — one
# place to tune the XS test configuration (the same blob used to be repeated
# per test and drifted).
TINY_WM_ARGS = [
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=8",
    "algo.learning_starts=0",
    "algo.horizon=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "algo.world_model.encoder.cnn_channels_multiplier=4",
    "algo.dense_units=16",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.transition_model.hidden_size=16",
    "algo.world_model.representation_model.hidden_size=16",
]

DV3_XS_ARGS = [
    "algo=dreamer_v3_XS",
    *TINY_WM_ARGS,
    "algo.replay_ratio=1",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "env.screen_size=64",
    "env.max_episode_steps=20",
    "buffer.size=200",
]


@pytest.mark.parametrize("env_id", ["discrete_dummy", "multidiscrete_dummy", "continuous_dummy"])
def test_ppo_dry_run(tmp_path, devices, env_id):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=ppo",
            "env=dummy",
            f"env.id={env_id}",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=8",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=16",
        ],
        devices=devices,
    )
    run(args)
    # a checkpoint must exist
    import glob

    assert find_checkpoints(f"{tmp_path}/logs")


def test_ppo_pixel_encoder(tmp_path):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=ppo",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=8",
            "env.screen_size=32",
        ],
    )
    run(args)


def test_ppo_resume_from_checkpoint(tmp_path):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=ppo",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=8",
            "algo.run_test=False",
        ],
    )
    run(args)
    import glob

    ckpts = find_checkpoints(f"{tmp_path}/logs")
    assert ckpts
    run(args + [f"checkpoint.resume_from={ckpts[0]}"])


def test_unknown_algorithm_raises(tmp_path):
    from sheeprl_tpu.config.compose import ConfigError

    with pytest.raises(ConfigError):
        run(["env=dummy", "algo.name=not_an_algo", "algo.total_steps=1", "algo.per_rank_batch_size=1"])


def test_evaluation_cli(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = standard_args(
        tmp_path,
        extra=[
            "exp=ppo",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=8",
            "algo.run_test=False",
        ],
    )
    run(args)
    import glob

    from sheeprl_tpu.cli import evaluation

    ckpts = find_checkpoints(f"{tmp_path}/logs")
    evaluation([f"checkpoint_path={ckpts[0]}", "env.capture_video=False"])


def test_evaluation_cli_after_dreamer(tmp_path, monkeypatch):
    """Eval dispatch must rebuild a Dreamer agent from its checkpoint too —
    the reference evaluates every registered algorithm
    (sheeprl/cli.py:evaluation); r1 covered only PPO (VERDICT weak #8)."""
    monkeypatch.chdir(tmp_path)
    args = standard_args(
        tmp_path,
        extra=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            *DV3_XS_ARGS,
            "algo.run_test=False",
        ],
    )
    run(args)
    import glob

    from sheeprl_tpu.cli import evaluation

    ckpts = find_checkpoints(f"{tmp_path}/logs")
    assert ckpts
    evaluation([f"checkpoint_path={ckpts[0]}", "env.capture_video=False"])


@pytest.mark.parametrize("env_id", ["discrete_dummy", "continuous_dummy"])
def test_a2c_dry_run(tmp_path, devices, env_id):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=a2c",
            "env=dummy",
            f"env.id={env_id}",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=8",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=16",
        ],
        devices=devices,
    )
    run(args)


def test_sac_dry_run(tmp_path, devices):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=sac",
            "env=dummy",
            "env.id=continuous_dummy",
            "algo.per_rank_batch_size=8",
            "algo.learning_starts=4",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=16",
            "buffer.size=64",
        ],
        devices=devices,
    )
    run(args)


def test_sac_rejects_discrete(tmp_path):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=sac",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo.per_rank_batch_size=8",
            "algo.mlp_keys.encoder=[state]",
            "buffer.size=64",
        ],
    )
    with pytest.raises(ValueError, match="continuous"):
        run(args)


def test_ppo_decoupled_dry_run(tmp_path, devices):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=ppo_decoupled",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=8",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=16",
        ],
        devices=devices,
    )
    run(args)


@pytest.mark.parametrize("env_id", ["discrete_dummy", "continuous_dummy"])
def test_ppo_recurrent_dry_run(tmp_path, env_id):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=ppo_recurrent",
            "env=dummy",
            f"env.id={env_id}",
            "env.mask_velocities=False",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=8",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=16",
        ],
    )
    run(args)


@pytest.mark.parametrize("env_id", ["discrete_dummy", "multidiscrete_dummy", "continuous_dummy"])
def test_dreamer_v3_dry_run(tmp_path, env_id):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=dreamer_v3",
            "env=dummy",
            f"env.id={env_id}",
            *DV3_XS_ARGS,
        ],
    )
    run(args)


def test_droq_dry_run(tmp_path):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=droq",
            "env=dummy",
            "env.id=continuous_dummy",
            "algo.per_rank_batch_size=8",
            "algo.learning_starts=4",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=16",
            "buffer.size=64",
        ],
    )
    run(args)


def test_sac_ae_dry_run(tmp_path):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=sac_ae",
            "env=dummy",
            "env.id=continuous_dummy",
            "algo.per_rank_batch_size=4",
            "algo.learning_starts=4",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_channels_multiplier=4",
            "algo.hidden_size=32",
            "algo.encoder.features_dim=16",
            "env.screen_size=32",
            "env.max_episode_steps=16",
            "buffer.size=64",
        ],
    )
    run(args)


def test_sac_decoupled_dry_run(tmp_path):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=sac_decoupled",
            "env=dummy",
            "env.id=continuous_dummy",
            "algo.per_rank_batch_size=8",
            "algo.learning_starts=4",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=16",
            "buffer.size=64",
        ],
    )
    run(args)


@pytest.mark.parametrize("buffer_type", ["sequential", "episode"])
def test_dreamer_v2_dry_run(tmp_path, buffer_type):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=dreamer_v2",
            "env=dummy",
            "env.id=discrete_dummy",
            *TINY_WM_ARGS,
            "algo.mlp_layers=1",
            "algo.world_model.discrete_size=4",
            "algo.world_model.stochastic_size=4",
            f"buffer.type={buffer_type}",
            "env.max_episode_steps=12",
            "buffer.size=400",
        ],
    )
    run(args)


def test_dreamer_v1_dry_run(tmp_path):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=dreamer_v1",
            "env=dummy",
            "env.id=continuous_dummy",
            *TINY_WM_ARGS,
            "algo.mlp_layers=1",
            "algo.world_model.stochastic_size=8",
            "env.max_episode_steps=12",
            "buffer.size=400",
        ],
    )
    run(args)


TINY_DV3_ARGS = [
    *TINY_WM_ARGS,
    "algo.mlp_layers=1",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "env.max_episode_steps=12",
    "buffer.size=400",
]


def test_p2e_dv3_exploration_and_finetuning(tmp_path):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=p2e_dv3_exploration",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo.ensembles.n=3",
            *TINY_DV3_ARGS,
        ],
    )
    run(args)
    import glob

    ckpts = find_checkpoints(f"{tmp_path}/logs")
    assert ckpts
    ft_args = standard_args(
        tmp_path,
        extra=[
            "exp=p2e_dv3_finetuning",
            "env=dummy",
            "env.id=discrete_dummy",
            f"checkpoint.exploration_ckpt_path={ckpts[0]}",
            *TINY_DV3_ARGS,
        ],
    )
    run(ft_args)


@pytest.mark.parametrize("version", ["1", "2"])
def test_p2e_dv12_exploration_and_finetuning(tmp_path, version):
    tiny = [
        "algo.per_rank_batch_size=2",
        "algo.per_rank_sequence_length=8",
        "algo.learning_starts=0",
        "algo.per_rank_pretrain_steps=0",
        "algo.horizon=4",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "algo.world_model.encoder.cnn_channels_multiplier=4",
        "algo.dense_units=16",
        "algo.mlp_layers=1",
        "algo.world_model.recurrent_model.recurrent_state_size=16",
        "algo.world_model.transition_model.hidden_size=16",
        "algo.world_model.representation_model.hidden_size=16",
        "algo.ensembles.n=2",
        "env.max_episode_steps=12",
        "buffer.size=400",
    ]
    if version == "2":
        tiny += ["algo.world_model.discrete_size=4", "algo.world_model.stochastic_size=4"]
    else:
        tiny += ["algo.world_model.stochastic_size=8"]
    args = standard_args(
        tmp_path,
        extra=[f"exp=p2e_dv{version}_exploration", "env=dummy", "env.id=continuous_dummy", *tiny],
    )
    run(args)
    import glob

    ckpts = find_checkpoints(f"{tmp_path}/logs")
    assert ckpts
    run(
        standard_args(
            tmp_path,
            extra=[
                f"exp=p2e_dv{version}_finetuning",
                "env=dummy",
                "env.id=continuous_dummy",
                f"checkpoint.exploration_ckpt_path={ckpts[0]}",
                *tiny,
            ],
        )
    )


def test_dreamer_v3_decoupled_rssm(tmp_path):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo.world_model.decoupled_rssm=True",
            *TINY_DV3_ARGS,
        ],
    )
    run(args)


@pytest.mark.parametrize("dist_type", ["tanh_normal", "trunc_normal"])
def test_ppo_continuous_distribution_types(tmp_path, dist_type):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=ppo",
            "env=dummy",
            "env.id=continuous_dummy",
            f"distribution.type={dist_type}",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=8",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=16",
        ],
    )
    run(args)


def test_dreamer_v3_resume_from_checkpoint(tmp_path):
    args = standard_args(
        tmp_path,
        extra=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            "buffer.checkpoint=True",
            "algo.run_test=False",
            *TINY_DV3_ARGS,
        ],
    )
    run(args)
    import glob

    ckpts = find_checkpoints(f"{tmp_path}/logs")
    assert ckpts
    # resume restores params/opt/counters/ratio and the replay buffer
    run(args + [f"checkpoint.resume_from={ckpts[0]}"])


def test_end_of_training_model_registration(tmp_path, monkeypatch):
    """With model_manager.disabled=False the final checkpoint's sub-models are
    exported to the registry with the configured names (reference:
    end-of-`main` register_model hook, sheeprl/algos/ppo/ppo.py:448-453,
    driven by configs/model_manager/ppo.yaml)."""
    monkeypatch.chdir(tmp_path)
    args = standard_args(
        tmp_path,
        extra=[
            "exp=ppo",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=8",
            "algo.run_test=False",
            "model_manager.disabled=False",
            f"model_manager.registry_root={tmp_path}/registry",
        ],
    )
    run(args)
    from sheeprl_tpu.utils.model_manager import FileSystemModelManager

    manager = FileSystemModelManager(f"{tmp_path}/registry")
    # exp_name = ppo_discrete_dummy → model_name from configs/model_manager/ppo.yaml
    assert manager.get_latest_version("ppo_discrete_dummy_agent") == 1
    params = manager.load_model("ppo_discrete_dummy_agent")
    assert params is not None


def test_dreamer_v3_remat(tmp_path):
    """algo.remat=True rematerializes the RSSM/imagination scan bodies
    (jax.checkpoint) — the whole loop must still run and checkpoint."""
    args = standard_args(
        tmp_path,
        extra=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo.remat=True",
            "algo.run_test=False",
            *TINY_DV3_ARGS,
        ],
    )
    run(args)
    import glob

    assert find_checkpoints(f"{tmp_path}/logs")


def test_profiler_gate_captures_trace(tmp_path):
    """metric.profiler.enabled=True captures a jax.profiler trace window
    into <log_dir>/profiler (TPU-tuning aid; reference has timers only)."""
    args = standard_args(
        tmp_path,
        extra=[
            "exp=ppo",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=8",
            "algo.run_test=False",
            "algo.total_steps=64",
            "dry_run=False",
            "metric.profiler.enabled=True",
            "metric.profiler.start_update=2",
            "metric.profiler.stop_update=4",
        ],
    )
    run(args)
    import glob

    traces = glob.glob(f"{tmp_path}/logs/**/profiler/**/*", recursive=True)
    assert traces, "no profiler trace captured"


def test_sac_accelerator_player(tmp_path):
    """algo.player.device=accelerator routes rollout inference through the
    first process-local mesh device instead of the host player device
    (fabric.player_device accelerator branch) — the on-pod big-encoder
    configuration (VERDICT r2 #9)."""
    args = standard_args(
        tmp_path,
        extra=[
            "exp=sac",
            "env=dummy",
            "env.id=continuous_dummy",
            "algo.per_rank_batch_size=8",
            "algo.learning_starts=4",
            "algo.mlp_keys.encoder=[state]",
            "algo.player.device=accelerator",
            "env.max_episode_steps=16",
            "buffer.size=64",
        ],
    )
    run(args)


def test_dreamer_v3_accelerator_player(tmp_path):
    """Accelerator player through the Dreamer family loop (stateful player:
    recurrent state carried on the chosen device)."""
    args = standard_args(
        tmp_path,
        extra=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            *DV3_XS_ARGS,
            "algo.player.device=accelerator",
        ],
    )
    run(args)


@pytest.mark.parametrize(
    "exp,extra",
    [
        ("ppo_decoupled", ["algo.rollout_steps=8", "algo.per_rank_batch_size=8", "algo.update_epochs=1"]),
        ("sac_decoupled", ["algo.per_rank_batch_size=8", "algo.learning_starts=8", "buffer.size=256"]),
    ],
)
def test_evaluation_cli_after_decoupled(tmp_logdir, exp, extra):
    """Decoupled-run checkpoints must be evaluable: the saved config carries
    algo.name=<algo>_decoupled, which needs its own evaluation registration
    (reference: sheeprl/algos/ppo/evaluate.py:58, sac/evaluate.py:15)."""
    env_id = "discrete_dummy" if exp == "ppo_decoupled" else "continuous_dummy"
    args = standard_args(
        tmp_logdir,
        extra=[
            f"exp={exp}",
            "env=dummy",
            f"env.id={env_id}",
            "algo.mlp_keys.encoder=[state]",
            "env.max_episode_steps=16",
            "algo.run_test=False",
            *extra,
        ],
        devices=2,
    )
    run(args)
    import glob

    from sheeprl_tpu.cli import evaluation

    ckpts = find_checkpoints(f"{tmp_logdir}/logs")
    assert ckpts
    evaluation([f"checkpoint_path={ckpts[0]}", "env.capture_video=False"])
