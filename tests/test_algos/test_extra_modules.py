"""E2E test of the out-of-tree extension mechanism: an algorithm living in a
user package, wired in ONLY via SHEEPRL_SEARCH_PATH (configs) and
algo.extra_modules (code), runs end-to-end through the real CLI — the
workflow documented in howto/register_external_algorithm.md (reference
mechanism: hydra_plugins/sheeprl_search_path.py:11-33 +
howto/register_external_algorithm.md).
"""

import sys
import textwrap

from sheeprl_tpu.cli import run


def test_external_algorithm_end_to_end(tmp_path, monkeypatch):
    pkg = tmp_path / "ext_pkg"
    (pkg / "my_ext").mkdir(parents=True)
    (pkg / "my_ext" / "__init__.py").write_text("")
    # The external entrypoint registers under its own name and delegates to
    # the built-in PPO loop — proving registration + dispatch, not PPO.
    (pkg / "my_ext" / "my_ext.py").write_text(
        textwrap.dedent(
            """
            from sheeprl_tpu.utils.registry import register_algorithm


            @register_algorithm(name="my_ext")
            def main(fabric, cfg):
                from sheeprl_tpu.algos.ppo.ppo import main as ppo_main

                cfg.ext_marker_seen = True
                ppo_main(fabric, cfg)
            """
        )
    )

    cfgs = tmp_path / "configs"
    (cfgs / "algo").mkdir(parents=True)
    (cfgs / "exp").mkdir()
    # Out-of-tree algo config: inherits the BUILT-IN ppo group (external
    # dirs are searched first, built-ins still resolve) and re-names it.
    (cfgs / "algo" / "my_ext.yaml").write_text(
        textwrap.dedent(
            """
            defaults:
              - ppo

            name: my_ext
            extra_modules:
              - my_ext.my_ext
            """
        )
    )
    (cfgs / "exp" / "my_ext.yaml").write_text(
        textwrap.dedent(
            """
            # @package _global_
            defaults:
              - override /algo: my_ext
              - override /env: dummy

            algo:
              total_steps: 64
              per_rank_batch_size: 16
              rollout_steps: 8
              mlp_keys:
                encoder: [state]
              cnn_keys:
                encoder: []
            """
        )
    )

    monkeypatch.setenv("SHEEPRL_SEARCH_PATH", str(cfgs))
    monkeypatch.syspath_prepend(str(pkg))
    try:
        run(
            [
                "exp=my_ext",
                "env.id=discrete_dummy",
                "dry_run=True",
                "env.num_envs=2",
                "env.sync_env=True",
                "env.capture_video=False",
                "fabric.devices=1",
                "fabric.accelerator=cpu",
                "metric.log_level=0",
                "checkpoint.every=0",
                "checkpoint.save_last=False",
                "buffer.memmap=False",
                "algo.run_test=False",
                "print_config=False",
                f"log_dir={tmp_path}/logs",
            ]
        )
    finally:
        # keep the registry/module table clean for other tests
        from sheeprl_tpu.utils.registry import algorithm_registry

        algorithm_registry.pop("my_ext", None)
        sys.modules.pop("my_ext.my_ext", None)
        sys.modules.pop("my_ext", None)
