"""``buffer.share_data`` semantics (VERDICT r3 missing #2).

The reference's two DP minibatch modes (reference:
sheeprl/algos/ppo/ppo.py:40-55,363-370):

* ``share_data=True``  — all ranks minibatch the GLOBAL rollout pool;
* ``share_data=False`` — classic DDP: each rank minibatches only its own
  rollout, gradients averaged.

Here the single global train program realizes both through the epoch
permutation layout (`sheeprl_tpu.algos.ppo.ppo.epoch_permutation`), so the
semantics are exactly testable at the index level — stronger than a
stochastic two-run comparison.
"""

import jax
import numpy as np
import pytest

from sheeprl_tpu.algos.ppo.ppo import epoch_permutation


def _perm(T, B, bs, share_data, n_shards):
    num_mb = -(-T * B // bs)
    p = epoch_permutation(jax.random.PRNGKey(0), T, B, bs, num_mb, share_data, n_shards)
    return np.asarray(p), num_mb


def test_shared_pool_is_global_permutation():
    T, B, bs = 8, 4, 8
    perm, num_mb = _perm(T, B, bs, share_data=True, n_shards=2)
    assert perm.shape == (num_mb * bs,)
    # covers the whole global pool exactly once (no pad at this shape)
    assert sorted(perm.tolist()) == list(range(T * B))


def test_ddp_mode_minibatches_are_rank_balanced():
    T, B, bs, n_shards = 8, 4, 8, 2
    b_loc = B // n_shards
    perm, num_mb = _perm(T, B, bs, share_data=False, n_shards=n_shards)
    pr_bs = bs // n_shards
    for i in range(num_mb):
        mb = perm[i * bs : (i + 1) * bs]
        # rank r's slice sits at [r*pr_bs, (r+1)*pr_bs) of every minibatch
        for r in range(n_shards):
            rows = mb[r * pr_bs : (r + 1) * pr_bs]
            cols = rows % B
            assert np.all((cols >= r * b_loc) & (cols < (r + 1) * b_loc)), (
                f"minibatch {i}: rank {r} slice contains foreign env columns {cols}"
            )


def test_ddp_mode_each_rank_covers_its_rows_exactly_once():
    T, B, bs, n_shards = 8, 4, 8, 2
    b_loc = B // n_shards
    perm, _ = _perm(T, B, bs, share_data=False, n_shards=n_shards)
    for r in range(n_shards):
        own = sorted(int(g) for g in perm if r * b_loc <= g % B < (r + 1) * b_loc)
        expect = sorted(t * B + r * b_loc + b for t in range(T) for b in range(b_loc))
        assert own == expect


def test_ddp_mode_pads_by_wraparound_when_uneven():
    # T*B_loc = 12 rows per rank, pr_bs = 5 -> 3 minibatches, 3 rows padded
    T, B, bs, n_shards = 6, 4, 10, 2
    perm, num_mb = _perm(T, B, bs, share_data=False, n_shards=n_shards)
    assert num_mb == 3 and perm.shape == (30,)
    b_loc = B // n_shards
    for r in range(n_shards):
        own = [int(g) for g in perm if r * b_loc <= g % B < (r + 1) * b_loc]
        assert len(own) == 15 and len(set(own)) == 12  # all rows + 3 repeats


def test_single_shard_ignores_share_data():
    a, _ = _perm(4, 2, 4, share_data=False, n_shards=1)
    b, _ = _perm(4, 2, 4, share_data=True, n_shards=1)
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("share_data", [True, False])
def test_ppo_runs_with_share_data_flag(tmp_path, share_data):
    """The flag is consumed end-to-end (it was silently ignored before)."""
    from sheeprl_tpu.cli import run

    run(
        [
            "exp=ppo",
            f"buffer.share_data={share_data}",
            "env=dummy",
            "env.id=discrete_dummy",
            "dry_run=True",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.devices=1",
            "fabric.accelerator=cpu",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.run_test=False",
            "metric.log_level=0",
            "checkpoint.every=0",
            "checkpoint.save_last=False",
            "buffer.memmap=False",
            f"log_dir={tmp_path}/logs",
            "print_config=False",
        ]
    )
