"""Sebulba actor–learner topology (ISSUE 12): queue semantics, actor
compile-once, staleness accounting, chaos drills, and end-to-end runs.

The contract under test:

* the trajectory queue is BOUNDED and BLOCKING — a full queue applies
  backpressure to producers and never drops a segment;
* torn segments (the ``sebulba.traj_queue`` truncate fault) are rejected
  at ``put`` and can never reach the learner;
* actor inference is compile-once: 50 steady dispatch windows reuse ONE
  executable per ladder rung (``cache_size() == 1``);
* a killed or hung env worker (the ``sebulba.env_worker`` fault site) is
  deposed and respawned, and the run completes with no torn trajectories;
* ppo_decoupled / sac_decoupled train end-to-end through
  ``topology=sebulba`` on a fake-device split under
  ``algo.max_recompiles=1``.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.config.compose import compose
from sheeprl_tpu.parallel.fabric import Fabric, build_fabric
from sheeprl_tpu.resilience.faults import FaultPlan, clear_plan, install_plan
from sheeprl_tpu.sebulba.queues import ObsBlock, QueueFull, TornTrajectory, TrajQueue


def _seg(t=4, b=2, version=0):
    return {
        "state": np.zeros((t, b, 4), np.float32),
        "rewards": np.zeros((t, b), np.float32),
        "last_state": np.zeros((b, 4), np.float32),
    }


class TestTrajQueueSemantics:
    def _queue(self, capacity=2, steps=4, stage=True):
        fab = Fabric(devices=2, accelerator="cpu")
        return TrajQueue(
            capacity, steps, fab, stage=stage,
            bootstrap_keys=("last_state",), timeout_s=2.0,
        )

    def test_backpressure_blocks_producer_and_never_drops(self):
        q = self._queue(capacity=2)
        q.put(_seg(), {"version": 0})
        q.put(_seg(), {"version": 1})
        assert q.qsize() == 2

        unblocked_at = {}

        def producer():
            q.put(_seg(), {"version": 2})  # must BLOCK until the learner pops
            unblocked_at["t"] = time.monotonic()

        t = threading.Thread(target=producer)
        t.start()
        time.sleep(0.3)
        assert "t" not in unblocked_at, "full queue must block, not drop"
        t0 = time.monotonic()
        items = q.get_many(2)
        t.join(5.0)
        assert unblocked_at["t"] >= t0
        # nothing was dropped: all three segments arrive, in order
        items += q.get_many(1)
        assert [m["version"] for _, m in items] == [0, 1, 2]
        assert q.total_put == 3

    def test_put_times_out_loudly_when_learner_wedged(self):
        q = self._queue(capacity=1)
        q.put(_seg(), {})
        with pytest.raises(QueueFull):
            q.put(_seg(), {})  # nobody pops: fail after timeout_s, not hang

    def test_staged_segments_live_on_the_learner_mesh(self):
        fab = Fabric(devices=2, accelerator="cpu")
        q = TrajQueue(2, 4, fab, stage=True, bootstrap_keys=("last_state",), timeout_s=2.0)
        q.put(_seg(t=4, b=2), {})
        (staged, _), = q.get_many(1)
        leaf = staged["state"]
        assert isinstance(leaf, jax.Array)
        # env axis (2 rows) divides the 2-device learner mesh → sharded
        assert set(leaf.devices()) == set(fab.mesh.devices.flat)
        assert "data" in str(leaf.sharding.spec)

    def test_torn_segment_rejected_never_enqueued(self):
        q = self._queue()
        torn = _seg()
        torn["state"] = torn["state"][:2]  # tail-torn time axis
        with pytest.raises(TornTrajectory):
            q.put(torn, {})
        assert q.qsize() == 0 and q.torn_rejected == 1

    def test_truncate_fault_at_traj_queue_is_rejected(self):
        # the sebulba.traj_queue chaos site: a truncate fault tears the
        # segment in flight — the queue's shape validation must catch it
        install_plan(FaultPlan.from_specs([
            {"site": "sebulba.traj_queue", "kind": "truncate", "at": 1},
        ]))
        try:
            q = self._queue()
            with pytest.raises(TornTrajectory):
                q.put(_seg(), {})
            assert q.torn_rejected == 1
            q.put(_seg(), {})  # the fault fired once; clean puts flow again
            assert q.qsize() == 1
        finally:
            clear_plan()


class TestActorCompileOnce:
    def test_cache_size_one_per_rung_across_50_windows(self):
        from sheeprl_tpu.parallel.topology import ParamBroadcast
        from sheeprl_tpu.sebulba.actor import ActorEngine, derive_ladder
        from sheeprl_tpu.sebulba.queues import ObsQueue

        fab = Fabric(devices=2, accelerator="cpu")
        actor_dev = fab.devices[0]
        bc = ParamBroadcast(fab, [actor_dev], max_staleness=8)
        params = fab.replicate({"w": jnp.zeros((4, 3), jnp.float32)})
        bc.publish(params, version=0)

        def policy_fn(p, obs, k):
            k_s, k_next = jax.random.split(k)
            h = obs["state"] @ p["w"]
            return {"actions": h[:, :1], "values": h[:, 2]}, k_next

        ladder = derive_ladder(2, 2)  # blocks of 2 rows, up to 2 blocks
        eng = ActorEngine(
            0, actor_dev, policy_fn, {"state": ((4,), np.dtype(np.float32))},
            jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params),
            ladder, 2, ObsQueue(4), bc, jax.random.PRNGKey(0),
        )
        eng.warmup()
        warm_sizes = dict(eng.cache_sizes())
        assert all(size == 1 for size in warm_sizes.values())
        for window in range(50):
            blocks = [ObsBlock(0, {"state": np.zeros((2, 4), np.float32)}, 2),
                      ObsBlock(1, {"state": np.ones((2, 4), np.float32)}, 2)]
            eng._dispatch(blocks)
            for b in blocks:
                out = b.wait(1.0)
                assert out["actions"].shape == (2, 1)
        # 50 steady windows: every rung still holds exactly ONE executable
        assert eng.cache_sizes() == warm_sizes
        assert max(eng.cache_sizes().values()) == 1
        assert eng.dispatches == 50 and eng.rows_served == 200

    def test_partial_round_pads_to_a_warmed_rung(self):
        from sheeprl_tpu.parallel.topology import ParamBroadcast
        from sheeprl_tpu.sebulba.actor import ActorEngine, derive_ladder
        from sheeprl_tpu.sebulba.queues import ObsQueue

        fab = Fabric(devices=1, accelerator="cpu")
        bc = ParamBroadcast(fab, [fab.device], max_staleness=8)
        bc.publish(fab.replicate({"w": jnp.zeros((4, 3), jnp.float32)}), version=0)

        def policy_fn(p, obs, k):
            k_s, k_next = jax.random.split(k)
            return {"actions": obs["state"] @ p["w"]}, k_next

        eng = ActorEngine(
            0, fab.device, policy_fn, {"state": ((4,), np.dtype(np.float32))},
            {"w": jax.ShapeDtypeStruct((4, 3), jnp.float32)},
            derive_ladder(2, 4), 2, ObsQueue(8), bc, jax.random.PRNGKey(0),
        )
        eng.warmup()
        # 3 blocks of 2 rows = 6 → padded to the 8-rung (a warmed shape)
        blocks = [ObsBlock(i, {"state": np.zeros((2, 4), np.float32)}, 2) for i in range(3)]
        eng._dispatch(blocks)
        assert eng.rows_served == 6 and eng.rows_padded == 2
        assert max(eng.cache_sizes().values()) == 1


SEBULBA_PPO_ARGS = [
    "exp=ppo_decoupled",
    "env=dummy",
    "env.id=discrete_dummy",
    "env.max_episode_steps=16",
    "env.num_envs=4",
    "env.sync_env=True",
    "env.capture_video=False",
    "topology=sebulba",
    "topology.env_workers=2",
    "topology.traj_queue_slots=2",
    "fabric.devices=2",
    "fabric.accelerator=cpu",
    "algo.rollout_steps=4",
    "algo.per_rank_batch_size=8",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
    "algo.max_recompiles=1",
    "algo.run_test=False",
    "checkpoint.every=0",
    "checkpoint.save_last=False",
    "buffer.memmap=False",
    "metric.log_level=1",
    "metric.log_every=1",
    "print_config=False",
]


def _run_sebulba_ppo(tmp_path, extra=()):
    from sheeprl_tpu.sebulba.ppo import run_sebulba
    from sheeprl_tpu.utils.utils import force_cpu_backend

    force_cpu_backend()
    cfg = compose([*SEBULBA_PPO_ARGS, f"log_dir={tmp_path}/logs", *extra])
    fabric = build_fabric(cfg)
    return run_sebulba(fabric, cfg)


class TestSebulbaEndToEnd:
    def test_ppo_worker_path_trains_and_reports(self, tmp_path):
        stats = _run_sebulba_ppo(tmp_path, extra=["algo.total_steps=64"])
        assert stats["updates"] == 4
        assert stats["env_steps"] == 64
        assert stats["torn_rejected"] == 0 and stats["worker_restarts"] == 0
        # every actor executable stayed compile-once
        for sizes in stats["actor_cache_sizes"]:
            assert all(s <= 1 for s in sizes.values())
        assert 0.0 <= stats["actor_idle_frac"] <= 1.0
        assert 0.0 <= stats["queue_depth_frac"] <= 1.0

    def test_ppo_fused_jax_actor_path(self, tmp_path):
        from sheeprl_tpu.sebulba.ppo import run_sebulba
        from sheeprl_tpu.utils.utils import force_cpu_backend

        force_cpu_backend()
        cfg = compose([
            "exp=ppo_decoupled", "env=jax_cartpole", "env.num_envs=4",
            "env.capture_video=False",
            "topology=sebulba", "topology.actor_devices=2", "topology.traj_queue_slots=2",
            "fabric.devices=4", "fabric.accelerator=cpu",
            "algo.rollout_steps=4", "algo.per_rank_batch_size=8",
            "algo.update_epochs=1", "algo.total_steps=64",
            "algo.cnn_keys.encoder=[]", "algo.mlp_keys.encoder=[state]",
            "algo.max_recompiles=1", "algo.run_test=False",
            "checkpoint.every=0", "checkpoint.save_last=False",
            "buffer.memmap=False", "buffer.transfer_guard=True",
            "metric.log_level=0", "print_config=False",
            f"log_dir={tmp_path}/logs",
        ])
        fabric = build_fabric(cfg)
        stats = run_sebulba(fabric, cfg)
        assert stats["updates"] == 4
        # the fused rollout shard is ONE executable per actor device and the
        # armed transfer guard proved its steady state ships nothing H2D
        for sizes in stats["actor_cache_sizes"]:
            assert list(sizes.values()) == [1]
        # the gate bounds actor-param staleness at dispatch; consumed
        # segments can add at most the queue's depth on top
        assert stats["param_staleness_max"] <= cfg.topology.max_staleness + 1
        assert (
            stats["traj_staleness_max"]
            <= cfg.topology.max_staleness + cfg.topology.traj_queue_slots
        )

    def test_sac_sebulba_device_replay_learner(self, tmp_path):
        from sheeprl_tpu.sebulba.sac import run_sebulba
        from sheeprl_tpu.utils.utils import force_cpu_backend

        force_cpu_backend()
        cfg = compose([
            "exp=sac_decoupled", "env=dummy", "env.id=continuous_dummy",
            "env.max_episode_steps=16", "env.num_envs=4", "env.sync_env=True",
            "env.capture_video=False",
            "topology=sebulba", "topology.env_workers=2", "topology.segment_steps=4",
            "fabric.devices=2", "fabric.accelerator=cpu",
            "algo.per_rank_batch_size=8", "algo.learning_starts=16",
            "algo.total_steps=96", "algo.replay_ratio=0.5",
            "algo.mlp_keys.encoder=[state]", "algo.max_recompiles=1",
            "algo.run_test=False", "checkpoint.every=0", "checkpoint.save_last=False",
            "buffer.memmap=False", "buffer.size=256", "buffer.device=True",
            "metric.log_level=1", "metric.log_every=1", "print_config=False",
            f"log_dir={tmp_path}/logs",
        ])
        fabric = build_fabric(cfg)
        stats = run_sebulba(fabric, cfg)
        assert stats["updates"] > 0  # training windows actually ran
        assert stats["env_steps"] == 96
        assert stats["torn_rejected"] == 0


class TestChaosDrills:
    def test_killed_env_worker_respawned_run_completes(self, tmp_path):
        # the sebulba.env_worker crash drill: one worker dies mid-rollout;
        # the supervisor respawns it with fresh envs and the run completes
        # with the full env-step count and zero torn trajectories
        install_plan(FaultPlan.from_specs([
            {"site": "sebulba.env_worker", "kind": "raise", "at": 6, "max_fires": 1},
        ]))
        try:
            stats = _run_sebulba_ppo(tmp_path, extra=["algo.total_steps=96"])
        finally:
            clear_plan()
        assert stats["worker_restarts"] >= 1
        assert stats["updates"] == 6
        assert stats["env_steps"] == 96  # nothing torn, nothing lost
        assert stats["torn_rejected"] == 0

    def test_hung_env_worker_deposed_and_respawned(self, tmp_path):
        # the hang drill: a worker wedges (sleep past the heartbeat
        # deadline); the supervisor deposes it — the zombie can never push
        # again — and a respawn finishes the run
        install_plan(FaultPlan.from_specs([
            {"site": "sebulba.env_worker", "kind": "hang", "at": 6,
             "seconds": 6.0, "max_fires": 1},
        ]))
        try:
            stats = _run_sebulba_ppo(
                tmp_path,
                extra=["algo.total_steps=96", "topology.worker_deadline_s=1.0"],
            )
        finally:
            clear_plan()
        assert stats["worker_restarts"] >= 1
        assert stats["updates"] == 6
        assert stats["torn_rejected"] == 0


class TestPreemption:
    def test_sigterm_mid_run_drains_and_commits_final_save(self, tmp_path):
        # ISSUE 14 satellite: the sebulba path must honor the preemption
        # latch — a SIGTERM landing while the learner waits on the
        # trajectory queue (the drain loop polls the latch) must depose the
        # workers, exit through a final COMMITTED save, and return cleanly
        # instead of waiting out the queue timeout or dying uncommitted.
        import glob as _glob
        import os as _os
        import signal as _signal

        from sheeprl_tpu.checkpoint import PREEMPTION_GUARD
        from sheeprl_tpu.checkpoint.protocol import checkpoint_step
        from sheeprl_tpu.telemetry.spans import SPANS

        base_updates = SPANS.updates_done
        stop = threading.Event()

        def preempt_after_progress():
            # latch only once the learner has really trained (>=2 update
            # dispatches), so the drill exercises mid-run preemption, not
            # startup
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline and not stop.is_set():
                if SPANS.updates_done >= base_updates + 2:
                    _os.kill(_os.getpid(), _signal.SIGTERM)
                    return
                time.sleep(0.05)

        killer = threading.Thread(target=preempt_after_progress, daemon=True)
        killer.start()
        try:
            stats = _run_sebulba_ppo(
                tmp_path,
                extra=[
                    # long enough that only preemption can end the run early
                    "algo.total_steps=100000",
                    "checkpoint.every=0",
                    "checkpoint.save_last=True",
                ],
            )
        finally:
            stop.set()
            killer.join(5)
            PREEMPTION_GUARD.reset()
        # the run ended EARLY and cleanly (no queue-timeout, no crash)
        assert stats["updates"] < 100000 // 16
        assert stats["updates"] >= 2
        # ...and left a COMMITTED snapshot at a real (post-progress) step
        steps = [
            checkpoint_step(p)
            for p in _glob.glob(f"{tmp_path}/logs/**/checkpoint/step_*", recursive=True)
        ]
        committed = [s for s in steps if s >= 0]
        assert committed, f"no committed snapshot (found {steps})"
        assert max(committed) >= 16 * 2  # at least two rounds' progress
