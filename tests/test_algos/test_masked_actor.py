"""Mask-aware MineDojo action sampling (reference: MinedojoActor).

The masks arrive as float observations; sampling must give exactly zero
probability to excluded actions, and the argument branches must only be
constrained when the corresponding compound action was selected."""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import Actor
from sheeprl_tpu.envs.minedojo import (
    FN_CRAFT,
    FN_DESTROY,
    N_MOVEMENT_ACTIONS,
)

N_CRAFT, N_ITEMS = 5, 7
ACTIONS_DIM = (19, N_CRAFT, N_ITEMS)


def _actor_and_head(batch=64):
    actor = Actor(
        actions_dim=ACTIONS_DIM, is_continuous=False, dense_units=16,
        mlp_layers=1, act="silu", layer_norm=False, unimix=0.01,
        min_std=0.1, max_std=1.0, init_std=0.0, action_clip=1.0,
        dtype=jnp.float32,
    )
    latent = jnp.zeros((batch, 8))
    params = actor.init(jax.random.PRNGKey(0), latent)
    head = actor.apply(params, latent)
    return actor, head


def _split(sample):
    a0 = sample[..., :19]
    a1 = sample[..., 19:19 + N_CRAFT]
    a2 = sample[..., 19 + N_CRAFT:]
    return a0, a1, a2


def test_action_type_mask_zeroes_excluded():
    actor, head = _actor_and_head()
    mask_action = np.ones((64, 19), np.float32)
    mask_action[:, 12:] = 0.0  # no functional actions legal
    masks = {
        "mask_action_type": jnp.asarray(mask_action),
        "mask_craft_smelt": jnp.ones((64, N_CRAFT)),
        "mask_equip_place": jnp.ones((64, N_ITEMS)),
        "mask_destroy": jnp.ones((64, N_ITEMS)),
    }
    for seed in range(5):
        sample = actor.sample_masked(head, jax.random.PRNGKey(seed), masks)
        a0, _, _ = _split(np.asarray(sample))
        assert a0[:, 12:].sum() == 0.0  # excluded actions never sampled


def test_craft_mask_applies_only_on_craft_action():
    actor, head = _actor_and_head()
    craft_compound = N_MOVEMENT_ACTIONS + FN_CRAFT - 1
    # force the craft compound action via the action-type mask
    mask_action = np.zeros((64, 19), np.float32)
    mask_action[:, craft_compound] = 1.0
    craft_mask = np.zeros((64, N_CRAFT), np.float32)
    craft_mask[:, 2] = 1.0  # only item 2 craftable
    masks = {
        "mask_action_type": jnp.asarray(mask_action),
        "mask_craft_smelt": jnp.asarray(craft_mask),
        "mask_equip_place": jnp.ones((64, N_ITEMS)),
        "mask_destroy": jnp.ones((64, N_ITEMS)),
    }
    sample = actor.sample_masked(head, jax.random.PRNGKey(1), masks)
    a0, a1, _ = _split(np.asarray(sample))
    assert (a0.argmax(-1) == craft_compound).all()
    assert (a1.argmax(-1) == 2).all()

    # with a movement action forced instead, the craft arg is unconstrained
    mask_action = np.zeros((64, 19), np.float32)
    mask_action[:, 1] = 1.0  # forward only
    masks["mask_action_type"] = jnp.asarray(mask_action)
    sample = actor.sample_masked(head, jax.random.PRNGKey(2), masks)
    _, a1, _ = _split(np.asarray(sample))
    assert len(np.unique(a1.argmax(-1))) > 1  # not pinned to item 2


def test_destroy_mask_constrains_inventory_arg():
    actor, head = _actor_and_head()
    destroy_compound = N_MOVEMENT_ACTIONS + FN_DESTROY - 1
    mask_action = np.zeros((64, 19), np.float32)
    mask_action[:, destroy_compound] = 1.0
    destroy_mask = np.zeros((64, N_ITEMS), np.float32)
    destroy_mask[:, 4] = 1.0
    masks = {
        "mask_action_type": jnp.asarray(mask_action),
        "mask_craft_smelt": jnp.ones((64, N_CRAFT)),
        "mask_equip_place": jnp.zeros((64, N_ITEMS)),  # irrelevant for destroy
        "mask_destroy": jnp.asarray(destroy_mask),
    }
    sample = actor.sample_masked(head, jax.random.PRNGKey(3), masks)
    _, _, a2 = _split(np.asarray(sample))
    assert (a2.argmax(-1) == 4).all()


def test_greedy_masked_mode():
    actor, head = _actor_and_head(batch=4)
    mask_action = np.ones((4, 19), np.float32)
    masks = {
        "mask_action_type": jnp.asarray(mask_action),
        "mask_craft_smelt": jnp.ones((4, N_CRAFT)),
        "mask_equip_place": jnp.ones((4, N_ITEMS)),
        "mask_destroy": jnp.ones((4, N_ITEMS)),
    }
    s1 = actor.sample_masked(head, jax.random.PRNGKey(0), masks, greedy=True)
    s2 = actor.sample_masked(head, jax.random.PRNGKey(9), masks, greedy=True)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))  # key-independent
