"""Anakin fused rollouts (envs/jax/anakin.py + ppo/a2c integration).

The contract under test (ISSUE 11 acceptance):

* 50 fused rollout iterations reuse ONE compiled executable — env state,
  episode accounting and the update counter are device data, not
  signature.
* PPO/A2C on ``env=jax_cartpole`` train multi-window runs end-to-end
  through the CLI with the transfer guard armed over every post-warmup
  window and ``algo.max_recompiles=1`` — a fused path that ships
  anything H2D in steady state, or churns executable signatures, dies
  here red.
* ``algo.anakin`` mode resolution (auto / forced / disabled) behaves.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sheeprl_tpu.cli import run
from sheeprl_tpu.envs.jax.cartpole import JaxCartPole
from sheeprl_tpu.envs.jax.core import VectorJaxEnv
from sheeprl_tpu.envs.jax.registry import anakin_enabled
from sheeprl_tpu.parallel.fabric import Fabric


def _anakin_args(tmp_path, exp, extra=()):
    return [
        f"exp={exp}",
        "env=jax_cartpole",
        "env.num_envs=2",
        "env.capture_video=False",
        "fabric.devices=1",
        "fabric.accelerator=cpu",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=8",
        "algo.total_steps=48",  # 3 fused windows: guard arms from window 2
        "algo.mlp_keys.encoder=[state]",
        "algo.max_recompiles=1",
        "buffer.transfer_guard=True",
        "metric.log_level=1",
        "metric.log_every=1",
        "checkpoint.every=0",
        "checkpoint.save_last=False",
        "buffer.memmap=False",
        f"log_dir={tmp_path}/logs",
        "print_config=False",
        "algo.run_test=False",
        *extra,
    ]


class TestFusedExecutableReuse:
    def test_cache_size_one_across_50_rollout_iterations(self):
        from sheeprl_tpu.algos.ppo.agent import sample_actions
        from sheeprl_tpu.envs.jax.anakin import init_actor_state, make_rollout_fn

        fabric = Fabric(devices=1, accelerator="cpu")
        venv = VectorJaxEnv(JaxCartPole(), 4)

        def apply(p, obs):
            h = obs["state"] @ p["w"]
            return h[:, :2], h[:, 2:3]

        def sample(out, k):
            return sample_actions(out, (2,), False, k)

        rollout_fn = make_rollout_fn(
            venv, apply, sample,
            cnn_keys=(), mlp_keys=("state",),
            action_space=venv.single_action_space,
            gamma=0.99, rollout_steps=5,
        )

        def fused(p, actor, k):
            k_roll, k_next = jax.random.split(k)
            actor, rollout, last_obs, stats = rollout_fn(p, actor, k_roll)
            # a stand-in "train": fold the rollout into a param delta so
            # params depend on the whole fused trajectory
            delta = jnp.mean(rollout["state"]) + jnp.mean(rollout["rewards"])
            return {"w": p["w"] + 0.0 * delta}, actor, k_next, stats

        fused = fabric.compile(fused, name="test.anakin_fused", donate_argnums=(1,))
        params = {"w": jnp.zeros((4, 3), jnp.float32)}
        actor = init_actor_state(fabric, venv, jax.random.PRNGKey(0), 0, sharded=True)
        key = jax.random.PRNGKey(1)
        for i in range(50):
            params, actor, key, stats = fused(params, actor, key)
        assert fused.cache_size() == 1
        assert int(np.asarray(actor["update"])) == 50
        # episodes completed and were accounted during the 250 fused steps
        assert np.asarray(stats["ep_done"]).dtype == np.bool_

    def test_rollout_layout_matches_train_contract(self):
        from sheeprl_tpu.algos.ppo.agent import sample_actions
        from sheeprl_tpu.envs.jax.anakin import init_actor_state, make_rollout_fn

        fabric = Fabric(devices=1, accelerator="cpu")
        venv = VectorJaxEnv(JaxCartPole(), 3)

        def apply(p, obs):
            h = obs["state"] @ p["w"]
            return h[:, :2], h[:, 2:3]

        rollout_fn = make_rollout_fn(
            venv, apply, lambda out, k: sample_actions(out, (2,), False, k),
            cnn_keys=(), mlp_keys=("state",),
            action_space=venv.single_action_space,
            gamma=0.99, rollout_steps=7,
        )
        actor = init_actor_state(fabric, venv, jax.random.PRNGKey(0), 0, sharded=True)
        params = {"w": jnp.zeros((4, 3), jnp.float32)}
        actor2, rollout, last_obs, stats = jax.jit(rollout_fn)(
            params, actor, jax.random.PRNGKey(2)
        )
        # (T, B, *) layout, float obs, storage-format actions — exactly what
        # the on-policy train phases consume from the host staging path
        assert rollout["state"].shape == (7, 3, 4) and rollout["state"].dtype == jnp.float32
        assert rollout["actions"].shape == (7, 3, 1)
        assert rollout["logprobs"].shape == (7, 3)
        assert rollout["rewards"].shape == (7, 3)
        assert rollout["dones"].shape == (7, 3) and rollout["dones"].dtype == jnp.float32
        assert last_obs["state"].shape == (3, 4)
        assert int(np.asarray(actor2["update"])) == 1


class TestAnakinEndToEnd:
    def test_ppo_multiwindow_guarded(self, tmp_path):
        run(_anakin_args(tmp_path, "ppo", extra=["algo.update_epochs=1"]))

    def test_a2c_multiwindow_guarded_annealed(self, tmp_path):
        run(_anakin_args(tmp_path, "a2c", extra=["algo.anneal_lr=True"]))

    def test_ppo_recurrent_multiwindow_guarded(self, tmp_path):
        # ISSUE 12 satellite (ROADMAP item 5 remaining): the nn.scan LSTM
        # policy fused into the rollout scan — recurrent state, prev-action
        # encoding and episode-start mask all live in the donated carry, so
        # the armed guard + compile budget prove zero steady-state H2D
        run(
            _anakin_args(
                tmp_path, "ppo_recurrent",
                extra=[
                    "env.mask_velocities=False",
                    "algo.update_epochs=1",
                    "algo.per_rank_sequence_length=4",
                    "algo.anneal_lr=True",
                    "algo.anneal_ent_coef=True",
                ],
            )
        )

    def test_ppo_recurrent_adapter_fallback_when_disabled(self, tmp_path):
        run(
            _anakin_args(
                tmp_path, "ppo_recurrent",
                extra=[
                    "env.mask_velocities=False",
                    "algo.update_epochs=1",
                    "algo.per_rank_sequence_length=4",
                    "algo.anakin=False",
                    "dry_run=True",
                ],
            )
        )

    def test_ppo_adapter_fallback_when_disabled(self, tmp_path):
        # algo.anakin=False: same jax env through JaxToGymAdapter +
        # vector-env machinery (guard still green: staging is explicit)
        run(
            _anakin_args(tmp_path, "ppo", extra=["algo.anakin=False", "dry_run=True"])
        )


class TestModeResolution:
    def _cfg(self, overrides=()):
        from sheeprl_tpu.config.compose import compose

        return compose(["exp=ppo", "algo.mlp_keys.encoder=[state]", *overrides])

    def test_auto_on_jax_env_single_process(self):
        fabric = Fabric(devices=1, accelerator="cpu")
        assert anakin_enabled(self._cfg(["env=jax_cartpole"]), fabric)

    def test_auto_off_on_gym_env(self):
        fabric = Fabric(devices=1, accelerator="cpu")
        assert not anakin_enabled(self._cfg(["env=gym"]), fabric)

    def test_forced_on_non_jax_env_raises(self):
        fabric = Fabric(devices=1, accelerator="cpu")
        with pytest.raises(ValueError, match="anakin"):
            anakin_enabled(self._cfg(["env=gym", "algo.anakin=True"]), fabric)

    def test_disabled_wins(self):
        fabric = Fabric(devices=1, accelerator="cpu")
        assert not anakin_enabled(
            self._cfg(["env=jax_cartpole", "algo.anakin=False"]), fabric
        )
