"""`algo.train_window_iters`: the scanned SAC train window (round-4 perf work).

K > 1 accrues the Ratio-owed gradient steps over K env iterations and runs
them as one scanned dispatch.  The update COUNT must be preserved exactly —
the replay-ratio contract (reference: sheeprl Ratio semantics) is what makes
the workload comparable across K.
"""

import csv
from pathlib import Path

import pytest

from sheeprl_tpu.cli import run


def _run_sac(tmp_path, window: int, steps: int = 512):
    log_dir = tmp_path / f"w{window}"
    run(
        [
            "exp=sac",
            "env=dummy",
            "env.id=continuous_dummy",
            f"algo.train_window_iters={window}",
            f"algo.total_steps={steps}",
            "algo.learning_starts=8",
            "algo.per_rank_batch_size=16",
            "algo.hidden_size=16",
            "algo.mlp_keys.encoder=[state]",
            "seed=3",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "env.max_episode_steps=16",
            "fabric.devices=1",
            "fabric.accelerator=cpu",
            "metric.log_level=1",
            "metric.log_every=1000000",  # only the final flush
            "metric/logger=csv",
            "checkpoint.every=0",
            "checkpoint.save_last=False",
            "buffer.memmap=False",
            "buffer.size=1000",
            "algo.run_test=False",
            "print_config=False",
            f"log_dir={log_dir}",
        ]
    )
    out = {}
    for p in sorted(Path(log_dir).glob("**/metrics.csv")):
        with open(p) as f:
            for row in csv.DictReader(f):
                out[row["name"]] = float(row["value"])
    return out


@pytest.mark.parametrize("window", [4, 7])
def test_windowed_sac_preserves_gradient_step_count(tmp_path, window):
    base = _run_sac(tmp_path / "base", 1)
    windowed = _run_sac(tmp_path / "win", window)
    # Params/replay_ratio = grad_steps * world / policy_steps — the Ratio
    # contract must hold regardless of windowing (incl. the final partial
    # window flushed at the last iteration)
    assert base["Params/replay_ratio"] == pytest.approx(1.0, abs=0.1)
    assert windowed["Params/replay_ratio"] == pytest.approx(
        base["Params/replay_ratio"], abs=1e-6
    ), "windowing changed the number of gradient updates"
    for k in ("Loss/value_loss", "Loss/policy_loss"):
        assert k in windowed
