"""DeviceReplay ring primitives at explicit coordinates + the mirror shim.

Migrated off the PR 9 deprecation shims (ISSUE 11 satellite): the parity
law the old ``DeviceMirror`` tests pinned — a device gather at
host-sampled ring coordinates is bit-identical to the host ring's fancy
indexing — is a property of ``DeviceReplay.write_at``/``gather_at``, and
is asserted on that API directly.  The ``attach_mirror`` /
``maybe_attach_mirror`` shims exist ONLY for external callers now; one
compat test per shim pins that they still honor the old contract (and
warn).  The old ``device_mirror`` True/False e2e equivalence runs became
vacuous when the loops stopped reading ``buffer.device_mirror`` — the live
e2e coverage of the device-resident dataflow is
``tests/test_data/test_device_replay_e2e.py`` and run_ci stage 9.
"""

import numpy as np
import pytest

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, ReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.device_replay import DeviceReplay


def _frame(t, e, hw=8):
    return np.full((hw, hw, 3), (t * 7 + e * 31) % 256, np.uint8)


class _HostRing:
    """Reference host ring writing the same explicit slots."""

    def __init__(self, size, n_envs, hw=8):
        self.buf = np.zeros((size, n_envs, hw, hw, 3), np.uint8)
        self.size = size

    def write(self, rows, time_pos, env_cols):
        for i, e in enumerate(env_cols):
            self.buf[np.asarray(time_pos)[:, i], e] = rows[:, i]

    def gather(self, t_idx, e_idx):
        return self.buf[np.asarray(t_idx), np.asarray(e_idx)]


# --------------------------------------------------------------------------
# write_at/gather_at parity at explicit coordinates (the mirror law)
# --------------------------------------------------------------------------

class TestRingPrimitivesParity:
    def _pair(self, size=8, n_envs=2):
        return DeviceReplay(size, n_envs), _HostRing(size, n_envs)

    def test_basic_write_gather(self):
        dev, host = self._pair(size=16)
        rng = np.random.default_rng(3)
        for t in range(10):
            rows = np.stack([[_frame(t, e)] for e in range(2)], axis=1).reshape(1, 2, 8, 8, 3)
            pos = np.full((1, 2), t % 16)
            dev.write_at("rgb", rows, pos, [0, 1])
            host.write(rows, pos, [0, 1])
        t_idx = rng.integers(0, 10, (3, 4, 2))
        e_idx = rng.integers(0, 2, (3, 4, 2))
        np.testing.assert_array_equal(
            np.asarray(dev.gather_at("rgb", t_idx, e_idx)), host.gather(t_idx, e_idx)
        )

    def test_wraparound(self):
        dev, host = self._pair(size=8)
        rng = np.random.default_rng(4)
        for t in range(37):  # several full wraps of the size-8 ring
            rows = np.stack([[_frame(t, e)] for e in range(2)], axis=1).reshape(1, 2, 8, 8, 3)
            pos = np.full((1, 2), t % 8)
            dev.write_at("rgb", rows, pos, [0, 1])
            host.write(rows, pos, [0, 1])
        t_idx = rng.integers(0, 8, (2, 3, 4))
        e_idx = rng.integers(0, 2, (2, 3, 4))
        np.testing.assert_array_equal(
            np.asarray(dev.gather_at("rgb", t_idx, e_idx)), host.gather(t_idx, e_idx)
        )

    def test_divergent_env_streams(self):
        # per-env write heads: one column runs ahead (the reset-row case)
        dev, host = self._pair(size=12)
        rng = np.random.default_rng(5)
        pos_per_env = [0, 0]
        for t in range(9):
            for e in range(2):
                extra = 1 if (e == 1 and t % 3 == 0) else 0
                for rep in range(1 + extra):
                    rows = _frame(t * 10 + rep, e)[None, None]
                    dev.write_at("rgb", rows, np.full((1, 1), pos_per_env[e] % 12), [e])
                    host.write(rows, np.full((1, 1), pos_per_env[e] % 12), [e])
                    pos_per_env[e] += 1
        assert pos_per_env[0] != pos_per_env[1]
        t_idx = rng.integers(0, 9, (4, 3))
        e_idx = rng.integers(0, 2, (4, 3))
        np.testing.assert_array_equal(
            np.asarray(dev.gather_at("rgb", t_idx, e_idx)), host.gather(t_idx, e_idx)
        )

    def test_multi_key_rings(self):
        dev, host_a = self._pair(size=8)
        host_b = _HostRing(8, 2)
        for t in range(6):
            rows = np.stack([[_frame(t, e)] for e in range(2)], axis=1).reshape(1, 2, 8, 8, 3)
            pos = np.full((1, 2), t)
            dev.write_at("rgb", rows, pos, [0, 1])
            dev.write_at("next_rgb", rows + 1, pos, [0, 1])
            host_a.write(rows, pos, [0, 1])
            host_b.write(rows + 1, pos, [0, 1])
        t_idx = np.arange(6).reshape(2, 3)
        e_idx = np.zeros((2, 3), int)
        np.testing.assert_array_equal(np.asarray(dev.gather_at("rgb", t_idx, e_idx)), host_a.gather(t_idx, e_idx))
        np.testing.assert_array_equal(np.asarray(dev.gather_at("next_rgb", t_idx, e_idx)), host_b.gather(t_idx, e_idx))


# --------------------------------------------------------------------------
# host-buffer-driven parity: the ring the SHIM used to sync, exercised
# through DeviceReplay directly via the buffers' sample-index tracking
# --------------------------------------------------------------------------

def _seq_step(t, n_envs=2, hw=8):
    rgb = np.zeros((1, n_envs, hw, hw, 3), np.uint8)
    for e in range(n_envs):
        rgb[0, e] = (t * 7 + e * 31) % 256
    return {"rgb": rgb, "rewards": np.full((1, n_envs), float(t), np.float32)}


class TestHostSampledGather:
    def test_sequential_sample_indices_gather(self):
        """Sample on the host ring, gather the SAME draw on device through
        write_at/gather_at — bit-identical pixels (no shim in the loop)."""
        np.random.seed(3)
        rb = EnvIndependentReplayBuffer(16, n_envs=2, buffer_cls=SequentialReplayBuffer)
        dev = DeviceReplay(16, 2)
        for t in range(10):
            step = _seq_step(t)
            rb.add(step)
            dev.write_at("rgb", step["rgb"], np.full((1, 2), t % 16), [0, 1])
        state = np.random.get_state()
        host = rb.sample(3, n_samples=2, sequence_length=4)
        np.random.set_state(state)
        rb.sample(3, n_samples=2, sequence_length=4, keys=("rewards",), track_indices=True)
        t_idx, e_idx = rb.last_sample_indices
        np.testing.assert_array_equal(
            np.asarray(dev.gather_at("rgb", t_idx, e_idx)), host["rgb"]
        )

    def test_track_indices_rejects_non_sequential_sub_buffers(self):
        # uniform sub-buffers never record their drawn ring slots — the
        # flag must fail loudly, not AttributeError mid-sample
        rb = EnvIndependentReplayBuffer(8, n_envs=2, buffer_cls=ReplayBuffer)
        rb.add({"obs": np.zeros((1, 2, 3), np.float32)})
        with pytest.raises(ValueError, match="track_indices"):
            rb.sample(3, track_indices=True)

    def test_uniform_sample_indices_gather(self):
        np.random.seed(11)
        rb = ReplayBuffer(16, n_envs=2)
        dev = DeviceReplay(16, 2)
        for t in range(10):
            step = _seq_step(t)
            rb.add(step)
            dev.write_at("rgb", step["rgb"], np.full((1, 2), t % 16), [0, 1])
        state = np.random.get_state()
        host = rb.sample(4, n_samples=3)
        np.random.set_state(state)
        rb.sample(4, n_samples=3, keys=("rewards",), track_indices=True)
        t_idx, e_idx = rb.last_sample_indices
        np.testing.assert_array_equal(
            np.asarray(dev.gather_at("rgb", t_idx, e_idx)), host["rgb"]
        )


# --------------------------------------------------------------------------
# shim compat: external callers of the deprecated surface keep working
# --------------------------------------------------------------------------

class TestDeprecatedShims:
    def test_attach_mirror_warns_and_keeps_contract(self):
        np.random.seed(7)
        rb = EnvIndependentReplayBuffer(8, n_envs=2, buffer_cls=SequentialReplayBuffer)
        for t in range(13):  # includes a pre-attach wrap (attach-time sync)
            rb.add(_seq_step(t))
        with pytest.warns(DeprecationWarning, match="attach_mirror is deprecated"):
            rb.attach_mirror(["rgb"])
        state = np.random.get_state()
        host = rb.sample(3, n_samples=2, sequence_length=3)
        np.random.set_state(state)
        rb.sample(3, n_samples=2, sequence_length=3, keys=("rewards",))
        t_idx, e_idx = rb.last_sample_indices
        np.testing.assert_array_equal(
            np.asarray(rb.mirror.gather("rgb", t_idx, e_idx)), host["rgb"]
        )

    def test_attach_requires_sequential_sub_buffers(self):
        # rejected before the shim constructs (so no deprecation warning)
        rb = EnvIndependentReplayBuffer(8, n_envs=2, buffer_cls=ReplayBuffer)
        with pytest.raises(ValueError):
            rb.attach_mirror(["rgb"])

    def test_maybe_attach_mirror_policy(self, monkeypatch):
        from sheeprl_tpu.data.buffers import maybe_attach_mirror

        class _Cfg(dict):
            __getattr__ = dict.__getitem__

        def cfg(value):
            return _Cfg(buffer=_Cfg({"device_mirror": value}))

        import gymnasium as gym

        space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (8, 8, 3), np.uint8)})
        monkeypatch.delenv("SHEEPRL_MIRROR_BUDGET_BYTES", raising=False)
        rb = EnvIndependentReplayBuffer(8, n_envs=2, buffer_cls=SequentialReplayBuffer)
        # auto + cpu -> off; auto + tpu -> on; explicit False -> off
        assert not maybe_attach_mirror(rb, cfg("auto"), "cpu", space, ("rgb",))
        with pytest.warns(DeprecationWarning):
            assert maybe_attach_mirror(rb, cfg("auto"), "tpu", space, ("rgb",))
        rb2 = EnvIndependentReplayBuffer(8, n_envs=2, buffer_cls=SequentialReplayBuffer)
        assert not maybe_attach_mirror(rb2, cfg(False), "tpu", space, ("rgb",))
        # budget refusal path
        monkeypatch.setenv("SHEEPRL_MIRROR_BUDGET_BYTES", "100")
        rb3 = EnvIndependentReplayBuffer(8, n_envs=2, buffer_cls=SequentialReplayBuffer)
        assert not maybe_attach_mirror(rb3, cfg(True), "tpu", space, ("rgb",))
