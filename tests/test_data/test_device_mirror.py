"""DeviceMirror: device-gathered pixel sequences == host-sampled ones.

The mirror (data/buffers.py:DeviceMirror) keeps a device-resident uint8
ring of the pixel keys and gathers sampled sequences on device, so pixel
blocks never cross the host->device link during training.  Correctness
contract: for the SAME host sampling draw, the mirror gather must be
bit-identical to the host gather — these tests drive wrap-around,
divergent per-env streams (reset rows via ``indices=``), attach-time
sync of pre-filled rings, and checkpoint-resume resync.
"""

import numpy as np
import pytest

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer


def _step(t, n_envs=2, hw=8):
    """Deterministic, distinguishable frame content per (t, env)."""
    rgb = np.zeros((1, n_envs, hw, hw, 3), np.uint8)
    for e in range(n_envs):
        rgb[0, e] = (t * 7 + e * 31) % 256
    return {
        "rgb": rgb,
        "rewards": np.full((1, n_envs), float(t), np.float32),
    }


def _mk(size=16, n_envs=2):
    rb = EnvIndependentReplayBuffer(size, n_envs=n_envs, buffer_cls=SequentialReplayBuffer)
    rb.attach_mirror(["rgb"])
    return rb


def _assert_mirror_matches(rb, batch_size=3, n_samples=2, seq_len=4):
    state = np.random.get_state()
    host = rb.sample(batch_size, n_samples=n_samples, sequence_length=seq_len)
    np.random.set_state(state)
    rb.sample(
        batch_size, n_samples=n_samples, sequence_length=seq_len, keys=("rewards",)
    )
    t_idx, e_idx = rb.last_sample_indices
    got = np.asarray(rb.mirror.gather("rgb", t_idx, e_idx))
    np.testing.assert_array_equal(got, host["rgb"])


def test_mirror_matches_host_basic():
    np.random.seed(3)
    rb = _mk()
    for t in range(10):
        rb.add(_step(t))
    _assert_mirror_matches(rb)


def test_mirror_matches_after_wraparound():
    np.random.seed(4)
    rb = _mk(size=8)
    for t in range(37):  # several full wraps of the size-8 ring
        rb.add(_step(t))
    _assert_mirror_matches(rb, seq_len=3)


def test_mirror_matches_with_divergent_env_streams():
    """Reset rows (``indices=[e]``) advance one env's ring ahead of the
    other — the mirror must track per-env write positions."""
    np.random.seed(5)
    rb = _mk(size=12)
    for t in range(9):
        rb.add(_step(t))
        if t % 3 == 0:  # extra row for env 1 only
            rb.add({k: v[:, 1:2] for k, v in _step(100 + t).items()}, indices=[1])
    assert len(rb.buffer[0]) != len(rb.buffer[1])
    _assert_mirror_matches(rb, seq_len=3)


def test_attach_syncs_prefilled_ring():
    np.random.seed(6)
    rb = EnvIndependentReplayBuffer(8, n_envs=2, buffer_cls=SequentialReplayBuffer)
    for t in range(13):  # includes a wrap before the mirror exists
        rb.add(_step(t))
    rb.attach_mirror(["rgb"])
    _assert_mirror_matches(rb, seq_len=3)


def test_resume_resyncs_mirror():
    np.random.seed(7)
    rb = _mk(size=8)
    for t in range(6):
        rb.add(_step(t))
    state = rb.state_dict()
    rb2 = _mk(size=8)
    rb2.load_state_dict(state)
    _assert_mirror_matches(rb2, seq_len=3)


def test_attach_requires_sequential_sub_buffers():
    from sheeprl_tpu.data.buffers import ReplayBuffer

    rb = EnvIndependentReplayBuffer(8, n_envs=2, buffer_cls=ReplayBuffer)
    with pytest.raises(ValueError):
        rb.attach_mirror(["rgb"])


@pytest.mark.slow
def test_dreamer_e2e_mirror_equivalence(tmp_path):
    """Full DV3-XS dry run with the mirror ON equals the host-ship path
    bit-for-bit: same RNG draws (the keys filter does not change the
    sampling stream), same pixel bytes (gathered on device vs shipped),
    so identical losses."""
    from tests.test_regression.test_golden import COMMON, FAMILIES, _last_metrics
    from sheeprl_tpu.cli import run

    results = {}
    for mirror in ("False", "True"):
        logs = tmp_path / f"mirror_{mirror}"
        run(
            COMMON
            + FAMILIES["dreamer_v3"]
            + [f"buffer.device_mirror={mirror}", f"log_dir={logs}"]
        )
        results[mirror] = _last_metrics(logs)
    assert results["False"] and results["False"] == results["True"]


# ---- base ReplayBuffer mirror (SAC-AE layout: stored next_<k> rows) ----


def _uniform_step(t, n_envs=2, hw=8):
    rgb = np.zeros((1, n_envs, hw, hw, 3), np.uint8)
    nxt = np.zeros((1, n_envs, hw, hw, 3), np.uint8)
    for e in range(n_envs):
        rgb[0, e] = (t * 5 + e * 17) % 256
        nxt[0, e] = (t * 5 + e * 17 + 1) % 256
    return {
        "rgb": rgb,
        "next_rgb": nxt,
        "rewards": np.full((1, n_envs), float(t), np.float32),
    }


def _assert_uniform_mirror_matches(rb, batch_size=4, n_samples=3):
    state = np.random.get_state()
    host = rb.sample(batch_size, n_samples=n_samples)
    np.random.set_state(state)
    rb.sample(batch_size, n_samples=n_samples, keys=("rewards",))
    t_idx, e_idx = rb.last_sample_indices
    for k in ("rgb", "next_rgb"):
        got = np.asarray(rb.mirror.gather(k, t_idx, e_idx))
        np.testing.assert_array_equal(got, host[k])


def test_uniform_mirror_matches_host():
    from sheeprl_tpu.data.buffers import ReplayBuffer

    np.random.seed(11)
    rb = ReplayBuffer(16, n_envs=2)
    rb.attach_mirror(["rgb", "next_rgb"])
    for t in range(10):
        rb.add(_uniform_step(t))
    _assert_uniform_mirror_matches(rb)


def test_uniform_mirror_wraparound_and_prefill_sync():
    from sheeprl_tpu.data.buffers import ReplayBuffer

    np.random.seed(12)
    rb = ReplayBuffer(8, n_envs=2)
    for t in range(11):  # wrap before the mirror exists
        rb.add(_uniform_step(t))
    rb.attach_mirror(["rgb", "next_rgb"])
    for t in range(11, 30):  # and after
        rb.add(_uniform_step(t))
    _assert_uniform_mirror_matches(rb)


def test_uniform_mirror_resume_resync():
    from sheeprl_tpu.data.buffers import ReplayBuffer

    np.random.seed(13)
    rb = ReplayBuffer(8, n_envs=2)
    rb.attach_mirror(["rgb", "next_rgb"])
    for t in range(6):
        rb.add(_uniform_step(t))
    rb2 = ReplayBuffer(8, n_envs=2)
    rb2.attach_mirror(["rgb", "next_rgb"])
    rb2.load_state_dict(rb.state_dict())
    _assert_uniform_mirror_matches(rb2)


@pytest.mark.slow
@pytest.mark.parametrize("frame_stack", [1, 2])
def test_sac_ae_e2e_mirror_equivalence(tmp_path, frame_stack):
    """SAC-AE dry run with the mirror ON equals the host-ship path
    bit-for-bit (same draws, same bytes).  ``frame_stack=2`` covers the
    stacked-pixels layout: the host-ship path merges the (U, B, S, H, W, C)
    sample with ``ndim >= 6`` (a ``== 7`` guard used to never fire there,
    feeding the encoder unmerged stacks only on the host path)."""
    from tests.test_regression.test_golden import COMMON, FAMILIES, _last_metrics
    from sheeprl_tpu.cli import run

    results = {}
    for mirror in ("False", "True"):
        logs = tmp_path / f"mirror_{mirror}"
        run(
            COMMON
            + FAMILIES["sac_ae"]
            + [
                f"env.frame_stack={frame_stack}",
                f"buffer.device_mirror={mirror}",
                f"log_dir={logs}",
            ]
        )
        results[mirror] = _last_metrics(logs)
    assert results["False"] and results["False"] == results["True"]


# ---- maybe_attach_mirror policy ----


class _Cfg(dict):
    __getattr__ = dict.__getitem__


def _cfg(value):
    return _Cfg(buffer=_Cfg({"device_mirror": value}))


def _obs_space():
    import gymnasium as gym

    return gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (8, 8, 3), np.uint8)})


def test_maybe_attach_auto_resolution(monkeypatch):
    from sheeprl_tpu.data.buffers import maybe_attach_mirror

    monkeypatch.delenv("SHEEPRL_MIRROR_BUDGET_BYTES", raising=False)
    rb = EnvIndependentReplayBuffer(8, n_envs=2, buffer_cls=SequentialReplayBuffer)
    # auto + cpu accelerator -> off
    assert not maybe_attach_mirror(rb, _cfg("auto"), "cpu", _obs_space(), ("rgb",))
    assert rb.mirror is None
    # auto + tpu accelerator -> on
    assert maybe_attach_mirror(rb, _cfg("auto"), "tpu", _obs_space(), ("rgb",))
    assert rb.mirror is not None
    # explicit False -> off even on tpu
    rb2 = EnvIndependentReplayBuffer(8, n_envs=2, buffer_cls=SequentialReplayBuffer)
    assert not maybe_attach_mirror(rb2, _cfg(False), "tpu", _obs_space(), ("rgb",))


def test_maybe_attach_budget_refusal(monkeypatch, capsys):
    from sheeprl_tpu.data.buffers import maybe_attach_mirror

    rb = EnvIndependentReplayBuffer(8, n_envs=2, buffer_cls=SequentialReplayBuffer)
    monkeypatch.setenv("SHEEPRL_MIRROR_BUDGET_BYTES", "100")  # ring needs 3072 B
    assert not maybe_attach_mirror(rb, _cfg(True), "tpu", _obs_space(), ("rgb",))
    assert rb.mirror is None
    assert "device_mirror disabled" in capsys.readouterr().out


def test_maybe_attach_no_cnn_keys():
    from sheeprl_tpu.data.buffers import maybe_attach_mirror

    rb = EnvIndependentReplayBuffer(8, n_envs=2, buffer_cls=SequentialReplayBuffer)
    assert not maybe_attach_mirror(rb, _cfg(True), "tpu", _obs_space(), ())
