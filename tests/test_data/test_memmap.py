import pickle

import numpy as np

from sheeprl_tpu.data.memmap import MemmapArray


def test_from_array_roundtrip(tmp_path):
    src = np.arange(24, dtype=np.float32).reshape(4, 6)
    m = MemmapArray.from_array(src, filename=tmp_path / "a.memmap")
    assert np.array_equal(np.asarray(m), src)
    assert m.shape == (4, 6) and m.dtype == np.float32


def test_setitem_persists(tmp_path):
    m = MemmapArray((4, 2), np.float32, filename=tmp_path / "b.memmap")
    m[1] = 7.0
    m.flush()
    m2 = MemmapArray((4, 2), np.float32, filename=tmp_path / "b.memmap")
    assert np.all(m2[1] == 7.0)


def test_pickle_reopens_map(tmp_path):
    m = MemmapArray.from_array(np.ones((3, 3)), filename=tmp_path / "c.memmap")
    m2 = pickle.loads(pickle.dumps(m))
    assert np.array_equal(np.asarray(m2), np.ones((3, 3)))
    m2[0, 0] = 5
    assert m[0, 0] == 5  # same backing file


def test_ufunc_and_len(tmp_path):
    m = MemmapArray.from_array(np.full((5,), 2.0), filename=tmp_path / "d.memmap")
    assert len(m) == 5
    assert np.all((m + 1) == 3.0)


def test_close_without_delete(tmp_path):
    m = MemmapArray.from_array(np.zeros((2,)), filename=tmp_path / "e.memmap")
    m.close(delete_file=False)
    assert (tmp_path / "e.memmap").exists()


def test_anonymous_tempfile_cleanup():
    m = MemmapArray((4,), np.float32)
    path = m.filename
    m.close()  # owner → deletes
    import os

    assert not os.path.exists(path)
