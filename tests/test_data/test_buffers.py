import numpy as np
import pytest

from sheeprl_tpu.data.buffers import (
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
)


def make_step(t, n_envs=2, obs_dim=3):
    return {
        "obs": np.full((1, n_envs, obs_dim), t, dtype=np.float32),
        "actions": np.full((1, n_envs, 1), t, dtype=np.float32),
        "rewards": np.full((1, n_envs, 1), t, dtype=np.float32),
        "dones": np.zeros((1, n_envs, 1), dtype=np.float32),
    }


class TestReplayBuffer:
    def test_add_and_len(self):
        rb = ReplayBuffer(8, n_envs=2)
        for t in range(5):
            rb.add(make_step(t))
        assert len(rb) == 5 and not rb.full

    def test_ring_wraparound(self):
        rb = ReplayBuffer(4, n_envs=2)
        for t in range(6):
            rb.add(make_step(t))
        assert rb.full and len(rb) == 4
        # oldest remaining value is t=2
        assert rb["obs"].min() == 2

    def test_multi_step_add(self):
        rb = ReplayBuffer(10, n_envs=2)
        data = {k: np.concatenate([make_step(t)[k] for t in range(3)]) for k in make_step(0)}
        rb.add(data)
        assert len(rb) == 3

    def test_oversized_add_keeps_tail(self):
        rb = ReplayBuffer(4, n_envs=2)
        data = {k: np.concatenate([make_step(t)[k] for t in range(7)]) for k in make_step(0)}
        rb.add(data)
        assert rb.full
        assert rb["obs"].min() == 3

    def test_sample_shapes(self):
        rb = ReplayBuffer(16, n_envs=2)
        for t in range(10):
            rb.add(make_step(t))
        batch = rb.sample(6, n_samples=3)
        assert batch["obs"].shape == (3, 6, 3)
        assert batch["rewards"].shape == (3, 6, 1)

    def test_sample_next_obs_excludes_write_head(self):
        rb = ReplayBuffer(4, n_envs=1, obs_keys=("obs",))
        for t in range(6):
            rb.add(make_step(t, n_envs=1))
        batch = rb.sample(64, sample_next_obs=True)
        # successor of value v must always be v+1 (never the wrap to oldest)
        assert np.all(batch["next_obs"] - batch["obs"] == 1)

    def test_sample_empty_raises(self):
        rb = ReplayBuffer(4)
        with pytest.raises(RuntimeError):
            rb.sample(1)

    def test_memmap_roundtrip(self, tmp_path):
        rb = ReplayBuffer(8, n_envs=2, memmap=True, memmap_dir=tmp_path / "rb")
        for t in range(8):
            rb.add(make_step(t))
        assert rb.is_memmap
        assert (tmp_path / "rb" / "obs.memmap").exists()
        assert rb["obs"][3, 0, 0] == 3

    def test_state_dict_roundtrip(self):
        rb = ReplayBuffer(8, n_envs=2)
        for t in range(5):
            rb.add(make_step(t))
        rb2 = ReplayBuffer(8, n_envs=2)
        rb2.load_state_dict(rb.state_dict())
        assert len(rb2) == 5
        assert np.array_equal(rb2["obs"], rb["obs"])
        bad = ReplayBuffer(4, n_envs=2)
        with pytest.raises(ValueError):
            bad.load_state_dict(rb.state_dict())


class TestSequentialReplayBuffer:
    def test_sequence_shapes_and_contiguity(self):
        rb = SequentialReplayBuffer(32, n_envs=2)
        for t in range(20):
            rb.add(make_step(t))
        batch = rb.sample(5, sequence_length=8, n_samples=2)
        assert batch["obs"].shape == (2, 8, 5, 3)
        # contiguity: consecutive steps differ by exactly 1
        diffs = np.diff(batch["obs"][..., 0], axis=1)
        assert np.all(diffs == 1)

    def test_wraparound_sequences_stay_ordered(self):
        rb = SequentialReplayBuffer(16, n_envs=1)
        for t in range(24):
            rb.add(make_step(t, n_envs=1))
        batch = rb.sample(16, sequence_length=4)
        diffs = np.diff(batch["obs"][..., 0], axis=1)
        assert np.all(diffs == 1)
        assert batch["obs"].min() >= 8  # oldest surviving step

    def test_too_short_raises(self):
        rb = SequentialReplayBuffer(16, n_envs=1)
        for t in range(3):
            rb.add(make_step(t, n_envs=1))
        with pytest.raises(RuntimeError):
            rb.sample(1, sequence_length=8)


class TestEnvIndependentReplayBuffer:
    def test_per_env_add_and_sample(self):
        rb = EnvIndependentReplayBuffer(16, n_envs=3, buffer_cls=SequentialReplayBuffer)
        for t in range(12):
            rb.add(make_step(t, n_envs=3))
        # add two extra steps only for env 1
        rb.add(make_step(99, n_envs=1), indices=[1])
        batch = rb.sample(6, sequence_length=4)
        assert batch["obs"].shape == (1, 4, 6, 3)

    def test_uniform_buffer_cls(self):
        rb = EnvIndependentReplayBuffer(16, n_envs=2, buffer_cls=ReplayBuffer)
        for t in range(10):
            rb.add(make_step(t))
        batch = rb.sample(8)
        assert batch["obs"].shape == (1, 8, 3)


class TestEpisodeBuffer:
    def make_episode_data(self, length, n_envs=1, value=0.0):
        d = make_step(value, n_envs=n_envs)
        data = {k: np.repeat(v, length, axis=0) for k, v in d.items()}
        data["dones"][-1] = 1.0
        return data

    def test_commit_on_done(self):
        eb = EpisodeBuffer(100, sequence_length=4, n_envs=1)
        eb.add(self.make_episode_data(10))
        assert len(eb) == 10
        assert len(eb.buffer) == 1

    def test_short_episode_dropped(self):
        eb = EpisodeBuffer(100, sequence_length=4, n_envs=1)
        eb.add(self.make_episode_data(2))
        assert len(eb) == 0

    def test_eviction(self):
        eb = EpisodeBuffer(20, sequence_length=4, n_envs=1)
        for i in range(5):
            eb.add(self.make_episode_data(8, value=i))
        assert len(eb) <= 20

    def test_sample_shapes(self):
        eb = EpisodeBuffer(1000, sequence_length=4, n_envs=2)
        for _ in range(3):
            eb.add(self.make_episode_data(16, n_envs=2))
        batch = eb.sample(5, n_samples=2, sequence_length=4)
        assert batch["obs"].shape == (2, 4, 5, 3)

    def test_open_episode_not_sampled(self):
        eb = EpisodeBuffer(100, sequence_length=2, n_envs=1)
        data = self.make_episode_data(6)
        data["dones"][-1] = 0.0  # never closes
        eb.add(data)
        with pytest.raises(RuntimeError):
            eb.sample(1)

    def test_repair_tail_drops_open_episode(self):
        eb = EpisodeBuffer(100, sequence_length=2, n_envs=1)
        data = self.make_episode_data(6)
        data["dones"][-1] = 0.0  # still open
        eb.add(data)
        eb.repair_tail(0)
        assert eb._open[0] is None
        assert len(eb) == 0

    def test_truncated_commits_without_terminated_key(self):
        # 'dones' + 'truncated' data (no 'terminated'): a truncation alone
        # must close the episode (reference: data/buffers.py EpisodeBuffer.add
        # ORs truncated into the end signal unconditionally).
        eb = EpisodeBuffer(100, sequence_length=2, n_envs=1)
        data = self.make_episode_data(6)
        data["dones"][-1] = 0.0
        data["truncated"] = np.zeros_like(data["dones"])
        data["truncated"][-1] = 1.0
        eb.add(data)
        assert len(eb.buffer) == 1
        assert len(eb) == 6


class TestReviewRegressions:
    def test_sequential_sample_next_obs(self):
        rb = SequentialReplayBuffer(32, n_envs=1, obs_keys=("obs",))
        for t in range(20):
            rb.add(make_step(t, n_envs=1))
        batch = rb.sample(8, sequence_length=4, sample_next_obs=True)
        assert "next_obs" in batch
        assert np.all(batch["next_obs"] - batch["obs"] == 1)

    def test_env_independent_skips_short_subbuffers(self):
        rb = EnvIndependentReplayBuffer(32, n_envs=2, buffer_cls=SequentialReplayBuffer)
        # env 0 gets 10 steps, env 1 only 2 (< sequence_length)
        for t in range(10):
            rb.add(make_step(t, n_envs=1), indices=[0])
        for t in range(2):
            rb.add(make_step(t, n_envs=1), indices=[1])
        for _ in range(10):  # must never crash by picking env 1
            batch = rb.sample(4, sequence_length=8)
            assert batch["obs"].shape == (1, 8, 4, 3)


class TestRepairTail:
    def _dreamer_step(self, t, n_envs=1):
        d = make_step(t, n_envs=n_envs)
        d["terminated"] = np.zeros((1, n_envs, 1), np.float32)
        d["truncated"] = np.zeros((1, n_envs, 1), np.float32)
        d["is_first"] = np.ones((1, n_envs, 1), np.float32) * (t == 0)
        return d

    def test_replay_buffer_repair_tail(self):
        rb = ReplayBuffer(8, n_envs=2)
        for t in range(3):
            rb.add(self._dreamer_step(t, n_envs=2))
        rb.repair_tail(env=1)
        assert rb["truncated"][2, 1, 0] == 1.0 and rb["truncated"][2, 0, 0] == 0.0
        assert rb["terminated"][2, 1, 0] == 0.0
        assert rb["is_first"][2, 1, 0] == 0.0

    def test_repair_tail_empty_buffer_noop(self):
        ReplayBuffer(8, n_envs=1).repair_tail(0)

    def test_env_independent_repair_tail(self):
        rb = EnvIndependentReplayBuffer(16, n_envs=2, buffer_cls=SequentialReplayBuffer)
        for t in range(4):
            rb.add(self._dreamer_step(t, n_envs=1), indices=[0])
            rb.add(self._dreamer_step(t, n_envs=1), indices=[1])
        rb.repair_tail(0)
        assert rb.buffer[0]["truncated"][3, 0, 0] == 1.0
        assert rb.buffer[1]["truncated"][3, 0, 0] == 0.0


class TestEpisodeBufferMemmap:
    def test_memmap_commit_sample_evict(self, tmp_path):
        eb = EpisodeBuffer(20, sequence_length=4, n_envs=1, memmap=True, memmap_dir=tmp_path / "eb")
        def episode(length, value):
            d = make_step(value, n_envs=1)
            data = {k: np.repeat(v, length, axis=0) for k, v in d.items()}
            data["dones"][-1] = 1.0
            return data
        eb.add(episode(8, 1.0))
        assert list((tmp_path / "eb").glob("*.memmap"))
        batch = eb.sample(3, sequence_length=4)
        assert batch["obs"].shape == (1, 4, 3, 3)
        # evict: total steps capped at 20 -> first episode's files deleted
        eb.add(episode(8, 2.0))
        eb.add(episode(8, 3.0))
        files = list((tmp_path / "eb").glob("*.memmap"))
        # only episodes still stored keep files (2 episodes x 4 keys)
        assert len(files) == len(eb.buffer) * 4
        # oldest-first eviction: episode value 1.0 is gone, 2.0/3.0 remain
        kept = sorted(float(np.asarray(ep["obs"])[0, 0]) for ep in eb.buffer)
        assert kept == [2.0, 3.0]
        assert not list((tmp_path / "eb").glob("ep_1_*.memmap"))
