"""E2E: training loops on the device-resident replay path, guard armed.

CPU resolves ``buffer.device=auto`` to off, so these force ``True`` to
exercise the zero-copy path end to end: multi-window SAC (uniform law,
steady windows under ``jax.transfer_guard_host_to_device("disallow")``)
and a DreamerV3 dryrun (sequence law through the fused dispatch).  The
heavier 2-device + ``max_recompiles=1`` variant lives in ``run_ci.sh``
stage 9.
"""

import pytest

from sheeprl_tpu.cli import run


def _common(tmp_path):
    return [
        "env=dummy", "env.num_envs=2", "env.sync_env=True", "env.capture_video=False",
        "fabric.devices=1", "fabric.accelerator=cpu",
        "buffer.memmap=False", "buffer.size=512",
        "buffer.device=True", "buffer.transfer_guard=True",
        "checkpoint.every=0", "checkpoint.save_last=False",
        "metric.log_level=0", "algo.run_test=False",
        f"log_dir={tmp_path}", "print_config=False",
    ]


def test_sac_trains_multi_window_zero_copy(tmp_path):
    """Steady-state SAC windows sample on device under the armed transfer
    guard — an implicit H2D anywhere in the update path raises here."""
    run([
        "exp=sac", "env.id=continuous_dummy",
        "algo.learning_starts=8", "algo.total_steps=48", "algo.replay_ratio=0.5",
        "algo.per_rank_batch_size=4",
    ] + _common(tmp_path))


def test_dreamer_v3_dryrun_on_device_replay(tmp_path):
    run([
        "exp=dreamer_v3", "env.id=discrete_dummy", "dry_run=True",
        "algo.cnn_keys.encoder=[rgb]", "algo.mlp_keys.encoder=[state]",
        "algo.horizon=4", "algo.dense_units=16", "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=4",
        "algo.world_model.recurrent_model.recurrent_state_size=16",
        "algo.world_model.transition_model.hidden_size=16",
        "algo.world_model.representation_model.hidden_size=16",
        "algo.world_model.discrete_size=4", "algo.world_model.stochastic_size=4",
        "algo.per_rank_batch_size=2", "algo.per_rank_sequence_length=8",
    ] + _common(tmp_path))


@pytest.mark.slow
def test_sac_device_replay_checkpoint_roundtrip(tmp_path):
    """Buffer-checkpointed save + resume stays on the device backend."""
    run([
        "exp=sac", "env.id=continuous_dummy",
        "algo.learning_starts=4", "algo.total_steps=32", "algo.replay_ratio=0.5",
        "algo.per_rank_batch_size=4", "buffer.checkpoint=True",
    ] + [
        a if not a.startswith("checkpoint.every") else "checkpoint.every=16"
        for a in _common(tmp_path)
    ])
    from tests.ckpt_utils import find_checkpoints

    ckpt = find_checkpoints(tmp_path)[-1]
    run([
        "exp=sac", "env.id=continuous_dummy",
        "algo.learning_starts=4", "algo.total_steps=48", "algo.replay_ratio=0.5",
        "algo.per_rank_batch_size=4", "buffer.checkpoint=True",
        f"checkpoint.resume_from={ckpt}",
    ] + [
        a if not a.startswith("checkpoint.every") else "checkpoint.every=16"
        for a in _common(tmp_path / "resume")
    ])
