"""EpisodeBuffer sampling DISTRIBUTION tests (VERDICT r3 #7).

Plumbing tests prove shapes; these prove the sampling law itself matches the
reference semantics (reference: sheeprl/data/buffers.py:1077-1099):

* episodes are chosen UNIFORMLY among the eligible ones (no length
  weighting);
* without ``prioritize_ends`` the start index is uniform over the valid
  range ``[0, ep_len - L]``;
* with ``prioritize_ends`` the start is drawn uniformly over
  ``[0, ep_len]`` and clamped, so the LAST valid start carries
  ``(L+1)/(ep_len+1)`` of the mass and every earlier start
  ``1/(ep_len+1)``.

Each assertion uses >= 10k draws with 5-sigma binomial tolerances — loose
enough to be deterministic in CI, tight enough that the old length-weighted
(or off-by-one clamped) law fails decisively.
"""

import numpy as np
import pytest

from sheeprl_tpu.data.buffers import EpisodeBuffer


def _build(prioritize_ends: bool, lengths=(20, 40), L=10) -> EpisodeBuffer:
    rb = EpisodeBuffer(
        buffer_size=1000,
        sequence_length=L,
        n_envs=1,
        prioritize_ends=prioritize_ends,
        minimum_episode_length=L,
    )
    for ep_id, ep_len in enumerate(lengths):
        dones = np.zeros((ep_len, 1, 1), np.float32)
        dones[-1] = 1.0
        rb.add(
            {
                # step index + episode id recoverable from every sample
                "state": np.arange(ep_len, dtype=np.float32).reshape(ep_len, 1, 1),
                "ep": np.full((ep_len, 1, 1), float(ep_id), np.float32),
                "dones": dones,
            }
        )
    return rb


def _draw_starts(rb: EpisodeBuffer, total: int, L: int = 10):
    """(episode id, start index) for ``total`` sampled sequences."""
    out = rb.sample(batch_size=total, n_samples=1, sequence_length=L)
    ep_ids = out["ep"][0, 0, :, 0].astype(int)  # (L=first step, batch)
    starts = out["state"][0, 0, :, 0].astype(int)
    return ep_ids, starts


def _binom_tol(n: int, p: float, sigmas: float = 5.0) -> float:
    return sigmas * np.sqrt(p * (1 - p) / n)


@pytest.mark.parametrize("prioritize_ends", [False, True])
def test_episode_choice_is_uniform_not_length_weighted(prioritize_ends):
    np.random.seed(3)
    rb = _build(prioritize_ends)
    N = 20000
    ep_ids, _ = _draw_starts(rb, N)
    frac_short = float(np.mean(ep_ids == 0))
    # uniform -> 0.5; the old length-weighted law -> 20/60 = 0.333
    assert abs(frac_short - 0.5) < _binom_tol(N, 0.5), (
        f"episode choice not uniform: short-episode fraction {frac_short:.4f}"
    )


def test_start_distribution_without_prioritize_ends():
    np.random.seed(4)
    L, lengths = 10, (20, 40)
    rb = _build(False, lengths, L)
    N = 30000
    ep_ids, starts = _draw_starts(rb, N, L)
    for ep_id, ep_len in enumerate(lengths):
        s = starts[ep_ids == ep_id]
        max_start = ep_len - L
        assert s.min() >= 0 and s.max() <= max_start
        # each start uniform at 1/(max_start+1)
        p = 1.0 / (max_start + 1)
        for v in range(max_start + 1):
            frac = float(np.mean(s == v))
            assert abs(frac - p) < _binom_tol(len(s), p), (
                f"ep {ep_id}: start {v} frequency {frac:.4f}, expected {p:.4f}"
            )


def test_prioritize_ends_tail_mass_matches_reference_law():
    np.random.seed(5)
    L, lengths = 10, (20, 40)
    rb = _build(True, lengths, L)
    N = 40000
    ep_ids, starts = _draw_starts(rb, N, L)
    for ep_id, ep_len in enumerate(lengths):
        s = starts[ep_ids == ep_id]
        max_start = ep_len - L
        # reference law: draw uniform over [0, ep_len] then clamp ->
        # P(start == max_start) = (L+1)/(ep_len+1), others 1/(ep_len+1)
        p_tail = (L + 1) / (ep_len + 1)
        frac_tail = float(np.mean(s == max_start))
        assert abs(frac_tail - p_tail) < _binom_tol(len(s), p_tail), (
            f"ep {ep_id}: tail mass {frac_tail:.4f}, reference law {p_tail:.4f}"
        )
        p_other = 1.0 / (ep_len + 1)
        for v in range(max_start):
            frac = float(np.mean(s == v))
            assert abs(frac - p_other) < _binom_tol(len(s), p_other), (
                f"ep {ep_id}: start {v} frequency {frac:.4f}, expected {p_other:.4f}"
            )


def test_prioritize_ends_oversamples_tails_end_to_end():
    """The user-visible property: with prioritize_ends the average sampled
    start sits meaningfully later in the episode."""
    np.random.seed(6)
    L = 10
    rb_flat = _build(False, (40,), L)
    rb_ends = _build(True, (40,), L)
    N = 10000
    _, s_flat = _draw_starts(rb_flat, N, L)
    _, s_ends = _draw_starts(rb_ends, N, L)
    assert s_ends.mean() > s_flat.mean() + 2.0, (
        f"prioritize_ends did not shift starts: {s_ends.mean():.2f} vs {s_flat.mean():.2f}"
    )
