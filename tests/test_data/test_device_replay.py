"""DeviceReplay: the zero-copy device-resident replay contract.

Four claims, each a test family:

* **Seeded parity** — for the SAME PRNG-drawn index stream, on-device
  uniform and sequence gathers are bit-identical to the host-numpy
  ``ReplayBuffer``/``SequentialReplayBuffer`` gather at those coordinates
  (the gather path carries no law of its own).
* **Signature stability** — 50 add + fused-sample+update iterations reuse
  ONE compiled executable: cursor motion is device data, not signature.
* **Mesh sharding** — on a 2x4 ``(data, model)`` fake-device mesh the ring
  arrays carry ``PartitionSpec(None, 'data')`` and donated writes preserve
  it (the layout ``fabric.shard_batch`` gives shipped batches).
* **Spill chaos** — a stalled/raising/truncating spill tier (fault site
  ``replay.spill``) slows or degrades capacity eviction but never blocks or
  corrupts the device ring or the compiled step.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_replay import (
    DeviceReplay,
    HostSpill,
    fit_hbm_window,
    fused_uniform_train,
    steady_guard,
    update_chunks,
)


def _fill(cap=16, n_envs=3, steps=23, feat=4, seed=0, extra_keys=("next_obs", "rewards")):
    """Identically-filled (DeviceReplay, host ReplayBuffer) pair."""
    rng = np.random.default_rng(seed)
    dev = DeviceReplay(cap, n_envs)
    host = ReplayBuffer(cap, n_envs, obs_keys=("obs",))
    for _ in range(steps):
        data = {"obs": rng.normal(size=(1, n_envs, feat)).astype(np.float32)}
        for k in extra_keys:
            width = feat if k.startswith("next") else 1
            data[k] = rng.normal(size=(1, n_envs, width)).astype(np.float32)
        dev.add(data)
        host.add(data)
    return dev, host


# --------------------------------------------------------------------------
# seeded parity with the host-numpy sampling path
# --------------------------------------------------------------------------

class TestSeededParity:
    def test_uniform_batches_match_host_gather(self):
        dev, host = _fill()
        key = jax.random.PRNGKey(7)
        batch = dev.sample_uniform(dev.buffers, dev.cursor, key, batch_size=5, n_samples=4)
        # identical PRNG stream -> identical indices -> identical batches
        step, env = dev.uniform_indices(dev.cursor, key, 20)
        step, env = np.asarray(step), np.asarray(env)
        expected = host._gather(step, env, sample_next_obs=False)
        for k in ("obs", "next_obs", "rewards"):
            np.testing.assert_array_equal(
                np.asarray(batch[k]).reshape(20, -1), expected[k].reshape(20, -1)
            )

    def test_uniform_ring_content_matches_host_after_wrap(self):
        dev, host = _fill(cap=8, steps=37)
        for k in dev.keys():
            np.testing.assert_array_equal(np.asarray(dev.buffers[k]), host.buffer[k])

    def test_derived_next_obs_matches_successor_row(self):
        dev, host = _fill(cap=16, steps=10, extra_keys=())
        key = jax.random.PRNGKey(3)
        batch = dev.sample_uniform(
            dev.buffers, dev.cursor, key, batch_size=6, n_samples=1, derive_next=("obs",)
        )
        step, env = dev.uniform_indices(dev.cursor, key, 6, sample_next_obs=True)
        step, env = np.asarray(step), np.asarray(env)
        expected = host._gather(step, env, sample_next_obs=True)
        np.testing.assert_array_equal(
            np.asarray(batch["next_obs"]).reshape(6, -1), expected["next_obs"]
        )

    def test_uniform_never_draws_beyond_filled(self):
        dev, _ = _fill(cap=32, steps=5)
        step, _ = dev.uniform_indices(dev.cursor, jax.random.PRNGKey(0), 512)
        assert int(np.max(np.asarray(step))) < 5

    def test_sequence_batches_match_host_gather(self):
        cap, n_envs, L = 16, 2, 4
        rng = np.random.default_rng(1)
        dev = DeviceReplay(cap, n_envs)
        rows = []
        for t in range(30):  # wraps
            d = {"x": rng.normal(size=(1, n_envs, 3)).astype(np.float32)}
            rows.append(d["x"][0])
            dev.add(d)
        full_history = np.stack(rows)  # (T, E, 3)
        ring = full_history[-cap:]  # what the ring holds, in ring order:
        # ring slot s holds history step (30 - cap) + ((s - pos) % cap)
        key = jax.random.PRNGKey(9)
        total = 12
        t_idx, env = dev.sequence_indices(dev.cursor, key, total, L)
        t_idx, env = np.asarray(t_idx), np.asarray(env)
        batch = dev.sample_sequences(
            dev.buffers, dev.cursor, key, batch_size=4, sequence_length=L, n_samples=3
        )
        got = np.asarray(batch["x"]).swapaxes(1, 2).reshape(total, L, 3)
        expected = np.asarray(dev.buffers["x"])[t_idx, env[:, None]]
        np.testing.assert_array_equal(got, expected)
        # sequences are contiguous history (never cross the write head):
        pos = int(np.asarray(dev.cursor["pos"])[0])
        age = (t_idx - pos) % cap  # position in oldest->newest order
        assert np.all(np.diff(age, axis=1) == 1)
        for i in range(total):
            np.testing.assert_array_equal(
                got[i], full_history[30 - cap + age[i], env[i]]
            )

    def test_sequence_sampling_respects_partial_envs(self):
        """Envs with fewer than L steps get zero sampling mass (the host
        multinomial-eligibility law)."""
        dev = DeviceReplay(16, 2)
        for t in range(6):
            dev.add({"x": np.full((1, 1, 1), t, np.float32)}, indices=[0])
        dev.add({"x": np.full((1, 1, 1), 99.0, np.float32)}, indices=[1])  # env 1: 1 step
        _, env = dev.sequence_indices(dev.cursor, jax.random.PRNGKey(0), 256, 4)
        assert set(np.asarray(env).tolist()) == {0}


# --------------------------------------------------------------------------
# compile-once: no signature churn from cursors
# --------------------------------------------------------------------------

class TestSignatureStability:
    def test_fused_sample_update_reuses_one_executable_over_50_iters(self):
        from sheeprl_tpu.parallel.fabric import Fabric

        fabric = Fabric(devices=1, accelerator="cpu")
        rb = DeviceReplay(32, 2, mesh=fabric.mesh, data_axis=fabric.data_axis)

        def train_phase(p, o, batch, k, counter):
            loss = jnp.mean(batch["obs"]) + jnp.mean(batch["rewards"])
            return p + loss * 1e-3, o, loss

        fused = fused_uniform_train(
            fabric, train_phase, rb, batch_size=4,
            prep=lambda b: {"obs": b["obs"], "rewards": b["rewards"][..., 0]},
            name="test.fused",
        )
        params = jax.device_put(jnp.zeros(3))
        opt = jax.device_put(jnp.zeros(3))
        counter = jax.device_put(np.int32(0))
        key = jax.random.PRNGKey(0)
        rng = np.random.default_rng(0)
        for i in range(50):
            rb.add({
                "obs": rng.normal(size=(1, 2, 4)).astype(np.float32),
                "rewards": rng.normal(size=(1, 2, 1)).astype(np.float32),
            })
            key, tk = jax.random.split(key)
            # steady guard armed past the first window: the fused dispatch
            # must perform ZERO implicit H2D (cursors/counter are device data)
            with steady_guard(i >= 1):
                params, opt, counter, _ = fused(
                    params, opt, rb.buffers, rb.cursor, tk, counter, n_samples=2
                )
        assert fused.cache_size() == 1
        assert int(counter) == 100

    def test_update_chunks_power_of_two_decomposition(self):
        assert update_chunks(1) == [1]
        assert update_chunks(7) == [4, 2, 1]
        assert update_chunks(8) == [8]
        assert update_chunks(1300, cap=64) == [64] * 20 + [16, 4]
        # chunk set stays small: a burst mints few distinct signatures
        assert len(set(update_chunks(1023))) == 10


# --------------------------------------------------------------------------
# mesh sharding (2x4 fake-device mesh from conftest's 8 virtual devices)
# --------------------------------------------------------------------------

class TestMeshSharding:
    @pytest.fixture()
    def mesh_fabric(self):
        from sheeprl_tpu.parallel.fabric import Fabric

        return Fabric(devices=8, accelerator="cpu", mesh_shape={"data": 2, "model": 4})

    def test_ring_carries_data_axis_partition_spec(self, mesh_fabric):
        rb = DeviceReplay(16, 4, mesh=mesh_fabric.mesh, data_axis=mesh_fabric.data_axis)
        rng = np.random.default_rng(0)
        for _ in range(5):
            rb.add({"obs": rng.normal(size=(1, 4, 6)).astype(np.float32)})
        assert rb.buffers["obs"].sharding.spec == P(None, "data")
        # donated in-place writes preserve the placement
        rb.add({"obs": rng.normal(size=(1, 4, 6)).astype(np.float32)})
        assert rb.buffers["obs"].sharding.spec == P(None, "data")

    def test_indivisible_env_count_replicates(self, mesh_fabric):
        from sheeprl_tpu.parallel.sharding import replay_partition_spec

        assert replay_partition_spec(4, mesh_fabric.mesh) == P(None, "data")
        assert replay_partition_spec(3, mesh_fabric.mesh) == P()

    def test_sampling_on_mesh_produces_constrained_batches(self, mesh_fabric):
        rb = DeviceReplay(16, 4, mesh=mesh_fabric.mesh, data_axis=mesh_fabric.data_axis)
        rng = np.random.default_rng(0)
        for _ in range(8):
            rb.add({"obs": rng.normal(size=(1, 4, 6)).astype(np.float32)})
        key = jax.random.PRNGKey(0)
        b = rb.sample_uniform(rb.buffers, rb.cursor, key, batch_size=4, n_samples=2)
        assert b["obs"].shape == (2, 4, 6)
        s = rb.sample_sequences(rb.buffers, rb.cursor, key, 4, 3, n_samples=2)
        assert s["obs"].shape == (2, 3, 4, 6)


# --------------------------------------------------------------------------
# spill tier + replay.spill chaos
# --------------------------------------------------------------------------

class TestSpillTier:
    def test_spill_shadows_full_capacity(self):
        spill = HostSpill(32, 2)
        rb = DeviceReplay(8, 2, spill=spill)
        for t in range(20):
            rb.add({"x": np.full((1, 2, 1), t, np.float32)})
        assert spill.flush(30.0)
        # HBM window holds the last 8 steps; the spill ring all 20
        assert len(spill.buffer) == 20
        np.testing.assert_array_equal(
            spill.buffer.buffer["x"][:20, 0, 0], np.arange(20, dtype=np.float32)
        )
        # checkpoint prefers the (bigger) spill history
        state = rb.state_dict()
        assert state["device_replay"]["from_spill"]
        spill.close()

    def test_spill_checkpoint_roundtrips_into_a_fresh_device_ring(self):
        """A spill-tier checkpoint must restore under the SAME config that
        wrote it: full shadow history reloaded, HBM window rebuilt at the
        saved cursors (the preemption auto-resume path)."""
        spill = HostSpill(32, 2)
        rb = DeviceReplay(8, 2, spill=spill)
        rng = np.random.default_rng(3)
        for _ in range(20):  # wraps the window
            rb.add({
                "x": rng.normal(size=(1, 2, 3)).astype(np.float32),
                "truncated": np.zeros((1, 2, 1), np.float32),
            })
        state = rb.state_dict()
        assert state["device_replay"]["from_spill"]
        # the spill snapshot carries the tail-consistency patch too: the
        # write-head row must not look continuable on resume
        tail = (int(state["pos"]) - 1) % int(state["buffer_size"])
        assert np.all(np.asarray(state["buffer"]["truncated"])[tail] == 1.0)
        # ...applied to the snapshot COPY, not the live spill ring
        assert np.all(np.asarray(spill.buffer["truncated"])[tail] == 0.0)
        spill2 = HostSpill(32, 2)
        rb2 = DeviceReplay(8, 2, spill=spill2).load_state_dict(state)
        np.testing.assert_array_equal(
            np.asarray(rb2.buffers["x"]), np.asarray(rb.buffers["x"])
        )
        assert np.array_equal(rb2._pos_h, rb._pos_h)
        assert np.array_equal(rb2._filled_h, rb._filled_h)
        # the restored spill holds the FULL 20-step history, not just the window
        spill2.flush(30.0)
        assert len(spill2.buffer) == 20
        spill.close(); spill2.close()

    def test_sequential_spill_tracks_per_env_subset_adds(self):
        """The dreamer add path appends reset rows to done envs only
        (``indices=``): the sequential spill must keep per-env streams
        aligned (EnvIndependent sub-buffers, not a shared cursor)."""
        spill = HostSpill(64, 2, sequential=True)
        rb = DeviceReplay(16, 2, spill=spill)
        for t in range(10):
            rb.add({"x": np.full((1, 2, 1), t, np.float32)})
            if t % 3 == 0:  # extra reset row for env 1 only
                rb.add({"x": np.full((1, 1, 1), 100 + t, np.float32)}, indices=[1])
        spill.flush(30.0)
        # per-env spill streams match the device ring's per-env history
        for env in range(2):
            n = int(rb._filled_h[env])
            dev_rows = np.asarray(rb.buffers["x"])[:n, env, 0]
            sub = spill.buffer.buffer[env]
            np.testing.assert_array_equal(np.asarray(sub["x"])[:n, 0, 0], dev_rows)
        assert len(spill.buffer.buffer[0]) != len(spill.buffer.buffer[1])
        # and the checkpoint written from this spill restores cleanly
        state = rb.state_dict()
        rb2 = DeviceReplay(16, 2, spill=HostSpill(64, 2, sequential=True)).load_state_dict(state)
        np.testing.assert_array_equal(
            np.asarray(rb2.buffers["x"])[:, :, 0] * (np.arange(16)[:, None] < rb._filled_h[None, :]),
            np.asarray(rb.buffers["x"])[:, :, 0] * (np.arange(16)[:, None] < rb._filled_h[None, :]),
        )
        rb2.spill.close(); spill.close()

    def test_fit_hbm_window_arms_spill_under_budget(self, monkeypatch):
        monkeypatch.setenv("SHEEPRL_REPLAY_BUDGET_BYTES", str(1000 * 4))
        window, spill_needed = fit_hbm_window(10_000, 2, step_bytes=4)
        assert window == 500 and spill_needed
        window, spill_needed = fit_hbm_window(100, 2, step_bytes=4)
        assert window == 100 and not spill_needed

    def _plan(self, spec):
        from sheeprl_tpu.resilience.faults import FaultPlan, install_plan

        install_plan(FaultPlan.from_specs([spec], seed=1))

    def teardown_method(self):
        from sheeprl_tpu.resilience.faults import clear_plan

        clear_plan()

    def test_stalled_spill_never_blocks_the_compiled_step(self):
        """A latency fault in the spill worker slows eviction bookkeeping
        (the queue backs up) but append + on-device sampling proceed — the
        train step never touches the spill tier."""
        import time

        self._plan({"site": "replay.spill", "kind": "latency", "every": 1, "seconds": 0.2})
        spill = HostSpill(64, 2)
        rb = DeviceReplay(8, 2, spill=spill)
        t0 = time.perf_counter()
        for t in range(10):
            rb.add({"x": np.full((1, 2, 1), t, np.float32)})
        append_wall = time.perf_counter() - t0
        # 10 x 0.2 s of injected latency runs on the WORKER thread
        assert append_wall < 1.0, f"appends blocked on the spill tier ({append_wall:.2f}s)"
        batch = rb.sample_uniform(rb.buffers, rb.cursor, jax.random.PRNGKey(0), 4, 1)
        assert batch["x"].shape == (1, 4, 1)
        assert spill.flush(30.0) and not spill.degraded
        assert len(spill.buffer) == 10
        spill.close()

    def test_raising_spill_degrades_without_corrupting_the_ring(self):
        self._plan({"site": "replay.spill", "kind": "raise", "at": 2})
        spill = HostSpill(64, 2)
        rb = DeviceReplay(8, 2, spill=spill)
        with pytest.warns(RuntimeWarning, match="spill tier degraded"):
            for t in range(5):
                rb.add({"x": np.full((1, 2, 1), t, np.float32)})
            spill.flush(30.0)
        assert spill.degraded
        # the device ring is intact: every appended step is present
        ring = np.asarray(rb.buffers["x"])[:5, 0, 0]
        np.testing.assert_array_equal(ring, np.arange(5, dtype=np.float32))
        # and checkpointing falls back to the (authoritative) device ring
        assert not rb.state_dict()["device_replay"]["from_spill"]
        spill.close()

    def test_truncate_fault_halves_spilled_rows_only(self):
        self._plan({"site": "replay.spill", "kind": "truncate", "at": 1})
        spill = HostSpill(64, 1)
        rb = DeviceReplay(16, 1, spill=spill)
        rb.add({"x": np.arange(8, dtype=np.float32).reshape(8, 1, 1)})
        spill.flush(30.0)
        assert len(spill.buffer) == 4  # tail-halved by the fault
        # device ring holds the full 8 rows regardless
        np.testing.assert_array_equal(
            np.asarray(rb.buffers["x"])[:8, 0, 0], np.arange(8, dtype=np.float32)
        )
        spill.close()


# --------------------------------------------------------------------------
# host-buffer API parity pieces the loops rely on
# --------------------------------------------------------------------------

class TestLoopContract:
    def test_repair_tail_marks_truncation(self):
        rb = DeviceReplay(8, 2)
        for t in range(3):
            rb.add({
                "x": np.full((1, 2, 1), t, np.float32),
                "truncated": np.zeros((1, 2, 1), np.float32),
                "terminated": np.zeros((1, 2, 1), np.float32),
            })
        rb.repair_tail(1)
        assert np.asarray(rb.buffers["truncated"])[2, 1, 0] == 1.0
        assert np.asarray(rb.buffers["truncated"])[2, 0, 0] == 0.0

    def test_state_dict_roundtrip(self):
        rb = DeviceReplay(8, 2)
        rng = np.random.default_rng(0)
        for t in range(11):
            rb.add({"x": rng.normal(size=(1, 2, 3)).astype(np.float32)})
        state = rb.state_dict()
        rb2 = DeviceReplay(8, 2).load_state_dict(state)
        np.testing.assert_array_equal(np.asarray(rb2.buffers["x"]), np.asarray(rb.buffers["x"]))
        assert np.array_equal(rb2._pos_h, rb._pos_h)
        assert np.array_equal(
            np.asarray(rb2.cursor["filled"]), np.asarray(rb.cursor["filled"])
        )

    def test_state_dict_applies_tail_consistency_patch(self):
        """The checkpoint callback's _consistent_tail contract: the write-head
        row must not look continuable on resume (no next_* rows stored) —
        only truncated/dones are forced; terminated is a value-semantics
        flag and must survive untouched (a real episode end at the head
        would otherwise bootstrap across a true terminal after resume)."""
        rb = DeviceReplay(8, 1)
        for t in range(3):
            rb.add({
                "x": np.full((1, 1, 1), t, np.float32),
                "truncated": np.zeros((1, 1, 1), np.float32),
                "terminated": np.full((1, 1, 1), float(t == 2), np.float32),
            })
        state = rb.state_dict()
        assert state["buffer"]["truncated"][2, 0, 0] == 1.0
        assert state["buffer"]["terminated"][2, 0, 0] == 1.0  # preserved
        # the live ring is NOT patched (the patch lands on the host copy)
        assert np.asarray(rb.buffers["truncated"])[2, 0, 0] == 0.0

    def test_eligibility_shadows(self):
        rb = DeviceReplay(16, 2)
        assert not rb.can_sample()
        rb.add({"x": np.zeros((1, 2, 1), np.float32)})
        assert rb.can_sample() and not rb.can_sample_sequences(4)
        for _ in range(5):
            rb.add({"x": np.zeros((1, 2, 1), np.float32)})
        assert rb.can_sample_sequences(4)
        assert len(rb) == 12
