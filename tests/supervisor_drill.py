#!/usr/bin/env python
"""run_ci stage 13: self-healing supervisor drill.

A short SAC training run is supervised end-to-end across a REAL process
boundary (``sheeprl_tpu.supervisor`` spawning ``python -m sheeprl_tpu``):

1. a seeded ``env.step`` raise is planted at invocation 40 (mid-run, well
   past several committed checkpoints) via ``SHEEPRL_FAULT_PLAN`` — the
   fault is FATAL (``env.restart_on_exception`` defaults off for SAC), so
   episode 0 crashes with a postmortem;
2. the supervisor classifies the crash (transient: first occurrence of
   that fatal signature), restarts with ``checkpoint.resume_from=auto``,
   and the resumed episode — whose remaining iterations never reach
   invocation 40 again — runs to completion;
3. asserted: supervisor exit 0; ``supervisor_log.jsonl`` holds exactly
   the crash episode (classification ``transient``, action ``restart``,
   a postmortem path whose document carries the injected fault) and the
   success episode; and the experiment root's newest COMMITTED snapshot
   sits at the FULL configured step count — the run lost nothing but the
   uncommitted tail.

This is the loop PRs 2/8/13 could not close alone: the crash leaves
evidence (PR 13), the evidence names a committed snapshot (PR 2), and now
something acts on it without a human.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LOG_DIR = "/tmp/run_ci_supervisor"
TOTAL_STEPS = 64  # 32 iterations x 2 envs
FAULT_AT = 40  # env.step invocation 40 = iteration 20: past the step-32 commit

FAULT_PLAN = json.dumps(
    {"seed": 5, "plan": [{"site": "env.step", "kind": "raise", "at": FAULT_AT}]}
)

RUN_ARGS = [
    "exp=sac",
    "env=dummy",
    "env.id=continuous_dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "algo.learning_starts=8",
    f"algo.total_steps={TOTAL_STEPS}",
    "algo.replay_ratio=0.5",
    "algo.per_rank_batch_size=8",
    "algo.run_test=False",
    "fabric.devices=1",
    "fabric.accelerator=cpu",
    "checkpoint.every=8",
    "checkpoint.save_last=True",
    "buffer.memmap=False",
    "buffer.size=512",
    "metric.log_level=1",
    "metric.log_every=1",
    f"log_dir={LOG_DIR}",
    "print_config=False",
    # drill pacing: tight backoff, no long watchdog interplay
    "supervisor.max_restarts=3",
    "supervisor.backoff_base_s=0.2",
    "supervisor.poll_interval_s=1.0",
]


def main() -> int:
    shutil.rmtree(LOG_DIR, ignore_errors=True)
    os.environ["SHEEPRL_FAULT_PLAN"] = FAULT_PLAN
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.supervisor import Supervisor

    cfg = compose(RUN_ARGS)
    sup = Supervisor(cfg, RUN_ARGS)
    rc = sup.run()
    assert rc == 0, f"supervisor exited {rc} — the supervised run never completed"

    # -- audit trail ---------------------------------------------------------
    audit = sup.audit_path
    assert os.path.isfile(audit), f"no supervisor_log.jsonl at {audit}"
    episodes = [json.loads(line) for line in open(audit)]
    assert len(episodes) == 2, f"expected crash+success episodes, got {episodes}"
    crash, success = episodes
    assert crash["classification"] == "transient", crash
    assert crash["action"] == "restart", crash
    assert crash["returncode"] not in (0, None), crash
    assert success["classification"] == "success" and success["returncode"] == 0, success
    print(f"[drill] audit OK: {audit} ({len(episodes)} episodes)")

    # -- the crash left evidence and the supervisor read it ------------------
    assert crash["postmortem"], "crash episode has no postmortem path"
    doc = json.load(open(crash["postmortem"]))
    assert doc["schema"].startswith("sheeprl.postmortem/")
    assert any(
        e.get("kind") == "fault.injected" and e.get("site") == "env.step"
        for e in doc["events"]
    ), "postmortem does not show the injected env.step fault"
    assert crash["signature"], "crash verdict carries no fatal signature"
    print(f"[drill] postmortem OK: {crash['postmortem']}")

    # -- the run finished with the FULL configured step count ----------------
    from sheeprl_tpu.checkpoint.protocol import checkpoint_step

    steps = sorted(
        checkpoint_step(p)
        for p in glob.glob(os.path.join(sup.exp_root, "*", "version_*", "checkpoint", "step_*"))
        if checkpoint_step(p) >= 0
    )
    assert steps, "no committed snapshots under the experiment root"
    assert steps[-1] == TOTAL_STEPS, (
        f"newest committed snapshot is step {steps[-1]}, expected {TOTAL_STEPS} "
        f"(all: {steps})"
    )
    # the resumed episode must have CONTINUED, not restarted from zero: a
    # from-scratch rerun would re-commit the early steps into its own run
    # dir — instead the pre-crash commits and the post-resume commits must
    # interleave into one monotone history
    assert TOTAL_STEPS - 8 in steps or len(set(steps)) > 1, steps
    print(f"[drill] checkpoints OK: committed steps {steps}")
    print(
        "supervisor drill OK: fatal mid-run fault -> postmortem-classified "
        "restart -> auto-resume -> full step count"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
