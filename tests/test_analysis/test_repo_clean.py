"""The tier-1 gate: graftlint over the live ``sheeprl_tpu/`` package must
report ZERO unsuppressed findings against the checked-in baseline, with no
stale baseline entries, inside the CI wall budget.

This is the acceptance criterion of the analyzer PR made permanent: every
later PR that introduces a donation/purity/PRNG/registry violation — or
fixes a baselined one without deleting its ledger entry — goes red here.
"""

import pytest

from sheeprl_tpu.analysis import Baseline, DEFAULT_BASELINE, METRIC_FAMILIES, RULE_IDS, run_analysis
from sheeprl_tpu.analysis.core import repo_root


@pytest.fixture(scope="module")
def repo_report():
    return run_analysis(baseline=Baseline.load(DEFAULT_BASELINE))


def test_zero_unsuppressed_findings(repo_report):
    assert repo_report.findings == [], "\n" + "\n".join(
        f.render() for f in repo_report.findings
    )


def test_no_stale_baseline_entries(repo_report):
    assert repo_report.stale_baseline == [], (
        "baseline entries matching nothing (delete them — their findings "
        f"are fixed): {repo_report.stale_baseline}"
    )


def test_every_baselined_finding_has_a_reasoned_entry(repo_report):
    # the ledger carries real reasons (Baseline.load validates non-empty);
    # spot-check the shape the analyzer PR established
    b = Baseline.load(DEFAULT_BASELINE)
    for entry in b.entries:
        assert len(entry["reason"]) > 40, entry  # a sentence, not a shrug

def test_analyzer_covers_the_whole_package(repo_report):
    # ~170 files today; a collapse in coverage (walker bug, parse regression)
    # must not masquerade as cleanliness
    assert repo_report.files_analyzed > 150


def test_wall_budget(repo_report):
    # CI gives the lint stage 60 s; the in-process run must stay far inside
    assert repo_report.wall_s < 60, f"graftlint took {repo_report.wall_s:.1f}s"


def test_rule_catalogue_is_documented():
    doc = (repo_root() / "docs" / "static_analysis.md").read_text()
    for rule in RULE_IDS:
        assert f"`{rule}`" in doc, f"rule {rule} missing from docs/static_analysis.md"


def test_metric_families_are_documented():
    doc = (repo_root() / "docs" / "static_analysis.md").read_text()
    for family in METRIC_FAMILIES:
        assert f"`{family}/`" in doc, (
            f"metric family {family}/ missing from docs/static_analysis.md — "
            "the analyzer registry and the docs table must stay in sync"
        )
