"""Suppression-comment and baseline mechanics.

The zero-unsuppressed invariant only means something if the two accept
mechanisms are themselves well-behaved: suppressions must be precise (the
named rule, that line, nothing else), typos must not silently disarm, and
baseline entries must carry reasons and go stale loudly.
"""

import json
import textwrap
from pathlib import Path

import pytest

from sheeprl_tpu.analysis import Baseline, BaselineError, run_analysis
from sheeprl_tpu.analysis.baseline import DEFAULT_BASELINE
from sheeprl_tpu.analysis.core import Finding, SourceFile

VIOLATION = """
import jax


def run(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,)){trailer}
    return a, b
"""


def _write(tmp_path: Path, code: str) -> Path:
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent(code))
    return p


def _run(tmp_path, code, **kwargs):
    return run_analysis([_write(tmp_path, code)], root=tmp_path, **kwargs)


class TestSuppressionComments:
    def test_unsuppressed_violation_is_reported(self, tmp_path):
        report = _run(tmp_path, VIOLATION.format(trailer=""))
        assert [f.rule for f in report.findings] == ["prng-key-reuse"]
        assert report.suppressed == []

    def test_same_line_suppression(self, tmp_path):
        report = _run(
            tmp_path,
            VIOLATION.format(trailer="  # graftlint: disable=prng-key-reuse"),
        )
        assert report.findings == []
        assert [f.rule for f in report.suppressed] == ["prng-key-reuse"]

    def test_preceding_comment_line_suppression(self, tmp_path):
        code = """
        import jax


        def run(key):
            a = jax.random.normal(key, (4,))
            # deliberate: arms are mutually exclusive downstream
            # graftlint: disable=prng-key-reuse
            b = jax.random.uniform(key, (4,))
            return a, b
        """
        report = _run(tmp_path, code)
        assert report.findings == []
        assert len(report.suppressed) == 1

    def test_wrong_rule_does_not_suppress(self, tmp_path):
        report = _run(
            tmp_path,
            VIOLATION.format(trailer="  # graftlint: disable=use-after-donate"),
        )
        assert [f.rule for f in report.findings] == ["prng-key-reuse"]

    def test_unknown_rule_does_not_suppress(self, tmp_path):
        report = _run(
            tmp_path,
            VIOLATION.format(trailer="  # graftlint: disable=prng-key-resue"),
        )
        assert [f.rule for f in report.findings] == ["prng-key-reuse"]
        # ...and the typo is surfaced, not silently ignored
        assert any("prng-key-resue" in n for n in report.notes)

    def test_file_wide_suppression(self, tmp_path):
        code = "# graftlint: disable-file=prng-key-reuse\n" + textwrap.dedent(
            VIOLATION.format(trailer="")
        )
        p = tmp_path / "mod.py"
        p.write_text(code)
        report = run_analysis([p], root=tmp_path)
        assert report.findings == []
        assert len(report.suppressed) == 1

    def test_string_literal_cannot_fake_a_suppression(self, tmp_path):
        # comments come from tokenize: a string containing the magic text
        # must not suppress anything
        code = VIOLATION.format(trailer="") + (
            '\nMAGIC = "graftlint: disable-file=prng-key-reuse"\n'
        )
        report = _run(tmp_path, code)
        assert [f.rule for f in report.findings] == ["prng-key-reuse"]


class TestBaseline:
    def _finding(self):
        return Finding("prng-key-reuse", "mod.py", 7, "key 'key' consumed again by 'uniform'")

    def test_match_by_rule_file_substring(self, tmp_path):
        b = Baseline(
            [{"rule": "prng-key-reuse", "file": "mod.py", "match": "consumed again", "reason": "r"}]
        )
        report = _run(tmp_path, VIOLATION.format(trailer=""), baseline=b)
        assert report.findings == []
        assert len(report.baselined) == 1
        assert b.stale_entries() == []

    def test_wrong_file_does_not_match(self, tmp_path):
        b = Baseline(
            [{"rule": "prng-key-reuse", "file": "other.py", "match": "consumed again", "reason": "r"}]
        )
        report = _run(tmp_path, VIOLATION.format(trailer=""), baseline=b)
        assert [f.rule for f in report.findings] == ["prng-key-reuse"]
        assert report.stale_baseline == b.entries

    def test_stale_entry_is_surfaced(self, tmp_path):
        b = Baseline(
            [{"rule": "use-after-donate", "file": "mod.py", "match": "nothing", "reason": "r"}]
        )
        report = _run(tmp_path, VIOLATION.format(trailer=""), baseline=b)
        assert len(report.stale_baseline) == 1

    def test_entry_without_reason_is_rejected(self):
        with pytest.raises(BaselineError, match="reason"):
            Baseline([{"rule": "prng-key-reuse", "match": "x"}])

    def test_entry_with_unknown_rule_is_rejected(self):
        with pytest.raises(BaselineError, match="unknown rule"):
            Baseline([{"rule": "not-a-rule", "reason": "r"}])

    def test_write_and_reload_roundtrip(self, tmp_path):
        findings = [self._finding()]
        path = tmp_path / "baseline.json"
        Baseline.write(findings, path, "bootstrap")
        b = Baseline.load(path)
        assert b.matches(findings[0])
        data = json.loads(path.read_text())
        assert data["entries"][0]["reason"] == "bootstrap"

    def test_checked_in_baseline_is_valid(self):
        b = Baseline.load(DEFAULT_BASELINE)
        for entry in b.entries:
            assert entry["reason"].strip()

    def test_select_does_not_stale_other_rules_entries(self, tmp_path):
        # `--select x --strict` must not report baseline entries for OTHER
        # rules as stale: matching runs before the selection filter
        b = Baseline(
            [{"rule": "prng-key-reuse", "file": "mod.py", "match": "consumed again", "reason": "r"}]
        )
        report = _run(
            tmp_path,
            VIOLATION.format(trailer=""),
            baseline=b,
            select=["use-after-donate"],
        )
        assert report.findings == []  # prng finding deselected
        assert report.stale_baseline == []  # ...but its entry still matched


class TestCLI:
    def test_exit_codes(self, tmp_path, capsys):
        from sheeprl_tpu.analysis.__main__ import main

        bad = _write(tmp_path, VIOLATION.format(trailer=""))
        assert main([str(bad), "--no-baseline"]) == 1
        good = tmp_path / "good.py"
        good.write_text("x = 1\n")
        assert main([str(good), "--no-baseline"]) == 0
        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        assert "use-after-donate" in out

    def test_json_format(self, tmp_path, capsys):
        from sheeprl_tpu.analysis.__main__ import main

        bad = _write(tmp_path, VIOLATION.format(trailer=""))
        assert main([str(bad), "--no-baseline", "--format", "json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["unsuppressed"][0]["rule"] == "prng-key-reuse"

    def test_select_unknown_rule_is_usage_error(self, tmp_path, capsys):
        from sheeprl_tpu.analysis.__main__ import main

        good = tmp_path / "good.py"
        good.write_text("x = 1\n")
        assert main([str(good), "--select", "nope"]) == 2
