"""Historical-bug regression fixtures: the two worst shipped bugs, as the
analyzer must see them.  These snippets are structural reductions of the
actual defective code (PR 7's same-platform ``copy_to`` zero-copy alias;
PR 14's ``HealthSentinel`` donation-aliasing and ``device_put``-borrowed
-buffer pair) — if a rule refactor stops flagging either, CI stops the
regression HERE rather than in a chaos drill three PRs later.
"""

from tests.test_analysis.conftest import lint_snippet, line_of, rules_of


class TestPR7CopyToAlias:
    """PR 7: ``fabric.copy_to(params, host)`` on a same-platform pair was a
    zero-copy alias of shard 0, so the first donated train dispatch deleted
    the player's param copy ("buffer has been deleted or donated")."""

    FIXTURE = """
    def train_loop(fabric, train, params, host, obs):
        step = fabric.compile(train, donate_argnums=(0,))
        player_params = fabric.copy_to(params, host)   # zero-copy alias
        for _ in range(10):
            params = step(params)                      # donates the aliased buffer
            act(player_params, obs)                    # READ of the dead alias
        return params
    """

    def test_flagged_by_use_after_donate(self):
        findings = lint_snippet(self.FIXTURE)
        assert rules_of(findings) == ["use-after-donate"]
        f = findings[0]
        assert f.line == line_of(self.FIXTURE, "# READ")
        assert "player_params" in f.message
        assert "alias" in f.message

    def test_the_pr7_fix_shape_is_clean(self):
        # the actual fix: copy_to alias-breaks internally; the analyzer's
        # spelling of that at a call site is an explicit .copy()
        code = """
        def train_loop(fabric, train, params, host, obs):
            step = fabric.compile(train, donate_argnums=(0,))
            player_params = fabric.copy_to(params, host).copy()
            for _ in range(10):
                params = step(params)
                act(player_params, obs)
            return params
        """
        assert lint_snippet(code) == []


class TestPR14DonationAliasing:
    """PR 14: ``HealthSentinel.wrap`` traced the JITTED (donating) callable
    inside the guard program and re-read the original params for the
    old-vs-new select — the inner donate_argnums survives inlining as an
    aliasing hint, so XLA may clobber the donated input mid-read."""

    FIXTURE = """
    import jax
    import jax.numpy as jnp

    def wrap(compile_once, phase_raw):
        phase = compile_once(phase_raw, donate_argnums=(0, 1))

        def guarded(h, p, o, batch):
            new_p, new_o, aux = phase(p, o, batch)
            keep = jax.tree.map(lambda a, b: jnp.where(h, a, b), new_p, p)  # READ
            return keep, new_o, aux

        return guarded
    """

    def test_flagged_by_use_after_donate(self):
        findings = lint_snippet(self.FIXTURE)
        assert rules_of(findings) == ["use-after-donate"]
        f = findings[0]
        assert f.line == line_of(self.FIXTURE, "# READ")
        assert "'p'" in f.message

    def test_the_pr14_fix_shape_is_clean(self):
        # the fix: trace the RAW (undonated) phase — AOTFunction.fn
        code = """
        import jax
        import jax.numpy as jnp

        def wrap(compile_once, phase_raw):
            def guarded(h, p, o, batch):
                new_p, new_o, aux = phase_raw(p, o, batch)
                keep = jax.tree.map(lambda a, b: jnp.where(h, a, b), new_p, p)
                return keep, new_o, aux

            return guarded
        """
        assert lint_snippet(code) == []


class TestPR14BorrowedBuffer:
    """PR 14 sibling facet: the zero HealthState was built by
    ``jax.device_put`` of numpy scalars; CPU device_put can zero-copy
    BORROW the numpy buffer, so donating it hands XLA memory it does not
    own (intermittent heap corruption, reproduced 5x in the kill -9
    chaos-resume drill)."""

    FIXTURE = """
    import jax
    import numpy as np

    def init_and_train(compile_once, phase, p, o, batch):
        h_dev = jax.device_put(np.zeros((4,), np.float32))   # borrowed buffer
        guarded = compile_once(phase, donate_argnums=(0, 1, 2))
        p, o, h_dev = guarded(p, o, h_dev, batch)  # DONATE
        return p, o, h_dev
    """

    def test_flagged_by_donation_rule(self):
        findings = lint_snippet(self.FIXTURE)
        assert rules_of(findings) == ["donation-borrowed-buffer"]
        f = findings[0]
        assert f.line == line_of(self.FIXTURE, "# DONATE")
        assert "h_dev" in f.message

    def test_the_pr14_fix_shape_is_clean(self):
        # the fix: build the state from jnp (XLA-owned) values
        code = """
        import jax.numpy as jnp

        def init_and_train(compile_once, phase, p, o, batch):
            h_dev = jnp.zeros((4,), jnp.float32)
            guarded = compile_once(phase, donate_argnums=(0, 1, 2))
            p, o, h_dev = guarded(p, o, h_dev, batch)
            return p, o, h_dev
        """
        assert lint_snippet(code) == []


class TestRealLoopShapesStayClean:
    """The canonical healthy loop shapes from the live codebase must never
    regress into findings — zero-unsuppressed is a hard repo invariant."""

    def test_sac_style_loop(self):
        code = """
        import jax

        def sac_loop(fabric, phase_raw, params, opt_state, key, batches):
            train_phase = fabric.compile(
                phase_raw, donate_argnums=(0, 1), max_recompiles=1
            )
            for update in range(100):
                key, tk = jax.random.split(key)
                params, opt_state, losses = train_phase(params, opt_state, batches, tk)
            return params, opt_state, losses
        """
        assert lint_snippet(code) == []

    def test_sebulba_learner_style_loop(self):
        code = """
        import jax
        import jax.numpy as jnp

        def learner(learner_fab, phase, params, opt_state, key, queue, broadcast):
            learner_phase = learner_fab.compile(
                phase, donate_argnums=(0, 1), max_recompiles=1
            )
            for update in range(100):
                segs = queue.pop_all()
                key, tk = jax.random.split(key)
                params, opt_state, losses = learner_phase(
                    params, opt_state, segs, tk
                )
                broadcast.publish(params, version=update)
            return params, opt_state
        """
        assert lint_snippet(code) == []
