"""Shared helpers for the graftlint test suite.

``lint_snippet`` runs the per-file rules over an inline code snippet and
returns findings; ``line_of`` locates an expected finding's line by a
source marker so tests never hard-code brittle line numbers.
"""

import textwrap
from pathlib import Path

import pytest

from sheeprl_tpu.analysis import RepoContext
from sheeprl_tpu.analysis.core import SourceFile, repo_root
from sheeprl_tpu.analysis import donation, prng, purity, registry


@pytest.fixture(scope="session")
def repo_ctx():
    """The real RepoContext (config tree + fault registry), built once."""
    return RepoContext.build(repo_root())


def lint_snippet(code: str, ctx=None, rules=("donation", "purity", "prng", "registry")):
    src = SourceFile(Path("snippet.py"), "snippet.py", textwrap.dedent(code))
    findings = []
    if "donation" in rules:
        findings += donation.check(src, ctx)
    if "purity" in rules:
        findings += purity.check(src, ctx)
    if "prng" in rules:
        findings += prng.check(src, ctx)
    if "registry" in rules and ctx is not None:
        findings += registry.check_file(src, ctx)
    # dedupe like the driver (the loop two-pass can repeat findings)
    uniq = {}
    for f in findings:
        uniq.setdefault((f.rule, f.path, f.line, f.message), f)
    return sorted(uniq.values(), key=lambda f: (f.line, f.rule))


def line_of(code: str, marker: str) -> int:
    """1-based line of the first line containing ``marker`` (post-dedent —
    dedent only strips leading whitespace, line numbers are unchanged)."""
    for i, line in enumerate(textwrap.dedent(code).splitlines(), 1):
        if marker in line:
            return i
    raise AssertionError(f"marker {marker!r} not in snippet")


def rules_of(findings):
    return [f.rule for f in findings]
