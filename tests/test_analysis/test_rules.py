"""Per-rule fixture pairs: one violating snippet and its clean twin, each
asserting the exact rule id AND line.  These are the contract of every
graftlint rule — a precision tweak that stops flagging a violating snippet,
or starts flagging a clean one, must show up here first.
"""

import pytest

from tests.test_analysis.conftest import lint_snippet, line_of, rules_of


# ---------------------------------------------------------------------------
# rule 1: use-after-donate
# ---------------------------------------------------------------------------

class TestUseAfterDonate:
    def test_violating_straight_line(self):
        code = """
        def run(compile_once, f, x):
            g = compile_once(f, donate_argnums=(0,))
            y = g(x)
            return x + y  # READ
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["use-after-donate"]
        assert findings[0].line == line_of(code, "# READ")
        assert "'x'" in findings[0].message

    def test_clean_rebinding(self):
        code = """
        def run(compile_once, f, x):
            g = compile_once(f, donate_argnums=(0,))
            x = g(x)
            return x
        """
        assert lint_snippet(code) == []

    def test_clean_copy_at_call_site(self):
        code = """
        def run(compile_once, f, x):
            g = compile_once(f, donate_argnums=(0,))
            y = g(x.copy())
            return x + y
        """
        assert lint_snippet(code) == []

    def test_loop_donation_reaches_next_iteration(self):
        code = """
        def run(compile_once, f, x, xs):
            g = compile_once(f, donate_argnums=(0,))
            for _ in range(3):
                y = g(x)  # DONATE, never rebinds x
            return y
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["use-after-donate"]
        # the read is x's use in the SECOND loop pass, at the call line
        assert findings[0].line == line_of(code, "# DONATE")

    def test_loop_rebinding_is_clean(self):
        code = """
        def run(fabric, f, params, opt, batch):
            step = fabric.compile(f, donate_argnums=(0, 1))
            for _ in range(10):
                params, opt, aux = step(params, opt, batch)
            return params, opt
        """
        assert lint_snippet(code) == []

    def test_branch_donation_flags_later_read(self):
        code = """
        def run(compile_once, f, x, flag):
            g = compile_once(f, donate_argnums=(0,))
            if flag:
                y = g(x)
            else:
                y = None
            return x  # READ on the path where x was donated
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["use-after-donate"]
        assert findings[0].line == line_of(code, "# READ")

    def test_early_return_branch_does_not_leak(self):
        code = """
        def run(compile_once, f, x, flag):
            g = compile_once(f, donate_argnums=(0,))
            if flag:
                return g(x)
            return x
        """
        assert lint_snippet(code) == []

    def test_factory_returned_callable_is_tracked(self):
        """The make_sac_train_fns shape: the donating callable is built in a
        factory and tuple-unpacked by the loop."""
        code = """
        def make_fns(compile_once, act, phase):
            act_fn = compile_once(act)
            train_phase = compile_once(phase, donate_argnums=(0, 1))
            return act_fn, train_phase

        def loop(compile_once, act, phase, params, opt, batch):
            act_fn, train_phase = make_fns(compile_once, act, phase)
            train_phase(params, opt, batch)
            return params  # READ
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["use-after-donate"]
        assert findings[0].line == line_of(code, "# READ")

    def test_single_return_factory_is_tracked(self):
        code = """
        def make_step(compile_once, f):
            g = compile_once(f, donate_argnums=(0,))
            return g

        def loop(compile_once, f, x):
            step = make_step(compile_once, f)
            y = step(x)
            return x  # READ
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["use-after-donate"]
        assert findings[0].line == line_of(code, "# READ")

    def test_known_fused_builder_is_tracked(self):
        code = """
        def loop(fabric, phase, rb, key, counter):
            dev = fused_uniform_train(fabric, phase, rb, 64, None)
            params, opt = init()
            dev(params, opt, rb.buffers, key, counter)
            return params  # READ
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["use-after-donate"]
        assert findings[0].line == line_of(code, "# READ")

    def test_donated_attribute_args_are_skipped(self):
        # rb.buffers at a donated position is not a trackable name — the
        # rule must stay silent rather than guess
        code = """
        def loop(compile_once, f, rb):
            g = compile_once(f, donate_argnums=(0,))
            g(rb.buffers)
            return rb.buffers
        """
        assert lint_snippet(code) == []


class TestPipelineStageDonation:
    """The ISSUE 16 hazard class: compile_stage_pair's backward donates the
    inter-stage activation buffer (arg 1) and the incoming cotangent (arg 2)
    — donate a stage-N output, read it again for the 1F1B backward, and the
    buffer is gone.  Curated-table entry 'compile_stage_pair@1' makes the
    cross-module call sites (bench.py) visible to the flow scan."""

    def test_violating_activation_read_after_backward(self):
        code = """
        def bench_stage(fabric, stage_fn, params, x):
            fwd, bwd = compile_stage_pair(fabric, stage_fn, name="s0")
            act = fwd(params, x)
            dy = fwd(params, x)
            dx = bwd(params, act, dy)
            return act.sum() + dx.sum()  # READ
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["use-after-donate"]
        assert findings[0].line == line_of(code, "# READ")
        assert "'act'" in findings[0].message

    def test_violating_cotangent_reused_across_iterations(self):
        # dy built once, donated every pass: dead buffer from pass 2 on
        code = """
        def bench_stage(fabric, stage_fn, params, x, steps):
            fwd, bwd = compile_stage_pair(fabric, stage_fn, name="s0")
            dy = fwd(params, x)
            for _ in range(steps):
                act = fwd(params, x)
                dx = bwd(params, act, dy)  # DONATE
            return dx
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["use-after-donate"]
        assert findings[0].line == line_of(code, "# DONATE")
        assert "'dy'" in findings[0].message

    def test_clean_canonical_rebinding_loop(self):
        # the sanctioned shape: act and dy rebound from fwd every pass,
        # params (arg 0) is NOT donated by the backward
        code = """
        def bench_stage(fabric, stage_fn, params, x, steps):
            fwd, bwd = compile_stage_pair(fabric, stage_fn, name="s0")
            for _ in range(steps):
                act = fwd(params, x)
                dy = fwd(params, x)
                dx = bwd(params, act, dy)
            return params, dx
        """
        assert lint_snippet(code) == []

    def test_clean_forward_only(self):
        # fwd (tuple position 0) donates nothing: reuse is legal
        code = """
        def bench_stage(fabric, stage_fn, params, x, steps):
            fwd, bwd = compile_stage_pair(fabric, stage_fn, name="s0")
            act = fwd(params, x)
            act2 = fwd(params, x)
            return act, act2
        """
        assert lint_snippet(code) == []


# ---------------------------------------------------------------------------
# rule 1b: donation-borrowed-buffer
# ---------------------------------------------------------------------------

class TestDonationBorrowedBuffer:
    def test_violating_device_put_numpy(self):
        code = """
        import jax
        import numpy as np

        def run(compile_once, phase, p, o):
            h0 = jax.device_put(np.zeros((4,), np.float32))
            g = compile_once(phase, donate_argnums=(0, 1, 2))
            p, o, h = g(p, o, h0)  # DONATE
            return p, o, h
        """
        findings = lint_snippet(code)
        assert "donation-borrowed-buffer" in rules_of(findings)
        f = next(f for f in findings if f.rule == "donation-borrowed-buffer")
        assert f.line == line_of(code, "# DONATE")
        assert "'h0'" in f.message

    def test_clean_jnp_built_state(self):
        code = """
        import jax.numpy as jnp

        def run(compile_once, phase, p, o):
            h0 = jnp.zeros((4,), jnp.float32)
            g = compile_once(phase, donate_argnums=(0, 1, 2))
            p, o, h = g(p, o, h0)
            return p, o, h
        """
        assert rules_of(lint_snippet(code)) == []


# ---------------------------------------------------------------------------
# rule 2: trace purity
# ---------------------------------------------------------------------------

class TestTracePurity:
    def test_violating_time_call(self):
        code = """
        import time

        def run(fabric):
            def body(p, x):
                t = time.time()  # IMPURE
                return p, x + t
            return fabric.compile(body, donate_argnums=(0,))
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["trace-impure-time"]
        assert findings[0].line == line_of(code, "# IMPURE")

    def test_violating_python_branch(self):
        code = """
        def run(compile_once):
            def body(p, x):
                if x > 0:  # BRANCH
                    return p, x
                return p, -x
            return compile_once(body)
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["trace-python-branch"]
        assert findings[0].line == line_of(code, "# BRANCH")

    def test_violating_host_concretize(self):
        code = """
        import numpy as np

        def run(compile_once):
            def body(p, x):
                a = float(x)     # CONCRETIZE
                b = np.abs(x)    # NUMPY
                return p, a + b
            return compile_once(body)
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["trace-host-concretize", "trace-host-concretize"]
        assert findings[0].line == line_of(code, "# CONCRETIZE")
        assert findings[1].line == line_of(code, "# NUMPY")

    def test_clean_partial_jit_static_argnums_decorator(self):
        code = """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnums=(2,))
        def body(p, x, greedy):
            if greedy:
                return p, x
            return p, -x
        """
        assert lint_snippet(code) == []

    def test_clean_static_argname_branch(self):
        code = """
        def run(compile_once):
            def body(p, x, greedy=False):
                if greedy:
                    return p, x
                return p, -x
            return compile_once(body, static_argnames=("greedy",))
        """
        assert lint_snippet(code) == []

    def test_clean_structural_tests_and_jnp(self):
        code = """
        import jax.numpy as jnp

        def run(compile_once):
            def body(p, x):
                if isinstance(x, dict):
                    x = x["a"]
                if x is None:
                    return p, None
                if x.ndim == 3:
                    x = x[None]
                return p, jnp.where(x > 0, x, -x)
            return compile_once(body)
        """
        assert lint_snippet(code) == []

    def test_untraced_function_is_not_checked(self):
        code = """
        import time

        def host_only(x):
            if x > 0:
                return time.time()
            return float(x)
        """
        assert lint_snippet(code) == []

    def test_lax_scan_body_is_traced(self):
        code = """
        import time
        from jax import lax

        def run(carry, xs):
            def step(c, x):
                t = time.time()  # IMPURE
                return c, x + t
            return lax.scan(step, carry, xs)
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["trace-impure-time"]
        assert findings[0].line == line_of(code, "# IMPURE")


# ---------------------------------------------------------------------------
# rule 3: PRNG discipline
# ---------------------------------------------------------------------------

class TestPrng:
    def test_violating_two_sinks(self):
        code = """
        import jax

        def run(key):
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))  # REUSE
            return a, b
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["prng-key-reuse"]
        assert findings[0].line == line_of(code, "# REUSE")

    def test_clean_split_and_thread(self):
        code = """
        import jax

        def run(key):
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, (4,))
            b = jax.random.uniform(k2, (4,))
            return a, b
        """
        assert lint_snippet(code) == []

    def test_use_after_split_is_reuse(self):
        code = """
        import jax

        def run(key):
            k1, k2 = jax.random.split(key)
            return jax.random.normal(key, (4,))  # REUSE
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["prng-key-reuse"]
        assert findings[0].line == line_of(code, "# REUSE")

    def test_loop_consumption_without_rebind(self):
        code = """
        import jax

        def run(key, n):
            out = []
            for _ in range(n):
                out.append(jax.random.normal(key, (4,)))  # REUSE (every iter)
            return out
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["prng-key-reuse"]
        assert findings[0].line == line_of(code, "# REUSE")

    def test_loop_with_threading_is_clean(self):
        code = """
        import jax

        def run(key, n):
            out = []
            for _ in range(n):
                key, k = jax.random.split(key)
                out.append(jax.random.normal(k, (4,)))
            return out
        """
        assert lint_snippet(code) == []

    def test_fold_in_does_not_consume(self):
        code = """
        import jax

        def run(key, n):
            keys = [jax.random.fold_in(key, i) for i in range(n)]
            k1, k2 = jax.random.split(key)
            return keys, k1, k2
        """
        assert lint_snippet(code) == []

    def test_branches_do_not_pair(self):
        # the sac-loop shape: if/else arms each consume tk once
        code = """
        import jax

        def run(train_a, train_b, key, flag):
            key, tk = jax.random.split(key)
            if flag:
                out = train_a(tk)
            else:
                out = train_b(tk)
            return out
        """
        assert lint_snippet(code) == []

    def test_early_return_does_not_pair(self):
        code = """
        import jax

        def sample(dist, key, continuous):
            if continuous:
                return dist.sample(key)
            keys = jax.random.split(key, 3)
            return [dist.sample(k) for k in keys]
        """
        assert lint_snippet(code) == []

    def test_consume_after_both_branches_consumed(self):
        code = """
        import jax

        def run(train_a, train_b, key, flag):
            key, tk = jax.random.split(key)
            if flag:
                out = train_a(tk)
            else:
                out = train_b(tk)
            return out, train_a(tk)  # REUSE
        """
        findings = lint_snippet(code)
        assert rules_of(findings) == ["prng-key-reuse"]
        assert findings[0].line == line_of(code, "# REUSE")

    def test_split_discarded(self):
        code = """
        import jax

        def run(key):
            jax.random.split(key)  # DISCARD
            return jax.random.normal(key, (4,))
        """
        findings = lint_snippet(code)
        assert "prng-split-discarded" in rules_of(findings)
        f = next(f for f in findings if f.rule == "prng-split-discarded")
        assert f.line == line_of(code, "# DISCARD")

    def test_key_named_int_param_is_not_a_key(self):
        # copies_per_key is an int; builtins must not count as sinks
        code = """
        def estimate(copies_per_key):
            a = int(copies_per_key)
            b = int(copies_per_key) * 2
            return a + b
        """
        assert lint_snippet(code) == []


# ---------------------------------------------------------------------------
# rule 4: registries (uses the real repo config tree / fault registry)
# ---------------------------------------------------------------------------

class TestRegistries:
    def test_cfg_known_key_is_clean(self, repo_ctx):
        code = """
        def run(cfg):
            return cfg.algo.total_steps, cfg.buffer.size, cfg.env.num_envs
        """
        assert lint_snippet(code, ctx=repo_ctx) == []

    def test_cfg_unknown_key_flags(self, repo_ctx):
        code = """
        def run(cfg):
            return cfg.algo.learning_startss  # TYPO
        """
        findings = lint_snippet(code, ctx=repo_ctx)
        assert rules_of(findings) == ["cfg-unknown-key"]
        assert findings[0].line == line_of(code, "# TYPO")
        assert "algo.learning_startss" in findings[0].message

    def test_cfg_optional_get_is_never_an_error(self, repo_ctx):
        code = """
        def run(cfg):
            return cfg.algo.get("definitely_not_a_key"), cfg.get("nope", 1)
        """
        assert lint_snippet(code, ctx=repo_ctx) == []

    def test_cfg_leaf_value_methods_are_not_keys(self, repo_ctx):
        code = """
        def run(cfg):
            return cfg.buffer.device.lower()
        """
        assert lint_snippet(code, ctx=repo_ctx) == []

    def test_fault_site_known_is_clean(self, repo_ctx):
        code = """
        from sheeprl_tpu.resilience.faults import fault_point

        def run():
            fault_point("env.step")
        """
        assert lint_snippet(code, ctx=repo_ctx) == []

    def test_fault_site_typo_flags(self, repo_ctx):
        code = """
        from sheeprl_tpu.resilience.faults import fault_point

        def run():
            fault_point("env.stpe")  # TYPO
        """
        findings = lint_snippet(code, ctx=repo_ctx)
        assert rules_of(findings) == ["fault-site-unknown"]
        assert findings[0].line == line_of(code, "# TYPO")

    def test_fault_spec_dict_and_kwarg_checked(self, repo_ctx):
        code = """
        def plan(FaultSpec):
            a = FaultSpec(site="serve.htpp", kind="raise", at=1)  # KWARG
            b = {"site": "env.reset", "at": 2}
            c = {"site": "checkpoint.commmit", "every": 3}  # DICT
            return a, b, c
        """
        findings = lint_snippet(code, ctx=repo_ctx)
        assert rules_of(findings) == ["fault-site-unknown", "fault-site-unknown"]
        assert findings[0].line == line_of(code, "# KWARG")
        assert findings[1].line == line_of(code, "# DICT")

    def test_retry_site_label_is_not_a_fault_site(self, repo_ctx):
        # retry()'s site= labels Resilience/* metrics — a different registry
        code = """
        def run(retry, job):
            return retry(job, attempts=3, site="checkpoint.write")
        """
        assert lint_snippet(code, ctx=repo_ctx) == []

    def test_metric_documented_family_is_clean(self, repo_ctx):
        code = """
        def run(aggregator, logger):
            aggregator.update("Loss/value_loss", 1.0)
            logger.log_metrics({"Rewards/rew_avg": 1.0}, 0)
        """
        assert lint_snippet(code, ctx=repo_ctx) == []

    def test_metric_unknown_family_flags(self, repo_ctx):
        code = """
        def run(aggregator, metrics):
            aggregator.update("Bogus/value", 1.0)  # AGG
            metrics["AlsoBogus/x"] = 2.0  # STORE
        """
        findings = lint_snippet(code, ctx=repo_ctx)
        assert rules_of(findings) == ["metric-family-unknown", "metric-family-unknown"]
        assert findings[0].line == line_of(code, "# AGG")
        assert findings[1].line == line_of(code, "# STORE")

    def test_non_metric_slash_strings_ignored(self, repo_ctx):
        code = """
        def run(d):
            protocol_version = "HTTP/1.1"
            d["some/path/like/thing"] = 1
            return protocol_version
        """
        assert lint_snippet(code, ctx=repo_ctx) == []
