import os
import textwrap

import pytest

from sheeprl_tpu.config.compose import ConfigError, compose
from sheeprl_tpu.utils.structured import deep_merge, dotdict, get_by_path, set_by_path


def base_overrides():
    return [
        "env=default",
        "env.id=CartPole-v1",
        "algo.name=x",
        "algo.total_steps=64",
        "algo.per_rank_batch_size=4",
    ]


def test_defaults_tree_composes():
    cfg = compose(base_overrides())
    for group in ("algo", "buffer", "checkpoint", "distribution", "env", "fabric", "metric", "model_manager", "topology"):
        assert group in cfg, group
    assert cfg.env.num_envs == 4
    assert cfg.fabric.devices == 1


def test_dot_overrides_and_yaml_typing():
    cfg = compose(base_overrides() + ["env.num_envs=16", "fabric.precision=bf16-mixed", "dry_run=True"])
    assert cfg.env.num_envs == 16 and isinstance(cfg.env.num_envs, int)
    assert cfg.dry_run is True
    assert cfg.fabric.precision == "bf16-mixed"


def test_interpolation_resolution():
    cfg = compose(base_overrides() + ["seed=9"])
    assert cfg.exp_name == "x_CartPole-v1"
    assert cfg.metric.logger.root_dir.endswith("x/CartPole-v1")
    assert "${" not in str(cfg.run_name)


def test_new_key_via_plus_override():
    cfg = compose(base_overrides() + ["+algo.brand_new=3"])
    assert cfg.algo.brand_new == 3


def test_unknown_group_file_raises():
    with pytest.raises(ConfigError):
        compose(["env=this_env_does_not_exist"])


def test_search_path_extension(tmp_path, monkeypatch):
    # SHEEPRL_SEARCH_PATH adds out-of-tree config dirs, like the reference's
    # hydra plugin (reference: hydra_plugins/sheeprl_search_path.py:11-33).
    (tmp_path / "exp").mkdir()
    (tmp_path / "exp" / "custom.yaml").write_text(
        textwrap.dedent(
            """
            algo:
              name: custom_algo
              total_steps: 1
              per_rank_batch_size: 1
            env:
              id: none
            """
        )
    )
    monkeypatch.setenv("SHEEPRL_SEARCH_PATH", str(tmp_path))
    cfg = compose(["exp=custom", "env=default"])
    assert cfg.algo.name == "custom_algo"


def test_eval_and_env_resolvers(monkeypatch):
    monkeypatch.setenv("MY_TEST_VAR", "21")
    cfg = compose(base_overrides() + ["+algo.derived=${eval:2*3}", "+algo.from_env=${env:MY_TEST_VAR,0}"])
    assert cfg.algo.derived == 6
    assert cfg.algo.from_env == 21 or cfg.algo.from_env == "21"


def test_dotdict_helpers():
    d = dotdict({"a": {"b": 1}})
    assert d.a.b == 1
    set_by_path(d, "a.c.d", 5)
    assert get_by_path(d, "a.c.d") == 5
    merged = deep_merge({"x": {"y": 1, "z": 2}}, {"x": {"y": 10}})
    assert merged == {"x": {"y": 10, "z": 2}}
    assert d.as_dict() == {"a": {"b": 1, "c": {"d": 5}}}


def test_nested_group_placement_cli(monkeypatch):
    """`metric/logger=mlflow` swaps a group instance placed at a nested path
    (the `/logger@logger:` defaults packaging) from the CLI — hydra's
    `logger@metric.logger=...` equivalent."""
    from sheeprl_tpu.config.compose import ConfigError, compose

    # strict oc.env: missing variable with no default fails fast
    monkeypatch.delenv("MLFLOW_TRACKING_URI", raising=False)
    with pytest.raises(ConfigError):
        compose(["exp=ppo", "env.id=x", "metric/logger=mlflow"])
    monkeypatch.setenv("MLFLOW_TRACKING_URI", "http://tracking:5000")
    cfg = compose(["exp=ppo", "env.id=x", "metric/logger=mlflow"])
    assert cfg.metric.logger.kind == "mlflow"
    assert cfg.metric.logger.tracking_uri == "http://tracking:5000"
    # the default instance is untouched without the override
    assert compose(["exp=ppo", "env.id=x"]).metric.logger.kind == "tensorboard"


def test_apply_cli_overrides_on_saved_config():
    """The eval/registration dispatchers replay CLI overrides onto a saved
    run config; group syntax must behave like compose's (the eval path used
    to set a literal "metric/logger" key, silently ignoring the override)."""
    from sheeprl_tpu.config.compose import apply_cli_overrides

    cfg = compose(base_overrides() + ["algo=ppo"])
    assert cfg.metric.logger.kind == "tensorboard"
    apply_cli_overrides(cfg, ["metric/logger=csv", "seed=7", "algo.gamma=0.5"])
    assert cfg.metric.logger.kind == "csv"
    assert cfg.seed == 7
    assert cfg.algo.gamma == 0.5
    with pytest.raises(ConfigError):
        apply_cli_overrides(cfg, ["not-an-override"])
    with pytest.raises(ConfigError):
        apply_cli_overrides(cfg, ["exp=ppo"])


def test_apply_cli_overrides_group_replaces_and_resolves():
    from sheeprl_tpu.config.compose import apply_cli_overrides

    cfg = compose(base_overrides() + ["algo=ppo", "env=minerl"])
    assert "sticky_attack" in cfg.env.wrapper
    apply_cli_overrides(cfg, ["env=dummy"])
    # re-select REPLACES the instance: no minerl keys may leak into the
    # dummy wrapper kwargs (they would become unexpected constructor args)
    assert cfg.env.wrapper.kind == "dummy"
    assert "sticky_attack" not in cfg.env.wrapper
    # freshly loaded group files carry ${...} references which must resolve
    # against the final tree, not survive as literal strings
    import json

    assert "${" not in json.dumps(cfg.as_dict())


def test_apply_cli_overrides_ordering_matches_compose():
    from sheeprl_tpu.config.compose import apply_cli_overrides

    cfg = compose(base_overrides() + ["algo=ppo"])
    # dot overrides are applied LAST regardless of CLI position, like compose
    apply_cli_overrides(cfg, ["env.num_envs=1", "env=dummy"])
    assert cfg.env.wrapper.kind == "dummy"
    assert cfg.env.num_envs == 1


def test_apply_cli_overrides_validates_before_mutating():
    from sheeprl_tpu.config.compose import apply_cli_overrides

    cfg = compose(base_overrides()[1:] + ["env=dummy", "algo=ppo"])
    assert cfg.env.wrapper.kind == "dummy"
    with pytest.raises(ConfigError):
        apply_cli_overrides(cfg, ["env=gym", "exp=ppo"])
    assert cfg.env.wrapper.kind == "dummy"  # untouched on error

    # a bare key naming a SECTION that is not a known group dir (e.g. the
    # group came from SHEEPRL_SEARCH_PATH at train time but is absent now)
    # must fail loudly, not silently replace the subtree with a scalar
    cfg.mygroup = dotdict({"a": 1, "b": 2})
    with pytest.raises(ConfigError):
        apply_cli_overrides(cfg, ["mygroup=name"])
    assert cfg.mygroup.a == 1

    # a group load failing MID-APPLY must also leave the tree untouched
    with pytest.raises(ConfigError):
        apply_cli_overrides(cfg, ["env=this_env_does_not_exist"])
    assert cfg.env.wrapper.kind == "dummy"
    with pytest.raises(ConfigError):
        apply_cli_overrides(cfg, ["metric/logger=typo_logger"])
    assert cfg.metric.logger.kind == "tensorboard"


def test_group_at_path_placement_grammar():
    """hydra's `group@dot.path=name` CLI grammar (documented in
    howto/run_experiments.md for optimizer swaps)."""
    from sheeprl_tpu.config.compose import apply_cli_overrides

    cfg = compose(base_overrides() + ["exp=dreamer_v3", "env=dummy"])
    assert cfg.algo.world_model.optimizer.name == "adam"
    cfg2 = compose(base_overrides() + ["exp=dreamer_v3", "env=dummy",
                                       "optim@algo.world_model.optimizer=sgd"])
    assert cfg2.algo.world_model.optimizer.name == "sgd"
    assert cfg2.algo.world_model.optimizer.lr == 1e-2
    # and on a saved config through the eval path
    apply_cli_overrides(cfg, ["optim@algo.actor.optimizer=rmsprop"])
    assert cfg.algo.actor.optimizer.name == "rmsprop"
