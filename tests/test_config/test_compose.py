import os
import textwrap

import pytest

from sheeprl_tpu.config.compose import ConfigError, compose
from sheeprl_tpu.utils.structured import deep_merge, dotdict, get_by_path, set_by_path


def base_overrides():
    return [
        "env=default",
        "env.id=CartPole-v1",
        "algo.name=x",
        "algo.total_steps=64",
        "algo.per_rank_batch_size=4",
    ]


def test_defaults_tree_composes():
    cfg = compose(base_overrides())
    for group in ("algo", "buffer", "checkpoint", "distribution", "env", "fabric", "metric", "model_manager"):
        assert group in cfg, group
    assert cfg.env.num_envs == 4
    assert cfg.fabric.devices == 1


def test_dot_overrides_and_yaml_typing():
    cfg = compose(base_overrides() + ["env.num_envs=16", "fabric.precision=bf16-mixed", "dry_run=True"])
    assert cfg.env.num_envs == 16 and isinstance(cfg.env.num_envs, int)
    assert cfg.dry_run is True
    assert cfg.fabric.precision == "bf16-mixed"


def test_interpolation_resolution():
    cfg = compose(base_overrides() + ["seed=9"])
    assert cfg.exp_name == "x_CartPole-v1"
    assert cfg.metric.logger.root_dir.endswith("x/CartPole-v1")
    assert "${" not in str(cfg.run_name)


def test_new_key_via_plus_override():
    cfg = compose(base_overrides() + ["+algo.brand_new=3"])
    assert cfg.algo.brand_new == 3


def test_unknown_group_file_raises():
    with pytest.raises(ConfigError):
        compose(["env=this_env_does_not_exist"])


def test_search_path_extension(tmp_path, monkeypatch):
    # SHEEPRL_SEARCH_PATH adds out-of-tree config dirs, like the reference's
    # hydra plugin (reference: hydra_plugins/sheeprl_search_path.py:11-33).
    (tmp_path / "exp").mkdir()
    (tmp_path / "exp" / "custom.yaml").write_text(
        textwrap.dedent(
            """
            algo:
              name: custom_algo
              total_steps: 1
              per_rank_batch_size: 1
            env:
              id: none
            """
        )
    )
    monkeypatch.setenv("SHEEPRL_SEARCH_PATH", str(tmp_path))
    cfg = compose(["exp=custom", "env=default"])
    assert cfg.algo.name == "custom_algo"


def test_eval_and_env_resolvers(monkeypatch):
    monkeypatch.setenv("MY_TEST_VAR", "21")
    cfg = compose(base_overrides() + ["+algo.derived=${eval:2*3}", "+algo.from_env=${env:MY_TEST_VAR,0}"])
    assert cfg.algo.derived == 6
    assert cfg.algo.from_env == 21 or cfg.algo.from_env == "21"


def test_dotdict_helpers():
    d = dotdict({"a": {"b": 1}})
    assert d.a.b == 1
    set_by_path(d, "a.c.d", 5)
    assert get_by_path(d, "a.c.d") == 5
    merged = deep_merge({"x": {"y": 1, "z": 2}}, {"x": {"y": 10}})
    assert merged == {"x": {"y": 10, "z": 2}}
    assert d.as_dict() == {"a": {"b": 1, "c": {"d": 5}}}


def test_nested_group_placement_cli(monkeypatch):
    """`metric/logger=mlflow` swaps a group instance placed at a nested path
    (the `/logger@logger:` defaults packaging) from the CLI — hydra's
    `logger@metric.logger=...` equivalent."""
    from sheeprl_tpu.config.compose import ConfigError, compose

    # strict oc.env: missing variable with no default fails fast
    monkeypatch.delenv("MLFLOW_TRACKING_URI", raising=False)
    with pytest.raises(ConfigError):
        compose(["exp=ppo", "env.id=x", "metric/logger=mlflow"])
    monkeypatch.setenv("MLFLOW_TRACKING_URI", "http://tracking:5000")
    cfg = compose(["exp=ppo", "env.id=x", "metric/logger=mlflow"])
    assert cfg.metric.logger.kind == "mlflow"
    assert cfg.metric.logger.tracking_uri == "http://tracking:5000"
    # the default instance is untouched without the override
    assert compose(["exp=ppo", "env.id=x"]).metric.logger.kind == "tensorboard"
