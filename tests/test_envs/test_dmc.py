"""DMC wrapper tests against the REAL dm_control backend (present in this
image; EGL renders headless).  These are the only suite tests that exercise
a real physics engine rather than a mock — the observation contract, the
terminated/truncated mapping, and the full make_env pipeline over real
MuJoCo renders (reference surface: sheeprl/envs/dmc.py:49+)."""

import numpy as np
import pytest

from sheeprl_tpu.envs.dmc import _DMC_AVAILABLE

pytestmark = pytest.mark.skipif(not _DMC_AVAILABLE, reason="dm_control not installed")


def _cfg(extra=()):
    from sheeprl_tpu.config.compose import compose

    return compose(
        [
            "exp=sac",
            "env=dmc",
            "env.id=cartpole_balance",
            "algo.mlp_keys.encoder=[state]",
            "env.capture_video=False",
            *extra,
        ]
    )


def test_vectors_only_contract():
    from sheeprl_tpu.utils.env import make_env

    cfg = _cfg(["env.wrapper.from_pixels=False", "env.wrapper.from_vectors=True"])
    env = make_env(cfg, seed=3, rank=0)()
    assert set(env.observation_space.spaces) == {"state"}
    obs, _ = env.reset()
    assert obs["state"].dtype == np.float32 and obs["state"].ndim == 1
    total = 0.0
    for _ in range(5):
        obs, r, term, trunc, _ = env.step(env.action_space.sample())
        total += r
        assert not term  # cartpole_balance has no early termination
    env.close()


def test_pixels_through_full_pipeline():
    """Real MuJoCo EGL render → resize/grayscale pipeline → frame stack."""
    from sheeprl_tpu.utils.env import make_env

    cfg = _cfg(
        [
            "env.wrapper.from_pixels=True",
            "env.wrapper.from_vectors=True",
            "env.screen_size=64",
            "env.frame_stack=3",
            "algo.cnn_keys.encoder=[rgb]",
        ]
    )
    env = make_env(cfg, seed=3, rank=0)()
    obs, _ = env.reset()
    # framework frame-stack contract: (stack, H, W, C) channel-last uint8,
    # merged into channels at encoder input (see dv3 build_agent)
    assert obs["rgb"].shape == (3, 64, 64, 3)
    assert obs["rgb"].dtype == np.uint8
    assert obs["rgb"].max() > 0  # a real render, not a black frame
    assert obs["state"].dtype == np.float32
    obs2, r, term, trunc, _ = env.step(env.action_space.sample())
    assert obs2["rgb"].shape == (3, 64, 64, 3)
    env.close()


def test_action_repeat_and_seeding_determinism():
    from sheeprl_tpu.utils.env import make_env

    cfg = _cfg(["env.wrapper.from_pixels=False", "env.wrapper.from_vectors=True", "env.action_repeat=2"])
    rollouts = []
    for _ in range(2):
        env = make_env(cfg, seed=11, rank=0)()
        obs, _ = env.reset(seed=11)
        acts = np.linspace(-1, 1, 4, dtype=np.float32)
        traj = []
        for a in acts:
            o, r, *_ = env.step(np.full(env.action_space.shape, a, np.float32))
            traj.append((o["state"].copy(), r))
        env.close()
        rollouts.append(traj)
    for (o1, r1), (o2, r2) in zip(*rollouts):
        np.testing.assert_allclose(o1, o2)
        assert r1 == r2


def test_dreamer_v3_e2e_on_real_dmc_pixels(tmp_path):
    """Full DreamerV3 training iteration over REAL MuJoCo physics + EGL
    renders through the actual CLI — the only E2E that crosses a real
    simulator (everything else uses the deterministic dummy envs)."""
    from sheeprl_tpu.cli import run

    run(
        [
            "exp=dreamer_v3", "env=dmc", "env.id=cartpole_balance",
            "algo=dreamer_v3_XS", "dry_run=True",
            "env.num_envs=1", "env.sync_env=True", "env.capture_video=False",
            "env.action_repeat=2",
            "fabric.devices=1", "fabric.accelerator=cpu",
            "algo.learning_starts=32", "algo.per_rank_batch_size=2",
            "algo.per_rank_sequence_length=8",
            "algo.world_model.recurrent_model.recurrent_state_size=32",
            "algo.world_model.stochastic_size=4", "algo.world_model.discrete_size=4",
            "algo.dense_units=16", "algo.mlp_layers=1",
            "algo.world_model.encoder.cnn_channels_multiplier=2",
            "metric.log_level=0", "checkpoint.every=0", "checkpoint.save_last=False",
            "buffer.memmap=False", "algo.run_test=False", "print_config=False",
            f"log_dir={tmp_path}",
        ]
    )
