"""One env contract across the three families the scenario matrix drives:
{dummy, cpu-gym, pure-JAX (adapter)} (ISSUE 11, satellite).

Per family, the same three claims:

* ``reset(seed=s)`` is reproducible (same seed → same first obs and same
  fixed-action trajectory) and seed-distinct where the env has any
  stochasticity to seed;
* exactly one of terminated/truncated is ever set on an episode end;
* through ``utils.env.vectorize`` (SAME_STEP autoreset) a finished episode
  surfaces ``final_obs``/``final_info`` in vector infos.
"""

import numpy as np
import pytest

from sheeprl_tpu.config.compose import compose
from sheeprl_tpu.utils.env import make_env, vectorize

# (family, compose overrides, fixed action, max steps to see an episode end)
FAMILIES = {
    "dummy": (
        ["env=dummy", "env.id=discrete_dummy",
         "env.wrapper.episode_len=12", "env.wrapper.random_start=True"],
        1,
        40,
    ),
    "cpu_gym": (
        ["env=gym", "env.id=CartPole-v1", "env.sync_env=True"],
        1,
        200,
    ),
    "jax": (
        ["env=jax_cartpole"],
        1,
        200,
    ),
}


def _cfg(overrides):
    return compose(
        [
            "exp=ppo", "algo.mlp_keys.encoder=[state]", "env.num_envs=2",
            "env.capture_video=False", *overrides,
        ]
    )


@pytest.fixture(params=sorted(FAMILIES), ids=sorted(FAMILIES))
def family(request):
    return request.param


def _rollout(env, seed, action, n=6):
    obs, _ = env.reset(seed=seed)
    traj = [obs["state"].copy()]
    for _ in range(n):
        obs, _, term, trunc, _ = env.step(action)
        traj.append(obs["state"].copy())
        if term or trunc:
            break
    return traj


class TestSeededReset:
    def test_same_seed_reproduces(self, family):
        overrides, action, _ = FAMILIES[family]
        env = make_env(_cfg(overrides), None, 0)()
        t1 = _rollout(env, 123, action)
        t2 = _rollout(env, 123, action)
        assert len(t1) == len(t2)
        for a, b in zip(t1, t2):
            np.testing.assert_array_equal(a, b)
        env.close()

    def test_different_seeds_diverge(self, family):
        overrides, action, _ = FAMILIES[family]
        env = make_env(_cfg(overrides), None, 0)()
        o1, _ = env.reset(seed=1)
        o2, _ = env.reset(seed=2)
        assert not np.array_equal(o1["state"], o2["state"])
        env.close()


class TestLevelAxis:
    """The ``env.level`` difficulty axis (ISSUE 20 satellite): every jax env
    takes a level, a nonzero level changes the *dynamics* at a fixed seed,
    the default is bit-identical to the pre-axis envs (the gymnasium parity
    goldens in test_jax_envs.py stay untouched), and the knob plumbs through
    the JaxToGymAdapter config path and the scenario-matrix grid."""

    # per env: (fixed action, obs key to compare)
    JAX_ENVS = {
        "cartpole": (1, "state"),
        "pendulum": ([1.0], "state"),
        "forage": (1, "rgb"),
        "multiroom": (4, "rgb"),
    }

    @staticmethod
    def _traj(env, seed, action, obs_key, n=20):
        import jax
        import jax.numpy as jnp

        state, obs = env.reset(jax.random.PRNGKey(seed))
        act = jnp.asarray(action)
        traj = [np.asarray(obs[obs_key])]
        for _ in range(n):
            state, obs, _, _, _ = env.step(state, act)
            traj.append(np.asarray(obs[obs_key]))
        return traj

    @pytest.mark.parametrize("name", sorted(JAX_ENVS))
    def test_level_changes_dynamics_at_fixed_seed(self, name):
        from sheeprl_tpu.envs.jax.registry import make_jax_env

        action, obs_key = self.JAX_ENVS[name]
        t0 = self._traj(make_jax_env(name), 7, action, obs_key)
        t2 = self._traj(make_jax_env(name, level=2.0), 7, action, obs_key)
        assert any(not np.array_equal(a, b) for a, b in zip(t0, t2))
        if name in ("cartpole", "pendulum"):
            # classic control: the seeded reset is level-independent — the
            # divergence is purely in the transition function
            np.testing.assert_array_equal(t0[0], t2[0])
            assert not np.array_equal(t0[1], t2[1])

    @pytest.mark.parametrize("name", sorted(JAX_ENVS))
    def test_default_level_is_bit_identical(self, name):
        from sheeprl_tpu.envs.jax.registry import make_jax_env

        action, obs_key = self.JAX_ENVS[name]
        t_default = self._traj(make_jax_env(name), 11, action, obs_key)
        t_zero = self._traj(make_jax_env(name, level=0.0), 11, action, obs_key)
        for a, b in zip(t_default, t_zero):
            np.testing.assert_array_equal(a, b)

    def test_level_plumbs_through_adapter_config(self):
        from sheeprl_tpu.envs.jax.registry import jax_env_from_cfg

        # the top-level env.level knob reaches the registry ctor ...
        assert jax_env_from_cfg(_cfg(["env=jax_cartpole", "env.level=2.0"])).level == 2.0
        assert jax_env_from_cfg(_cfg(["env=jax_cartpole"])).level == 0.0
        # ... and the adapter (make_env) rollout actually feels it
        hard = make_env(_cfg(["env=jax_cartpole", "env.level=2.0"]), None, 0)()
        easy = make_env(_cfg(["env=jax_cartpole"]), None, 0)()
        th = _rollout(hard, 31, 1)
        te = _rollout(easy, 31, 1)
        np.testing.assert_array_equal(th[0], te[0])  # same seeded reset
        assert any(not np.array_equal(a, b) for a, b in zip(th[1:], te[1:]))
        hard.close()
        easy.close()

    def test_level_plumbs_through_scenario_matrix(self):
        from tests.scenario_matrix import build_cells

        cells = {name: overrides for name, overrides, _, _ in build_cells()}
        assert "ppo×jax_multiroom×coupled-anakin-cnn" in cells
        assert "ppo×jax_multiroom×coupled-adapter" in cells
        overrides = cells["ppo×jax_multiroom×coupled-anakin-cnn"]
        assert "env.level=1.0" in overrides
        cfg = compose(["env.num_envs=2", *overrides])
        assert float(cfg.env.level) == 1.0
        assert cfg.env.wrapper.kind == "jax"


class TestEpisodeEnd:
    def test_flags_exclusive_and_final_obs_surfaced(self, family):
        overrides, action, max_steps = FAMILIES[family]
        cfg = _cfg(overrides)
        envs = vectorize(cfg, [make_env(cfg, 5, 0, vector_env_idx=i) for i in range(2)])
        obs, _ = envs.reset(seed=5)
        saw_end = False
        act = np.full(2, action, dtype=np.int64)
        for _ in range(max_steps):
            obs, rew, term, trunc, info = envs.step(act)
            assert not (np.asarray(term) & np.asarray(trunc)).any()
            done = np.asarray(term) | np.asarray(trunc)
            if done.any():
                saw_end = True
                # SAME_STEP autoreset: real terminal obs in final_obs, the
                # returned obs is already the reset obs
                assert "final_obs" in info and "final_info" in info
                for i in np.nonzero(done)[0]:
                    assert info["final_obs"][i] is not None
                    assert "state" in info["final_obs"][i]
                break
        envs.close()
        assert saw_end
