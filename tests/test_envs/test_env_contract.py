"""One env contract across the three families the scenario matrix drives:
{dummy, cpu-gym, pure-JAX (adapter)} (ISSUE 11, satellite).

Per family, the same three claims:

* ``reset(seed=s)`` is reproducible (same seed → same first obs and same
  fixed-action trajectory) and seed-distinct where the env has any
  stochasticity to seed;
* exactly one of terminated/truncated is ever set on an episode end;
* through ``utils.env.vectorize`` (SAME_STEP autoreset) a finished episode
  surfaces ``final_obs``/``final_info`` in vector infos.
"""

import numpy as np
import pytest

from sheeprl_tpu.config.compose import compose
from sheeprl_tpu.utils.env import make_env, vectorize

# (family, compose overrides, fixed action, max steps to see an episode end)
FAMILIES = {
    "dummy": (
        ["env=dummy", "env.id=discrete_dummy",
         "env.wrapper.episode_len=12", "env.wrapper.random_start=True"],
        1,
        40,
    ),
    "cpu_gym": (
        ["env=gym", "env.id=CartPole-v1", "env.sync_env=True"],
        1,
        200,
    ),
    "jax": (
        ["env=jax_cartpole"],
        1,
        200,
    ),
}


def _cfg(overrides):
    return compose(
        [
            "exp=ppo", "algo.mlp_keys.encoder=[state]", "env.num_envs=2",
            "env.capture_video=False", *overrides,
        ]
    )


@pytest.fixture(params=sorted(FAMILIES), ids=sorted(FAMILIES))
def family(request):
    return request.param


def _rollout(env, seed, action, n=6):
    obs, _ = env.reset(seed=seed)
    traj = [obs["state"].copy()]
    for _ in range(n):
        obs, _, term, trunc, _ = env.step(action)
        traj.append(obs["state"].copy())
        if term or trunc:
            break
    return traj


class TestSeededReset:
    def test_same_seed_reproduces(self, family):
        overrides, action, _ = FAMILIES[family]
        env = make_env(_cfg(overrides), None, 0)()
        t1 = _rollout(env, 123, action)
        t2 = _rollout(env, 123, action)
        assert len(t1) == len(t2)
        for a, b in zip(t1, t2):
            np.testing.assert_array_equal(a, b)
        env.close()

    def test_different_seeds_diverge(self, family):
        overrides, action, _ = FAMILIES[family]
        env = make_env(_cfg(overrides), None, 0)()
        o1, _ = env.reset(seed=1)
        o2, _ = env.reset(seed=2)
        assert not np.array_equal(o1["state"], o2["state"])
        env.close()


class TestEpisodeEnd:
    def test_flags_exclusive_and_final_obs_surfaced(self, family):
        overrides, action, max_steps = FAMILIES[family]
        cfg = _cfg(overrides)
        envs = vectorize(cfg, [make_env(cfg, 5, 0, vector_env_idx=i) for i in range(2)])
        obs, _ = envs.reset(seed=5)
        saw_end = False
        act = np.full(2, action, dtype=np.int64)
        for _ in range(max_steps):
            obs, rew, term, trunc, info = envs.step(act)
            assert not (np.asarray(term) & np.asarray(trunc)).any()
            done = np.asarray(term) | np.asarray(trunc)
            if done.any():
                saw_end = True
                # SAME_STEP autoreset: real terminal obs in final_obs, the
                # returned obs is already the reset obs
                assert "final_obs" in info and "final_info" in info
                for i in np.nonzero(done)[0]:
                    assert info["final_obs"][i] is not None
                    assert "state" in info["final_obs"][i]
                break
        envs.close()
        assert saw_end
