"""Suite wrappers (MineRL / MineDojo / DIAMBRA / Super Mario Bros) against
mock backends.

The real backends (Java Minecraft, the DIAMBRA docker engine, nes-py) are
not installable in this image; these tests drive the full conversion logic
— action maps, sticky actions, inventory/mask vectorization, termination
semantics — through fake simulators wired in via each module's
``_make_backend`` / ``_item_vocab`` seams.
"""

from typing import Any, Dict, List, Optional

import numpy as np
import pytest
from gymnasium import spaces

import sheeprl_tpu.envs.minedojo as minedojo_mod
import sheeprl_tpu.envs.minerl as minerl_mod
import sheeprl_tpu.envs.super_mario_bros as smb_mod
import sheeprl_tpu.envs.diambra as diambra_mod
from sheeprl_tpu.envs.minerl_envs import specs as minerl_specs


# =========================================================================
# Super Mario Bros
# =========================================================================
class _FakeNES:
    """Old-gym NES backend: reset()->obs, step()->(obs, r, done, info)."""

    def __init__(self):
        self.observation_space = spaces.Box(0, 255, (240, 256, 3), np.uint8)
        self.action_space = spaces.Discrete(7)
        self.next = (0.0, False, {"time": 300})

    def reset(self, seed=None, options=None):
        return np.zeros((240, 256, 3), np.uint8)

    def step(self, action):
        assert isinstance(action, int)
        r, done, info = self.next
        return np.full((240, 256, 3), 7, np.uint8), r, done, info

    def close(self):
        pass


@pytest.fixture
def smb(monkeypatch):
    fake = _FakeNES()
    monkeypatch.setattr(smb_mod, "_make_backend", lambda env_id, action_set: fake)
    return smb_mod.SuperMarioBrosWrapper("SuperMarioBros-v0", action_space="simple"), fake


def test_smb_spaces_and_reset(smb):
    env, _ = smb
    obs, info = env.reset()
    assert set(env.observation_space.spaces) == {"rgb"}
    assert obs["rgb"].shape == (240, 256, 3)
    assert env.action_space == spaces.Discrete(7)


def test_smb_death_is_terminated(smb):
    env, fake = smb
    env.reset()
    fake.next = (-15.0, True, {"time": 250})  # died with time on the clock
    _, r, terminated, truncated, _ = env.step(np.array([3]))
    assert terminated and not truncated and r == -15.0


def test_smb_timeout_is_truncated(smb):
    env, fake = smb
    env.reset()
    fake.next = (0.0, True, {"time": 0})  # timer expired
    _, _, terminated, truncated, _ = env.step(2)
    assert truncated and not terminated


def test_smb_new_api_backend(monkeypatch):
    fake = _FakeNES()

    def step5(action):
        return np.zeros((240, 256, 3), np.uint8), 1.0, False, True, {"time": 100}

    fake.step = step5
    monkeypatch.setattr(smb_mod, "_make_backend", lambda env_id, action_set: fake)
    env = smb_mod.SuperMarioBrosWrapper("SuperMarioBros-v0")
    env.reset()
    _, r, terminated, truncated, _ = env.step(0)
    assert r == 1.0 and truncated and not terminated


def test_smb_rejects_unknown_action_set(monkeypatch):
    with pytest.raises(ValueError):
        smb_mod.SuperMarioBrosWrapper("SuperMarioBros-v0", action_space="bogus")


# =========================================================================
# DIAMBRA
# =========================================================================
class _FakeArena:
    def __init__(self):
        self.observation_space = spaces.Dict(
            {
                "frame": spaces.Box(0, 255, (64, 64, 3), np.uint8),
                "stage": spaces.Discrete(5),
                "moves": spaces.MultiDiscrete([9, 4]),
            }
        )
        self.action_space = spaces.Discrete(10)
        self.last_action: Any = None
        self.info: Dict[str, Any] = {}

    def reset(self, seed=None, options=None):
        return self._obs(), {}

    def step(self, action):
        self.last_action = action
        return self._obs(), 1.5, False, False, dict(self.info)

    def _obs(self):
        return {
            "frame": np.zeros((64, 64, 3), np.uint8),
            "stage": 2,
            "moves": np.array([3, 1]),
        }

    def close(self):
        pass


@pytest.fixture
def diambra(monkeypatch):
    fake = _FakeArena()
    monkeypatch.setattr(diambra_mod, "_make_backend", lambda *a, **k: fake)
    return diambra_mod.DiambraWrapper("doapp"), fake


def test_diambra_space_flattening(diambra):
    env, _ = diambra
    assert isinstance(env.observation_space["stage"], spaces.Box)
    assert env.observation_space["stage"].shape == (1,)
    assert env.observation_space["moves"].shape == (2,)
    obs, info = env.reset()
    assert obs["stage"].shape == (1,) and obs["stage"][0] == 2
    assert obs["moves"].shape == (2,)
    assert info["env_domain"] == "DIAMBRA"


def test_diambra_env_done_terminates(diambra):
    env, fake = diambra
    env.reset()
    fake.info = {"env_done": True}
    _, _, terminated, _, info = env.step(np.array([4]))
    assert terminated
    assert fake.last_action == 4  # squeezed to a python int for DISCRETE


def test_diambra_validates_args():
    with pytest.raises(ValueError):
        diambra_mod.DiambraWrapper("doapp", action_space="BOGUS")
    with pytest.raises(ValueError):
        diambra_mod.DiambraWrapper("doapp", diambra_settings={"role": "P3"})


def test_diambra_managed_settings_warn(monkeypatch):
    fake = _FakeArena()
    monkeypatch.setattr(diambra_mod, "_make_backend", lambda *a, **k: fake)
    with pytest.warns(UserWarning):
        diambra_mod.DiambraWrapper("doapp", diambra_settings={"n_players": 2})


# =========================================================================
# MineDojo
# =========================================================================
_VOCAB = ["air", "log", "planks", "stone", "wooden_pickaxe"]
_CRAFT = ["planks", "stick", "crafting_table"]


class _FakeMineDojo:
    def __init__(self):
        self.observation_space = spaces.Dict(
            {"rgb": spaces.Box(0, 255, (64, 64, 3), np.uint8)}
        )
        self.actions: List[np.ndarray] = []
        self.done = False
        self.info: Dict[str, Any] = {}
        self.unwrapped = self
        self._prev_obs = None

    def make_obs(self, *, inv_names=("air", "log"), inv_qty=(1, 3), pitch=0.0):
        n_slots = len(inv_names)
        return {
            "rgb": np.zeros((64, 64, 3), np.uint8),
            "inventory": {
                "name": np.array(inv_names, dtype=object),
                "quantity": np.asarray(inv_qty, dtype=np.float32),
            },
            "delta_inv": {
                "inc_name_by_craft": ["planks"],
                "inc_quantity_by_craft": [4],
                "dec_name_by_craft": ["log"],
                "dec_quantity_by_craft": [1],
                "inc_name_by_other": [],
                "inc_quantity_by_other": [],
                "dec_name_by_other": [],
                "dec_quantity_by_other": [],
            },
            "equipment": {"name": ["wooden pickaxe"]},
            "life_stats": {
                "life": np.array([20.0]),
                "food": np.array([20.0]),
                "oxygen": np.array([300.0]),
            },
            "masks": {
                "action_type": np.ones(8, dtype=bool),
                "equip": np.array([False] * n_slots),
                "destroy": np.array([True] * n_slots),
                "craft_smelt": np.array([True, False, True]),
            },
            "location_stats": {
                "pos": np.array([0.0, 64.0, 0.0]),
                "pitch": np.array([pitch]),
                "yaw": np.array([0.0]),
                "biome_id": np.array([1]),
            },
        }

    def reset(self):
        return self.make_obs()

    def step(self, action):
        self.actions.append(np.asarray(action).copy())
        return self.make_obs(), 1.0, self.done, dict(self.info)

    def close(self):
        pass


@pytest.fixture
def minedojo(monkeypatch):
    fake = _FakeMineDojo()
    monkeypatch.setattr(minedojo_mod, "_item_vocab", lambda: (_VOCAB, _CRAFT))
    monkeypatch.setattr(minedojo_mod, "_make_backend", lambda *a, **k: fake)
    env = minedojo_mod.MineDojoWrapper("open-ended", sticky_attack=0, sticky_jump=0,
                                       break_speed_multiplier=100)
    return env, fake


def test_minedojo_spaces(minedojo):
    env, _ = minedojo
    n = len(_VOCAB)
    assert list(env.action_space.nvec) == [19, len(_CRAFT), n]
    assert env.observation_space["inventory"].shape == (n,)
    assert env.observation_space["mask_action_type"].shape == (19,)
    assert env.observation_space["mask_craft_smelt"].shape == (len(_CRAFT),)


def test_minedojo_inventory_and_masks(minedojo):
    env, _ = minedojo
    obs, info = env.reset()
    # slot air counts 1 per slot, log counts quantity
    assert obs["inventory"][_VOCAB.index("air")] == 1.0
    assert obs["inventory"][_VOCAB.index("log")] == 3.0
    assert obs["inventory_delta"][_VOCAB.index("planks")] == 4.0
    assert obs["inventory_delta"][_VOCAB.index("log")] == -1.0
    assert obs["equipment"][_VOCAB.index("wooden_pickaxe")] == 1
    # nothing equippable -> equip/place compound actions masked off
    assert not obs["mask_equip_place"].any()
    mask = obs["mask_action_type"]
    assert mask[:12].all()
    equip_idx = 12 + minedojo_mod.FN_EQUIP - 1
    place_idx = 12 + minedojo_mod.FN_PLACE - 1
    destroy_idx = 12 + minedojo_mod.FN_DESTROY - 1
    assert not mask[equip_idx] and not mask[place_idx]
    assert mask[destroy_idx]  # destroyables exist
    assert obs["life_stats"].tolist() == [20.0, 20.0, 300.0]


def test_minedojo_action_conversion(minedojo):
    env, fake = minedojo
    env.reset()
    # forward
    env.step(np.array([1, 0, 0]))
    assert fake.actions[-1][minedojo_mod.SLOT_MOVE] == 1
    # craft passes the craft argument through
    craft_action = 12 + minedojo_mod.FN_CRAFT - 1
    env.step(np.array([craft_action, 2, 0]))
    assert fake.actions[-1][minedojo_mod.SLOT_FN] == minedojo_mod.FN_CRAFT
    assert fake.actions[-1][minedojo_mod.SLOT_CRAFT_ARG] == 2
    # destroy resolves the item id to its inventory slot (log is slot 1)
    destroy_action = 12 + minedojo_mod.FN_DESTROY - 1
    env.step(np.array([destroy_action, 0, _VOCAB.index("log")]))
    assert fake.actions[-1][minedojo_mod.SLOT_INV_ARG] == 1


def test_minedojo_pitch_clamp(monkeypatch):
    fake = _FakeMineDojo()
    monkeypatch.setattr(minedojo_mod, "_item_vocab", lambda: (_VOCAB, _CRAFT))
    monkeypatch.setattr(minedojo_mod, "_make_backend", lambda *a, **k: fake)
    env = minedojo_mod.MineDojoWrapper("open-ended", pitch_limits=(-60, 60))
    env.reset()
    fake.make_obs = lambda **kw: _FakeMineDojo.make_obs(fake, pitch=60.0)
    env.step(np.array([9, 0, 0]))  # pitch up from 0: fine
    env.step(np.array([9, 0, 0]))  # pitch up from 60: must be clamped
    assert fake.actions[-1][minedojo_mod.SLOT_PITCH] == minedojo_mod.CAMERA_NOOP


def test_minedojo_sticky_attack(monkeypatch):
    fake = _FakeMineDojo()
    monkeypatch.setattr(minedojo_mod, "_item_vocab", lambda: (_VOCAB, _CRAFT))
    monkeypatch.setattr(minedojo_mod, "_make_backend", lambda *a, **k: fake)
    env = minedojo_mod.MineDojoWrapper(
        "open-ended", sticky_attack=3, sticky_jump=0, break_speed_multiplier=1
    )
    env.reset()
    attack = 12 + minedojo_mod.FN_ATTACK - 1
    env.step(np.array([attack, 0, 0]))
    env.step(np.array([0, 0, 0]))  # no-op -> attack repeats
    assert fake.actions[-1][minedojo_mod.SLOT_FN] == minedojo_mod.FN_ATTACK
    craft = 12 + minedojo_mod.FN_CRAFT - 1
    env.step(np.array([craft, 0, 0]))  # other functional action interrupts
    env.step(np.array([0, 0, 0]))
    assert fake.actions[-1][minedojo_mod.SLOT_FN] == minedojo_mod.FN_NOOP


def test_minedojo_sticky_jump(monkeypatch):
    fake = _FakeMineDojo()
    monkeypatch.setattr(minedojo_mod, "_item_vocab", lambda: (_VOCAB, _CRAFT))
    monkeypatch.setattr(minedojo_mod, "_make_backend", lambda *a, **k: fake)
    env = minedojo_mod.MineDojoWrapper("open-ended", sticky_attack=0, sticky_jump=5)
    env.reset()
    env.step(np.array([5, 0, 0]))  # jump+forward
    env.step(np.array([0, 0, 0]))  # no-op: jump held, forced forward
    assert fake.actions[-1][minedojo_mod.SLOT_JUMP] == 1
    assert fake.actions[-1][minedojo_mod.SLOT_MOVE] == 1


# =========================================================================
# MineRL
# =========================================================================
class _EnumSpace(spaces.Space):
    def __init__(self, values):
        super().__init__((), np.dtype(object))
        self.values = np.array(values, dtype=object)

    def sample(self, mask=None):
        return self.values[0]

    def contains(self, x):
        return x in self.values


def _fake_minerl_backend(with_compass=True, with_equipment=False):
    class _Backend:
        def __init__(self):
            self.action_space = spaces.Dict(
                {
                    "forward": spaces.Discrete(2),
                    "jump": spaces.Discrete(2),
                    "attack": spaces.Discrete(2),
                    "camera": spaces.Box(-180.0, 180.0, (2,), np.float32),
                    "place": _EnumSpace(["none", "dirt"]),
                }
            )
            obs = {
                "pov": spaces.Box(0, 255, (64, 64, 3), np.uint8),
                "inventory": spaces.Dict({"dirt": spaces.Box(0, 2304, (), np.float32)}),
            }
            if with_compass:
                obs["compass"] = spaces.Dict(
                    {"angle": spaces.Box(-180.0, 180.0, (), np.float32)}
                )
            if with_equipment:
                obs["equipped_items"] = spaces.Dict(
                    {"mainhand": spaces.Dict({"type": _EnumSpace(["air", "iron_pickaxe"])})}
                )
            self.observation_space = spaces.Dict(obs)
            self.actions: List[Dict[str, Any]] = []
            self.with_equipment = with_equipment

        def make_obs(self):
            out = {
                "pov": np.zeros((64, 64, 3), np.uint8),
                "life_stats": {"life": 20.0, "food": 20.0, "air": 300.0},
                "inventory": {"dirt": np.float32(5.0), "air": np.float32(64.0)},
            }
            if with_compass:
                out["compass"] = {"angle": np.float32(42.0)}
            if self.with_equipment:
                out["equipped_items"] = {"mainhand": {"type": "unknown_item"}}
            return out

        def reset(self):
            return self.make_obs()

        def step(self, action):
            self.actions.append(action)
            return self.make_obs(), 0.5, False, {}

        def close(self):
            pass

    return _Backend()


@pytest.fixture
def minerl(monkeypatch):
    fake = _fake_minerl_backend()
    monkeypatch.setattr(minerl_mod, "_make_backend", lambda *a, **k: fake)
    monkeypatch.setattr(minerl_mod, "_item_vocab", lambda: ["air", "dirt", "stone"])
    env = minerl_mod.MineRLWrapper(
        "custom_navigate", sticky_attack=0, sticky_jump=0,
        break_speed_multiplier=100, multihot_inventory=True,
    )
    return env, fake


def test_minerl_action_map_enumeration(minerl):
    env, _ = minerl
    # 1 noop + forward + jump + attack + 4 camera turns + 1 place value
    assert env.action_space.n == 9
    amap = env.actions_map
    assert amap[0] == {}
    # jump also presses forward
    jump_actions = [a for a in amap.values() if a.get("jump") == 1]
    assert jump_actions and all(a.get("forward") == 1 for a in jump_actions)
    place_actions = [a for a in amap.values() if "place" in a]
    assert place_actions == [{"place": "dirt"}]


def test_minerl_obs_conversion(minerl):
    env, _ = minerl
    obs, _ = env.reset()
    assert obs["rgb"].shape == (64, 64, 3)  # channel-last, no transpose
    assert obs["life_stats"].tolist() == [20.0, 20.0, 300.0]
    assert obs["inventory"][1] == 5.0  # dirt
    assert obs["inventory"][0] == 1.0  # air counted once
    assert obs["compass"].shape == (1,) and obs["compass"][0] == 42.0


def test_minerl_max_inventory_tracks(minerl):
    env, fake = minerl
    env.reset()
    obs, *_ = env.step(np.array(0))
    assert obs["max_inventory"][1] == 5.0


def test_minerl_pitch_clamp_and_yaw_wrap(minerl):
    env, fake = minerl
    env.reset()
    # camera actions: find pitch-down (negative pitch delta)
    pitch_down = next(
        i for i, a in env.actions_map.items()
        if "camera" in a and np.asarray(a["camera"])[0] < 0
    )
    for _ in range(4):  # 4 * -15° = -60° : at the limit
        env.step(np.array(pitch_down))
    env.step(np.array(pitch_down))  # would pass -60 -> camera zeroed
    assert np.asarray(fake.actions[-1]["camera"])[0] == 0.0
    yaw_left = next(
        i for i, a in env.actions_map.items()
        if "camera" in a and np.asarray(a["camera"])[1] < 0
    )
    for _ in range(13):  # 13 * -15 = -195 -> wraps to +165
        env.step(np.array(yaw_left))
    assert env._pos["yaw"] == pytest.approx(165.0)


def test_minerl_sticky_attack_releases_jump(monkeypatch):
    fake = _fake_minerl_backend()
    monkeypatch.setattr(minerl_mod, "_make_backend", lambda *a, **k: fake)
    monkeypatch.setattr(minerl_mod, "_item_vocab", lambda: ["air", "dirt"])
    env = minerl_mod.MineRLWrapper(
        "custom_navigate", sticky_attack=3, sticky_jump=2, break_speed_multiplier=1
    )
    env.reset()
    attack = next(i for i, a in env.actions_map.items() if a.get("attack") == 1)
    jump = next(i for i, a in env.actions_map.items() if a.get("jump") == 1)
    env.step(np.array(attack))
    sent = fake.actions[-1]
    assert sent["attack"] == 1
    env.step(np.array(jump))  # sticky attack still holds: jump suppressed
    sent = fake.actions[-1]
    assert sent["attack"] == 1 and sent["jump"] == 0
    env.reset()
    assert env._sticky_attack_counter == 0


def test_minerl_task_local_inventory(monkeypatch):
    fake = _fake_minerl_backend(with_equipment=True)
    monkeypatch.setattr(minerl_mod, "_make_backend", lambda *a, **k: fake)
    env = minerl_mod.MineRLWrapper(
        "custom_obtain_diamond", multihot_inventory=False, sticky_attack=0, sticky_jump=0,
    )
    # task-local inventory: only the backend's own item list
    assert env.inventory_size == 1
    obs, _ = env.reset()
    # unknown equipped item falls back to "air"
    assert obs["equipment"][0] == 1


def test_minerl_specs_data():
    nav = minerl_specs.navigate_spec(dense=True, extreme=False)
    assert nav.compass and nav.start_inventory == (("compass", 1),)
    assert minerl_specs.success_from_rewards(nav, [100.0, 60.0])
    assert not minerl_specs.success_from_rewards(nav, [100.0])
    dia = minerl_specs.obtain_diamond_spec(dense=False)
    assert dia.milestones[-1] == ("diamond", 1024.0)
    assert len(dia.milestones) == 12
    # success tolerates 10% missing distinct milestone values (1 of 10)
    rewards = sorted({r for _, r in dia.milestones})[:-1]
    assert minerl_specs.success_from_rewards(dia, rewards)
    assert not minerl_specs.success_from_rewards(dia, rewards[:-1])
    pick = minerl_specs.obtain_iron_pickaxe_spec(dense=False)
    assert pick.quit_on_craft == (("iron_pickaxe", 1),)
