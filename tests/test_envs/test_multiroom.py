"""JaxMultiRoom — the procedural multi-room pixel gridworld (ISSUE 20).

Pins the env's design claims: in-trace per-episode layout generation
(reseeded on reset, completable by construction), the unlock-progression
mechanics (key → door → next room → goal terminates), the pixel contract,
and the traced room-count difficulty axis.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sheeprl_tpu.envs.jax.core import VectorJaxEnv
from sheeprl_tpu.envs.jax.multiroom import _MAX_WALLS, JaxMultiRoom, MultiRoomState


def _env(**kw):
    return JaxMultiRoom(**kw)


class TestLayoutGeneration:
    def test_procedural_reset_reseeds_layout(self):
        env = _env()
        s1, _ = env.reset(jax.random.PRNGKey(0))
        s2, _ = env.reset(jax.random.PRNGKey(1))
        layout1 = np.concatenate(
            [np.asarray(s1.door_row), np.asarray(s1.key_pos).ravel(), np.asarray(s1.food).ravel()]
        )
        layout2 = np.concatenate(
            [np.asarray(s2.door_row), np.asarray(s2.key_pos).ravel(), np.asarray(s2.food).ravel()]
        )
        assert not np.array_equal(layout1, layout2)
        # same seed → same layout (pure function of the key)
        s1b, _ = env.reset(jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(s1.door_row), np.asarray(s1b.door_row))
        np.testing.assert_array_equal(np.asarray(s1.key_pos), np.asarray(s1b.key_pos))

    def test_every_layout_is_completable(self):
        # key w strictly LEFT of wall w and never on a wall column, so
        # rooms always unlock in order
        env = _env()
        for seed in range(50):
            s, _ = env.reset(jax.random.PRNGKey(seed))
            key_col = np.asarray(s.key_pos)[:, 1]
            for w, c in enumerate(env.wall_cols):
                assert key_col[w] < c
                assert key_col[w] not in env.wall_cols
            # goal in the last column, agent starts in column 0
            assert int(np.asarray(s.goal)[1]) == env.grid - 1
            assert int(np.asarray(s.pos)[1]) == 0

    def test_ctor_validation(self):
        with pytest.raises(ValueError, match="grid"):
            _env(grid=4)
        with pytest.raises(ValueError, match="multiple"):
            _env(grid=8, image_hw=60)


class TestMechanics:
    def _state(self, env, **overrides):
        s, _ = env.reset(jax.random.PRNGKey(0))
        return s._replace(**{k: jnp.asarray(v) for k, v in overrides.items()})

    def test_wall_blocks_until_key_opens_door(self):
        env = _env()
        wall = env.wall_cols[0]
        # agent just left of wall 0, in the door row, door closed
        s = self._state(
            env,
            pos=np.array([3, wall - 1], np.int32),
            door_row=np.array([2, 0, 0], np.int32),
            door_open=np.zeros(_MAX_WALLS, bool),
        )
        s2, _, r, term, _ = env.step(s, jnp.asarray(4))  # right, into the wall
        assert tuple(np.asarray(s2.pos)) == (3, wall - 1)  # blocked
        assert float(r) == 0.0 and not bool(term)
        # in the door row with the door open: passes through
        s = self._state(
            env,
            pos=np.array([2, wall - 1], np.int32),
            door_row=np.array([2, 0, 0], np.int32),
            door_open=np.array([True, False, False]),
        )
        s2, _, _, _, _ = env.step(s, jnp.asarray(4))
        assert tuple(np.asarray(s2.pos)) == (2, wall)

    def test_key_pickup_pays_and_unlocks(self):
        env = _env()
        s = self._state(
            env,
            pos=np.array([5, 0], np.int32),
            key_pos=np.array([[5, 1], [0, 3], [0, 5]], np.int32),
            food=np.zeros((env.grid, env.grid), bool),
        )
        s2, _, r, _, _ = env.step(s, jnp.asarray(4))  # right onto key 0
        assert float(r) == pytest.approx(0.2)
        assert bool(np.asarray(s2.key_taken)[0]) and bool(np.asarray(s2.door_open)[0])
        # second visit pays nothing (key gone)
        s3 = s2._replace(pos=jnp.asarray(np.array([5, 0], np.int32)))
        s4, _, r2, _, _ = env.step(s3, jnp.asarray(4))
        assert float(r2) == 0.0

    def test_food_pays_once(self):
        env = _env()
        food = np.zeros((env.grid, env.grid), bool)
        food[6, 1] = True
        s = self._state(env, pos=np.array([6, 0], np.int32), food=food)
        s2, _, r, _, _ = env.step(s, jnp.asarray(4))
        assert float(r) == pytest.approx(0.1)
        assert not bool(np.asarray(s2.food)[6, 1])

    def test_goal_pays_and_terminates(self):
        env = _env()
        s = self._state(
            env,
            pos=np.array([4, env.grid - 2], np.int32),
            goal=np.array([4, env.grid - 1], np.int32),
            food=np.zeros((env.grid, env.grid), bool),
        )
        _, _, r, term, trunc = env.step(s, jnp.asarray(4))
        assert float(r) == pytest.approx(1.0)
        assert bool(term) and not bool(trunc)

    def test_truncates_at_step_limit(self):
        env = _env(max_episode_steps=3)
        s, _ = env.reset(jax.random.PRNGKey(2))
        term = trunc = False
        for _ in range(3):
            s, _, _, term, trunc = env.step(s, jnp.asarray(0))  # noop
        assert bool(trunc) and not bool(term)


class TestPixelsAndLevel:
    def test_pixel_contract(self):
        env = _env()
        _, obs = env.reset(jax.random.PRNGKey(0))
        assert obs["rgb"].shape == (64, 64, 3) and obs["rgb"].dtype == jnp.uint8
        img = np.asarray(obs["rgb"])
        # agent (white) and goal (blue) visible; default level renders
        # exactly ONE wall column (gray/red), the others are floor
        assert (img == 255).all(axis=-1).any()
        assert (img == np.array([0, 0, 255])).all(axis=-1).any()
        cell = env.cell
        wall_px = [c * cell for c in env.wall_cols]
        col0 = img[:, wall_px[0], :]
        assert ((col0 == 128).all(axis=-1) | (col0 == np.array([200, 0, 0])).all(axis=-1)).all()
        assert (img[:, wall_px[1], :] == 0).all(axis=-1).sum() > 0  # inactive → floor

    def test_level_activates_more_walls(self):
        hard = _env(level=2.0)
        s, obs = hard.reset(jax.random.PRNGKey(0))
        assert int(hard._n_walls(s.level)) == 3
        img = np.asarray(obs["rgb"])
        cell = hard.cell
        for c in hard.wall_cols:  # all three walls render solid
            col = img[:, c * cell, :]
            assert ((col == 128).all(axis=-1) | (col == np.array([200, 0, 0])).all(axis=-1)).all()

    def test_level_rides_the_carry_through_autoreset(self):
        # a curriculum-overridden traced level survives episode ends
        venv = VectorJaxEnv(_env(max_episode_steps=4), 2)
        state, _ = venv.reset(jax.random.PRNGKey(0))
        state = state._replace(level=jnp.full((2,), 1.5, jnp.float32))
        step = jax.jit(venv.step)
        for _ in range(12):  # crosses 3 truncation boundaries
            state, *_ = step(state, jnp.zeros((2,), jnp.int32))
        np.testing.assert_array_equal(np.asarray(state.level), 1.5)

    def test_fused_rollout_scan_traces(self):
        # the whole env steps inside one jitted scan (the Anakin property)
        venv = VectorJaxEnv(_env(), 4)

        @jax.jit
        def run(key):
            state, obs = venv.reset(key)

            def body(carry, k):
                state = carry
                a = jax.random.randint(k, (4,), 0, 5)
                state, obs, r, term, trunc, _ = venv.step(state, a)
                return state, r

            _, rews = jax.lax.scan(body, state, jax.random.split(jax.random.PRNGKey(1), 32))
            return rews

        rews = run(jax.random.PRNGKey(0))
        assert rews.shape == (32, 4)
