"""Unit tests for the environment factory (``sheeprl_tpu.utils.env``).

Covers the wrapper pipeline assembly the E2E tests exercise only
implicitly (reference surface: sheeprl/utils/env.py:26-231): Dict
normalization, image resize/grayscale, frame stacking, reward/actions as
observations, reward clipping, TimeLimit, seeding determinism, and the
Async vectorization path (VERDICT r1 weak #8: "no AsyncVectorEnv run, no
make_env unit tests").
"""

import numpy as np
import pytest
from gymnasium import spaces

from sheeprl_tpu.config.compose import compose
from sheeprl_tpu.utils.env import episode_stats, make_env, vectorize


def _cfg(*overrides):
    return compose(
        [
            "exp=ppo",
            "env=dummy",
            "env.id=discrete_dummy",
            "env.capture_video=False",
            "env.num_envs=2",
            "print_config=False",
            *overrides,
        ]
    )


def test_dict_obs_and_image_pipeline():
    cfg = _cfg("env.screen_size=32", "env.grayscale=True")
    env = make_env(cfg, seed=0)()
    obs_space = env.observation_space
    assert isinstance(obs_space, spaces.Dict)
    assert obs_space["rgb"].shape == (32, 32, 1)  # resized + grayscaled, HWC
    assert obs_space["rgb"].dtype == np.uint8
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (32, 32, 1)
    assert obs["state"].shape == (4,)
    env.close()


def test_frame_stack_prepends_axis():
    cfg = _cfg("env.screen_size=16", "env.frame_stack=3")
    env = make_env(cfg, seed=0)()
    assert env.observation_space["rgb"].shape == (3, 16, 16, 3)
    obs, _ = env.reset(seed=0)
    for _ in range(5):
        obs, *_ = env.step(env.action_space.sample())
    assert obs["rgb"].shape == (3, 16, 16, 3)
    env.close()


def test_reward_and_actions_as_observation():
    cfg = _cfg(
        "env.reward_as_observation=True",
        "env.actions_as_observation.num_stack=2",
        "env.actions_as_observation.noop=0",
    )
    env = make_env(cfg, seed=0)()
    sp = env.observation_space
    assert "reward" in sp.spaces and sp["reward"].shape == (1,)
    # discrete noop → one-hot stack of 2 actions, 4 classes each
    assert "action_stack" in sp.spaces or any("action" in k for k in sp.spaces)
    obs, _ = env.reset(seed=0)
    obs, r, *_ = env.step(0)
    assert obs["reward"].shape == (1,)
    env.close()


def test_clip_rewards_tanh():
    cfg = _cfg("env.clip_rewards=True")
    env = make_env(cfg, seed=0)()
    env.reset(seed=0)
    _, r, *_ = env.step(0)
    assert abs(r) <= 1.0
    assert r == pytest.approx(np.tanh(1.0))  # dummy env emits reward 1.0
    env.close()


def test_time_limit_truncates():
    cfg = _cfg("env.max_episode_steps=3")
    env = make_env(cfg, seed=0)()
    env.reset(seed=0)
    truncated = False
    for _ in range(3):
        *_, truncated, _ = env.step(0)
    assert truncated
    env.close()


def test_action_repeat_wraps_non_engine_suites():
    cfg = _cfg("env.action_repeat=2", "env.max_episode_steps=0")
    env = make_env(cfg, seed=0)()
    env.reset(seed=0)
    obs, r, *_ = env.step(0)
    # dummy env emits reward 1.0/step and encodes step count into "state"
    assert r == 2.0
    assert obs["state"][0] == 2.0
    env.close()


def test_seeding_is_deterministic():
    cfg = _cfg()
    e1 = make_env(cfg, seed=7)()
    e2 = make_env(cfg, seed=7)()
    a1 = [e1.action_space.sample() for _ in range(5)]
    a2 = [e2.action_space.sample() for _ in range(5)]
    assert a1 == a2
    e1.close()
    e2.close()


def test_unknown_dummy_env_raises():
    cfg = _cfg("env.id=not_a_dummy")
    with pytest.raises(ValueError, match="Unknown"):
        make_env(cfg, seed=0)()


@pytest.mark.parametrize("sync", [True, False], ids=["sync", "async"])
def test_vectorize_same_step_autoreset(sync):
    """Both vectorization modes run the pipeline and surface final_obs /
    episode stats with SAME_STEP autoreset semantics."""
    cfg = _cfg(f"env.sync_env={sync}", "env.max_episode_steps=4")
    envs = vectorize(cfg, [make_env(cfg, seed=3, vector_env_idx=i) for i in range(2)])
    try:
        obs, _ = envs.reset(seed=3)
        assert obs["rgb"].shape[0] == 2
        stats = []
        for _ in range(6):
            actions = np.stack([envs.single_action_space.sample() for _ in range(2)])
            obs, rewards, terminated, truncated, info = envs.step(actions)
            done = np.logical_or(terminated, truncated)
            if done.any():
                assert info.get("final_obs") is not None
                rows = [info["final_obs"][i] for i in np.nonzero(done)[0]]
                assert all(isinstance(r, dict) and "rgb" in r for r in rows)
            stats.extend(episode_stats(info))
        # 2 envs × 6 steps with a 4-step limit → at least one finished episode
        assert stats and all(length == 4 for _, length in stats)
    finally:
        envs.close()
