"""Wrapper tests (reference parity: tests/test_envs/test_frame_stack.py,
test_actions_as_observations.py, test_make_env.py)."""

import gymnasium as gym
import numpy as np
import pytest

from sheeprl_tpu.config.compose import compose
from sheeprl_tpu.envs.dummy import ContinuousDummyEnv, DiscreteDummyEnv
from sheeprl_tpu.envs.wrappers import (
    ActionRepeat,
    ActionsAsObservationWrapper,
    FrameStack,
    RestartOnException,
    RewardAsObservationWrapper,
)
from sheeprl_tpu.utils.env import make_env


class TestFrameStack:
    def test_stack_shape_and_rolling(self):
        env = FrameStack(DiscreteDummyEnv(), num_stack=4, cnn_keys=["rgb"])
        obs, _ = env.reset()
        assert obs["rgb"].shape == (4, 64, 64, 3)
        # after reset all frames identical
        assert np.all(obs["rgb"][0] == obs["rgb"][-1])
        obs, *_ = env.step(env.action_space.sample())
        # newest frame differs from oldest after a step
        assert obs["rgb"][-1][0, 0, 0] != obs["rgb"][0][0, 0, 0]

    def test_dilation(self):
        env = FrameStack(DiscreteDummyEnv(), num_stack=2, cnn_keys=["rgb"], dilation=2)
        env.reset()
        for _ in range(4):
            obs, *_ = env.step(env.action_space.sample())
        # with dilation 2 the two stacked frames are 2 steps apart
        assert int(obs["rgb"][1][0, 0, 0]) - int(obs["rgb"][0][0, 0, 0]) == 2

    def test_invalid_args(self):
        with pytest.raises(ValueError):
            FrameStack(DiscreteDummyEnv(), num_stack=0, cnn_keys=["rgb"])
        with pytest.raises(RuntimeError):
            FrameStack(DiscreteDummyEnv(), num_stack=2, cnn_keys=[])


class TestActionsAsObservation:
    @pytest.mark.parametrize("env_cls, noop", [(DiscreteDummyEnv, 0), (ContinuousDummyEnv, [0.0, 0.0])])
    def test_action_stack_key(self, env_cls, noop):
        env = ActionsAsObservationWrapper(env_cls(), num_stack=3, noop=noop)
        obs, _ = env.reset()
        assert "action_stack" in obs
        expected = 3 * (4 if env_cls is DiscreteDummyEnv else 2)
        assert obs["action_stack"].shape == (expected,)
        obs, *_ = env.step(env.action_space.sample())
        assert obs["action_stack"].shape == (expected,)

    def test_invalid_num_stack(self):
        with pytest.raises(ValueError):
            ActionsAsObservationWrapper(DiscreteDummyEnv(), num_stack=0, noop=0)


class TestRestartOnException:
    def test_restarts_crashed_env(self):
        calls = {"n": 0}

        class Crashy(DiscreteDummyEnv):
            def step(self, action):
                calls["n"] += 1
                if calls["n"] == 3:
                    raise RuntimeError("boom")
                return super().step(action)

        env = RestartOnException(lambda: Crashy(), max_restarts=2)
        env.reset()
        infos = []
        for _ in range(5):
            obs, r, term, trunc, info = env.step(env.action_space.sample())
            infos.append(info)
        assert any(i.get("restart_on_exception") for i in infos)

    def test_gives_up_after_max_restarts(self):
        class AlwaysCrash(DiscreteDummyEnv):
            def step(self, action):
                raise RuntimeError("boom")

        env = RestartOnException(lambda: AlwaysCrash(), max_restarts=1, window=60.0)
        env.reset()
        with pytest.raises(RuntimeError):
            for _ in range(5):
                env.step(env.action_space.sample())

    # -- restart-budget WINDOW semantics (not just the count) ----------------
    def _crash_storm_env(self):
        class AlwaysCrash(DiscreteDummyEnv):
            def step(self, action):
                raise RuntimeError("boom")

        return AlwaysCrash

    def test_storm_within_window_exhausts_budget(self, monkeypatch):
        """A storm of crashes inside one window burns max_restarts and the
        (max_restarts+1)-th crash propagates — the budget is a rate limit,
        and a persistently broken env must fail the run."""
        import sheeprl_tpu.envs.wrappers as wrappers

        clock = {"t": 1000.0}
        monkeypatch.setattr(wrappers.time, "monotonic", lambda: clock["t"])
        env = RestartOnException(self._crash_storm_env(), max_restarts=3, window=60.0)
        env.reset()
        for _ in range(3):  # three restarts, all at t=1000 (inside the window)
            env.step(env.action_space.sample())
        with pytest.raises(RuntimeError, match="3 times within"):
            env.step(env.action_space.sample())

    def test_sparse_crashes_outside_window_keep_budget_fresh(self, monkeypatch):
        """Crashes spaced wider than the window never accumulate: each one
        falls out of the sliding window before the next, so an occasionally
        flaky env can restart forever without tripping the budget."""
        import sheeprl_tpu.envs.wrappers as wrappers

        clock = {"t": 1000.0}
        monkeypatch.setattr(wrappers.time, "monotonic", lambda: clock["t"])
        env = RestartOnException(self._crash_storm_env(), max_restarts=2, window=60.0)
        env.reset()
        for _ in range(10):  # 10 restarts, 61s apart — far beyond the budget
            obs, r, term, trunc, info = env.step(env.action_space.sample())
            assert info.get("restart_on_exception") is True
            clock["t"] += 61.0

    def test_budget_refills_as_old_restarts_age_out(self, monkeypatch):
        """Partial aging: after a burst, one restart falling out of the
        window frees exactly one slot."""
        import sheeprl_tpu.envs.wrappers as wrappers

        clock = {"t": 0.0}
        monkeypatch.setattr(wrappers.time, "monotonic", lambda: clock["t"])
        env = RestartOnException(self._crash_storm_env(), max_restarts=2, window=60.0)
        env.reset()
        env.step(env.action_space.sample())  # restart 1 at t=0
        clock["t"] = 30.0
        env.step(env.action_space.sample())  # restart 2 at t=30 — budget full
        clock["t"] = 61.0  # restart 1 aged out, one slot free again
        env.step(env.action_space.sample())  # restart 3 at t=61 — allowed
        with pytest.raises(RuntimeError):  # t=61: restarts 2+3 in window
            env.step(env.action_space.sample())


class TestMakeEnv:
    def _cfg(self, extra=()):
        return compose(
            [
                "env=dummy",
                "algo.name=x",
                "algo.total_steps=1",
                "algo.per_rank_batch_size=1",
                *extra,
            ]
        )

    def test_dict_obs_and_image_transform(self):
        cfg = self._cfg(["env.screen_size=32"])
        env = make_env(cfg, seed=3, rank=0)()
        obs, _ = env.reset()
        assert set(obs.keys()) == {"rgb", "state"}
        assert obs["rgb"].shape == (32, 32, 3) and obs["rgb"].dtype == np.uint8

    def test_grayscale(self):
        cfg = self._cfg(["env.grayscale=True", "env.screen_size=32"])
        env = make_env(cfg, seed=3, rank=0)()
        obs, _ = env.reset()
        assert obs["rgb"].shape == (32, 32, 1)

    def test_frame_stack_and_rewards_obs(self):
        cfg = self._cfg(["env.frame_stack=3", "env.reward_as_observation=True"])
        env = make_env(cfg, seed=3, rank=0)()
        obs, _ = env.reset()
        assert obs["rgb"].shape == (3, 64, 64, 3)
        assert "reward" in obs

    def test_action_repeat(self):
        cfg = self._cfg(["env.action_repeat=2", "env.max_episode_steps=10"])
        env = make_env(cfg, seed=3, rank=0)()
        env.reset()
        obs, *_ = env.step(env.action_space.sample())
        # dummy env counts steps; 2 inner steps per outer step
        assert obs["state"][0] == 2

    def test_vector_env_gym(self):
        cfg = compose(
            ["env=gym", "env.id=CartPole-v1", "env.capture_video=False",
             "algo.name=x", "algo.total_steps=1", "algo.per_rank_batch_size=1"]
        )
        env = make_env(cfg, seed=0, rank=0)()
        obs, _ = env.reset()
        assert "state" in obs


def test_reward_as_observation_values():
    env = RewardAsObservationWrapper(DiscreteDummyEnv())
    obs, _ = env.reset()
    assert obs["reward"][0] == 0.0
    obs, *_ = env.step(env.action_space.sample())
    assert obs["reward"][0] == 1.0


def test_restart_flag_reaches_vector_env_top_level_info():
    """The crash step must NOT be a done: the flag has to surface in the
    vectorized top-level info so the Dreamer loop's buffer repair runs."""
    from sheeprl_tpu.utils.env import vectorize

    calls = {"n": 0}

    class CrashOnce(DiscreteDummyEnv):
        def step(self, action):
            calls["n"] += 1
            if calls["n"] == 3:
                raise RuntimeError("boom")
            return super().step(action)

    cfg = compose(
        ["env=dummy", "env.sync_env=True", "algo.name=x",
         "algo.total_steps=1", "algo.per_rank_batch_size=1"]
    )
    envs = vectorize(cfg, [lambda: RestartOnException(lambda: CrashOnce()),
                           lambda: RestartOnException(lambda: DiscreteDummyEnv())])
    envs.reset(seed=0)
    seen = False
    for _ in range(5):
        _, _, term, trunc, info = envs.step([envs.single_action_space.sample()] * 2)
        roe = info.get("restart_on_exception")
        if roe is not None and np.asarray(roe, bool).any():
            seen = True
            assert not term.any() and not trunc.any()
    assert seen
