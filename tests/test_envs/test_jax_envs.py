"""Pure-JAX env semantics (envs/jax/, ISSUE 11).

Four claims, each a test family:

* **Transition parity** — from identical explicit states and actions,
  ``JaxCartPole``/``JaxPendulum`` reproduce gymnasium's next obs, reward
  and termination within float tolerance.  (Seeded *reset draws* cannot
  match: threefry vs PCG64 — parity is pinned at the transition level,
  which is what the train data actually sees.)
* **Auto-reset + truncation boundary** — SAME_STEP semantics: the step
  that finishes an episode returns the reset obs, surfaces the true
  terminal obs as ``final_obs``, resets the step counter, and sets
  exactly one of terminated/truncated at the time-limit boundary.
* **Procedural pixel world** — forage renders uint8 channel-last pixels
  in-trace, pays reward on eating, terminates when all food is gone,
  and reseeds placements procedurally per episode.
* **Adapter** — ``JaxToGymAdapter`` honors the gymnasium seeding
  contract and composes with the existing ``make_env``/``vectorize``
  pipeline (``final_obs`` in vector infos).
"""

import numpy as np
import pytest

import gymnasium as gym
import jax
import jax.numpy as jnp

from sheeprl_tpu.envs.jax.adapter import JaxToGymAdapter
from sheeprl_tpu.envs.jax.cartpole import CartPoleState, JaxCartPole
from sheeprl_tpu.envs.jax.core import VectorJaxEnv
from sheeprl_tpu.envs.jax.forage import JaxForage
from sheeprl_tpu.envs.jax.pendulum import JaxPendulum, PendulumState
from sheeprl_tpu.envs.jax.registry import JAX_ENVS, make_jax_env


# --------------------------------------------------------------------------
# transition parity vs gymnasium
# --------------------------------------------------------------------------

class TestTransitionParity:
    def test_cartpole_matches_gymnasium(self):
        je = JaxCartPole()
        ge = gym.make("CartPole-v1").unwrapped
        rng = np.random.default_rng(11)
        step = jax.jit(je.step)
        for _ in range(100):
            s = rng.uniform(-0.2, 0.2, 4).astype(np.float32)
            a = int(rng.integers(2))
            ge.reset()
            ge.state = tuple(s)
            g_obs, g_rew, g_term, _, _ = ge.step(a)
            st = CartPoleState(
                x=jnp.float32(s[0]), x_dot=jnp.float32(s[1]),
                theta=jnp.float32(s[2]), theta_dot=jnp.float32(s[3]),
                t=jnp.int32(0), key=jax.random.PRNGKey(0),
            )
            _, j_obs, j_rew, j_term, j_trunc = step(st, jnp.int32(a))
            np.testing.assert_allclose(g_obs, np.asarray(j_obs["state"]), atol=1e-5)
            assert float(g_rew) == float(j_rew) == 1.0
            assert bool(g_term) == bool(j_term)
            assert not bool(j_trunc)

    def test_cartpole_termination_thresholds(self):
        je = JaxCartPole()
        # drive the pole over the 12 degree threshold
        st = CartPoleState(
            x=jnp.float32(0.0), x_dot=jnp.float32(0.0),
            theta=jnp.float32(0.2), theta_dot=jnp.float32(2.0),
            t=jnp.int32(0), key=jax.random.PRNGKey(0),
        )
        _, _, _, term, _ = je.step(st, jnp.int32(1))
        assert bool(term)

    def test_pendulum_matches_gymnasium(self):
        jp = JaxPendulum()
        gp = gym.make("Pendulum-v1").unwrapped
        rng = np.random.default_rng(12)
        step = jax.jit(jp.step)
        for _ in range(100):
            th, thdot = rng.uniform(-np.pi, np.pi), rng.uniform(-8, 8)
            u = rng.uniform(-2, 2, (1,)).astype(np.float32)
            gp.reset()
            gp.state = np.array([th, thdot])
            g_obs, g_rew, g_term, _, _ = gp.step(u)
            st = PendulumState(
                theta=jnp.float32(th), theta_dot=jnp.float32(thdot),
                t=jnp.int32(0), key=jax.random.PRNGKey(0),
            )
            _, j_obs, j_rew, j_term, _ = step(st, jnp.asarray(u))
            np.testing.assert_allclose(g_obs, np.asarray(j_obs["state"]), atol=1e-4)
            assert abs(float(g_rew) - float(j_rew)) < 1e-4
            assert not bool(g_term) and not bool(j_term)

    def test_reset_within_gymnasium_bounds(self):
        # the draw distribution matches even though the PRNG cannot
        states = [JaxCartPole().reset(jax.random.PRNGKey(i))[1]["state"] for i in range(20)]
        arr = np.stack([np.asarray(s) for s in states])
        assert np.all(np.abs(arr) <= 0.05)
        p_obs = JaxPendulum().reset(jax.random.PRNGKey(0))[1]["state"]
        assert np.abs(np.asarray(p_obs)[2]) <= 1.0  # theta_dot ~ U(-1, 1)


# --------------------------------------------------------------------------
# auto-reset + truncation boundary
# --------------------------------------------------------------------------

class TestAutoReset:
    def test_same_step_autoreset_surfaces_final_obs(self):
        venv = VectorJaxEnv(JaxCartPole(), 4)
        state, obs = venv.reset(jax.random.PRNGKey(0))
        step = jax.jit(venv.step)
        # always-right eventually topples every pole
        saw_done = False
        for _ in range(60):
            prev_t = np.asarray(state.t)
            state, obs, rew, term, trunc, final_obs = step(state, jnp.ones((4,), jnp.int32))
            done = np.asarray(term) | np.asarray(trunc)
            t = np.asarray(state.t)
            if done.any():
                saw_done = True
                # finished rows restarted (SAME_STEP): counter back to 0,
                # returned obs is the RESET obs (within reset bounds), the
                # true terminal obs preserved in final_obs
                assert (t[done] == 0).all()
                assert (np.abs(np.asarray(obs["state"])[done]) <= 0.05).all()
                assert (np.abs(np.asarray(final_obs["state"])[done]) > 0.05).any()
            assert (t[~done] == prev_t[~done] + 1).all()
        assert saw_done

    def test_truncation_boundary_flags(self):
        # a pendulum never terminates: at the limit it must truncate, once
        venv = VectorJaxEnv(JaxPendulum(max_episode_steps=7), 2)
        state, _ = venv.reset(jax.random.PRNGKey(3))
        acts = jnp.zeros((2, 1), jnp.float32)
        for i in range(1, 15):
            state, obs, rew, term, trunc, final_obs = venv.step(state, acts)
            assert not np.asarray(term).any()
            expect_trunc = i % 7 == 0
            assert np.asarray(trunc).all() == expect_trunc
            assert np.asarray(trunc).any() == expect_trunc

    def test_terminated_and_truncated_never_both(self):
        venv = VectorJaxEnv(JaxCartPole(max_episode_steps=5), 8)
        state, _ = venv.reset(jax.random.PRNGKey(4))
        for _ in range(40):
            state, _, _, term, trunc, _ = venv.step(state, jnp.ones((8,), jnp.int32))
            assert not (np.asarray(term) & np.asarray(trunc)).any()

    def test_instances_decorrelate(self):
        # per-instance PRNG keys: vector reset must not clone one episode
        venv = VectorJaxEnv(JaxCartPole(), 8)
        _, obs = venv.reset(jax.random.PRNGKey(5))
        assert len(np.unique(np.asarray(obs["state"])[:, 0])) > 1


# --------------------------------------------------------------------------
# procedural pixel world
# --------------------------------------------------------------------------

class TestForage:
    def test_pixel_contract(self):
        env = JaxForage(grid=4, n_food=3, image_hw=64)
        state, obs = env.reset(jax.random.PRNGKey(0))
        assert obs["rgb"].shape == (64, 64, 3) and obs["rgb"].dtype == jnp.uint8
        img = np.asarray(obs["rgb"])
        # agent painted white, food green, exactly as placed
        assert (img == 255).all(axis=-1).sum() == 16 * 16  # one white cell
        assert int(np.asarray(state.food).sum()) == 3

    def test_eating_pays_and_terminates(self):
        env = JaxForage(grid=2, n_food=1, image_hw=8, max_episode_steps=50)
        state, _ = env.reset(jax.random.PRNGKey(1))
        # walk the 2x2 grid until the single food is eaten
        total = 0.0
        term = False
        for a in [1, 3, 2, 4, 1, 3]:
            state, _, rew, term, trunc, = env.step(state, jnp.int32(a))
            total += float(rew)
            if bool(term):
                break
        assert term and total == 1.0
        # no food left on the grid
        assert int(np.asarray(state.food).sum()) == 0

    def test_procedural_reset_reseeds_placement(self):
        env = JaxForage()
        _, o1 = env.reset(jax.random.PRNGKey(1))
        _, o2 = env.reset(jax.random.PRNGKey(2))
        _, o1b = env.reset(jax.random.PRNGKey(1))
        assert not np.array_equal(np.asarray(o1["rgb"]), np.asarray(o2["rgb"]))
        assert np.array_equal(np.asarray(o1["rgb"]), np.asarray(o1b["rgb"]))


# --------------------------------------------------------------------------
# registry + adapter
# --------------------------------------------------------------------------

class TestRegistryAdapter:
    def test_registry_names(self):
        assert {"cartpole", "pendulum", "forage"} <= set(JAX_ENVS)
        assert isinstance(make_jax_env("jax_cartpole"), JaxCartPole)
        with pytest.raises(ValueError, match="Unknown jax env"):
            make_jax_env("jax_nope")

    def test_adapter_seeding_contract(self):
        ad = JaxToGymAdapter(make_jax_env("cartpole"))
        o1, _ = ad.reset(seed=9)
        t1 = [ad.step(1)[0]["state"] for _ in range(5)]
        o2, _ = ad.reset(seed=9)
        t2 = [ad.step(1)[0]["state"] for _ in range(5)]
        np.testing.assert_array_equal(o1["state"], o2["state"])
        for a, b in zip(t1, t2):
            np.testing.assert_array_equal(a, b)
        o3, _ = ad.reset(seed=10)
        assert not np.array_equal(o1["state"], o3["state"])

    def test_adapter_through_make_env_vectorize(self):
        from sheeprl_tpu.config.compose import compose
        from sheeprl_tpu.utils.env import make_env, vectorize

        cfg = compose(
            [
                "exp=ppo", "env=jax_cartpole", "env.num_envs=2",
                "algo.mlp_keys.encoder=[state]", "env.capture_video=False",
            ]
        )
        envs = vectorize(cfg, [make_env(cfg, 7, 0, vector_env_idx=i) for i in range(2)])
        obs, _ = envs.reset(seed=7)
        assert obs["state"].shape == (2, 4)
        saw_final = False
        for _ in range(600):
            obs, rew, term, trunc, info = envs.step(np.ones(2, dtype=np.int64))
            if "final_obs" in info:
                saw_final = True
                break
        envs.close()
        assert saw_final
