"""In-trace PBT math + the one-executable population contract
(sheeprl_tpu/population/core.py, ISSUE 20 acceptance).

* truncation selection is seeded-deterministic and copies params AND
  opt-state through the SAME source index (a member never gets weights
  from one donor and optimizer moments from another);
* log-uniform perturbation stays inside the exploration bounds;
* the exploit gate is a pure ``jnp.where`` select: off-cadence (or
  pre-warmup) windows are bitwise no-ops, with NO second executable;
* 50 fused population windows — rollout, member train, fitness EMA and
  gated exploit/explore vmapped over the population — reuse ONE compiled
  executable under the armed transfer guard (zero steady H2D).
"""

import contextlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sheeprl_tpu.envs.jax.cartpole import JaxCartPole
from sheeprl_tpu.envs.jax.core import VectorJaxEnv
from sheeprl_tpu.parallel.fabric import Fabric
from sheeprl_tpu.population import (
    PBTConfig,
    init_population_state,
    make_population_phase,
    pbt_exploit_explore,
    tile_stack,
)
from sheeprl_tpu.utils.structured import dotdict

BASE = {"lr": 1e-3, "ent_coef": 0.01}


def _pbt_cfg(**over):
    pop = dict(
        size=4, exploit_every=2, warmup=0, frac=0.25,
        perturb_min=0.8, perturb_max=1.25, init_min=0.5, init_max=2.0,
        bound_min=0.05, bound_max=20.0, fitness_alpha=0.3, levels=None,
    )
    pop.update(over)
    return PBTConfig.from_cfg(dotdict({"population": pop}), base=dict(BASE))


def _member_stacks(cfg):
    # per-member-distinguishable params and a toy two-leaf opt state
    size = cfg.size
    params = {"w": jnp.arange(size * 3, dtype=jnp.float32).reshape(size, 3)}
    opt_state = {
        "mu": jnp.arange(size, dtype=jnp.float32) * 10.0,
        "nu": jnp.arange(size, dtype=jnp.float32) * 100.0,
    }
    hp = cfg.init_hyperparams(jax.random.PRNGKey(11))
    fitness = jnp.asarray([3.0, 0.5, 2.0, 1.0])  # member 1 is worst, 0 best
    return params, opt_state, hp, fitness


class TestExploitExplore:
    def test_truncation_selection_is_seeded_deterministic(self):
        cfg = _pbt_cfg()
        params, opt_state, hp, fitness = _member_stacks(cfg)
        do = jnp.asarray(True)
        out1 = pbt_exploit_explore(params, opt_state, hp, fitness, do, jax.random.PRNGKey(5), cfg)
        out2 = pbt_exploit_explore(params, opt_state, hp, fitness, do, jax.random.PRNGKey(5), cfg)
        for a, b in zip(jax.tree.leaves(out1), jax.tree.leaves(out2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # a different key perturbs differently (the explore half is seeded)
        out3 = pbt_exploit_explore(params, opt_state, hp, fitness, do, jax.random.PRNGKey(6), cfg)
        assert any(
            not np.array_equal(np.asarray(out1[2][k]), np.asarray(out3[2][k])) for k in hp
        )

    def test_exploit_copies_params_and_opt_state_together(self):
        cfg = _pbt_cfg()
        params, opt_state, hp, fitness = _member_stacks(cfg)
        p2, o2, hp2, fit2, n_copied = pbt_exploit_explore(
            params, opt_state, hp, fitness, jnp.asarray(True), jax.random.PRNGKey(0), cfg
        )
        assert int(n_copied) == cfg.n_select == 1
        # worst member (1) received the best member's (0) weights AND both
        # optimizer-moment leaves — a coherent (weights, moments) pair
        np.testing.assert_array_equal(np.asarray(p2["w"][1]), np.asarray(params["w"][0]))
        assert float(o2["mu"][1]) == float(opt_state["mu"][0])
        assert float(o2["nu"][1]) == float(opt_state["nu"][0])
        # the copied member inherits the source's fitness
        assert float(fit2[1]) == float(fitness[0])
        # untouched members keep their state bitwise
        for m in (0, 2, 3):
            np.testing.assert_array_equal(np.asarray(p2["w"][m]), np.asarray(params["w"][m]))
            assert float(o2["mu"][m]) == float(opt_state["mu"][m])
        # only the copied member's hyperparams were perturbed
        for name in hp:
            changed = np.asarray(hp2[name]) != np.asarray(hp[name])
            assert changed[1] or BASE[name] == 0.0
            assert not changed[[0, 2, 3]].any()

    def test_perturbation_stays_within_bounds(self):
        cfg = _pbt_cfg(size=8, frac=0.5, perturb_min=0.5, perturb_max=3.0, bound_min=0.5, bound_max=2.0)
        params, opt_state, _, _ = {"w": jnp.zeros((8, 3))}, {"mu": jnp.zeros((8,))}, None, None
        hp = cfg.init_hyperparams(jax.random.PRNGKey(1))
        fitness = jnp.arange(8.0)
        for seed in range(5):
            _, _, hp, fitness, _ = pbt_exploit_explore(
                params, opt_state, hp, fitness, jnp.asarray(True), jax.random.PRNGKey(seed), cfg
            )
            for name, base in BASE.items():
                v = np.asarray(hp[name])
                assert (v >= base * cfg.bound_min - 1e-12).all()
                assert (v <= base * cfg.bound_max + 1e-12).all()

    def test_closed_gate_is_bitwise_noop(self):
        cfg = _pbt_cfg()
        params, opt_state, hp, fitness = _member_stacks(cfg)
        p2, o2, hp2, fit2, n_copied = pbt_exploit_explore(
            params, opt_state, hp, fitness, jnp.asarray(False), jax.random.PRNGKey(0), cfg
        )
        assert int(n_copied) == 0
        np.testing.assert_array_equal(np.asarray(p2["w"]), np.asarray(params["w"]))
        np.testing.assert_array_equal(np.asarray(o2["mu"]), np.asarray(opt_state["mu"]))
        np.testing.assert_array_equal(np.asarray(fit2), np.asarray(fitness))
        for name in hp:
            np.testing.assert_array_equal(np.asarray(hp2[name]), np.asarray(hp[name]))


class TestPopulationPhaseGating:
    def _toy_phase(self, cfg):
        # members report fitness proportional to their lr, so selection
        # ordering is known without running a real env
        def member_phase(p, o_state, actor, k, hp):
            stats = {
                "ep_done": jnp.ones((2, 3), bool),
                "ep_ret": jnp.ones((2, 3)) * hp["lr"] * 1e3,
                "ep_len": jnp.ones((2, 3), jnp.int32),
            }
            actor = {**actor, "update": actor["update"] + 1}
            return p, o_state, actor, (jnp.zeros(()),), stats

        return make_population_phase(member_phase, cfg)

    def test_no_exploit_below_warmup_or_off_cadence(self):
        cfg = _pbt_cfg(exploit_every=2, warmup=5)
        phase = jax.jit(self._toy_phase(cfg))
        params = tile_stack({"w": jnp.zeros((3,))}, cfg.size)
        opt_state = tile_stack({"mu": jnp.zeros(())}, cfg.size)
        members = {"update": jnp.zeros((cfg.size,), jnp.int32)}
        pop = init_population_state(members, cfg, num_envs=3)
        hp = cfg.init_hyperparams(jax.random.PRNGKey(2))
        hp0 = {k: np.asarray(v) for k, v in hp.items()}
        key = jax.random.PRNGKey(0)
        exploit_updates = []
        for update in range(1, 9):
            params, opt_state, pop, hp, key, _, _ = phase(params, opt_state, pop, hp, key)
            if int(pop["exploits"]) > len(exploit_updates) * cfg.n_select:
                exploit_updates.append(update)
        # cadence 2, warmup 5 → exploit fires at updates 6 and 8 only
        assert exploit_updates == [6, 8]
        # fitness tracked the lr ordering, so the lowest-lr member copied up
        assert int(pop["exploits"]) == 2 * cfg.n_select
        worst = int(np.asarray(hp0["lr"]).argmin())
        assert float(np.asarray(hp["lr"])[worst]) != float(hp0["lr"][worst])


class TestOneExecutablePopulation:
    def test_cache_size_one_across_50_windows_guarded(self):
        from sheeprl_tpu.algos.ppo.agent import sample_actions
        from sheeprl_tpu.envs.jax.anakin import make_rollout_fn

        cfg = _pbt_cfg(size=3, exploit_every=3, warmup=2)
        fabric = Fabric(devices=1, accelerator="cpu")
        venv = VectorJaxEnv(JaxCartPole(), 2)

        def apply(p, obs):
            h = obs["state"] @ p["w"]
            return h[:, :2], h[:, 2:3]

        rollout_fn = make_rollout_fn(
            venv, apply, lambda out, k: sample_actions(out, (2,), False, k),
            cnn_keys=(), mlp_keys=("state",),
            action_space=venv.single_action_space,
            gamma=0.99, rollout_steps=4,
        )

        def member_phase(p, o_state, actor, k, hp):
            actor, rollout, last_obs, stats = rollout_fn(p, actor, k)
            # stand-in train: params/opt-state depend on the rollout AND the
            # member's traced hyperparameters
            delta = jnp.mean(rollout["state"]) + jnp.mean(rollout["rewards"])
            p = {"w": p["w"] + 0.0 * delta * hp["lr"]}
            o_state = {"mu": o_state["mu"] * 0.9 + hp["lr"] + 0.0 * hp["ent_coef"]}
            return p, o_state, actor, (jnp.zeros(()),), stats

        population_step = fabric.compile(
            make_population_phase(member_phase, cfg),
            name="test.population_phase",
            donate_argnums=(0, 1, 2, 3),
        )

        def _init_member(k):
            env_state, _ = venv.reset(k)
            return {
                "env": env_state,
                "ep_ret": jnp.zeros((2,), jnp.float32),
                "ep_len": jnp.zeros((2,), jnp.int32),
            }

        members = jax.vmap(_init_member)(jax.random.split(jax.random.PRNGKey(0), cfg.size))
        members["update"] = jnp.zeros((cfg.size,), jnp.int32)
        pop = init_population_state(members, cfg, num_envs=2)
        params = tile_stack({"w": jnp.zeros((4, 3), jnp.float32)}, cfg.size)
        opt_state = tile_stack({"mu": jnp.zeros(())}, cfg.size)
        hp = cfg.init_hyperparams(jax.random.PRNGKey(3))
        key = jax.random.PRNGKey(1)
        for i in range(50):
            # steady state (every window after the first) runs under the
            # armed guard: ANY implicit H2D — including from the gated
            # exploit windows at updates 3·k — dies here
            guard = (
                jax.transfer_guard_host_to_device("disallow")
                if i > 0
                else contextlib.nullcontext()
            )
            with guard:
                params, opt_state, pop, hp, key, losses, stats = population_step(
                    params, opt_state, pop, hp, key
                )
        assert population_step.cache_size() == 1
        np.testing.assert_array_equal(np.asarray(pop["members"]["update"]), 50)
        # the PBT gate opened on cadence inside the ONE executable
        assert int(pop["exploits"]) == cfg.n_select * len([u for u in range(1, 51) if u > 2 and u % 3 == 0])


class TestConfigValidation:
    def test_rejects_degenerate_populations(self):
        with pytest.raises(ValueError, match="size"):
            _pbt_cfg(size=1)
        with pytest.raises(ValueError, match="frac"):
            _pbt_cfg(frac=0.9)
        with pytest.raises(ValueError, match="perturb"):
            _pbt_cfg(perturb_min=1.5, perturb_max=1.2)

    def test_n_select_clamps_to_half(self):
        assert _pbt_cfg(size=4, frac=0.25).n_select == 1
        assert _pbt_cfg(size=8, frac=0.5).n_select == 4
        assert _pbt_cfg(size=2, frac=0.5).n_select == 1
