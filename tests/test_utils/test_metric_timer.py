import time

import numpy as np

from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.timer import timer


class TestMetricAggregator:
    def test_modes(self):
        agg = MetricAggregator({"m": "mean", "s": "sum", "l": "last", "mx": "max"})
        for v in (1.0, 2.0, 3.0):
            for k in ("m", "s", "l", "mx"):
                agg.update(k, v)
        out = agg.compute()
        assert out == {"m": 2.0, "s": 6.0, "l": 3.0, "mx": 3.0}

    def test_nan_and_nonscalar_dropped(self):
        agg = MetricAggregator({"a": "mean", "b": "mean"})
        agg.update("a", float("nan"))
        agg.update("b", np.ones(3))  # non-scalar
        assert agg.compute() == {}

    def test_unregistered_silently_ignored_or_raises(self):
        agg = MetricAggregator({"a": "mean"})
        agg.update("nope", 1.0)  # raise_on_missing=False default
        assert "nope" not in agg.compute()
        import pytest

        strict = MetricAggregator({"a": "mean"}, raise_on_missing=True)
        with pytest.raises(KeyError):
            strict.update("nope", 1.0)

    def test_reset(self):
        agg = MetricAggregator({"a": "mean"})
        agg.update("a", 5.0)
        agg.reset()
        assert agg.compute() == {}


class TestTimer:
    def test_accumulates_and_resets(self):
        timer.disabled = False
        with timer("Time/test_phase"):
            time.sleep(0.01)
        with timer("Time/test_phase"):
            time.sleep(0.01)
        out = timer.to_dict(reset=True)
        assert out["Time/test_phase"] >= 0.02
        assert timer.to_dict() == {}

    def test_disabled(self):
        timer.disabled = True
        with timer("Time/off"):
            pass
        assert "Time/off" not in timer.to_dict()
        timer.disabled = False
