import pytest

from sheeprl_tpu.utils.registry import (
    algorithm_registry,
    register_algorithm,
    resolve_algorithm,
)


def test_register_and_resolve():
    @register_algorithm(name="unit_test_algo")
    def main(fabric, cfg):
        return "ran"

    entry = resolve_algorithm("unit_test_algo")
    assert entry.module == __name__
    assert entry.decoupled is False
    algorithm_registry.pop("unit_test_algo")


def test_decoupled_variant_selection():
    @register_algorithm(name="unit_test_algo2")
    def main(fabric, cfg):
        pass

    @register_algorithm(name="unit_test_algo2", decoupled=True)
    def main_decoupled(fabric, cfg):
        pass

    assert resolve_algorithm("unit_test_algo2", decoupled=True).decoupled
    assert not resolve_algorithm("unit_test_algo2", decoupled=False).decoupled
    algorithm_registry.pop("unit_test_algo2")


def test_unknown_algorithm_raises():
    with pytest.raises(ValueError):
        resolve_algorithm("definitely_not_registered")


def test_every_algorithm_has_an_evaluation():
    """The reference validates the evaluation registry against the algorithm
    registry (reference: sheeprl/utils/registry.py:38-94); without an entry,
    'sheeprl-tpu eval' refuses that algorithm's checkpoints outright (the
    decoupled variants regressed exactly this way once)."""
    import sheeprl_tpu
    from sheeprl_tpu.utils.registry import algorithm_registry, evaluation_registry

    sheeprl_tpu.register_all_algorithms()
    missing = [n for n in algorithm_registry if n not in evaluation_registry]
    assert not missing, f"algorithms without a registered evaluation: {missing}"
