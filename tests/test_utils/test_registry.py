import pytest

from sheeprl_tpu.utils.registry import (
    algorithm_registry,
    register_algorithm,
    resolve_algorithm,
)


def test_register_and_resolve():
    @register_algorithm(name="unit_test_algo")
    def main(fabric, cfg):
        return "ran"

    entry = resolve_algorithm("unit_test_algo")
    assert entry.module == __name__
    assert entry.decoupled is False
    algorithm_registry.pop("unit_test_algo")


def test_decoupled_variant_selection():
    @register_algorithm(name="unit_test_algo2")
    def main(fabric, cfg):
        pass

    @register_algorithm(name="unit_test_algo2", decoupled=True)
    def main_decoupled(fabric, cfg):
        pass

    assert resolve_algorithm("unit_test_algo2", decoupled=True).decoupled
    assert not resolve_algorithm("unit_test_algo2", decoupled=False).decoupled
    algorithm_registry.pop("unit_test_algo2")


def test_unknown_algorithm_raises():
    with pytest.raises(ValueError):
        resolve_algorithm("definitely_not_registered")
