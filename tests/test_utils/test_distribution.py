import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.utils.distribution import (
    Bernoulli,
    Categorical,
    MSEDistribution,
    MultiCategorical,
    Normal,
    OneHotCategorical,
    SymlogDistribution,
    TanhNormal,
    TruncatedNormal,
    TwoHotEncodingDistribution,
    kl_categorical,
    kl_normal,
)

KEY = jax.random.PRNGKey(0)


def test_categorical_log_prob_and_entropy():
    logits = jnp.log(jnp.array([[0.7, 0.2, 0.1]]))
    d = Categorical(logits)
    np.testing.assert_allclose(np.asarray(d.log_prob(jnp.array([0]))), np.log(0.7), rtol=1e-5)
    uniform = Categorical(jnp.zeros((1, 4)))
    np.testing.assert_allclose(np.asarray(uniform.entropy()), np.log(4), rtol=1e-5)
    assert int(d.mode()[0]) == 0


def test_categorical_sampling_distribution():
    d = Categorical(jnp.log(jnp.array([0.8, 0.15, 0.05])))
    samples = jax.vmap(lambda k: d.sample(k))(jax.random.split(KEY, 2000))
    freq = np.bincount(np.asarray(samples), minlength=3) / 2000
    np.testing.assert_allclose(freq, [0.8, 0.15, 0.05], atol=0.05)


def test_multicategorical():
    d = MultiCategorical([jnp.zeros((2, 3)), jnp.zeros((2, 4))])
    a = d.sample(KEY)
    assert a.shape == (2, 2)
    lp = d.log_prob(a)
    np.testing.assert_allclose(np.asarray(lp), np.log(1 / 3) + np.log(1 / 4), rtol=1e-5)


def test_onehot_straight_through_gradient():
    def loss(logits):
        d = OneHotCategorical(logits)
        s = d.rsample(KEY)
        return jnp.sum(s * jnp.arange(3.0))

    g = jax.grad(loss)(jnp.zeros((3,)))
    assert np.any(np.asarray(g) != 0)  # gradient flows through probs


def test_onehot_unimix():
    sharp = jnp.array([100.0, 0.0, 0.0])
    d = OneHotCategorical(sharp, unimix=0.01)
    probs = np.asarray(d.probs)
    assert probs.min() >= 0.01 / 3 - 1e-6


def test_kl_categorical_self_zero():
    d = OneHotCategorical(jnp.array([0.5, 1.0, -0.2]))
    np.testing.assert_allclose(float(kl_categorical(d, d)), 0.0, atol=1e-6)


def test_normal_log_prob_matches_scipy():
    d = Normal(jnp.array(1.0), jnp.array(2.0))
    from scipy.stats import norm

    np.testing.assert_allclose(float(d.log_prob(jnp.array(0.5))), norm.logpdf(0.5, 1.0, 2.0), rtol=1e-5)


def test_kl_normal_self_zero():
    d = Normal(jnp.array([1.0]), jnp.array([2.0]), event_dims=1)
    np.testing.assert_allclose(float(kl_normal(d, d)), 0.0, atol=1e-6)


def test_tanh_normal_log_prob_is_corrected():
    d = TanhNormal(jnp.zeros((5, 2)), jnp.ones((5, 2)))
    a, lp = d.sample_and_log_prob(KEY)
    assert a.shape == (5, 2) and lp.shape == (5,)
    assert np.all(np.abs(np.asarray(a)) < 1.0)
    # analytic check against change-of-variables with base log_prob
    base = Normal(jnp.zeros((5, 2)), jnp.ones((5, 2)), event_dims=1)
    pre = np.arctanh(np.clip(np.asarray(a), -0.999999, 0.999999))
    expected = np.asarray(base.log_prob(jnp.array(pre))) - np.sum(
        np.log(1 - np.asarray(a) ** 2 + 1e-7), axis=-1
    )
    np.testing.assert_allclose(np.asarray(lp), expected, rtol=1e-3, atol=1e-3)


def test_truncated_normal_support_and_mass():
    d = TruncatedNormal(jnp.zeros((1000,)), jnp.ones((1000,)) * 2.0)
    s = d.sample(KEY)
    assert np.all(np.abs(np.asarray(s)) <= 1.0)
    # log_prob integrates to ~1 over [-1, 1]
    xs = jnp.linspace(-0.999, 0.999, 500)
    d1 = TruncatedNormal(jnp.zeros(()), jnp.ones(()) * 2.0)
    dens = np.exp(np.asarray(jax.vmap(d1.log_prob)(xs)))
    mass = np.trapezoid(dens, np.asarray(xs))
    np.testing.assert_allclose(mass, 1.0, atol=0.02)


def test_mse_and_symlog_distributions():
    pred = jnp.array([[1.0, 2.0]])
    target = jnp.array([[1.5, 2.0]])
    d = MSEDistribution(pred, event_dims=1)
    np.testing.assert_allclose(np.asarray(d.log_prob(target)), -0.25, rtol=1e-5)
    sd = SymlogDistribution(jnp.zeros((1, 2)), event_dims=1)
    assert np.asarray(sd.log_prob(jnp.zeros((1, 2))))[0] == 0.0
    np.testing.assert_allclose(np.asarray(sd.mode()), 0.0, atol=1e-6)


def test_two_hot_distribution_mean_recovers_target():
    # put all logit mass exactly on the two-hot encoding of a target value
    target = 3.7
    d0 = TwoHotEncodingDistribution(jnp.zeros((1, 255)))
    enc = d0._two_hot(jnp.array([[target]]))
    d = TwoHotEncodingDistribution(jnp.log(enc + 1e-8))
    np.testing.assert_allclose(float(d.mean[0, 0]), target, rtol=1e-2)


def test_two_hot_log_prob_peaks_at_argmax_bin():
    """``log_prob(x) = two_hot(symlog x) · log-softmax(logits)`` is a convex
    interpolation between ADJACENT bin log-probs, so its global maximum over
    x sits exactly on the encoded bin carrying the largest logit — NOT at
    the distribution's mean: for a multimodal categorical the symexp-expected
    value can land between low-probability bins, where the interpolated
    log-prob is far below the peak.  (The old expectation here,
    peak-at-mean, asserted exactly that and failed for random logits — the
    math, not the implementation, was wrong.)"""
    from sheeprl_tpu.utils.utils import symexp

    logits = jax.random.normal(KEY, (1, 255))
    d = TwoHotEncodingDistribution(logits)
    best = int(np.argmax(np.asarray(d.logits)[0]))
    x_star = symexp(d.bins[best]).reshape(1, 1)
    lp_star = float(np.asarray(d.log_prob(x_star)).reshape(-1)[0])
    np.testing.assert_allclose(
        lp_star, float(np.asarray(d.logits)[0, best]), rtol=1e-5
    )  # the mode's log-prob IS the max logit
    # ... and it dominates every other bin center (global max over the support)
    d_all = TwoHotEncodingDistribution(jnp.tile(logits, (255, 1)))
    lp_bins = np.asarray(d_all.log_prob(symexp(d_all.bins).reshape(255, 1))).reshape(-1)
    assert lp_star >= lp_bins.max() - 1e-5
    # ... including far outside the support (saturated top bucket)
    lp_far = float(np.asarray(d.log_prob(d.mean + 1e6)).reshape(-1)[0])
    assert lp_star > lp_far


def test_two_hot_log_prob_peaks_at_target_when_mass_is_concentrated():
    """When the categorical's mass IS concentrated on one value's two-hot
    encoding, the log-prob peak does coincide with the mean — the shape the
    old peak-at-mean expectation implicitly assumed."""
    target = 3.7
    d0 = TwoHotEncodingDistribution(jnp.zeros((1, 255)))
    enc = d0._two_hot(jnp.array([[target]]))
    d = TwoHotEncodingDistribution(jnp.log(enc + 1e-8))
    lp_mean = float(np.asarray(d.log_prob(d.mean)).reshape(-1)[0])
    lp_far = float(np.asarray(d.log_prob(d.mean + 100.0)).reshape(-1)[0])
    lp_near = float(np.asarray(d.log_prob(d.mean + 1.0)).reshape(-1)[0])
    assert lp_mean > lp_near and lp_mean > lp_far


def test_bernoulli_safe_mode():
    d = Bernoulli(jnp.array([10.0, -10.0]))
    np.testing.assert_allclose(np.asarray(d.mode()), [1.0, 0.0])
    lp = d.log_prob(jnp.array([1.0, 0.0]))
    assert np.all(np.asarray(lp) < 0) and np.all(np.asarray(lp) > -1e-3)
