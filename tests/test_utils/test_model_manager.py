import jax.numpy as jnp
import pytest

from sheeprl_tpu.utils.model_manager import FileSystemModelManager


def test_register_load_roundtrip(tmp_path):
    mm = FileSystemModelManager(tmp_path / "registry")
    params = {"w": jnp.ones((3, 3))}
    v1 = mm.register_model("ppo_agent", params, description="test")
    assert v1 == 1
    v2 = mm.register_model("ppo_agent", params)
    assert v2 == 2
    loaded = mm.load_model("ppo_agent")  # latest
    assert loaded["w"].shape == (3, 3)
    assert mm.get_latest_version("ppo_agent") == 2


def test_transition_and_info(tmp_path):
    mm = FileSystemModelManager(tmp_path / "registry")
    mm.register_model("m", {"w": jnp.zeros(2)})
    mm.transition_model("m", 1, "production")
    assert mm.get_model_info("m", 1)["stage"] == "production"


def test_delete(tmp_path):
    mm = FileSystemModelManager(tmp_path / "registry")
    mm.register_model("m", {"w": jnp.zeros(2)})
    mm.register_model("m", {"w": jnp.zeros(2)})
    mm.delete_model("m", 1)
    assert mm.get_latest_version("m") == 2
    mm.delete_model("m")
    assert mm.get_latest_version("m") is None


def test_missing_model_raises(tmp_path):
    mm = FileSystemModelManager(tmp_path / "registry")
    with pytest.raises(FileNotFoundError):
        mm.load_model("nope")


def test_register_best_models(tmp_path):
    """register_best_models picks the run whose metric peaked highest and
    registers that run's last checkpoint's sub-models."""
    import csv

    from sheeprl_tpu.utils.checkpoint import save_checkpoint
    from sheeprl_tpu.utils.model_manager import register_best_models
    from sheeprl_tpu.utils.structured import dotdict

    log_dir = tmp_path / "runs"
    for run, (reward, w) in {"a": (10.0, 1.0), "b": (99.0, 2.0)}.items():
        vdir = log_dir / run / "version_0"
        (vdir / "checkpoint").mkdir(parents=True)
        with open(vdir / "metrics.csv", "w", newline="") as f:
            wr = csv.writer(f)
            wr.writerow(["step", "name", "value"])
            wr.writerow([1, "Rewards/rew_avg", reward / 2])
            wr.writerow([2, "Rewards/rew_avg", reward])
        save_checkpoint(
            vdir / "checkpoint" / "ckpt_2_0.ckpt",
            {"agent": {"actor": {"w": jnp.full(2, w)}}},
        )
    cfg = dotdict(
        {
            "algo": {"name": "ppo"},
            "env": {"id": "CartPole-v1"},
            "seed": 0,
            "model_manager": {"registry_root": str(tmp_path / "registry")},
        }
    )
    versions = register_best_models(str(log_dir), cfg, metric="Rewards/rew_avg")
    assert versions == {"actor": 1}
    mm = FileSystemModelManager(tmp_path / "registry")
    best = mm.load_model("ppo_actor")
    assert float(jnp.asarray(best["w"])[0]) == 2.0  # run "b" won


def test_extra_modules_import(tmp_path, monkeypatch):
    """algo.extra_modules imports user packages so external algorithms
    register (howto/register_external_algorithm.md)."""
    import sys

    from sheeprl_tpu.cli import import_extra_modules
    from sheeprl_tpu.utils.structured import dotdict

    (tmp_path / "ext_algo_pkg.py").write_text(
        "from sheeprl_tpu.utils.registry import register_algorithm\n"
        "@register_algorithm(name='ext_algo')\n"
        "def main(fabric, cfg):\n"
        "    pass\n"
    )
    monkeypatch.syspath_prepend(str(tmp_path))
    import_extra_modules(dotdict({"algo": {"extra_modules": ["ext_algo_pkg"]}}))
    from sheeprl_tpu.utils.registry import algorithm_registry

    assert "ext_algo" in algorithm_registry
    algorithm_registry.pop("ext_algo", None)
    sys.modules.pop("ext_algo_pkg", None)


# ---- MLflow backend (skip-gated on the optional dep) -----------------------

mlflow_required = pytest.mark.skipif(
    not __import__("sheeprl_tpu.utils.imports", fromlist=["_IS_MLFLOW_AVAILABLE"])._IS_MLFLOW_AVAILABLE,
    reason="mlflow not installed",
)


def test_get_model_manager_dispatch(tmp_path):
    from sheeprl_tpu.utils.mlflow_manager import get_model_manager
    from sheeprl_tpu.utils.structured import dotdict

    mm = get_model_manager(dotdict({"model_manager": {"registry_root": str(tmp_path / "r")}}))
    assert isinstance(mm, FileSystemModelManager)


def test_mlflow_backend_unavailable_raises():
    from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

    if _IS_MLFLOW_AVAILABLE:
        pytest.skip("mlflow installed — gate not exercised")
    from sheeprl_tpu.utils.mlflow_manager import MlflowModelManager

    with pytest.raises(ModuleNotFoundError):
        MlflowModelManager(tracking_uri="file:/tmp/nope")


@mlflow_required
def test_mlflow_register_load_roundtrip(tmp_path):
    from sheeprl_tpu.utils.mlflow_manager import MlflowModelManager

    mm = MlflowModelManager(tracking_uri=f"file:{tmp_path}/mlruns", experiment_name="t")
    params = {"w": jnp.ones((3, 3))}
    assert mm.register_model("ppo_agent", params, description="first") == 1
    assert mm.register_model("ppo_agent", params) == 2
    assert mm.get_latest_version("ppo_agent") == 2
    loaded = mm.load_model("ppo_agent", version=1)
    assert loaded["w"].shape == (3, 3)
    # changelog maintained on the registered model (reference behavior)
    desc = mm.client.get_registered_model("ppo_agent").description
    assert "MODEL CHANGELOG" in desc and "Version 1" in desc and "Version 2" in desc


@mlflow_required
def test_mlflow_transition_and_delete(tmp_path):
    from sheeprl_tpu.utils.mlflow_manager import MlflowModelManager

    mm = MlflowModelManager(tracking_uri=f"file:{tmp_path}/mlruns", experiment_name="t")
    mm.register_model("m", {"w": jnp.zeros(2)})
    mm.transition_model("m", 1, "Staging", description="promote")
    assert mm._safe_get_stage("m", 1) == "Staging"
    mm.register_model("m", {"w": jnp.zeros(2)})
    mm.delete_model("m", 1, description="cleanup")
    assert mm.get_latest_version("m") == 2
    assert "Deletion" in mm.client.get_registered_model("m").description
    mm.delete_model("m")
    assert mm.get_latest_version("m") is None
