import jax.numpy as jnp
import pytest

from sheeprl_tpu.utils.model_manager import FileSystemModelManager


def test_register_load_roundtrip(tmp_path):
    mm = FileSystemModelManager(tmp_path / "registry")
    params = {"w": jnp.ones((3, 3))}
    v1 = mm.register_model("ppo_agent", params, description="test")
    assert v1 == 1
    v2 = mm.register_model("ppo_agent", params)
    assert v2 == 2
    loaded = mm.load_model("ppo_agent")  # latest
    assert loaded["w"].shape == (3, 3)
    assert mm.get_latest_version("ppo_agent") == 2


def test_transition_and_info(tmp_path):
    mm = FileSystemModelManager(tmp_path / "registry")
    mm.register_model("m", {"w": jnp.zeros(2)})
    mm.transition_model("m", 1, "production")
    assert mm.get_model_info("m", 1)["stage"] == "production"


def test_delete(tmp_path):
    mm = FileSystemModelManager(tmp_path / "registry")
    mm.register_model("m", {"w": jnp.zeros(2)})
    mm.register_model("m", {"w": jnp.zeros(2)})
    mm.delete_model("m", 1)
    assert mm.get_latest_version("m") == 2
    mm.delete_model("m")
    assert mm.get_latest_version("m") is None


def test_missing_model_raises(tmp_path):
    mm = FileSystemModelManager(tmp_path / "registry")
    with pytest.raises(FileNotFoundError):
        mm.load_model("nope")
