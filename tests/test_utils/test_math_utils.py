import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.utils.utils import (
    Ratio,
    gae,
    lambda_returns,
    polynomial_decay,
    symexp,
    symlog,
    two_hot_decoder,
    two_hot_encoder,
)


def reference_gae(rewards, values, dones, next_value, gamma, lmbda):
    """Straight-line numpy reimplementation of the textbook recursion."""
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    lastgaelam = np.zeros_like(next_value)
    nv = next_value
    for t in reversed(range(T)):
        nd = 1.0 - dones[t]
        delta = rewards[t] + gamma * nv * nd - values[t]
        lastgaelam = delta + gamma * lmbda * nd * lastgaelam
        adv[t] = lastgaelam
        nv = values[t]
    return adv + values, adv


def test_gae_matches_reference_recursion():
    rng = np.random.default_rng(0)
    T, B = 12, 4
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    dones = (rng.random((T, B)) < 0.2).astype(np.float32)
    next_value = rng.normal(size=(B,)).astype(np.float32)
    ret, adv = gae(jnp.array(rewards), jnp.array(values), jnp.array(dones), jnp.array(next_value), 0.99, 0.95)
    ref_ret, ref_adv = reference_gae(rewards, values, dones, next_value, 0.99, 0.95)
    np.testing.assert_allclose(np.asarray(adv), ref_adv, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ret), ref_ret, rtol=1e-4, atol=1e-5)


def test_lambda_returns_terminal_bootstrap():
    T, B = 8, 3
    rewards = jnp.ones((T, B))
    values = jnp.ones((T, B)) * 2.0
    continues = jnp.ones((T, B)) * 0.99
    rets = lambda_returns(rewards, values, continues, lmbda=0.95)
    assert rets.shape == (T, B)
    # all-continue, constant reward: returns exceed values
    assert np.all(np.asarray(rets) > 1.0)


def test_symlog_symexp_inverse():
    x = jnp.array([-100.0, -1.0, 0.0, 0.5, 30.0])
    np.testing.assert_allclose(np.asarray(symexp(symlog(x))), np.asarray(x), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("value", [-42.3, -1.0, 0.0, 0.1, 7.77, 123.0])
def test_two_hot_roundtrip(value):
    x = jnp.array([[value]])
    enc = two_hot_encoder(x, support_range=300)
    assert enc.shape == (1, 601)
    np.testing.assert_allclose(float(jnp.sum(enc)), 1.0, rtol=1e-5)
    dec = two_hot_decoder(enc, support_range=300)
    np.testing.assert_allclose(float(dec[0, 0]), value, rtol=1e-3, atol=1e-3)


def test_two_hot_at_most_two_nonzero():
    enc = two_hot_encoder(jnp.array([[3.7]]), support_range=300)
    assert int(jnp.sum(enc > 0)) <= 2


def test_polynomial_decay():
    assert polynomial_decay(0, initial=1.0, final=0.0, max_decay_steps=10) == 1.0
    assert polynomial_decay(10, initial=1.0, final=0.0, max_decay_steps=10) == 0.0
    mid = polynomial_decay(5, initial=1.0, final=0.0, max_decay_steps=10)
    assert 0.49 < mid < 0.51
    assert polynomial_decay(99, initial=1.0, final=0.3, max_decay_steps=10) == 0.3


class TestRatio:
    def test_unit_ratio(self):
        r = Ratio(1.0)
        assert r(10) == 10
        assert r(25) == 15

    def test_fractional_ratio_accumulates(self):
        # first call converts the full current step count (reference law);
        # afterwards deltas accumulate with fractional carry
        r = Ratio(0.5)
        total = sum(r(i) for i in range(1, 101))
        assert total in (49, 50)
        r2 = Ratio(0.5)
        assert r2(100) == 50

    def test_pretrain_steps(self):
        # pretrain counts in STEP units and is clamped to the current steps
        # (reference: sheeprl/utils/utils.py:278-287)
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            r = Ratio(1.0, pretrain_steps=7)
            assert r(4) == 4
        r = Ratio(0.5, pretrain_steps=6)
        assert r(10) == 3

    def test_state_roundtrip(self):
        r = Ratio(0.3)
        r(10)
        r2 = Ratio(0.3).load_state_dict(r.state_dict())
        assert r2(20) == r(20)

    def test_validation(self):
        with pytest.raises(ValueError):
            Ratio(-1.0)
        with pytest.raises(ValueError):
            Ratio(1.0, pretrain_steps=-1)


class TestUpdateChunks:
    """data/device_replay.update_chunks: burst update windows split into
    power-of-two dispatch chunks for compile reuse, with the on-device
    gathered-block HBM cap honored when per-update bytes are known (the
    r5 TPU learning capture OOMed on a single 25.8 GiB padded block).
    (Migrated off the deprecated ``utils.window_chunks`` byte-probe shim —
    ISSUE 11 satellite; one shim-compat test remains below.)"""

    def test_steady_state_single_chunk(self):
        from sheeprl_tpu.data.device_replay import update_chunks

        assert update_chunks(1) == [1]
        assert update_chunks(4) == [4]

    def test_burst_split_and_total_preserved(self):
        from sheeprl_tpu.data.device_replay import update_chunks

        # DV3-S pixel shape: ~12.6 MB gathered per update, 2 GiB HBM cap
        # -> power-of-two sizes (compile reuse: each distinct U compiles once)
        chunks = update_chunks(1026, bytes_per_update=12.6e6)
        assert sum(chunks) == 1026
        assert max(chunks) * 12.6e6 <= 2**31
        assert all(c & (c - 1) == 0 for c in chunks)  # powers of two
        assert len(set(chunks)) <= 3  # few distinct compiled shapes

    def test_cap_env_override(self, monkeypatch):
        from sheeprl_tpu.data.device_replay import update_chunks

        monkeypatch.setenv("SHEEPRL_MAX_WINDOW_UPDATES", "2")
        assert update_chunks(10) == [2, 2, 2, 2, 2]

    def test_hbm_budget_env_override(self, monkeypatch):
        from sheeprl_tpu.data.device_replay import update_chunks

        monkeypatch.setenv("SHEEPRL_MAX_HBM_WINDOW_BYTES", "100")
        assert update_chunks(10, bytes_per_update=50.0) == [2, 2, 2, 2, 2]

    def test_huge_per_update_never_zero(self):
        from sheeprl_tpu.data.device_replay import update_chunks

        assert update_chunks(3, bytes_per_update=1e12) == [1, 1, 1]

    def test_window_chunks_shim_compat(self):
        # the deprecated byte-probed spelling (external callers only)
        # still decomposes under its own budget law
        from sheeprl_tpu.utils.utils import window_chunks

        chunks = window_chunks(1026, 12.6e6)
        assert sum(chunks) == 1026
        assert all(c & (c - 1) == 0 for c in chunks)
