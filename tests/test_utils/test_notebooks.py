"""The committed notebooks must actually run.

No jupyter kernel ships in this image, so instead of nbconvert --execute
the test execs every code cell in order inside one namespace — the same
top-to-bottom semantics a kernel gives a fresh 'Run All'.
"""

import matplotlib
import nbformat
import pytest

matplotlib.use("Agg")

from pathlib import Path  # noqa: E402

REPO_ROOT = Path(__file__).resolve().parents[2]
NOTEBOOKS = sorted((REPO_ROOT / "notebooks").glob("*.ipynb"))


@pytest.mark.parametrize("path", NOTEBOOKS, ids=lambda p: p.name)
def test_notebook_code_cells_execute(path, monkeypatch, tmp_path):
    monkeypatch.chdir(REPO_ROOT)  # notebooks locate the repo from cwd
    nb = nbformat.read(path, as_version=4)
    code = [c.source for c in nb.cells if c.cell_type == "code"]
    assert code, f"{path.name} has no code cells"
    ns = {"__name__": "__notebook__"}
    for i, src in enumerate(code):
        try:
            exec(compile(src, f"{path.name}[cell {i}]", "exec"), ns)
        except Exception as e:  # pragma: no cover - failure reporting
            pytest.fail(f"{path.name} cell {i} raised {type(e).__name__}: {e}")


def test_notebook_dir_has_imagination_notebook():
    assert any(p.name == "dreamer_v3_imagination.ipynb" for p in NOTEBOOKS)
