import jax.numpy as jnp
import optax

from sheeprl_tpu.utils.optim import build_optimizer, get_learning_rate, rmsprop_tf, set_learning_rate


def test_set_lr_on_bare_inject():
    # max_grad_norm=0 → no chain wrapper (the review-found silent no-op)
    opt = build_optimizer({"name": "adam", "lr": 1e-3}, max_grad_norm=None)
    state = opt.init({"w": jnp.zeros(3)})
    set_learning_rate(state, 5e-4)
    assert abs(get_learning_rate(state) - 5e-4) < 1e-9


def test_set_lr_on_chained():
    opt = build_optimizer({"name": "adam", "lr": 1e-3}, max_grad_norm=0.5)
    state = opt.init({"w": jnp.zeros(3)})
    set_learning_rate(state, 1e-4)
    assert abs(get_learning_rate(state) - 1e-4) < 1e-9


def test_rmsprop_tf_square_avg_ones_init():
    opt = rmsprop_tf(1e-2)
    params = {"w": jnp.ones(4)}
    state = opt.init(params)
    grads = {"w": jnp.full(4, 0.1)}
    updates, state = opt.update(grads, state, params)
    new = optax.apply_updates(params, updates)
    # ones-init square_avg keeps the first step small (unlike torch default)
    assert float(jnp.abs(new["w"] - 1.0).max()) < 2e-3


def test_unknown_optimizer_raises():
    import pytest

    with pytest.raises(ValueError):
        build_optimizer({"name": "nope"})
