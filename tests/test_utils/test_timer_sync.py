"""metric.sync_timers: phase-time ATTRIBUTION changes, totals don't.

VERDICT r4 weak #5: with async dispatch, device compute launched in the
train phase lands in whichever later phase first blocks, so
``Time/sps_train`` was misleading on single-stream hosts.  Sync mode must
move the time back into the dispatching phase without inflating the sum.
"""

import jax
import jax.numpy as jnp
import pytest

from sheeprl_tpu.utils.timer import timer


@pytest.fixture(autouse=True)
def _reset_timer_state():
    saved = (timer.disabled, timer.sync, dict(timer.timers), dict(timer._counts))
    timer.timers = {}
    timer._counts = {}
    yield
    timer.disabled, timer.sync, timer.timers, timer._counts = saved


@jax.jit
def _heavy(x):
    for _ in range(20):
        x = x @ x / jnp.sqrt(jnp.float32(x.shape[0]))
    return x


def _run_phases(sync: bool):
    timer.disabled = False
    timer.sync = sync
    timer.timers = {}
    timer._counts = {}
    x = jnp.ones((400, 400))
    with timer("Time/train_time"):
        y = _heavy(x)  # dispatched, not awaited — the realistic train phase
    with timer("Time/env_interaction_time"):
        y.block_until_ready()  # the next phase's first device touch
    t = timer.to_dict(reset=True)
    return t["Time/train_time"], t["Time/env_interaction_time"]


def test_sync_mode_moves_compute_into_dispatching_phase():
    _heavy(jnp.ones((400, 400))).block_until_ready()  # compile outside timing
    train_async, env_async = _run_phases(sync=False)
    train_sync, env_sync = _run_phases(sync=True)
    # sync: the train phase owns (at least) its own device compute
    assert train_sync > env_sync, (train_sync, env_sync)
    assert train_sync > train_async, (train_sync, train_async)
    # the total is the same work either way (generous bound: shared 1-core
    # host; attribution moves ~all of the compute, totals only jitter)
    total_async = train_async + env_async
    total_sync = train_sync + env_sync
    assert total_sync < 3.0 * total_async + 0.1, (total_sync, total_async)


def test_configure_reads_sync_timers_flag():
    class M(dict):
        __getattr__ = dict.__getitem__

    cfg = M(disable_timer=False, log_level=1, sync_timers=True)
    timer.configure(cfg)
    assert timer.sync is True and timer.disabled is False
    cfg = M(disable_timer=False, log_level=0, sync_timers=False)
    timer.configure(cfg)
    assert timer.disabled is True and timer.sync is False
