"""window_scan: trace-time unrolling must be semantically identical to scan.

Background (BENCH_CPU.md round 5): XLA-CPU runs convolution-bearing update
bodies ~5x slower inside ``lax.scan``'s outlined call, and ``unroll=True``
does not remove the penalty — only true trace-time inlining does.  The
helper must therefore agree with ``lax.scan`` exactly, on every path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.utils.utils import window_scan


def _body(carry, x):
    new = carry * 0.9 + x["a"].sum() + x["b"]
    return new, {"y": new * 2.0, "z": new - 1.0}


@pytest.mark.parametrize("U", [1, 3, 16])
def test_unrolled_matches_scan(U):
    xs = {
        "a": jnp.arange(U * 6, dtype=jnp.float32).reshape(U, 6),
        "b": jnp.linspace(0.0, 1.0, U),
    }
    c0 = jnp.float32(2.0)
    c_scan, ys_scan = jax.lax.scan(_body, c0, xs)
    c_ws, ys_ws = jax.jit(lambda c, x: window_scan(_body, c, x))(c0, xs)
    np.testing.assert_allclose(np.asarray(c_ws), np.asarray(c_scan), rtol=1e-6)
    for k in ys_scan:
        assert ys_ws[k].shape == ys_scan[k].shape
        np.testing.assert_allclose(np.asarray(ys_ws[k]), np.asarray(ys_scan[k]), rtol=1e-6)


def test_long_window_falls_back_to_scan():
    U = 40  # > unroll_limit: must take the lax.scan path (same semantics)
    xs = {"a": jnp.ones((U, 2)), "b": jnp.ones((U,))}
    c_scan, ys_scan = jax.lax.scan(_body, jnp.float32(0.0), xs)
    c_ws, ys_ws = window_scan(_body, jnp.float32(0.0), xs)
    np.testing.assert_allclose(np.asarray(c_ws), np.asarray(c_scan), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ys_ws["y"]), np.asarray(ys_scan["y"]), rtol=1e-6)


def test_respects_custom_unroll_limit():
    xs = {"a": jnp.ones((4, 2)), "b": jnp.ones((4,))}
    c_scan, _ = jax.lax.scan(_body, jnp.float32(1.0), xs)
    c_ws, _ = window_scan(_body, jnp.float32(1.0), xs, unroll_limit=2)  # forces scan path
    np.testing.assert_allclose(np.asarray(c_ws), np.asarray(c_scan), rtol=1e-6)
