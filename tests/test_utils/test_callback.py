"""Checkpoint-callback buffer-consistency trick.

The tail patch must only touch storage-boundary keys (truncated/dones) and
must skip buffers that store an explicit next_obs per row — forcing a fake
``terminated=1`` would permanently kill that transition's bootstrap after a
buffer-checkpointed resume (reference: sheeprl/utils/callback.py:87-142
patches only 'truncated').
"""

import numpy as np

from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.utils.callback import _consistent_tail


def _filled_buffer(keys, steps=4, n_envs=1):
    rb = ReplayBuffer(buffer_size=8, n_envs=n_envs)
    data = {k: np.zeros((steps, n_envs, 1), dtype=np.float32) for k in keys}
    if "obs" not in data:
        data["obs"] = np.arange(steps * n_envs, dtype=np.float32).reshape(steps, n_envs, 1)
    rb.add(data)
    return rb


def test_tail_patch_sets_truncated_and_dones_only():
    rb = _filled_buffer(["obs", "terminated", "truncated", "dones"])
    tail = (rb._pos - 1) % rb.buffer_size
    with _consistent_tail(rb):
        assert rb["truncated"][tail].item() == 1.0
        assert rb["dones"][tail].item() == 1.0
        assert rb["terminated"][tail].item() == 0.0  # never forced
    # restored afterwards
    assert rb["truncated"][tail].item() == 0.0
    assert rb["dones"][tail].item() == 0.0


def test_tail_patch_never_forces_terminated_when_only_terminated():
    rb = _filled_buffer(["obs", "terminated"])
    tail = (rb._pos - 1) % rb.buffer_size
    with _consistent_tail(rb):
        assert rb["terminated"][tail].item() == 0.0


def test_tail_patch_skipped_for_next_obs_buffers():
    rb = _filled_buffer(["obs", "next_obs", "terminated", "truncated"])
    tail = (rb._pos - 1) % rb.buffer_size
    with _consistent_tail(rb):
        # rows are self-contained: nothing is patched at all
        assert rb["truncated"][tail].item() == 0.0
        assert rb["terminated"][tail].item() == 0.0


def test_tail_patch_empty_buffer_noop():
    rb = ReplayBuffer(buffer_size=8, n_envs=1)
    with _consistent_tail(rb):
        pass
