"""TelemetryHub contract: monitor-shim equivalence, registration, flush."""

from sheeprl_tpu.telemetry import HUB
from sheeprl_tpu.telemetry.spans import SPANS


class TestMonitorShims:
    def test_profiler_globals_are_the_telemetry_monitors(self):
        """The old ``utils.profiler`` globals are thin shims: the SAME
        objects the telemetry subsystem owns, not copies."""
        from sheeprl_tpu.telemetry import monitors
        from sheeprl_tpu.utils import profiler

        assert profiler.COMPILE_MONITOR is monitors.COMPILE_MONITOR
        assert profiler.CHECKPOINT_MONITOR is monitors.CHECKPOINT_MONITOR
        assert profiler.RESILIENCE_MONITOR is monitors.RESILIENCE_MONITOR
        assert profiler.RecompileLimitExceeded is monitors.RecompileLimitExceeded

    def test_recording_via_old_global_reaches_hub_flush(self):
        """A record through the legacy import path surfaces as the same
        ``Compile/*`` / ``Resilience/*`` metrics through ``HUB.flush()``."""
        from sheeprl_tpu.utils.profiler import COMPILE_MONITOR, RESILIENCE_MONITOR

        exe_before = HUB.flush().get("Compile/executables", 0.0)
        COMPILE_MONITOR.begin("hub_shim_test", "sig0")
        COMPILE_MONITOR.end("hub_shim_test", 0.25)
        retries_before = RESILIENCE_MONITOR.totals()["retries"]
        RESILIENCE_MONITOR.record_retry("hub_shim_test")
        out = HUB.flush()
        assert out["Compile/executables"] == exe_before + 1
        assert out["Resilience/retries"] == float(retries_before + 1)

    def test_checkpoint_monitor_flows_through_hub(self):
        from sheeprl_tpu.utils.profiler import CHECKPOINT_MONITOR

        saves_before = CHECKPOINT_MONITOR.totals()["saves"]
        CHECKPOINT_MONITOR.record_save(seconds=0.5, nbytes=1024, asynchronous=True)
        out = HUB.flush()
        assert out["Checkpoint/total_saves"] == float(saves_before + 1)
        assert out["Checkpoint/save_s"] == 0.5


class TestRegistration:
    def test_register_callable_and_object_sources(self):
        class Source:
            def metrics(self):
                return {"Obj/x": 2.0}

        HUB.register("test_source", lambda: {"Call/x": 1.0})
        assert HUB.flush()["Call/x"] == 1.0
        HUB.register("test_source", Source())  # re-register replaces
        out = HUB.flush()
        assert out["Obj/x"] == 2.0
        assert "Call/x" not in out
        HUB.unregister("test_source")
        assert "Obj/x" not in HUB.flush()

    def test_broken_source_is_skipped_not_fatal(self):
        def broken():
            raise RuntimeError("bad exporter")

        HUB.register("test_source", broken)
        out = HUB.flush()  # must not raise
        assert isinstance(out, dict)
        HUB.unregister("test_source")

    def test_source_names_listed(self):
        HUB.register("test_source", lambda: {})
        assert "test_source" in HUB.source_names()
        # the monitors registered at import are permanent residents
        for name in ("compile", "checkpoint", "resilience", "spans"):
            assert name in HUB.source_names()


class TestFlushContract:
    def test_flush_roll_resets_span_window(self):
        with SPANS.span("rollout"):
            pass
        assert "Phase/rollout" in HUB.flush(roll=False)
        assert "Phase/rollout" in HUB.flush(roll=True)  # roll AFTER collect
        assert "Phase/rollout" not in HUB.flush(roll=False)  # window rolled

    def test_final_flush_lands_last_window_through_attached_logger(self):
        logged = []

        class FakeLogger:
            def log_metrics(self, metrics, step):
                logged.append((dict(metrics), step))

        HUB.attach_logger(FakeLogger())
        HUB.note_step(1234)
        with SPANS.span("update.dispatch"):
            pass
        out = HUB.final_flush()
        assert logged, "final_flush must log through the attached logger"
        metrics, step = logged[0]
        assert step == 1234
        assert "Phase/update.dispatch" in metrics
        assert metrics == out
        # detached after: a second final flush must not double-log
        logged.clear()
        HUB.final_flush()
        assert not logged

    def test_final_flush_survives_broken_logger(self):
        class ClosedLogger:
            def log_metrics(self, metrics, step):
                raise RuntimeError("writer closed")

        HUB.attach_logger(ClosedLogger())
        with SPANS.span("rollout"):
            pass
        HUB.final_flush()  # must not raise
